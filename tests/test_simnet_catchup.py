"""simnet chain-replay catch-up e2e (ISSUE 14, ROADMAP item 3).

A node crashes early, the cluster runs on under validator churn and 10%
message-drop links until a height gap has built, then a CatchupDriver
replays the gap LIVE (consensus keeps committing) through the
ReplayEngine — epoch-cut range packing at PRIORITY_REPLAY — and
restarts the node into consensus at the tip. SimReport.catchup carries
the replayed-range hit rate, and the whole trajectory must be
replay-exact per seed.

Needs a working ed25519 signer. With the `cryptography` wheel the module
runs directly; without it, tests/test_replay_isolated.py re-runs it in a
subprocess under TM_TPU_PUREPY_CRYPTO=1.
"""

import importlib.util
import os

import pytest

if importlib.util.find_spec("cryptography") is None and not os.environ.get(
    "TM_TPU_PUREPY_CRYPTO"
):
    pytest.skip(
        "needs an ed25519 signer (cryptography wheel or the isolated runner)",
        allow_module_level=True,
    )

from tendermint_tpu.simnet import (  # noqa: E402
    CatchupDriver,
    Cluster,
    Fault,
    LinkConfig,
    rotation_schedule,
)


def _run_catchup(seed, *, target, behind_at, every, until, start=8,
                 drop=0.10, max_virtual_s=900.0, max_wall_s=400.0):
    """5-validator cluster, node 4 crashes at h=3, churn every `every`
    heights, 10% drop links; catch-up begins once the tip reaches
    `behind_at` and the node must then rejoin and commit `target`."""
    faults = [Fault(kind="crash", at_height=3, node=4)]
    faults += rotation_schedule(5, 5, every=every, start=start, until=until)
    c = Cluster(
        n_nodes=5, n_validators=5, seed=seed, faults=faults,
        link=LinkConfig(drop=0.10), sig_memo=True,
    )
    CatchupDriver(
        c, 4, drop=drop, start_after=5.0, start_at_height=behind_at,
    )
    try:
        rep = c.run_to_height(
            target, max_virtual_s=max_virtual_s, max_wall_s=max_wall_s,
        )
    finally:
        c.stop()
    return rep


class TestCatchup:
    def test_crashed_node_rejoins_via_range_replay(self):
        """The fast shape of the acceptance scenario: ~120 heights
        behind under churn + lossy links, caught up by epoch-cut device
        ranges (not the per-height sequential path), rejoined, and the
        whole cluster converges with invariants green."""
        rep = _run_catchup(
            seed=11, target=130, behind_at=120, every=25, start=20,
            until=150,
        )
        assert rep.ok, rep.reason
        assert min(rep.heights) >= 130
        assert rep.catchup is not None and len(rep.catchup) == 1
        cu = rep.catchup[0]
        assert cu["rejoined"], cu
        assert cu["behind_at_start"] >= 100, cu
        assert cu["heights_applied"] >= 100, cu
        # the point of the PR: the gap rode the range path, not the
        # sequential fallback
        assert cu["hit_rate"] > 0.9, cu
        assert cu["fallback_ranges"] == 0, cu
        assert cu["failed"] == [], cu
        assert cu["sigs_submitted"] > 0, cu
        # churn actually happened while the chain was being replayed
        assert rep.valset_changes, rep.valset_changes

    def test_catchup_replay_exact_across_seeds(self):
        """Same seed ⇒ byte-identical fingerprint AND catch-up summary
        (the determinism contract extends to the replay trajectory);
        different seed ⇒ different delivery schedule."""
        kw = dict(target=50, behind_at=38, every=12, until=50)
        a1 = _run_catchup(seed=21, **kw)
        a2 = _run_catchup(seed=21, **kw)
        b = _run_catchup(seed=22, **kw)
        assert a1.ok and a2.ok and b.ok, (a1.reason, a2.reason, b.reason)
        assert a1.fingerprint == a2.fingerprint
        assert a1.schedule_digest == a2.schedule_digest
        assert a1.catchup == a2.catchup
        assert b.schedule_digest != a1.schedule_digest

    @pytest.mark.slow
    def test_thousand_heights_behind(self):
        """The full acceptance scenario: the node rejoins >= 1000
        heights behind and the replayed-range hit rate stays above
        0.9."""
        # target sits ~25 heights past the gap threshold: the replay
        # takes a few virtual seconds (fetch steps + 10% request drop
        # retries) and the run must not end before the rejoin lands
        rep = _run_catchup(
            seed=31, target=1030, behind_at=1005, every=50, start=25,
            until=1030, max_virtual_s=3600.0, max_wall_s=1500.0,
        )
        assert rep.ok, rep.reason
        cu = rep.catchup[0]
        assert cu["rejoined"], cu
        assert cu["behind_at_start"] >= 1000, cu
        assert cu["heights_applied"] >= 1000, cu
        assert cu["hit_rate"] > 0.9, cu
        assert cu["failed"] == [], cu
