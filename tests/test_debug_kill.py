"""`debug dump` / `debug kill` CLI parity (cmd/tendermint/commands/debug,
kill.go: capture-then-SIGKILL).

Runs tier-1 WITHOUT the cryptography wheel: the CLI's debug path is pure
urllib + os.kill, so the node RPC is stood in for by a stdlib HTTP server
serving canned JSON, and the victim is a throwaway sleeper subprocess.
The real /thread_dump endpoint is covered in tests/test_node_rpc.py."""

import http.server
import json
import os
import signal
import subprocess
import sys
import threading
import time

import pytest

from tendermint_tpu import cli

CANNED = {
    "status": {"node_info": {"network": "dbg-chain"}, "sync_info": {"latest_block_height": "7"}},
    "net_info": {"n_peers": "3"},
    "dump_consensus_state": {"round_state": {"height": 8}},
    "consensus_state": {"round_state": {"height/round/step": "8/0/1"}},
    "thread_dump": {"n_threads": 2, "threads": []},
    "dump_trace": {"enabled": False, "summary": {}},
}


class _FakeRPC(http.server.BaseHTTPRequestHandler):
    def do_GET(self):
        method = self.path.lstrip("/").split("?")[0]
        body = CANNED.get(method)
        if body is None:
            self.send_response(404)
            self.end_headers()
            return
        data = json.dumps(body).encode()
        self.send_response(200)
        self.send_header("Content-Type", "application/json")
        self.send_header("Content-Length", str(len(data)))
        self.end_headers()
        self.wfile.write(data)

    def log_message(self, *a):  # quiet
        pass


@pytest.fixture
def fake_rpc():
    srv = http.server.HTTPServer(("127.0.0.1", 0), _FakeRPC)
    t = threading.Thread(target=srv.serve_forever, daemon=True)
    t.start()
    yield f"http://127.0.0.1:{srv.server_address[1]}"
    srv.shutdown()


def test_debug_dump_captures_all_methods(fake_rpc, tmp_path):
    out = str(tmp_path / "dump")
    rc = cli.main(
        ["debug", "dump", "--rpc-laddr", fake_rpc, "--output-directory", out]
    )
    assert rc == 0
    for method in CANNED:
        path = os.path.join(out, f"{method}.json")
        assert os.path.exists(path), f"missing {method}.json"
        assert json.load(open(path)) == CANNED[method]


def test_debug_default_mode_is_dump(fake_rpc, tmp_path):
    out = str(tmp_path / "dump2")
    rc = cli.main(["debug", "--rpc-laddr", fake_rpc, "--output-directory", out])
    assert rc == 0
    assert os.path.exists(os.path.join(out, "status.json"))


def test_debug_kill_captures_then_sigkills(fake_rpc, tmp_path):
    victim = subprocess.Popen([sys.executable, "-c", "import time; time.sleep(120)"])
    try:
        out = str(tmp_path / "killdump")
        rc = cli.main(
            [
                "debug", "kill",
                "--rpc-laddr", fake_rpc,
                "--output-directory", out,
                "--pid", str(victim.pid),
            ]
        )
        assert rc == 0
        # capture happened BEFORE the kill (kill.go ordering)
        assert os.path.exists(os.path.join(out, "dump_consensus_state.json"))
        assert os.path.exists(os.path.join(out, "thread_dump.json"))
        # and the process is gone, by SIGKILL
        deadline = time.time() + 10
        while victim.poll() is None and time.time() < deadline:
            time.sleep(0.05)
        assert victim.returncode == -signal.SIGKILL
    finally:
        if victim.poll() is None:
            victim.kill()


def test_debug_kill_requires_pid(fake_rpc, tmp_path):
    rc = cli.main(
        [
            "debug", "kill",
            "--rpc-laddr", fake_rpc,
            "--output-directory", str(tmp_path / "nopid"),
        ]
    )
    assert rc == 1


def test_debug_kill_bad_pid_fails_cleanly(fake_rpc, tmp_path):
    # spawn-and-reap so the pid is definitely unused (ESRCH, not a live kill)
    p = subprocess.Popen([sys.executable, "-c", "pass"])
    p.wait()
    rc = cli.main(
        [
            "debug", "kill",
            "--rpc-laddr", fake_rpc,
            "--output-directory", str(tmp_path / "badpid"),
            "--pid", str(p.pid),
        ]
    )
    assert rc == 1
