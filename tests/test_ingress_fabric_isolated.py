"""Tier-1 face of the unified ingress fabric (ISSUE 17).

Same pattern as test_vote_ingress_isolated.py: the container lacks the
`cryptography` wheel, so the fabric suite (tests/test_ingress_fabric.py
— adaptive-controller policy [deepen-under-flood / shrink-when-idle /
deadline-aware flush], lane-keyed knob resolution with legacy
deprecation, poisoned-window isolation, stepped semantics, cross-lane
stats parity) and the `tools/prep_bench.py --fabric` gate run in
SUBPROCESSES with TM_TPU_PUREPY_CRYPTO=1, which must never leak into
the main pytest process.
"""

import os
import subprocess
import sys

import pytest


def _repo_root():
    return os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _purepy_env():
    from tendermint_tpu.libs import jaxcache

    env = dict(os.environ, TM_TPU_PUREPY_CRYPTO="1", JAX_PLATFORMS="cpu")
    env.pop("TM_TPU_DONATE", None)
    env.pop("TM_TPU_MESH", None)
    jaxcache.set_env(env, _repo_root())
    return env


# -- subprocess faces ----------------------------------------------------


def test_ingress_fabric_suite_under_purepy_fallback():
    try:
        import cryptography  # noqa: F401

        pytest.skip("cryptography present; test_ingress_fabric runs directly")
    except ModuleNotFoundError:
        pass
    here = os.path.dirname(os.path.abspath(__file__))
    r = subprocess.run(
        [
            sys.executable, "-m", "pytest",
            os.path.join(here, "test_ingress_fabric.py"),
            "-q", "-m", "not slow", "-p", "no:cacheprovider",
        ],
        capture_output=True,
        env=_purepy_env(),
        cwd=_repo_root(),
        timeout=800,
    )
    tail = (r.stdout or b"").decode(errors="replace")[-3000:]
    assert r.returncode == 0, \
        f"isolated test_ingress_fabric run failed:\n{tail}"


def test_prep_bench_fabric_gate():
    """ISSUE 17 satellite: the --fabric gate — all four lane patterns on
    ONE scheduler + completer thread, the adaptive window moving BOTH
    directions under real kernels with a slow readback, exactly the
    forged signature rejected, zero pool-slot leak — wired into tier-1
    through the isolated runner."""
    r = subprocess.run(
        [
            sys.executable,
            os.path.join(_repo_root(), "tools", "prep_bench.py"),
            "--fabric",
        ],
        capture_output=True,
        env=_purepy_env(),
        cwd=_repo_root(),
        timeout=600,
    )
    out = (r.stdout or b"").decode(errors="replace")
    err = (r.stderr or b"").decode(errors="replace")
    assert r.returncode == 0, f"--fabric gate failed:\n{out}\n{err[-2000:]}"
