"""Model-based-test conformance: replay the TLA+-derived light-client
traces against the verifier, on both the host oracle and the device batch
path.

Reference parity: light/mbt/driver_test.go — the JSON vectors
(light/mbt/json/MC4_4_faulty_*.json, copied verbatim into
tests/vectors/mbt/) are the bit-exactness oracle for the verifier
(SURVEY.md §4): header hashing, validator-set hashing, canonical vote
sign-bytes, ZIP-215 signature acceptance, trust-level arithmetic, and the
SUCCESS / NOT_ENOUGH_TRUST / INVALID error taxonomy all have to line up
for every step of every trace.
"""

import base64
import calendar
import glob
import json
import os
import re

import pytest

from tendermint_tpu.crypto import batch as cbatch
from tendermint_tpu.crypto import ed25519
from tendermint_tpu.light import verifier
from tendermint_tpu.types import Validator, ValidatorSet
from tendermint_tpu.types.block import (
    BlockID,
    Commit,
    CommitSig,
    Header,
    PartSetHeader,
    SignedHeader,
    Version,
)
from tendermint_tpu.wire.canonical import Timestamp

VECTOR_DIR = os.path.join(os.path.dirname(__file__), "vectors", "mbt")

_TIME_RE = re.compile(
    r"^(\d{4})-(\d{2})-(\d{2})T(\d{2}):(\d{2}):(\d{2})(?:\.(\d+))?Z$"
)


def parse_time(s: str) -> Timestamp:
    m = _TIME_RE.match(s)
    assert m, f"bad RFC3339 time {s!r}"
    y, mo, d, h, mi, sec = (int(m.group(i)) for i in range(1, 7))
    frac = (m.group(7) or "").ljust(9, "0")
    secs = calendar.timegm((y, mo, d, h, mi, sec, 0, 0, 0))
    return Timestamp(seconds=secs, nanos=int(frac) if frac else 0)


def _hex(v) -> bytes:
    return bytes.fromhex(v) if v else b""


def parse_block_id(d) -> BlockID:
    if d is None:
        return BlockID()
    parts = d.get("parts") or d.get("part_set_header")
    psh = (
        PartSetHeader(total=int(parts["total"]), hash=_hex(parts["hash"]))
        if parts
        else PartSetHeader()
    )
    return BlockID(hash=_hex(d["hash"]), part_set_header=psh)


def parse_header(d) -> Header:
    return Header(
        version=Version(
            block=int(d["version"]["block"]), app=int(d["version"]["app"])
        ),
        chain_id=d["chain_id"],
        height=int(d["height"]),
        time=parse_time(d["time"]),
        last_block_id=parse_block_id(d.get("last_block_id")),
        last_commit_hash=_hex(d.get("last_commit_hash")),
        data_hash=_hex(d.get("data_hash")),
        validators_hash=_hex(d["validators_hash"]),
        next_validators_hash=_hex(d["next_validators_hash"]),
        consensus_hash=_hex(d["consensus_hash"]),
        app_hash=_hex(d.get("app_hash")),
        last_results_hash=_hex(d.get("last_results_hash")),
        evidence_hash=_hex(d.get("evidence_hash")),
        proposer_address=_hex(d["proposer_address"]),
    )


def parse_commit(d) -> Commit:
    sigs = []
    for s in d["signatures"]:
        sigs.append(
            CommitSig(
                block_id_flag=int(s["block_id_flag"]),
                validator_address=_hex(s.get("validator_address")),
                timestamp=(
                    parse_time(s["timestamp"])
                    if s.get("timestamp")
                    else Timestamp.zero()
                ),
                signature=(
                    base64.b64decode(s["signature"]) if s.get("signature") else b""
                ),
            )
        )
    return Commit(
        height=int(d["height"]),
        round=int(d["round"]),
        block_id=parse_block_id(d["block_id"]),
        signatures=sigs,
    )


def parse_signed_header(d) -> SignedHeader:
    return SignedHeader(header=parse_header(d["header"]), commit=parse_commit(d["commit"]))


def parse_valset(d) -> ValidatorSet:
    """Order-preserving: the Go driver unmarshals straight into
    types.ValidatorSet without re-sorting, so the hash commits to the
    vector's order."""
    vals = []
    for v in d["validators"]:
        assert v["pub_key"]["type"] == "tendermint/PubKeyEd25519"
        pk = ed25519.PubKey(base64.b64decode(v["pub_key"]["value"]))
        val = Validator.new(pk, int(v["voting_power"]))
        assert val.address == _hex(v["address"]), "address derivation mismatch"
        if v.get("proposer_priority") is not None:
            val.proposer_priority = int(v["proposer_priority"])
        vals.append(val)
    vs = ValidatorSet(validators=vals)
    vs._update_total_voting_power()
    return vs


def trace_files():
    files = sorted(glob.glob(os.path.join(VECTOR_DIR, "*.json")))
    assert len(files) == 9, "expected the 9 MC4_4_faulty vectors"
    return files


@pytest.fixture(params=["host", "device"])
def batch_backend(request, monkeypatch):
    """Run every trace on both sides of the dispatch seam: the host
    per-signature oracle and the device batch engine (forced below its
    size threshold so the 4-signature commits still take the device
    path)."""
    if request.param == "host":
        monkeypatch.setattr(cbatch, "_device_verifier_factory", None)
    else:
        from tendermint_tpu.ops.backend import Ed25519DeviceBatchVerifier

        monkeypatch.setattr(
            cbatch,
            "_device_verifier_factory",
            lambda: Ed25519DeviceBatchVerifier(force_device=True),
        )
    return request.param


@pytest.mark.parametrize("path", trace_files(), ids=os.path.basename)
def test_mbt_trace(path, batch_backend):
    with open(path) as f:
        tc = json.load(f)

    trusted_sh = parse_signed_header(tc["initial"]["signed_header"])
    trusted_next_vals = parse_valset(tc["initial"]["next_validator_set"])
    trusting_period = int(tc["initial"]["trusting_period"]) / 1e9  # ns -> s

    for step, inp in enumerate(tc["input"]):
        blk = inp["block"]
        new_sh = parse_signed_header(blk["signed_header"])
        new_vals = parse_valset(blk["validator_set"])
        now = parse_time(inp["now"])

        err = None
        try:
            verifier.verify(
                trusted_sh,
                trusted_next_vals,
                new_sh,
                new_vals,
                trusting_period,
                now,
                1.0,  # maxClockDrift = 1s, as in driver_test.go:57
                verifier.DEFAULT_TRUST_LEVEL,
            )
        except ValueError as e:
            err = e

        verdict = inp["verdict"]
        ctx = f"{os.path.basename(path)} step {step} ({batch_backend})"
        if verdict == "SUCCESS":
            assert err is None, f"{ctx}: expected SUCCESS, got {err!r}"
        elif verdict == "NOT_ENOUGH_TRUST":
            assert isinstance(err, verifier.ErrNotEnoughTrust), (
                f"{ctx}: expected NOT_ENOUGH_TRUST, got {err!r}"
            )
        elif verdict == "INVALID":
            assert isinstance(
                err, (verifier.ErrInvalidHeader, verifier.ErrOldHeaderExpired)
            ), f"{ctx}: expected INVALID, got {err!r}"
        else:
            pytest.fail(f"{ctx}: unexpected verdict {verdict!r}")

        if err is None:  # advance trust, as the driver does
            trusted_sh = new_sh
            trusted_next_vals = parse_valset(blk["next_validator_set"])
