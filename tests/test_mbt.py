"""Model-based-test conformance: replay the TLA+-derived light-client
traces against the verifier, on both the host oracle and the device batch
path.

Reference parity: light/mbt/driver_test.go — the JSON vectors
(light/mbt/json/MC4_4_faulty_*.json, copied verbatim into
tests/vectors/mbt/) are the bit-exactness oracle for the verifier
(SURVEY.md §4): header hashing, validator-set hashing, canonical vote
sign-bytes, ZIP-215 signature acceptance, trust-level arithmetic, and the
SUCCESS / NOT_ENOUGH_TRUST / INVALID error taxonomy all have to line up
for every step of every trace.
"""

import glob
import json
import os

import pytest

from tendermint_tpu.crypto import batch as cbatch
from tendermint_tpu.light import verifier
from tendermint_tpu.wire.json_types import (
    parse_signed_header,
    parse_time,
    parse_validator_set as parse_valset,
)

VECTOR_DIR = os.path.join(os.path.dirname(__file__), "vectors", "mbt")


def trace_files():
    files = sorted(glob.glob(os.path.join(VECTOR_DIR, "*.json")))
    assert len(files) == 9, "expected the 9 MC4_4_faulty vectors"
    return files


@pytest.fixture(params=["host", "device"])
def batch_backend(request, monkeypatch):
    """Run every trace on both sides of the dispatch seam: the host
    per-signature oracle and the device batch engine (forced below its
    size threshold so the 4-signature commits still take the device
    path)."""
    if request.param == "host":
        monkeypatch.setattr(cbatch, "_device_verifier_factory", None)
    else:
        from tendermint_tpu.ops.backend import Ed25519DeviceBatchVerifier

        monkeypatch.setattr(
            cbatch,
            "_device_verifier_factory",
            lambda: Ed25519DeviceBatchVerifier(force_device=True),
        )
    return request.param


@pytest.mark.parametrize("path", trace_files(), ids=os.path.basename)
def test_mbt_trace(path, batch_backend):
    with open(path) as f:
        tc = json.load(f)

    trusted_sh = parse_signed_header(tc["initial"]["signed_header"])
    trusted_next_vals = parse_valset(tc["initial"]["next_validator_set"])
    trusting_period = int(tc["initial"]["trusting_period"]) / 1e9  # ns -> s

    for step, inp in enumerate(tc["input"]):
        blk = inp["block"]
        new_sh = parse_signed_header(blk["signed_header"])
        new_vals = parse_valset(blk["validator_set"])
        now = parse_time(inp["now"])

        err = None
        try:
            verifier.verify(
                trusted_sh,
                trusted_next_vals,
                new_sh,
                new_vals,
                trusting_period,
                now,
                1.0,  # maxClockDrift = 1s, as in driver_test.go:57
                verifier.DEFAULT_TRUST_LEVEL,
            )
        except ValueError as e:
            err = e

        verdict = inp["verdict"]
        ctx = f"{os.path.basename(path)} step {step} ({batch_backend})"
        if verdict == "SUCCESS":
            assert err is None, f"{ctx}: expected SUCCESS, got {err!r}"
        elif verdict == "NOT_ENOUGH_TRUST":
            assert isinstance(err, verifier.ErrNotEnoughTrust), (
                f"{ctx}: expected NOT_ENOUGH_TRUST, got {err!r}"
            )
        elif verdict == "INVALID":
            assert isinstance(
                err, (verifier.ErrInvalidHeader, verifier.ErrOldHeaderExpired)
            ), f"{ctx}: expected INVALID, got {err!r}"
        else:
            pytest.fail(f"{ctx}: unexpected verdict {verdict!r}")

        if err is None:  # advance trust, as the driver does
            trusted_sh = new_sh
            trusted_next_vals = parse_valset(blk["next_validator_set"])
