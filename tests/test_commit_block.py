"""Columnar-from-decode commit path (ISSUE 4): CommitBlock <-> CommitSig
lazy-view parity, fused commit prep differential (numpy fallback vs
native vs the object paths — verdicts, tally, blame, absent/nil flags),
EntryBlock RAM columns, and the pipeline's single dispatch-owner
thread."""

import threading

import numpy as np
import pytest

try:
    from tendermint_tpu.crypto import ed25519
except ModuleNotFoundError:
    # No cryptography wheel in this container. Do NOT flip
    # TM_TPU_PUREPY_CRYPTO here (env leaks into later-collected modules);
    # test_commit_block_isolated.py re-runs this module in a subprocess
    # with the fallback enabled instead.
    pytest.skip(
        "ed25519 backend unavailable (runs via test_commit_block_isolated.py)",
        allow_module_level=True,
    )

from tendermint_tpu.ops import backend, commit_prep as cp
from tendermint_tpu.ops import pipeline as pl
from tendermint_tpu.ops import sha512 as sha
from tendermint_tpu.ops.entry_block import CommitBlock, EntryBlock
from tendermint_tpu.types import validation
from tendermint_tpu.types.block import (
    BLOCK_ID_FLAG_ABSENT,
    BLOCK_ID_FLAG_COMMIT,
    BLOCK_ID_FLAG_NIL,
    BlockID,
    Commit,
    CommitSig,
    CommitSigs,
    PartSetHeader,
)
from tendermint_tpu.types.validator_set import Validator, ValidatorSet
from tendermint_tpu.wire.canonical import Timestamp

CHAIN_ID = "commit-block-test"


def _block_id():
    return BlockID(
        hash=b"\x11" * 32,
        part_set_header=PartSetHeader(total=1, hash=b"\x22" * 32),
    )


def _signed_commit(n, height=7, bad=(), nil=(), absent=(), power=None):
    """A REAL signed commit over n validators (index-aligned set)."""
    sks = [ed25519.gen_priv_key(bytes([i + 1]) * 32) for i in range(n)]
    vals = [
        Validator.new(sk.pub_key(), (power or [100] * n)[i])
        for i, sk in enumerate(sks)
    ]
    vset = ValidatorSet(validators=vals, proposer=vals[0])
    bid = _block_id()
    sigs = []
    for i, sk in enumerate(sks):
        if i in absent:
            sigs.append(CommitSig.absent())
            continue
        flag = BLOCK_ID_FLAG_NIL if i in nil else BLOCK_ID_FLAG_COMMIT
        ts = Timestamp(seconds=1_700_000_000, nanos=i + 1)
        commit_stub = Commit(height=height, round=0, block_id=bid)
        tpl = commit_stub.sign_bytes_template(CHAIN_ID, flag)
        from tendermint_tpu.wire.canonical import compose_vote_sign_bytes

        sb = compose_vote_sign_bytes(tpl, ts)
        sig = sk.sign(sb)
        if i in bad:
            sig = sig[:-1] + bytes([sig[-1] ^ 1])
        sigs.append(
            CommitSig(
                block_id_flag=flag,
                validator_address=sk.pub_key().address(),
                timestamp=ts,
                signature=sig,
            )
        )
    return vset, bid, Commit(height=height, round=0, block_id=bid,
                             signatures=sigs)


def _random_commit(n, seed=0, nil=(), absent=()):
    """Structurally-valid commit with random (invalid) signatures — for
    prep-stage differentials where validity doesn't matter."""
    rng = np.random.RandomState(seed)
    vals = []
    sigs = []
    for i in range(n):
        pk = ed25519.PubKey(rng.randint(0, 256, 32, dtype=np.uint8).tobytes())
        vals.append(Validator.new(pk, 50 + (i % 7)))
        if i in absent:
            sigs.append(CommitSig.absent())
            continue
        flag = BLOCK_ID_FLAG_NIL if i in nil else BLOCK_ID_FLAG_COMMIT
        sigs.append(
            CommitSig(
                block_id_flag=flag,
                validator_address=pk.address(),
                timestamp=Timestamp(
                    seconds=1_700_000_000 + (i % 3), nanos=(i * 37) % 1000
                ),
                signature=rng.randint(0, 256, 64, dtype=np.uint8).tobytes(),
            )
        )
    vset = ValidatorSet(validators=vals, proposer=vals[0])
    return vset, Commit(height=42, round=1, block_id=_block_id(),
                        signatures=sigs)


class TestCommitSigsView:
    def test_decode_is_columnar_and_lazy(self):
        _, commit = _random_commit(40, nil=(3, 9), absent=(5,))
        dec = Commit.decode(commit.encode())
        assert isinstance(dec.signatures, CommitSigs)
        assert dec.commit_block() is not None
        # lazy: only the accessed index materializes
        _ = dec.signatures[7]
        mat = [x is not None for x in dec.signatures._items]
        assert mat[7] and sum(mat) == 1

    def test_view_parity_with_object_decode(self):
        _, commit = _random_commit(60, nil=(1, 2), absent=(4, 44))
        enc = commit.encode()
        dec = Commit.decode(enc)
        assert list(dec.signatures) == list(commit.signatures)
        assert dec.signatures == list(commit.signatures)
        assert dec.encode() == enc
        assert dec.hash() == commit.hash()
        assert dec == commit

    def test_mutation_detaches_columns(self):
        _, commit = _random_commit(10)
        dec = Commit.decode(commit.encode())
        cs = dec.signatures[2]
        dec.signatures[2] = CommitSig(
            block_id_flag=cs.block_id_flag,
            validator_address=cs.validator_address,
            timestamp=cs.timestamp,
            signature=b"\x07" * 64,
        )
        assert dec.signatures.block() is None
        blk = dec.commit_block()  # rebuilt from the mutated objects
        assert blk is not None
        assert blk.sig[2].tobytes() == b"\x07" * 64

    def test_reassignment_invalidates_block_and_hash(self):
        _, commit = _random_commit(8)
        dec = Commit.decode(commit.encode())
        h0 = dec.hash()
        blk0 = dec.commit_block()
        assert blk0 is not None
        dec.signatures = [CommitSig.absent()] * 8
        blk1 = dec.commit_block()  # rebuilt from the new list
        assert blk1 is not blk0
        assert (blk1.flags == 1).all()
        assert dec.hash() != h0

    def test_in_place_mutation_of_plain_list_never_sees_stale_columns(self):
        # commit_block() must NOT cache object-built columns: a plain
        # list's `signatures[i] = ...` has no hook, so a cache would let
        # a tampered signature verify against pre-mutation bytes
        _, commit = _random_commit(6)
        blk0 = commit.commit_block()
        assert blk0 is not None
        cs = commit.signatures[2]
        commit.signatures[2] = CommitSig(
            block_id_flag=cs.block_id_flag,
            validator_address=cs.validator_address,
            timestamp=cs.timestamp,
            signature=b"\xff" * 64,
        )
        blk1 = commit.commit_block()
        assert blk1.sig[2].tobytes() == b"\xff" * 64

    def test_detached_view_second_mutation_never_sees_stale_columns(self):
        _, commit = _random_commit(6)
        dec = Commit.decode(commit.encode())
        cs = dec.signatures[1]

        def forged(sig_byte):
            return CommitSig(
                block_id_flag=cs.block_id_flag,
                validator_address=cs.validator_address,
                timestamp=cs.timestamp,
                signature=bytes([sig_byte]) * 64,
            )

        dec.signatures[1] = forged(0xAA)  # detaches the view
        assert dec.commit_block().sig[1].tobytes() == b"\xaa" * 64
        dec.signatures[1] = forged(0xBB)  # second mutation, view already
        assert dec.commit_block().sig[1].tobytes() == b"\xbb" * 64

    def test_non_canonical_wire_falls_back_to_objects(self):
        # an absent CommitSig carrying a signature is invalid-but-
        # decodable; the columnar form cannot represent it, so decode
        # must keep plain objects (and validate_basic still rejects it)
        from tendermint_tpu.wire.proto import ProtoWriter

        w = ProtoWriter()
        w.write_varint(1, 7)
        w.write_message(2, _block_id().encode(), always=True)
        bad_cs = CommitSig(
            block_id_flag=BLOCK_ID_FLAG_ABSENT,
            signature=b"\x01" * 64,
            timestamp=Timestamp(seconds=1, nanos=0),
        )
        # build via encode(): absent-with-signature still encodes
        commit = Commit(height=7, round=0, block_id=_block_id(),
                        signatures=[bad_cs, CommitSig.absent()])
        dec = Commit.decode(commit.encode())
        assert not isinstance(dec.signatures, CommitSigs)
        assert dec.commit_block() is None
        with pytest.raises(ValueError):
            dec.validate_basic()

    def test_commit_block_rejects_non_canonical_objects(self):
        _, commit = _random_commit(4)
        sigs = list(commit.signatures)
        cs = sigs[1]
        sigs[1] = CommitSig(
            block_id_flag=cs.block_id_flag,
            validator_address=cs.validator_address,
            timestamp=cs.timestamp,
            signature=b"\x01" * 63,  # wrong length
        )
        commit.signatures = sigs
        assert commit.commit_block() is None


class TestFusedPrepDifferential:
    @pytest.mark.parametrize("mode", [
        0,
        cp.MODE_COUNT_FOR_BLOCK,
        cp.MODE_SELECT_COMMIT_ONLY | cp.MODE_EARLY_STOP,
        cp.MODE_SELECT_COMMIT_ONLY | cp.MODE_COUNT_FOR_BLOCK
        | cp.MODE_EARLY_STOP,
    ])
    @pytest.mark.parametrize("ram", [0, 256])
    def test_numpy_matches_object_sign_bytes(self, mode, ram):
        vset, commit = _random_commit(120, nil=(0, 7, 33), absent=(5, 60))
        dec = Commit.decode(commit.encode())
        cb = dec.commit_block()
        cols = vset.ed25519_columns()
        pc = dec.sign_bytes_template(CHAIN_ID, BLOCK_ID_FLAG_COMMIT)
        pn = dec.sign_bytes_template(CHAIN_ID, BLOCK_ID_FLAG_NIL)
        needed = vset.total_voting_power() * 2 // 3
        sel, tallied, blk = cp._prep_commit_numpy(
            cb, cols[0], cols[1], pc[0], pn[0], pc[1], needed, mode, ram
        )
        assert blk is not None
        # per-lane parity with the object-path sign bytes + columns
        for j in range(len(sel)):
            i = int(sel[j])
            assert blk.msg(j) == dec.vote_sign_bytes(CHAIN_ID, i)
            assert blk.pub[j].tobytes() == vset.validators[i].pub_key.bytes()
            assert blk.sig[j].tobytes() == dec.signatures[i].signature
        if ram:
            assert blk.ram_hi is not None
            hi, lo, counts = sha.pad_ram_block(
                blk[0 : len(blk)], len(blk), ram
            )
            got = sha.pad_ram_rows(blk, len(blk), ram)
            assert got is not None
            assert np.array_equal(got[0], hi)
            assert np.array_equal(got[1], lo)
            assert np.array_equal(got[2], counts)

    @pytest.mark.native_required
    @pytest.mark.parametrize("mode", [
        0,
        cp.MODE_SELECT_COMMIT_ONLY,
        cp.MODE_COUNT_FOR_BLOCK,
        cp.MODE_EARLY_STOP,
        cp.MODE_SELECT_COMMIT_ONLY | cp.MODE_EARLY_STOP,
        cp.MODE_COUNT_FOR_BLOCK | cp.MODE_EARLY_STOP,
    ])
    def test_native_matches_numpy(self, mode):
        from tendermint_tpu.native import load as _load_native

        native = _load_native()
        if not hasattr(native, "commit_prep_fused"):
            pytest.skip("tm_native built without commit_prep_fused")
        vset, commit = _random_commit(150, nil=(2, 9, 77), absent=(1, 80))
        # edge-case timestamps: zero seconds, negative nanos, zero nanos
        sigs = list(commit.signatures)
        for i, ts in ((3, Timestamp(0, 5)), (4, Timestamp(9, -3)),
                      (6, Timestamp(12, 0))):
            cs = sigs[i]
            sigs[i] = CommitSig(
                block_id_flag=cs.block_id_flag,
                validator_address=cs.validator_address,
                timestamp=ts,
                signature=cs.signature,
            )
        commit.signatures = sigs
        dec = Commit.decode(commit.encode())
        cb = dec.commit_block()
        cols = vset.ed25519_columns()
        pc = dec.sign_bytes_template(CHAIN_ID, BLOCK_ID_FLAG_COMMIT)
        pn = dec.sign_bytes_template(CHAIN_ID, BLOCK_ID_FLAG_NIL)
        for thr in (100, vset.total_voting_power() * 2 // 3, 10 ** 12):
            for ram in (0, 256):
                a = cp.prep_commit(cb, cols[0], cols[1], pc[0], pn[0],
                                   pc[1], thr, mode, ram)
                b = cp._prep_commit_numpy(cb, cols[0], cols[1], pc[0],
                                          pn[0], pc[1], thr, mode, ram)
                assert np.array_equal(a[0], b[0])
                assert a[1] == b[1]
                assert (a[2] is None) == (b[2] is None)
                if a[2] is None:
                    continue
                assert np.array_equal(a[2].pub, b[2].pub)
                assert np.array_equal(a[2].sig, b[2].sig)
                assert np.array_equal(a[2].offsets, b[2].offsets)
                assert bytes(a[2].msgs) == bytes(b[2].msgs)
                for x, y in ((a[2].ram_hi, b[2].ram_hi),
                             (a[2].ram_lo, b[2].ram_lo),
                             (a[2].ram_counts, b[2].ram_counts)):
                    assert (x is None) == (y is None)
                    if x is not None:
                        assert np.array_equal(np.asarray(x, dtype=np.uint32),
                                              np.asarray(y, dtype=np.uint32))

    def test_commit_entries_fused_matches_legacy(self):
        vset, commit = _random_commit(90, absent=(4,))
        dec = Commit.decode(commit.encode())
        needed = vset.total_voting_power() * 2 // 3
        blk_f, tallied_f = pl.commit_entries(CHAIN_ID, vset, dec, needed)
        blk_l, tallied_l = pl.commit_entries_legacy(
            CHAIN_ID, vset, commit, needed
        )
        assert tallied_f == tallied_l
        assert np.array_equal(blk_f.pub, blk_l.pub)
        assert np.array_equal(blk_f.sig, blk_l.sig)
        assert np.array_equal(blk_f.offsets, np.asarray(blk_l.offsets))
        assert bytes(blk_f.msgs) == bytes(blk_l.msgs)

    def test_not_enough_power_parity(self):
        vset, commit = _random_commit(10, absent=tuple(range(2, 10)))
        dec = Commit.decode(commit.encode())
        needed = vset.total_voting_power() * 2 // 3
        with pytest.raises(validation.ErrNotEnoughVotingPowerSigned) as e1:
            pl.commit_entries(CHAIN_ID, vset, dec, needed)
        with pytest.raises(validation.ErrNotEnoughVotingPowerSigned) as e2:
            pl.commit_entries_legacy(CHAIN_ID, vset, commit, needed)
        assert str(e1.value) == str(e2.value)


class TestVerifyCommitFused:
    def test_valid_commit_verifies_via_fused_path(self, monkeypatch):
        vset, bid, commit = _signed_commit(6, nil=(4,))
        dec = Commit.decode(commit.encode())
        calls = []
        orig = cp.prep_commit

        def spy(*a, **k):
            calls.append(1)
            return orig(*a, **k)

        monkeypatch.setattr(cp, "prep_commit", spy)
        validation.verify_commit(CHAIN_ID, vset, bid, 7, dec)
        assert calls, "fused prep was not taken for a columnar commit"

    def test_blame_parity_fused_vs_object_path(self, monkeypatch):
        vset, bid, commit = _signed_commit(6, bad=(3,))
        dec = Commit.decode(commit.encode())
        with pytest.raises(ValueError) as e_fused:
            validation.verify_commit(CHAIN_ID, vset, bid, 7, dec)
        # force the object path: no validator columns
        monkeypatch.setattr(ValidatorSet, "ed25519_columns", lambda self: None)
        with pytest.raises(ValueError) as e_obj:
            validation.verify_commit(CHAIN_ID, vset, bid, 7, commit)
        assert str(e_fused.value) == str(e_obj.value)
        assert "wrong signature (#3)" in str(e_fused.value)

    def test_light_path_parity(self, monkeypatch):
        vset, bid, commit = _signed_commit(8, bad=(6,), absent=(1,))
        dec = Commit.decode(commit.encode())
        with pytest.raises(ValueError) as e_fused:
            validation.verify_commit_light(CHAIN_ID, vset, bid, 7, dec)
        monkeypatch.setattr(ValidatorSet, "ed25519_columns", lambda self: None)
        with pytest.raises(ValueError) as e_obj:
            validation.verify_commit_light(CHAIN_ID, vset, bid, 7, commit)
        assert str(e_fused.value) == str(e_obj.value)

    def test_light_early_stop_skips_trailing_bad_sig(self):
        # with equal powers, 2/3 is crossed before the last lane: the
        # light path must accept without ever verifying the bad tail
        # signature (countAllSignatures=false semantics)
        vset, bid, commit = _signed_commit(9, bad=(8,))
        dec = Commit.decode(commit.encode())
        validation.verify_commit_light(CHAIN_ID, vset, bid, 7, dec)

    def test_not_enough_power_error_parity(self, monkeypatch):
        vset, bid, commit = _signed_commit(6, absent=(1, 2, 3, 4))
        dec = Commit.decode(commit.encode())
        with pytest.raises(validation.ErrNotEnoughVotingPowerSigned) as e1:
            validation.verify_commit(CHAIN_ID, vset, bid, 7, dec)
        monkeypatch.setattr(ValidatorSet, "ed25519_columns", lambda self: None)
        with pytest.raises(validation.ErrNotEnoughVotingPowerSigned) as e2:
            validation.verify_commit(CHAIN_ID, vset, bid, 7, commit)
        assert str(e1.value) == str(e2.value)


class TestEntryBlockRamColumns:
    def _block_with_ram(self, n=20, seed=3):
        vset, commit = _random_commit(n, seed=seed)
        dec = Commit.decode(commit.encode())
        needed = vset.total_voting_power() * 2 // 3
        blk, _ = pl.commit_entries(CHAIN_ID, vset, dec, needed)
        assert blk.ram_hi is not None
        return blk

    def test_slice_and_concat_preserve_ram(self):
        blk = self._block_with_ram(24)
        a, b = blk[:10], blk[10:]
        assert a.ram_hi is not None and b.ram_hi is not None
        back = EntryBlock.concat([a, b])
        assert np.array_equal(
            np.asarray(back.ram_hi, dtype=np.uint32),
            np.asarray(blk.ram_hi, dtype=np.uint32),
        )
        assert np.array_equal(back.ram_counts, blk.ram_counts)
        assert bytes(back.msgs_contiguous()[0]) == bytes(
            blk.msgs_contiguous()[0]
        )

    def test_concat_drops_ram_when_any_block_lacks_it(self):
        blk = self._block_with_ram(12)
        plain = EntryBlock(blk.pub.copy(), blk.sig.copy(),
                           bytes(blk.msgs_contiguous()[0]),
                           np.asarray(blk.offsets).copy())
        out = EntryBlock.concat([blk, plain])
        assert out.ram_hi is None

    def test_concat_single_block_passes_through_by_identity(self):
        blk = self._block_with_ram(8)
        assert EntryBlock.concat([blk]) is blk
        assert EntryBlock.concat([EntryBlock.empty(), blk]) is blk

    def test_prepare_device_hash_ram_fast_path_matches_generic(self):
        blk = self._block_with_ram(30)
        bucket = 128
        fast = backend.prepare_batch_device_hash(blk, bucket)
        plain = EntryBlock(blk.pub, blk.sig,
                           bytes(blk.msgs_contiguous()[0]),
                           np.asarray(blk.offsets))
        generic = backend.prepare_batch_device_hash(plain, bucket)
        assert all(np.array_equal(a, b) for a, b in zip(fast, generic))


class TestDispatchOwnerThread:
    def _entries(self, n, tag=0, bad=()):
        out = []
        for i in range(n):
            sk = ed25519.gen_priv_key(bytes([tag + 1]) * 31 + bytes([i + 1]))
            m = b"own-%d-%d" % (tag, i)
            s = sk.sign(m)
            if i in bad:
                s = s[:-1] + bytes([s[-1] ^ 1])
            out.append((sk.pub_key().bytes(), m, s))
        return out

    def test_exactly_one_thread_issues_device_dispatches(self):
        v = pl.AsyncBatchVerifier(depth=2)
        try:
            futs = []
            threads = []
            # concurrent submitters: the relay-ownership invariant must
            # hold regardless of caller concurrency
            def submit_from_thread(t):
                futs.append(v.submit(self._entries(6, tag=t)))

            for t in range(6):
                th = threading.Thread(target=submit_from_thread, args=(t,))
                th.start()
                threads.append(th)
            for th in threads:
                th.join()
            for f in list(futs):
                assert np.asarray(f.result(timeout=120)).all()
        finally:
            v.close()
        assert len(v.dispatch_thread_idents) == 1
        (ident,) = v.dispatch_thread_idents
        assert ident == v._dispatch_thread.ident
        assert ident != threading.get_ident()

    def test_single_job_passthrough_to_prepare(self, monkeypatch):
        seen = []
        orig = pl.AsyncBatchVerifier._prepare

        def spy(entries):
            seen.append(entries)
            return orig(entries)

        monkeypatch.setattr(pl.AsyncBatchVerifier, "_prepare",
                            staticmethod(spy))
        from tendermint_tpu.ops.entry_block import as_block

        blk = as_block(self._entries(5))
        v = pl.AsyncBatchVerifier(depth=1)
        try:
            res = v.submit(blk).result(timeout=120)
            assert res.all()
        finally:
            v.close()
        assert any(e is blk for e in seen), (
            "single-job dispatch must hand the submitted EntryBlock "
            "through by identity (zero-copy)"
        )

    def test_oversized_submit_splits_and_reaggregates(self, monkeypatch):
        monkeypatch.setattr(backend, "max_coalesce", lambda: 8)
        v = pl.AsyncBatchVerifier(depth=2)
        try:
            ents = self._entries(20, bad=(13,))
            res = np.asarray(v.submit(ents).result(timeout=120))
        finally:
            v.close()
        assert res.shape == (20,)
        assert not res[13] and res.sum() == 19

    def test_dispatch_gauges_exported(self):
        from tendermint_tpu.libs.metrics import ops_stats

        v = pl.AsyncBatchVerifier(depth=1)
        try:
            assert v.submit(self._entries(4)).result(timeout=120).all()
        finally:
            v.close()
        stats = ops_stats()
        assert "dispatch_queue_depth" in stats
        assert "dispatch_busy_ratio" in stats
        assert 0.0 <= stats["dispatch_busy_ratio"] <= 1.0

    def test_queue_wait_span_recorded(self):
        from tendermint_tpu.observability import trace as _trace

        _trace.TRACER.clear()
        _trace.configure(enabled=True)
        try:
            v = pl.AsyncBatchVerifier(depth=1)
            try:
                assert v.submit(self._entries(4)).result(timeout=120).all()
            finally:
                v.close()
            names = {e[0] for e in _trace.TRACER.events()}
        finally:
            _trace.configure(enabled=False)
            _trace.TRACER.clear()
        assert "pipeline.queue_wait" in names
        assert "pipeline.dispatch" in names
