"""gRPC transports + signer conformance harness.

Reference parity: abci/client/grpc_client.go:46 + abci/server (kvstore
over gRPC), privval/grpc/ (remote signer), and tools/tm-signer-harness
(the conformance battery, run here against the local FilePV, the socket
remote signer, and the gRPC remote signer — all three must pass the same
checks)."""

import pytest

pytest.importorskip("grpc")

from tendermint_tpu.abci import KVStoreApplication, types as abci  # noqa: E402
from tendermint_tpu.abci.grpc import GRPCClient, GRPCServer  # noqa: E402
from tendermint_tpu.crypto import ed25519  # noqa: E402
from tendermint_tpu.privval import FilePV  # noqa: E402
from tendermint_tpu.privval.grpc import GRPCSignerClient, GRPCSignerServer  # noqa: E402
from tendermint_tpu.tools.signer_harness import run_harness  # noqa: E402


class TestGRPCABCI:
    def test_kvstore_over_grpc(self):
        srv = GRPCServer(KVStoreApplication(), "127.0.0.1:0")
        srv.start()
        c = GRPCClient(srv.address)
        try:
            assert c.echo("ping") == "ping"
            c.flush()
            assert c.check_tx(abci.RequestCheckTx(tx=b"a=1")).code == 0
            c.begin_block(abci.RequestBeginBlock())
            assert c.deliver_tx(abci.RequestDeliverTx(tx=b"a=1")).code == 0
            c.end_block(abci.RequestEndBlock(height=1))
            commit = c.commit()
            assert commit.data  # app hash
            q = c.query(abci.RequestQuery(data=b"a", path="/key"))
            assert q.value == b"1"
            info = c.info(abci.RequestInfo())
            assert info.last_block_height == 1
        finally:
            c.close()
            srv.stop()

    def test_grpc_app_runs_a_chain(self):
        """A consensus node drives its application over the gRPC ABCI
        connection (node.go 'grpc' transport parity)."""
        from tests.test_consensus import FAST, make_node

        srv = GRPCServer(KVStoreApplication(), "127.0.0.1:0")
        srv.start()
        sk = ed25519.gen_priv_key(bytes([21]) * 32)
        cs, bstore, _ = make_node([sk], 0, proxy=GRPCClient(srv.address))
        cs.start()
        try:
            cs.wait_for_height(3, timeout=60)
        finally:
            cs.stop()
            srv.stop()
        assert bstore.height() >= 3


class TestSignerHarness:
    def _expect_pass(self, signer, pv):
        rep = run_harness(signer, expected_pub_key=pv.get_pub_key())
        assert rep.passed, [(r.name, r.detail) for r in rep.results if not r.ok]
        assert len(rep.results) >= 6

    def test_file_pv_conformance(self):
        pv = FilePV(ed25519.gen_priv_key(bytes([22]) * 32))
        self._expect_pass(pv, pv)

    def test_grpc_signer_conformance(self):
        pv = FilePV(ed25519.gen_priv_key(bytes([23]) * 32))
        srv = GRPCSignerServer(pv, "127.0.0.1:0")
        srv.start()
        c = GRPCSignerClient(srv.address)
        try:
            self._expect_pass(c, pv)
        finally:
            c.close()
            srv.stop()

    def test_socket_signer_conformance(self):
        import socket as _socket

        from tendermint_tpu.privval.remote import SignerClient, SignerServer

        pv = FilePV(ed25519.gen_priv_key(bytes([24]) * 32))
        s = _socket.socket()
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]
        s.close()
        listen = f"tcp://127.0.0.1:{port}"
        client = SignerClient(listen)
        server = SignerServer(pv, listen)
        server.start()
        try:
            self._expect_pass(client, pv)
        finally:
            server.stop()
            client.close()
