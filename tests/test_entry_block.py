"""Columnar EntryBlock path: tuple <-> block parity (args, verdicts,
blame) across the prep/kernel stack, coalescing straddle, native-absent
fallbacks, and the RLC env-knob hardening (ISSUE 2 satellites)."""

import os

import numpy as np
import pytest

try:
    from tendermint_tpu.crypto import ed25519
except ModuleNotFoundError:
    # No cryptography wheel in this container. Do NOT flip
    # TM_TPU_PUREPY_CRYPTO here: the env leaks into every later-collected
    # module and unlocks slow OpenSSL-dependent e2e failures.
    # test_entry_block_isolated.py re-runs this module in a subprocess
    # with the fallback enabled instead.
    pytest.skip(
        "ed25519 backend unavailable (runs via test_entry_block_isolated.py)",
        allow_module_level=True,
    )
from tendermint_tpu.ops import backend
from tendermint_tpu.ops import pallas_rlc
from tendermint_tpu.ops import pipeline as pl
from tendermint_tpu.ops.entry_block import EntryBlock, as_block


def _entries(n, tag=0, bad=(), msg_len=None):
    out = []
    for i in range(n):
        sk = ed25519.gen_priv_key(bytes([tag + 1]) * 31 + bytes([i + 1]))
        m = b"eb-%d-%d" % (tag, i)
        if msg_len:
            m = m.ljust(msg_len, b"x")
        s = sk.sign(m)
        if i in bad:
            s = s[:-1] + bytes([s[-1] ^ 1])
        out.append((sk.pub_key().bytes(), m, s))
    return out


def _no_native(monkeypatch):
    import tendermint_tpu.native as native

    monkeypatch.setattr(native, "load", lambda: None)


class TestEntryBlock:
    def test_roundtrip_and_shapes(self):
        ents = _entries(5)
        blk = EntryBlock.from_entries(ents)
        assert len(blk) == 5
        assert blk.pub.shape == (5, 32) and blk.sig.shape == (5, 64)
        assert blk.to_entries() == ents
        assert blk.entry(3) == ents[3]
        assert blk.msg(2) == ents[2][1]

    def test_as_block_passthrough(self):
        blk = EntryBlock.from_entries(_entries(3))
        assert as_block(blk) is blk
        assert as_block([]).n == 0

    def test_slicing_is_zero_copy_and_correct(self):
        ents = _entries(7)
        blk = EntryBlock.from_entries(ents)
        sub = blk[2:5]
        assert sub.to_entries() == ents[2:5]
        assert sub.pub.base is not None  # numpy view, not a copy
        # nested slice of a slice
        assert sub[1:3].to_entries() == ents[3:5]
        # full + empty slices
        assert blk[:].to_entries() == ents
        assert len(blk[4:4]) == 0

    def test_concat(self):
        a, b, c = (_entries(3, tag=t) for t in range(3))
        blk = EntryBlock.concat(
            [EntryBlock.from_entries(a), EntryBlock.from_entries(b),
             EntryBlock.from_entries(c)]
        )
        assert blk.to_entries() == a + b + c
        # concat of slices (the coalescing straddle shape)
        blk2 = EntryBlock.concat(
            [EntryBlock.from_entries(a)[1:3], EntryBlock.from_entries(b)[0:2]]
        )
        assert blk2.to_entries() == a[1:3] + b[0:2]
        assert len(EntryBlock.concat([])) == 0

    def test_length_validation(self):
        with pytest.raises(ValueError, match="triples"):
            EntryBlock.from_entries([(b"\x00" * 31, b"m", b"\x00" * 64)])
        with pytest.raises(ValueError, match="triples"):
            EntryBlock.from_entries([(b"\x00" * 32, b"m", b"\x00" * 63)])

    def test_non_monotonic_offsets_rejected(self):
        # a decreasing offset table would wrap to a huge size_t length in
        # the GIL-released native consumers — must be rejected up front
        with pytest.raises(ValueError, match="non-decreasing"):
            EntryBlock(
                np.zeros((2, 32), dtype=np.uint8),
                np.zeros((2, 64), dtype=np.uint8),
                b"x" * 10,
                np.array([0, 8, 4], dtype=np.int64),
            )

    def test_commit_entries_rejects_wrong_size_key(self):
        from tests.test_types import CHAIN_ID, build_commit

        _, vset, _, commit = build_commit(n=4, height=6, round_=0)

        class FakeKey:
            def bytes(self):
                return b"\x00" * 33

        v = vset.validators[1]
        vset.validators[1] = type(v)(
            address=v.address, pub_key=FakeKey(), voting_power=v.voting_power,
            proposer_priority=v.proposer_priority,
        )
        with pytest.raises(TypeError, match="not ed25519"):
            pl.commit_entries(
                CHAIN_ID, vset, commit, vset.total_voting_power() * 2 // 3
            )


class TestSignBytesBlock:
    def test_block_matches_many_and_single(self):
        from tests.test_types import CHAIN_ID, build_commit

        _, vset, _, commit = build_commit(n=6, height=9, round_=0)
        idxs = list(range(6))
        ref = [commit.vote_sign_bytes(CHAIN_ID, i) for i in idxs]
        assert commit.vote_sign_bytes_many(CHAIN_ID, idxs) == ref
        buf, offs = commit.vote_sign_bytes_block(CHAIN_ID, idxs)
        got = [bytes(buf[offs[i] : offs[i + 1]]) for i in range(6)]
        assert got == ref

    def test_block_pure_python_fallback_parity(self, monkeypatch):
        from tests.test_types import CHAIN_ID, build_commit

        _, vset, _, commit = build_commit(n=6, height=9, round_=0)
        idxs = list(range(6))
        buf_n, offs_n = commit.vote_sign_bytes_block(CHAIN_ID, idxs)
        _no_native(monkeypatch)
        commit._sb_tpl = None
        buf_p, offs_p = commit.vote_sign_bytes_block(CHAIN_ID, idxs)
        assert bytes(buf_n) == bytes(buf_p)
        assert np.array_equal(offs_n, offs_p)

    def test_vectorized_composer_differential(self):
        """Grouped numpy composer == per-call ProtoWriter composer across
        varint length boundaries and proto3 zero-skips."""
        from tendermint_tpu.wire import canonical as C

        tpl = C.canonical_vote_template(
            chain_id="eb-chain", msg_type=C.SIGNED_MSG_TYPE_PRECOMMIT,
            height=77, round_=1, block_id=None,
        )
        cases = [0, 1, 127, 128, 16383, 16384, 2**31 - 1, 2**40,
                 C.GO_ZERO_TIME_SECONDS, 1_700_000_000]
        tss = [C.Timestamp(s, nn) for s in cases for nn in cases]
        # pad above the n >= 64 vectorized-path threshold
        tss = tss + tss
        ref = [C.compose_vote_sign_bytes(tpl, ts) for ts in tss]
        buf, offs = C.compose_vote_sign_bytes_block(tpl, tss)
        got = [buf[offs[i] : offs[i + 1]] for i in range(len(tss))]
        assert got == ref


class TestPrepParity:
    """Identical kernel argument tuples from tuple lists and EntryBlocks,
    with and without the native module (native-absent fallback parity)."""

    @pytest.mark.parametrize("use_native", [True, False])
    @pytest.mark.parametrize(
        "prep", ["prepare_batch", "prepare_batch_device_hash", "prepare_compact"]
    )
    def test_args_match(self, monkeypatch, prep, use_native):
        if not use_native:
            _no_native(monkeypatch)
        elif __import__("tendermint_tpu.native", fromlist=["load"]).load() is None:
            pytest.skip("native module unavailable")
        ents = _entries(11, bad=(2,))
        blk = EntryBlock.from_entries(ents)
        if prep == "prepare_compact":
            from tendermint_tpu.ops import pallas_verify

            fn = pallas_verify.prepare_compact
        else:
            fn = getattr(backend, prep)
        a = fn(ents, 16)
        b = fn(blk, 16)
        assert len(a) == len(b)
        for x, y in zip(a, b):
            assert np.array_equal(np.asarray(x), np.asarray(y))

    @pytest.mark.parametrize("use_native", [True, False])
    def test_prepare_rlc_args_match(self, monkeypatch, use_native):
        if not use_native:
            _no_native(monkeypatch)
        elif __import__("tendermint_tpu.native", fromlist=["load"]).load() is None:
            pytest.skip("native module unavailable")
        # deterministic z so tuple and block runs draw identical
        # coefficients (CPU backend: seed is honored)
        monkeypatch.setenv("TM_TPU_RLC_SEED", "7")
        M = pallas_rlc.M
        ents = _entries(2 * M + 1, bad=(1,))
        bucket = ((len(ents) + M - 1) // M + 1) * M  # one padding lane
        a = pallas_rlc.prepare_rlc(ents, bucket)
        b = pallas_rlc.prepare_rlc(EntryBlock.from_entries(ents), bucket)
        for x, y in zip(a, b):
            assert np.array_equal(np.asarray(x), np.asarray(y))

    def test_expand_lanes_blame_parity(self):
        M = pallas_rlc.M
        ents = _entries(2 * M, bad=(1, M + 2))
        lane_valid = np.array([False, False])
        per_tuple = pallas_rlc.expand_lanes(lane_valid, ents)
        per_block = pallas_rlc.expand_lanes(
            lane_valid, EntryBlock.from_entries(ents)
        )
        assert np.array_equal(per_tuple, per_block)
        expected = np.ones(2 * M, dtype=bool)
        expected[[1, M + 2]] = False
        assert np.array_equal(per_block, expected)

    def test_pad_ram_block_matches_list_path(self, monkeypatch):
        _no_native(monkeypatch)
        # empty-message and max-length edges
        sk = ed25519.gen_priv_key(b"\x09" * 32)
        ents = [
            (sk.pub_key().bytes(), b"", sk.sign(b"")),
            (sk.pub_key().bytes(), b"y" * backend.DEVICE_HASH_MAX_MSG,
             sk.sign(b"y" * backend.DEVICE_HASH_MAX_MSG)),
        ] + _entries(3)
        a = backend.prepare_batch_device_hash(ents, 8)
        b = backend.prepare_batch_device_hash(EntryBlock.from_entries(ents), 8)
        for x, y in zip(a, b):
            assert np.array_equal(np.asarray(x), np.asarray(y))


class TestKernelVerdictParity:
    def test_xla_verify_batch_tuple_vs_block(self):
        """Same verdicts and blame lanes through the XLA kernel on CPU
        for both representations."""
        ents = _entries(70, bad=(3, 41))
        ref = backend.verify_batch(ents)
        got = backend.verify_batch(EntryBlock.from_entries(ents))
        assert np.array_equal(ref, got)
        assert not got[3] and not got[41] and got.sum() == 68

    def test_device_verifier_add_block(self):
        bv = backend.Ed25519DeviceBatchVerifier(force_device=True)
        ents = _entries(70, bad=(5,))
        bv.add_block(
            EntryBlock.from_entries(ents),
            keys=[ed25519.PubKey(pk) for pk, _, _ in ents],
        )
        ok, valid = bv.verify()
        assert not ok and valid[5] is False and sum(valid) == 69

    def test_add_block_rejects_wrong_key_type(self):
        bv = backend.Ed25519DeviceBatchVerifier()
        with pytest.raises(TypeError, match="not ed25519"):
            bv.add_block(EntryBlock.from_entries(_entries(2)), keys=[object(), object()])


class TestCoalescingStraddle:
    def test_job_straddles_two_device_batches(self, monkeypatch):
        """A pipelined job whose signatures split across two coalesced
        device batches re-aggregates per-job verdicts (and blame indices
        WITHIN the job) correctly."""
        from tests.test_types import CHAIN_ID, build_commit

        monkeypatch.setattr(backend, "BUCKETS", (16,))
        jobs = []
        # commit_entries early-stops past 2/3: 10 validators x 100 power
        # -> 7 entries per job. With max_b=16, job 2's entries split 2+5
        # across the first and second device batches; the tampered lane
        # (entry 5 of job 2) lands in the SECOND batch segment.
        commits = [build_commit(n=10, height=40 + i, round_=0) for i in range(3)]
        for i, (_, vset, bid, commit) in enumerate(commits):
            if i == 2:
                cs = commit.signatures[5]
                sig = cs.signature[:-1] + bytes([cs.signature[-1] ^ 1])
                commit.signatures[5] = type(cs)(
                    block_id_flag=cs.block_id_flag,
                    validator_address=cs.validator_address,
                    timestamp=cs.timestamp,
                    signature=sig,
                )
            jobs.append((vset, bid, 40 + i, commit))
        v = pl.AsyncBatchVerifier(depth=2)
        try:
            errors = pl.verify_commits_pipelined(CHAIN_ID, jobs, verifier=v)
        finally:
            v.close()
        assert errors[0] is None and errors[1] is None
        assert errors[2] is not None and "entry 5" in errors[2]

    def test_worker_coalesces_blocks(self, monkeypatch):
        monkeypatch.setattr(backend, "max_coalesce", lambda: 16)
        v = pl.AsyncBatchVerifier(depth=2)
        try:
            futs = [
                v.submit(EntryBlock.from_entries(
                    _entries(6, tag=t, bad=(2,) if t == 1 else ())
                ))
                for t in range(4)
            ]
            results = [f.result(timeout=120) for f in futs]
        finally:
            v.close()
        for t, res in enumerate(results):
            assert res.shape == (6,)
            if t == 1:
                assert not res[2] and res.sum() == 5
            else:
                assert res.all()

    def test_idle_worker_wakes_promptly(self):
        import time

        v = pl.AsyncBatchVerifier(depth=2)
        try:
            time.sleep(0.3)  # let the worker go idle (event wait path)
            t0 = time.monotonic()
            res = v.submit(_entries(4)).result(timeout=60)
            assert res.all()
        finally:
            t0 = time.monotonic()
            v.close()
            assert time.monotonic() - t0 < 2.0  # close() sets the wake event


class TestRlcEnvHardening:
    def test_rlc_buckets_respect_cap(self):
        assert pallas_rlc.RLC_BUCKETS == tuple(sorted(pallas_rlc.RLC_BUCKETS))
        assert pallas_rlc.RLC_BUCKETS[-1] == pallas_rlc.MAX_SIGS
        step = pallas_rlc.M * pallas_rlc.BLOCK_LANES
        assert all(b % step == 0 and b <= pallas_rlc.MAX_SIGS
                   for b in pallas_rlc.RLC_BUCKETS)

    def test_plan_bucket_never_exceeds_cap(self):
        for n in (1, 511, 512, 513, 10240, pallas_rlc.MAX_SIGS,
                  pallas_rlc.MAX_SIGS + 1):
            bucket, g, block = pallas_rlc.plan_bucket(n)
            assert bucket <= pallas_rlc.MAX_SIGS
            assert g % block == 0

    def test_max_sigs_validated_at_import(self):
        import subprocess
        import sys

        env = dict(os.environ, TM_TPU_RLC_MAX_SIGS="1000",
                   JAX_PLATFORMS="cpu")
        r = subprocess.run(
            [sys.executable, "-c", "import tendermint_tpu.ops.pallas_rlc"],
            capture_output=True, env=env, timeout=120,
        )
        assert r.returncode != 0
        assert b"TM_TPU_RLC_MAX_SIGS" in r.stderr

    def test_seed_refused_on_tpu_backend(self, monkeypatch):
        import warnings

        monkeypatch.setenv("TM_TPU_RLC_SEED", "5")
        monkeypatch.delenv("TM_TPU_RLC_SEED_UNSAFE", raising=False)
        monkeypatch.setattr(pallas_rlc.jax, "default_backend", lambda: "tpu")
        monkeypatch.setattr(pallas_rlc, "_seed_refused", False)
        with warnings.catch_warnings(record=True) as w:
            warnings.simplefilter("always")
            z1 = pallas_rlc._gen_z(64)
            z2 = pallas_rlc._gen_z(64)
        assert any("TM_TPU_RLC_SEED ignored" in str(x.message) for x in w)
        # seed ignored: draws are CSPRNG, not the deterministic stream
        assert not np.array_equal(z1, z2)

    def test_seed_honored_off_tpu_and_with_override(self, monkeypatch):
        monkeypatch.setenv("TM_TPU_RLC_SEED", "5")
        z1 = pallas_rlc._gen_z(32)
        z2 = pallas_rlc._gen_z(32)
        assert np.array_equal(z1, z2)  # cpu backend: deterministic ok
        monkeypatch.setattr(pallas_rlc.jax, "default_backend", lambda: "tpu")
        monkeypatch.setenv("TM_TPU_RLC_SEED_UNSAFE", "1")
        z3 = pallas_rlc._gen_z(32)
        assert np.array_equal(z1, z3)


class TestReplayConsoleStep:
    def _playback(self, handler, height=10):
        """A Playback shell around a stub consensus state — step() logic
        only, no stores/WAL."""
        from types import SimpleNamespace

        from tendermint_tpu.consensus.replay_console import Playback

        pb = Playback.__new__(Playback)
        pb.warnings = []
        pb.count = 0
        rs = SimpleNamespace(height=height)
        pb.cs = SimpleNamespace(
            rs=rs,
            _handle_timeout=handler,
            _set_proposal=handler,
            _add_proposal_block_part=handler,
            _try_add_vote=lambda v, p: handler(v),
        )
        return pb

    def _rec(self, **kw):
        from types import SimpleNamespace

        base = dict(end_height=None, timeout=None, msg_kind=None,
                    msg_payload=b"", peer_id="p")
        base.update(kw)
        return SimpleNamespace(**base)

    def test_corrupt_record_warns(self, capsys):
        pb = self._playback(lambda *a: None)
        pb._records = [self._rec(msg_kind="vote", msg_payload=b"\xff\x00garbage")]
        assert pb.step(1) == 1
        assert len(pb.warnings) == 1 and "vote" in pb.warnings[0]
        assert "replay:" in capsys.readouterr().err

    def test_stale_height_skips_silently(self):
        def boom(*a):
            raise ValueError("stale")

        pb = self._playback(boom, height=10)
        pb._records = [self._rec(timeout=(1000, 3, 0, 1))]  # height 3 < 10
        assert pb.step(1) == 1
        assert pb.warnings == []

    def test_current_height_failure_warns(self):
        def boom(*a):
            raise RuntimeError("handler rejected")

        pb = self._playback(boom, height=10)
        pb._records = [self._rec(timeout=(1000, 10, 0, 1))]
        assert pb.step(1) == 1
        assert len(pb.warnings) == 1 and "handler rejected" in pb.warnings[0]


@pytest.mark.slow
class TestInterpretKernels:
    """Pallas kernels in interpret mode — slow on the CPU image (minutes
    per grid); run on the TPU driver image or with -m slow."""

    def test_pallas_interpret_parity(self):
        from tendermint_tpu.ops import pallas_verify

        ents = _entries(8, bad=(2,))
        a = pallas_verify.prepare_compact(ents, 8)
        b = pallas_verify.prepare_compact(EntryBlock.from_entries(ents), 8)
        ra = pallas_verify.verify_compact(*a, block=8, interpret=True)
        rb = pallas_verify.verify_compact(*b, block=8, interpret=True)
        assert np.array_equal(ra, rb)
        assert not ra[2] and ra.sum() == 7

    def test_rlc_interpret_parity(self, monkeypatch):
        monkeypatch.setenv("TM_TPU_RLC_SEED", "3")
        M = pallas_rlc.M
        ents = _entries(2 * M, bad=(1,))
        ra = pallas_rlc.verify_batch_rlc(ents, interpret=True)
        rb = pallas_rlc.verify_batch_rlc(
            EntryBlock.from_entries(ents), interpret=True
        )
        assert np.array_equal(ra, rb)
        assert not ra[1] and ra.sum() == 2 * M - 1
