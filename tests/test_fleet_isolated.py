"""Tier-1 face of the verification fleet (ISSUE 18).

Same pattern as test_ingress_fabric_isolated.py: the container lacks
the `cryptography` wheel, so the real-ed25519 fleet suite
(tests/test_fleet.py — local vs through-fleet verdict AND blame parity
per lane over real sockets and real CPU kernels) and the
`tools/prep_bench.py --fleet` gate run in SUBPROCESSES with
TM_TPU_PUREPY_CRYPTO=1, which must never leak into the main pytest
process.
"""

import os
import subprocess
import sys

import pytest


def _repo_root():
    return os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _purepy_env():
    from tendermint_tpu.libs import jaxcache

    env = dict(os.environ, TM_TPU_PUREPY_CRYPTO="1", JAX_PLATFORMS="cpu")
    env.pop("TM_TPU_DONATE", None)
    env.pop("TM_TPU_MESH", None)
    jaxcache.set_env(env, _repo_root())
    return env


# -- subprocess faces ----------------------------------------------------


def test_fleet_suite_under_purepy_fallback():
    """Re-runs the whole fleet suite — wire round-trips/adversarial
    frames, socket service behavior, local-vs-fleet verdict+blame
    parity, and the simnet shared-fleet scenario — in one purepy
    subprocess (those modules skip themselves in a crypto-less main
    process because importing the ops package pulls the crypto stack)."""
    try:
        import cryptography  # noqa: F401

        pytest.skip("cryptography present; the fleet suite runs directly")
    except ModuleNotFoundError:
        pass
    here = os.path.dirname(os.path.abspath(__file__))
    r = subprocess.run(
        [
            sys.executable, "-m", "pytest",
            os.path.join(here, "test_fleet_wire.py"),
            os.path.join(here, "test_fleet_service.py"),
            os.path.join(here, "test_fleet.py"),
            os.path.join(here, "test_simnet_fleet.py"),
            "-q", "-m", "not slow", "-p", "no:cacheprovider",
        ],
        capture_output=True,
        env=_purepy_env(),
        cwd=_repo_root(),
        timeout=800,
    )
    tail = (r.stdout or b"").decode(errors="replace")[-3000:]
    assert r.returncode == 0, f"isolated test_fleet run failed:\n{tail}"


def test_prep_bench_fleet_gate():
    """ISSUE 18 satellite: the --fleet gate — two client nodes'
    same-epoch blocks coalesce into fewer launches than solo through one
    fleet server over real sockets, the one forged signature demuxes to
    the right node/row, a mid-window fleet kill loses zero items (host
    fallback) with automatic rejoin after restart, zero pool-slot leak —
    wired into tier-1 through the isolated runner."""
    r = subprocess.run(
        [
            sys.executable,
            os.path.join(_repo_root(), "tools", "prep_bench.py"),
            "--fleet",
        ],
        capture_output=True,
        env=_purepy_env(),
        cwd=_repo_root(),
        timeout=600,
    )
    out = (r.stdout or b"").decode(errors="replace")
    err = (r.stderr or b"").decode(errors="replace")
    assert r.returncode == 0, f"--fleet gate failed:\n{out}\n{err[-2000:]}"
