"""Mesh dispatcher (ISSUE 9): lane-packed superbatch verdict/blame
parity against the single-device path, across mixed-epoch and mixed-size
lane packs (including a pure identity-padding lane), on the 1-lane and
2-lane (simulated) mesh — the CPU/tier-1 face of multichip serving. Also
the warn-once shard_map fallback and the mesh observability gauges.

Runs with devcheck armed: the mesh superbatch path must satisfy the
relay single-owner assertions and the write-after-resolve canary exactly
like the single-device dispatcher."""

import logging
import os

import numpy as np
import pytest

try:
    from tendermint_tpu.crypto import ed25519
except ModuleNotFoundError:
    # No cryptography wheel in this container. Do NOT flip
    # TM_TPU_PUREPY_CRYPTO here (env leaks into later-collected modules);
    # test_mesh_isolated.py re-runs this module in a subprocess with the
    # fallback enabled instead.
    pytest.skip(
        "ed25519 backend unavailable (runs via test_mesh_isolated.py)",
        allow_module_level=True,
    )

from tendermint_tpu.libs import devcheck
from tendermint_tpu.libs.metrics import ops_stats
from tendermint_tpu.ops import backend, epoch_cache, mesh as ms
from tendermint_tpu.ops import pipeline as pl
from tendermint_tpu.ops import sharded
from tendermint_tpu.ops._testing import drain_pool
from tendermint_tpu.ops.entry_block import EntryBlock


@pytest.fixture(autouse=True)
def _devcheck_armed():
    devcheck.enable(reset=True)
    yield
    try:
        devcheck.check()
    finally:
        devcheck.reset_state()
        devcheck.disable()


@pytest.fixture(autouse=True)
def _lane_bucket_128(monkeypatch):
    """Small lanes keep the compiled superbatch shapes at {128, 256} —
    the tier-1 compile budget — and make pack shapes predictable."""
    monkeypatch.setenv("TM_TPU_MESH_LANE_BUCKET", "128")


def _signed(n, tag=0, bad=()):
    out = []
    for i in range(n):
        sk = ed25519.gen_priv_key((tag * 4096 + i + 1).to_bytes(32, "little"))
        m = b"mesh-%d-%d" % (tag, i)
        sig = sk.sign(m) if i not in bad else b"\x07" * 64
        out.append((sk.pub_key().bytes(), m, sig))
    return out


class _J:
    def __init__(self, blk):
        self.entries = blk


def _run_plan(plan):
    """Launch a hand-built plan the way the dispatcher would (direct
    call — no pipeline threads), returning the raw verdict row."""
    from tendermint_tpu.ops import device_pool as dp

    block, spans = ms.build_superblock(plan)
    res = ms.prepare_superbatch(block, plan)
    f, args = res[0], res[1]
    shardings = res[4] if len(res) > 4 else None
    with devcheck.exempt():
        dev = f(*dp.transfer(args, shardings=shardings))
    arr = np.array(dev)
    if arr.ndim == 2:
        arr = arr[0]
    return arr.astype(bool), spans


class TestMeshParity:
    def test_one_lane_mesh_parity_mixed_sizes(self):
        """lanes=1: the mesh packer's (1, bucket) superbatch must be
        verdict-identical to the classic single-device path."""
        jobs = [_signed(96, 1, bad=(3,)), _signed(31, 2), _signed(5, 3)]
        v = pl.AsyncBatchVerifier(depth=2, mesh_lanes=1)
        try:
            futs = [v.submit(j) for j in jobs]
            res = [np.asarray(f.result(timeout=300)) for f in futs]
            drain_pool(v._pool)
            assert v._pool.stats()["in_flight"] == 0
        finally:
            v.close()
        for j, r in zip(jobs, res):
            assert np.array_equal(r, np.asarray(backend.verify_batch(j)))
        assert not res[0][3] and res[0].sum() == 95

    def test_two_lane_pack_parity_and_blame(self):
        """2 simulated lanes, mixed job sizes, tampered rows in two
        different jobs: verdicts and blame indices survive the per-lane
        demux bit-identically."""
        jobs = [
            _signed(96, 10, bad=(17,)),
            _signed(31, 11),
            _signed(128, 12, bad=(0, 127)),
            _signed(64, 13),
            _signed(7, 14),
        ]
        v = pl.AsyncBatchVerifier(depth=2, mesh_lanes=2)
        try:
            futs = [v.submit(j) for j in jobs]
            res = [np.asarray(f.result(timeout=300)) for f in futs]
            drain_pool(v._pool)
            assert v._pool.stats()["in_flight"] == 0
        finally:
            v.close()
        for j, r in zip(jobs, res):
            assert np.array_equal(r, np.asarray(backend.verify_batch(j)))
        assert not res[0][17] and res[0].sum() == 95
        assert not res[2][0] and not res[2][127] and res[2].sum() == 126
        assert res[1].all() and res[3].all() and res[4].all()
        # the verdict rows delivered to callers are owned memory (the
        # PR-7 aliasing rule holds on the mesh path too)
        assert all(r.flags.owndata or r.base.flags.owndata for r in res)

    def test_pure_identity_pad_lane(self):
        """A superbatch whose lane count rounds past its live lanes
        carries a PURE padding lane — verdicts of the live jobs are
        unaffected and the pad lane verifies trivially."""
        blk = EntryBlock.from_entries(_signed(100, 20, bad=(5,)))
        plan, held = ms.pack_jobs([_J(blk)], 2, 128)
        assert not held and len(plan.lanes) == 1
        plan.n_lanes = 2  # force the trailing pure-pad lane
        assert plan.bucket == 256 and plan.pad == 156
        arr, spans = _run_plan(plan)
        assert len(spans) == 1
        job, off, n = spans[0]
        got = arr[off:off + n]
        want = np.asarray(backend.verify_batch(blk))
        assert np.array_equal(got, want)
        assert not got[5] and got.sum() == 99
        # every identity padding row (incl. the whole second lane)
        # verifies trivially
        assert arr[n:].all()

    def test_mixed_epoch_lanes_never_share_a_lane(self):
        """Jobs of two different (warm) epochs plus an uncached job pack
        into single-epoch lanes; the mixed superbatch rides the uncached
        prep and stays verdict-identical per job."""
        epoch_cache.reset(depth=4)
        try:
            e1 = EntryBlock.from_entries(_signed(40, 30))
            e1.epoch_key, e1.val_idx = b"ek-1", np.arange(40, dtype=np.int32)
            e2 = EntryBlock.from_entries(_signed(50, 31, bad=(9,)))
            e2.epoch_key, e2.val_idx = b"ek-2", np.arange(50, dtype=np.int32)
            e3 = EntryBlock.from_entries(_signed(30, 32))
            plan, held = ms.pack_jobs([_J(e1), _J(e2), _J(e3)], 4, 128)
            assert not held
            # e1/e2 differ in key, e3 has none: three distinct lanes
            assert [l.key for l in plan.lanes] == [b"ek-1", b"ek-2", None]
            block, _ = ms.build_superblock(plan)
            # mixed keys: concat drops the epoch metadata -> uncached
            assert block.epoch_key is None
            arr, spans = _run_plan(plan)
            for job, off, n in spans:
                want = np.asarray(backend.verify_batch(job.entries))
                assert np.array_equal(arr[off:off + n], want)
        finally:
            epoch_cache.reset()

    def test_same_warm_epoch_pack_uses_cached_prep(self):
        """A pack whose every lane shares ONE warm epoch preps through
        the gather path (no pubkey-derived arrays ship) and stays
        verdict-identical to the uncached launch of the same rows."""
        epoch_cache.reset(depth=4)
        try:
            entries = _signed(48, 40, bad=(11,))
            pub_col = np.frombuffer(
                b"".join(p for p, _, _ in entries), dtype=np.uint8
            ).reshape(48, 32)
            c = epoch_cache.cache()
            assert c.note(b"mesh-warm", pub_col) is None  # cold register
            assert c.note(b"mesh-warm", pub_col) is not None  # warm

            def jb(lo, hi, tag):
                blk = EntryBlock.from_entries(entries[lo:hi])
                blk.epoch_key = b"mesh-warm"
                blk.val_idx = np.arange(lo, hi, dtype=np.int32)
                return _J(blk)

            plan, held = ms.pack_jobs([jb(0, 20, 0), jb(20, 48, 1)], 2, 128)
            # same warm key: first-fit shares ONE lane (same-epoch jobs
            # gather from the same table rows)
            assert not held and len(plan.lanes) == 1
            block, _ = ms.build_superblock(plan)
            assert block.epoch_key == b"mesh-warm"
            res = ms.prepare_superbatch(block, plan)
            args = res[1]
            # cached arg shape: these short messages select the
            # device-hash family (mirroring _prepare), so the warm args
            # are (idx, r, s, hi, lo, counts, s_ok) — structurally
            # pub-free (the --transfer gate's invariant, mesh face)
            assert len(args) == 7 and args[0].dtype == np.int32
            arr, spans = _run_plan(plan)
            flat = np.zeros(48, dtype=bool)
            for job, off, n in spans:
                flat[job.entries.val_idx] = arr[off:off + n]
            want = np.asarray(backend.verify_batch(
                EntryBlock.from_entries(entries)
            ))
            assert np.array_equal(flat, want)
            assert not flat[11] and flat.sum() == 47
        finally:
            epoch_cache.reset()


class TestShardMapFallback:
    def test_warn_once_not_per_batch(self, caplog):
        """ISSUE 9 satellite: with jax.shard_map unavailable the sharded
        verifiers degrade to single-device dispatch and warn exactly
        ONCE, not on every warm block."""
        if sharded.shard_map_available():
            pytest.skip("jax.shard_map present — fallback not exercised")
        sharded._fallback_warned.discard("verify_commit_sharded")
        mesh = sharded.make_mesh(1)
        entries = _signed(12, 50, bad=(5,))
        powers = [10 + i for i in range(12)]
        with caplog.at_level(logging.WARNING,
                             logger="tendermint_tpu.ops.sharded"):
            v1, t1, a1 = sharded.verify_commit_sharded(entries, powers, mesh)
            v2, t2, a2 = sharded.verify_commit_sharded(entries, powers, mesh)
        warns = [r for r in caplog.records
                 if "verify_commit_sharded:" in r.getMessage()]
        assert len(warns) == 1
        assert np.array_equal(v1, v2) and t1 == t2 == sum(powers) - 15
        assert not a1 and not v1[5] and v1.sum() == 11

    def test_mesh_ready_false_degrades_to_simulated_lanes(self):
        if sharded.shard_map_available():
            pytest.skip("jax.shard_map present — fallback not exercised")
        assert sharded.mesh_ready(2) is False
        # prepare_superbatch then returns no shardings (plain kernel)
        blk = EntryBlock.from_entries(_signed(8, 51))
        plan, _ = ms.pack_jobs([_J(blk)], 2, 128)
        block, _spans = ms.build_superblock(plan)
        res = ms.prepare_superbatch(block, plan)
        assert len(res) == 5 and res[4] is None


class TestMeshObservability:
    def test_gauges_published_and_complementary(self):
        jobs = [_signed(96, 60), _signed(31, 61)]
        v = pl.AsyncBatchVerifier(depth=2, mesh_lanes=2)
        try:
            for f in [v.submit(j) for j in jobs]:
                f.result(timeout=300)
            drain_pool(v._pool)
        finally:
            v.close()
        s = ops_stats()
        occ, pad = s["mesh_lane_occupancy"], s["mesh_pad_waste_ratio"]
        assert 0.0 < occ <= 1.0
        assert occ + pad == pytest.approx(1.0)

    def test_oversized_submit_chunks_at_lane_cap(self):
        """A job bigger than one lane chunk-splits at submit (mesh mode
        packs WHOLE jobs into lanes) and re-aggregates into one future."""
        entries = _signed(200, 70, bad=(150,))
        v = pl.AsyncBatchVerifier(depth=2, mesh_lanes=2)
        try:
            r = np.asarray(v.submit(entries).result(timeout=300))
            drain_pool(v._pool)
        finally:
            v.close()
        assert r.shape == (200,)
        want = np.asarray(backend.verify_batch(entries))
        assert np.array_equal(r, want)
        assert not r[150] and r.sum() == 199
