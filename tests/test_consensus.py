"""Consensus engine tests.

Mirrors the reference's in-process multi-validator harness
(internal/consensus/common_test.go, SURVEY.md §4): single-validator chain
producing blocks against kvstore, then a 4-validator net wired through the
broadcast seam (no network) — the "multi-node without a cluster" pattern.
"""

import threading
import time

import pytest

from tendermint_tpu.abci import KVStoreApplication, LocalClient
from tendermint_tpu.config import ConsensusConfig
from tendermint_tpu.consensus import ConsensusState, WAL, WALMessage
from tendermint_tpu.crypto import ed25519
from tendermint_tpu.db import MemDB
from tendermint_tpu.eventbus import EventBus
from tendermint_tpu.mempool import TxMempool
from tendermint_tpu.privval import FilePV
from tendermint_tpu.state import make_genesis_state
from tendermint_tpu.state.execution import BlockExecutor
from tendermint_tpu.state.store import StateStore
from tendermint_tpu.store import BlockStore
from tendermint_tpu.types import Timestamp
from tendermint_tpu.types.genesis import GenesisDoc, GenesisValidator

CHAIN_ID = "cs-chain"

FAST = ConsensusConfig(
    timeout_propose_ms=400,
    timeout_propose_delta_ms=100,
    timeout_prevote_ms=200,
    timeout_prevote_delta_ms=100,
    timeout_precommit_ms=200,
    timeout_precommit_delta_ms=100,
    timeout_commit_ms=50,
    skip_timeout_commit=True,
)


def make_node(sks, idx, wal_path=None, tx_source=None, proxy=None):
    """One in-process consensus node for validator idx."""
    doc = GenesisDoc(
        chain_id=CHAIN_ID,
        genesis_time=Timestamp(seconds=1_700_000_000),
        validators=[
            GenesisValidator(address=b"", pub_key=sk.pub_key(), power=10) for sk in sks
        ],
    )
    state = make_genesis_state(doc)
    app = KVStoreApplication()
    proxy = proxy or LocalClient(app)
    sstore = StateStore(MemDB())
    sstore.save(state)
    bstore = BlockStore(MemDB())
    mp = TxMempool(LocalClient(app))
    if tx_source:
        for tx in tx_source:
            mp.check_tx(tx)
    bus = EventBus()
    ex = BlockExecutor(sstore, proxy, mempool=mp, block_store=bstore, event_bus=bus)
    wal = WAL(wal_path) if wal_path else None
    pv = FilePV(sks[idx]) if idx is not None else None
    cs = ConsensusState(
        FAST, state, ex, bstore, mempool=mp, event_bus=bus, wal=wal, priv_validator=pv
    )
    return cs, bstore, app


class TestSingleValidator:
    def test_one_validator_chain_produces_blocks(self):
        sk = ed25519.gen_priv_key(bytes([1]) * 32)
        cs, bstore, app = make_node([sk], 0, tx_source=[b"a=1", b"b=2"])
        cs.start()
        try:
            cs.wait_for_height(3, timeout=30)
        finally:
            cs.stop()
        assert bstore.height() >= 3
        b1 = bstore.load_block(1)
        assert b1.header.chain_id == CHAIN_ID
        b2 = bstore.load_block(2)
        # height-2 commit carries height-1 signatures
        assert b2.last_commit.height == 1
        assert len(b2.last_commit.signatures) == 1
        # txs from the mempool were included in some block
        all_txs = [tx for h in range(1, bstore.height() + 1) for tx in bstore.load_block(h).data.txs]
        assert b"a=1" in all_txs and b"b=2" in all_txs

    def test_wal_replay_restarts_cleanly(self, tmp_path):
        sk = ed25519.gen_priv_key(bytes([2]) * 32)
        wal_path = str(tmp_path / "cs.wal")
        cs, bstore, _ = make_node([sk], 0, wal_path=wal_path)
        cs.start()
        try:
            cs.wait_for_height(2, timeout=30)
        finally:
            cs.stop()
        # WAL contains end-height markers
        wal = WAL(wal_path)
        ends = [m.end_height for m in wal.iter_messages() if m.end_height is not None]
        assert 0 in ends and 1 in ends and 2 in ends


def wire_nodes(nodes):
    """Relay each node's own proposals/parts/votes to every other node —
    the test stand-in for the consensus reactor's gossip."""
    from tendermint_tpu.consensus import BlockPartMessage, ProposalMessage, VoteMessage

    def make_hook(src_idx):
        def hook(msg):
            for j, n in enumerate(nodes):
                if j == src_idx:
                    continue
                if isinstance(msg, ProposalMessage):
                    n.set_proposal(msg.proposal, peer_id=f"n{src_idx}")
                elif isinstance(msg, BlockPartMessage):
                    n.add_block_part(msg.height, msg.round, msg.part, peer_id=f"n{src_idx}")
                elif isinstance(msg, VoteMessage):
                    n.add_vote_msg(msg.vote, peer_id=f"n{src_idx}")

        return hook

    for i, n in enumerate(nodes):
        n.broadcast_hooks.append(make_hook(i))


class TestMultiValidator:
    def test_four_validator_net_commits_blocks(self):
        sks = [ed25519.gen_priv_key(bytes([i + 1]) * 32) for i in range(4)]
        nodes = []
        stores = []
        for i in range(4):
            cs, bstore, _ = make_node(sks, i)
            nodes.append(cs)
            stores.append(bstore)
        wire_nodes(nodes)
        for n in nodes:
            n.start()
        try:
            for n in nodes:
                n.wait_for_height(3, timeout=60)
        finally:
            for n in nodes:
                n.stop()
        hashes = [s.load_block(3).hash() for s in stores]
        assert all(h == hashes[0] for h in hashes), "nodes diverged"
        # commits carry signatures from (at least quorum of) the 4 validators
        b3 = stores[0].load_block(3)
        non_absent = [cs for cs in b3.last_commit.signatures if not cs.is_absent()]
        assert len(non_absent) >= 3

    def test_net_survives_one_silent_node(self):
        """3 of 4 validators online still commit (BFT liveness, f=1)."""
        sks = [ed25519.gen_priv_key(bytes([i + 1]) * 32) for i in range(4)]
        nodes = []
        stores = []
        for i in range(3):  # node 3 never starts
            cs, bstore, _ = make_node(sks, i)
            nodes.append(cs)
            stores.append(bstore)
        wire_nodes(nodes)
        for n in nodes:
            n.start()
        try:
            for n in nodes:
                n.wait_for_height(2, timeout=60)
        finally:
            for n in nodes:
                n.stop()
        assert stores[0].load_block(2).hash() == stores[1].load_block(2).hash()
