"""Wire-layer tests: varint/field primitives, delimited framing, and a
differential check of canonical sign-bytes against the official protobuf
runtime (schema compiled from tests/protos/canonical_ref.proto, which
mirrors the reference's proto/tendermint/types/canonical.proto)."""

import subprocess
import sys
from pathlib import Path

import pytest

from tendermint_tpu.wire import (
    ProtoWriter,
    decode_message,
    decode_uvarint,
    encode_uvarint,
    marshal_delimited,
    unmarshal_delimited,
)
from tendermint_tpu.wire import canonical
from tendermint_tpu.wire.canonical import (
    CanonicalBlockID,
    CanonicalPartSetHeader,
    Timestamp,
    canonical_proposal_sign_bytes,
    canonical_vote_sign_bytes,
)

PROTO_DIR = Path(__file__).parent / "protos"


@pytest.fixture(scope="module")
def refpb(tmp_path_factory):
    out = tmp_path_factory.mktemp("pb")
    subprocess.run(
        [
            "protoc",
            f"--proto_path={PROTO_DIR}",
            f"--python_out={out}",
            str(PROTO_DIR / "canonical_ref.proto"),
        ],
        check=True,
    )
    sys.path.insert(0, str(out))
    try:
        import canonical_ref_pb2  # noqa: F401

        yield canonical_ref_pb2
    finally:
        sys.path.remove(str(out))


class TestPrimitives:
    def test_uvarint_roundtrip(self):
        for v in (0, 1, 127, 128, 300, 2**32, 2**64 - 1):
            enc = encode_uvarint(v)
            dec, off = decode_uvarint(enc)
            assert dec == v and off == len(enc)

    def test_negative_varint_is_ten_bytes(self):
        w = ProtoWriter()
        w.write_varint(1, -1)
        data = w.bytes()
        assert len(data) == 1 + 10  # tag + 10-byte two's complement

    def test_zero_fields_omitted(self):
        w = ProtoWriter()
        w.write_varint(1, 0)
        w.write_bytes(2, b"")
        w.write_string(3, "")
        w.write_sfixed64(4, 0)
        assert w.bytes() == b""

    def test_always_emits_zero(self):
        w = ProtoWriter()
        w.write_message(5, b"", always=True)
        assert w.bytes() == bytes([0x2A, 0x00])

    def test_decode_roundtrip(self):
        w = ProtoWriter()
        w.write_varint(1, 42)
        w.write_sfixed64(2, -7)
        w.write_bytes(3, b"abc")
        fields = decode_message(w.bytes())
        assert fields[1][0][1] == 42
        assert fields[2][0][1] == (-7) % 2**64
        assert fields[3][0][1] == b"abc"

    def test_delimited(self):
        msg = b"\x08\x01"
        framed = marshal_delimited(msg)
        assert framed == b"\x02" + msg
        got, n = unmarshal_delimited(framed)
        assert got == msg and n == len(framed)


def _mk_ref_vote(pb, *, vtype, height, round_, bid, ts, chain_id):
    v = pb.CanonicalVote()
    v.type = vtype
    v.height = height
    v.round = round_
    if bid is not None:
        v.block_id.hash = bid.hash
        v.block_id.part_set_header.total = bid.part_set_header.total
        v.block_id.part_set_header.hash = bid.part_set_header.hash
    v.timestamp.seconds = ts.seconds
    v.timestamp.nanos = ts.nanos
    v.chain_id = chain_id
    return v


class TestCanonicalDifferential:
    BID = CanonicalBlockID(
        hash=bytes(range(32)),
        part_set_header=CanonicalPartSetHeader(total=3, hash=bytes(reversed(range(32)))),
    )
    TS = Timestamp(seconds=1700000000, nanos=123456789)

    def test_vote_matches_protobuf_runtime(self, refpb):
        cases = [
            dict(
                vtype=canonical.SIGNED_MSG_TYPE_PRECOMMIT,
                height=12345,
                round_=2,
                bid=self.BID,
                ts=self.TS,
                chain_id="test-chain",
            ),
            # nil vote: no block_id
            dict(
                vtype=canonical.SIGNED_MSG_TYPE_PREVOTE,
                height=1,
                round_=0,
                bid=None,
                ts=self.TS,
                chain_id="c",
            ),
            # zero height/round omitted; go zero time
            dict(
                vtype=canonical.SIGNED_MSG_TYPE_PREVOTE,
                height=0,
                round_=0,
                bid=None,
                ts=Timestamp.zero(),
                chain_id="chain-µ-unicode",
            ),
        ]
        for c in cases:
            ref = _mk_ref_vote(refpb, **c).SerializeToString(deterministic=True)
            ours = canonical_vote_sign_bytes(
                c["chain_id"], c["vtype"], c["height"], c["round_"], c["bid"], c["ts"]
            )
            body, n = unmarshal_delimited(ours)
            assert n == len(ours)
            assert body == ref, f"case {c}: {body.hex()} != {ref.hex()}"

    def test_proposal_matches_protobuf_runtime(self, refpb):
        for pol_round in (-1, 0, 7):
            p = refpb.CanonicalProposal()
            p.type = canonical.SIGNED_MSG_TYPE_PROPOSAL
            p.height = 100
            p.round = 1
            p.pol_round = pol_round
            p.block_id.hash = self.BID.hash
            p.block_id.part_set_header.total = self.BID.part_set_header.total
            p.block_id.part_set_header.hash = self.BID.part_set_header.hash
            p.timestamp.seconds = self.TS.seconds
            p.timestamp.nanos = self.TS.nanos
            p.chain_id = "test-chain"
            ref = p.SerializeToString(deterministic=True)
            ours = canonical_proposal_sign_bytes(
                "test-chain", 100, 1, pol_round, self.BID, self.TS
            )
            body, _ = unmarshal_delimited(ours)
            assert body == ref

    def test_golden_vector(self):
        """Pin one full sign-bytes vector so semantics can never drift
        silently (delimited CanonicalVote, precommit h=2 r=1, nil block)."""
        got = canonical_vote_sign_bytes(
            "chain", canonical.SIGNED_MSG_TYPE_PRECOMMIT, 2, 1,
            None, Timestamp(seconds=10, nanos=5),
        )
        expect = bytes.fromhex(
            "2108021102000000000000001901000000000000002a04080a10053205636861696e"
        )
        assert got == expect

    def test_go_zero_time_encoding(self):
        # Go zero time seconds must be the proto3 negative-varint encoding
        enc = canonical.encode_timestamp(Timestamp.zero())
        fields = decode_message(enc)
        from tendermint_tpu.wire.proto import to_signed64

        assert to_signed64(fields[1][0][1]) == canonical.GO_ZERO_TIME_SECONDS
        assert 2 not in fields


def test_vote_sign_bytes_template_parity():
    """Commit.vote_sign_bytes's per-(chain_id, flag) template must produce
    byte-identical output to the direct CanonicalVote encoding for every
    timestamp and flag combination."""
    from tendermint_tpu.types.block import (
        BLOCK_ID_FLAG_COMMIT,
        BLOCK_ID_FLAG_NIL,
        BlockID,
        Commit,
        CommitSig,
        PartSetHeader,
    )
    from tendermint_tpu.wire import canonical as canon

    bid = BlockID(hash=b"\xaa" * 32, part_set_header=PartSetHeader(total=3, hash=b"\xbb" * 32))
    sigs = [
        CommitSig(
            block_id_flag=BLOCK_ID_FLAG_COMMIT if i % 3 else BLOCK_ID_FLAG_NIL,
            validator_address=bytes([i]) * 20,
            timestamp=canon.Timestamp(seconds=1_600_000_000 + 977 * i, nanos=i * 13),
            signature=b"s" * 64,
        )
        for i in range(12)
    ]
    for height, round_ in ((1, 0), (1 << 40, 7)):
        commit = Commit(height=height, round=round_, block_id=bid, signatures=list(sigs))
        for chain_id in ("chain-a", ""):
            for idx, cs in enumerate(commit.signatures):
                direct = canon.canonical_vote_sign_bytes(
                    chain_id=chain_id,
                    msg_type=canon.SIGNED_MSG_TYPE_PRECOMMIT,
                    height=commit.height,
                    round_=commit.round,
                    block_id=cs.block_id(commit.block_id).canonical(),
                    timestamp=cs.timestamp,
                )
                assert commit.vote_sign_bytes(chain_id, idx) == direct, (chain_id, idx)


class TestNativeSignBytesParity:
    def test_vote_sign_bytes_many_matches_python_composer(self):
        """Consensus-critical parity: the native batch composer
        (tm_native.vote_sign_bytes_batch) must match the pure-Python
        compose_vote_sign_bytes byte-for-byte, including edge timestamps
        (zero fields skipped, Go zero-time negative 10-byte varints,
        > 2^32 seconds)."""
        import struct

        import pytest as _pytest

        from tendermint_tpu.native import load
        from tendermint_tpu.wire import canonical as _c

        native = load()
        if native is None or not hasattr(native, "vote_sign_bytes_batch"):
            _pytest.skip("native module unavailable")
        tpl = _c.canonical_vote_template(
            chain_id="parity-chain", msg_type=_c.SIGNED_MSG_TYPE_PRECOMMIT,
            height=77, round_=2, block_id=None,
        )
        cases = [
            (0, 0), (0, 5), (5, 0), (-62135596800, 0), (-1, 999999999),
            (1 << 33, 17), (2**62, 1), (1_600_000_000, 123456789),
        ]
        want = [
            _c.compose_vote_sign_bytes(tpl, _c.Timestamp(seconds=s, nanos=n))
            for s, n in cases
        ]
        times = b"".join(struct.pack("<qq", s, n) for s, n in cases)
        got = native.vote_sign_bytes_batch(tpl[0], tpl[1], times)
        assert got == want
        with _pytest.raises(ValueError):
            native.vote_sign_bytes_batch(tpl[0], tpl[1], b"\x00" * 15)
