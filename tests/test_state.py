"""State layer: genesis bootstrap, block production + execution against the
kvstore app (the "one model running" e2e slice before consensus), state
store checkpoints, median time."""

import pytest

from tendermint_tpu.abci import KVStoreApplication, LocalClient
from tendermint_tpu.abci import types as abci
from tendermint_tpu.crypto import ed25519
from tendermint_tpu.crypto.encoding import pubkey_to_proto
from tendermint_tpu.db import MemDB
from tendermint_tpu.state import State, make_genesis_state, median_time
from tendermint_tpu.state.execution import BlockExecutor
from tendermint_tpu.state.store import StateStore
from tendermint_tpu.store import BlockStore
from tendermint_tpu.types import (
    BlockID,
    Timestamp,
    Validator,
    Vote,
    VoteSet,
    PRECOMMIT_TYPE,
)
from tendermint_tpu.types.genesis import GenesisDoc, GenesisValidator

CHAIN_ID = "exec-chain"


def make_genesis(n=3):
    sks = [ed25519.gen_priv_key(bytes([i + 1]) * 32) for i in range(n)]
    doc = GenesisDoc(
        chain_id=CHAIN_ID,
        genesis_time=Timestamp(seconds=1_700_000_000),
        validators=[
            GenesisValidator(address=b"", pub_key=sk.pub_key(), power=10) for sk in sks
        ],
    )
    st = make_genesis_state(doc)
    return sks, st


def sign_commit(sks, state: State, block, parts, height, round_=0, ts_base=1_700_000_100):
    """Build a valid precommit commit for `block` signed by state's current
    validators (they will be last_validators at height+1)."""
    vset = state.validators
    block_id = BlockID(hash=block.hash(), part_set_header=parts.header())
    vs = VoteSet(CHAIN_ID, height, round_, PRECOMMIT_TYPE, vset)
    by_addr = {sk.pub_key().address(): sk for sk in sks}
    for idx, val in enumerate(vset.validators):
        sk = by_addr[val.address]
        vote = Vote(
            type=PRECOMMIT_TYPE,
            height=height,
            round=round_,
            block_id=block_id,
            timestamp=Timestamp(seconds=ts_base + height),
            validator_address=val.address,
            validator_index=idx,
        )
        sig = sk.sign(vote.sign_bytes(CHAIN_ID))
        vs.add_vote(Vote(**{**vote.__dict__, "signature": sig}))
    return vs.make_commit(), block_id


def build_executor():
    app = KVStoreApplication()
    proxy = LocalClient(app)
    store = StateStore(MemDB())
    block_store = BlockStore(MemDB())
    ex = BlockExecutor(store, proxy, block_store=block_store)
    return ex, store, block_store, app


class TestChainExecution:
    def test_three_block_chain(self):
        sks, state = make_genesis()
        ex, sstore, bstore, app = build_executor()
        sstore.save(state)

        commit = None
        for height in range(1, 4):
            proposer = state.validators.get_proposer()
            block, parts = ex.create_proposal_block(height, state, commit, proposer.address)
            # give the block a tx
            block.data.txs = [b"k%d=v%d" % (height, height)]
            block.header = type(block.header)(**{**block.header.__dict__})
            block.fill_header()
            # refresh data hash after adding txs
            from dataclasses import replace as drep

            block.header = drep(block.header, data_hash=block.data.hash())
            parts = type(parts).from_data(block.encode())
            block_id = BlockID(hash=block.hash(), part_set_header=parts.header())

            new_state = ex.apply_block(state, block_id, block)
            bstore.save_block(block, parts, sign_commit(sks, new_state, block, parts, height)[0])

            commit, _ = sign_commit(sks, state, block, parts, height)
            assert new_state.last_block_height == height
            assert new_state.last_block_id == block_id
            state = new_state

        assert app._size == 3  # 3 txs delivered
        assert state.app_hash  # app hash flowed back
        # results hash of a single OK tx is stable and lands in next header
        assert state.last_results_hash

    def test_apply_block_rejects_wrong_height(self):
        sks, state = make_genesis()
        ex, sstore, _, _ = build_executor()
        sstore.save(state)
        proposer = state.validators.get_proposer()
        block, parts = ex.create_proposal_block(5, state, None, proposer.address)
        block_id = BlockID(hash=block.hash(), part_set_header=parts.header())
        from tendermint_tpu.state.execution import InvalidBlockError

        with pytest.raises(InvalidBlockError):
            ex.apply_block(state, block_id, block)

    def test_validator_update_via_endblock(self):
        """EndBlock validator updates flow into next_validators (n+2 rule)."""
        from tendermint_tpu.abci.application import BaseApplication

        new_sk = ed25519.gen_priv_key(bytes([42]) * 32)

        class ValApp(KVStoreApplication):
            def end_block(self, req):
                resp = super().end_block(req)
                if req.height == 1:
                    resp.validator_updates = [
                        abci.ValidatorUpdate(
                            pub_key=pubkey_to_proto(new_sk.pub_key()), power=7
                        )
                    ]
                return resp

        sks, state = make_genesis()
        sstore = StateStore(MemDB())
        sstore.save(state)
        ex = BlockExecutor(sstore, LocalClient(ValApp()))
        proposer = state.validators.get_proposer()
        block, parts = ex.create_proposal_block(1, state, None, proposer.address)
        block_id = BlockID(hash=block.hash(), part_set_header=parts.header())
        ns = ex.apply_block(state, block_id, block)
        assert ns.next_validators.has_address(new_sk.pub_key().address())
        assert not ns.validators.has_address(new_sk.pub_key().address())
        assert ns.last_height_validators_changed == 3  # height+1+1


class TestStateStore:
    def test_save_load_roundtrip(self):
        _, state = make_genesis()
        store = StateStore(MemDB())
        store.save(state)
        loaded = store.load()
        assert loaded.chain_id == state.chain_id
        assert loaded.validators.hash() == state.validators.hash()
        assert loaded.next_validators.hash() == state.next_validators.hash()
        assert loaded.consensus_params == state.consensus_params

    def test_load_validators_checkpoint_walkback(self):
        _, state = make_genesis()
        store = StateStore(MemDB())
        store.save(state)
        v1 = store.load_validators(1)
        assert v1.hash() == state.validators.hash()
        v2 = store.load_validators(2)
        assert v2.hash() == state.next_validators.hash()


class TestMedianTime:
    def test_weighted_median(self):
        sks, state = make_genesis()
        ex, sstore, _, _ = build_executor()
        sstore.save(state)
        proposer = state.validators.get_proposer()
        block, parts = ex.create_proposal_block(1, state, None, proposer.address)
        commit, _ = sign_commit(sks, state, block, parts, 1, ts_base=500)
        med = median_time(commit, state.validators)
        assert med.seconds == 501  # all voted with seconds=500+height


class TestMempoolEvictionTTL:
    def _mp(self, **cfg_kw):
        from tendermint_tpu.abci import LocalClient
        from tendermint_tpu.abci.application import Application
        from tendermint_tpu.abci import types as abci_t
        from tendermint_tpu.config import MempoolConfig
        from tendermint_tpu.mempool import TxMempool

        class PriorityApp(Application):
            def check_tx(self, req):
                # priority = first byte of the tx
                return abci_t.ResponseCheckTx(code=0, priority=req.tx[0])

        cfg = MempoolConfig(**cfg_kw)
        return TxMempool(LocalClient(PriorityApp()), config=cfg)

    def test_priority_eviction_when_full(self):
        """mempool.go:498 + priority_queue.go GetEvictableTxs: a full
        mempool evicts strictly-lower-priority txs for a higher-priority
        arrival, and rejects arrivals that cannot displace anything."""
        from tendermint_tpu.mempool import MempoolFullError

        mp = self._mp(size=3)
        mp.check_tx(bytes([10]) + b"a")
        mp.check_tx(bytes([20]) + b"b")
        mp.check_tx(bytes([30]) + b"c")
        assert mp.size() == 3
        # higher priority than the lowest: evicts priority-10
        mp.check_tx(bytes([40]) + b"d")
        assert mp.size() == 3
        txs = mp.reap_max_txs(-1)
        assert bytes([10]) + b"a" not in txs
        assert bytes([40]) + b"d" in txs
        # lower than everything resident: rejected outright
        import pytest as _pytest

        with _pytest.raises(MempoolFullError):
            mp.check_tx(bytes([5]) + b"e")
        assert bytes([5]) + b"e" not in mp.reap_max_txs(-1)

    def test_ttl_num_blocks_purge(self):
        mp = self._mp(size=10, ttl_num_blocks=2)
        mp.check_tx(bytes([10]) + b"x")
        assert mp.size() == 1
        with mp._mtx:
            mp.update(1, [], [])
            mp.update(2, [], [])
            assert mp.size() == 1  # height delta 2, not yet > ttl
            mp.update(3, [], [])
        assert mp.size() == 0

    def test_ttl_duration_purge(self):
        import time as _t

        mp = self._mp(size=10, ttl_duration_ms=50)
        mp.check_tx(bytes([10]) + b"y")
        _t.sleep(0.08)
        with mp._mtx:
            mp.update(1, [], [])
        assert mp.size() == 0
        # a fresh tx survives an immediate update
        mp.check_tx(bytes([10]) + b"z")
        with mp._mtx:
            mp.update(2, [], [])
        assert mp.size() == 1
