"""Byzantine behavior: a double-signing validator is detected, evidence
flows through the pool into a block, and the app learns via BeginBlock
(reference internal/consensus/byzantine_test.go + evidence flow)."""

import time

import pytest

from tendermint_tpu.abci import KVStoreApplication, LocalClient
from tendermint_tpu.abci import types as abci
from tendermint_tpu.consensus import ConsensusState
from tendermint_tpu.crypto import ed25519
from tendermint_tpu.db import MemDB
from tendermint_tpu.eventbus import EventBus
from tendermint_tpu.evidence import Pool
from tendermint_tpu.mempool import TxMempool
from tendermint_tpu.privval import FilePV
from tendermint_tpu.state import make_genesis_state
from tendermint_tpu.state.execution import BlockExecutor
from tendermint_tpu.state.store import StateStore
from tendermint_tpu.store import BlockStore
from tendermint_tpu.types import Timestamp, Vote
from tendermint_tpu.types.evidence import DuplicateVoteEvidence, decode_evidence
from tendermint_tpu.types.genesis import GenesisDoc, GenesisValidator
from tests.test_consensus import CHAIN_ID, FAST
from tests.test_types import make_validators
from tendermint_tpu.types.vote import PREVOTE_TYPE


class RecordingApp(KVStoreApplication):
    def __init__(self):
        super().__init__()
        self.byzantine_reports = []

    def begin_block(self, req):
        self.byzantine_reports.extend(req.byzantine_validators)
        return super().begin_block(req)


def make_evidence_node(sks, idx, app=None):
    doc = GenesisDoc(
        chain_id=CHAIN_ID,
        genesis_time=Timestamp(seconds=1_700_000_000),
        validators=[
            GenesisValidator(address=b"", pub_key=sk.pub_key(), power=10) for sk in sks
        ],
    )
    state = make_genesis_state(doc)
    app = app or RecordingApp()
    proxy = LocalClient(app)
    sstore = StateStore(MemDB())
    sstore.save(state)
    bstore = BlockStore(MemDB())
    evpool = Pool(MemDB(), state_store=sstore, block_store=bstore)
    evpool.set_state(state)
    mp = TxMempool(LocalClient(app))
    bus = EventBus()
    ex = BlockExecutor(
        sstore, proxy, mempool=mp, evpool=evpool, block_store=bstore, event_bus=bus
    )
    cs = ConsensusState(
        FAST, state, ex, bstore, mempool=mp, evpool=evpool, event_bus=bus,
        priv_validator=FilePV(sks[idx]),
    )
    return cs, bstore, evpool, app


class TestDoubleSignEvidence:
    def test_conflicting_votes_become_evidence_and_reach_the_app(self):
        sks, vset = make_validators(2, power=[10, 10])
        # a chain run by validator 0 only needs both signatures; instead run a
        # 2-validator in-process net where validator 1 equivocates prevotes
        nodes, stores, pools, apps = [], [], [], []
        for i in range(2):
            cs, bstore, evpool, app = make_evidence_node(sks, i)
            nodes.append(cs)
            stores.append(bstore)
            pools.append(evpool)
            apps.append(app)
        from tests.test_consensus import wire_nodes

        wire_nodes(nodes)

        # byzantine override on node 1: prevote BOTH the proposal block and a
        # fabricated block each round (byzantine_test.go's equivocation)
        victim = nodes[0]
        byz = nodes[1]
        orig_do_prevote = byz._do_prevote

        def equivocating_prevote(cs_self, height, round_):
            orig_do_prevote(height, round_)
            # craft a complete-but-different block id and sign it too
            from tendermint_tpu.types.block import BlockID, PartSetHeader

            addr = cs_self._priv_validator_pub_key.address()
            idx, _ = cs_self.rs.validators.get_by_address(addr)
            bid = BlockID(
                hash=b"\x42" * 32,
                part_set_header=PartSetHeader(total=1, hash=b"\x42" * 32),
            )
            evil = Vote(
                type=PREVOTE_TYPE,
                height=cs_self.rs.height,
                round=cs_self.rs.round,
                block_id=bid,
                timestamp=cs_self._vote_time(),
                validator_address=addr,
                validator_index=idx,
            )
            sig = cs_self._priv_validator._priv_key.sign(evil.sign_bytes(CHAIN_ID))
            evil = Vote(**{**evil.__dict__, "signature": sig})
            victim.add_vote_msg(evil, peer_id="byz")

        byz.do_prevote_override = equivocating_prevote

        for n in nodes:
            n.start()
        try:
            deadline = time.time() + 60
            while time.time() < deadline:
                if apps[0].byzantine_reports:
                    break
                time.sleep(0.1)
        finally:
            for n in nodes:
                n.stop()

        # the victim collected DuplicateVoteEvidence and it reached the app
        assert apps[0].byzantine_reports, "no byzantine validators reported to app"
        report = apps[0].byzantine_reports[0]
        assert report.type == abci.EVIDENCE_TYPE_DUPLICATE_VOTE
        assert report.validator.address == sks[1].pub_key().address()
        # evidence is recorded in a committed block
        found = False
        for h in range(1, stores[0].height() + 1):
            blk = stores[0].load_block(h)
            for raw in blk.evidence:
                ev = decode_evidence(raw)
                assert isinstance(ev, DuplicateVoteEvidence)
                found = True
        assert found, "evidence not found in any committed block"
