"""Tier-1 face of device-batched live-vote ingress (ISSUE 15).

Same pattern as test_ingress_isolated.py: the container lacks the
`cryptography` wheel, so the vote-ingress suite (tests/test_vote_ingress.py
— batched-vs-sequential add_vote error parity, equivocation evidence,
DispatchError poisoned-window isolation, stepped determinism, the
HasVoteBits wire round-trip) and the `tools/prep_bench.py --votes` gate
run in SUBPROCESSES with TM_TPU_PUREPY_CRYPTO=1, which must never leak
into the main pytest process (even envelope parsing pulls the crypto
import chain, so there are no in-process units here).
"""

import os
import subprocess
import sys

import pytest


def _repo_root():
    return os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _purepy_env():
    from tendermint_tpu.libs import jaxcache

    env = dict(os.environ, TM_TPU_PUREPY_CRYPTO="1", JAX_PLATFORMS="cpu")
    env.pop("TM_TPU_DONATE", None)
    env.pop("TM_TPU_MESH", None)
    jaxcache.set_env(env, _repo_root())
    return env


# -- subprocess faces ----------------------------------------------------


def test_vote_ingress_suite_under_purepy_fallback():
    try:
        import cryptography  # noqa: F401

        pytest.skip("cryptography present; test_vote_ingress runs directly")
    except ModuleNotFoundError:
        pass
    here = os.path.dirname(os.path.abspath(__file__))
    r = subprocess.run(
        [
            sys.executable, "-m", "pytest",
            os.path.join(here, "test_vote_ingress.py"),
            "-q", "-m", "not slow", "-p", "no:cacheprovider",
        ],
        capture_output=True,
        env=_purepy_env(),
        cwd=_repo_root(),
        timeout=800,
    )
    tail = (r.stdout or b"").decode(errors="replace")[-3000:]
    assert r.returncode == 0, f"isolated test_vote_ingress run failed:\n{tail}"


def test_prep_bench_votes_gate():
    """ISSUE 15 satellite: the --votes gate — vote-window fusing proven
    by launch count (N gossiped votes in <= K device launches), exactly
    the forged signature rejected, zero pool-slot leak — wired into
    tier-1 through the isolated runner."""
    r = subprocess.run(
        [
            sys.executable,
            os.path.join(_repo_root(), "tools", "prep_bench.py"),
            "--votes",
        ],
        capture_output=True,
        env=_purepy_env(),
        cwd=_repo_root(),
        timeout=600,
    )
    out = (r.stdout or b"").decode(errors="replace")
    err = (r.stderr or b"").decode(errors="replace")
    assert r.returncode == 0, f"--votes gate failed:\n{out}\n{err[-2000:]}"
