"""State sync: a fresh node bootstraps from an app snapshot served by a
peer, with light-client-verified trust (SURVEY.md §7 stage 6)."""

import time

import pytest

from tendermint_tpu.abci import KVStoreApplication, LocalClient
from tendermint_tpu.crypto import ed25519
from tendermint_tpu.db import MemDB
from tendermint_tpu.p2p import (
    MemoryTransport,
    NodeKey,
    PeerAddress,
    PeerManager,
    Router,
    new_memory_network,
)
from tendermint_tpu.state import make_genesis_state
from tendermint_tpu.state.store import StateStore
from tendermint_tpu.statesync import StateSyncReactor, SyncError
from tendermint_tpu.store import BlockStore
from tendermint_tpu.types import Timestamp
from tendermint_tpu.types.genesis import GenesisDoc, GenesisValidator
from tests.test_consensus import FAST


CHAIN_ID = "cs-chain"


@pytest.fixture(scope="module")
def snapshotting_chain():
    """A 1-validator chain with snapshot_interval=2, run past height 6."""
    from tendermint_tpu.config import ConsensusConfig
    from tendermint_tpu.consensus import ConsensusState
    from tendermint_tpu.eventbus import EventBus
    from tendermint_tpu.mempool import TxMempool
    from tendermint_tpu.privval import FilePV
    from tendermint_tpu.state.execution import BlockExecutor

    sk = ed25519.gen_priv_key(bytes([7]) * 32)
    doc = GenesisDoc(
        chain_id=CHAIN_ID,
        genesis_time=Timestamp(seconds=1_700_000_000),
        validators=[GenesisValidator(address=b"", pub_key=sk.pub_key(), power=10)],
    )
    state = make_genesis_state(doc)
    app = KVStoreApplication(snapshot_interval=2)
    proxy = LocalClient(app)
    sstore = StateStore(MemDB())
    sstore.save(state)
    bstore = BlockStore(MemDB())
    mp = TxMempool(LocalClient(app))
    for i in range(3):
        mp.check_tx(b"snap%d=v%d" % (i, i))
    ex = BlockExecutor(sstore, proxy, mempool=mp, block_store=bstore)
    cs = ConsensusState(FAST, state, ex, bstore, mempool=mp, priv_validator=FilePV(sk))
    cs.start()
    try:
        cs.wait_for_height(7, timeout=60)
    finally:
        cs.stop()
    return app, proxy, sstore, bstore, doc


class TestStateSync:
    def test_fresh_node_state_syncs(self, snapshotting_chain):
        app, proxy, src_sstore, src_bstore, doc = snapshotting_chain
        assert app._snapshots, "source app has no snapshots"

        hub = new_memory_network()
        keys = [NodeKey.generate(bytes([i + 40]) * 32) for i in range(2)]
        routers = []
        for i in range(2):
            t = MemoryTransport(hub, keys[i].node_id, keys[i].pub_key)
            pm = PeerManager(keys[i].node_id)
            routers.append(Router(t, pm, keys[i].node_id))

        server = StateSyncReactor(
            routers[0], proxy, src_sstore, src_bstore, CHAIN_ID, serving=True
        )

        fresh_app = KVStoreApplication()
        fresh_conn = LocalClient(fresh_app)
        fresh_sstore = StateStore(MemDB())
        fresh_bstore = BlockStore(MemDB())
        client = StateSyncReactor(
            routers[1], fresh_conn, fresh_sstore, fresh_bstore, CHAIN_ID, serving=False
        )

        routers[0]._pm.add_address(PeerAddress(keys[1].node_id, keys[1].node_id))
        for r in routers:
            r.start()
        server.start()
        client.start()
        # wait for connectivity
        deadline = time.time() + 5
        while time.time() < deadline and not routers[1].connected():
            time.sleep(0.05)

        genesis_state = make_genesis_state(doc)
        # choose a snapshot with light blocks available at h, h+1, h+2
        usable = [h for h in app._snapshots if h + 2 <= src_bstore.height()]
        assert usable, (app._snapshots.keys(), src_bstore.height())
        snap_height = max(usable)
        trust_block = server._load_local_light_block(snap_height)
        try:
            state, commit = client.sync_any(
                genesis_state,
                trust_height=snap_height,
                trust_hash=trust_block.hash(),
                discovery_time=10.0,
            )
        finally:
            server.stop()
            client.stop()
            for r in routers:
                r.stop()

        assert state.last_block_height == snap_height
        # trusted app hash came from the header at snap_height+1
        next_meta = src_bstore.load_block_meta(snap_height + 1)
        assert state.app_hash == next_meta.header.app_hash
        # the fresh app restored the snapshot: data is queryable
        from tendermint_tpu.abci import types as abci_t

        q = fresh_conn.query(abci_t.RequestQuery(data=b"snap0", path="/key"))
        assert q.value == b"v0"
        info = fresh_conn.info(abci_t.RequestInfo())
        assert info.last_block_height == snap_height
        # stores were bootstrapped
        assert fresh_bstore.load_block_meta(snap_height) is not None
        assert fresh_sstore.load().last_block_height == snap_height
        assert fresh_sstore.load_validators(snap_height + 1).hash() == state.validators.hash()
        assert commit.height == snap_height
        # consensus params were fetched at the snapshot height over the
        # params channel, not defaulted from genesis (reactor.go params ch)
        assert state.last_height_consensus_params_changed == snap_height
        # bootstrap checkpoints the fetched params at the next height
        assert fresh_sstore.load_consensus_params(snap_height + 1).block.max_bytes == \
            state.consensus_params.block.max_bytes

    def test_backfill_stores_evidence_window(self, snapshotting_chain):
        """reactor.go:504 backfill: after restore, the historical window
        of headers/commits/validator sets is fetched, hash-link-verified
        and persisted so old-window evidence can be verified."""
        app, proxy, src_sstore, src_bstore, doc = snapshotting_chain
        hub = new_memory_network()
        keys = [NodeKey.generate(bytes([i + 50]) * 32) for i in range(2)]
        routers = []
        for i in range(2):
            t = MemoryTransport(hub, keys[i].node_id, keys[i].pub_key)
            routers.append(Router(t, PeerManager(keys[i].node_id), keys[i].node_id))
        server = StateSyncReactor(
            routers[0], proxy, src_sstore, src_bstore, CHAIN_ID, serving=True
        )
        fresh_sstore = StateStore(MemDB())
        fresh_bstore = BlockStore(MemDB())
        client = StateSyncReactor(
            routers[1], LocalClient(KVStoreApplication()), fresh_sstore,
            fresh_bstore, CHAIN_ID, serving=False,
        )
        routers[0]._pm.add_address(PeerAddress(keys[1].node_id, keys[1].node_id))
        for r in routers:
            r.start()
        server.start()
        client.start()
        deadline = time.time() + 5
        while time.time() < deadline and not routers[1].connected():
            time.sleep(0.05)

        genesis_state = make_genesis_state(doc)
        usable = [h for h in app._snapshots if h + 2 <= src_bstore.height()]
        snap_height = max(usable)
        trust_block = server._load_local_light_block(snap_height)
        try:
            state, _ = client.sync_any(
                genesis_state, trust_height=snap_height,
                trust_hash=trust_block.hash(), discovery_time=10.0,
            )
            stored = client.backfill(state)
        finally:
            server.stop()
            client.stop()
            for r in routers:
                r.stop()
        # whole window back to initial height is present and linked
        assert stored == snap_height - 1, stored
        for h in range(1, snap_height):
            meta = fresh_bstore.load_block_meta(h)
            assert meta is not None, f"missing backfilled header at {h}"
            assert fresh_sstore.load_validators(h) is not None


class _OfflineReactor(StateSyncReactor):
    """A reactor with the network replaced by a dict of light blocks, for
    exercising the chain-of-trust verification in isolation."""

    def __init__(self, chain_id, blocks):
        self._chain_id = chain_id
        self._blocks = blocks

    def _fetch_light_block(self, height, timeout=10.0):
        try:
            return self._blocks[height]
        except KeyError:
            raise SyncError(f"no light block at height {height}")


class TestStateSyncTrust:
    """stateprovider.go:33: every header the state provider hands out is
    verified through the light client from the trusted root — a
    self-consistent forged block (attacker valset + header + commit signed
    by the attacker) must NOT bootstrap the node."""

    def _root(self, sstore, bstore, h):
        from tendermint_tpu.light.provider import LightBlock
        from tendermint_tpu.types import SignedHeader

        meta = bstore.load_block_meta(h)
        return LightBlock(
            signed_header=SignedHeader(
                header=meta.header, commit=bstore.load_block_commit(h)
            ),
            validators=sstore.load_validators(h),
        )

    def test_forged_light_block_rejected(self, snapshotting_chain):
        from dataclasses import replace as dc_replace

        from tendermint_tpu.light.provider import LightBlock
        from tendermint_tpu.types import SignedHeader, Validator, ValidatorSet, Vote
        from tendermint_tpu.types.block import BlockID, PartSetHeader
        from tendermint_tpu.types.vote import PRECOMMIT_TYPE
        from tendermint_tpu.types.vote_set import VoteSet

        app, proxy, sstore, bstore, doc = snapshotting_chain
        h = bstore.height() - 2
        root = self._root(sstore, bstore, h)

        atk_sk = ed25519.gen_priv_key(b"\x66" * 32)
        atk_vset = ValidatorSet.new([Validator.new(atk_sk.pub_key(), 10)])
        real_next = bstore.load_block_meta(h + 1).header
        forged_header = dc_replace(
            real_next,
            validators_hash=atk_vset.hash(),
            next_validators_hash=atk_vset.hash(),
            app_hash=b"\x66" * 32,
        )
        bid = BlockID(
            hash=forged_header.hash(),
            part_set_header=PartSetHeader(total=1, hash=b"\x66" * 32),
        )
        vs = VoteSet(CHAIN_ID, h + 1, 0, PRECOMMIT_TYPE, atk_vset)
        vote = Vote(
            type=PRECOMMIT_TYPE, height=h + 1, round=0, block_id=bid,
            timestamp=forged_header.time,
            validator_address=atk_sk.pub_key().address(), validator_index=0,
        )
        vote = Vote(**{**vote.__dict__, "signature": atk_sk.sign(vote.sign_bytes(CHAIN_ID))})
        assert vs.add_vote(vote)
        forged = LightBlock(
            signed_header=SignedHeader(header=forged_header, commit=vs.make_commit()),
            validators=atk_vset,
        )
        # The forged block is self-consistent: its commit has 100% of its
        # OWN validator set. Under self-referential verification it passes;
        # under chain-of-trust verification it must fail.
        r = _OfflineReactor(CHAIN_ID, {h: root, h + 1: forged})
        with pytest.raises(SyncError):
            r._verified_light_block(h + 1, {h: root})

    def test_real_light_block_accepted(self, snapshotting_chain):
        app, proxy, sstore, bstore, doc = snapshotting_chain
        h = bstore.height() - 2
        root = self._root(sstore, bstore, h)
        real_next = self._root(sstore, bstore, h + 1)
        r = _OfflineReactor(CHAIN_ID, {h: root, h + 1: real_next})
        lb = r._verified_light_block(h + 1, {h: root})
        assert lb.height == h + 1


class TestChunkRecovery:
    def test_retry_refetch_reject_senders(self, snapshotting_chain):
        """syncer.go:420-470 applyChunks semantics: the app can demand the
        same chunk again (RETRY), discard and re-request a chunk
        (refetch_chunks), and ban its sender (reject_senders) — the sync
        must still complete."""
        from tendermint_tpu.abci import types as abci_t

        app, proxy, src_sstore, src_bstore, doc = snapshotting_chain

        class FlakyRestoreApp(KVStoreApplication):
            def __init__(self):
                super().__init__()
                self.events = []
                self._snap_retried = False
                self._retried = False
                self._refetched = False

            def apply_snapshot_chunk(self, req):
                last = self._restoring.chunks - 1 if self._restoring else 0
                if not self._snap_retried:
                    # errRetrySnapshot: restart restoration of the SAME
                    # snapshot (sync_any must re-offer, not reject)
                    self._snap_retried = True
                    self.events.append(("retry-snapshot", req.index))
                    return abci_t.ResponseApplySnapshotChunk(
                        result=abci_t.APPLY_SNAPSHOT_CHUNK_RETRY_SNAPSHOT
                    )
                if req.index == 0 and not self._retried:
                    self._retried = True
                    self.events.append(("retry", req.index))
                    return abci_t.ResponseApplySnapshotChunk(
                        result=abci_t.APPLY_SNAPSHOT_CHUNK_RETRY
                    )
                if req.index == last and not self._refetched:
                    self._refetched = True
                    self.events.append(("refetch", req.index, req.sender))
                    # "discard" the chunk: accept without buffering, ask
                    # for it again and blame a (fictional) second sender
                    return abci_t.ResponseApplySnapshotChunk(
                        result=abci_t.APPLY_SNAPSHOT_CHUNK_ACCEPT,
                        refetch_chunks=[last],
                        reject_senders=["ghost-peer"],
                    )
                self.events.append(("accept", req.index))
                return super().apply_snapshot_chunk(req)

        hub = new_memory_network()
        keys = [NodeKey.generate(bytes([i + 80]) * 32) for i in range(2)]
        routers = []
        for i in range(2):
            t = MemoryTransport(hub, keys[i].node_id, keys[i].pub_key)
            pm = PeerManager(keys[i].node_id)
            routers.append(Router(t, pm, keys[i].node_id))
        server = StateSyncReactor(
            routers[0], proxy, src_sstore, src_bstore, CHAIN_ID, serving=True
        )
        fresh_app = FlakyRestoreApp()
        client = StateSyncReactor(
            routers[1], LocalClient(fresh_app), StateStore(MemDB()),
            BlockStore(MemDB()), CHAIN_ID, serving=False,
        )
        routers[0]._pm.add_address(PeerAddress(keys[1].node_id, keys[1].node_id))
        for r in routers:
            r.start()
        server.start()
        client.start()
        deadline = time.time() + 5
        while time.time() < deadline and not routers[1].connected():
            time.sleep(0.05)
        genesis_state = make_genesis_state(doc)
        usable = [h for h in app._snapshots if h + 2 <= src_bstore.height()]
        snap_height = max(usable)
        trust_block = server._load_local_light_block(snap_height)
        try:
            state, _commit = client.sync_any(
                genesis_state,
                trust_height=snap_height,
                trust_hash=trust_block.hash(),
                discovery_time=10.0,
            )
        finally:
            server.stop()
            client.stop()
            for r in routers:
                r.stop()
        assert state.last_block_height == snap_height
        kinds = [e[0] for e in fresh_app.events]
        assert "retry-snapshot" in kinds
        assert "retry" in kinds and "refetch" in kinds
        # restore finished AFTER the recovery dance
        assert kinds[-1] == "accept"
        # the blamed sender is banned for the rest of the sync
        assert "ghost-peer" in client._banned_senders
