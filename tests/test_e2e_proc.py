"""Multi-process e2e: real OS processes over TCP, kill -9 mid-consensus,
restart, WAL replay + handshake recovery; plus the fail-point crash
matrix over every fail_point() in ApplyBlock.

Reference parity: test/e2e/runner/main.go:45-130 (setup -> start ->
perturb -> wait -> test), perturb.go (kill/restart), and the
FAIL_TEST_INDEX crash-consistency protocol of internal/libs/fail
(execution.go:171-218).
"""

import json
import os
import signal
import socket
import subprocess
import sys
import time
import urllib.request

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _free_port_base(n_nodes: int) -> int:
    """A base such that base..base+10*n are (probabilistically) free."""
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    base = s.getsockname()[1]
    s.close()
    return min(base, 55000)


def _env():
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env.pop("PALLAS_AXON_POOL_IPS", None)  # the axon plugin can hang imports
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    return env


def _rpc(port: int, path: str, timeout: float = 2.0):
    with urllib.request.urlopen(
        f"http://127.0.0.1:{port}/{path}", timeout=timeout
    ) as r:
        return json.loads(r.read())


def _status_height(port: int) -> int:
    res = _rpc(port, "status")
    return int(res["result"]["sync_info"]["latest_block_height"])


def _spawn(home: str, extra_env=None) -> subprocess.Popen:
    env = _env()
    if extra_env:
        env.update(extra_env)
    return subprocess.Popen(
        [sys.executable, "-m", "tendermint_tpu", "--home", home, "start"],
        env=env,
        cwd=REPO,
        stdout=subprocess.DEVNULL,
        stderr=subprocess.PIPE,
    )


def _wait_height(port: int, h: int, timeout: float) -> int:
    deadline = time.time() + timeout
    last = -1
    while time.time() < deadline:
        try:
            last = _status_height(port)
            if last >= h:
                return last
        except (OSError, ValueError, KeyError):
            pass
        time.sleep(0.3)
    raise AssertionError(f"height {h} not reached on :{port} (last {last})")


def _make_testnet(tmp_path, n: int, base: int) -> list:
    from tendermint_tpu import cli
    from tendermint_tpu.config import Config

    out = str(tmp_path / "net")
    rc = cli.main(
        ["testnet", "--v", str(n), "--o", out, "--port-base", str(base)]
    )
    assert rc == 0
    homes = [os.path.join(out, f"node{i}") for i in range(n)]
    for home in homes:
        cfg = Config.load(os.path.join(home, "config", "config.toml"))
        cfg.base.home = home
        # fast consensus so the test finishes in seconds
        cfg.consensus.timeout_propose_ms = 400
        cfg.consensus.timeout_propose_delta_ms = 100
        cfg.consensus.timeout_prevote_ms = 200
        cfg.consensus.timeout_prevote_delta_ms = 100
        cfg.consensus.timeout_precommit_ms = 200
        cfg.consensus.timeout_precommit_delta_ms = 100
        cfg.consensus.timeout_commit_ms = 200
        cfg.base.proxy_app = "kvstore"
        cfg.save(os.path.join(home, "config", "config.toml"))
    return homes


@pytest.mark.slow
def test_four_process_testnet_kill9_restart(tmp_path):
    n = 4
    base = _free_port_base(n)
    homes = _make_testnet(tmp_path, n, base)
    rpc_ports = [base + 1 + 10 * i for i in range(n)]
    procs = [_spawn(h) for h in homes]
    try:
        for p in rpc_ports:
            _wait_height(p, 2, timeout=90)

        # SIGKILL node 3 mid-consensus (perturb.go "kill")
        procs[3].kill()
        procs[3].wait(timeout=10)

        # the remaining 3/4 (+2/3 power) keep committing
        h_before = _status_height(rpc_ports[0])
        for p in rpc_ports[:3]:
            _wait_height(p, h_before + 3, timeout=60)

        # restart: WAL replay + handshake + catchup (replay.go:240)
        procs[3] = _spawn(homes[3])
        tip = _status_height(rpc_ports[0])
        h3 = _wait_height(rpc_ports[3], tip, timeout=90)
        assert h3 >= tip

        # all nodes agree on the app hash at a common height
        common = min(_status_height(p) for p in rpc_ports)
        hashes = set()
        for p in rpc_ports:
            blk = _rpc(p, f"block?height={common}")
            hashes.add(blk["result"]["block"]["header"]["app_hash"])
        assert len(hashes) == 1, f"app hash divergence at {common}: {hashes}"
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
        err = procs[3].stderr.read().decode()[-2000:] if procs[3].stderr else ""
        assert True, err


@pytest.mark.slow
def test_crash_at_every_fail_point_then_replay(tmp_path):
    """FAIL_TEST_INDEX matrix: a single-validator node is killed at each
    numbered fail_point() inside ApplyBlock; after every crash a restart
    must recover via WAL/handshake replay and keep committing — with WAL
    rotation forced on tiny chunks so recovery also crosses chunk
    boundaries (autofile/group.go + execution.go:171-218)."""
    base = _free_port_base(1)
    homes = _make_testnet(tmp_path, 1, base)
    home, port = homes[0], base + 1
    # force aggressive WAL rotation so replay spans rotated chunks
    extra = {"TM_TPU_WAL_HEAD_LIMIT": "4096"}

    for fail_idx in range(1, 5):  # fail points 1..4 in apply_block
        proc = _spawn(home, {**extra, "FAIL_TEST_INDEX": str(fail_idx)})
        rc = proc.wait(timeout=120)
        assert rc == 1, f"fail point {fail_idx} did not fire (rc={rc})"

        # recover: restart without the fail point and make progress
        proc = _spawn(home, extra)
        try:
            deadline = time.time() + 90
            h = None
            while time.time() < deadline:
                try:
                    h = _status_height(port)
                    break
                except (OSError, ValueError, KeyError):
                    time.sleep(0.3)
            assert h is not None, f"no RPC after crash at point {fail_idx}"
            _wait_height(port, h + 2, timeout=60)
        finally:
            proc.kill()
            proc.wait(timeout=10)

    # rotation actually happened
    wal_dir = os.path.join(home, "data", "cs.wal")
    rotated = [f for f in os.listdir(os.path.dirname(wal_dir) or home)
               if ".wal" in f] if os.path.isdir(os.path.dirname(wal_dir)) else []
    assert rotated, "expected WAL files on disk"


@pytest.mark.slow
def test_replay_console_redrive_after_kill9(tmp_path, capsys):
    """VERDICT r4 item 6: the replay CLI must RE-DRIVE the WAL through the
    consensus state machine (replay_file.go:38-90), not just print
    records. A single-validator node is SIGKILLed mid-height, then the
    WAL is replayed via the CLI against snapshot stores and the
    reconstructed round state asserted; the Playback console surface
    (next/back/rs/n) is exercised directly on the same home."""
    base = _free_port_base(1)
    homes = _make_testnet(tmp_path, 1, base)
    home = homes[0]
    port = base + 1

    proc = _spawn(home)
    try:
        _wait_height(port, 3, timeout=90)
    finally:
        proc.kill()  # SIGKILL mid-height: WAL tail has in-flight records
        proc.wait(timeout=10)

    from tendermint_tpu import cli
    from tendermint_tpu.config import Config
    from tendermint_tpu.consensus.replay_console import Playback
    from tendermint_tpu.state.store import StateStore
    from tendermint_tpu.db import backend as db_backend

    cfg = Config.load(os.path.join(home, "config", "config.toml"))
    cfg.base.home = home
    stored = StateStore(
        db_backend(cfg.base.db_backend, cfg.base.db_path("state"))
    ).load()
    assert stored is not None and stored.last_block_height >= 3

    # CLI (non-console): applies every record, prints the round state
    rc = cli.main(["--home", home, "replay"])
    assert rc == 0 or rc is None
    out = capsys.readouterr().out
    assert "replayed" in out and "round state" in out
    # the re-driven state machine must stand at the next height to decide
    assert f"round state: {stored.last_block_height + 1}/" in out

    # console surface: step, inspect, reset-and-replay (playback manager)
    pb = Playback(cfg)
    total = len(pb._records)
    assert total > 0
    assert pb.round_state("short").startswith(f"{stored.last_block_height + 1}/")
    pb.step(5)
    assert pb.count == 5
    assert pb.step(total) == total - 5  # drains the rest, reports applied
    h_full = pb.cs.rs.height
    pb.reset_back(total)  # rewind to the beginning (replayReset)
    assert pb.count == 0
    pb.step(total)
    assert pb.cs.rs.height == h_full, "replay must be deterministic"
