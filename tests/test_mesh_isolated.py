"""Tier-1 face of the mesh dispatcher (ISSUE 9).

Two layers, same pattern as test_overlap_isolated.py:

- jax-free, crypto-free unit tests of the lane packer (ops/mesh.py:
  pack_jobs / MeshPlan / pad_block / build_superblock / env knobs) run
  IN PROCESS — pure numpy bookkeeping, no kernel compiles;
- the kernel-level parity suite (tests/test_mesh.py) and the
  `tools/prep_bench.py --mesh` pack/demux/slot-leak/single-owner gate
  run in SUBPROCESSES with TM_TPU_PUREPY_CRYPTO=1, which must never
  leak into the main pytest process.
"""

import os
import subprocess
import sys

import numpy as np
import pytest

try:
    from tendermint_tpu.ops import mesh as ms
except ModuleNotFoundError:
    # The ops package __init__ wires the crypto.batch seam, which needs
    # the cryptography wheel this container lacks. mesh.py's packing
    # half is numpy + entry_block bookkeeping — load the module file
    # directly so the plan/pack unit tests still run in the main tier-1
    # process (mesh.py carries its own standalone entry_block loader).
    import importlib.util

    _p = os.path.join(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        "tendermint_tpu", "ops", "mesh.py",
    )
    _spec = importlib.util.spec_from_file_location(
        "_tm_tpu_mesh_standalone", _p
    )
    ms = importlib.util.module_from_spec(_spec)
    _spec.loader.exec_module(ms)


class _J:
    def __init__(self, blk):
        self.entries = blk


def _blk(n, key=None, tag=0):
    eb = ms.EntryBlock(
        np.zeros((n, 32), dtype=np.uint8),
        np.zeros((n, 64), dtype=np.uint8),
        b"m" * n,
        np.arange(n + 1, dtype=np.int64),
    )
    eb.epoch_key = key
    if key is not None:
        eb.val_idx = np.arange(n, dtype=np.int32)
    return eb


class _Ep:
    """Epoch-entry stub: just the fields pad_block consumes."""

    def __init__(self, vp=64, key=b"ep"):
        self.vp = vp
        self.key = key


class TestPackJobs:
    def test_first_fit_same_key_shares_a_lane(self):
        plan, held = ms.pack_jobs(
            [_J(_blk(40, b"k")), _J(_blk(50, b"k")), _J(_blk(30))], 4, 128
        )
        assert not held
        assert [(l.key, l.n) for l in plan.lanes] == [(b"k", 90), (None, 30)]

    def test_mixed_keys_never_share_a_lane(self):
        plan, _ = ms.pack_jobs(
            [_J(_blk(10, b"a")), _J(_blk(10, b"b")), _J(_blk(10))], 4, 128
        )
        assert [l.key for l in plan.lanes] == [b"a", b"b", None]

    def test_overflow_jobs_are_held(self):
        jobs = [_J(_blk(128)) for _ in range(3)]
        plan, held = ms.pack_jobs(jobs, 2, 128)
        assert len(held) == 1 and held[0] is jobs[2]
        assert plan.n_lanes == 2 and plan.live == 256

    def test_job_over_lane_cap_raises(self):
        with pytest.raises(ValueError):
            ms.pack_jobs([_J(_blk(200))], 2, 128)

    def test_empty_job_gets_zero_width_span(self):
        plan, held = ms.pack_jobs([_J(_blk(0))], 2, 128)
        assert not held
        _, spans = ms.build_superblock(plan)
        assert len(spans) == 1 and spans[0][2] == 0

    def test_lane_count_rounds_to_pow2(self):
        plan, _ = ms.pack_jobs(
            [_J(_blk(128, bytes([i]))) for i in range(3)], 8, 128
        )
        assert len(plan.lanes) == 3 and plan.n_lanes == 4
        assert plan.pad == 128  # one pure padding lane

    def test_non_pow2_max_lanes_floors_to_pow2(self):
        # TM_TPU_MESH=3 must not mint 3-lane compiled shapes: the lane
        # budget floors to 2 and the third epoch's job is held
        plan, held = ms.pack_jobs(
            [_J(_blk(100, bytes([i]))) for i in range(3)], 3, 128
        )
        assert plan.n_lanes == 2 and len(plan.lanes) == 2
        assert len(held) == 1

    def test_empty_job_does_not_pin_or_demote_a_lane(self):
        # an empty (keyless) submission must not open a None-keyed lane
        # that demotes a same-warm-epoch pack to the uncached prep
        plan, held = ms.pack_jobs(
            [_J(_blk(0)), _J(_blk(40, b"k")), _J(_blk(30, b"k"))], 2, 128
        )
        assert not held
        assert [l.key for l in plan.lanes] == [b"k"]
        assert plan.epoch_key() == b"k"
        assert len(plan.empty_jobs) == 1
        _, spans = ms.build_superblock(plan)
        assert sum(1 for s in spans if s[2] == 0) == 1

    def test_occupancy_and_pad_are_complementary(self):
        plan, _ = ms.pack_jobs([_J(_blk(96)), _J(_blk(32))], 2, 128)
        assert plan.occupancy() + plan.pad_ratio() == pytest.approx(1.0)
        assert plan.live == 128 and plan.bucket == plan.n_lanes * 128


class TestSuperblock:
    def test_spans_tile_live_rows_exactly(self):
        plan, _ = ms.pack_jobs(
            [_J(_blk(96)), _J(_blk(31)), _J(_blk(5, b"z"))], 4, 128
        )
        block, spans = ms.build_superblock(plan)
        assert len(block) == plan.bucket
        rows = np.zeros(plan.bucket, dtype=bool)
        for _, off, n in spans:
            assert not rows[off:off + n].any()
            rows[off:off + n] = True
        assert int(rows.sum()) == plan.live
        # every span stays inside its lane (no straddling)
        lb = plan.lane_bucket
        for _, off, n in spans:
            assert off // lb == (off + max(n, 1) - 1) // lb

    def test_pad_rows_are_identity(self):
        p = ms.pad_block(5)
        assert (p.pub[:, 0] == 1).all() and (p.pub[:, 1:] == 0).all()
        assert (p.sig[:, 0] == 1).all() and (p.sig[:, 1:] == 0).all()
        assert p.msg_nbytes() == 0 and p.epoch_key is None

    def test_pad_rows_carry_epoch_identity_index(self):
        p = ms.pad_block(4, _Ep(vp=64, key=b"warm"))
        assert p.epoch_key == b"warm"
        assert (p.val_idx == 63).all()

    def test_lane_bucket_quantizes_to_ladder(self):
        plan, _ = ms.pack_jobs([_J(_blk(129))], 1, 10240)
        assert plan.lane_bucket == 1024
        plan2, _ = ms.pack_jobs([_J(_blk(17))], 1, 10240)
        assert plan2.lane_bucket == 128


class TestKnobs:
    def test_lanes_from_env(self, monkeypatch):
        monkeypatch.delenv("TM_TPU_MESH", raising=False)
        assert ms.lanes_from_env() == 0
        monkeypatch.setenv("TM_TPU_MESH", "0")
        assert ms.lanes_from_env() == 0
        monkeypatch.setenv("TM_TPU_MESH", "4")
        assert ms.lanes_from_env() == 4
        monkeypatch.setenv("TM_TPU_MESH", "garbage")
        assert ms.lanes_from_env() == 0

    def test_lane_cap_env(self, monkeypatch):
        monkeypatch.delenv("TM_TPU_MESH_LANE_BUCKET", raising=False)
        assert ms.lane_cap() == 10240
        monkeypatch.setenv("TM_TPU_MESH_LANE_BUCKET", "1024")
        assert ms.lane_cap() == 1024
        monkeypatch.setenv("TM_TPU_MESH_LANE_BUCKET", "4")
        # floored at the secp lane-bucket floor (ISSUE 19), not 128: the
        # scheme lane's per-row kernel cost makes small lanes worthwhile
        assert ms.lane_cap() == 16
        monkeypatch.setenv("TM_TPU_MESH_LANE_BUCKET", "999999")
        assert ms.lane_cap() == 10240  # clamped into the bucket ladder


def _purepy_env():
    from tendermint_tpu.libs import jaxcache

    env = dict(os.environ, TM_TPU_PUREPY_CRYPTO="1", JAX_PLATFORMS="cpu")
    env.pop("TM_TPU_DONATE", None)
    env.pop("TM_TPU_MESH", None)
    jaxcache.set_env(env, _repo_root())
    return env


def _repo_root():
    return os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def test_mesh_under_purepy_fallback():
    try:
        import cryptography  # noqa: F401

        pytest.skip("cryptography present; test_mesh runs directly")
    except ModuleNotFoundError:
        pass
    here = os.path.dirname(os.path.abspath(__file__))
    # devcheck armed for the whole run (ISSUE 8 pattern): the mesh
    # superbatch path must hold the relay single-owner + canary
    # invariants under the runtime checkers, not just the AST pass
    env = dict(_purepy_env(), TM_TPU_DEVCHECK="1")
    r = subprocess.run(
        [
            sys.executable, "-m", "pytest",
            os.path.join(here, "test_mesh.py"),
            "-q", "-m", "not slow", "-p", "no:cacheprovider",
        ],
        capture_output=True,
        env=env,
        cwd=_repo_root(),
        timeout=800,
    )
    tail = (r.stdout or b"").decode(errors="replace")[-3000:]
    assert r.returncode == 0, f"isolated test_mesh run failed:\n{tail}"


def test_prep_bench_mesh_gate():
    """ISSUE 9 satellite: the --mesh pack/demux-parity + slot-leak +
    single-owner gate on the mocked 2-lane mesh, wired into tier-1
    through the isolated runner (same pattern as --overlap)."""
    r = subprocess.run(
        [
            sys.executable,
            os.path.join(_repo_root(), "tools", "prep_bench.py"),
            "--mesh",
        ],
        capture_output=True,
        env=_purepy_env(),
        cwd=_repo_root(),
        timeout=600,
    )
    out = (r.stdout or b"").decode(errors="replace")
    err = (r.stderr or b"").decode(errors="replace")
    assert r.returncode == 0, f"--mesh gate failed:\n{out}\n{err[-2000:]}"
