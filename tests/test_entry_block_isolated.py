"""Isolated runner for test_entry_block.py on containers without the
`cryptography` wheel.

The EntryBlock tests need a working ed25519 signer for their fixtures.
The pure-Python fallback (TM_TPU_PUREPY_CRYPTO=1) provides one, but the
flag must NOT be set inside the main pytest process: it changes how
`tendermint_tpu.crypto` imports for every module collected afterwards
and unlocks slow OpenSSL-dependent e2e failure paths. So when the wheel
is absent, this wrapper re-runs the whole module in a subprocess where
the flag can't leak."""

import os
import subprocess
import sys

import pytest


def test_entry_block_under_purepy_fallback():
    try:
        import cryptography  # noqa: F401

        pytest.skip("cryptography present; test_entry_block runs directly")
    except ModuleNotFoundError:
        pass
    here = os.path.dirname(os.path.abspath(__file__))
    env = dict(os.environ, TM_TPU_PUREPY_CRYPTO="1", JAX_PLATFORMS="cpu")
    r = subprocess.run(
        [
            sys.executable, "-m", "pytest",
            os.path.join(here, "test_entry_block.py"),
            "-q", "-m", "not slow", "-p", "no:cacheprovider",
        ],
        capture_output=True,
        env=env,
        cwd=os.path.dirname(here),
        timeout=700,
    )
    tail = (r.stdout or b"").decode(errors="replace")[-3000:]
    assert r.returncode == 0, f"isolated test_entry_block run failed:\n{tail}"
