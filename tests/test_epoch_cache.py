"""Valset epoch cache (ISSUE 5): LRU hit/miss/evict + invalidation
semantics, EntryBlock epoch metadata through slice/concat/coalescing,
device-unpack vs host-pack parity, and cached-vs-uncached verdict/blame
bit-parity on the XLA kernels (pallas/RLC cached kernels are covered by
the slow interpret tests at the bottom)."""

import numpy as np
import pytest

try:
    from tendermint_tpu.crypto import ed25519
except ModuleNotFoundError:
    # No cryptography wheel in this container. Do NOT flip
    # TM_TPU_PUREPY_CRYPTO here (env leaks into later-collected modules);
    # test_epoch_cache_isolated.py re-runs this module in a subprocess
    # with the fallback enabled instead.
    pytest.skip(
        "ed25519 backend unavailable (runs via test_epoch_cache_isolated.py)",
        allow_module_level=True,
    )

from tendermint_tpu.libs import metrics as _metrics
from tendermint_tpu.ops import backend, epoch_cache, pipeline
from tendermint_tpu.ops import ed25519_verify as ev
from tendermint_tpu.ops.entry_block import EntryBlock
from tendermint_tpu.types import Vote, validation
from tendermint_tpu.types.block import (
    BLOCK_ID_FLAG_ABSENT,
    BLOCK_ID_FLAG_COMMIT,
    BLOCK_ID_FLAG_NIL,
    BlockID,
    Commit,
    CommitSig,
    PartSetHeader,
)
from tendermint_tpu.types.validator_set import Validator, ValidatorSet
from tendermint_tpu.types.vote import PRECOMMIT_TYPE
from tendermint_tpu.wire.canonical import Timestamp

CHAIN_ID = "epoch-cache-test"


@pytest.fixture(autouse=True)
def _fresh_cache():
    """Every test starts with an ENABLED, empty cache and leaves the
    process on the environment default (disabled on CPU unless
    TM_TPU_EPOCH_CACHE is set) so other modules see no behavior change."""
    epoch_cache.reset(depth=4)
    yield
    epoch_cache.reset()


def _block_id():
    return BlockID(
        hash=b"\x11" * 32,
        part_set_header=PartSetHeader(total=1, hash=b"\x22" * 32),
    )


def _signed_commit(n, height=7, bad=(), nil=(), absent=(), power=None):
    """A REAL signed commit over n validators (index-aligned set)."""
    sks = [ed25519.gen_priv_key(bytes([i + 1]) * 32) for i in range(n)]
    vals = [
        Validator.new(sk.pub_key(), (power or [100] * n)[i])
        for i, sk in enumerate(sks)
    ]
    vset = ValidatorSet(validators=vals, proposer=vals[0])
    bid = _block_id()
    ts = Timestamp(seconds=1_700_000_000)
    sigs = []
    for i, sk in enumerate(sks):
        if i in absent:
            sigs.append(CommitSig.absent())
            continue
        flag = BLOCK_ID_FLAG_NIL if i in nil else BLOCK_ID_FLAG_COMMIT
        v = Vote(
            type=PRECOMMIT_TYPE, height=height, round=0,
            block_id=BlockID() if i in nil else bid,
            timestamp=ts, validator_address=vals[i].address,
            validator_index=i,
        )
        sig = (
            b"\x01" * 64 if i in bad else sk.sign(v.sign_bytes(CHAIN_ID))
        )
        sigs.append(
            CommitSig(
                block_id_flag=flag, validator_address=vals[i].address,
                timestamp=ts, signature=sig,
            )
        )
    commit = Commit(height=height, round=0, block_id=bid, signatures=sigs)
    return vset, commit, bid, sks


def _ops():
    return _metrics.ops_metrics()


# ---------------------------------------------------------------------------
# Cache core: hit/miss/evict, keying, invalidation
# ---------------------------------------------------------------------------


class TestEpochCacheCore:
    def test_cold_then_warm(self):
        vset, commit, _, _ = _signed_commit(6)
        key1 = epoch_cache.note_valset(vset)
        assert key1 is None  # first sight: cold, registers only
        key2 = epoch_cache.note_valset(vset)
        assert key2 == vset.hash()  # second sight: warm
        ep = epoch_cache.cache().get(key2)
        assert ep is not None
        assert ep.n_vals == 6
        assert ep.vp >= ep.n_vals + 1
        assert ep.vp & (ep.vp - 1) == 0  # power of two

    def test_hit_miss_evict_counters(self):
        m = _ops()
        h0, m0, e0 = (
            m.epoch_cache_hits.total(),
            m.epoch_cache_misses.total(),
            m.epoch_cache_evictions.total(),
        )
        sets = [_signed_commit(4 + i)[0] for i in range(5)]
        for vs in sets:
            assert epoch_cache.note_valset(vs) is None  # 5 misses
        # depth=4: registering the 5th evicted the 1st (LRU)
        assert m.epoch_cache_misses.total() - m0 == 5
        assert m.epoch_cache_evictions.total() - e0 == 1
        assert epoch_cache.note_valset(sets[4]) is not None  # hit
        assert m.epoch_cache_hits.total() - h0 == 1
        # the evicted set is cold again
        assert epoch_cache.note_valset(sets[0]) is None
        assert m.epoch_cache_misses.total() - m0 == 6

    def test_lru_ordering(self):
        sets = [_signed_commit(4 + i)[0] for i in range(4)]
        for vs in sets:
            epoch_cache.note_valset(vs)
        # touch the oldest so it is no longer the LRU victim
        assert epoch_cache.note_valset(sets[0]) is not None
        epoch_cache.note_valset(_signed_commit(12)[0])  # evicts sets[1]
        assert epoch_cache.note_valset(sets[0]) is not None
        assert epoch_cache.note_valset(sets[1]) is None  # was evicted

    def test_power_change_invalidates(self):
        vset, _, _, sks = _signed_commit(5)
        epoch_cache.note_valset(vset)
        key_a = epoch_cache.note_valset(vset)
        assert key_a is not None
        vset.update_with_change_set(
            [Validator.new(sks[0].pub_key(), 999)]
        )
        # _update_with_change_set cleared _hash and _ed_cols: the changed
        # set keys to a NEW epoch (cold), never the stale table
        assert vset.hash() != key_a
        assert epoch_cache.note_valset(vset) is None
        key_b = epoch_cache.note_valset(vset)
        assert key_b is not None and key_b != key_a

    def test_membership_change_invalidates(self):
        vset, _, _, _ = _signed_commit(5)
        epoch_cache.note_valset(vset)
        key_a = epoch_cache.note_valset(vset)
        new_sk = ed25519.gen_priv_key(b"\x77" * 32)
        vset.update_with_change_set([Validator.new(new_sk.pub_key(), 50)])
        assert vset.hash() != key_a
        assert epoch_cache.note_valset(vset) is None  # cold under new key
        ep = epoch_cache.cache().get(vset.hash())
        assert ep.n_vals == 6

    def test_non_ed25519_set_not_cached(self):
        class FakeKey:
            def bytes(self):
                return b"\x00" * 32

            def address(self):
                return b"\x00" * 20

        vset, _, _, _ = _signed_commit(3)
        vset.validators[1].pub_key = FakeKey()
        vset._ed_cols = None
        vset._hash = None
        epoch_cache.note_valset(vset)
        assert epoch_cache.note_valset(vset) is None  # never warm

    def test_disabled_cache(self):
        epoch_cache.reset(depth=0)
        vset, _, _, _ = _signed_commit(3)
        assert epoch_cache.note_valset(vset) is None
        assert epoch_cache.note_valset(vset) is None
        assert epoch_cache.cache() is None

    def test_copy_shares_epoch(self):
        vset, _, _, _ = _signed_commit(4)
        epoch_cache.note_valset(vset)
        c = vset.copy()
        # copy preserves (pub, power): same hash, same (warm) epoch
        assert epoch_cache.note_valset(c) == vset.hash()


# ---------------------------------------------------------------------------
# EntryBlock epoch metadata: slices, concat, coalescer fallback
# ---------------------------------------------------------------------------


def _meta_block(n, key, base=0):
    pub = np.arange(n * 32, dtype=np.uint8).reshape(n, 32)
    sig = np.zeros((n, 64), dtype=np.uint8)
    offs = np.arange(n + 1, dtype=np.int64) * 3
    return EntryBlock(
        pub, sig, b"abc" * n, offs,
        val_idx=np.arange(base, base + n, dtype=np.int32), epoch_key=key,
    )


class TestEntryBlockEpochMeta:
    def test_slice_preserves(self):
        b = _meta_block(6, b"K" * 32)
        s = b[2:5]
        assert s.epoch_key == b"K" * 32
        assert list(s.val_idx) == [2, 3, 4]

    def test_concat_same_key(self):
        a = _meta_block(3, b"K" * 32)
        b = _meta_block(2, b"K" * 32, base=7)
        c = EntryBlock.concat([a, b])
        assert c.epoch_key == b"K" * 32
        assert list(c.val_idx) == [0, 1, 2, 7, 8]

    def test_concat_mixed_key_falls_back(self):
        a = _meta_block(3, b"K" * 32)
        b = _meta_block(2, b"L" * 32)
        c = EntryBlock.concat([a, b])
        assert c.epoch_key is None and c.val_idx is None

    def test_concat_missing_key_falls_back(self):
        a = _meta_block(3, b"K" * 32)
        b = _meta_block(2, None)
        c = EntryBlock.concat([a, b])
        assert c.epoch_key is None and c.val_idx is None

    def test_coalescer_never_fuses_mixed_epochs(self, monkeypatch):
        """Jobs with differing epoch keys must reach _prepare in
        separate batches (the dispatch-level face of the mixed-valset
        fallback)."""
        seen = []
        orig = pipeline.AsyncBatchVerifier._prepare

        def spy(entries):
            seen.append((entries.epoch_key, len(entries)))
            return orig(entries)

        monkeypatch.setattr(
            pipeline.AsyncBatchVerifier, "_prepare", staticmethod(spy)
        )
        v = pipeline.AsyncBatchVerifier()
        try:
            sks = [ed25519.gen_priv_key(bytes([i + 1]) * 32) for i in range(4)]
            blocks = []
            for key in (b"A" * 32, b"A" * 32, b"B" * 32):
                ents = [
                    (sk.pub_key().bytes(), b"m", sk.sign(b"m")) for sk in sks
                ]
                blk = EntryBlock.from_entries(ents)
                blk.val_idx = np.arange(4, dtype=np.int32)
                blk.epoch_key = key
                blocks.append(blk)
            futs = [v.submit(b) for b in blocks]
            for f in futs:
                assert np.asarray(f.result(timeout=120)).all()
        finally:
            v.close()
        assert seen, "no batches dispatched"
        # every dispatched batch carries ONE epoch key — fused batches of
        # mixed keys would show epoch_key=None with 8+ entries
        for key, n in seen:
            assert key in (b"A" * 32, b"B" * 32)


# ---------------------------------------------------------------------------
# Device unpack vs host pack parity (the on-device prologue)
# ---------------------------------------------------------------------------


class TestDeviceUnpackParity:
    def test_limbs_and_bits(self):
        rng = np.random.RandomState(9)
        enc = rng.randint(0, 256, (37, 32), dtype=np.uint8)
        import jax.numpy as jnp

        limbs_dev, sign_dev = ev.unpack_limbs_rows(
            jnp.asarray(enc.astype(np.int32))
        )
        assert np.array_equal(
            np.asarray(limbs_dev), backend._pack_le_limbs(enc)
        )
        assert np.array_equal(
            np.asarray(sign_dev), (enc[:, 31] >> 7).astype(np.int32)
        )
        scal = enc.copy()
        scal[:, 31] &= 0x1F  # < 2^253
        bits_dev = ev.bits253_rows(jnp.asarray(scal.astype(np.int32)))
        assert np.array_equal(np.asarray(bits_dev), backend._bits_253(scal))

    def test_epoch_table_matches_host_pack(self):
        vset, _, _, _ = _signed_commit(5)
        epoch_cache.note_valset(vset)
        key = epoch_cache.note_valset(vset)
        ep = epoch_cache.cache().get(key)
        limbs, sign = ep.xla_tables()
        assert np.array_equal(
            np.asarray(limbs), backend._pack_le_limbs(ep.pub_rows)
        )
        # identity pad rows: limb0 = 1, rest 0, sign 0
        pad = np.asarray(limbs)[ep.n_vals:]
        assert (pad[:, 0] == 1).all() and (pad[:, 1:] == 0).all()


# ---------------------------------------------------------------------------
# Cached vs uncached verdict/blame bit-parity (XLA kernels, CPU)
# ---------------------------------------------------------------------------


def _warm_block(vset, commit, needed):
    dec = Commit.decode(commit.encode())
    assert dec.commit_block() is not None
    blk, _ = pipeline.commit_entries(CHAIN_ID, vset, dec, needed)
    if blk.epoch_key is None:  # first sight was cold
        blk, _ = pipeline.commit_entries(CHAIN_ID, vset, dec, needed)
    assert blk.epoch_key is not None
    return blk


class TestCachedVerdictParity:
    @pytest.mark.parametrize("n,bad,nil,absent", [
        (90, (17,), (), ()),
        (90, (3, 88), (11,), (40,)),
    ])
    def test_host_hash_parity(self, n, bad, nil, absent):
        vset, commit, _, _ = _signed_commit(n, bad=bad, nil=nil,
                                            absent=absent)
        # threshold just under the commit lanes' total power: the
        # early-stop selection keeps EVERY commit lane (bad ones too)
        needed = 100 * (n - len(nil) - len(absent)) - 1
        blk = _warm_block(vset, commit, needed)
        ep = epoch_cache.lookup(blk)
        assert ep is not None
        bucket = backend._bucket_for(len(blk))
        args_u = backend.prepare_batch(blk, bucket)
        res_u = np.asarray(ev.jitted_verify()(*args_u))[: len(blk)]
        args_c = backend.prepare_batch_cached(blk, bucket, ep)
        res_c = np.asarray(
            backend.cached_kernel(ep, device_hash=False)(*args_c)
        )[: len(blk)]
        assert np.array_equal(res_u, res_c)
        assert not res_c.all()  # the bad lanes really reject

    @pytest.mark.parametrize("n", [90, 150])  # buckets 128 and 1024
    def test_device_hash_parity(self, n):
        vset, commit, _, _ = _signed_commit(n, bad=(n - 2,), nil=(1,))
        blk = _warm_block(vset, commit, 100 * (n - 1) - 1)
        ep = epoch_cache.lookup(blk)
        bucket = backend._bucket_for(len(blk))
        args_u = backend.prepare_batch_device_hash(blk, bucket)
        res_u = np.asarray(ev.jitted_verify_device_hash()(*args_u))[: len(blk)]
        args_c = backend.prepare_batch_cached_device_hash(blk, bucket, ep)
        res_c = np.asarray(
            backend.cached_kernel(ep, device_hash=True)(*args_c)
        )[: len(blk)]
        assert np.array_equal(res_u, res_c)
        assert not res_c.all()
        # warm-epoch transfer really shrinks (acceptance: <= 0.5x)
        assert backend.h2d_arg_bytes(args_c) <= 0.5 * (
            backend.h2d_arg_bytes(args_u)
        )

    def test_verify_commit_blame_parity_cached_vs_uncached(self):
        n, bad_i = 90, 23
        vset, commit, bid, _ = _signed_commit(n, bad=(bad_i,))
        dec = Commit.decode(commit.encode())
        # uncached pass (cold epoch) — the PR-4 behavior
        epoch_cache.reset(depth=4)
        with pytest.raises(ValueError) as cold_err:
            validation.verify_commit(CHAIN_ID, vset, bid, 7, dec)
        # warm pass: same commit, epoch now resident -> cached kernels
        with pytest.raises(ValueError) as warm_err:
            validation.verify_commit(CHAIN_ID, vset, bid, 7, dec)
        assert str(cold_err.value) == str(warm_err.value)
        assert f"wrong signature (#{bad_i})" in str(warm_err.value)
        m = _ops()
        assert m.epoch_cache_hits.total() >= 1

    def test_verify_commit_accepts_warm(self):
        vset, commit, bid, _ = _signed_commit(80)
        dec = Commit.decode(commit.encode())
        validation.verify_commit(CHAIN_ID, vset, bid, 7, dec)  # cold
        validation.verify_commit(CHAIN_ID, vset, bid, 7, dec)  # warm
        # a light verify on the same epoch stays warm too
        validation.verify_commit_light(CHAIN_ID, vset, bid, 7, dec)

    def test_evicted_epoch_falls_back(self):
        """A key that points at an evicted entry degrades to the uncached
        path (verify still succeeds) — never an error."""
        vset, commit, bid, _ = _signed_commit(70)
        dec = Commit.decode(commit.encode())
        needed = vset.total_voting_power() * 2 // 3
        blk = _warm_block(vset, commit, needed)
        epoch_cache.cache().clear()  # simulate eviction after submit
        assert epoch_cache.lookup(blk) is None
        from tendermint_tpu.ops.pipeline import shared_verifier

        res = np.asarray(
            shared_verifier().submit(blk).result(timeout=300)
        )
        assert res.all()


# ---------------------------------------------------------------------------
# Churn lifecycle (ISSUE 6 satellite): realistic validator-set rotation —
# join + leave through the REAL update_with_change_set path, exactly what
# an EndBlock validator update drives — must cycle the cache through
# cold -> warm -> invalidate -> evict -> re-register, with verdict/blame
# parity on the evicted-fallback path. Sizes stay in the vp=128/bucket-128
# shape class the parity tests above already compiled.
# ---------------------------------------------------------------------------


def _vset_with_sks(n, first_byte=1):
    sks = [ed25519.gen_priv_key(bytes([first_byte + i]) * 32) for i in range(n)]
    vals = [Validator.new(sk.pub_key(), 100) for sk in sks]
    vset = ValidatorSet(validators=vals, proposer=vals[0])
    return vset, {sk.pub_key().bytes(): sk for sk in sks}


def _commit_signed_by(vset, by_pub, height=7, bad=()):
    """A commit signed by the CURRENT set in its CURRENT order (rotation
    re-sorts validators, so indices must be re-derived per epoch)."""
    bid = _block_id()
    ts = Timestamp(seconds=1_700_000_000)
    sigs = []
    for i, val in enumerate(vset.validators):
        v = Vote(
            type=PRECOMMIT_TYPE, height=height, round=0, block_id=bid,
            timestamp=ts, validator_address=val.address, validator_index=i,
        )
        sig = (
            b"\x01" * 64 if i in bad
            else by_pub[val.pub_key.bytes()].sign(v.sign_bytes(CHAIN_ID))
        )
        sigs.append(
            CommitSig(
                block_id_flag=BLOCK_ID_FLAG_COMMIT,
                validator_address=val.address, timestamp=ts, signature=sig,
            )
        )
    return Commit(height=height, round=0, block_id=bid, signatures=sigs), bid


def _rotate(vset, by_pub, joiner_byte):
    """One churn: a fresh validator joins, the current first leaves —
    the same change-set shape state.execution.update_state applies from
    EndBlock updates (power 0 = removal)."""
    new_sk = ed25519.gen_priv_key(bytes([joiner_byte]) * 32)
    by_pub[new_sk.pub_key().bytes()] = new_sk
    leaver = vset.validators[0]
    vset.update_with_change_set(
        [
            Validator.new(new_sk.pub_key(), 100),
            Validator.new(leaver.pub_key, 0),
        ]
    )


class TestChurnLifecycle:
    def test_rotation_cycles_cold_warm_invalidate_evict_reregister(self):
        epoch_cache.reset(depth=2)
        m = _ops()
        vset, by_pub = _vset_with_sks(90)

        def deltas():
            return (
                m.epoch_cache_hits.total(),
                m.epoch_cache_misses.total(),
                m.epoch_cache_evictions.total(),
            )

        def verify(h):
            commit, bid = _commit_signed_by(vset, by_pub, height=h)
            dec = Commit.decode(commit.encode())
            validation.verify_commit(CHAIN_ID, vset, bid, h, dec)

        h0, m0, e0 = deltas()
        key_a = vset.hash()
        epoch_a = vset.copy()  # pre-rotation snapshot: same hash/key
        verify(7)  # cold: registers epoch A
        h1, m1, e1 = deltas()
        assert (m1 - m0, e1 - e0) == (1, 0)
        verify(8)  # warm: hits epoch A
        h2, m2, _ = deltas()
        assert h2 - h1 >= 1 and m2 == m1

        _rotate(vset, by_pub, 200)  # epoch B: structural invalidation
        assert vset.hash() != key_a
        verify(9)   # cold under the NEW key (depth 2: A + B resident)
        verify(10)  # warm B
        _, m3, e3 = deltas()
        assert m3 - m2 == 1 and e3 - e1 == 0
        assert len(epoch_cache.cache()) == 2

        _rotate(vset, by_pub, 201)  # epoch C: LRU depth 2 evicts A
        verify(11)
        _, m4, e4 = deltas()
        assert m4 - m3 == 1 and e4 - e3 == 1
        assert epoch_cache.cache().get(key_a) is None  # A really evicted

        # re-register: the SAME membership (content-derived hash == key_a)
        # returning after eviction is a fresh cold registration, then warm
        assert epoch_a.hash() == key_a
        assert epoch_cache.note_valset(epoch_a) is None       # cold again
        assert epoch_cache.note_valset(epoch_a) == key_a      # warm again
        _, m5, _ = deltas()
        assert m5 - m4 == 1

    def test_evicted_epoch_verdict_and_blame_bit_identical(self):
        """The satellite's parity leg: a commit verified WARM (cached
        kernels) and the same commit verified after EVICTION (uncached
        fallback) must produce byte-identical error strings — same
        verdicts, same blamed lane."""
        epoch_cache.reset(depth=4)
        vset, by_pub = _vset_with_sks(90)
        bad_i = 31
        commit, bid = _commit_signed_by(vset, by_pub, height=7, bad=(bad_i,))
        dec = Commit.decode(commit.encode())
        with pytest.raises(ValueError) as cold_err:
            validation.verify_commit(CHAIN_ID, vset, bid, 7, dec)  # cold
        with pytest.raises(ValueError) as warm_err:
            validation.verify_commit(CHAIN_ID, vset, bid, 7, dec)  # cached
        epoch_cache.cache().clear()  # evict everything mid-stream
        with pytest.raises(ValueError) as evicted_err:
            validation.verify_commit(CHAIN_ID, vset, bid, 7, dec)  # fallback
        assert str(cold_err.value) == str(warm_err.value) == str(
            evicted_err.value
        )
        assert "wrong signature (#" in str(evicted_err.value)
        # a GOOD commit from the same (re-registered) epoch verifies warm
        good, gbid = _commit_signed_by(vset, by_pub, height=8)
        gdec = Commit.decode(good.encode())
        validation.verify_commit(CHAIN_ID, vset, gbid, 8, gdec)
        validation.verify_commit(CHAIN_ID, vset, gbid, 8, gdec)


# ---------------------------------------------------------------------------
# Sharded cached path (needs jax.shard_map — absent on this container's
# jax; runs on images that have it, e.g. the TPU driver)
# ---------------------------------------------------------------------------


class TestShardedCached:
    def test_sharded_cached_matches_uncached(self):
        import jax

        try:
            from jax import shard_map  # noqa: F401
        except ImportError:
            pytest.skip("jax.shard_map unavailable on this jax version")
        from tendermint_tpu.ops import sharded

        n_dev = min(8, len(jax.devices()))
        mesh = sharded.make_mesh(n_dev)
        n = 2 * n_dev
        sks = [ed25519.gen_priv_key(bytes([i + 1]) * 32) for i in range(n)]
        ents = [
            (sk.pub_key().bytes(), b"shard-%d" % i, sk.sign(b"shard-%d" % i))
            for i, sk in enumerate(sks)
        ]
        ents[3] = (ents[3][0], ents[3][1], b"\x01" * 64)
        powers = [100 + i for i in range(n)]
        blk = EntryBlock.from_entries(ents)
        v_u, t_u, a_u = sharded.verify_commit_sharded(
            blk, powers, mesh, bucket=n
        )
        # warm the epoch and re-run: verify_commit_sharded auto-dispatches
        # to the cached variant (replicated table, per-shard gather)
        key = b"E" * 32
        epoch_cache.cache().note(key, blk.pub.copy())
        assert epoch_cache.cache().note(key, blk.pub.copy()) is not None
        blk.val_idx = np.arange(n, dtype=np.int32)
        blk.epoch_key = key
        assert epoch_cache.lookup(blk) is not None
        v_c, t_c, a_c = sharded.verify_commit_sharded(
            blk, powers, mesh, bucket=n
        )
        assert np.array_equal(v_u, v_c)
        assert t_u == t_c and a_u == a_c
        assert not v_c[3] and not a_c


# ---------------------------------------------------------------------------
# Pallas cached kernels (interpret mode: minutes per grid — slow-marked;
# the TPU driver image runs them compiled)
# ---------------------------------------------------------------------------


@pytest.mark.slow
class TestCachedPallasInterpret:
    def _blk(self, n):
        sks = [ed25519.gen_priv_key(bytes([i + 1]) * 32) for i in range(n)]
        ents = [
            (sk.pub_key().bytes(), b"m%d" % i, sk.sign(b"m%d" % i))
            for i, sk in enumerate(sks)
        ]
        ents[min(3, n - 1)] = (ents[min(3, n - 1)][0], b"m", b"\x01" * 64)
        blk = EntryBlock.from_entries(ents)
        ep = epoch_cache.EpochEntry(b"k" * 32, blk.pub.copy())
        blk.val_idx = np.arange(n, dtype=np.int32)
        blk.epoch_key = b"k" * 32
        return blk, ep

    def test_rlc_cached_parity(self, monkeypatch):
        from tendermint_tpu.ops import pallas_rlc as pr

        monkeypatch.setenv("TM_TPU_RLC_SEED", "7")
        monkeypatch.setenv("TM_TPU_RLC_SEED_UNSAFE", "1")
        blk, ep = self._blk(6)
        bucket, g, b = pr.plan_bucket(len(blk))
        lanes_u = pr.verify_rlc_compact(
            *pr.prepare_rlc(blk, bucket), block=b, interpret=True
        )
        dev = pr.rlc_cached_fn(ep, g, b, True)(
            *pr.prepare_rlc_cached(blk, bucket, ep)
        )
        lanes_c = np.asarray(dev)[0].astype(bool)
        assert np.array_equal(lanes_u, lanes_c)
        assert np.array_equal(
            pr.expand_lanes(lanes_u, blk), pr.expand_lanes(lanes_c, blk)
        )

    def test_compact_cached_parity(self):
        from tendermint_tpu.ops import pallas_verify as pv

        blk, ep = self._blk(8)
        res_u = pv.verify_compact(
            *pv.prepare_compact(blk, 8), block=8, interpret=True
        )
        res_c = pv.verify_compact_cached(
            pv.prepare_compact_cached(blk, 8, ep), ep, block=8,
            interpret=True,
        )
        assert np.array_equal(res_u, res_c)
        assert not res_c.all()
