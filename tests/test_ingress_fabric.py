"""Ingress-fabric unit suite (ISSUE 17): controller + engine mechanics.

Everything here runs against fake verifiers — no jax, no crypto wheel,
no pipeline — so the fabric's window policy, knob resolution, QoS
routing, poisoned-window isolation and stepped semantics are pinned in
a plain interpreter. The ADAPTIVE controller's three behaviors are each
pinned explicitly:

* deepen-under-flood — FULL flushes at target grow batch ×2 / window
  ×1.5 up to 8× the base;
* shrink-when-idle — sparse timer flushes halve both back down to the
  base batch / quarter window;
* deadline-aware flush — the effective window is clamped to
  budget − 2×(service EWMA) so flush + device service fit the lane's
  p99 budget.

Cross-lane parity rides along: all four production lane names register
on one private engine and expose the same stats contract.
"""

import importlib.util
import os
import sys
import threading
import time
import warnings
from concurrent.futures import Future

import pytest

if importlib.util.find_spec("cryptography") is None and not os.environ.get(
    "TM_TPU_PUREPY_CRYPTO"
):
    # the fabric itself is crypto-free, but importing tendermint_tpu.ops
    # pulls the crypto chain; the isolated runner
    # (test_ingress_fabric_isolated.py) re-runs this suite under
    # TM_TPU_PUREPY_CRYPTO=1 so tier-1 keeps the coverage
    pytest.skip(
        "cryptography wheel absent; runs via test_ingress_fabric_isolated",
        allow_module_level=True,
    )

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO_ROOT not in sys.path:
    sys.path.insert(0, REPO_ROOT)

from tendermint_tpu.ops import ingress  # noqa: E402
from tendermint_tpu.ops.entry_block import EntryBlock  # noqa: E402


def wait_until(cond, timeout=5.0, msg="condition"):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if cond():
            return
        time.sleep(0.002)
    raise AssertionError(f"timed out waiting for {msg}")


def entry(i: int):
    return (bytes([i % 256]) * 32, b"msg-%d" % i, bytes([i % 256]) * 64)


class FakeVerifier:
    """Records submissions; resolves futures per `mode`:
    - "ok": every signature verifies
    - "manual": caller resolves via self.futures
    - "poison_first": first submit raises DispatchError-shaped failure
      post-submit, later submits verify
    - "raise": submit() itself raises (pre-submit failure)
    """

    def __init__(self, mode="ok"):
        self.mode = mode
        self.calls = []          # (n, flow, priority)
        self.futures = []
        self._n = 0

    def submit(self, block, flow=None, priority=None):
        self._n += 1
        if self.mode == "raise":
            raise RuntimeError("verifier rejected submit")
        self.calls.append((len(block), flow, priority))
        fut = Future()
        self.futures.append(fut)
        if self.mode == "ok":
            fut.set_result([True] * len(block))
        elif self.mode == "poison_first" and self._n == 1:
            fut.set_exception(RuntimeError("DispatchError: lost slot"))
        elif self.mode == "poison_first":
            fut.set_result([True] * len(block))
        return fut


class NarrowVerifier:
    """The duck-typed test-double shape the light suite uses: no
    priority parameter at all."""

    def __init__(self):
        self.calls = 0

    def submit(self, block, flow=None):
        self.calls += 1
        fut = Future()
        fut.set_result([True] * len(block))
        return fut


class Sink:
    """Collects deliver() callbacks."""

    def __init__(self):
        self.windows = []        # (items, verdicts, err)
        self.mtx = threading.Lock()

    def __call__(self, items, verdicts, err):
        for i, it in enumerate(items):      # deliver() owns item futures
            if it.future is not None:
                if err is not None:
                    it.future.set_exception(err)
                else:
                    it.future.set_result(verdicts[i])
        with self.mtx:
            self.windows.append(([it.item for it in items], verdicts, err))

    def count(self):
        with self.mtx:
            return sum(len(w[0]) for w in self.windows)


def make_lane(engine, sink, verifier=None, **kw):
    defaults = dict(
        name="test", priority=ingress.PRIORITY_INGRESS, batch=4,
        window_ms=60_000.0, verifier=verifier or FakeVerifier(),
        entries_fn=lambda i: entry(i), deliver=sink,
        host_fn=lambda items: [True] * len(items),
    )
    defaults.update(kw)
    return engine.register(ingress.LaneSpec(**defaults))


@pytest.fixture
def engine():
    eng = ingress.IngressEngine()
    yield eng
    eng.close(timeout=2.0)


# ---------------------------------------------------------------------------
# the adaptive controller


class TestAdaptiveWindow:
    def test_deepen_under_flood(self):
        c = ingress.AdaptiveWindow(batch=64, window_ms=2.0)
        for _ in range(16):
            c.on_flush(c.batch_target(), ingress.CAUSE_FULL)
        assert c.batch_target() == 64 * 8          # capped at 8x base
        assert c.window_ms == pytest.approx(2.0 * 8)
        assert c.grows >= 3                        # 64->128->256->512

    def test_partial_full_does_not_grow(self):
        c = ingress.AdaptiveWindow(batch=64, window_ms=2.0)
        c.on_flush(10, ingress.CAUSE_FULL)
        assert c.batch_target() == 64 and c.grows == 0

    def test_shrink_when_idle(self):
        c = ingress.AdaptiveWindow(batch=64, window_ms=2.0)
        for _ in range(8):
            c.on_flush(c.batch_target(), ingress.CAUSE_FULL)
        assert c.batch_target() > 64
        for _ in range(32):
            c.on_flush(1, ingress.CAUSE_TIMER)
        assert c.batch_target() == 64              # back to base
        assert c.window_ms == pytest.approx(2.0 / 4)   # quarter window
        assert c.shrinks >= 3

    def test_busy_timer_flush_does_not_shrink(self):
        c = ingress.AdaptiveWindow(batch=64, window_ms=2.0)
        c.on_flush(40, ingress.CAUSE_TIMER)        # > 1/4 of target
        assert c.shrinks == 0 and c.window_ms == 2.0

    def test_manual_stepped_close_never_adapt(self):
        c = ingress.AdaptiveWindow(batch=64, window_ms=2.0)
        for cause in (ingress.CAUSE_MANUAL, ingress.CAUSE_STEPPED,
                      ingress.CAUSE_CLOSE):
            c.on_flush(10_000, cause)
            c.on_flush(1, cause)
        assert c.grows == 0 and c.shrinks == 0
        assert c.batch_target() == 64 and c.window_ms == 2.0

    def test_frozen_when_not_adaptive(self):
        c = ingress.AdaptiveWindow(batch=64, window_ms=2.0, adaptive=False)
        c.on_flush(64, ingress.CAUSE_FULL)
        c.on_flush(1, ingress.CAUSE_TIMER)
        assert c.batch_target() == 64 and c.window_ms == 2.0

    def test_deadline_bounds_effective_window(self):
        c = ingress.AdaptiveWindow(batch=64, window_ms=4.0, budget_ms=5.0)
        assert c.effective_window_ms() == pytest.approx(4.0)
        assert not c.deadline_bound
        c.note_service(2.0)                        # EWMA seeds at 2ms
        # budget 5 - SAFETY(2) * 2ms = 1ms < base window
        assert c.effective_window_ms() == pytest.approx(1.0)
        assert c.deadline_bound

    def test_deadline_floor_is_min_window(self):
        c = ingress.AdaptiveWindow(batch=64, window_ms=4.0, budget_ms=5.0)
        c.note_service(100.0)                      # budget hopeless
        assert c.effective_window_ms() == pytest.approx(4.0 / 4)

    def test_frozen_lane_keeps_deadline_bound(self):
        """SLO awareness is not optional — only adaptivity is."""
        c = ingress.AdaptiveWindow(batch=64, window_ms=4.0, budget_ms=5.0,
                                   adaptive=False)
        c.note_service(2.0)
        assert c.effective_window_ms() == pytest.approx(1.0)

    def test_service_ewma(self):
        c = ingress.AdaptiveWindow(batch=64, window_ms=4.0)
        c.note_service(10.0)
        assert c.service_ewma_ms == pytest.approx(10.0)
        c.note_service(0.0)
        assert c.service_ewma_ms == pytest.approx(10.0 * 0.7)
        c.note_service(-1.0)                       # ignored
        assert c.service_ewma_ms == pytest.approx(10.0 * 0.7)

    def test_deadline_flush_counter(self):
        c = ingress.AdaptiveWindow(batch=64, window_ms=4.0, budget_ms=5.0)
        c.on_flush(1, ingress.CAUSE_DEADLINE)
        assert c.deadline_flushes == 1
        # one idle flush is within hysteresis patience — no shrink yet
        assert c.shrinks == 0 and c.window_ms == pytest.approx(4.0)
        # sustained idle deadline flushes DO shrink: deadline pressure
        # with near-empty windows means the window is too deep
        c.on_flush(1, ingress.CAUSE_DEADLINE)
        assert c.deadline_flushes == 2
        assert c.shrinks == 1 and c.window_ms == pytest.approx(2.0)

    def test_shrink_hysteresis_survives_jitter(self):
        """A lone jitter-thinned timer flush mid-flood must not collapse
        the window the next burst needs — the full flush resets the
        idle streak before it reaches SHRINK_PATIENCE."""
        c = ingress.AdaptiveWindow(batch=64, window_ms=2.0)
        c.on_flush(64, ingress.CAUSE_FULL)         # grow to 128
        grown = c.batch_target()
        assert grown > 64
        for _ in range(8):
            c.on_flush(1, ingress.CAUSE_TIMER)     # jitter: streak -> 1
            c.on_flush(c.batch_target(), ingress.CAUSE_FULL)  # flood resumes
        assert c.shrinks == 0
        assert c.batch_target() >= grown
        # a busy (non-idle) timer flush also resets the streak
        c2 = ingress.AdaptiveWindow(batch=64, window_ms=2.0)
        c2.on_flush(64, ingress.CAUSE_FULL)
        c2.on_flush(1, ingress.CAUSE_TIMER)
        c2.on_flush(40, ingress.CAUSE_TIMER)       # > 1/4 target: busy
        c2.on_flush(1, ingress.CAUSE_TIMER)
        assert c2.shrinks == 0


# ---------------------------------------------------------------------------
# knob resolution


class TestResolveLaneConfig:
    def setup_method(self):
        ingress._warned_legacy.clear()

    def test_lane_defaults(self, monkeypatch):
        for k in list(os.environ):
            if k.startswith("TM_TPU_INGRESS"):
                monkeypatch.delenv(k)
        cfg = ingress.resolve_lane_config("votes")
        assert (cfg.batch, cfg.window_ms) == (128, 2.0)
        assert cfg.budget_ms == 5.0                # the paper's hot-path p99
        assert cfg.adaptive

    def test_explicit_args_pin_determinism(self):
        cfg = ingress.resolve_lane_config("votes", batch=32, window_ms=1.0)
        assert (cfg.batch, cfg.window_ms) == (32, 1.0)
        assert not cfg.adaptive
        # default SLO budget only engages with adaptivity: a pinned
        # caller gets EXACTLY the flush timing it pinned
        assert cfg.budget_ms is None

    def test_lane_keyed_env(self, monkeypatch):
        monkeypatch.setenv("TM_TPU_INGRESS_VOTES_BATCH", "99")
        monkeypatch.setenv("TM_TPU_INGRESS_VOTES_WINDOW_MS", "7.5")
        cfg = ingress.resolve_lane_config("votes")
        assert (cfg.batch, cfg.window_ms) == (99, 7.5)
        assert cfg.adaptive                        # env knobs stay adaptive

    def test_legacy_env_honored_with_warning(self, monkeypatch):
        monkeypatch.setenv("TM_TPU_VOTE_BATCH", "48")
        with warnings.catch_warnings(record=True) as w:
            warnings.simplefilter("always")
            cfg = ingress.resolve_lane_config(
                "votes", legacy_batch="TM_TPU_VOTE_BATCH")
        assert cfg.batch == 48
        assert any(issubclass(x.category, DeprecationWarning) for x in w)

    def test_new_name_wins_over_legacy(self, monkeypatch):
        monkeypatch.setenv("TM_TPU_VOTE_BATCH", "48")
        monkeypatch.setenv("TM_TPU_INGRESS_VOTES_BATCH", "96")
        with warnings.catch_warnings(record=True) as w:
            warnings.simplefilter("always")
            cfg = ingress.resolve_lane_config(
                "votes", legacy_batch="TM_TPU_VOTE_BATCH")
        assert cfg.batch == 96
        assert not w                               # no deprecation fired

    def test_adaptive_env_overrides(self, monkeypatch):
        monkeypatch.setenv("TM_TPU_INGRESS_VOTES_ADAPTIVE", "1")
        cfg = ingress.resolve_lane_config("votes", batch=32, window_ms=1.0)
        assert cfg.adaptive
        monkeypatch.setenv("TM_TPU_INGRESS_VOTES_ADAPTIVE", "0")
        cfg = ingress.resolve_lane_config("votes")
        assert not cfg.adaptive

    def test_global_adaptive_env(self, monkeypatch):
        monkeypatch.setenv("TM_TPU_INGRESS_ADAPTIVE", "1")
        cfg = ingress.resolve_lane_config("votes", batch=32, window_ms=1.0)
        assert cfg.adaptive

    def test_budget_env_always_applies(self, monkeypatch):
        monkeypatch.setenv("TM_TPU_INGRESS_VOTES_BUDGET_MS", "7")
        cfg = ingress.resolve_lane_config("votes", batch=32, window_ms=1.0)
        assert cfg.budget_ms == 7.0                # even though pinned


# ---------------------------------------------------------------------------
# QoS tiers mirror the pipeline's


class TestPriorityTiers:
    def test_constants_match_pipeline(self):
        pl = pytest.importorskip("tendermint_tpu.ops.pipeline")
        assert ingress.PRIORITY_CONSENSUS == pl.PRIORITY_CONSENSUS
        assert ingress.PRIORITY_REPLAY == pl.PRIORITY_REPLAY
        assert ingress.PRIORITY_INGRESS == pl.PRIORITY_INGRESS


# ---------------------------------------------------------------------------
# engine mechanics (fake verifier — no pipeline, no jax)


class TestEngineMechanics:
    def test_full_flush_delivers_at_lane_priority(self, engine):
        sink, v = Sink(), FakeVerifier()
        lane = make_lane(engine, sink, verifier=v)
        futs = [lane.submit(i, want_future=True) for i in range(4)]
        wait_until(lambda: sink.count() == 4, msg="full-window delivery")
        assert v.calls == [(4, None, ingress.PRIORITY_INGRESS)]
        assert all(f.done() for f in futs)
        st = lane.stats()
        assert st["batches"] == 1 and st["sigs"] == 4

    def test_consensus_tier_omits_priority_kwarg(self, engine):
        sink, v = Sink(), NarrowVerifier()
        lane = make_lane(engine, sink, verifier=v,
                         priority=ingress.PRIORITY_CONSENSUS)
        block = EntryBlock.from_entries([entry(i) for i in range(3)])
        fut = lane.submit_block(block)
        assert fut.result(timeout=1) == [True, True, True]
        assert v.calls == 1
        assert lane.stats()["blocks"] == 1 and lane.stats()["sigs"] == 3

    def test_timer_flush(self, engine):
        sink = Sink()
        lane = make_lane(engine, sink, window_ms=10.0)
        lane.submit(1)
        wait_until(lambda: sink.count() == 1, msg="timer flush")
        assert lane.stats()["queue_depth"] == 0

    def test_flush_now_and_stale_force(self, engine):
        sink = Sink()
        lane = make_lane(engine, sink)               # 60s window
        lane.submit(1)
        lane.flush_now()
        wait_until(lambda: sink.count() == 1, msg="manual flush")
        # flush_now on an empty lane leaves the force latched: the NEXT
        # submit flushes immediately (the pre-fabric full-event shape)
        lane.flush_now()
        lane.submit(2)
        wait_until(lambda: sink.count() == 2, msg="stale-force flush")

    def test_window_dedup(self, engine):
        sink = Sink()
        lane = make_lane(engine, sink, batch=64)
        assert lane.submit(1, dedup_key="a") is None   # no future asked
        assert lane.submit(1, dedup_key="a") is None   # dropped
        assert lane.stats()["window_dups"] == 1
        lane.flush_now()
        wait_until(lambda: sink.count() == 1, msg="flush")
        lane.submit(1, dedup_key="a")                  # re-enters post-flush
        lane.flush_now()
        wait_until(lambda: sink.count() == 2, msg="re-entry")
        assert lane.stats()["window_dups"] == 1

    def test_poisoned_window_is_isolated(self, engine):
        sink = Sink()
        lane = make_lane(engine, sink, verifier=FakeVerifier("poison_first"))
        for i in range(4):
            lane.submit(i)
        wait_until(lambda: sink.count() == 4, msg="poisoned window")
        for i in range(4, 8):
            lane.submit(i)
        wait_until(lambda: sink.count() == 8, msg="clean window")
        with sink.mtx:
            (w1, w2) = sink.windows
        assert w1[1] is None and isinstance(w1[2], RuntimeError)
        assert w2[1] == [True] * 4 and w2[2] is None
        assert lane.stats()["dispatch_errors"] == 1

    def test_presubmit_error_to_host(self, engine):
        """submit_error_to_host lanes (votes) host-verify the window a
        pre-submit failure orphaned — no dispatch_errors, verdicts real."""
        sink = Sink()
        lane = make_lane(engine, sink, verifier=FakeVerifier("raise"),
                         submit_error_to_host=True)
        for i in range(4):
            lane.submit(i)
        wait_until(lambda: sink.count() == 4, msg="host fallback")
        with sink.mtx:
            (items, verdicts, err) = sink.windows[0]
        assert verdicts == [True] * 4 and err is None
        st = lane.stats()
        assert st["sync_fallbacks"] >= 1 and st["dispatch_errors"] == 0

    def test_presubmit_error_to_futures(self, engine):
        """Lanes without the host contract (mempool) deliver the error
        to exactly that window's futures."""
        sink = Sink()
        lane = make_lane(engine, sink, verifier=FakeVerifier("raise"))
        futs = [lane.submit(i, want_future=False) for i in range(4)]
        del futs
        wait_until(lambda: sink.count() == 4, msg="error delivery")
        with sink.mtx:
            (_, verdicts, err) = sink.windows[0]
        assert verdicts is None and isinstance(err, RuntimeError)
        assert lane.stats()["dispatch_errors"] == 0    # pre-submit, not poison

    def test_device_threshold_host_fallback(self, engine, monkeypatch):
        monkeypatch.delenv("TM_TPU_FORCE_DEVICE", raising=False)
        sink = Sink()
        v = FakeVerifier()
        lane = make_lane(engine, sink, verifier=v, device_threshold=16)
        for i in range(4):
            lane.submit(i)
        lane.flush_now()
        wait_until(lambda: sink.count() == 4, msg="sub-threshold host")
        assert v.calls == []                       # never reached the device
        assert lane.stats()["sync_fallbacks"] == 1

    def test_route_fn_splits_host_lane(self, engine):
        sink, v = Sink(), FakeVerifier()
        host_seen = []

        def host_fn(items):
            host_seen.extend(items)
            return [True] * len(items)

        lane = make_lane(engine, sink, verifier=v,
                         route_fn=lambda i: i % 2 == 0, host_fn=host_fn)
        for i in range(8):
            lane.submit(i)
        lane.flush_now()
        wait_until(lambda: sink.count() == 8, msg="split delivery")
        assert sorted(host_seen) == [1, 3, 5, 7]
        assert v.calls and v.calls[0][0] == 4
        st = lane.stats()
        assert st["host_lane_sigs"] == 4
        assert st["sync_fallbacks"] == 0           # routed, not fallen back

    def test_stepped_lane_never_scheduler_flushed(self, engine):
        sink = Sink()
        lane = make_lane(engine, sink, stepped=True, window_ms=0.0)
        lane.submit(1)
        lane.submit(2)
        time.sleep(0.15)                           # scheduler ticks ~20x
        assert sink.count() == 0                   # nothing moved
        assert lane.flush_pending() is True        # the ONLY flush point
        assert sink.count() == 2                   # inline, on this thread
        assert lane.flush_pending() is False
        assert lane.stats()["sync_fallbacks"] == 1

    def test_completer_thread_delivery(self, engine):
        sink = Sink()
        lane = make_lane(engine, sink, use_completer=True)
        threads = []
        orig = sink.__call__

        def recording(items, verdicts, err):
            threads.append(threading.current_thread().name)
            orig(items, verdicts, err)

        lane.spec.deliver = recording
        for i in range(4):
            lane.submit(i)
        wait_until(lambda: sink.count() == 4, msg="completer delivery")
        assert threads == ["ingress-fabric-complete"]
        wait_until(lambda: lane._inflight == 0, msg="inflight drain")

    def test_close_drains_and_rejects(self, engine):
        sink = Sink()
        lane = make_lane(engine, sink, closed_msg="lane shut")
        lane.submit(1)
        lane.close(timeout=2.0)
        assert sink.count() == 1                   # final drain flushed it
        with pytest.raises(RuntimeError, match="lane shut"):
            lane.submit(2)
        assert lane not in engine.lanes()

    def test_keyed_windows_flush_separately(self, engine):
        """full_by_window (votes): the size trigger counts the keyed
        window, and each keyed window becomes its own submission."""
        sink, v = Sink(), FakeVerifier()
        lane = make_lane(engine, sink, verifier=v, batch=4,
                         full_by_window=True)
        for i in range(3):
            lane.submit(i, key="h10")
        for i in range(3):
            lane.submit(10 + i, key="h11")         # 6 total, no window full
        time.sleep(0.05)
        assert sink.count() == 0
        lane.submit(3, key="h10")                  # h10 hits 4 -> flush all
        wait_until(lambda: sink.count() == 7, msg="keyed flush")
        assert sorted(c[0] for c in v.calls) == [3, 4]
        assert lane.stats()["batches"] == 2


# ---------------------------------------------------------------------------
# the replay range fuse


class TestBlockFuser:
    def test_packs_to_cap_and_reports_spans(self, engine):
        sink, v = Sink(), FakeVerifier()
        lane = make_lane(engine, sink, verifier=v,
                         priority=ingress.PRIORITY_REPLAY)
        chunks = []
        fuser = ingress.BlockFuser(lane, cap=10,
                                   on_chunk=lambda f, p: chunks.append(p),
                                   flow=42)
        for h in range(3):                         # 4 + 4 + 4 sigs, cap 10
            fuser.add(h, EntryBlock.from_entries(
                [entry(4 * h + i) for i in range(4)]))
        fuser.flush()
        assert [c[0] for c in v.calls] == [8, 4]   # fused pair + tail
        assert all(c[1] == 42 for c in v.calls)
        assert all(c[2] == ingress.PRIORITY_REPLAY for c in v.calls)
        assert chunks == [[(0, 0, 4), (1, 4, 4)], [(2, 0, 4)]]
        assert lane.stats()["blocks"] == 2
        assert lane.stats()["sigs"] == 12

    def test_flush_on_empty_is_noop(self, engine):
        sink, v = Sink(), FakeVerifier()
        lane = make_lane(engine, sink, verifier=v)
        fuser = ingress.BlockFuser(lane, cap=10, on_chunk=lambda f, p: None)
        fuser.flush()
        assert v.calls == []


# ---------------------------------------------------------------------------
# cross-lane parity: the four production lanes share one stats contract


class TestCrossLaneParity:
    LANES = ("mempool", "votes", "light", "replay")

    def test_four_lanes_one_engine_one_contract(self, engine):
        sinks = {}
        for name in self.LANES:
            cfg = ingress.LANE_DEFAULTS[name]
            sinks[name] = Sink()
            make_lane(engine, sinks[name], name=name,
                      batch=int(cfg["batch"]), window_ms=0.0,
                      stepped=name in ("light", "replay"))
        assert sorted(engine.stats()) == sorted(self.LANES)
        keys = None
        for name, st in engine.stats().items():
            if keys is None:
                keys = set(st)
            assert set(st) == keys, f"{name} diverges from the contract"
        for k in ("queue_depth", "batches", "sigs", "sync_fallbacks",
                  "dispatch_errors", "batch_wait_ms_avg", "max_batch",
                  "window_ms", "window_grows", "window_shrinks",
                  "deadline_flushes", "adaptive", "stepped"):
            assert k in keys

    def test_one_scheduler_for_all_lanes(self, engine):
        """The point of the fabric: N lanes, ONE flush thread."""
        sinks = [Sink() for _ in range(4)]
        lanes = [make_lane(engine, s, name=f"lane{i}", window_ms=5.0)
                 for i, s in enumerate(sinks)]
        before = {t.name for t in threading.enumerate()}
        assert sum("ingress-fabric-flush" in n for n in before) == 1
        for lane in lanes:
            lane.submit(1)
        for s in sinks:
            wait_until(lambda s=s: s.count() == 1, msg="per-lane flush")
        after = {t.name for t in threading.enumerate()}
        assert sum("ingress-fabric-flush" in n for n in after) == 1
