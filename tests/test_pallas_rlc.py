"""RLC fast-accept kernel (ops.pallas_rlc): differential conformance
against the ZIP-215 oracle, lane-reject fallback blame, scalar-prep
parity (native C vs pure Python), and pipeline dispatch wiring.

Runs the real 3-kernel RLC pipeline in interpret mode at tiny buckets —
the same traced program Mosaic compiles on TPU (hardware-validated at
bucket 10240 in round 5; see PERF_r05.md).
"""

import os

import numpy as np
import pytest

pytest.importorskip("jax")

from tendermint_tpu.crypto import _edwards as E  # noqa: E402
from tendermint_tpu.crypto import ed25519  # noqa: E402
from tendermint_tpu.ops import backend, pallas_rlc as pr  # noqa: E402
from tests.test_ops import _edge_entries  # noqa: E402


def _oracle(entries):
    return [E.verify_zip215(p, m, s) for p, m, s in entries]


@pytest.fixture(autouse=True)
def _deterministic_z(monkeypatch):
    monkeypatch.setenv("TM_TPU_RLC_SEED", "1234")


def _sign_batch(n, tamper=()):
    entries = []
    for i in range(n):
        sk = ed25519.gen_priv_key(bytes([i + 1]) * 32)
        m = b"rlc-%d" % i
        sig = sk.sign(m)
        if i in tamper:
            sig = sig[:-1] + bytes([sig[-1] ^ 1])
        entries.append((sk.pub_key().bytes(), m, sig))
    return entries


class TestRlcKernel:
    def test_valid_batch_with_straddling_padding(self):
        # 14 live sigs in a 16-sig bucket: one lane straddles live/padding
        entries = _sign_batch(14)
        res = pr.verify_batch_rlc(entries, block=4, interpret=True)
        assert res.tolist() == [True] * 14

    def test_lane_reject_falls_back_per_sig(self):
        entries = _sign_batch(14, tamper={6})
        res = pr.verify_batch_rlc(entries, block=4, interpret=True)
        assert res.tolist() == [i != 6 for i in range(14)]

    def test_edge_vectors_bit_exact(self):
        """The ZIP-215 edge battery (small-order points, non-canonical
        encodings, s >= L, corruptions) through the RLC path must match
        the oracle per signature — valid lanes accept directly, mixed
        lanes reject and the host fallback restores exact per-sig
        semantics."""
        entries = _edge_entries()
        res = pr.verify_batch_rlc(entries, block=4, interpret=True)
        assert res.tolist() == _oracle(entries)

    def test_all_valid_small_order_lane_fast_accepts(self):
        """A lane of entirely-valid small-order signatures must accept
        WITHOUT the fallback: [8]e_j = O for each, so the combination
        [8]acc = O identically (torsion cancels under the cofactor)."""
        ident_pk = (1).to_bytes(32, "little")
        entries = [(ident_pk, b"m%d" % i, bytes(64)) for i in range(pr.M)]
        args = pr.prepare_rlc(entries, 4 * pr.M)  # shape shared with above
        lanes = pr.verify_rlc_compact(*args, block=4, interpret=True)
        assert lanes.tolist() == [True] * 4  # lane 0 small-order, 1-3 padding

    def test_scalar_prep_native_matches_python(self):
        entries = _sign_batch(8)
        from tendermint_tpu.ops.backend import _challenges, _pack_rows
        from tendermint_tpu.native import load as _load_native

        native = _load_native()
        if native is None:
            pytest.skip("native module unavailable")
        pub, r_enc, s_enc = _pack_rows(entries, 8)
        ks = _challenges(r_enc, pub, [m for _, m, _ in entries])
        k_enc = np.frombuffer(ks, dtype=np.uint8).reshape(8, 32)
        z = pr._gen_z(8)
        a = native.ed25519_rlc_scalars(
            s_enc.tobytes(), k_enc.tobytes(), z.tobytes(), pr.M
        )
        b = pr._rlc_scalars_py(s_enc.tobytes(), k_enc.tobytes(), z.tobytes(), pr.M)
        assert a == b

    def test_seeded_z_deterministic(self):
        assert (pr._gen_z(8) == pr._gen_z(8)).all()
        # slot-0 coefficients are fixed at 1 (ignored entries stay zero)
        os.environ.pop("TM_TPU_RLC_SEED", None)
        z1, z2 = pr._gen_z(8), pr._gen_z(8)
        assert (z1[:, 16:] == 0).all()
        assert (z1 != z2).any(), "unseeded z must be random per batch"

    def test_backend_dispatch_uses_rlc(self, monkeypatch):
        """TM_TPU_PALLAS=1 + TM_TPU_RLC=1 routes verify_batch through the
        RLC fast-accept path on the CPU interpret backend."""
        monkeypatch.setenv("TM_TPU_PALLAS", "1")
        monkeypatch.setenv("TM_TPU_RLC", "1")
        # tiny lane blocks so interpret mode stays fast (env var is read
        # at module import; patch the module attribute)
        monkeypatch.setattr(pr, "BLOCK_LANES", 4)
        backend._use_pallas.cache_clear()
        backend._use_rlc.cache_clear()
        try:
            entries = _sign_batch(10, tamper={3})
            res = backend.verify_batch(entries)
            assert res.tolist() == [i != 3 for i in range(10)]
        finally:
            backend._use_pallas.cache_clear()
            backend._use_rlc.cache_clear()

    def test_pipeline_dispatch_rlc_lane_expansion(self, monkeypatch):
        """The shared async pipeline expands RLC lane verdicts back to
        per-signature verdicts (with fallback blame on reject lanes)."""
        monkeypatch.setenv("TM_TPU_PALLAS", "1")
        monkeypatch.setenv("TM_TPU_RLC", "1")
        backend._use_pallas.cache_clear()
        backend._use_rlc.cache_clear()
        monkeypatch.setattr(pr, "BLOCK_LANES", 4)
        from tendermint_tpu.ops import pallas_verify as pv
        monkeypatch.setattr(pv, "BLOCK", 16)  # _pallas_bucket granularity
        from tendermint_tpu.ops.pipeline import AsyncBatchVerifier

        v = AsyncBatchVerifier()
        try:
            entries = _sign_batch(12, tamper={5})
            res = v.submit(entries).result(timeout=600)
            assert res.tolist() == [i != 5 for i in range(12)]
        finally:
            v.close()
            backend._use_pallas.cache_clear()
            backend._use_rlc.cache_clear()


class TestShardedRlc:
    def test_sharded_rlc_matches_host_oracle(self):
        """The flagship RLC kernel under shard_map over the 8-device
        virtual mesh: lane-sharded dp, psum voting-power tally of
        accepted lanes, host fallback restores per-sig blame and adds
        the rejected lane's valid power back — totals must match the
        per-sig oracle exactly."""
        import jax

        from tendermint_tpu.crypto import _edwards as E
        from tendermint_tpu.ops import sharded

        mesh = sharded.make_mesh(min(8, len(jax.devices())))
        entries = _sign_batch(22, tamper={9})
        powers = [100 + i for i in range(22)]
        valid, tallied, all_valid = sharded.verify_commit_sharded_rlc(
            entries, powers, mesh
        )
        expect = [E.verify_zip215(p, m, s) for p, m, s in entries]
        assert valid.tolist() == expect == [i != 9 for i in range(22)]
        assert not all_valid
        assert tallied == sum(p for i, p in enumerate(powers) if i != 9)

    def test_sharded_rlc_all_valid(self):
        import jax

        from tendermint_tpu.ops import sharded

        mesh = sharded.make_mesh(min(8, len(jax.devices())))
        entries = _sign_batch(16)
        powers = [7] * 16
        valid, tallied, all_valid = sharded.verify_commit_sharded_rlc(
            entries, powers, mesh
        )
        assert valid.all() and all_valid and tallied == 7 * 16
