"""The BLS12-381 aggregation lane (ISSUE 20): oracle, wire type, and
commit-seam integration.

Two layers, same pattern as test_secp_lane.py:

- the pure-Python BLS oracle (crypto/bls12381.py — stdlib-only big-int
  math) and the AggregatedCommit wire type import WITHOUT the
  cryptography wheel, so their unit tests run IN PROCESS in the main
  tier-1 run;
- the validation/kernel seam (types/validation.py pulls the crypto
  package) and the `tools/prep_bench.py --bls` fused-launch +
  blame-parity gate run in SUBPROCESSES with TM_TPU_PUREPY_CRYPTO=1,
  which must never leak into the main pytest process.
"""

import os
import subprocess
import sys

import pytest

from tendermint_tpu.crypto import bls12381 as bls
from tendermint_tpu.libs.bits import BitArray

try:
    # types/__init__ reaches validation -> crypto.batch -> the
    # cryptography wheel; everything below the oracle tests needs it
    from tendermint_tpu.types.block import (
        AggregatedCommit,
        BlockID,
        PartSetHeader,
    )

    _HAVE_CRYPTO = True
except ModuleNotFoundError:
    # No cryptography wheel in this container; the subprocess runner
    # below re-runs this module with TM_TPU_PUREPY_CRYPTO=1 instead.
    _HAVE_CRYPTO = False

needs_crypto = pytest.mark.skipif(
    not _HAVE_CRYPTO,
    reason="crypto backend unavailable (runs via the purepy subprocess "
    "runner)",
)


def _repo_root():
    return os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _bad_g1() -> bytes:
    """Smallest-x on-curve G1 point OUTSIDE the prime subgroup (the
    cofactor is ~2^125, so the first few on-curve x qualify)."""
    x = 1
    while True:
        y = bls.fp_sqrt((x * x * x + bls.B) % bls.P)
        if y is not None and not bls.g1_in_subgroup((x, y)):
            return bls.g1_compress((x, y))
        x += 1


def _bad_g2() -> bytes:
    c = 1
    while True:
        xx = (c, 0)
        y2 = bls.f2_add(bls.f2_mul(xx, bls.f2_sqr(xx)),
                        bls.f2_scalar(bls.XI, bls.B))
        y = bls.f2_sqrt(y2)
        if y is not None and not bls.g2_in_subgroup((xx, y)):
            return bls.g2_compress((xx, y))
        c += 1


class TestOracle:
    def test_compress_roundtrip(self):
        sk = bls.PrivKey(b"\x01" * 32)
        pub = sk.pub_key().bytes()
        assert len(pub) == 48
        pt = bls.g1_decompress(pub)
        assert bls.g1_compress(pt) == pub
        sig = sk.sign(b"msg")
        assert len(sig) == 96
        q = bls.g2_decompress(sig)
        assert bls.g2_compress(q) == sig

    def test_pubkey_status_words(self):
        good = bls.PrivKey(b"\x02" * 32).pub_key().bytes()
        assert bls.pubkey_status(good) == (bls.g1_decompress(good), None)
        assert bls.pubkey_status(b"\xff" * 48)[1] == "malformed"
        inf = bytes([0xC0]) + b"\x00" * 47
        assert bls.pubkey_status(inf)[1] == "identity"
        assert bls.pubkey_status(_bad_g1())[1] == "subgroup"

    def test_signature_status_words(self):
        sig = bls.PrivKey(b"\x03" * 32).sign(b"m")
        assert bls.signature_status(sig)[1] is None
        assert bls.signature_status(b"\xff" * 96)[1] == "malformed"
        inf = bytes([0xC0]) + b"\x00" * 95
        assert bls.signature_status(inf)[1] == "identity"
        assert bls.signature_status(_bad_g2())[1] == "subgroup"

    def test_g1_subgroup_check_is_not_vacuous(self):
        # Regression: g1_mul used to reduce k mod R, turning the
        # subgroup check [R]P == O into [0]P == O — vacuously true for
        # every on-curve point, so non-subgroup pubkeys (which break
        # apk-aggregation soundness) sailed through.
        pub = _bad_g1()
        pt = bls.g1_decompress(pub)
        assert bls.g1_on_curve(pt)
        assert not bls.g1_in_subgroup(pt)
        assert bls.g1_mul(bls.R, pt) is not None

    def test_aggregate_pubkeys_flags_lowest_bad_index(self):
        pubs = [bls.PrivKey(bytes([i + 1]) * 32).pub_key().bytes()
                for i in range(3)]
        apk, bad = bls.aggregate_pubkeys(pubs)
        assert apk is not None and bad is None
        apk2, bad2 = bls.aggregate_pubkeys([pubs[0], _bad_g1(), b"\x00" * 48])
        assert apk2 is None and bad2 == 1

    def test_fast_aggregate_verify_end_to_end(self):
        # ONE full pairing on the brute-force oracle (~seconds): the
        # exhaustive kernel-vs-oracle differential lives in the
        # subprocess gate, not here.
        sks = [bls.PrivKey(bytes([7 + i]) * 32) for i in range(3)]
        msg = b"one vote, one message"
        sig = bls.aggregate([sk.sign(msg) for sk in sks])
        pubs = [sk.pub_key().bytes() for sk in sks]
        assert bls.fast_aggregate_verify(pubs, msg, sig)
        assert not bls.fast_aggregate_verify(pubs[:2], msg, sig)


@needs_crypto
class TestAggregatedCommitWire:
    def _agg(self, n=8, signers=(0, 1, 2, 3, 4, 5)):
        ba = BitArray(n)
        for i in signers:
            ba.set_index(i, True)
        bid = BlockID(hash=b"\x21" * 32,
                      part_set_header=PartSetHeader(total=1, hash=b"\x22" * 32))
        return AggregatedCommit(height=11, round=2, block_id=bid,
                                signature=b"\x05" * 96, signers=ba)

    def test_proto_roundtrip(self):
        agg = self._agg()
        assert AggregatedCommit.decode(agg.encode()) == agg

    def test_wire_footprint_is_constant_in_signers(self):
        # one signature + a bitmap: adding signers must not add 96-byte
        # rows (the 2302.00418 bandwidth win the lane exists for). The
        # bitmap words are varints, so two extra bits may cost ONE more
        # byte — never another signature row.
        a6 = self._agg(signers=(0, 1, 2, 3, 4, 5))
        a8 = self._agg(signers=tuple(range(8)))
        assert abs(len(a8.encode()) - len(a6.encode())) <= 1

    def test_sign_bytes_identical_across_signers(self):
        # aggregation requires ONE message: the canonical vote is
        # composed with the zero timestamp for every signer
        agg = self._agg()
        sb = agg.sign_bytes("chain")
        assert isinstance(sb, bytes) and len(sb) > 0
        assert sb == self._agg(signers=(2, 5)).sign_bytes("chain")

    def test_validate_basic(self):
        agg = self._agg()
        agg.validate_basic()
        bad = self._agg()
        bad.signature = b"\x05" * 64
        with pytest.raises(ValueError):
            bad.validate_basic()
        neg = self._agg()
        neg.height = -1
        with pytest.raises(ValueError):
            neg.validate_basic()


@needs_crypto
class TestCommitSeam:
    """Sequential verify + prepare/conclude on paths that fail BEFORE
    the pairing (cheap); pairing-path parity is the subprocess gate."""

    def _committee(self, n=4):
        from tendermint_tpu.types import Validator, ValidatorSet

        sks = [bls.PrivKey((40 + i).to_bytes(32, "big")) for i in range(n)]
        vset = ValidatorSet.new([Validator.new(sk.pub_key(), 100)
                                 for sk in sks])
        by = {sk.pub_key().address(): sk for sk in sks}
        return vset, [by[v.address] for v in vset.validators]

    def _agg(self, vset, sks, signers, chain_id="seam"):
        bid = BlockID(hash=b"\x31" * 32,
                      part_set_header=PartSetHeader(total=1, hash=b"\x32" * 32))
        ba = BitArray(len(sks))
        for i in signers:
            ba.set_index(i, True)
        agg = AggregatedCommit(height=3, round=0, block_id=bid, signers=ba)
        msg = agg.sign_bytes(chain_id)
        agg.signature = bls.aggregate([sks[i].sign(msg) for i in signers])
        return bid, agg

    def test_malformed_signature_blame(self):
        from tendermint_tpu.types import validation as V

        vset, sks = self._committee()
        bid, agg = self._agg(vset, sks, [0, 1, 2])
        agg.signature = b"\xff" * 96
        with pytest.raises(ValueError) as ei:
            V.verify_aggregated_commit("seam", vset, bid, 3, agg)
        assert str(ei.value) == (
            f"malformed aggregate signature: {agg.signature.hex().upper()}")

    def test_bitmap_size_mismatch_is_pre_crypto(self):
        from tendermint_tpu.types import validation as V
        from tendermint_tpu.types.validation import ErrInvalidCommitSignatures

        vset, sks = self._committee()
        bid, agg = self._agg(vset, sks, [0, 1, 2])
        agg.signers = BitArray(7)
        for fn in (
            lambda: V.verify_aggregated_commit("seam", vset, bid, 3, agg),
            lambda: V.prepare_aggregated_commit("seam", vset, bid, 3, agg,
                                                k_hint=8),
        ):
            with pytest.raises(ErrInvalidCommitSignatures):
                fn()

    def test_insufficient_power_precedes_crypto(self):
        from tendermint_tpu.types import validation as V
        from tendermint_tpu.types.validator_set import (
            ErrNotEnoughVotingPowerSigned,
        )

        vset, sks = self._committee()
        bid, agg = self._agg(vset, sks, [0])
        agg.signature = b"\xff" * 96  # never reached: tally first
        with pytest.raises(ErrNotEnoughVotingPowerSigned):
            V.verify_aggregated_commit("seam", vset, bid, 3, agg)

    def test_prepare_below_threshold_stays_sync(self):
        from tendermint_tpu.ops import backend
        from tendermint_tpu.types import validation as V

        vset, sks = self._committee()
        bid, agg = self._agg(vset, sks, [0, 1, 2])
        assert backend.BLS_DEVICE_THRESHOLD > 1
        blk, conc = V.prepare_aggregated_commit("seam", vset, bid, 3, agg,
                                                k_hint=1)
        assert blk is None and conc is None

    def test_aggblock_pad_and_concat_rules(self):
        from tendermint_tpu.ops.entry_block import AggBlock, block_concat
        from tendermint_tpu.types import validation as V
        from tendermint_tpu.ops import epoch_cache as _epoch

        _epoch.reset(8)
        vset, sks = self._committee()
        _epoch.note_valset(vset)
        _epoch.note_valset(vset)
        bid, agg = self._agg(vset, sks, [0, 1, 2])
        blk, _ = V.prepare_aggregated_commit("seam", vset, bid, 3, agg,
                                             k_hint=8)
        assert blk is not None and len(blk) == 1
        fused = block_concat([blk, AggBlock.pad(3)])
        assert len(fused) == 4 and fused.epoch_key == blk.epoch_key
        vset2, sks2 = self._committee(n=5)
        bid2, agg2 = self._agg(vset2, sks2, [0, 1, 2, 3])
        _epoch.note_valset(vset2)
        _epoch.note_valset(vset2)
        blk2, _ = V.prepare_aggregated_commit("seam", vset2, bid2, 3, agg2,
                                              k_hint=8)
        with pytest.raises(ValueError):
            block_concat([blk, blk2])  # mixed committees never fuse

    def test_mesh_bls_lane_width_quantizes(self):
        from tendermint_tpu.ops import mesh as ms

        assert ms._lane_width(1, "bls12381", 10240) == 4
        assert ms._lane_width(4, "bls12381", 10240) == 4
        assert ms._lane_width(5, "bls12381", 10240) == 16
        assert ms._lane_width(17, "bls12381", 10240) == 17
        assert ms._lane_width(5, "ed25519", 128) == 128


def _purepy_env():
    from tendermint_tpu.libs import jaxcache

    env = dict(os.environ, TM_TPU_PUREPY_CRYPTO="1", JAX_PLATFORMS="cpu")
    env.pop("TM_TPU_DONATE", None)
    env.pop("TM_TPU_MESH", None)
    jaxcache.set_env(env, _repo_root())
    return env


def test_bls_isolated_runners():
    """The purepy subprocess re-run of this file (the tier-1 home of
    the crypto-gated seam tests above) and the `prep_bench --bls`
    acceptance gate (fused multi-pairing launch + verdict-code/blame
    parity incl. crafted non-subgroup points, three-lane superbatch,
    zero pool-slot leak), run back to back like the secp runner."""
    if os.environ.get("TM_TPU_BLS_ISOLATED"):
        pytest.skip("already inside the isolated runner")
    have_crypto = _HAVE_CRYPTO
    here = os.path.dirname(os.path.abspath(__file__))
    cmds = {}
    if not have_crypto:  # with the wheel present the seam tests ran direct
        cmds["lane suite"] = (
            [
                sys.executable, "-m", "pytest",
                os.path.join(here, "test_bls_lane_isolated.py"),
                "-q", "-m", "not slow", "-p", "no:cacheprovider",
            ],
            dict(_purepy_env(), TM_TPU_BLS_ISOLATED="1"),
        )
    cmds["--bls gate"] = (
        [
            sys.executable,
            os.path.join(_repo_root(), "tools", "prep_bench.py"),
            "--bls",
        ],
        _purepy_env(),
    )
    fails = []
    for label, (cmd, env) in cmds.items():
        r = subprocess.run(
            cmd,
            stdout=subprocess.PIPE,
            stderr=subprocess.STDOUT,
            env=env,
            cwd=_repo_root(),
            timeout=800,
        )
        if r.returncode != 0:
            fails.append(f"{label}: rc={r.returncode}\n"
                         f"{(r.stdout or b'').decode(errors='replace')[-3000:]}")
    assert not fails, "\n\n".join(fails)
