"""Tier-1 face of the overlapped relay (ISSUE 7).

Two layers, same pattern as test_epoch_cache_isolated.py:

- crypto-free unit tests of the device buffer pool and the windowed-ratio
  accounting (ops/device_pool.py) run IN PROCESS — no cryptography wheel,
  no jax, no kernel compiles;
- the signature-level tests (tests/test_overlap.py) and the
  `tools/prep_bench.py --overlap` span-order/pool-reuse gate run in
  SUBPROCESSES with TM_TPU_PUREPY_CRYPTO=1, which must never leak into
  the main pytest process.
"""

import os
import subprocess
import sys
import threading
import time

import pytest

try:
    from tendermint_tpu.ops import device_pool as dp
except ModuleNotFoundError:
    # The ops package __init__ wires the crypto.batch seam, which needs
    # the cryptography wheel this container lacks. device_pool itself is
    # stdlib+numpy bookkeeping — load the module file directly so the
    # pool/ratio unit tests still run in the main tier-1 process. (The
    # lazy `_ops()` metrics hook is unusable in this mode; every test
    # below passes `_metrics=` explicitly.)
    import importlib.util

    _p = os.path.join(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        "tendermint_tpu", "ops", "device_pool.py",
    )
    _spec = importlib.util.spec_from_file_location(
        "_tm_tpu_device_pool_standalone", _p
    )
    dp = importlib.util.module_from_spec(_spec)
    _spec.loader.exec_module(dp)


class _Gauge:
    def __init__(self):
        self.v = None

    def set(self, v):
        self.v = v


class _Counter:
    def __init__(self):
        self.n = 0

    def inc(self, v=1):
        self.n += v


class _Metrics:
    def __init__(self):
        self.buffer_pool_hits = _Counter()
        self.buffer_pool_misses = _Counter()


class TestDeviceBufferPool:
    def test_mint_then_recycle(self):
        pool = dp.DeviceBufferPool(depth=2)
        m = _Metrics()
        key = ((128, 32), "|u1")
        s1 = pool.acquire(key, _metrics=m)
        s2 = pool.acquire(key, _metrics=m)
        assert m.buffer_pool_misses.n == 2 and m.buffer_pool_hits.n == 0
        pool.release(s1)
        s3 = pool.acquire(key, _metrics=m)
        assert s3 is s1  # recycled
        assert m.buffer_pool_hits.n == 1
        pool.release(s2)
        pool.release(s3)
        st = pool.stats()
        assert st == {"depth": 2, "in_flight": 0, "layouts": 1,
                      "minted": 2, "free": 2}

    def test_distinct_layouts_do_not_share_slots(self):
        pool = dp.DeviceBufferPool(depth=1)
        m = _Metrics()
        a = pool.acquire(("a",), _metrics=m)
        b = pool.acquire(("b",), _metrics=m)  # different layout: no block
        assert a.key != b.key
        assert m.buffer_pool_misses.n == 2
        pool.release(a)
        pool.release(b)

    def test_acquire_blocks_at_depth_until_release(self):
        pool = dp.DeviceBufferPool(depth=1)
        m = _Metrics()
        held = pool.acquire(("k",), _metrics=m)
        got = []

        def worker():
            got.append(pool.acquire(("k",), _metrics=m))

        t = threading.Thread(target=worker, daemon=True)
        t.start()
        time.sleep(0.15)
        assert not got  # blocked: depth reached
        pool.release(held)
        t.join(timeout=5)
        assert got and got[0] is held
        pool.release(got[0])
        assert pool.in_flight() == 0

    def test_acquire_abort(self):
        pool = dp.DeviceBufferPool(depth=1)
        m = _Metrics()
        held = pool.acquire(("k",), _metrics=m)
        stop = threading.Event()
        got = []

        def worker():
            got.append(pool.acquire(("k",), abort=stop.is_set, _metrics=m))

        t = threading.Thread(target=worker, daemon=True)
        t.start()
        stop.set()
        t.join(timeout=5)
        assert got == [None]
        pool.release(held)

    def test_release_none_is_noop(self):
        pool = dp.DeviceBufferPool(depth=1)
        pool.release(None)
        assert pool.in_flight() == 0

    def test_layout_key_separates_shapes_and_dtypes(self):
        import numpy as np

        a = (np.zeros((128, 32), np.uint8), np.zeros((128,), np.int32))
        b = (np.zeros((128, 32), np.uint8), np.zeros((128,), np.int64))
        c = (np.zeros((1024, 32), np.uint8), np.zeros((1024,), np.int32))
        k = dp.layout_key
        assert k(128, a) != k(128, b) != k(1024, c)
        assert k(128, a) == k(128, tuple(x.copy() for x in a))
        # non-arrays (e.g. a pre-resolved jax table) don't key
        assert k(128, a + ("not-an-array",)) == k(128, a)


class TestWindowedRatio:
    def test_occupancy_mode(self):
        g = _Gauge()
        r = dp.WindowedRatio(g, window=60.0, wall=True)
        time.sleep(0.05)
        r.add(0.025)  # ~0.025 busy over >=0.05 elapsed
        assert g.v is not None and 0.0 < g.v <= 1.0

    def test_ratio_mode(self):
        g = _Gauge()
        r = dp.WindowedRatio(g, window=60.0, wall=False)
        r.add(1.0, 4.0)
        assert g.v == pytest.approx(0.25)
        r.add(1.0, 0.0)
        assert g.v == pytest.approx(0.5)

    def test_ratio_mode_idle_tick_decays_to_zero(self):
        # an empty ratio window (nothing transferred) must read 0, not
        # stick at the last busy value (den==0 skips normal publish)
        g = _Gauge()
        r = dp.WindowedRatio(g, window=0.05, wall=False)
        r.add(1.0, 2.0)
        assert g.v == pytest.approx(0.5)
        time.sleep(0.08)
        r.tick()  # flushes the residual pre-idle window, resets
        time.sleep(0.06)
        r.tick()  # empty window: decays to 0
        assert g.v == pytest.approx(0.0)

    def test_ratio_mode_add_after_idle_tick_starts_fresh_window(self):
        # the dispatcher tick()s through idle stretches, so a sample
        # landing after idle meets reset accumulators, not the stale
        # pre-idle window
        g = _Gauge()
        r = dp.WindowedRatio(g, window=0.05, wall=False)
        r.add(4.0, 4.0)  # pre-idle: ratio 1.0
        time.sleep(0.08)
        r.tick()         # idle heartbeat rolls the window
        r.add(0.0, 1.0)  # fresh window: 0 hidden of 1
        assert g.v == pytest.approx(0.0)

    def test_occupancy_boundary_sample_cannot_clamp_to_one(self):
        # a 30ms-busy sample arriving after ~0.1s idle closes the window
        # against the FULL elapsed time — the gauge must read the true
        # low occupancy, not 1.0 (crediting the sample to a zero-length
        # fresh window)
        g = _Gauge()
        r = dp.WindowedRatio(g, window=0.05, wall=True)
        time.sleep(0.1)
        r.add(0.03)
        assert g.v == pytest.approx(0.03 / 0.1, rel=0.5)
        r.add(0.005)  # next sample lands in the fresh window
        assert g.v < 1.0

    def test_window_rolls_and_idle_tick_decays(self):
        g = _Gauge()
        r = dp.WindowedRatio(g, window=0.05, wall=True)
        time.sleep(0.01)  # give the window a real measurement base
        r.add(0.04)
        first = g.v
        assert first is not None
        time.sleep(0.08)
        r.tick()  # idle: publish the (quiet) window, reset
        assert g.v <= first
        time.sleep(0.06)
        r.tick()
        assert g.v == pytest.approx(0.0, abs=1e-6)

    def test_ops_stats_exposes_overlap_fields(self):
        from tendermint_tpu.libs.metrics import ops_stats

        s = ops_stats()
        for key in ("transfer_overlap_ratio", "buffer_pool_hits",
                    "buffer_pool_misses"):
            assert key in s


def _purepy_env():
    env = dict(os.environ, TM_TPU_PUREPY_CRYPTO="1", JAX_PLATFORMS="cpu")
    env.pop("TM_TPU_DONATE", None)
    return env


def _repo_root():
    return os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def test_overlap_under_purepy_fallback():
    try:
        import cryptography  # noqa: F401

        pytest.skip("cryptography present; test_overlap runs directly")
    except ModuleNotFoundError:
        pass
    here = os.path.dirname(os.path.abspath(__file__))
    # TM_TPU_DEVCHECK=1 at process start (ISSUE 8): import-time lock
    # creation (metrics registries, epoch cache) is instrumented too, so
    # the overlap suite's autouse devcheck fixture sees the full lock-
    # order graph, not just locks created after enable()
    env = dict(_purepy_env(), TM_TPU_DEVCHECK="1")
    r = subprocess.run(
        [
            sys.executable, "-m", "pytest",
            os.path.join(here, "test_overlap.py"),
            "-q", "-m", "not slow", "-p", "no:cacheprovider",
        ],
        capture_output=True,
        env=env,
        cwd=_repo_root(),
        timeout=800,
    )
    tail = (r.stdout or b"").decode(errors="replace")[-3000:]
    assert r.returncode == 0, f"isolated test_overlap run failed:\n{tail}"


def test_prep_bench_overlap_gate():
    """ISSUE 7 satellite: the --overlap span-order + pool-reuse gate,
    wired into tier-1 through the isolated runner."""
    r = subprocess.run(
        [
            sys.executable,
            os.path.join(_repo_root(), "tools", "prep_bench.py"),
            "--overlap",
        ],
        capture_output=True,
        env=_purepy_env(),
        cwd=_repo_root(),
        timeout=600,
    )
    out = (r.stdout or b"").decode(errors="replace")
    err = (r.stderr or b"").decode(errors="replace")
    assert r.returncode == 0, f"--overlap gate failed:\n{out}\n{err[-2000:]}"
