"""Tier-1 flight-recorder coverage for containers without the
`cryptography` wheel (ISSUE 10).

Three layers, same pattern as tests/test_simnet_isolated.py:
  1. Crypto-free unit tests IN PROCESS: trace flow events / per-node
     tracers / merging, the devcheck unbalanced-span canary (+ its
     TM_TPU_INJECT_LINTBUG=span seam), and tools/bench_report.py over
     both synthetic shapes and every committed BENCH/MULTICHIP artifact.
  2. Subprocess acceptance runs under TM_TPU_PUREPY_CRYPTO=1: the
     cluster/RPC suite (tests/test_flight_recorder.py), the
     `simnet_run.py --smoke --trace` merged-trace acceptance, and the
     tracing-disabled overhead guard extended to flow-carrying spans.
  3. The committed-artifact gate: `bench_report --validate` and
     `--trajectory` must exit 0 over everything committed at the root.
"""

import json
import os
import subprocess
import sys

import pytest

from tendermint_tpu.libs import devcheck
from tendermint_tpu.observability import trace as tr

HERE = os.path.dirname(os.path.abspath(__file__))
REPO = os.path.dirname(HERE)

sys.path.insert(0, os.path.join(REPO, "tools"))
try:
    import bench_report
finally:
    sys.path.pop(0)


@pytest.fixture(autouse=True)
def _reset_tracer():
    tr.configure(enabled=False)
    tr.TRACER.clear()
    yield
    tr.configure(enabled=False)
    tr.TRACER.clear()


# ---------------------------------------------------------------------------
# trace: flow events, per-node tracers, merging
# ---------------------------------------------------------------------------


class TestFlowEvents:
    def test_span_with_flow_exports_flow_event(self):
        t = tr.SpanTracer(node="n0", now=lambda: 5.0, epoch=0.0)
        t.configure(enabled=True)
        with t.span("a", flow=3, flow_phase="s", k=1):
            pass
        doc = t.export_chrome()
        xs = [e for e in doc["traceEvents"] if e["ph"] == "X"]
        flows = [e for e in doc["traceEvents"] if e["ph"] in "stf"]
        assert len(xs) == 1 and len(flows) == 1
        assert xs[0]["args"]["flow"] == 3
        assert xs[0]["args"]["flow_phase"] == "s"
        assert flows[0] == {
            "name": "flow", "cat": "flow", "ph": "s", "id": 3,
            "pid": xs[0]["pid"], "tid": xs[0]["tid"], "ts": xs[0]["ts"],
        }

    def test_finish_phase_binds_enclosing(self):
        t = tr.SpanTracer(node="n0")
        t.configure(enabled=True)
        with t.span("end", flow=9, flow_phase="f"):
            pass
        fev = [e for e in t.export_chrome()["traceEvents"]
               if e["ph"] == "f"][0]
        assert fev["bp"] == "e"

    def test_flow_point_is_instant(self):
        clock = {"t": 1.0}
        t = tr.SpanTracer(node="n1", now=lambda: clock["t"], epoch=0.0)
        t.configure(enabled=True)
        t.flow_point("send", 7, "s", to="x")
        (name, s, e, _tid, args), = t.events()
        assert name == "send" and s == e == 1.0
        assert args["flow"] == 7 and args["to"] == "x"
        # disabled / flow-less points record nothing
        t.flow_point("send", None, "s")
        t.configure(enabled=False)
        t.flow_point("send", 8, "s")
        assert len(t.events()) == 1

    def test_spans_without_flow_unchanged(self):
        tr.configure(enabled=True)
        with tr.span("plain", n=4):
            pass
        doc = tr.TRACER.export_chrome()
        assert [e["ph"] for e in doc["traceEvents"]] == ["X"]
        assert doc["traceEvents"][0]["args"] == {"n": 4}

    def test_next_flow_unique_and_offset(self):
        a, b = tr.next_flow(), tr.next_flow()
        assert a != b and min(a, b) > (1 << 32)

    def test_node_tracer_metadata_and_injected_clock(self):
        clock = {"t": 10.0}
        t = tr.SpanTracer(node="sim7", now=lambda: clock["t"], epoch=10.0)
        t.configure(enabled=True)
        with t.span("work"):
            clock["t"] = 10.5
        doc = t.export_chrome()
        meta = doc["traceEvents"][0]
        assert meta["ph"] == "M" and meta["args"]["name"] == "sim7"
        ev = [e for e in doc["traceEvents"] if e["ph"] == "X"][0]
        assert ev["ts"] == 0.0
        assert ev["dur"] == pytest.approx(0.5e6)
        assert ev["pid"] != os.getpid()


class TestMergeTraces:
    def _doc(self, node, flow, phase, name="ev"):
        t = tr.SpanTracer(node=node, now=lambda: 1.0, epoch=0.0)
        t.configure(enabled=True)
        t.flow_point(name, flow, phase)
        return t.export_chrome()

    def test_pids_rekeyed_flow_ids_preserved(self):
        a = self._doc("alpha", 42, "s", "send")
        b = self._doc("beta", 42, "f", "recv")
        m = tr.merge_traces([a, b])
        xs = [e for e in m["traceEvents"] if e["ph"] == "X"]
        pids = {e["pid"] for e in xs}
        assert len(pids) == 2
        chains = tr.flow_chains(m)
        assert list(chains) == [42]
        assert [e["name"] for e in chains[42]] == ["send", "recv"]
        names = {e["args"]["name"] for e in m["traceEvents"]
                 if e["ph"] == "M"}
        assert names == {"alpha", "beta"}

    def test_labels_name_unnamed_docs(self):
        tr.configure(enabled=True)
        with tr.span("global"):
            pass
        g = tr.TRACER.export_chrome()  # no process_name of its own
        m = tr.merge_traces([g], labels=["driver"])
        meta = [e for e in m["traceEvents"] if e["ph"] == "M"]
        assert meta and meta[0]["args"]["name"] == "driver"

    def test_merge_then_summarize(self):
        a = self._doc("n0", 1, "s")
        b = self._doc("n1", 1, "f")
        s = tr.summarize_events(tr.merge_traces([a, b]))
        assert s["ev"]["count"] == 2  # flow/meta events not double-counted

    def test_flow_chains_orders_by_phase(self):
        doc = {"traceEvents": [
            {"ph": "X", "name": "c", "pid": 1, "ts": 5.0,
             "args": {"flow": 1, "flow_phase": "f"}},
            {"ph": "X", "name": "a", "pid": 2, "ts": 9.0,
             "args": {"flow": 1, "flow_phase": "s"}},
            {"ph": "X", "name": "b", "pid": 1, "ts": 7.0,
             "args": {"flow": 1, "flow_phase": "t"}},
        ]}
        chains = tr.flow_chains(doc)
        assert [e["name"] for e in chains[1]] == ["a", "b", "c"]


# ---------------------------------------------------------------------------
# devcheck: unbalanced-span canary + inject seam
# ---------------------------------------------------------------------------


class TestSpanCanary:
    @pytest.fixture(autouse=True)
    def _fresh_devcheck(self):
        was_on = devcheck.enabled()
        devcheck.enable(reset=True)
        yield
        devcheck.reset_state()
        if not was_on:
            devcheck.disable()

    def test_balanced_spans_are_clean(self):
        t = tr.SpanTracer(node="x")
        t.configure(enabled=True)
        with t.span("outer"):
            with t.span("inner"):
                pass
        t.close()  # must not raise
        assert not devcheck.violations()
        assert devcheck.report()["counts"]["span_opens"] == 2
        assert devcheck.report()["open_spans"] == 0

    def test_leaked_span_fires_at_close(self):
        t = tr.SpanTracer(node="x")
        t.configure(enabled=True)
        s = t.span("leaky")
        s.__enter__()  # never exited — the bug class
        with pytest.raises(devcheck.DevcheckViolation, match="leaky"):
            t.close()
        assert devcheck.violations()[0]["kind"] == "unbalanced-span"
        # state cleared: the same leak does not re-report forever
        devcheck._violations.clear()
        t.close()
        assert not devcheck.violations()

    def test_inject_seam_fires(self, monkeypatch):
        """TM_TPU_INJECT_LINTBUG=span: a well-formed `with` leaks its
        balance bookkeeping, and close() must catch it."""
        monkeypatch.setenv("TM_TPU_INJECT_LINTBUG", "span")
        t = tr.SpanTracer(node="x")
        t.configure(enabled=True)
        with t.span("seeded"):
            pass
        assert len(t.events()) == 1  # the span still records
        with pytest.raises(devcheck.DevcheckViolation,
                           match="unbalanced-span|seeded"):
            t.close()

    def test_inject_seam_inert_without_devcheck(self, monkeypatch):
        devcheck.disable()
        monkeypatch.setenv("TM_TPU_INJECT_LINTBUG", "span")
        t = tr.SpanTracer(node="x")
        t.configure(enabled=True)
        with t.span("quiet"):
            pass
        t.close()
        assert not devcheck.violations()

    def test_disable_mid_span_pops_like_devlock(self):
        t = tr.SpanTracer(node="x")
        t.configure(enabled=True)
        with t.span("outer"):
            devcheck.disable()
        devcheck.enable()
        t.close()  # the armed-time open was popped unconditionally
        assert not devcheck.violations()

    def test_zero_cost_when_devcheck_off(self):
        devcheck.disable()
        tr.configure(enabled=True)
        with tr.span("a"):
            pass
        assert devcheck.report()["counts"]["span_opens"] == 0


# ---------------------------------------------------------------------------
# bench_report: normalizer, validate, trajectory, compare gate
# ---------------------------------------------------------------------------


BENCH_WRAPPER = {
    "n": 4, "cmd": "python bench.py", "rc": 0, "tail": "...",
    "parsed": {
        "metric": "verify_commit_10000", "value": 264349.2,
        "unit": "sigs/s", "sustained_sigs_per_s": 264349.2,
        "relay_rtt_ms": 64.3, "pipelined_headers_per_s": 1652.0,
        "mode": "stream8", "backend": "tpu",
    },
}


class TestNormalizer:
    def test_bench_wrapper(self):
        art = bench_report.normalize(BENCH_WRAPPER, "BENCH_r04.json")
        assert art["schema_version"] == bench_report.SCHEMA_VERSION
        assert art["kind"] == "bench" and art["round"] == 4
        assert art["ok"] and art["value"] == 264349.2
        assert art["metrics"]["sustained_sigs_per_s"] == 264349.2
        assert not bench_report.validate(art)

    def test_failed_round_is_valid_but_not_ok(self):
        art = bench_report.normalize(
            {"n": 1, "cmd": "x", "rc": 1, "tail": "boom", "parsed": None},
            "BENCH_r01.json",
        )
        assert not art["ok"] and art["value"] is None
        assert not bench_report.validate(art), "an honest failure is valid"

    def test_legacy_multichip_wrapper(self):
        art = bench_report.normalize(
            {"n_devices": 8, "ok": True, "rc": 0, "skipped": False,
             "tail": ""},
            "MULTICHIP_r02.json",
        )
        assert art["kind"] == "multichip" and art["ok"]
        assert art["metrics"]["n_devices"] == 8
        assert not bench_report.validate(art)

    def test_direct_artifact_and_key_alias(self):
        art = bench_report.normalize(
            {"metric": "m", "device_sigs_per_s": 99.0, "unit": "sigs/s"},
            "MULTICHIP_r06.json",
        )
        assert art["ok"]
        assert art["metrics"]["value"] == 99.0  # old key -> canonical

    def test_unrecognized_shape_fails_validation(self):
        art = bench_report.normalize({"bogus": 1}, "BENCH_r09.json")
        assert bench_report.validate(art)

    def test_tracing_false_span_summary_tolerated(self):
        raw = dict(BENCH_WRAPPER)
        raw["parsed"] = dict(raw["parsed"], span_summary={"tracing": False})
        art = bench_report.normalize(raw, "BENCH_r07.json")
        assert art["span_tracing"] is False
        assert not bench_report.validate(art)


class TestCompareGate:
    def test_regression_past_gate_fails(self):
        a = bench_report.normalize(BENCH_WRAPPER, "BENCH_r04.json")
        raw_b = dict(BENCH_WRAPPER)
        raw_b["parsed"] = dict(
            raw_b["parsed"], value=150000.0, sustained_sigs_per_s=150000.0
        )
        b = bench_report.normalize(raw_b, "BENCH_r05.json")
        res = bench_report.compare(a, b, gate_pct=10.0)
        assert not res["ok"]
        assert "value" in res["regressions"]
        assert "relay_rtt_ms" not in res["regressions"]

    def test_within_gate_passes_and_rtt_is_lower_better(self):
        a = bench_report.normalize(BENCH_WRAPPER, "BENCH_r04.json")
        raw_b = dict(BENCH_WRAPPER)
        raw_b["parsed"] = dict(
            raw_b["parsed"], value=260000.0, sustained_sigs_per_s=260000.0,
            relay_rtt_ms=80.0,
        )
        b = bench_report.normalize(raw_b, "BENCH_r05.json")
        res = bench_report.compare(a, b, gate_pct=10.0)
        assert res["regressions"] == ["relay_rtt_ms"]  # a RISE regressed


class TestCommittedArtifacts:
    """The satellite/acceptance gate: every artifact committed at the repo
    root validates, and --trajectory renders one row per round, exit 0."""

    def test_defaults_find_all_committed_artifacts(self):
        paths = bench_report.default_paths()
        assert len(paths) >= 10, paths
        assert any("BENCH_r01" in p for p in paths)
        assert any("MULTICHIP_r06" in p for p in paths)

    def test_validate_exit_0(self, capsys):
        assert bench_report.main(["--validate"]) == 0
        out = capsys.readouterr().out
        assert "0 invalid" in out

    def test_trajectory_exit_0_one_row_per_artifact(self, capsys):
        assert bench_report.main(["--trajectory"]) == 0
        out = capsys.readouterr().out
        n = len(bench_report.default_paths())
        rows = [ln for ln in out.splitlines()
                if ln.startswith(("bench_r", "multichip_r", "light_r",
                                  "mempool_r", "blocksync_r", "votes_r",
                                  "soak_r", "lanes_r", "fleet_r",
                                  "schemes_r", "agg_r"))]
        assert len(rows) == n, out
        assert any("152,542" in ln or "152542" in ln for ln in rows), (
            "r03's sustained figure must survive normalization"
        )

    def test_trajectory_json_mode(self, capsys):
        assert bench_report.main(["--trajectory", "--json"]) == 0
        rows = json.loads(capsys.readouterr().out)
        assert {r["kind"] for r in rows} == {"bench", "multichip", "light",
                                             "mempool", "blocksync", "votes",
                                             "soak", "lanes", "fleet",
                                             "schemes", "agg"}
        r5 = next(r for r in rows
                  if r["kind"] == "bench" and r["round"] == 5)
        assert r5["kernel_stream"] == pytest.approx(470560.0)

    def test_cli_compare_gate_exit_codes(self, tmp_path):
        a = tmp_path / "BENCH_r90.json"
        b = tmp_path / "BENCH_r91.json"
        raw_b = dict(BENCH_WRAPPER)
        raw_b["parsed"] = dict(raw_b["parsed"], value=100.0)
        a.write_text(json.dumps(BENCH_WRAPPER))
        b.write_text(json.dumps(raw_b))
        r = subprocess.run(
            [sys.executable, os.path.join(REPO, "tools", "bench_report.py"),
             "--compare", str(a), str(b), "--gate-pct", "5"],
            capture_output=True, text=True, cwd=REPO, timeout=60,
        )
        assert r.returncode == 1, r.stdout
        assert "REGRESSED" in r.stdout
        r2 = subprocess.run(
            [sys.executable, os.path.join(REPO, "tools", "bench_report.py"),
             "--compare", str(a), str(a)],
            capture_output=True, text=True, cwd=REPO, timeout=60,
        )
        assert r2.returncode == 0, r2.stdout

    def test_cli_usage_error(self):
        r = subprocess.run(
            [sys.executable, os.path.join(REPO, "tools", "bench_report.py"),
             "/nonexistent/dir/*.json"],
            capture_output=True, text=True, cwd=REPO, timeout=60,
        )
        assert r.returncode == 1  # unreadable artifact is a finding


# ---------------------------------------------------------------------------
# subprocess acceptance (purepy; env must not leak into this interpreter)
# ---------------------------------------------------------------------------


def _purepy_env(**extra):
    env = dict(os.environ, TM_TPU_PUREPY_CRYPTO="1", JAX_PLATFORMS="cpu")
    env.update(extra)
    return env


def test_flight_recorder_suite_under_purepy_fallback():
    try:
        import cryptography  # noqa: F401

        pytest.skip("cryptography present; test_flight_recorder runs directly")
    except ModuleNotFoundError:
        pass
    r = subprocess.run(
        [
            sys.executable, "-m", "pytest",
            os.path.join(HERE, "test_flight_recorder.py"),
            "-q", "-m", "not slow", "-p", "no:cacheprovider",
        ],
        capture_output=True, env=_purepy_env(), cwd=REPO, timeout=600,
    )
    tail = (r.stdout or b"").decode(errors="replace")[-3000:]
    assert r.returncode == 0, f"isolated flight-recorder run failed:\n{tail}"


def test_smoke_exports_merged_trace_with_cross_node_chain(tmp_path):
    """THE acceptance criterion: `simnet_run.py --smoke --trace` exports
    one merged Chrome trace containing at least one cross-node flow chain
    (gossip send → deliver → verify dispatch) and its verdict carries a
    populated height_timelines ring — while staying replay-exact."""
    trace_path = str(tmp_path / "merged.json")
    r = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "simnet_run.py"),
         "--smoke", "--trace", trace_path],
        capture_output=True, env=_purepy_env(), cwd=REPO, timeout=120,
    )
    out = (r.stdout or b"").decode(errors="replace")
    assert r.returncode == 0, f"smoke failed:\n{out[-3000:]}"
    verdict = json.loads(out)
    assert verdict["ok"] and verdict["replay_exact"]
    # populated timeline ring in the report
    tls = verdict["height_timelines"]
    assert tls and tls[-1]["height"] >= 20
    assert any(t.get("phases") for t in tls)
    # ONE merged trace document, flow chain crossing node boundaries
    doc = json.load(open(trace_path))
    procs = {
        e["args"]["name"] for e in doc["traceEvents"]
        if e.get("ph") == "M" and e.get("name") == "process_name"
    }
    assert {"sim0", "sim1", "sim2", "sim3"} <= procs
    chains = tr.flow_chains(doc)
    full = [
        evs for evs in chains.values()
        if [e["name"] for e in evs][0] == "gossip.send"
        and evs[-1]["name"] == "consensus.verify_dispatch"
        and len({e["pid"] for e in evs}) > 1
    ]
    assert full, "no cross-node gossip send -> deliver -> verify chain"


def test_disabled_overhead_guard_covers_flow_spans():
    """Satellite 6: the <2% tracing-disabled overhead guard, extended to
    flow-carrying span sites, wired tier-1 without the OpenSSL wheel —
    the reference cost is a single pure-Python ed25519 verify (~3 ms,
    ~20x STRICTER than the device-batch wall clock the in-wheel guard
    divides by)."""
    code = r"""
import time
from tendermint_tpu.crypto import ed25519
from tendermint_tpu.observability import trace as tr

sk = ed25519.gen_priv_key(b"\x07" * 32)
msg = b"overhead-guard"
sig = sk.sign(msg)
assert ed25519.verify_zip215_fast(sk.pub_key().bytes(), msg, sig)
t0 = time.perf_counter()
for _ in range(10):
    ed25519.verify_zip215_fast(sk.pub_key().bytes(), msg, sig)
verify_s = (time.perf_counter() - t0) / 10

assert not tr.TRACER.enabled
n = 20000
t0 = time.perf_counter()
for i in range(n):
    with tr.span("x", n=64, bucket=128, flow=123, flow_phase="t"):
        pass
    tr.TRACER.flow_point("pipeline.submit", 123, "s", n=64)
per_site = (time.perf_counter() - t0) / (2 * n)
# ~10 instrument sites fire per verify_batch dispatch
assert per_site * 10 < 0.02 * verify_s, (per_site, verify_s)
print("OK", per_site, verify_s)
"""
    r = subprocess.run(
        [sys.executable, "-c", code],
        capture_output=True, env=_purepy_env(), cwd=REPO, timeout=120,
    )
    out = (r.stdout or b"").decode(errors="replace")
    err = (r.stderr or b"").decode(errors="replace")
    assert r.returncode == 0 and "OK" in out, f"{out}\n{err[-2000:]}"
