"""Fleet wire-format round-trips and adversarial frames (ISSUE 18).

Every EntryBlock column must survive encode → (fragmented) decode →
parse byte-for-byte — including empty blocks, slices, concats, the
epoch-metadata tail and frames at the size ceiling — and every
malformed, truncated or version-skewed frame must be REJECTED with the
right exception class without corrupting the decoder's stream state.
Pure host-side: numpy only, no jax, no crypto wheel.
"""

import numpy as np
import pytest

try:
    from tendermint_tpu.fleet import wire
except ModuleNotFoundError:
    # importing tendermint_tpu.ops (EntryBlock's package) pulls the
    # crypto stack; without the cryptography wheel this module re-runs
    # in a purepy subprocess via test_fleet_isolated.py
    pytest.skip(
        "ops stack unavailable (runs via test_fleet_isolated.py)",
        allow_module_level=True,
    )
from tendermint_tpu.ops.entry_block import EntryBlock  # noqa: E402


def make_block(n=8, msg_len=40, epoch=False, seed=0):
    rng = np.random.RandomState(seed)
    msgs = bytes(rng.randint(0, 256, msg_len * n, dtype=np.uint8))
    blk = EntryBlock(
        rng.randint(0, 256, (n, 32), dtype=np.uint8),
        rng.randint(0, 256, (n, 64), dtype=np.uint8),
        msgs,
        np.arange(0, msg_len * (n + 1), msg_len, dtype=np.int64),
        val_idx=(np.arange(n, dtype=np.int32) if epoch else None),
        epoch_key=(b"wire-test-epoch" if epoch else None),
    )
    return blk


def encode_bytes(rid, blk, **kw):
    return b"".join(bytes(b) for b in wire.encode_submit(rid, blk, **kw))


def roundtrip(rid, blk, **kw):
    dec = wire.FrameDecoder()
    payloads = dec.feed(encode_bytes(rid, blk, **kw))
    assert len(payloads) == 1 and dec.pending == 0
    return wire.parse_frame(payloads[0])


def assert_blocks_equal(a: EntryBlock, b: EntryBlock):
    assert len(a) == len(b)
    assert np.array_equal(a.pub, b.pub)
    assert np.array_equal(a.sig, b.sig)
    am, ao = a.msgs_contiguous()
    bm, bo = b.msgs_contiguous()
    assert bytes(am) == bytes(bm)
    assert np.array_equal(ao, bo)
    assert a.epoch_key == b.epoch_key
    if a.val_idx is None:
        assert b.val_idx is None
    else:
        assert np.array_equal(a.val_idx, b.val_idx)
    # the per-entry view agrees too (offsets decoded correctly)
    for i in range(len(a)):
        assert a.entry(i) == b.entry(i)


class TestRoundTrip:
    def test_every_column_survives(self):
        blk = make_block(16)
        f = roundtrip(7, blk, flow=123, priority=2, lane="mempool")
        assert isinstance(f, wire.SubmitFrame)
        assert (f.request_id, f.flow, f.priority, f.lane) == (
            7, 123, 2, "mempool")
        assert_blocks_equal(blk, f.block)

    def test_epoch_metadata_tail(self):
        blk = make_block(8, epoch=True)
        f = roundtrip(1, blk)
        assert f.block.epoch_key == b"wire-test-epoch"
        assert np.array_equal(f.block.val_idx,
                              np.arange(8, dtype=np.int32))

    def test_empty_block(self):
        blk = EntryBlock(
            np.zeros((0, 32), dtype=np.uint8),
            np.zeros((0, 64), dtype=np.uint8),
            b"", np.zeros(1, dtype=np.int64))
        f = roundtrip(9, blk)
        assert len(f.block) == 0

    def test_sliced_block(self):
        # a slice's columns are views with nonzero offsets — the encoder
        # must serialize the window, not the parent buffer
        blk = make_block(12)[3:9]
        f = roundtrip(2, blk)
        assert_blocks_equal(blk, f.block)

    def test_concat_block(self):
        a, b = make_block(5, epoch=True, seed=1), make_block(7, epoch=True,
                                                             seed=2)
        blk = EntryBlock.concat([a, b])
        f = roundtrip(3, blk)
        assert_blocks_equal(blk, f.block)

    def test_varlen_messages(self):
        lens = [0, 1, 17, 300, 5]
        msgs = [bytes([i]) * ln for i, ln in enumerate(lens)]
        blk = EntryBlock.from_entries([
            (bytes([i]) * 32, m, bytes([i]) * 64)
            for i, m in enumerate(msgs)
        ])
        f = roundtrip(4, blk)
        assert_blocks_equal(blk, f.block)

    def test_max_size_frame_roundtrips_and_one_past_raises(self, monkeypatch):
        # shrink the ceiling so the test stays cheap; min clamp is 4096
        monkeypatch.setenv("TM_TPU_FLEET_MAX_FRAME", "4096")
        assert wire.max_frame_bytes() == 4096
        # binary-search the largest n that still fits, prove it survives
        fits = 0
        for n in range(1, 40):
            try:
                roundtrip(1, make_block(n))
                fits = n
            except wire.OversizeFrame:
                break
        assert fits > 0
        with pytest.raises(wire.OversizeFrame):
            wire.encode_submit(1, make_block(fits + 1))

    def test_verdict_frame(self):
        v = np.array([True, False, True, True], dtype=bool)
        f = wire.parse_frame(
            wire.encode_verdicts(42, v)[4:])  # strip length prefix
        assert isinstance(f, wire.VerdictFrame)
        assert f.request_id == 42
        assert f.verdicts.dtype == bool and np.array_equal(f.verdicts, v)

    def test_error_frame(self):
        f = wire.parse_frame(
            wire.encode_error(13, wire.ERR_DISPATCH, "boom: bad batch")[4:])
        assert isinstance(f, wire.ErrorFrame)
        assert (f.request_id, f.code, f.message) == (
            13, wire.ERR_DISPATCH, "boom: bad batch")


class TestIncrementalDecode:
    def test_byte_at_a_time(self):
        blk = make_block(6, epoch=True)
        raw = encode_bytes(5, blk, lane="votes")
        dec = wire.FrameDecoder()
        got = []
        for i in range(len(raw)):
            got += dec.feed(raw[i:i + 1])
        assert len(got) == 1 and dec.pending == 0
        assert_blocks_equal(blk, wire.parse_frame(got[0]).block)
        dec.eof()  # clean EOF at a frame boundary

    def test_many_frames_one_chunk(self):
        raw = b"".join(encode_bytes(i, make_block(3, seed=i))
                       for i in range(5))
        raw += wire.encode_verdicts(99, np.ones(3, dtype=bool))
        dec = wire.FrameDecoder()
        frames = [wire.parse_frame(p) for p in dec.feed(raw)]
        assert [f.request_id for f in frames] == [0, 1, 2, 3, 4, 99]

    def test_eof_mid_frame_is_truncated(self):
        raw = encode_bytes(1, make_block(4))
        dec = wire.FrameDecoder()
        assert dec.feed(raw[:-3]) == []
        with pytest.raises(wire.TruncatedFrame):
            dec.eof()


class TestAdversarialFrames:
    """Each rejection must leave the DECODER usable: framing came from
    the length prefix, so a bad payload is one frame's problem, not the
    stream's (the server replies with an ERROR frame and carries on)."""

    def _feed_one(self, dec, payload):
        return dec.feed(wire._LEN.pack(len(payload)) + payload)

    def test_bad_magic(self):
        dec = wire.FrameDecoder()
        (p,) = self._feed_one(dec, b"NOPE" + b"\x00" * 20)
        with pytest.raises(wire.WireError, match="bad magic"):
            wire.parse_frame(p)
        # ... and the NEXT frame on the same decoder parses fine
        (p2,) = dec.feed(encode_bytes(8, make_block(2)))
        assert wire.parse_frame(p2).request_id == 8

    def test_version_skew(self):
        raw = encode_bytes(1, make_block(2))
        payload = bytearray(raw[4:])
        payload[4:6] = (99).to_bytes(2, "little")  # version field
        with pytest.raises(wire.VersionSkew) as ei:
            wire.parse_frame(bytes(payload))
        assert ei.value.got == 99

    def test_unknown_kind(self):
        raw = encode_bytes(1, make_block(2))
        payload = bytearray(raw[4:])
        payload[6] = 77  # kind byte
        with pytest.raises(wire.WireError, match="unknown frame kind"):
            wire.parse_frame(bytes(payload))

    @pytest.mark.parametrize("cut", [6, 20, 40])
    def test_truncated_payload(self, cut):
        payload = encode_bytes(1, make_block(4))[4:]
        with pytest.raises(wire.WireError):
            wire.parse_frame(payload[:cut])

    def test_trailing_junk(self):
        payload = encode_bytes(1, make_block(4))[4:] + b"JUNK"
        with pytest.raises(wire.WireError, match="trailing junk"):
            wire.parse_frame(payload)

    def test_offsets_must_start_at_zero(self):
        blk = make_block(4)
        payload = bytearray(encode_bytes(1, blk)[4:])
        # offsets column sits after header+meta+lane+shape+pub+sig
        base = (wire._HDR.size + wire._SUBMIT_META.size
                + wire._SUBMIT_SHAPE.size + 4 * 32 + 4 * 64)
        payload[base:base + 8] = (1).to_bytes(8, "little")
        with pytest.raises(wire.WireError, match="offsets"):
            wire.parse_frame(bytes(payload))

    def test_offsets_must_be_nondecreasing(self):
        blk = make_block(4)
        payload = bytearray(encode_bytes(1, blk)[4:])
        base = (wire._HDR.size + wire._SUBMIT_META.size
                + wire._SUBMIT_SHAPE.size + 4 * 32 + 4 * 64)
        # swap offsets[1] and offsets[2] to break monotonicity
        payload[base + 8:base + 16] = (80).to_bytes(8, "little")
        payload[base + 16:base + 24] = (40).to_bytes(8, "little")
        with pytest.raises(wire.WireError, match="non-decreasing"):
            wire.parse_frame(bytes(payload))

    def test_oversize_length_prefix_kills_framing(self):
        dec = wire.FrameDecoder(max_frame=4096)
        with pytest.raises(wire.OversizeFrame):
            dec.feed(wire._LEN.pack(1 << 30) + b"x" * 64)

    def test_non_utf8_lane(self):
        blk = make_block(2)
        payload = bytearray(encode_bytes(1, blk, lane="ab")[4:])
        lane_off = wire._HDR.size + wire._SUBMIT_META.size
        payload[lane_off:lane_off + 2] = b"\xff\xfe"
        with pytest.raises(wire.WireError, match="utf-8"):
            wire.parse_frame(bytes(payload))
