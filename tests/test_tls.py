"""TLS on the JSON-RPC server and clients (VERDICT r3 item 7).

Reference parity: rpc/jsonrpc/server/http_server.go ServeTLS — the same
handler tree (HTTP JSON-RPC + the /websocket upgrade) served over TLS when
the config names a cert/key pair; clients pin the CA.
"""

import datetime
import json
import ssl
import urllib.request

import pytest

from tendermint_tpu.config import Config
from tendermint_tpu.crypto import ed25519
from tendermint_tpu.node import make_node
from tendermint_tpu.abci import KVStoreApplication
from tendermint_tpu.privval import FilePV
from tendermint_tpu.p2p import NodeKey
from tendermint_tpu.types.genesis import GenesisDoc, GenesisValidator
from tendermint_tpu.wire.canonical import Timestamp
from tests.test_node_rpc import CHAIN, FAST


def _self_signed_cert(tmp_path):
    """Generate a self-signed localhost certificate (test CA == leaf)."""
    from cryptography import x509
    from cryptography.hazmat.primitives import hashes, serialization
    from cryptography.hazmat.primitives.asymmetric import rsa
    from cryptography.x509.oid import NameOID

    key = rsa.generate_private_key(public_exponent=65537, key_size=2048)
    name = x509.Name([x509.NameAttribute(NameOID.COMMON_NAME, "127.0.0.1")])
    now = datetime.datetime.now(datetime.timezone.utc)
    cert = (
        x509.CertificateBuilder()
        .subject_name(name)
        .issuer_name(name)
        .public_key(key.public_key())
        .serial_number(x509.random_serial_number())
        .not_valid_before(now - datetime.timedelta(minutes=5))
        .not_valid_after(now + datetime.timedelta(days=1))
        .add_extension(
            x509.SubjectAlternativeName(
                [x509.DNSName("localhost"),
                 x509.IPAddress(__import__("ipaddress").ip_address("127.0.0.1"))]
            ),
            critical=False,
        )
        .sign(key, hashes.SHA256())
    )
    cert_path = tmp_path / "rpc.crt"
    key_path = tmp_path / "rpc.key"
    cert_path.write_bytes(cert.public_bytes(serialization.Encoding.PEM))
    key_path.write_bytes(
        key.private_bytes(
            serialization.Encoding.PEM,
            serialization.PrivateFormat.TraditionalOpenSSL,
            serialization.NoEncryption(),
        )
    )
    return str(cert_path), str(key_path)


@pytest.fixture
def tls_node(tmp_path):
    cert, key = _self_signed_cert(tmp_path)
    sk = ed25519.gen_priv_key(bytes([9]) * 32)
    doc = GenesisDoc(
        chain_id=CHAIN,
        genesis_time=Timestamp(seconds=1_700_000_000),
        validators=[GenesisValidator(address=b"", pub_key=sk.pub_key(), power=10)],
    )
    cfg = Config()
    cfg.base.home = ""
    cfg.base.db_backend = "memdb"
    cfg.consensus = FAST
    cfg.p2p.laddr = "tcp://127.0.0.1:0"
    cfg.rpc.laddr = "tcp://127.0.0.1:0"
    cfg.rpc.tls_cert_file = cert
    cfg.rpc.tls_key_file = key
    node = make_node(
        cfg,
        app=KVStoreApplication(),
        genesis=doc,
        priv_validator=FilePV(sk),
        node_key=NodeKey.generate(bytes([77]) * 32),
        with_rpc=True,
    )
    node.start()
    try:
        yield node, cert
    finally:
        node.stop()


class TestRPCOverTLS:
    def test_https_rpc_and_plaintext_rejected(self, tls_node):
        from tendermint_tpu.rpc.client import HTTPClient

        node, ca = tls_node
        assert node.rpc_server.tls
        addr = node.rpc_server.listen_addr
        node.wait_for_height(1, timeout=60)

        c = HTTPClient(f"https://{addr}", ca_file=ca)
        st = c.status()
        assert int(st["sync_info"]["latest_block_height"]) >= 1
        assert c.health() == {}

        # an unpinned default context must REFUSE the self-signed cert
        with pytest.raises(Exception):
            urllib.request.urlopen(f"https://{addr}/health", timeout=10)

        # plaintext HTTP against the TLS listener cannot produce a result
        with pytest.raises(Exception):
            with urllib.request.urlopen(f"http://{addr}/health", timeout=10) as r:
                json.loads(r.read())

    def test_wss_subscribe(self, tls_node):
        from tendermint_tpu.rpc.client import WSClient

        node, ca = tls_node
        node.wait_for_height(1, timeout=60)
        c = WSClient(f"wss://{node.rpc_server.listen_addr}", ca_file=ca)
        try:
            st = c.call("status")
            assert int(st["sync_info"]["latest_block_height"]) >= 1
            c.subscribe("tm.event='NewBlock'")
            ev = c.next_event(timeout=30)
            assert ev["query"] == "tm.event='NewBlock'"
        finally:
            c.close()

    def test_wss_refuses_unpinned(self, tls_node):
        from tendermint_tpu.rpc.client import WSClient

        node, _ = tls_node
        with pytest.raises(ssl.SSLError):
            WSClient(f"wss://{node.rpc_server.listen_addr}")
