"""WebSocket subscriptions + indexed search over a running node."""

import base64
import json
import socket
import time

import pytest

from tendermint_tpu.rpc.websocket import OP_TEXT, encode_frame, read_frame
from tests.test_node_rpc import two_node_net  # noqa: F401 — fixture


def _ws_connect(addr: str):
    host, _, port = addr.rpartition(":")
    sock = socket.create_connection((host, int(port)), timeout=10)
    key = base64.b64encode(b"0123456789abcdef").decode()
    req = (
        f"GET /websocket HTTP/1.1\r\nHost: {host}\r\n"
        "Upgrade: websocket\r\nConnection: Upgrade\r\n"
        f"Sec-WebSocket-Key: {key}\r\nSec-WebSocket-Version: 13\r\n\r\n"
    )
    sock.sendall(req.encode())
    # read the 101 response headers
    buf = b""
    while b"\r\n\r\n" not in buf:
        buf += sock.recv(4096)
    assert b"101" in buf.split(b"\r\n")[0]
    return sock


def _ws_send_json(sock, obj) -> None:
    payload = json.dumps(obj).encode()
    # client frames must be masked
    import os
    import struct

    mask = os.urandom(4)
    n = len(payload)
    head = bytes([0x80 | OP_TEXT])
    if n < 126:
        head += bytes([0x80 | n])
    else:
        head += bytes([0x80 | 126]) + struct.pack(">H", n)
    masked = bytes(b ^ mask[i % 4] for i, b in enumerate(payload))
    sock.sendall(head + mask + masked)


def _ws_recv_json(sock, timeout=15.0):
    sock.settimeout(timeout)
    rfile = sock.makefile("rb")
    frame = read_frame(rfile)
    assert frame is not None
    opcode, payload = frame
    assert opcode == OP_TEXT
    return json.loads(payload)


class TestWebSocket:
    def test_subscribe_new_block(self, two_node_net):  # noqa: F811
        nodes = two_node_net
        nodes[0].wait_for_height(1, timeout=60)
        sock = _ws_connect(nodes[0].rpc_server.listen_addr)
        try:
            _ws_send_json(
                sock,
                {
                    "jsonrpc": "2.0",
                    "id": 1,
                    "method": "subscribe",
                    "params": {"query": "tm.event='NewBlock'"},
                },
            )
            ack = _ws_recv_json(sock)
            assert ack["id"] == 1 and "result" in ack
            ev = _ws_recv_json(sock, timeout=30)
            assert ev["result"]["query"] == "tm.event='NewBlock'"
            assert "tm.event" in ev["result"]["events"]
        finally:
            sock.close()

    def test_rpc_method_over_websocket(self, two_node_net):  # noqa: F811
        nodes = two_node_net
        nodes[0].wait_for_height(1, timeout=60)
        sock = _ws_connect(nodes[0].rpc_server.listen_addr)
        try:
            _ws_send_json(sock, {"jsonrpc": "2.0", "id": 9, "method": "status", "params": {}})
            resp = _ws_recv_json(sock)
            assert resp["id"] == 9
            assert int(resp["result"]["sync_info"]["latest_block_height"]) >= 1
        finally:
            sock.close()


class TestTxSearch:
    def test_tx_search_and_block_search(self, two_node_net):  # noqa: F811
        nodes = two_node_net
        from tendermint_tpu.rpc import HTTPClient

        rpc = HTTPClient(nodes[0].rpc_server.listen_addr)
        res = rpc.broadcast_tx_commit(b"searchme=yes")
        height = int(res["height"])
        deadline = time.time() + 10
        hits = None
        while time.time() < deadline:
            hits = rpc.call("tx_search", query=f"tx.height={height}")
            if int(hits["total_count"]) > 0:
                break
            time.sleep(0.2)
        assert hits and int(hits["total_count"]) >= 1
        assert base64.b64decode(hits["txs"][0]["tx"]) == b"searchme=yes"
        # event-key search (kvstore emits app.creator)
        hits2 = rpc.call("tx_search", query="app.creator='Cosmoshi Netowoko'")
        assert int(hits2["total_count"]) >= 1
        blocks = rpc.call("block_search", query=f"block.height='{height}'")
        assert int(blocks["total_count"]) >= 1


class TestQueryGrammar:
    """pubsub query grammar parity (libs/pubsub/query/query.peg):
    EXISTS / CONTAINS / ordering comparisons through the kv sink search,
    not just equality."""

    def _sink(self):
        from tendermint_tpu.db import MemDB
        from tendermint_tpu.indexer import KVSink

        class _R:
            code = 0
            data = b""
            log = ""
            gas_wanted = 0
            gas_used = 0

        sink = KVSink(MemDB())
        sink.index_tx(5, 0, b"tx-a", _R(), {"transfer.amount": ["100"], "transfer.to": ["alice-addr"]})
        sink.index_tx(6, 0, b"tx-b", _R(), {"transfer.amount": ["250"], "transfer.to": ["bob-addr"]})
        sink.index_tx(7, 0, b"tx-c", _R(), {"mint.amount": ["9"]})
        return sink

    def test_exists(self):
        sink = self._sink()
        out = sink.search_txs("transfer.amount EXISTS")
        assert {r["height"] for r in out} == {5, 6}

    def test_contains(self):
        sink = self._sink()
        out = sink.search_txs("transfer.to CONTAINS 'bob'")
        assert [r["height"] for r in out] == [6]

    def test_ordering_comparisons(self):
        sink = self._sink()
        assert [r["height"] for r in sink.search_txs("transfer.amount > 150")] == [6]
        assert [r["height"] for r in sink.search_txs("transfer.amount <= 100")] == [5]
        assert [r["height"] for r in sink.search_txs("tx.height >= 6 AND transfer.amount EXISTS")] == [6]


class TestWSClient:
    """Library websocket client (rpc/jsonrpc/client/ws_client.go +
    rpc/client/http Subscribe): calls and event subscription through one
    connection, no hand-rolled frames."""

    def test_ws_client_subscribe_and_call(self, two_node_net):  # noqa: F811
        from tendermint_tpu.rpc.client import WSClient

        nodes = two_node_net
        nodes[0].wait_for_height(1, timeout=60)
        c = WSClient(nodes[0].rpc_server.listen_addr)
        try:
            # plain JSON-RPC call over the socket
            st = c.call("status")
            assert int(st["sync_info"]["latest_block_height"]) >= 1
            # subscription stream
            c.subscribe("tm.event='NewBlock'")
            ev = c.next_event(timeout=30)
            assert ev["query"] == "tm.event='NewBlock'"
            assert "tm.event" in ev["events"]
            ev2 = c.next_event(timeout=30)
            assert ev2["query"] == "tm.event='NewBlock'"
            c.unsubscribe_all()
        finally:
            c.close()
