"""Chain-replay catch-up (ISSUE 14): the range-batched ReplayEngine and
the blocksync speculation/wake-event satellites.

Covers: epoch-cut planning off header validators_hash, range verification
+ apply over a real hand-signed chain (device path through the shared
pipeline), mid-range forged-commit fallback with error-string parity vs
sequential verify_commit_light, valset rotation across ranges, the
writer-thread save pipeline, speculation-invalidation edges (valset
change at the speculated height, redo_request racing a pending future,
narrow DispatchError/TimeoutError handling with hit/miss/discard
metrics), and the no-hot-spin guard for the wake-event loops.

Needs a working ed25519 signer: with the `cryptography` wheel the module
runs directly; without it, tests/test_replay_isolated.py re-runs it in a
subprocess under TM_TPU_PUREPY_CRYPTO=1.
"""

import importlib.util
import os
import queue
import sys
import threading
import time

import pytest

if importlib.util.find_spec("cryptography") is None and not os.environ.get(
    "TM_TPU_PUREPY_CRYPTO"
):
    pytest.skip(
        "needs an ed25519 signer (cryptography wheel or the isolated runner)",
        allow_module_level=True,
    )

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO_ROOT not in sys.path:
    sys.path.insert(0, REPO_ROOT)

from tendermint_tpu.blocksync import (  # noqa: E402
    BlockPool,
    BlockSyncReactor,
)
from tendermint_tpu.blocksync.replay import (  # noqa: E402
    ReplayEngine,
    plan_epoch_range,
)
from tendermint_tpu.crypto import ed25519  # noqa: E402
from tendermint_tpu.libs import metrics as _metrics_mod  # noqa: E402
from tendermint_tpu.types import Validator, ValidatorSet  # noqa: E402
from tendermint_tpu.types.block import (  # noqa: E402
    Block,
    BlockID,
    Data,
    Header,
    PartSetHeader,
    Version,
)
from tendermint_tpu.types.part_set import (  # noqa: E402
    BLOCK_PART_SIZE_BYTES,
    PartSet,
)
from tendermint_tpu.types.validation import verify_commit_light  # noqa: E402
from tendermint_tpu.types.vote import PRECOMMIT_TYPE, Vote  # noqa: E402
from tendermint_tpu.types.vote_set import VoteSet  # noqa: E402
from tendermint_tpu.wire.canonical import Timestamp  # noqa: E402

CHAIN_ID = "replay-chain"


def _make_vals(n, seed):
    pairs = []
    for i in range(n):
        sk = ed25519.gen_priv_key(bytes([seed + i]) * 32)
        pairs.append((sk, Validator.new(sk.pub_key(), 100)))
    vset = ValidatorSet.new([v for _, v in pairs])
    by_addr = {v.address: sk for sk, v in pairs}
    return [by_addr[v.address] for v in vset.validators], vset


def _sign_vote(sk, vset, height, block_id):
    addr = sk.pub_key().address()
    idx, _ = vset.get_by_address(addr)
    vote = Vote(
        type=PRECOMMIT_TYPE,
        height=height,
        round=0,
        block_id=block_id,
        timestamp=Timestamp(seconds=1_600_000_000, nanos=0),
        validator_address=addr,
        validator_index=idx,
    )
    sig = sk.sign(vote.sign_bytes(CHAIN_ID))
    return Vote(**{**vote.__dict__, "signature": sig})


def _make_chain(n_blocks, n_vals=4, rotate_at=()):
    """Full blocks 1..n_blocks with real commit linkage: block h+1's
    last_commit signs block h's BlockID (hash + part-set header of the
    encoded block). `rotate_at` heights switch to a fresh validator set
    from that height onward. Returns (blocks, vals_at, keys_at)."""
    rotate_at = sorted(rotate_at)
    vals_at, keys_at = {}, {}
    seed, cur = 1, _make_vals(n_vals, 1)
    for h in range(1, n_blocks + 2):
        if h in rotate_at:
            seed += n_vals
            cur = _make_vals(n_vals, seed)
        keys_at[h], vals_at[h] = cur
    blocks = []
    last_commit = None
    prev_bid = BlockID()
    for h in range(1, n_blocks + 1):
        hdr = Header(
            version=Version(block=11, app=0),
            chain_id=CHAIN_ID,
            height=h,
            time=Timestamp(seconds=1_600_000_000 + h),
            last_block_id=prev_bid,
            validators_hash=vals_at[h].hash(),
            next_validators_hash=vals_at[h + 1].hash(),
            consensus_hash=b"\x01" * 32,
            app_hash=b"",
            proposer_address=vals_at[h].validators[0].address,
        )
        block = Block(header=hdr, data=Data(), last_commit=last_commit)
        block.fill_header()
        parts = PartSet.from_data(block.encode(), BLOCK_PART_SIZE_BYTES)
        bid = BlockID(hash=block.hash(), part_set_header=parts.header())
        vs = VoteSet(CHAIN_ID, h, 0, PRECOMMIT_TYPE, vals_at[h])
        for sk in keys_at[h]:
            vs.add_vote(_sign_vote(sk, vals_at[h], h, bid))
        last_commit = vs.make_commit()
        prev_bid = bid
        blocks.append(block)
    return blocks, vals_at, keys_at


class _State:
    def __init__(self, validators, height):
        self.chain_id = CHAIN_ID
        self.validators = validators
        self.last_block_height = height


def _run_engine(blocks, vals_at, engine=None, start=0):
    """Drive an engine over the whole chain like the reactor would:
    peek-run, replay, repeat. Returns (state, saves, outcomes)."""
    eng = engine or ReplayEngine(synchronous=True)
    st = _State(vals_at[blocks[start].header.height], blocks[start].header.height - 1)
    saves = []

    def _save(block, parts, seen_commit):
        saves.append((block.header.height, seen_commit.height))

    def _apply(bid, block):
        h = block.header.height
        st.last_block_height = h
        st.validators = vals_at[h + 1]
        return st

    outcomes = []
    i = start
    while i < len(blocks) - 1:
        st2, out = eng.replay_blocks(st, blocks[i:], _save, _apply)
        outcomes.append(out)
        if out.applied == 0:
            break
        i += out.applied
    eng.close()
    return st, saves, outcomes


# -- epoch-cut planner ----------------------------------------------------


class TestEpochPlanner:
    def test_cut_at_rotation(self):
        blocks, _, _ = _make_chain(12, n_vals=2, rotate_at=(6,))
        # heights 1..5 share block 1's validators_hash; block 6 differs
        assert plan_epoch_range(blocks, 64) == 5
        assert plan_epoch_range(blocks[5:], 64) == 6  # 6..11 (12 carries commit)

    def test_window_limit_and_short_runs(self):
        blocks, _, _ = _make_chain(10, n_vals=2)
        assert plan_epoch_range(blocks, 4) == 4
        assert plan_epoch_range(blocks[:2], 64) == 1
        assert plan_epoch_range(blocks[:1], 64) == 0
        assert plan_epoch_range([], 64) == 0

    def test_cut_at_next_validators_hash_announcement(self):
        # a header announcing a valset change via next_validators_hash
        # ends the range after its height even when later headers keep
        # claiming the old validators_hash (an inconsistent/forged chain)
        import dataclasses

        blocks, _, _ = _make_chain(10, n_vals=2)
        blocks[3] = dataclasses.replace(
            blocks[3],
            header=dataclasses.replace(
                blocks[3].header, next_validators_hash=b"\x07" * 32
            ),
        )
        assert plan_epoch_range(blocks, 64) == 4


# -- the range engine over a real signed chain ----------------------------


class TestReplayEngine:
    def test_replays_whole_chain_device_path(self):
        # prepare_commit_light stops at 2/3-of-power, so 8 vals give ~6
        # entries per height: 19 heights × 6 = 114 sigs ≥ DEVICE_THRESHOLD
        # — the range goes through the shared pipeline as superbatches
        blocks, vals_at, _ = _make_chain(20, n_vals=8)
        eng = ReplayEngine(synchronous=True)
        st, saves, outs = _run_engine(blocks, vals_at, engine=eng)
        assert st.last_block_height == 19
        assert [h for h, _ in saves] == list(range(1, 20))
        # every save carried the NEXT block's commit as seen-commit
        assert all(seen == h for h, seen in saves)
        assert sum(o.applied for o in outs) == 19
        assert eng.range_heights == 19
        assert eng.sequential_heights == 0
        assert eng.sigs_submitted >= 64

    def test_sub_threshold_range_stays_on_host(self):
        blocks, vals_at, _ = _make_chain(6, n_vals=2)  # 10 sigs < 64
        eng = ReplayEngine(synchronous=True)
        st, _, _ = _run_engine(blocks, vals_at, engine=eng)
        assert st.last_block_height == 5
        assert eng.range_heights == 0
        assert eng.sequential_heights == 5

    def test_rotation_chain_cuts_and_crosses_epochs(self):
        blocks, vals_at, _ = _make_chain(24, n_vals=4, rotate_at=(9, 17))
        eng = ReplayEngine(synchronous=True)
        st, saves, outs = _run_engine(blocks, vals_at, engine=eng)
        assert st.last_block_height == 23
        assert [h for h, _ in saves] == list(range(1, 24))
        # three epochs → at least three replay_blocks rounds
        assert len([o for o in outs if o.applied]) >= 3

    def test_forged_commit_mid_range_error_parity(self):
        # 23 verifiable heights × 4 sigs = 92 ≥ DEVICE_THRESHOLD: the
        # range really goes to the device, fails there, and falls back
        blocks, vals_at, _ = _make_chain(24, n_vals=4)
        bad_h = 12
        # forge one signature in the commit that vouches for height 8
        commit = blocks[bad_h].last_commit  # block 9 carries h=8's commit
        sig = commit.signatures[0]
        forged = sig.__class__(
            block_id_flag=sig.block_id_flag,
            validator_address=sig.validator_address,
            timestamp=sig.timestamp,
            signature=bytes(64),
        )
        commit.signatures[0] = forged
        eng = ReplayEngine(synchronous=True)
        st, saves, outs = _run_engine(blocks, vals_at, engine=eng)
        # heights before the forgery applied; the bad one rejected
        assert st.last_block_height == bad_h - 1
        bad = [o for o in outs if o.failed_height is not None]
        assert bad and bad[-1].failed_height == bad_h
        # error string byte-identical to the sequential path's
        p = PartSet.from_data(blocks[bad_h - 1].encode(), BLOCK_PART_SIZE_BYTES)
        bid = BlockID(hash=blocks[bad_h - 1].hash(), part_set_header=p.header())
        with pytest.raises((ValueError, RuntimeError)) as ei:
            verify_commit_light(
                CHAIN_ID, vals_at[bad_h], bid, bad_h,
                blocks[bad_h].last_commit,
            )
        assert bad[-1].error == str(ei.value)

    def test_flight_recorder_flow_chain(self):
        # satellite 6: one flow id rides a range end to end —
        # blocksync.fetch (s) → replay.range_pack (t, heights attached)
        # → pipeline.submit/dispatch → replay.apply (f)
        from tendermint_tpu.observability import trace as tr

        blocks, vals_at, _ = _make_chain(20, n_vals=8)
        tr.configure(enabled=True)
        try:
            eng = ReplayEngine(synchronous=True)
            _run_engine(blocks, vals_at, engine=eng)
            doc = tr.TRACER.export_chrome()
        finally:
            tr.configure(enabled=False)
        chains = [
            [e["name"] for e in evs]
            for evs in tr.flow_chains(doc).values()
            if evs[0]["name"] == "blocksync.fetch"
        ]
        assert chains, "no replay flow chains recorded"
        full = [
            names for names in chains
            if "replay.range_pack" in names
            and "pipeline.submit" in names
            and names[-1] == "replay.apply"
        ]
        assert full, chains
        packs = [
            ev for ev in doc["traceEvents"]
            if ev.get("name") == "replay.range_pack" and ev.get("ph") == "X"
        ]
        assert packs and all(
            ev["args"].get("heights", 0) > 0 for ev in packs
        ), packs

    def test_writer_thread_orders_saves(self):
        blocks, vals_at, _ = _make_chain(12, n_vals=4)
        eng = ReplayEngine()  # asynchronous: saves ride the writer thread
        heights = []
        lock = threading.Lock()
        st = _State(vals_at[1], 0)

        def _save(block, parts, seen_commit):
            with lock:
                if heights and block.header.height != heights[-1] + 1:
                    raise AssertionError("out-of-order save")
                heights.append(block.header.height)

        def _apply(bid, block):
            st.last_block_height = block.header.height
            st.validators = vals_at[block.header.height + 1]
            return st

        st2, out = eng.replay_blocks(st, blocks, _save, _apply)
        eng.close()
        assert out.failed_height is None
        # replay_blocks drains the writer before returning
        assert heights == list(range(1, out.applied + 1))

    def test_writer_error_propagates(self):
        blocks, vals_at, _ = _make_chain(8, n_vals=2)
        eng = ReplayEngine()
        st = _State(vals_at[1], 0)

        def _save(block, parts, seen_commit):
            raise OSError("disk gone")

        def _apply(bid, block):
            st.last_block_height = block.header.height
            return st

        with pytest.raises(RuntimeError, match="replay writer failed"):
            eng.replay_blocks(st, blocks, _save, _apply)
        eng.close()

    def test_apply_rejection_mid_range_falls_back(self):
        # device verification accepted the range under the headers'
        # claimed epoch, but apply — the authority, re-validating under
        # live state — rejects height 9 (the forged-valset shape). The
        # engine must not persist the rejected block, must not let the
        # rejection escape (the reactor's apply thread would die), and
        # must surface failed_height/error like the sequential path so
        # the reactor redo_requests.
        blocks, vals_at, _ = _make_chain(20, n_vals=8)
        bad_h = 9
        eng = ReplayEngine(synchronous=True)
        st = _State(vals_at[1], 0)
        saves = []

        def _save(block, parts, seen_commit):
            saves.append(block.header.height)

        def _apply(bid, block):
            h = block.header.height
            if h == bad_h:
                raise ValueError("wrong Header.ValidatorsHash")
            st.last_block_height = h
            st.validators = vals_at[h + 1]
            return st

        st2, out = eng.replay_blocks(st, blocks, _save, _apply)
        eng.close()
        assert out.failed_height == bad_h
        assert out.error == "wrong Header.ValidatorsHash"
        assert saves == list(range(1, bad_h))  # rejected block never saved
        assert st.last_block_height == bad_h - 1
        assert eng.fallback_ranges >= 1

    def test_writer_put_after_close_raises_drain_never_hangs(self):
        from tendermint_tpu.blocksync.replay import _Writer

        ran = []
        w = _Writer()
        w.put(lambda *a: ran.append(a), 1, 2, 3)
        w.close()
        assert ran  # saves queued before close still run
        with pytest.raises(RuntimeError, match="closed"):
            w.put(lambda *a: ran.append(a), 4, 5, 6)
        w.drain()  # writer thread already exited: returns, no hang

    def test_consecutive_heights_enforced(self):
        blocks, vals_at, _ = _make_chain(5, n_vals=2)
        eng = ReplayEngine(synchronous=True)
        st = _State(vals_at[1], 0)
        with pytest.raises(ValueError, match="consecutive"):
            eng.replay_blocks(
                st, [blocks[0], blocks[2]], lambda *a: None, lambda *a: st
            )


# -- reactor satellites: speculation edges + wake events ------------------


class _FakeChannel:
    def broadcast(self, data):
        pass

    def send(self, peer_id, data):
        pass

    def receive(self, timeout=None):
        time.sleep(timeout or 0.1)
        raise queue.Empty


class _FakeRouter:
    def open_channel(self, desc):
        return _FakeChannel()


class _FakeStore:
    def height(self):
        return 0

    def base(self):
        return 0

    def load_block(self, height):
        return None


def _mk_reactor(vset, height=0):
    return BlockSyncReactor(
        _FakeRouter(), block_store=_FakeStore(), block_exec=None,
        initial_state=_State(vset, height),
    )


class _FakeFuture:
    def __init__(self, exc=None, value=None):
        self._exc, self._value = exc, value

    def result(self, timeout=None):
        if self._exc is not None:
            raise self._exc
        return self._value


def _spec_counts():
    m = _metrics_mod.blocksync_metrics()
    return (
        int(m.speculation_hits.total()),
        int(m.speculation_misses.total()),
        int(m.speculation_discards.total()),
    )


class TestSpeculationEdges:
    def _fixture(self):
        blocks, vals_at, _ = _make_chain(4, n_vals=2)
        first, second = blocks[1], blocks[2]  # verify height 2
        parts = PartSet.from_data(first.encode(), BLOCK_PART_SIZE_BYTES)
        first_id = BlockID(hash=first.hash(), part_set_header=parts.header())
        return blocks, vals_at, first, first_id, second

    def test_no_spec_counts_miss(self):
        _, vals_at, first, first_id, second = self._fixture()
        r = _mk_reactor(vals_at[2], 1)
        h0, m0, d0 = _spec_counts()
        assert r._take_speculation(None, first, first_id, second) is None
        h1, m1, d1 = _spec_counts()
        assert (h1 - h0, m1 - m0, d1 - d0) == (0, 1, 0)

    def test_valset_change_at_speculated_height_discards(self):
        # speculation was prepared under the OLD set; the applied block
        # rotated validators → valhash mismatch → discard, sync verify
        _, vals_at, first, first_id, second = self._fixture()
        _, old_vset = _make_vals(2, 99)
        r = _mk_reactor(vals_at[2], 1)
        spec = (
            first.header.height, old_vset, old_vset.hash(),
            first.hash(), second.hash(), _FakeFuture(value=None),
        )
        h0, m0, d0 = _spec_counts()
        assert r._take_speculation(spec, first, first_id, second) is None
        h1, m1, d1 = _spec_counts()
        assert (h1 - h0, d1 - d0) == (0, 1)

    def test_redo_request_racing_pending_future(self):
        # redo_request(h) dropped + re-fetched the blocks while a spec
        # future for h was still pending: the re-fetched block hash no
        # longer matches → the stale verdict must be discarded unused
        blocks, vals_at, first, first_id, second = self._fixture()
        r = _mk_reactor(vals_at[2], 1)
        pool = r.pool
        pool.set_peer_range("p1", 1, 4)
        pool.next_requests()
        for b in blocks:
            pool.add_block("p1", b)
        pool.height = 2
        spec = (
            first.header.height, vals_at[2], vals_at[2].hash(),
            b"\xde" * 32,  # hash of the block the spec was taken against
            second.hash(), _FakeFuture(value=None),
        )
        pool.redo_request(2)
        a, b2 = pool.peek_two_blocks()
        assert a is None and b2 is None  # both dropped, will re-fetch
        h0, m0, d0 = _spec_counts()
        assert r._take_speculation(spec, first, first_id, second) is None
        h1, m1, d1 = _spec_counts()
        assert d1 - d0 == 1

    def test_dispatch_error_and_timeout_discard(self):
        from concurrent.futures import TimeoutError as FutTimeout

        from tendermint_tpu.ops.pipeline import DispatchError

        _, vals_at, first, first_id, second = self._fixture()
        r = _mk_reactor(vals_at[2], 1)
        for exc in (DispatchError("boom", bucket=128), FutTimeout()):
            spec = (
                first.header.height, vals_at[2], vals_at[2].hash(),
                first.hash(), second.hash(), _FakeFuture(exc=exc),
            )
            h0, m0, d0 = _spec_counts()
            assert r._take_speculation(spec, first, first_id, second) is None
            h1, m1, d1 = _spec_counts()
            assert d1 - d0 == 1

    def test_unexpected_exception_propagates(self):
        _, vals_at, first, first_id, second = self._fixture()
        r = _mk_reactor(vals_at[2], 1)
        spec = (
            first.header.height, vals_at[2], vals_at[2].hash(),
            first.hash(), second.hash(), _FakeFuture(exc=KeyError("bug")),
        )
        with pytest.raises(KeyError):
            r._take_speculation(spec, first, first_id, second)

    def test_usable_verdict_counts_hit(self):
        import numpy as np

        _, vals_at, first, first_id, second = self._fixture()
        r = _mk_reactor(vals_at[2], 1)
        spec = (
            first.header.height, vals_at[2], vals_at[2].hash(),
            first.hash(), second.hash(),
            _FakeFuture(value=np.ones(2, dtype=bool)),
        )
        h0, m0, d0 = _spec_counts()
        assert r._take_speculation(spec, first, first_id, second) is True
        h1, m1, d1 = _spec_counts()
        assert h1 - h0 == 1


class TestWakeEvents:
    def test_pool_wakers_fire_on_state_changes(self):
        pool = BlockPool(1)
        ev = pool.waker()
        pool.set_peer_range("p", 1, 5)
        assert ev.is_set()
        ev.clear()
        blocks, _, _ = _make_chain(2, n_vals=2)
        pool.next_requests()
        pool.add_block("p", blocks[0])
        assert ev.is_set()
        ev.clear()
        pool.pop_first()
        assert ev.is_set()

    def test_peek_run_returns_consecutive_prefix(self):
        blocks, _, _ = _make_chain(6, n_vals=2)
        pool = BlockPool(1)
        pool.set_peer_range("p", 1, 6)
        pool.next_requests()
        for b in blocks[:2] + blocks[3:]:  # gap at height 3
            pool.add_block("p", b)
        run = pool.peek_run(10)
        assert [b.header.height for b in run] == [1, 2]

    def test_injected_clock_drives_rerequest(self):
        now = [1000.0]
        pool = BlockPool(1, clock=lambda: now[0])
        pool.set_peer_range("p1", 1, 3)
        pool.set_peer_range("p2", 1, 3)
        first = pool.next_requests()
        assert first  # initial requests issued
        assert pool.next_requests() == {}  # within the peer timeout
        now[0] += 20.0  # past _PEER_TIMEOUT on the injected clock
        assert pool.next_requests()  # re-requested without wall time

    def test_reset_to_state_rebinds_loop_wake_events(self):
        # after a statesync reset the loops must park on the NEW pool's
        # wake events — a signal on the new pool wakes them well under
        # the 1s fallback timeout (a loop still caching the old event
        # would only advance on timeout polls)
        _, vset = _make_vals(2, 1)
        r = _mk_reactor(vset, 0)
        r.start()
        try:
            r.reset_to_state(_State(vset, 100))
            # wait for an iteration AFTER the reset: the loop re-reads
            # the wake event at the top of every iteration
            before = r.loop_wakes["request"]
            deadline = time.time() + 3.0
            while time.time() < deadline and r.loop_wakes["request"] == before:
                time.sleep(0.01)
            assert r.loop_wakes["request"] > before
            # it is now parked on the new pool's event
            before = r.loop_wakes["request"]
            r.pool.set_peer_range("p", 1, 200)
            deadline = time.time() + 0.4
            while time.time() < deadline and r.loop_wakes["request"] == before:
                time.sleep(0.01)
            assert r.loop_wakes["request"] > before, (
                "request loop missed the new pool's wake event"
            )
        finally:
            r.stop()

    def test_loops_do_not_hot_spin_idle(self):
        # the PR-2/PR-3 guard shape: with nothing to do, the wake-event
        # loops park on events — an idle half-second must cost a handful
        # of wakeups, not thousands of poll iterations
        _, vset = _make_vals(2, 1)
        r = _mk_reactor(vset, 0)
        r.start()
        try:
            time.sleep(0.6)
            assert r.loop_wakes["request"] < 20, r.loop_wakes
            assert r.loop_wakes["apply"] < 20, r.loop_wakes
            assert r.loop_wakes["status"] < 5, r.loop_wakes
        finally:
            r.stop()
