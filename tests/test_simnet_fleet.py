"""Simnet shared-fleet scenario (ISSUE 18 acceptance).

A 100-node cluster's verification rides ONE fleet host through the real
wire codec (loopback transport); a mid-run fleet-host crash degrades
gracefully — local-fallback verdicts, zero stalled requests — and the
run stays replay-exact. Pure host-side: the deterministic stand-in
checker needs neither jax nor the crypto wheel.
"""

import pytest

np = pytest.importorskip("numpy")

try:
    import tendermint_tpu.ops.entry_block  # noqa: F401
except ModuleNotFoundError:
    # the ops package import pulls the crypto stack; without the
    # cryptography wheel this module re-runs in a purepy subprocess via
    # test_fleet_isolated.py
    pytest.skip(
        "ops stack unavailable (runs via test_fleet_isolated.py)",
        allow_module_level=True,
    )
from tendermint_tpu.ops.entry_block import EntryBlock  # noqa: E402
from tendermint_tpu.simnet.fleet import (  # noqa: E402
    check_block,
    run_fleet_scenario,
)

KILL = dict(kill_at=4.0, revive_at=7.0)


class TestFleetScenario:
    def test_happy_path_all_fleet(self):
        rep = run_fleet_scenario(seed=3, n_nodes=20, reqs_per_node=4)
        assert rep["requests"] == 80
        assert rep["fallback_verdicts"] == 0
        assert rep["fleet_verdicts"] == 80
        assert rep["stalled_requests"] == 0
        assert rep["host"]["frames_accepted"] == 80
        # all three QoS tiers crossed the wire
        assert sorted(rep["host"]["by_priority"]) == [0, 1, 2]

    @pytest.mark.parametrize("seed", [7, 42])
    def test_replay_exact_with_crash(self, seed):
        a = run_fleet_scenario(seed=seed, **KILL)
        b = run_fleet_scenario(seed=seed, **KILL)
        assert a == b

    @pytest.mark.parametrize("seed", [7, 42])
    def test_crash_degrades_gracefully_no_stall(self, seed):
        rep = run_fleet_scenario(seed=seed, **KILL)
        assert rep["n_nodes"] == 100
        assert rep["stalled_requests"] == 0, "a fleet crash must not stall"
        assert rep["fallback_verdicts"] > 0, "crash window saw no fallbacks?"
        assert rep["fleet_verdicts"] > 0
        # revive_at < span: late requests ride the fleet again
        assert not rep["host"]["killed"]

    @pytest.mark.parametrize("seed", [7, 42])
    def test_verdict_parity_fleet_vs_all_local(self, seed):
        """Degradation moves WHERE a verdict is computed, never what it
        is: the fleet run (crash included) and the all-local run of the
        same seed produce byte-identical verdict streams."""
        fleet = run_fleet_scenario(seed=seed, **KILL)
        local = run_fleet_scenario(seed=seed, all_local=True)
        assert fleet["verdict_fingerprint"] == local["verdict_fingerprint"]
        # ... while the run fingerprints differ (sources differ)
        assert fleet["run_fingerprint"] != local["run_fingerprint"]

    def test_seeds_differ(self):
        a = run_fleet_scenario(seed=7, **KILL)
        b = run_fleet_scenario(seed=42, **KILL)
        assert a["run_fingerprint"] != b["run_fingerprint"]

    def test_permanent_crash_all_remaining_fall_back(self):
        rep = run_fleet_scenario(seed=5, n_nodes=30, reqs_per_node=4,
                                 kill_at=2.0)
        assert rep["stalled_requests"] == 0
        assert rep["fallback_verdicts"] > 0
        assert rep["host"]["killed"]
        total = rep["fleet_verdicts"] + rep["fallback_verdicts"]
        assert total == rep["requests"] == 120

    def test_checker_flags_forged_rows_only(self):
        from tendermint_tpu.simnet.fleet import _build_block, _sign, _pub
        import random
        blk = _build_block(random.Random(1), 0, 0, 16)
        v = check_block(blk)
        for i in range(16):
            pub, msg, sig = blk.entry(i)
            assert bool(v[i]) == (sig == _sign(pub, msg))
