"""Targeted consensus gossip: PeerState-driven catchup without blocksync.

Reference parity: internal/consensus/reactor.go gossipDataRoutine (:503,
catchup :556), gossipVotesRoutine (:715, stored-commit catchup :750-777),
queryMaj23Routine (:797) and peer_state.go — a node that missed heights
must be brought up purely by consensus gossip: peers serve precommits
reconstructed from stored commits and block parts from their stores,
keyed off the laggard's advertised round state.
"""

import time

import pytest

from tendermint_tpu.consensus.peer_state import PeerState, commit_to_vote
from tendermint_tpu.crypto import ed25519
from tendermint_tpu.libs.bits import BitArray
from tendermint_tpu.p2p import (
    MemoryTransport,
    NodeKey,
    PeerAddress,
    PeerManager,
    Router,
    new_memory_network,
)
from tendermint_tpu.types.vote import PRECOMMIT_TYPE, PREVOTE_TYPE


class TestPeerState:
    def test_new_round_step_resets_and_shifts_last_commit(self):
        ps = PeerState("p")
        ps.apply_new_round_step(5, 2, 4, -1)
        ps.ensure_vote_bit_arrays(5, 4)
        ps.set_has_vote(5, 2, PRECOMMIT_TYPE, 1)
        assert ps.prs.precommits.get_index(1)
        # move to next height with last_commit_round == old round: the
        # precommit bits become the last-commit bits
        ps.apply_new_round_step(6, 0, 1, 2)
        assert ps.prs.height == 6
        assert ps.prs.prevotes is None and ps.prs.precommits is None
        assert ps.prs.last_commit_round == 2
        assert ps.prs.last_commit is not None
        assert ps.prs.last_commit.get_index(1)

    def test_has_vote_tracking_and_pick(self):
        from tests.test_types import build_commit

        sks, vset, block_id, commit = build_commit(n=4, height=10, round_=1)
        ps = PeerState("p")
        ps.apply_new_round_step(10, commit.round, 6, -1)
        # peer has nothing: catchup pick returns some reconstructed vote
        v = ps.pick_commit_vote_to_send(commit)
        assert v is not None and v.height == 10 and v.type == PRECOMMIT_TYPE
        # votes verify against the validator set they came from
        idx, val = vset.get_by_address(v.validator_address)
        assert idx == v.validator_index
        val.pub_key.verify_signature  # attribute exists
        ps.set_has_catchup_commit_vote(10, commit.round, v.validator_index)
        seen = {v.validator_index}
        for _ in range(10):
            v2 = ps.pick_commit_vote_to_send(commit)
            if v2 is None:
                break
            ps.set_has_catchup_commit_vote(10, commit.round, v2.validator_index)
            seen.add(v2.validator_index)
        assert len(seen) == 4
        assert ps.pick_commit_vote_to_send(commit) is None

    def test_commit_to_vote_roundtrip_verifies(self):
        from tests.test_types import CHAIN_ID, build_commit

        sks, vset, block_id, commit = build_commit(n=4, height=7, round_=0)
        for i in range(4):
            v = commit_to_vote(commit, i)
            assert v is not None
            _, val = vset.get_by_address(v.validator_address)
            assert val.pub_key.verify_signature(v.sign_bytes(CHAIN_ID), v.signature)

    def test_vote_set_bits_learning(self):
        ps = PeerState("p")
        ps.apply_new_round_step(3, 0, 4, -1)
        ps.ensure_vote_bit_arrays(3, 4)
        bits = BitArray(4)
        bits.set_index(0, True)
        bits.set_index(2, True)
        ours = BitArray(4)
        ours.set_index(2, True)
        ours.set_index(3, True)
        ps.apply_vote_set_bits(3, 0, PREVOTE_TYPE, bits, our_votes=ours)
        # only the intersection with our votes is learned for keyed bits
        assert not ps.prs.prevotes.get_index(0)
        assert ps.prs.prevotes.get_index(2)
        assert not ps.prs.prevotes.get_index(3)


class TestGossipCatchup:
    def test_laggard_catches_up_via_consensus_gossip_only(self):
        """A validator that starts late (no blocksync wired) is caught up
        by consensus gossip alone: stored-commit precommits + block parts
        served off its advertised PeerRoundState."""
        from tendermint_tpu.consensus.reactor import ALL_DESCS, ConsensusReactor
        from tests.test_consensus import make_node

        sks = [ed25519.gen_priv_key(bytes([i + 1]) * 32) for i in range(4)]
        node_keys = [NodeKey.generate(bytes([i + 60]) * 32) for i in range(4)]
        hub = new_memory_network()
        nodes, stores, routers, reactors = [], [], [], []
        for i in range(4):
            cs, bstore, _ = make_node(sks, i)
            t = MemoryTransport(hub, node_keys[i].node_id, node_keys[i].pub_key)
            pm = PeerManager(node_keys[i].node_id)
            r = Router(t, pm, node_keys[i].node_id)
            reactors.append(ConsensusReactor(cs, r))
            nodes.append(cs)
            stores.append(bstore)
            routers.append(r)
        for i in range(4):
            for j in range(4):
                if i != j:
                    routers[i]._pm.add_address(
                        PeerAddress(node_keys[j].node_id, node_keys[j].node_id)
                    )
        # The laggard's router/reactor start ONLY after the others are at
        # height 4, so it cannot have buffered any live traffic — everything
        # it learns must come from catchup gossip off the peers' stores.
        for r in routers[:3]:
            r.start()
        for re in reactors[:3]:
            re.start()
        deadline = time.time() + 10
        while time.time() < deadline and any(
            len(r.connected()) < 2 for r in routers[:3]
        ):
            time.sleep(0.05)

        try:
            # 3 of 4 validators (power 300/400 >= 2/3+) run ahead
            for n in nodes[:3]:
                n.start()
            for n in nodes[:3]:
                n.wait_for_height(4, timeout=60)
            # the laggard joins at height 1 — consensus gossip only
            routers[3].start()
            reactors[3].start()
            nodes[3].start()
            nodes[3].wait_for_height(4, timeout=60)
        finally:
            for n in nodes:
                n.stop()
            for re in reactors:
                re.stop()
            for r in routers:
                r.stop()

        h2 = [s.load_block(2).hash() for s in stores]
        assert all(h == h2[0] for h in h2), "laggard diverged after catchup"
