"""FleetServer/FleetClient service behavior over real sockets (ISSUE 18).

The verifier here is a STUB (futures the test resolves by hand), so
these tests pin the transport contract itself — completion-order
verdict streaming, QoS/flow/lane preservation into the submit seam,
malformed-frame containment (ERROR reply, connection lives), oversize
containment (connection dies, server lives), dispatch-error taxonomy
(RemoteDispatchError, no host fallback) vs. fleet-death taxonomy
(FleetUnavailable, host fallback), deadline → degrade → rejoin — with
no jax, no kernels and no crypto wheel in the loop.
"""

import socket
import threading
import time
from concurrent.futures import Future

import numpy as np
import pytest

try:
    from tendermint_tpu.fleet import wire
except ModuleNotFoundError:
    # importing tendermint_tpu.ops (EntryBlock's package) pulls the
    # crypto stack; without the cryptography wheel this module re-runs
    # in a purepy subprocess via test_fleet_isolated.py
    pytest.skip(
        "ops stack unavailable (runs via test_fleet_isolated.py)",
        allow_module_level=True,
    )
from tendermint_tpu.fleet.client import (  # noqa: E402
    FleetClient,
    FleetUnavailable,
    RemoteDispatchError,
)
from tendermint_tpu.fleet.server import FleetServer  # noqa: E402
from tendermint_tpu.ops.entry_block import EntryBlock  # noqa: E402


def make_block(n=4, seed=0):
    rng = np.random.RandomState(seed)
    return EntryBlock(
        rng.randint(0, 256, (n, 32), dtype=np.uint8),
        rng.randint(0, 256, (n, 64), dtype=np.uint8),
        bytes(rng.randint(0, 256, 8 * n, dtype=np.uint8)),
        np.arange(0, 8 * (n + 1), 8, dtype=np.int64),
    )


class StubVerifier:
    """AsyncBatchVerifier-shaped: records every submit, hands back a
    Future the TEST resolves — so completion order is test-controlled."""

    def __init__(self):
        self.calls = []  # (block, flow, priority, origin, future)
        self._mtx = threading.Lock()
        self._arrived = threading.Condition(self._mtx)

    def submit(self, entries, flow=None, priority=0, origin=None):
        fut = Future()
        with self._arrived:
            self.calls.append((entries, flow, priority, origin, fut))
            self._arrived.notify_all()
        return fut

    def wait_calls(self, n, timeout=10.0):
        with self._arrived:
            ok = self._arrived.wait_for(lambda: len(self.calls) >= n,
                                        timeout=timeout)
        assert ok, f"server never dispatched {n} submit(s)"
        return self.calls[:n]


class RaisingVerifier:
    def submit(self, entries, flow=None, priority=0, origin=None):
        raise RuntimeError("verifier rejects: synthetic dispatch failure")


@pytest.fixture
def stub_rig():
    stub = StubVerifier()
    srv = FleetServer(verifier=stub).start()
    cli = FleetClient(srv.addr, name="svc", lane="svc-lane",
                      timeout_ms=60_000, rejoin_ms=50)
    yield stub, srv, cli
    cli.close()
    srv.stop()


class TestVerdictStreaming:
    def test_completion_order_not_submit_order(self, stub_rig):
        stub, _srv, cli = stub_rig
        futs = [cli.submit(make_block(n), flow=100 + n, priority=0)
                for n in (2, 3, 4)]
        calls = stub.wait_calls(3)
        # resolve in REVERSE submit order; each client future must still
        # get ITS verdicts (request_id demux), last-submitted first
        for i, (blk, _f, _p, _o, fut) in reversed(list(enumerate(calls))):
            fut.set_result(np.arange(len(blk)) % 2 == i % 2)
        for i, f in enumerate(futs):
            got = f.result(timeout=10)
            assert got.shape == (i + 2,)
            assert np.array_equal(got, np.arange(i + 2) % 2 == i % 2)

    def test_qos_flow_lane_preserved_into_submit_seam(self, stub_rig):
        stub, _srv, cli = stub_rig
        cli.submit(make_block(3), flow=777, priority=2)
        (blk, flow, priority, origin, fut) = stub.wait_calls(1)[0]
        assert (len(blk), flow, priority, origin) == (3, 777, 2, "svc-lane")
        fut.set_result(np.ones(3, dtype=bool))

    def test_out_of_range_priority_clamped(self, stub_rig):
        stub, _srv, cli = stub_rig
        cli.submit(make_block(2), priority=99)
        assert stub.wait_calls(1)[0][2] == 2  # clamped to ingress
        stub.calls[0][4].set_result(np.ones(2, dtype=bool))


class TestFailureContainment:
    def _raw_conn(self, addr):
        s = socket.create_connection(addr, timeout=10)
        s.settimeout(10)
        return s

    def _read_frame(self, sock):
        dec = wire.FrameDecoder()
        while True:
            data = sock.recv(1 << 16)
            assert data, "server closed before replying"
            payloads = dec.feed(data)
            if payloads:
                return wire.parse_frame(payloads[0])

    def test_malformed_then_valid_on_same_connection(self, stub_rig):
        stub, srv, _cli = stub_rig
        s = self._raw_conn(srv.addr)
        try:
            junk = b"NOPE" + b"\x00" * 30
            s.sendall(wire._LEN.pack(len(junk)) + junk)
            err = self._read_frame(s)
            assert isinstance(err, wire.ErrorFrame)
            assert err.code == wire.ERR_MALFORMED
            # ... and the SAME connection still serves a valid frame
            blk = make_block(2)
            for part in wire.encode_submit(5, blk, lane="raw"):
                s.sendall(bytes(part))
            stub.wait_calls(1)[0][4].set_result(np.ones(2, dtype=bool))
            ok = self._read_frame(s)
            assert isinstance(ok, wire.VerdictFrame)
            assert ok.request_id == 5 and bool(ok.verdicts.all())
        finally:
            s.close()

    def test_version_skew_earns_version_error(self, stub_rig):
        _stub, srv, _cli = stub_rig
        s = self._raw_conn(srv.addr)
        try:
            raw = b"".join(bytes(b) for b in wire.encode_submit(
                1, make_block(2)))
            payload = bytearray(raw[4:])
            payload[4:6] = (99).to_bytes(2, "little")
            s.sendall(wire._LEN.pack(len(payload)) + bytes(payload))
            err = self._read_frame(s)
            assert isinstance(err, wire.ErrorFrame)
            assert err.code == wire.ERR_VERSION
        finally:
            s.close()

    def test_oversize_kills_connection_not_server(self, stub_rig):
        stub, srv, cli = stub_rig
        s = self._raw_conn(srv.addr)
        try:
            s.sendall(wire._LEN.pack(1 << 31) + b"x" * 16)
            # the poisoned connection must die...
            deadline = time.monotonic() + 10
            closed = False
            while time.monotonic() < deadline:
                try:
                    if s.recv(1 << 16) == b"":
                        closed = True
                        break
                except OSError:
                    closed = True
                    break
            assert closed, "oversize prefix must kill the connection"
        finally:
            s.close()
        # ... while the server keeps serving: the long-lived client
        # still round-trips, and a brand-new connection is accepted
        f = cli.submit(make_block(2), flow=1)
        stub.wait_calls(1)[0][4].set_result(np.zeros(2, dtype=bool))
        assert not f.result(timeout=10).any()
        s2 = self._raw_conn(srv.addr)
        s2.close()

    def test_dispatch_error_poisons_only_that_request(self):
        srv = FleetServer(verifier=RaisingVerifier()).start()
        cli = FleetClient(srv.addr, name="derr", timeout_ms=60_000)
        try:
            f = cli.submit(make_block(2), flow=9)
            with pytest.raises(RemoteDispatchError,
                               match="synthetic dispatch failure"):
                f.result(timeout=10)
            # no host-fallback marker: a remote verifier raise is not a
            # fleet failure
            assert not getattr(RemoteDispatchError, "fallback_to_host",
                               False)
            assert cli.connected, "dispatch error must not degrade"
        finally:
            cli.close()
            srv.stop()

    def test_future_exception_streams_error_frame(self, stub_rig):
        stub, _srv, cli = stub_rig
        f = cli.submit(make_block(3))
        stub.wait_calls(1)[0][4].set_exception(
            RuntimeError("batch exploded late"))
        with pytest.raises(RemoteDispatchError, match="batch exploded"):
            f.result(timeout=10)


class TestDegradeAndRejoin:
    def test_timeout_degrades_with_fallback_marker(self):
        stub = StubVerifier()
        srv = FleetServer(verifier=stub).start()
        cli = FleetClient(srv.addr, name="slow", timeout_ms=200,
                          rejoin_ms=10_000)
        try:
            f = cli.submit(make_block(2), flow=3)
            stub.wait_calls(1)  # dispatched, but never resolved
            with pytest.raises(FleetUnavailable) as ei:
                f.result(timeout=10)
            assert ei.value.fallback_to_host is True
            assert cli.stats()["timeouts"] == 1
            # degraded: immediate-raise mode, no queueing behind a corpse
            with pytest.raises(FleetUnavailable):
                cli.submit(make_block(2))
        finally:
            cli.close()
            srv.stop()

    def test_server_stop_fails_pending_and_client_rejoins(self):
        stub = StubVerifier()
        srv = FleetServer(verifier=stub).start()
        port = srv.addr[1]
        cli = FleetClient(srv.addr, name="rj", timeout_ms=60_000,
                          rejoin_ms=50)
        try:
            f = cli.submit(make_block(2), flow=4)
            stub.wait_calls(1)
            srv.stop()  # crash: in-flight must fail with the marker
            with pytest.raises(FleetUnavailable):
                f.result(timeout=10)
            # restart on the same port; the rejoin loop redials
            stub2 = StubVerifier()
            srv = FleetServer(addr=("127.0.0.1", port),
                              verifier=stub2).start()
            deadline = time.monotonic() + 30
            while not cli.connected and time.monotonic() < deadline:
                time.sleep(0.02)
            assert cli.connected and cli.stats()["rejoins"] >= 1
            f2 = cli.submit(make_block(3), flow=5)
            stub2.wait_calls(1)[0][4].set_result(np.ones(3, dtype=bool))
            assert f2.result(timeout=10).all()
        finally:
            cli.close()
            srv.stop()


class TestLaneSpecSeam:
    """The tentpole's (c): a FleetClient IS a lane verifier. A lane's
    flushed windows ride the wire; post-submit fleet death host-verifies
    the window via host_fn (remote_fallbacks — zero lost items, no
    poison); while degraded, pre-submit raises ride
    submit_error_to_host; after a rejoin the next window rides the
    fleet again. The ingress fabric never imports fleet — the contract
    is the duck-typed fallback_to_host marker."""

    def test_lane_degrades_and_rejoins_through_fleet_backend(self):
        from tendermint_tpu.ops import ingress as ing

        stub = StubVerifier()
        srv = FleetServer(verifier=stub).start()
        port = srv.addr[1]
        cli = FleetClient(srv.addr, name="lane", lane="fleet-lane",
                          timeout_ms=60_000, rejoin_ms=50)
        host_runs = []

        def entries_fn(item):
            i = item["i"]
            return (bytes([i]) * 32, bytes([i]) * 8, bytes([i]) * 64)

        def host_fn(items):  # receives the raw payloads, unwrapped
            host_runs.append([it["i"] for it in items])
            return [True] * len(items)

        def deliver(items, verdicts, err):
            for it in items:
                if it.future is None or it.future.done():
                    continue
                if err is not None:
                    it.future.set_exception(err)
                else:
                    it.future.set_result(list(verdicts))

        eng = ing.IngressEngine()
        lane = eng.register(ing.LaneSpec(
            name="fleet-lane", priority=2, batch=4, window_ms=50.0,
            submit_error_to_host=True, verifier=cli,
            entries_fn=entries_fn, host_fn=host_fn, deliver=deliver))
        try:
            # 1) healthy: a full window flushes over the wire at the
            # lane's QoS tier, verdicts come back through deliver()
            futs = [lane.submit({"i": i}, want_future=True)
                    for i in range(4)]
            blk, _fl, prio, origin, sfut = stub.wait_calls(1)[0]
            assert (len(blk), prio, origin) == (4, 2, "fleet-lane")
            sfut.set_result(np.array([True, False, True, True]))
            assert futs[0].result(timeout=10) == [True, False, True, True]

            # 2) post-submit death: window reaches the fleet, then the
            # host dies — the window must HOST-verify, not poison
            futs2 = [lane.submit({"i": 10 + i}, want_future=True)
                     for i in range(4)]
            stub.wait_calls(2)  # the frame crossed the wire
            srv.stop()
            assert futs2[0].result(timeout=10) == [True] * 4
            assert host_runs == [[10, 11, 12, 13]]
            assert lane.remote_fallbacks == 1
            assert lane.dispatch_errors == 0, "fallback must not poison"

            # 3) degraded: pre-submit FleetUnavailable rides the
            # submit_error_to_host path (disjoint counter taxonomy)
            futs3 = [lane.submit({"i": 20 + i}, want_future=True)
                     for i in range(4)]
            assert futs3[0].result(timeout=10) == [True] * 4
            assert host_runs[-1] == [20, 21, 22, 23]
            assert lane.sync_fallbacks >= 1
            assert lane.remote_fallbacks == 1

            # 4) fleet returns on the same port: the client rejoins and
            # the NEXT window rides remote again
            stub2 = StubVerifier()
            srv = FleetServer(addr=("127.0.0.1", port),
                              verifier=stub2).start()
            deadline = time.monotonic() + 30
            while not cli.connected and time.monotonic() < deadline:
                time.sleep(0.02)
            assert cli.connected
            futs4 = [lane.submit({"i": 30 + i}, want_future=True)
                     for i in range(4)]
            blk4 = stub2.wait_calls(1)[0]
            assert len(blk4[0]) == 4
            blk4[4].set_result(np.ones(4, dtype=bool))
            assert futs4[0].result(timeout=10) == [True] * 4
            assert len(host_runs) == 2, "post-rejoin windows ride remote"
        finally:
            eng.close()
            cli.close()
            srv.stop()


class TestStatsSurface:
    def test_client_and_server_stats_keys(self, stub_rig):
        stub, srv, cli = stub_rig
        f = cli.submit(make_block(2), flow=8)
        stub.wait_calls(1)[0][4].set_result(np.ones(2, dtype=bool))
        f.result(timeout=10)
        cs = cli.stats()
        assert set(cs) >= {"target", "connected", "rtt_ewma_ms", "pending",
                           "rejoins", "fallbacks", "timeouts"}
        assert cs["connected"] and cs["pending"] == 0
        assert cs["rtt_ewma_ms"] is not None and cs["rtt_ewma_ms"] > 0
        assert cli.rtt_ewma_ms() == cs["rtt_ewma_ms"]
        ss = srv.stats()
        assert ss["connections"] >= 1 and not ss["stopped"]

    def test_fleet_stats_snapshot_covers_both_ends(self, stub_rig):
        from tendermint_tpu.libs.metrics import fleet_stats

        stub, _srv, cli = stub_rig
        f = cli.submit(make_block(2), flow=8)
        stub.wait_calls(1)[0][4].set_result(np.ones(2, dtype=bool))
        f.result(timeout=10)
        snap = fleet_stats()
        assert set(snap) == {"client", "server"}
        tgt = cli.stats()["target"]
        assert snap["client"]["connected"].get(tgt) == 1
        assert snap["client"]["requests"].get(tgt, 0) >= 1
        assert snap["server"]["frames_accepted"].get("svc-lane", 0) >= 1
        assert snap["server"]["verdicts_streamed"] >= 1
