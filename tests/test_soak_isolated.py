"""Tier-1 soak-harness coverage (ISSUE 16) for containers without the
`cryptography` wheel.

Two subprocess runs of `tools/simnet_run.py --soak` under
TM_TPU_PUREPY_CRYPTO=1 (the env flag must NOT leak into the main pytest
interpreter — same pattern as tests/test_simnet_isolated.py):

  1. mini-soak smoke: all four workload lanes drive ONE shared verifier
     on a mocked relay for a few virtual seconds, twice at the same
     seed — green verdict, replay-exact, every lane demonstrably active.
  2. starved run: TM_TPU_INJECT_LINTBUG=starve makes the pipeline worker
     withhold ingress-priority dispatch — the soak must FAIL with the
     breach localized to the ingress lane + a concrete time window, and
     the artifact must carry the flight-recorder tail.
"""

import json
import os
import subprocess
import sys

import pytest

HERE = os.path.dirname(os.path.abspath(__file__))
REPO = os.path.dirname(HERE)


def _env(**extra):
    env = dict(os.environ, TM_TPU_PUREPY_CRYPTO="1", JAX_PLATFORMS="cpu")
    env.update(extra)
    return env


@pytest.mark.parametrize("seed,duration", [("7", "6"), ("8", "5")])
def test_mini_soak_smoke_green_and_replay_exact(tmp_path, seed, duration):
    """`simnet_run.py --soak` — 4 nodes, crash + catchup rejoin +
    partition/heal, commit echo + light fleet + tx floods through one
    shared AsyncBatchVerifier on a mocked relay, twice per seed at TWO
    seeds: green verdict, identical fingerprint/schedule digest per
    seed, zero timeouts, devcheck-clean (no devcheck key when unarmed),
    all lanes active."""
    out = tmp_path / "soak.json"
    r = subprocess.run(
        [
            sys.executable, os.path.join(REPO, "tools", "simnet_run.py"),
            "--soak", duration, "--repeat", "2", "--seed", seed,
            "--soak-out", str(out),
        ],
        capture_output=True,
        env=_env(),
        cwd=REPO,
        timeout=240,
    )
    tail = (r.stdout or b"").decode(errors="replace")[-3000:]
    assert r.returncode == 0, f"mini soak failed:\n{tail}"
    v = json.loads(out.read_text())
    assert v["ok"] is True, v["reason"]
    assert v["replay_exact"] is True and v["runs"] == 2
    assert v["mode"] == "mocked-relay"
    assert v["slo"]["ok"] and v["slo"]["evaluated"] == 5
    assert v["violations"] == []
    # every workload lane demonstrably ran (a lane that silently no-ops
    # would still produce a "green" verdict — refuse that)
    c = v["counters"]
    assert c["echo_submitted"] > 0 and c["echo_errors"] == 0
    assert c["light_verdicts"] > 0 and c["light_timeouts"] == 0
    assert c["ingress_admitted"] > 0 and c["ingress_timeouts"] == 0
    # aggregated-commit echo probe (ISSUE 20): rode the shared verifier
    # through the fused BLS pairing seam, its SLO evaluated
    assert c["bls_echoes"] > 0 and c["bls_echo_errors"] == 0
    assert any(b["slo"] == "bls_agg_p99_ms" and b["ok"]
               for b in v["slo"]["results"])
    cu = v["catchup"][0]
    assert cu["rejoined"] and cu["heights_applied"] > 0
    # the shared verifier saw both consensus-priority and ingress traffic
    # (this short smoke's catchup gap sits under the device threshold, so
    # the replay lane goes through the sequential path — SOAK_r01's
    # 1000+-height gap covers the device replay lane)
    assert v["lane_counts"]["consensus"] > 0
    assert v["lane_counts"]["ingress"] > 0
    assert v["sampler_ticks"] >= int(duration) - 1  # 1 s cadence


def test_starved_soak_fails_localized_to_ingress(tmp_path):
    """ISSUE 16 satellite: with the deterministic starvation seam armed
    (TM_TPU_INJECT_LINTBUG=starve — the pipeline worker withholds
    ingress-priority dispatch), the soak must fail CONCLUSIVELY: exit 1,
    the abort reason naming ingress admission, the ingress SLO breach
    carrying an observed latency + a concrete breach window, and the
    flight-recorder tail attached to the artifact."""
    out = tmp_path / "soak_starved.json"
    r = subprocess.run(
        [
            sys.executable, os.path.join(REPO, "tools", "simnet_run.py"),
            "--soak", "8", "--seed", "7", "--inject-bug", "starve",
            "--soak-out", str(out),
        ],
        capture_output=True,
        env=_env(
            # short admission deadline + tight budget so the starved
            # burst times out (and breaches) in seconds, not minutes
            TM_TPU_SOAK_INGRESS_TIMEOUT_S="2",
            TM_TPU_SOAK_INGRESS_P99_MS="1000",
        ),
        cwd=REPO,
        timeout=120,
    )
    tail = (r.stdout or b"").decode(errors="replace")[-3000:]
    assert r.returncode == 1, f"starved soak did not fail:\n{tail}"
    v = json.loads(out.read_text())
    assert v["ok"] is False
    assert "ingress admission timed out" in v["reason"]
    assert v["counters"]["ingress_timeouts"] > 0
    assert v["counters"]["ingress_admitted"] == 0

    breaches = {b["slo"]: b for b in v["slo"]["breaches"]}
    ing = breaches["ingress_admission_p99_ms"]
    assert ing["lane"] == "ingress"
    # localization: observed latency == the admission deadline, and a
    # concrete worst window to point an operator at
    assert ing["observed"] is not None and ing["observed"] >= 1000.0
    bw = ing["breach_window"]
    assert bw and bw["t1"] > bw["t0"] and bw["count"] > 0
    # the ingress breach is the ONLY one with a localized window — the
    # other lanes breach as starved/idle because fail-fast ends the run
    # before they accrue samples (downstream of the same root cause)
    for name, b in breaches.items():
        if name != "ingress_admission_p99_ms":
            assert not b.get("breach_window"), name

    # conclusive-failure artifact: flight-recorder tail rides along, and
    # the armed devcheck checkers saw no UNRELATED violation (the seam
    # starves scheduling; it must not corrupt state)
    assert v.get("flight_recorder")
    assert (v.get("devcheck") or {}).get("violations") == []
