"""Light-client verification service (ISSUE 11): batched verdicts must
be byte-identical to the sequential light/verifier.py path — ok headers,
forged commits (blame string included), conflicting headers, expired
trust, the exactly-1/3 trust-level edge — while cross-request same-epoch
sig work coalesces through the shared device pipeline and verdicts
stream back in completion order. Plus the /light_verify RPC endpoint
(JSON + chunked NDJSON streaming) and the simnet e2e: hundreds of
simulated clients against a rotating-valset cluster with adversarial
clients, flight-recorder chains RPC-arrival → verdict.

Needs a working ed25519 signer: with the `cryptography` wheel the module
runs directly; without it, tests/test_light_service_isolated.py re-runs
it in a subprocess under TM_TPU_PUREPY_CRYPTO=1.
"""

import importlib.util
import json
import os
import sys
import urllib.request
from dataclasses import replace as dc_replace

import pytest

if importlib.util.find_spec("cryptography") is None and not os.environ.get(
    "TM_TPU_PUREPY_CRYPTO"
):
    pytest.skip(
        "needs an ed25519 signer (cryptography wheel or the isolated runner)",
        allow_module_level=True,
    )

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO_ROOT not in sys.path:
    sys.path.insert(0, REPO_ROOT)

import numpy as np  # noqa: E402

import bench as _bench  # noqa: E402  (chain builder)

from tendermint_tpu.light import verifier as lv  # noqa: E402
from tendermint_tpu.light.batch import (  # noqa: E402
    HeaderRequest,
    fingerprint,
    group_stats,
    prepare_request,
)
from tendermint_tpu.light.service import (  # noqa: E402
    LightVerifyService,
    request_from_json,
    request_to_json,
)
from tendermint_tpu.observability import trace as tr  # noqa: E402
from tendermint_tpu.ops import epoch_cache as _epoch  # noqa: E402
from tendermint_tpu.ops import pipeline as pl  # noqa: E402
from tendermint_tpu.types import Fraction, SignedHeader  # noqa: E402
from tendermint_tpu.types.block import (  # noqa: E402
    BLOCK_ID_FLAG_ABSENT,
    Commit,
    CommitSig,
)
from tendermint_tpu.wire.canonical import Timestamp  # noqa: E402

CHAIN_ID = "light-svc-chain"
N_VALS = 8
N_HDRS = 6
PERIOD = 1e9
NOW = Timestamp(seconds=1_600_000_000 + N_HDRS + 60)


@pytest.fixture(scope="module")
def chain():
    return _bench._build_header_chain(CHAIN_ID, N_HDRS, N_VALS)


@pytest.fixture(scope="module")
def svc():
    _epoch.reset(4)
    v = pl.AsyncBatchVerifier(depth=2)
    s = LightVerifyService(verifier=v)
    yield s
    s.close()
    v.close()


def mkreq(chain, t, u, untrusted=None, period=PERIOD, **kw):
    return HeaderRequest(
        trusted_header=chain[t][0], trusted_vals=chain[t][1],
        untrusted_header=untrusted or chain[u][0],
        untrusted_vals=chain[u][1],
        trusting_period=period, **kw,
    )


def seq_verdict(req, now=NOW):
    """The sequential path's outcome as (type_name, str) or None."""
    try:
        lv.verify(req.trusted_header, req.trusted_vals,
                  req.untrusted_header, req.untrusted_vals,
                  req.trusting_period, now, req.max_clock_drift,
                  req.trust_level)
        return None
    except Exception as e:  # noqa: BLE001 — the verdict IS the error
        return (type(e).__name__, str(e))


def svc_verdict(svc, req, now=NOW):
    r = svc.submit(req, now=now)
    return None if r["ok"] else (r["error_type"], r["error"])


def assert_parity(svc, req, now=NOW, expect_type=None):
    want = seq_verdict(req, now)
    got = svc_verdict(svc, req, now)
    assert got == want
    if expect_type is not None:
        assert want is not None and want[0] == expect_type
    return want


def forge_commit(sh, lane, sig=b"\x07" * 64):
    c = Commit.decode(sh.commit.encode())
    c.signatures[lane] = dc_replace(c.signatures[lane], signature=sig)
    return SignedHeader(header=sh.header, commit=c)


class TestVerdictParity:
    def test_ok_adjacent_and_non_adjacent(self, chain, svc):
        assert svc_verdict(svc, mkreq(chain, 0, 1)) is None  # adjacent
        assert svc_verdict(svc, mkreq(chain, 0, 5)) is None  # skipping
        assert svc_verdict(svc, mkreq(chain, 2, 5)) is None

    def test_forged_commit_blame_parity(self, chain, svc):
        """Bad sigs must blame the same lane with the same string as the
        sequential verifier — the wrong-signature error carries the sig
        index and hex, so parity here is parity of the whole demux."""
        forged = forge_commit(chain[3][0], 4)
        req = mkreq(chain, 0, 3, untrusted=forged)
        want = assert_parity(svc, req, expect_type="ErrInvalidHeader")
        assert "wrong signature (#4)" in want[1]

    def test_forged_commit_in_trusting_prefix(self, chain, svc):
        """A tampered lane INSIDE the 1/3 early-stop prefix fails the
        trusting stage first — stage-order precedence must match."""
        forged = forge_commit(chain[3][0], 0)
        req = mkreq(chain, 0, 3, untrusted=forged)
        want = assert_parity(svc, req, expect_type="ErrInvalidHeader")
        assert "wrong signature (#0)" in want[1]

    def test_conflicting_header_same_height(self, chain, svc):
        """A forged header over the genuine commit (the same-height
        conflict shape): commit binding fails in validate_basic."""
        sh = chain[4][0]
        conflicted = SignedHeader(
            header=dc_replace(sh.header, app_hash=b"\x66" * 32),
            commit=sh.commit,
        )
        req = mkreq(chain, 0, 4, untrusted=conflicted)
        want = assert_parity(svc, req, expect_type="ErrInvalidHeader")
        assert "ValidateBasic failed" in want[1]

    def test_conflicting_header_resigned_minority(self, chain, svc):
        """A conflicting header RE-SIGNED by one validator (the lunatic
        shape a forging primary serves): insufficient trusted power."""
        from tendermint_tpu.crypto import ed25519
        from tendermint_tpu.types import Vote
        from tendermint_tpu.types.block import BlockID, PartSetHeader
        from tendermint_tpu.types.vote import PRECOMMIT_TYPE

        sh, vset = chain[4]
        hdr = dc_replace(sh.header, app_hash=b"\x66" * 32)
        bid = BlockID(hash=hdr.hash(),
                      part_set_header=PartSetHeader(total=1, hash=hdr.hash()))
        # find the signer key for validator row 0 (builder seeds i+7)
        sks = {ed25519.gen_priv_key((i + 7).to_bytes(32, "little")).pub_key()
               .address(): ed25519.gen_priv_key((i + 7).to_bytes(32, "little"))
               for i in range(N_VALS)}
        sk = sks[vset.validators[0].address]
        v = Vote(type=PRECOMMIT_TYPE, height=hdr.height, round=0, block_id=bid,
                 timestamp=hdr.time,
                 validator_address=vset.validators[0].address,
                 validator_index=0)
        v = dc_replace(v, signature=sk.sign(v.sign_bytes(CHAIN_ID)))
        sigs = [v.to_commit_sig()] + [
            CommitSig.absent() for _ in range(N_VALS - 1)
        ]
        conflicted = SignedHeader(
            header=hdr,
            commit=Commit(height=hdr.height, round=0, block_id=bid,
                          signatures=sigs),
        )
        req = mkreq(chain, 0, 4, untrusted=conflicted)
        assert_parity(svc, req, expect_type="ErrNotEnoughTrust")

    def test_expired_trusted_header(self, chain, svc):
        req = mkreq(chain, 0, 5, period=1.0)
        want = assert_parity(svc, req, expect_type="ErrOldHeaderExpired")
        assert "old header has expired" in want[1]

    def test_trust_level_edge_exactly_one_third(self, chain, svc):
        """Exactly 1/3 of trusted power signing is NOT enough (the tally
        must EXCEED needed) — and one signer more flips the failing
        stage from trusting to the +2/3 check. Both orderings must match
        the sequential path byte-for-byte."""
        c3 = _bench._build_header_chain("edge-chain", 3, 3)
        for keep in (1, 2):
            sh = c3[2][0]
            commit = Commit.decode(sh.commit.encode())
            for lane in range(keep, 3):
                commit.signatures[lane] = CommitSig(
                    block_id_flag=BLOCK_ID_FLAG_ABSENT,
                    validator_address=b"", timestamp=Timestamp.zero(),
                    signature=b"",
                )
            thinned = SignedHeader(header=sh.header, commit=commit)
            req = HeaderRequest(
                trusted_header=c3[0][0], trusted_vals=c3[0][1],
                untrusted_header=thinned, untrusted_vals=c3[2][1],
                trusting_period=PERIOD,
            )
            want = assert_parity(
                svc, req,
                expect_type="ErrNotEnoughTrust" if keep == 1
                else "ErrInvalidHeader",
            )
            assert "voting power" in want[1]

    def test_height_not_greater(self, chain, svc):
        req = mkreq(chain, 3, 2)
        assert_parity(svc, req, expect_type="ErrInvalidHeader")

    def test_future_header_time_drift(self, chain, svc):
        # > max_clock_drift (10s) before chain[5]'s header time
        early = Timestamp(seconds=1_599_999_990)
        req = mkreq(chain, 0, 5)
        want = seq_verdict(req, early)
        assert want == svc_verdict(svc, req, early)
        assert want[0] == "ErrInvalidHeader" and "future" in want[1]


class TestServiceMechanics:
    def test_streaming_completion_order_covers_all_indices(self, chain, svc):
        reqs = [mkreq(chain, 0, k) for k in range(1, N_HDRS + 1)]
        batch = svc.submit_many(reqs, now=NOW)
        seen = [v["index"] for v in batch.stream(timeout=600)]
        assert sorted(seen) == list(range(len(reqs)))
        res = svc.submit_many(reqs, now=NOW).results(timeout=600)
        assert [r["index"] for r in res] == list(range(len(reqs)))
        assert all(r["ok"] for r in res)

    def test_memo_and_single_flight(self, chain, svc):
        req = mkreq(chain, 1, 5)
        s0 = svc.stats()
        r1 = svc.submit(req, now=NOW)
        # same fingerprint → memo hit, no new unique verification
        r2 = svc.submit(mkreq(chain, 1, 5), now=NOW)
        s1 = svc.stats()
        assert r1["ok"] and r2["ok"]
        assert s1["memo_hits"] >= s0["memo_hits"] + 1
        assert s1["unique"] == s0["unique"] + 1
        # a DIFFERENT now is a different verification (expiry depends on it)
        later = Timestamp(seconds=NOW.seconds + 1)
        assert fingerprint(req, NOW) != fingerprint(req, later)

    def test_unfingerprintable_requests_never_alias(self, chain, svc):
        """An incomplete header hashes to b'' (Header.hash's nil
        convention) — such requests must NOT share a memo/single-flight
        slot (review finding: two different b''-hash requests would
        alias one verdict). They verify uniquely instead."""
        sh, vset = chain[2]
        incomplete = SignedHeader(
            header=dc_replace(sh.header, validators_hash=b""),
            commit=sh.commit,
        )
        r1 = HeaderRequest(
            trusted_header=incomplete, trusted_vals=vset,
            untrusted_header=chain[4][0], untrusted_vals=chain[4][1],
            trusting_period=PERIOD,
        )
        r2 = HeaderRequest(
            trusted_header=SignedHeader(
                header=dc_replace(
                    sh.header, validators_hash=b"",
                    time=Timestamp(seconds=1),  # long expired
                ),
                commit=sh.commit,
            ),
            trusted_vals=vset,
            untrusted_header=chain[4][0], untrusted_vals=chain[4][1],
            trusting_period=PERIOD,
        )
        assert fingerprint(r1, NOW) is None and fingerprint(r2, NOW) is None
        s0 = svc.stats()
        got = [svc_verdict(svc, r) for r in (r1, r2)]
        s1 = svc.stats()
        assert s1["unique"] == s0["unique"] + 2  # no dedup, no memo
        assert s1["memo_hits"] == s0["memo_hits"]
        assert got[0] == seq_verdict(r1) and got[1] == seq_verdict(r2)
        assert got[0] != got[1]  # the aliasing bug would collapse these

    def test_service_clock_requests_dedup_across_calls(self, chain):
        """Requests that omit `now` must still share the memo across
        submit_many calls (review finding: a nanosecond-resolution
        service clock made every call's fingerprints unique). The
        resolved clock truncates to whole seconds — and the SAME
        truncated now drives verification, so key and verdict agree."""
        _epoch.reset(4)
        v = pl.AsyncBatchVerifier(depth=2)
        s = LightVerifyService(
            verifier=v,
            now_fn=lambda: Timestamp(seconds=NOW.seconds, nanos=123_456_789),
        )
        try:
            r1 = s.submit(mkreq(chain, 0, 4))  # no now anywhere
            r2 = s.submit(mkreq(chain, 0, 4))  # second CALL, same second
            assert r1["ok"] and r2["ok"]
            st = s.stats()
            assert st["unique"] == 1 and st["memo_hits"] == 1
        finally:
            s.close()
            v.close()

    def test_infra_failures_are_never_memoized(self, chain):
        """A pipeline-infrastructure failure (submit refused, dispatch
        died) must not become a sticky cached rejection — identical
        later requests re-verify (review finding: only parity verdicts
        are deterministic)."""

        class _FlakyVerifier:
            calls = 0

            def submit(self, entries, flow=None):
                _FlakyVerifier.calls += 1
                raise RuntimeError("verifier is closed")

        flaky = LightVerifyService(verifier=_FlakyVerifier())
        req = mkreq(chain, 0, 3)
        r1 = flaky.submit(req, now=NOW)
        assert not r1["ok"] and r1["error_type"] == "RuntimeError"
        r2 = flaky.submit(req, now=NOW)
        assert not r2["ok"]
        s = flaky.stats()
        # both attempts went through the full path: no memo entry, no hit
        assert s["unique"] == 2 and s["memo_hits"] == 0
        assert s["memo_entries"] == 0
        assert _FlakyVerifier.calls == 2
        flaky.close()

    def test_stream_deadline_raises_timeout(self):
        """stream(timeout) is an overall deadline: expiry surfaces as
        TimeoutError naming the pending count (never queue.Empty)."""
        from tendermint_tpu.light.service import VerdictBatch

        b = VerdictBatch(2)
        b._push({"index": 0, "ok": True})
        it = b.stream(timeout=0.05)
        assert next(it)["index"] == 0
        with pytest.raises(TimeoutError, match="1 of 2"):
            next(it)

    def test_epoch_grouping_metadata(self, chain):
        """Warm-epoch requests carry the valset's epoch key on every
        stage block — the coalescer's grouping input."""
        _epoch.reset(4)
        # first sight cold-registers the epoch and rides uncached (the
        # PR-5 contract); everything after is warm
        prepare_request(mkreq(chain, 0, 1), NOW)
        plans = [prepare_request(mkreq(chain, 0, k), NOW) for k in (2, 3, 4)]
        groups = group_stats(plans)
        # one warm epoch: every stage block shares one non-None key
        assert len(groups) == 1
        (key, count), = groups.items()
        assert key is not None and count == 6  # trusting+light per request

    def test_verdict_rows_are_owned_copies(self, chain, svc):
        """The service fans one device verdict row out to many waiters'
        conclude closures — rows must be host-owned (the PR-7 aliasing
        contract extended to the serving layer)."""
        plan = prepare_request(mkreq(chain, 0, 4), NOW)
        stages = plan.entry_stages()
        futs = [svc._v.submit(st.entries) for st in stages]
        rows = [np.array(f.result(timeout=600), dtype=bool) for f in futs]
        assert all(r.flags.owndata for r in rows)

    def test_flow_chain_rpc_arrival_to_verdict(self, chain, svc):
        tr.TRACER.clear()
        tr.configure(enabled=True)
        try:
            # fresh fingerprint (unseen height pair) so the request goes
            # through the full unique-verification path
            r = svc.submit(mkreq(chain, 2, 4), now=NOW)
            assert r["ok"]
        finally:
            tr.configure(enabled=False)
        chains = tr.flow_chains(tr.TRACER.export_chrome())
        light = [
            evs for evs in chains.values()
            if evs and evs[0]["name"] == "light.rpc_arrival"
        ]
        assert light, "no light-service flow chain recorded"
        names = [e["name"] for e in light[-1]]
        assert names[0] == "light.rpc_arrival"
        assert "pipeline.submit" in names
        assert names[-1] == "light.verdict"
        phases = [(e["args"] or {}).get("flow_phase") for e in light[-1]]
        assert phases[0] == "s" and phases[-1] == "f"


class _StubNode:
    """Environment(node) double for the endpoint test: /light_verify is
    self-contained and never touches the node's stores."""

    config = None


class TestRPCEndpoint:
    @pytest.fixture(scope="class")
    def server(self):
        from tendermint_tpu.rpc.core import Environment
        from tendermint_tpu.rpc.server import RPCServer

        env = Environment(_StubNode())
        srv = RPCServer("127.0.0.1:0", env)
        srv.start()
        yield srv
        srv.stop()

    def _post(self, srv, payload):
        req = urllib.request.Request(
            f"http://{srv.listen_addr}/", data=json.dumps(payload).encode(),
            headers={"Content-Type": "application/json"},
        )
        with urllib.request.urlopen(req, timeout=600) as r:
            return json.loads(r.read())

    def test_roundtrip_batch(self, chain, server):
        reqs = [request_to_json(mkreq(chain, 0, k)) for k in (1, 3, 5)]
        forged = forge_commit(chain[3][0], 4)
        reqs.append(request_to_json(mkreq(chain, 0, 3, untrusted=forged)))
        # pin now so the verdict matches the sequential reference
        for d in reqs:
            d["now"] = request_to_json(
                mkreq(chain, 0, 1, now=NOW)
            )["now"]
        res = self._post(server, {
            "jsonrpc": "2.0", "id": 1, "method": "light_verify",
            "params": {"requests": reqs},
        })
        out = res["result"]
        assert out["total"] == "4" and out["ok_count"] == "3"
        by_idx = {v["index"]: v for v in out["verdicts"]}
        assert by_idx[3]["ok"] is False
        want = seq_verdict(mkreq(chain, 0, 3, untrusted=forged))
        assert (by_idx[3]["error_type"], by_idx[3]["error"]) == want

    def test_json_codec_roundtrip_preserves_fingerprint(self, chain):
        req = mkreq(chain, 0, 4, now=NOW)
        rt = request_from_json(
            json.loads(json.dumps(request_to_json(req)))
        )
        assert fingerprint(rt, NOW) == fingerprint(req, NOW)

    def test_streaming_ndjson(self, chain, server):
        import urllib.parse

        reqs = [request_to_json(mkreq(chain, 0, k)) for k in (2, 4)]
        q = urllib.parse.quote(json.dumps(reqs))
        with urllib.request.urlopen(
            f"http://{server.listen_addr}/light_verify?requests={q}"
            "&stream=true", timeout=600,
        ) as r:
            assert r.headers.get("Content-Type") == "application/x-ndjson"
            lines = [json.loads(l) for l in r.read().splitlines() if l]
        assert lines[-1]["done"] is True and lines[-1]["total"] == 2
        verdicts = lines[:-1]
        assert sorted(v["index"] for v in verdicts) == [0, 1]
        assert all(v["ok"] for v in verdicts)

    def test_bad_request_is_rpc_error(self, server):
        res = self._post(server, {
            "jsonrpc": "2.0", "id": 2, "method": "light_verify",
            "params": {"requests": [{"trusted_header": {}}]},
        })
        assert "error" in res and res["error"]["code"] == -32602


N_CLIENTS = 220


class TestSimnetE2E:
    """The acceptance scenario: 200+ simulated clients against a
    rotating-valset cluster, adversarial clients rejected with
    sequential-parity errors, merged flight-recorder trace with
    complete RPC-arrival → verdict chains."""

    @pytest.fixture(scope="class")
    def cluster_run(self):
        from tendermint_tpu.simnet import Cluster, rotation_schedule

        faults = rotation_schedule(
            n_nodes=5, n_validators=4, every=4, start=4, until=10
        )
        c = Cluster(n_nodes=5, n_validators=4, seed=7, faults=faults,
                    tracing=True)
        try:
            rep = c.run_to_height(12, max_virtual_s=600.0)
            yield c, rep
        finally:
            c.stop()

    def test_light_fleet_against_churn_cluster(self, cluster_run):
        from tendermint_tpu.light.provider import NodeBackedProvider

        c, rep = cluster_run
        assert rep.ok, rep.violations
        assert rep.valset_changes, "rotation never changed the valset"
        node = c.nodes[0]
        provider = NodeBackedProvider(node.bstore, node.sstore)
        tip = node.bstore.height() - 1  # commits exist below the tip
        blocks = {h: provider.light_block(h) for h in range(1, tip + 1)}
        now = Timestamp(
            seconds=blocks[tip].signed_header.header.time.seconds + 60
        )

        def req_for(t, u, untrusted=None):
            return HeaderRequest(
                trusted_header=blocks[t].signed_header,
                trusted_vals=blocks[t].validators,
                untrusted_header=untrusted or blocks[u].signed_header,
                untrusted_vals=blocks[u].validators,
                trusting_period=PERIOD,
            )

        # honest fleet: every client skip-verifies 2 headers in its
        # trust window (trusted height varies → several epoch groups)
        honest = []
        for cl in range(N_CLIENTS):
            t = 1 + cl % 3
            u1 = t + 1 + cl % (tip - t - 1)
            u2 = tip - cl % 2
            honest.append(req_for(t, u1))
            honest.append(req_for(t, max(u2, t + 1)))
        # adversarial clients: forged commits + conflicting headers
        forged_sh = forge_commit(blocks[tip - 1].signed_header, 1)
        conflicted = SignedHeader(
            header=dc_replace(
                blocks[tip].signed_header.header, app_hash=b"\x66" * 32
            ),
            commit=blocks[tip].signed_header.commit,
        )
        bad = []
        for _ in range(8):
            bad.append(req_for(1, tip - 1, untrusted=forged_sh))
            bad.append(req_for(1, tip, untrusted=conflicted))

        _epoch.reset(8)
        v = pl.AsyncBatchVerifier(depth=2)
        svc = LightVerifyService(verifier=v)
        tr.TRACER.clear()
        tr.configure(enabled=True)
        try:
            batch = svc.submit_many(honest + bad, now=now)
            res = batch.results(timeout=900)
            stats = svc.stats()
        finally:
            tr.configure(enabled=False)
            svc.close()
            v.close()

        n_honest = len(honest)
        assert all(r["ok"] for r in res[:n_honest]), [
            r for r in res[:n_honest] if not r["ok"]
        ][:3]
        # adversarial verdicts: rejected, byte-identical to sequential
        want_forged = seq_verdict(req_for(1, tip - 1, untrusted=forged_sh), now)
        want_conf = seq_verdict(req_for(1, tip, untrusted=conflicted), now)
        assert want_forged is not None and want_conf is not None
        for i, r in enumerate(res[n_honest:]):
            want = want_forged if i % 2 == 0 else want_conf
            assert (r["error_type"], r["error"]) == want
        # the fleet amortized: far fewer unique verifications than
        # requests, across MULTIPLE epoch groups (the rotation's work)
        assert stats["requests"] == len(honest) + len(bad)
        assert stats["unique"] < stats["requests"] // 4
        assert stats["memo_hits"] + stats["inflight_joins"] > 0
        plans = [prepare_request(req_for(1 + k % 3, tip - k % 2), now)
                 for k in range(6)]
        assert len(group_stats(plans)) >= 2, "expected multiple epochs"

        # merged flight recorder: cluster doc + service doc share flow
        # namespaces; every unique verification's chain is COMPLETE
        merged = tr.merge_traces(
            [c.export_merged_trace(), tr.TRACER.export_chrome()],
            labels=["cluster", "light-service"],
        )
        chains = tr.flow_chains(merged)
        complete = [
            evs for evs in chains.values()
            if evs[0]["name"] == "light.rpc_arrival"
            and evs[-1]["name"] == "light.verdict"
        ]
        assert len(complete) == stats["unique"]
        # the cluster's own gossip→verify chains coexist in the doc
        assert any(
            evs[0]["name"] == "gossip.send" for evs in chains.values()
        )
