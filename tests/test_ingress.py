"""Device-batched transaction ingress (ISSUE 13): batched CheckTx must
be field-identical to the sequential host path — accept, bad-signature,
bad-nonce, duplicate, legacy/val: passthrough, malformed envelopes —
while signature windows ride the shared pipeline at PRIORITY_INGRESS and
a consensus commit preempts queued tx superbatches. Plus recheck-after-
commit parity under the held mempool lock, DispatchError poisoned-window
isolation (failed txs stay retryable), and the simnet flood: signed txs
injected mid-run through a partition+heal, consensus stays live, no tx
is lost silently, and the run is replay-exact.

Needs a working ed25519 signer: with the `cryptography` wheel the module
runs directly; without it, tests/test_ingress_isolated.py re-runs it in
a subprocess under TM_TPU_PUREPY_CRYPTO=1.
"""

import hashlib
import importlib.util
import os
import sys
import time

import pytest

if importlib.util.find_spec("cryptography") is None and not os.environ.get(
    "TM_TPU_PUREPY_CRYPTO"
):
    pytest.skip(
        "needs an ed25519 signer (cryptography wheel or the isolated runner)",
        allow_module_level=True,
    )

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO_ROOT not in sys.path:
    sys.path.insert(0, REPO_ROOT)

from tendermint_tpu.abci import LocalClient  # noqa: E402
from tendermint_tpu.abci import types as abci  # noqa: E402
from tendermint_tpu.abci.kvstore import (  # noqa: E402
    KVStoreApplication,
    make_validator_tx,
)
from tendermint_tpu.config import MempoolConfig  # noqa: E402
from tendermint_tpu.crypto import ed25519 as ed  # noqa: E402
from tendermint_tpu.crypto import sr25519 as sr  # noqa: E402
from tendermint_tpu.mempool import (  # noqa: E402
    CODE_BAD_NONCE,
    CODE_BAD_SIGNATURE,
    DuplicateTxError,
    TxMempool,
)
from tendermint_tpu.mempool import ingress as ing  # noqa: E402
from tendermint_tpu.ops import epoch_cache as _epoch  # noqa: E402
from tendermint_tpu.ops import pipeline as pl  # noqa: E402
from tendermint_tpu.ops._testing import (  # noqa: E402
    drain_pool,
    mock_mempool_prepare,
)
from tendermint_tpu.ops.entry_block import EntryBlock  # noqa: E402


def _priv(tag: bytes):
    return ed.gen_priv_key(seed=hashlib.sha256(tag).digest())


def _sr_priv(tag: bytes):
    return sr.gen_priv_key(seed=hashlib.sha256(tag).digest())


def _mk_mp(ingress=None, max_tx_bytes: int = 4096) -> TxMempool:
    cfg = MempoolConfig()
    cfg.max_tx_bytes = max_tx_bytes
    return TxMempool(LocalClient(KVStoreApplication()), config=cfg,
                     ingress=ingress)


@pytest.fixture(scope="module")
def acc():
    """One shared verifier + accumulator for the parity/recheck tests:
    the same topology a node runs — every mempool in the process feeds
    the single device pipeline."""
    _epoch.reset(8)
    v = pl.AsyncBatchVerifier(depth=2)
    a = ing.IngressAccumulator(verifier=v, max_batch=64, window_ms=4.0)
    yield a
    a.close()
    v.close()


# -- envelope ------------------------------------------------------------


class TestEnvelope:
    def test_roundtrip(self):
        priv = _priv(b"env-rt")
        tx = ing.make_signed_tx(priv, b"k=v", nonce=7)
        stx = ing.parse_signed_tx(tx)
        assert stx is not None
        assert stx.scheme == ing.SCHEME_ED25519
        assert stx.pub == priv.pub_key().bytes()
        assert stx.nonce == 7
        assert stx.payload == b"k=v"
        assert stx.raw == tx
        assert ing.host_verify(stx)

    def test_tampered_payload_fails_verify(self):
        tx = bytearray(ing.make_signed_tx(_priv(b"env-tamper"), b"k=v", nonce=1))
        tx[-1] ^= 0x01
        stx = ing.parse_signed_tx(bytes(tx))
        assert not ing.host_verify(stx)

    def test_legacy_tx_has_no_envelope(self):
        assert ing.parse_signed_tx(b"plain_key=plain_value") is None
        assert ing.parse_signed_tx(b"") is None

    def test_truncated_raises(self):
        with pytest.raises(ing.MalformedTxError):
            ing.parse_signed_tx(ing.MAGIC)
        with pytest.raises(ing.MalformedTxError):
            ing.parse_signed_tx(ing.MAGIC + bytes([ing.SCHEME_ED25519]) + b"\x00" * 10)

    def test_unknown_scheme_raises(self):
        with pytest.raises(ing.MalformedTxError):
            ing.parse_signed_tx(ing.MAGIC + bytes([9]) + b"\x00" * 120)

    def test_sr25519_roundtrip(self):
        priv = _sr_priv(b"env-sr")
        tx = ing.make_signed_tx(priv, b"s=1", nonce=3, scheme=ing.SCHEME_SR25519)
        stx = ing.parse_signed_tx(tx)
        assert stx.scheme == ing.SCHEME_SR25519
        assert ing.host_verify(stx)

    def test_signed_bytes_excludes_signature(self):
        pub = bytes(range(32))
        tx = ing.encode_signed_tx(ing.SCHEME_ED25519, pub, 42, bytes(64),
                                  b"k=v")
        stx = ing.parse_signed_tx(tx)
        assert stx.signed_bytes() == (
            ing.MAGIC + bytes([ing.SCHEME_ED25519]) + pub
            + (42).to_bytes(8, "big") + b"k=v"
        )

    def test_dispatch_queue_orders_consensus_first(self):
        q = pl._PriorityQueue()
        q.put("ingress-1", priority=pl.PRIORITY_INGRESS)
        q.put("ingress-2", priority=pl.PRIORITY_INGRESS)
        q.put("commit", priority=pl.PRIORITY_CONSENSUS)
        assert q.best_priority() == pl.PRIORITY_CONSENSUS
        assert q.get_nowait() == "commit"
        # FIFO within a priority class
        assert q.get_nowait() == "ingress-1"
        assert q.get_nowait() == "ingress-2"
        assert q.empty()


# -- batched vs sequential CheckTx parity --------------------------------


def _parity_cases():
    """One ordered script of CheckTx submissions covering every verdict
    class; ed25519 signing is deterministic, so both mempools see
    byte-identical txs."""
    a = _priv(b"parity-a")
    b = _priv(b"parity-b")
    s = _sr_priv(b"parity-sr")
    cases = [
        ("a-n1", ing.make_signed_tx(a, b"pa1=1", nonce=1)),
        ("b-n1", ing.make_signed_tx(b, b"pb1=1", nonce=1)),
        ("a-n2", ing.make_signed_tx(a, b"pa2=2", nonce=2)),
        ("sr-n1", ing.make_signed_tx(s, b"psr=1", nonce=1,
                                     scheme=ing.SCHEME_SR25519)),
    ]
    bad = bytearray(ing.make_signed_tx(a, b"pa3=3", nonce=3))
    bad[-1] ^= 0x5A
    cases += [
        ("a-badsig", bytes(bad)),
        # nonce 1 <= recorded 2: replay rejection, sig itself valid
        ("a-replay", ing.make_signed_tx(a, b"pa1b=9", nonce=1)),
        # byte-identical resubmission of a-n1: seen-cache duplicate
        ("a-dup", ing.make_signed_tx(a, b"pa1=1", nonce=1)),
        ("legacy", b"plain=v"),
        ("valtx", make_validator_tx(b.pub_key().bytes(), 5)),
        ("malformed", ing.MAGIC + bytes([ing.SCHEME_ED25519]) + b"\x00" * 4),
        ("badscheme", ing.MAGIC + bytes([7]) + b"\x00" * 120),
        ("oversized", b"x" * 5000),
    ]
    return cases


def _run_cases(mp: TxMempool, cases):
    out = []
    for label, tx in cases:
        try:
            r = mp.check_tx(tx)
            out.append((label, "res", r.code, r.log, r.codespace,
                        r.gas_wanted, r.sender))
        except Exception as e:  # noqa: BLE001 — parity on exception class too
            out.append((label, "exc", type(e).__name__, str(e)))
    return out


class TestParity:
    def test_batched_matches_sequential(self, acc):
        cases = _parity_cases()
        seq = _run_cases(_mk_mp(ingress=None), cases)
        mp_b = _mk_mp(ingress=acc)
        bat = _run_cases(mp_b, cases)
        assert bat == seq
        # spot-check the interesting verdicts landed as designed
        by = {row[0]: row for row in bat}
        assert by["a-n1"][2] == 0
        assert by["a-badsig"][2:5] == (CODE_BAD_SIGNATURE,
                                       "invalid signature", "ingress")
        assert by["a-replay"][2] == CODE_BAD_NONCE
        assert by["a-dup"][1:3] == ("exc", "DuplicateTxError")
        assert by["legacy"][2] == 0
        assert by["valtx"][2] == 0
        assert by["malformed"][1:3] == ("exc", "MalformedTxError")
        assert by["badscheme"][1:3] == ("exc", "MalformedTxError")
        assert by["oversized"][1:3] == ("exc", "ValueError")

    def test_mempool_contents_identical(self, acc):
        cases = _parity_cases()
        mp_s = _mk_mp(ingress=None)
        mp_b = _mk_mp(ingress=acc)
        _run_cases(mp_s, cases)
        _run_cases(mp_b, cases)
        assert mp_b.txs_fifo() == mp_s.txs_fifo()
        assert mp_b.size() == mp_s.size()
        assert mp_b.size_bytes() == mp_s.size_bytes()
        # only the valid txs made it in: a-n1, b-n1, a-n2, sr-n1,
        # legacy, valtx
        assert mp_b.size() == 6

    def test_rejected_sig_is_retryable_with_fresh_nonce(self, acc):
        """A bad-signature rejection drops the seen-cache entry, so the
        corrected tx (same payload, properly signed) goes through."""
        for mp in (_mk_mp(ingress=None), _mk_mp(ingress=acc)):
            priv = _priv(b"retry-k")
            bad = bytearray(ing.make_signed_tx(priv, b"r=1", nonce=1))
            bad[-1] ^= 0x10
            assert mp.check_tx(bytes(bad)).code == CODE_BAD_SIGNATURE
            assert mp.check_tx(
                ing.make_signed_tx(priv, b"r=1", nonce=1)
            ).code == 0
            assert mp.size() == 1


# -- recheck after commit ------------------------------------------------


class TestRecheck:
    def test_recheck_after_commit_parity(self, acc):
        """update() runs under the caller-held lock and (on the batched
        path) resubmits survivors' signatures as one block-sized window:
        the surviving FIFO must match the sequential mempool exactly, and
        the batched path must not deadlock on its own lock."""
        a, b = _priv(b"rc-a"), _priv(b"rc-b")
        script = [
            ing.make_signed_tx(a, b"ra1=1", nonce=1),
            ing.make_signed_tx(a, b"ra2=2", nonce=2),
            ing.make_signed_tx(b, b"rb1=1", nonce=1),
            ing.make_signed_tx(b, b"rb2=2", nonce=2),
            b"plain1=v",
            make_validator_tx(a.pub_key().bytes(), 3),
        ]
        committed = [script[0], script[2], script[4]]
        deliver = [abci.ResponseDeliverTx(code=0) for _ in committed]
        fifos = []
        for ingress in (None, acc):
            mp = _mk_mp(ingress=ingress)
            for tx in script:
                assert mp.check_tx(tx).code == 0
            mp.lock()
            try:
                mp.update(1, committed, deliver)
            finally:
                mp.unlock()
            fifos.append(mp.txs_fifo())
            # a committed tx stays in the cache: resubmission is a dup
            with pytest.raises(DuplicateTxError):
                mp.check_tx(script[0])
        assert fifos[0] == fifos[1]
        assert set(fifos[0]) == {script[1], script[3], script[5]}


# -- QoS: consensus preempts queued ingress ------------------------------


class TestQoS:
    def test_commit_preempts_queued_ingress_windows(self):
        """Two ingress waves on a depth-1 mocked-relay pipeline: wave 1
        is in flight and wave 2 is parked at the depth semaphore when a
        PRIORITY_CONSENSUS block arrives — the commit must jump the
        queue (preemption counted, wave-2 futures still pending when it
        completes) and every tx verdict must still land.
        """
        _epoch.reset(8)
        rtt = 0.12
        real = pl.AsyncBatchVerifier._prepare
        pl.AsyncBatchVerifier._prepare = staticmethod(
            mock_mempool_prepare(real, rtt)
        )
        v = pl.AsyncBatchVerifier(depth=1)
        a = ing.IngressAccumulator(verifier=v, max_batch=32, window_ms=2.0)
        try:
            privs = [_priv(b"qos-%d" % i) for i in range(8)]
            stxs = [
                ing.parse_signed_tx(
                    ing.make_signed_tx(privs[i % 8], b"q%d=v" % i,
                                       nonce=i // 8 + 1)
                )
                for i in range(128)
            ]
            commit_block = EntryBlock.from_entries(
                [(s.pub, s.signed_bytes(), s.sig) for s in stxs[:16]]
            )
            wave1 = [a.submit(s) for s in stxs[:32]]
            a.flush_now()
            time.sleep(rtt / 3)  # wave 1 launched, in flight
            wave2 = [a.submit(s) for s in stxs[32:]]
            a.flush_now()
            time.sleep(rtt / 4)  # wave 2 prepped, parked on the depth sem
            cfut = v.submit(commit_block, priority=pl.PRIORITY_CONSENSUS)
            assert all(cfut.result(timeout=60))
            pending = sum(1 for f in wave2 if not f.done())
            assert pending > 0, "commit should complete before queued ingress"
            assert all(f.result(timeout=60) is True for f in wave1 + wave2)
            assert v.preempted_total >= 1
            assert a.stats()["preemptions"] >= 1
            drain_pool(v._pool)
            assert v._pool.stats()["in_flight"] == 0
        finally:
            a.close()
            v.close()
            pl.AsyncBatchVerifier._prepare = real


# -- DispatchError: a poisoned window fails alone ------------------------


class TestDispatchError:
    def test_poisoned_window_fails_alone_and_is_retryable(self):
        """Prep blows up for exactly one window size: that window's
        check_tx futures raise DispatchError, its txs drop out of the
        seen-cache (retryable), and neighbouring windows are untouched.
        """
        _epoch.reset(8)
        poison_n = 5
        real = pl.AsyncBatchVerifier._prepare

        def poisoned(entries, *args, **kw):
            n = len(entries.entries) if hasattr(entries, "entries") else len(entries)
            if n == poison_n:
                raise RuntimeError("injected poison")
            return real(entries, *args, **kw)

        pl.AsyncBatchVerifier._prepare = staticmethod(poisoned)
        v = pl.AsyncBatchVerifier(depth=2)
        # giant window: only explicit flush_now() submits, so each wave
        # below is exactly one device window
        a = ing.IngressAccumulator(verifier=v, max_batch=256,
                                   window_ms=60_000.0)
        mp = _mk_mp(ingress=a)
        try:
            privs = [_priv(b"poison-%d" % i) for i in range(16)]

            def wave(lo, hi, nonce):
                futs = [
                    mp.check_tx_async(
                        ing.make_signed_tx(privs[i], b"dw%d=%d" % (i, nonce),
                                           nonce=nonce)
                    )
                    for i in range(lo, hi)
                ]
                a.flush_now()
                return futs

            for f in wave(0, 4, 1):  # healthy window before
                assert f.result(timeout=60).code == 0
            poisoned_futs = wave(4, 4 + poison_n, 1)
            for f in poisoned_futs:
                with pytest.raises(pl.DispatchError):
                    f.result(timeout=60)
            for f in wave(12, 16, 1):  # healthy window after
                assert f.result(timeout=60).code == 0
            assert a.stats()["dispatch_errors"] >= 1
            # the poisoned txs were dropped from the seen-cache: each is
            # retryable, and a 1-tx window passes the poison filter
            for i in range(4, 4 + poison_n):
                [f] = wave(i, i + 1, 1)
                assert f.result(timeout=60).code == 0
            assert mp.size() == 4 + poison_n + 4
        finally:
            a.close()
            v.close()
            pl.AsyncBatchVerifier._prepare = real


# -- simnet: signed-tx flood through a partition+heal --------------------


def _flood_run(seed: int):
    """4-node cluster, partition {0,1,2}|{3} at height 3 (quorum stays
    with the majority, so consensus never stalls), heal after 3 virtual
    seconds. Signed txs flood in at commits 2 and 4 — including a forged
    signature and a nonce replay — via node 0's commit hook, a
    deterministic point in the event loop. Returns the report plus the
    per-tx accounting."""
    from tendermint_tpu.simnet import Cluster, Fault

    faults = [Fault(kind="partition", at_height=3,
                    groups=[[0, 1, 2], [3]], duration=3.0)]
    c = Cluster(n_nodes=4, seed=seed, faults=faults)
    privs = [_priv(b"flood-%d" % i) for i in range(4)]
    results = {}  # tx -> ("res", code) | ("exc", type name)
    fired = set()

    def submit(node, tx):
        try:
            results[tx] = ("res", node.mp.check_tx(tx).code)
        except Exception as e:  # noqa: BLE001 — recorded, never dropped
            results[tx] = ("exc", type(e).__name__)

    def inject(wave: int):
        for i, n in enumerate(c.nodes):
            for j in range(2):
                submit(n, ing.make_signed_tx(
                    privs[i], b"f%d_%d_%d=v" % (wave, i, j),
                    nonce=(wave - 1) * 2 + j + 1,
                ))
        # adversarial traffic on node 0: a forged signature and a
        # nonce replay — both must be rejected, not lost
        forged = bytearray(ing.make_signed_tx(privs[0], b"forged%d=1" % wave,
                                              nonce=99 + wave))
        forged[-1] ^= 0x42
        submit(c.nodes[0], bytes(forged))
        submit(c.nodes[0], ing.make_signed_tx(privs[0], b"replay%d=1" % wave,
                                              nonce=1))

    def on_commit(height: int):
        if height == 2 and "w1" not in fired:
            fired.add("w1")
            inject(1)
        elif height == 4 and "w2" not in fired:
            fired.add("w2")
            inject(2)

    c.nodes[0].cs._height_events.append(on_commit)
    report = c.run_to_height(6, max_virtual_s=600.0)
    committed = set()
    for n in c.nodes:
        for h in range(1, n.bstore.height() + 1):
            blk = n.bstore.load_block(h)
            if blk is not None:
                committed.update(blk.data.txs)
    in_mempool = set()
    for n in c.nodes:
        in_mempool.update(n.mp.txs_fifo())
    c.stop()
    return report, results, committed, in_mempool


class TestSimnetFlood:
    def test_flood_through_partition_heal(self):
        report, results, committed, in_mempool = _flood_run(seed=13)
        assert report.ok, report.reason
        assert not report.violations
        assert len(results) == 20, "both waves must have been injected"
        rejected = 0
        for tx, (kind, detail) in results.items():
            if kind == "res" and detail == 0:
                # accepted: either committed into a block or still
                # sitting in some live mempool — never silently lost
                assert tx in committed or tx in in_mempool, (
                    "accepted tx lost: %r" % tx[:20]
                )
            else:
                rejected += 1
                assert tx not in committed
        # the forged-sig and replay txs per wave were rejected loudly
        assert rejected >= 2

    def test_replay_exact(self):
        r1, res1, _, _ = _flood_run(seed=21)
        r2, res2, _, _ = _flood_run(seed=21)
        assert r1.ok and r2.ok, (r1.reason, r2.reason)
        assert r1.fingerprint == r2.fingerprint
        assert res1 == res2
