"""rpc/client local + mock parity (rpc/client/local/local.go:1,
rpc/client/mock/client.go:1): the in-process client apps embed, and the
canned-response/recording client tests are written against."""

import pytest

from tendermint_tpu.rpc import Call, LocalRPCClient, MockClient
from tendermint_tpu.rpc.core import Environment


class _FakeNode:
    pass


@pytest.fixture
def env():
    return Environment(_FakeNode())


class TestLocalRPCClient:
    def test_direct_environment_dispatch(self, env):
        lc = LocalRPCClient(env)
        # health needs no node state — direct in-process Environment call
        assert lc.health() == {}
        # attribute access resolves Environment methods, not copies
        assert lc.unconfirmed_txs.__self__ is env

    def test_unknown_method_raises(self, env):
        lc = LocalRPCClient(env)
        with pytest.raises(AttributeError):
            lc.not_a_route()


class TestMockClient:
    def test_canned_response_and_recording(self):
        mc = MockClient()
        mc.expect(Call("status", response={"node_info": {"moniker": "mock"}}))
        assert mc.status() == {"node_info": {"moniker": "mock"}}
        assert [c.name for c in mc.calls] == ["status"]
        assert mc.calls[0].response["node_info"]["moniker"] == "mock"

    def test_canned_error(self):
        mc = MockClient()
        mc.expect(Call("broadcast_tx_sync", error=ValueError("tx too big")))
        with pytest.raises(ValueError, match="tx too big"):
            mc.broadcast_tx_sync(tx="00")
        assert mc.calls[0].name == "broadcast_tx_sync"
        assert isinstance(mc.calls[0].error, ValueError)

    def test_args_matched_response(self):
        # mock/client.go GetResponse: both set -> response iff args match
        call = Call(
            "abci_query",
            args={"path": "/key", "data": "61"},
            response={"value": "ok"},
            error=KeyError("wrong args"),
        )
        mc = MockClient().expect(call)
        assert mc.abci_query(path="/key", data="61") == {"value": "ok"}
        with pytest.raises(KeyError):
            mc.abci_query(path="/other", data="61")

    def test_fallthrough_to_base(self, env):
        # unconfigured methods hit the wrapped (local) client, still
        # recorded — the recorder shape from mock/client.go
        mc = MockClient(base=LocalRPCClient(env))
        assert mc.health() == {}
        assert [c.name for c in mc.calls] == ["health"]

    def test_unconfigured_without_base(self):
        with pytest.raises(NotImplementedError):
            MockClient().genesis()
