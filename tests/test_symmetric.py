"""Symmetric crypto utilities: xchacha20poly1305 + xsalsa20symmetric.

Vectors: HChaCha20 and the AEAD vector are the reference's own test data
(crypto/xchacha20poly1305/{xchachapoly_test.go,vector_test.go}, which are
in turn the draft-irtf-cfrg-xchacha vectors). xsalsa20symmetric matches
the reference's roundtrip strategy (crypto/xsalsa20symmetric/
symmetric_test.go) plus tamper/length failure cases.
"""

import pytest

from tendermint_tpu.crypto import symmetric as S


HCHACHA_VECTORS = [
    # (key, nonce16, out) — xchachapoly_test.go hChaCha20Vectors
    ("00" * 32, "00" * 16,
     "1140704c328d1d5d0e30086cdf209dbd6a43b8f41518a11cc387b669b2ee6586"),
    ("80" + "00" * 31, "00" * 16,
     "7d266a7fd808cae4c02a0a70dcbfbcc250dae65ce3eae7fc210f54cc8f77df86"),
    # Go vector 3's 24-byte nonce has its 0x02 at byte 23 — beyond the 16
    # bytes HChaCha20 reads, so the effective nonce is all-zero
    ("00" * 31 + "01", "00" * 16,
     "e0c77ff931bb9163a5460c02ac281c2b53d792b1c43fea817e9ad275ae546963"),
    ("000102030405060708090a0b0c0d0e0f101112131415161718191a1b1c1d1e1f",
     "000102030405060708090a0b0c0d0e0f",
     "51e3ff45a895675c4b33b46c64f4a9ace110d34df6a2ceab486372bacbd3eff6"),
    ("24f11cce8a1b3d61e441561a696c1c1b7e173d084fd4812425435a8896a013dc",
     "d9660c5900ae19ddad28d6e06e45fe5e",
     "5966b3eec3bff1189f831f06afe4d4e3be97fa9235ec8c20d08acfbbb4e851e3"),
]


class TestXChaCha20Poly1305:
    def test_hchacha20_vectors(self):
        for key, nonce, want in HCHACHA_VECTORS:
            got = S.hchacha20(bytes.fromhex(key), bytes.fromhex(nonce))
            assert got.hex() == want

    def test_aead_ietf_vector(self):
        # vector_test.go vectors[0] (draft-irtf-cfrg-xchacha A.1-style);
        # the Go test copies the 16-byte nonce into [24]byte (zero pad).
        key = bytes(range(0x80, 0xA0))
        nonce = bytes.fromhex("07000000404142434445464748494a4b") + b"\x00" * 8
        ad = bytes.fromhex("50515253c0c1c2c3c4c5c6c7")
        plaintext = (
            b"Ladies and Gentlemen of the class of '99: If I could offer you "
            b"only one tip for the future, sunscreen would be it."
        )
        want = (
            "453c0693a7407f04ff4c56aedb17a3c0a1afff01174930fc22287c33dbcf0ac8"
            "b89ad929530a1bb3ab5e69f24c7f6070c8f840c9abb4f69fbfc8a7ff5126faee"
            "bbb55805ee9c1cf2ce5a57263287aec5780f04ec324c3514122cfc3231fc1a8b"
            "718a62863730a2702bb76366116bed09e0fd5c6d84b6b0c1abaf249d5dd0f7f5"
            "a7ea"
        )
        aead = S.XChaCha20Poly1305(key)
        ct = aead.seal(nonce, plaintext, ad)
        assert ct.hex() == want
        assert aead.open(nonce, ct, ad) == plaintext

    def test_aead_reject(self):
        aead = S.XChaCha20Poly1305(b"\x01" * 32)
        nonce = b"\x02" * 24
        ct = aead.seal(nonce, b"hello", b"ad")
        bad = ct[:-1] + bytes([ct[-1] ^ 1])
        with pytest.raises(ValueError):
            aead.open(nonce, bad, b"ad")
        with pytest.raises(ValueError):
            aead.open(nonce, ct, b"wrong-ad")
        with pytest.raises(ValueError):
            S.XChaCha20Poly1305(b"\x01" * 16)
        with pytest.raises(ValueError):
            aead.seal(b"\x00" * 12, b"x")


class TestXSalsa20Symmetric:
    def test_roundtrip(self):
        # symmetric_test.go TestSimple
        plaintext = b"sometext"
        secret = b"somesecretoflengththirtytwo===32"
        ct = S.encrypt_symmetric(plaintext, secret)
        assert len(ct) == len(plaintext) + 24 + 16  # nonce + overhead
        assert S.decrypt_symmetric(ct, secret) == plaintext

    def test_kdf_style_secret_and_sizes(self):
        import hashlib

        secret = hashlib.sha256(b"somesecret-bcrypt-output").digest()
        # n = 0 round-trips through seal, but DecryptSymmetric rejects
        # len == overhead+nonce exactly like the reference's `<=` check
        for n in (1, 63, 64, 65, 200):
            pt = bytes(range(256))[:n] * 1
            ct = S.encrypt_symmetric(pt, secret)
            assert S.decrypt_symmetric(ct, secret) == pt

    def test_failures(self):
        secret = b"\x07" * 32
        ct = S.encrypt_symmetric(b"payload", secret)
        with pytest.raises(ValueError):
            S.decrypt_symmetric(ct[:30], secret)  # too short
        tampered = ct[:-1] + bytes([ct[-1] ^ 1])
        with pytest.raises(ValueError):
            S.decrypt_symmetric(tampered, secret)
        with pytest.raises(ValueError):
            S.decrypt_symmetric(ct, b"\x08" * 32)  # wrong key
        with pytest.raises(ValueError):
            S.encrypt_symmetric(b"x", b"short")

    def test_nonce_uniqueness(self):
        secret = b"\x07" * 32
        a = S.encrypt_symmetric(b"same", secret)
        b = S.encrypt_symmetric(b"same", secret)
        assert a != b  # random nonces

    def test_xsalsa20_block_structure(self):
        """The XSalsa20 KDF path: same key/nonce -> same stream; different
        16-byte prefixes -> different subkeys."""
        k = b"\x01" * 32
        assert S.hsalsa20(k, b"\x00" * 16) != S.hsalsa20(k, b"\x01" * 16)
        s1 = S._xsalsa20_stream(k, b"\x02" * 24, 100)
        s2 = S._xsalsa20_stream(k, b"\x02" * 24, 100)
        assert s1 == s2 and len(s1) == 100
