"""tmlint framework + rule tests (ISSUE 8).

Pure-AST layer: everything here runs without jax, numpy, or the crypto
wheel — fixture snippets per rule (positive / negative / suppressed /
baselined), suppression-comment parsing, baseline round-trip, the CLI
exit-code contract, and THE tier-1 gate: tmlint over the real tree must
report zero non-baselined findings.

The positive fixtures double as the static half of the seeded-regression
requirement: `PR7_ALIAS_BUG` re-introduces the exact readback-aliasing
shape PR 7 shipped and fixed, and `SINGLE_OWNER_BUG` a relay launch
outside the dispatcher — each pass must flag its bug class.
"""

import json
import os
import subprocess
import sys
import textwrap

import pytest

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO_ROOT not in sys.path:
    sys.path.insert(0, REPO_ROOT)

from tools.tmlint import core, run_source  # noqa: E402
from tools.tmlint.rules import ALL_RULES, RULES_BY_NAME  # noqa: E402

OPS_PATH = "tendermint_tpu/ops/fake_mod.py"
SIMNET_PATH = "tendermint_tpu/simnet/fake_mod.py"
REACTOR_PATH = "tendermint_tpu/blocksync/fake_mod.py"
LIGHT_PATH = "tendermint_tpu/light/fake_service.py"
HOT_PATH = "tendermint_tpu/ops/entry_block.py"


def lint(src: str, path: str, rule: str = None):
    rules = [RULES_BY_NAME[rule]] if rule else ALL_RULES
    return run_source(textwrap.dedent(src), path, rules)


def rules_of(findings):
    return [f.rule for f in findings]


# ---------------------------------------------------------------------------
# the seeded-regression fixtures: each checker's bug class, re-introduced


PR7_ALIAS_BUG = """
    import numpy as np

    def _resolve(spans, dev):
        arr = np.asarray(dev)          # zero-copy view of the XLA buffer
        for job, off, n in spans:
            job.future.set_result(arr[off : off + n])
"""

PR7_ALIAS_FIXED = """
    import numpy as np

    def _resolve(spans, dev):
        arr = np.asarray(dev)
        if not arr.flags.owndata:
            arr = np.array(arr, copy=True)
        for job, off, n in spans:
            job.future.set_result(arr[off : off + n])
"""

SINGLE_OWNER_BUG = """
    import jax

    def sneaky_verify(args):
        return jax.device_put(args)    # relay touch outside the dispatcher
"""


class TestSeededRegressions:
    def test_pr7_alias_bug_is_flagged(self):
        fs = lint(PR7_ALIAS_BUG, OPS_PATH, "donation-aliasing")
        assert fs, "the PR-7 readback-aliasing bug class must be flagged"
        assert "set_result" in fs[0].message.lower() or "escapes" in fs[0].message

    def test_pr7_fixed_shape_is_clean(self):
        assert not lint(PR7_ALIAS_FIXED, OPS_PATH, "donation-aliasing")

    def test_single_owner_violation_is_flagged(self):
        fs = lint(SINGLE_OWNER_BUG, REACTOR_PATH, "relay-ownership")
        assert fs and fs[0].rule == "relay-ownership"

    def test_single_owner_ok_inside_dispatcher(self):
        assert not lint(
            SINGLE_OWNER_BUG, "tendermint_tpu/ops/pipeline.py",
            "relay-ownership",
        )


# ---------------------------------------------------------------------------
# per-rule positive / negative / suppressed / baselined


class TestDonationAliasing:
    def test_positive_return_asarray(self):
        src = """
            import numpy as np
            def f(dev):
                return np.asarray(dev)
        """
        assert rules_of(lint(src, OPS_PATH)) == ["donation-aliasing"]

    def test_positive_tainted_slice_append(self):
        src = """
            import numpy as np
            def f(devs):
                out = []
                for d in devs:
                    res = np.asarray(d)[:4]
                    out.append(res)
                return out
        """
        assert "donation-aliasing" in rules_of(lint(src, OPS_PATH))

    def test_positive_annotated_assignment(self):
        # review fix: a type annotation must not launder the taint
        src = """
            import numpy as np
            def f(dev):
                res: np.ndarray = np.asarray(dev)
                return res
        """
        assert rules_of(lint(src, OPS_PATH)) == ["donation-aliasing"]

    def test_positive_walrus_assignment(self):
        src = """
            import numpy as np
            def f(dev):
                if (res := np.asarray(dev)) is not None:
                    return res
        """
        assert rules_of(lint(src, OPS_PATH)) == ["donation-aliasing"]

    def test_positive_tuple_assignment(self):
        src = """
            import numpy as np
            def f(dev, other):
                a, b = np.asarray(dev), other
                return a
        """
        assert rules_of(lint(src, OPS_PATH)) == ["donation-aliasing"]

    def test_negative_owned_copy(self):
        src = """
            import numpy as np
            def f(dev):
                return np.asarray(dev)[:4].copy()
        """
        assert not lint(src, OPS_PATH, "donation-aliasing")

    def test_positive_owned_init_overwritten_by_view(self):
        # review fix: last binding per name wins — an owned init must not
        # launder a later device-view reassignment (the PR-7 shape)
        src = """
            import numpy as np
            def f(dev, n):
                out = np.zeros(n)
                out = np.asarray(dev)[:n]
                return out
        """
        assert rules_of(lint(src, OPS_PATH)) == ["donation-aliasing"]

    def test_negative_owndata_guard_pattern(self):
        src = """
            import numpy as np
            def f(dev):
                arr = np.asarray(dev)
                arr = np.array(arr, copy=True)
                return arr[:3]
        """
        assert not lint(src, OPS_PATH, "donation-aliasing")

    def test_negative_outside_ops(self):
        src = """
            import numpy as np
            def f(dev):
                return np.asarray(dev)
        """
        assert not lint(src, "tendermint_tpu/light/client.py",
                        "donation-aliasing")

    def test_negative_owned_array_of_launch(self):
        # the ISSUE 19 secp chunked-verify shape: np.array(...) copies
        # by default (numpy 2), so slicing/appending the result is clean
        src = """
            import numpy as np
            def f(kern, args, n):
                res = np.array(kern(*args))
                return res[:n]
        """
        assert not lint(src, OPS_PATH, "donation-aliasing")

    def test_suppressed(self):
        src = """
            import numpy as np
            def f(dev):
                return np.asarray(dev)  # tmlint: disable=donation-aliasing — consumer copies
        """
        assert not lint(src, OPS_PATH, "donation-aliasing")


class TestRelayOwnership:
    def test_positive_entry_points(self):
        src = """
            def f(backend, args):
                k = backend.cached_kernel(None, True, True)
                return k(*args)
        """
        assert rules_of(lint(src, REACTOR_PATH)) == ["relay-ownership"]

    def test_positive_qualified_transfer(self):
        src = """
            def f(_dpool, args):
                return _dpool.transfer(args)
        """
        assert rules_of(lint(src, REACTOR_PATH)) == ["relay-ownership"]

    def test_negative_bare_transfer_is_not_flagged(self):
        src = """
            def f(conn, data):
                return conn.transfer(data)
        """
        assert not lint(src, REACTOR_PATH, "relay-ownership")

    def test_negative_whitelisted_module(self):
        src = """
            import jax
            def f(x):
                return jax.device_put(x)
        """
        assert not lint(src, "tendermint_tpu/ops/device_pool.py",
                        "relay-ownership")

    def test_suppressed_next_line_comment(self):
        src = """
            import jax
            def f(x):
                # tmlint: disable=relay-ownership — sanctioned one-off
                return jax.device_put(x)
        """
        assert not lint(src, REACTOR_PATH, "relay-ownership")

    def test_positive_mesh_launch_outside_whitelist(self):
        """ISSUE 9 satellite: a non-whitelisted mesh superbatch launch —
        building the mesh kernel or touching the replicated epoch
        tables outside the dispatcher modules — is flagged."""
        src = """
            from tendermint_tpu.ops import sharded

            def sneaky_mesh_verify(mesh, args):
                fn = sharded.mesh_valid_fn(mesh, donate=True)
                return fn(*args)
        """
        assert rules_of(lint(src, REACTOR_PATH)) == ["relay-ownership"]
        src_tbl = """
            def sneaky_tables(ep, mesh):
                return ep.sharded_xla_tables(mesh)
        """
        assert rules_of(lint(src_tbl, REACTOR_PATH)) == ["relay-ownership"]
        src_sh = """
            from tendermint_tpu.ops.sharded import epoch_tables_sharded

            def sneaky(ep, mesh):
                return epoch_tables_sharded(ep, mesh)
        """
        assert rules_of(lint(src_sh, REACTOR_PATH)) == ["relay-ownership"]

    def test_negative_mesh_module_is_whitelisted(self):
        src = """
            def prep(block, plan, _sharded, mesh):
                fn = _sharded.mesh_valid_fn_cached(mesh, None)
                return fn
        """
        assert not lint(src, "tendermint_tpu/ops/mesh.py",
                        "relay-ownership")
        # the packing entry point itself is an ENTRY_POINT elsewhere
        src_prep = """
            from tendermint_tpu.ops import mesh

            def f(block, plan):
                return mesh.prepare_superbatch(block, plan)
        """
        assert rules_of(lint(src_prep, REACTOR_PATH)) == ["relay-ownership"]

    # -- ISSUE 11: the light service's dispatch path -----------------------

    def test_positive_light_service_direct_relay(self):
        """A light-service-shaped module touching the relay directly —
        launching, transferring, or wiring a mocked-relay device double
        into the pipeline — is flagged; the service must submit through
        AsyncBatchVerifier."""
        src = """
            import jax

            def verify_unique(self, stages):
                return [jax.device_put(st.entries) for st in stages]
        """
        assert rules_of(lint(src, LIGHT_PATH)) == ["relay-ownership"]
        src_mock = """
            from tendermint_tpu.ops._testing import mock_light_prepare

            def install_fast_path(pl):
                pl.AsyncBatchVerifier._prepare = mock_light_prepare(
                    pl.AsyncBatchVerifier._prepare, 0.0
                )
        """
        assert rules_of(lint(src_mock, LIGHT_PATH)) == ["relay-ownership"]

    def test_negative_light_service_submit_pattern(self):
        """The real service shape — EntryBlocks submitted to the shared
        verifier, verdicts via futures — is clean."""
        src = """
            def verify_unique(self, stages, fid):
                futs = [self._v.submit(st.entries, flow=fid) for st in stages]
                return [f.result(timeout=600) for f in futs]
        """
        assert not lint(src, LIGHT_PATH, "relay-ownership")

    # -- ISSUE 20: BLS aggregation lane launch builders --------------------

    def test_positive_bls_pairing_launch_outside_whitelist(self):
        """ISSUE 20 satellite: jitting the fused multi-pairing kernel or
        driving the direct BLS code-row path outside the dispatcher
        whitelist is flagged — aggregated commits reach the device only
        through AsyncBatchVerifier / the mesh."""
        src = """
            from tendermint_tpu.ops import bls_verify

            def sneaky_pairing(gx, gy, masks, coeffs):
                fn = bls_verify.jitted_bls_verify(True)
                return fn(gx, gy, masks, coeffs)
        """
        assert rules_of(lint(src, REACTOR_PATH)) == ["relay-ownership"]
        src_kern = """
            def sneaky_kernel(_backend, blk):
                return _backend.bls_kernel(blk.bucket)(blk.rows)
        """
        assert rules_of(lint(src_kern, REACTOR_PATH)) == ["relay-ownership"]
        src_codes = """
            from tendermint_tpu.ops.backend import verify_batch_bls_codes

            def sneaky_codes(blk):
                return verify_batch_bls_codes(blk)
        """
        assert rules_of(lint(src_codes, REACTOR_PATH)) == ["relay-ownership"]

    def test_negative_bls_kernel_module_is_whitelisted(self):
        """The kernel-definition module and the sanctioned direct path in
        ops/backend.py hold these call sites legitimately."""
        src = """
            def _warm(gx, gy, masks, coeffs):
                return jitted_bls_verify(False)(gx, gy, masks, coeffs)
        """
        assert not lint(src, "tendermint_tpu/ops/bls_verify.py",
                        "relay-ownership")
        src_backend = """
            def verify_batch_bls(blk):
                codes = verify_batch_bls_codes(blk)
                return codes == 1
        """
        assert not lint(src_backend, "tendermint_tpu/ops/backend.py",
                        "relay-ownership")


class TestFleetTransport:
    """ISSUE 18: the fleet wire codec has exactly three sanctioned homes
    (fleet/wire.py, fleet/client.py, fleet/server.py) — frame encode /
    parse call sites anywhere else fork a versioned protocol surface."""

    def test_positive_encode_outside_fleet(self):
        src = """
            from tendermint_tpu.fleet import wire

            def sneaky_send(sock, rid, block):
                for buf in wire.encode_submit(rid, block, lane="rogue"):
                    sock.sendall(buf)
        """
        assert rules_of(lint(src, REACTOR_PATH)) == ["fleet-transport"]

    def test_positive_parse_and_decoder_outside_fleet(self):
        src = """
            from tendermint_tpu.fleet.wire import FrameDecoder, parse_frame

            def sneaky_recv(sock):
                dec = FrameDecoder()
                for payload in dec.feed(sock.recv(65536)):
                    yield parse_frame(payload)
        """
        assert sorted(rules_of(lint(src, REACTOR_PATH))) == [
            "fleet-transport", "fleet-transport"
        ]

    def test_negative_raw_sockets_stay_legal(self):
        """Generic socket traffic is NOT the invariant — rpc/, privval/,
        and p2p/ own their sockets; only the fleet codec is fenced."""
        src = """
            def send_all(conn, data):
                conn.sendall(data)
                return conn.recv(4096)
        """
        assert not lint(src, "tendermint_tpu/p2p/fake_transport.py",
                        "fleet-transport")

    def test_negative_whitelisted_modules(self):
        src = """
            from . import wire

            def reply(outbox, rid, verdicts):
                outbox.put(wire.encode_verdicts(rid, verdicts))
        """
        for path in ("tendermint_tpu/fleet/wire.py",
                     "tendermint_tpu/fleet/client.py",
                     "tendermint_tpu/fleet/server.py"):
            assert not lint(src, path, "fleet-transport")

    def test_negative_fleet_client_usage_is_clean(self):
        """The sanctioned consumer shape — a lane handing windows to a
        FleetClient via the LaneSpec verifier seam — is clean."""
        src = """
            from tendermint_tpu.fleet.client import FleetClient

            def make_lane_verifier(addr):
                return FleetClient(addr, name="node-a")
        """
        assert not lint(src, REACTOR_PATH, "fleet-transport")

    def test_suppressed_next_line_comment(self):
        src = """
            from tendermint_tpu.fleet import wire

            def forge(rid):
                # tmlint: disable=fleet-transport — wire-format test rig
                return wire.encode_error(rid, 3, "boom")
        """
        assert not lint(src, REACTOR_PATH, "fleet-transport")


class TestSimnetDeterminism:
    def test_positive_wall_clock(self):
        src = """
            import time
            def f():
                return time.time()
        """
        assert rules_of(lint(src, SIMNET_PATH)) == ["simnet-determinism"]

    def test_positive_global_rng_and_entropy(self):
        src = """
            import os, random
            def f():
                return random.random() + len(os.urandom(8))
        """
        assert rules_of(lint(src, SIMNET_PATH)) == [
            "simnet-determinism", "simnet-determinism"
        ]

    def test_positive_unseeded_random_instance(self):
        src = """
            import random
            def f():
                return random.Random()
        """
        assert lint(src, SIMNET_PATH, "simnet-determinism")

    def test_negative_seeded_rng_and_injected_clock(self):
        src = """
            import random
            def f(self, seed):
                rng = random.Random(seed)
                return rng.random() + self._now()
        """
        assert not lint(src, SIMNET_PATH, "simnet-determinism")

    def test_positive_set_iteration(self):
        src = """
            def f(peers):
                live = set(peers)
                for p in live:
                    p.poke()
        """
        assert lint(src, SIMNET_PATH, "simnet-determinism")

    def test_negative_sorted_set_iteration(self):
        src = """
            def f(peers):
                for p in sorted(set(peers)):
                    p.poke()
        """
        assert not lint(src, SIMNET_PATH, "simnet-determinism")

    def test_negative_outside_scope(self):
        src = """
            import time
            def f():
                return time.time()
        """
        assert not lint(src, "tendermint_tpu/rpc/fake.py",
                        "simnet-determinism")

    def test_positive_light_scope(self):
        """ISSUE 11 satellite: light/ is in the deterministic scope — a
        wall-clock read in a light-client module is flagged (the
        sanctioned default lives in libs/timeutil, injected via now_fn)."""
        src = """
            import time as _time
            def _now_ts():
                return _time.time()
        """
        assert rules_of(
            lint(src, "tendermint_tpu/light/client.py")
        ) == ["simnet-determinism"]

    def test_negative_light_injected_clock(self):
        src = """
            def verify_at(self, height, now=None):
                now = now or self._now_ts()
                return (height, now)
        """
        assert not lint(src, "tendermint_tpu/light/client.py",
                        "simnet-determinism")

    def test_light_tree_is_clean_without_suppressions(self):
        """The REAL light/ modules lint clean with zero suppressions —
        the satellite's acceptance: clock injection landed everywhere."""
        import tokenize

        light_dir = os.path.join(REPO_ROOT, "tendermint_tpu", "light")
        for name in sorted(os.listdir(light_dir)):
            if not name.endswith(".py"):
                continue
            path = os.path.join(light_dir, name)
            with open(path) as fh:
                src = fh.read()
            rel = f"tendermint_tpu/light/{name}"
            assert not run_source(src, rel, [RULES_BY_NAME["simnet-determinism"]]), \
                f"{rel} has determinism findings"
            with open(path, "rb") as fh:
                for tok in tokenize.tokenize(fh.readline):
                    if tok.type == tokenize.COMMENT:
                        assert "disable=simnet-determinism" not in tok.string, \
                            f"{rel} suppresses the determinism pass"

    def test_suppressed(self):
        src = """
            import time
            def f():
                return time.time()  # tmlint: disable=simnet-determinism — wall budget only
        """
        assert not lint(src, SIMNET_PATH, "simnet-determinism")


class TestHotPathPurity:
    def test_positive_per_element_loop(self):
        src = """
            def f(xs, out):
                for i in range(len(xs)):
                    out.append(xs[i])
        """
        assert rules_of(lint(src, HOT_PATH)) == ["hot-path-purity"]

    def test_positive_entries_loop(self):
        src = """
            def f(entries):
                acc = []
                for e in entries:
                    acc.append(e[0])
                return acc
        """
        assert lint(src, HOT_PATH, "hot-path-purity")

    def test_negative_grouped_loop(self):
        src = """
            import numpy as np
            def f(lens, buf):
                groups = []
                for length in np.unique(lens):
                    groups.append((length, buf))
                return groups
        """
        assert not lint(src, HOT_PATH, "hot-path-purity")

    def test_negative_other_module(self):
        src = """
            def f(xs, out):
                for i in range(len(xs)):
                    out.append(xs[i])
        """
        assert not lint(src, "tendermint_tpu/ops/backend.py",
                        "hot-path-purity")

    def test_fallback_marker_covers_function(self):
        src = """
            def f(xs):  # tmlint: fallback — object-path composer
                out = []
                for i in range(len(xs)):
                    out.append(xs[i])
                return out
        """
        assert not lint(src, HOT_PATH, "hot-path-purity")


class TestLockDiscipline:
    def test_positive_bare_acquire(self):
        src = """
            def f(self):
                self._mtx.acquire()
        """
        assert rules_of(lint(src, REACTOR_PATH)) == ["lock-discipline"]

    def test_negative_semaphore_and_with(self):
        src = """
            def f(self):
                self._sem.acquire()
                with self._mtx:
                    pass
        """
        assert not lint(src, REACTOR_PATH, "lock-discipline")

    def test_negative_assigned_acquire_result(self):
        src = """
            def f(self):
                slot = self._pool.acquire(("k",))
                return slot
        """
        assert not lint(src, REACTOR_PATH, "lock-discipline")

    def test_positive_lambda_thread_target(self):
        src = """
            import threading
            def f():
                t = threading.Thread(target=lambda: None)
                t.start()
        """
        assert rules_of(lint(src, REACTOR_PATH)) == ["lock-discipline"]

    def test_positive_relay_touching_thread_target(self):
        src = """
            import threading, jax
            def worker(x):
                jax.device_put(x)
            def f():
                threading.Thread(target=worker).start()
        """
        fs = lint(src, REACTOR_PATH)
        # the worker body also trips relay-ownership; the thread-target
        # finding is the lock-discipline one
        assert "lock-discipline" in rules_of(fs)

    def test_suppressed(self):
        src = """
            def f(self):
                self._mtx.acquire()  # tmlint: disable=lock-discipline — paired API
        """
        assert not lint(src, REACTOR_PATH, "lock-discipline")

    # -- ISSUE 13: .result() under a state mutex ------------------------

    def test_positive_result_under_mutex(self):
        """The bad shape satellite 2 removed from the mempool: waiting on
        a device verdict while holding the mempool's state mutex — the
        completing thread (the ingress completer) needs that same lock to
        finish CheckTx, so this deadlocks."""
        src = """
            def check_tx(self, tx):
                fut = self._ingress.submit(tx)
                with self._mtx:
                    verdict = fut.result(timeout=300)
                return verdict
        """
        fs = lint(src, "tendermint_tpu/mempool/fake_mod.py",
                  "lock-discipline")
        assert fs and "_mtx" in fs[0].message

    def test_positive_result_under_module_level_mtx_name(self):
        src = """
            def f(mtx, fut):
                with mtx:
                    return fut.result()
        """
        assert rules_of(
            lint(src, REACTOR_PATH, "lock-discipline")
        ) == ["lock-discipline"]

    def test_negative_result_outside_mutex(self):
        """The fixed shape: resolve the future first, take the lock for
        the state mutation only."""
        src = """
            def check_tx(self, tx):
                fut = self._ingress.submit(tx)
                verdict = fut.result(timeout=300)
                with self._mtx:
                    self._insert(tx, verdict)
                return verdict
        """
        assert not lint(src, "tendermint_tpu/mempool/fake_mod.py",
                        "lock-discipline")

    def test_negative_result_under_coordination_lock(self):
        """Locks NOT named *mtx* are out of scope: pipeline.py's chunked
        submit collects sub-results under `done_lock` by design (the
        completer there never needs that lock)."""
        src = """
            def _combine(done_lock, futs):
                out = []
                with done_lock:
                    for f in futs:
                        out.append(f.result())
                return out
        """
        assert not lint(src, "tendermint_tpu/ops/fake_mod.py",
                        "lock-discipline")

    def test_negative_result_in_nested_def_under_mutex(self):
        """A callback DEFINED under the lock runs later on another frame
        — defining it is not waiting under the lock."""
        src = """
            def f(self, fut):
                with self._mtx:
                    def _done(f):
                        return f.result()
                    fut.add_done_callback(_done)
        """
        assert not lint(src, "tendermint_tpu/mempool/fake_mod.py",
                        "lock-discipline")

    # -- ISSUE 13: ingress accumulator relay discipline ------------------

    def test_positive_ingress_wiring_mock_outside_whitelist(self):
        """Wiring the mempool mocked-relay double into the pipeline from
        production ingress code is a relay violation — only bench/gate
        harnesses (and ops/_testing.py itself) may do that."""
        src = """
            from tendermint_tpu.ops._testing import mock_mempool_prepare

            def fast_path(pl):
                pl.AsyncBatchVerifier._prepare = mock_mempool_prepare(
                    pl.AsyncBatchVerifier._prepare, 0.0
                )
        """
        assert rules_of(
            lint(src, "tendermint_tpu/mempool/ingress.py",
                 "relay-ownership")
        ) == ["relay-ownership"]

    def test_negative_ingress_accumulator_submit_path(self):
        """The real accumulator shape — EntryBlocks submitted to the
        shared verifier with an ingress priority, verdicts via futures —
        is clean: no relay entry point in sight."""
        src = """
            def _flush_device(self, batch):
                block = self._pack(batch)
                fut = self._verifier.submit(
                    block, priority=1
                )
                fut.add_done_callback(
                    self._on_device_done
                )
        """
        assert not lint(src, "tendermint_tpu/mempool/ingress.py",
                        "relay-ownership")

    # -- ISSUE 15: vote-ingress submit path ------------------------------

    def test_positive_vote_submit_under_window_mutex(self):
        """The shape _flush_window must never regress to: submitting the
        packed EntryBlock while still holding the accumulator's window
        mutex. submit() blocks on the pipeline depth semaphore under
        backpressure, and the verdict pump needs _mtx to stage the next
        window — a full stall of live-vote ingress."""
        src = """
            def _flush_window(self, key):
                with self._mtx:
                    batch = self._windows.pop(key)
                    fut = self._ensure_verifier().submit(
                        batch.block, priority=0
                    )
                return fut
        """
        fs = lint(src, "tendermint_tpu/consensus/fake_ingress.py",
                  "lock-discipline")
        assert fs and "depth semaphore" in fs[0].message

    def test_positive_vote_verdict_wait_under_mutex(self):
        """Waiting for a vote verdict under the VoteSet mutex is the
        ISSUE-13 shape resurfacing on the consensus side."""
        src = """
            def add_vote(self, vote):
                fut = self._ingress.submit(vote)
                with self._mtx:
                    return fut.result(timeout=60)
        """
        fs = lint(src, "tendermint_tpu/consensus/fake_ingress.py",
                  "lock-discipline")
        assert fs and "_mtx" in fs[0].message

    def test_negative_vote_ingress_stage_then_submit(self):
        """The real accumulator discipline: stage under _mtx, pop the
        window, RELEASE, then submit — clean."""
        src = """
            def _flush_window(self, key):
                with self._mtx:
                    batch = self._windows.pop(key)
                    self._inflight += 1
                fut = self._ensure_verifier().submit(
                    batch.block, priority=0
                )
                return fut
        """
        assert not lint(src, "tendermint_tpu/consensus/fake_ingress.py",
                        "lock-discipline")

    def test_negative_executor_pool_submit_under_lock(self):
        """Executor-pool submits are non-blocking enqueues, not pipeline
        dispatches — out of shape-4 scope even under a mutex."""
        src = """
            def f(self, entries):
                with self._mtx:
                    fut = prep_pool.submit(self._prepare, entries)
                return fut
        """
        assert not lint(src, "tendermint_tpu/ops/fake_mod.py",
                        "lock-discipline")

    def test_positive_vote_mock_wired_from_consensus(self):
        """mock_vote_prepare is a bench/gate double: wiring it from
        production consensus code is a relay violation."""
        src = """
            from tendermint_tpu.ops._testing import mock_vote_prepare

            def fast_votes(pl):
                pl.AsyncBatchVerifier._prepare = mock_vote_prepare(
                    pl.AsyncBatchVerifier._prepare, 0.0
                )
        """
        assert rules_of(
            lint(src, "tendermint_tpu/consensus/vote_ingress.py",
                 "relay-ownership")
        ) == ["relay-ownership"]


class TestIngressDiscipline:
    """ISSUE 17: the four hand-rolled windowed accumulators were unified
    behind ops/ingress.py; a fifth private batching stack (flush-timer
    thread + EntryBlock assembly in one module) must never grow back."""

    ACCUMULATOR_BUG = """
        import threading
        from ..ops.entry_block import EntryBlock

        class MyAccumulator:
            def __init__(self, verifier):
                self._verifier = verifier
                self._pending = []
                self._thread = threading.Thread(
                    target=self._flush_loop, daemon=True)
                self._thread.start()

            def _flush_loop(self):
                while True:
                    block = EntryBlock.from_entries(
                        [(p.pub, p.msg, p.sig) for p in self._pending])
                    self._verifier.submit(block)
    """

    def test_positive_private_accumulator(self):
        """The exact pre-ISSUE-17 shape: a per-workload flusher thread
        assembling EntryBlocks for submission."""
        fs = lint(self.ACCUMULATOR_BUG, REACTOR_PATH, "ingress-discipline")
        assert rules_of(fs) == ["ingress-discipline"]
        assert "LaneSpec" in fs[0].message

    def test_positive_window_timer_thread(self):
        src = """
            import threading
            from .entry_block import EntryBlock

            def start(pending, verifier):
                def _window_timer():
                    verifier.submit(EntryBlock.from_entries(pending))
                threading.Thread(target=_window_timer).start()
        """
        assert rules_of(
            lint(src, OPS_PATH, "ingress-discipline")
        ) == ["ingress-discipline"]

    def test_negative_assembly_without_thread(self):
        """Building EntryBlocks alone is fine — the replay prep path and
        every bench do it; the engine owns the flush cadence."""
        src = """
            from ..ops.entry_block import EntryBlock

            def prepare(votes):
                return EntryBlock.from_entries(
                    [(v.pub, v.msg, v.sig) for v in votes])
        """
        assert not lint(src, REACTOR_PATH, "ingress-discipline")

    def test_negative_thread_without_assembly(self):
        """Threads with flush-ish targets but no EntryBlock assembly are
        out of scope (the soak harness drains queues on threads)."""
        src = """
            import threading

            def start(q):
                threading.Thread(target=q.drain_loop, daemon=True).start()
        """
        assert not lint(src, REACTOR_PATH, "ingress-discipline")

    def test_negative_unrelated_thread_target(self):
        """A worker thread that is not a flush loop does not pair with
        assembly elsewhere in the module."""
        src = """
            import threading
            from .entry_block import EntryBlock

            def start(sock, votes):
                threading.Thread(target=sock.read_loop).start()
                return EntryBlock.from_entries(votes)
        """
        assert not lint(src, OPS_PATH, "ingress-discipline")

    def test_whitelisted_engine_module(self):
        """The engine itself is the one sanctioned owner."""
        assert not lint(self.ACCUMULATOR_BUG,
                        "tendermint_tpu/ops/ingress.py",
                        "ingress-discipline")

    def test_suppressed(self):
        src = """
            import threading
            from .entry_block import EntryBlock

            def start(pending, verifier):
                def _flush():
                    verifier.submit(EntryBlock.from_entries(pending))
                # tmlint: disable=ingress-discipline -- migration shim
                threading.Thread(target=_flush).start()
        """
        assert not lint(src, OPS_PATH, "ingress-discipline")


# ---------------------------------------------------------------------------
# framework mechanics


class TestSuppressionParsing:
    def test_multi_rule_and_justification(self):
        sup = core.Suppressions.scan(
            "x = 1  # tmlint: disable=a,b — because reasons\n"
        )
        assert sup.by_line[1] == {"a", "b"}

    def test_comment_only_line_covers_next(self):
        sup = core.Suppressions.scan(
            "# tmlint: disable=r\nx = 1\n"
        )
        assert sup.suppressed("r", 1) and sup.suppressed("r", 2)

    def test_disable_file(self):
        sup = core.Suppressions.scan("# tmlint: disable-file=r\nx = 1\n")
        assert sup.suppressed("r", 99)

    def test_disable_all(self):
        sup = core.Suppressions.scan("x = 1  # tmlint: disable=all\n")
        assert sup.suppressed("anything", 1)

    def test_unrelated_comments_ignored(self):
        sup = core.Suppressions.scan("x = 1  # a normal comment\n")
        assert not sup.by_line and not sup.file_wide

    def test_def_line_suppression_spans_body(self):
        src = textwrap.dedent("""
            import numpy as np
            def f(dev):  # tmlint: disable=donation-aliasing — whole fn
                a = np.asarray(dev)
                return a
        """)
        assert not run_source(
            src, OPS_PATH, [RULES_BY_NAME["donation-aliasing"]]
        )


class TestBaseline:
    SRC = """
        import numpy as np
        def f(dev):
            return np.asarray(dev)
    """

    def _findings(self, pad=0):
        return lint("\n" * pad + textwrap.dedent(self.SRC), OPS_PATH,
                    "donation-aliasing")

    def test_fingerprints_survive_line_drift(self):
        a = core.fingerprint_findings(self._findings(pad=0))
        b = core.fingerprint_findings(self._findings(pad=7))
        assert a == b and len(a) == 1

    def test_round_trip_and_gate(self, tmp_path):
        fs = self._findings()
        path = str(tmp_path / "BASE.json")
        core.write_baseline(path, fs)
        base = core.load_baseline(path)
        new, old = core.apply_baseline(fs, base)
        assert not new and len(old) == 1
        # a NEW finding (different source text) is not covered
        fs2 = lint(
            """
            import numpy as np
            def g(dev):
                return np.asarray(dev)[:2]
            """,
            OPS_PATH, "donation-aliasing",
        )
        new2, _ = core.apply_baseline(fs2, base)
        assert len(new2) == 1

    def test_missing_baseline_is_empty(self, tmp_path):
        assert core.load_baseline(str(tmp_path / "nope.json")) == set()

    def test_duplicate_lines_disambiguate_by_occurrence(self):
        src = """
            import numpy as np
            def f(dev):
                return np.asarray(dev)
            def g(dev):
                return np.asarray(dev)
        """
        fps = core.fingerprint_findings(lint(src, OPS_PATH,
                                             "donation-aliasing"))
        assert len(fps) == 2 and fps[0] != fps[1]

    def test_parse_error_is_a_finding(self):
        fs = run_source("def broken(:\n", OPS_PATH, ALL_RULES)
        assert rules_of(fs) == ["parse-error"]


# ---------------------------------------------------------------------------
# THE gate + CLI contract


class TestTreeGate:
    def test_tree_has_zero_nonbaselined_findings(self):
        """Tier-1 gate: tmlint over the real tree, with the committed
        baseline, must be clean — a new finding fails the build."""
        findings = core.run_paths(["tendermint_tpu"], REPO_ROOT, ALL_RULES)
        baseline = core.load_baseline(
            os.path.join(REPO_ROOT, "LINT_BASELINE.json")
        )
        new, _ = core.apply_baseline(findings, baseline)
        assert not new, "new tmlint findings:\n" + "\n".join(
            f"  {f!r}" for f in new
        )

    def test_baseline_has_no_stale_entries(self):
        """The committed baseline only shrinks: every fingerprint in it
        must still correspond to a real finding (delete fixed ones)."""
        findings = core.run_paths(["tendermint_tpu"], REPO_ROOT, ALL_RULES)
        live = set(core.fingerprint_findings(findings))
        baseline = core.load_baseline(
            os.path.join(REPO_ROOT, "LINT_BASELINE.json")
        )
        assert baseline <= live, f"stale baseline entries: {baseline - live}"


class TestCLI:
    def _run(self, *args, cwd=REPO_ROOT):
        return subprocess.run(
            [sys.executable, "-m", "tools.tmlint", *args],
            capture_output=True, text=True, cwd=cwd, timeout=120,
        )

    def test_exit_0_on_clean_tree(self):
        r = self._run()
        assert r.returncode == 0, r.stdout + r.stderr

    def test_exit_1_on_finding_and_json_output(self, tmp_path):
        mod = tmp_path / "tendermint_tpu" / "ops"
        mod.mkdir(parents=True)
        (mod / "bad.py").write_text(textwrap.dedent(PR7_ALIAS_BUG))
        r = self._run("tendermint_tpu", "--root", str(tmp_path),
                      "--no-baseline", "--json")
        assert r.returncode == 1, r.stdout + r.stderr
        data = json.loads(r.stdout)
        assert not data["ok"] and data["new"]
        assert data["new"][0]["rule"] == "donation-aliasing"

    def test_exit_2_on_unknown_rule(self):
        r = self._run("--rules", "no-such-rule")
        assert r.returncode == 2

    def test_exit_2_on_missing_path(self):
        r = self._run("no/such/dir")
        assert r.returncode == 2

    def test_write_baseline_refuses_rule_or_path_subset(self):
        # review fix: a subset-scoped rewrite would drop every other
        # rule's grandfathered fingerprints
        r = self._run("--write-baseline", "--rules", "donation-aliasing")
        assert r.returncode == 2
        r = self._run("tendermint_tpu/ops", "--write-baseline")
        assert r.returncode == 2

    def test_write_baseline_then_clean(self, tmp_path):
        mod = tmp_path / "tendermint_tpu" / "ops"
        mod.mkdir(parents=True)
        (mod / "bad.py").write_text(textwrap.dedent(PR7_ALIAS_BUG))
        r1 = self._run("tendermint_tpu", "--root", str(tmp_path),
                       "--write-baseline")
        assert r1.returncode == 0, r1.stdout + r1.stderr
        assert (tmp_path / "LINT_BASELINE.json").exists()
        r2 = self._run("tendermint_tpu", "--root", str(tmp_path))
        assert r2.returncode == 0, r2.stdout + r2.stderr

    def test_list_rules_names_all_five(self):
        r = self._run("--list-rules")
        assert r.returncode == 0
        for name in ("donation-aliasing", "relay-ownership",
                     "simnet-determinism", "hot-path-purity",
                     "lock-discipline"):
            assert name in r.stdout
