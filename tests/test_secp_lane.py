"""Scheme-keyed verification lanes (ISSUE 19): the secp256k1 device
kernel and its mesh/commit integration.

Two layers, same pattern as test_mesh_isolated.py:

- jax-free unit tests of the pure-Python Weierstrass oracle
  (crypto/_weierstrass.py — stdlib-only, loaded standalone) run IN
  PROCESS, no cryptography wheel needed;
- the kernel/commit parity suite (the classes below guarded by
  `needs_crypto`) and the `tools/prep_bench.py --schemes`
  one-superbatch-launch + blame-parity gate run in SUBPROCESSES with
  TM_TPU_PUREPY_CRYPTO=1, which must never leak into the main pytest
  process.
"""

import importlib.util
import os
import subprocess
import sys

import numpy as np
import pytest

try:
    from tendermint_tpu.crypto import ed25519 as _ed
    from tendermint_tpu.crypto import secp256k1 as _secp

    _HAVE_CRYPTO = True
except ModuleNotFoundError:
    # No cryptography wheel in this container. Do NOT flip
    # TM_TPU_PUREPY_CRYPTO here (env leaks into later-collected
    # modules); the subprocess runner below re-runs this module with
    # the fallback enabled instead.
    _HAVE_CRYPTO = False

needs_crypto = pytest.mark.skipif(
    not _HAVE_CRYPTO,
    reason="crypto backend unavailable (runs via the purepy subprocess "
    "runner)",
)


def _repo_root():
    return os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _load_weierstrass():
    """crypto/_weierstrass.py is stdlib-only big-int math — load the
    FILE so the oracle tests run even where the crypto package can't
    import (missing cryptography wheel in the main tier-1 process)."""
    if _HAVE_CRYPTO:
        from tendermint_tpu.crypto import _weierstrass as wst

        return wst
    p = os.path.join(_repo_root(), "tendermint_tpu", "crypto",
                     "_weierstrass.py")
    spec = importlib.util.spec_from_file_location(
        "_tm_tpu_weierstrass_standalone", p
    )
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


class TestWeierstrassOracle:
    """In-process: the semantics oracle the device kernel is
    differential-tested against."""

    def test_sign_verify_roundtrip_and_determinism(self):
        wst = _load_weierstrass()
        import hashlib

        d = 0x1234_5678_9ABC
        digest = hashlib.sha256(b"oracle-row").digest()
        r, s = wst.sign_digest(d, digest)
        assert (r, s) == wst.sign_digest(d, digest)  # RFC 6979
        q = wst.scalar_mult(d, wst.G)
        assert wst.verify_digest(q, digest, r, s)
        assert not wst.verify_digest(
            q, hashlib.sha256(b"tampered").digest(), r, s
        )
        assert not wst.verify_digest(q, digest, r, (s + 1) % wst.N)

    def test_compress_decompress_roundtrip(self):
        wst = _load_weierstrass()
        for d in (1, 2, 0xDEADBEEF, wst.N - 1):
            q = wst.scalar_mult(d, wst.G)
            enc = wst.compress(q)
            assert len(enc) == 33 and enc[0] in (2, 3)
            assert wst.decompress(enc) == q

    def test_decompress_rejects_non_curve_x(self):
        wst = _load_weierstrass()
        # x = 5: 5^3 + 7 = 132 is a quadratic non-residue mod p
        bad = bytes([2]) + (5).to_bytes(32, "big")
        assert wst.decompress(bad) is None
        assert wst.decompress(b"\x02" * 5) is None  # wrong length


def _signed_secp(n, tag=0, bad=()):
    from tendermint_tpu.ops.entry_block import EntryBlock

    out = []
    for i in range(n):
        sk = _secp.PrivKey((tag * 4096 + i + 1).to_bytes(32, "big"))
        m = b"lane-%d-%d" % (tag, i)
        sig = sk.sign(m) if i not in bad else b"\x07" * 64
        out.append((sk.pub_key().bytes(), m, sig))
    return EntryBlock.from_entries(out, scheme="secp256k1")


def _signed_ed(n, tag=0, bad=()):
    from tendermint_tpu.ops.entry_block import EntryBlock

    out = []
    for i in range(n):
        sk = _ed.gen_priv_key((tag * 4096 + i + 1).to_bytes(32, "little"))
        m = b"lane-ed-%d-%d" % (tag, i)
        sig = sk.sign(m) if i not in bad else b"\x07" * 64
        out.append((sk.pub_key().bytes(), m, sig))
    return EntryBlock.from_entries(out)


@needs_crypto
class TestSecpKernel:
    """Batched Strauss+GLV verdicts vs the per-signature oracle,
    including every host-side rejection class."""

    N = 0xFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFEBAAEDCE6AF48A03BBFD25E8CD0364141

    def _rows(self):
        rows = []
        for i in range(12):
            sk = _secp.PrivKey((900 + i).to_bytes(32, "big"))
            m = b"kernel-%d" % i
            rows.append((sk.pub_key().bytes(), m, sk.sign(m)))
        return rows

    def test_verdicts_match_host_oracle_with_rejections(self):
        from tendermint_tpu.ops import secp_verify as sv

        rows = self._rows()
        pub1, m1, s1 = rows[1]
        rows[1] = (pub1, m1, s1[:32] + s1[32:][::-1])  # tampered s
        pub2, m2, s2 = rows[2]
        s_val = int.from_bytes(s2[32:], "big")
        rows[2] = (pub2, m2, s2[:32] + (self.N - s_val).to_bytes(32, "big"))
        rows[3] = (rows[3][0], rows[3][1], rows[3][2][:40])  # bad length
        rows[4] = (bytes([2]) + (5).to_bytes(32, "big"),  # non-curve pub
                   rows[4][1], rows[4][2])
        rows[5] = (rows[5][0], rows[5][1],
                   self.N.to_bytes(32, "big") + rows[5][2][32:])  # r >= n
        got = sv.verify_rows(rows, size=16)
        want = np.asarray(
            [_secp.PubKey(p).verify_signature(m, s) if len(p) == 33
             else False for p, m, s in rows]
        )
        assert np.array_equal(got, want)
        # exactly the five rejection rows fail; non-lower-S (row 2) is
        # rejected even though (r, s') is a valid plain-ECDSA signature
        assert list(np.nonzero(~got)[0]) == [1, 2, 3, 4, 5]

    def test_prepare_rows_rejection_flags(self):
        from tendermint_tpu.ops import secp_verify as sv

        rows = self._rows()[:4]
        rows[0] = (rows[0][0], rows[0][1], b"")  # bad length
        *_, ok = sv.prepare_rows(rows, 8)
        assert list(ok) == [False, True, True, True] + [True] * 4  # pads ok

    def test_backend_device_row_equals_host_loop(self):
        from tendermint_tpu.ops import backend

        blk = _signed_secp(16, tag=30, bad=(7, 13))
        dev = np.asarray(backend.verify_batch(blk))
        host = np.asarray(
            [_secp.PubKey(blk.pub_bytes(i)).verify_signature(
                blk.msg(i), blk.sig[i].tobytes()) for i in range(len(blk))]
        )
        assert np.array_equal(dev, host)
        assert not dev[7] and not dev[13] and dev.sum() == 14


@needs_crypto
class TestEpochCachedSecp:
    def test_warm_valset_gather_parity(self):
        """The epoch table's device-resident Q columns (secp_tables)
        must reproduce the uncached verdicts bit-for-bit, bad row
        included."""
        from tendermint_tpu.ops import backend, epoch_cache as _epoch
        from tendermint_tpu.types import validation as V
        from tendermint_tpu.types import (
            BlockID, PartSetHeader, Timestamp, Validator, ValidatorSet,
            Vote, VoteSet,
        )
        from tendermint_tpu.types.block import CommitSig
        from tendermint_tpu.types.vote import PRECOMMIT_TYPE

        chain_id = "secp-epoch"
        pairs = []
        for i in range(10):
            sk = _secp.PrivKey((500 + i).to_bytes(32, "big"))
            pairs.append((sk, Validator.new(sk.pub_key(), 100)))
        vset = ValidatorSet.new([v for _, v in pairs])
        by_addr = {v.address: sk for sk, v in pairs}
        sks = [by_addr[v.address] for v in vset.validators]
        bid = BlockID(hash=b"\x09" * 32,
                      part_set_header=PartSetHeader(total=1,
                                                    hash=b"\x09" * 32))
        vs = VoteSet(chain_id, 3, 0, PRECOMMIT_TYPE, vset)
        for i, sk in enumerate(sks):
            vote = Vote(type=PRECOMMIT_TYPE, height=3, round=0,
                        block_id=bid,
                        timestamp=Timestamp(seconds=1_600_000_000, nanos=0),
                        validator_address=vset.validators[i].address,
                        validator_index=i)
            sig = sk.sign(vote.sign_bytes(chain_id))
            vs.add_vote(Vote(**{**vote.__dict__, "signature": sig}))
        commit = vs.make_commit()
        cs = commit.signatures[2]
        commit.signatures[2] = CommitSig(
            block_id_flag=cs.block_id_flag,
            validator_address=cs.validator_address,
            timestamp=cs.timestamp,
            signature=cs.signature[:32] + cs.signature[32:][::-1])

        _epoch.reset(8)
        cold, _ = V.prepare_commit_light(chain_id, vset, bid, 3, commit)
        assert cold.epoch_key is None
        v_cold = np.asarray(backend.verify_batch(cold))

        _epoch.note_valset(vset)
        _epoch.note_valset(vset)  # warm: second sighting attaches keys
        warm, _ = V.prepare_commit_light(chain_id, vset, bid, 3, commit)
        assert warm.epoch_key is not None and warm.val_idx is not None
        v_warm = np.asarray(backend.verify_batch(warm))
        assert np.array_equal(v_cold, v_warm)
        assert not v_warm[2] and v_warm.sum() == len(warm) - 1


@needs_crypto
class TestMixedSuperbatch:
    @pytest.fixture(autouse=True)
    def _lane_bucket_16(self, monkeypatch):
        # small lanes: the pack/demux logic is bucket-agnostic and the
        # secp ladder costs ~linear kernel time per padded row on CPU
        monkeypatch.setenv("TM_TPU_MESH_LANE_BUCKET", "16")

    def _run_plan(self, plan):
        from tendermint_tpu.ops import device_pool as dp, mesh as ms

        block, spans = ms.build_superblock(plan)
        res = ms.prepare_superbatch(block, plan)
        f, args = res[0], res[1]
        shardings = res[4] if len(res) > 4 else None
        arr = np.array(f(*dp.transfer(args, shardings=shardings)))
        if arr.ndim == 2:
            arr = arr[0]
        return arr.astype(bool), spans, block

    def test_mixed_plan_one_launch_demux_and_pads(self):
        """Both schemes in ONE superbatch: contiguous per-scheme
        segments, single launch fn, secp job rows bit-identical to the
        single-scheme lane, tampered rows demuxed, in-lane pads accept.
        (ed25519 superbatch parity is pinned bit-level by test_mesh;
        here the ed spans are checked positionally to keep this test
        from tracing the ed kernel a second time.)"""
        from tendermint_tpu.ops import backend, mesh as ms
        from tendermint_tpu.ops.entry_block import EntryBlock

        class _J:
            def __init__(self, blk):
                self.entries = blk

        jobs = [
            _J(_signed_ed(14, 40, bad=(9,))),
            _J(_signed_secp(12, 41, bad=(3,))),
            _J(_signed_ed(9, 42)),
            _J(_signed_secp(6, 43)),
        ]
        plan, held = ms.pack_jobs(jobs, 4)
        assert not held
        assert plan.schemes() == ["ed25519", "secp256k1"]
        arr, spans, block = self._run_plan(plan)
        assert isinstance(block, ms.SchemeSuperBlock)
        assert [s for s, _, _ in block.parts] == ["ed25519", "secp256k1"]
        assert block.epoch_key is None and len(block) == plan.bucket
        for job, off, n in spans:
            seg = arr[off:off + n]
            if job.entries.scheme == "secp256k1":
                want = np.asarray(backend.verify_batch(job.entries))
                assert np.array_equal(seg, want)
            elif job is jobs[0]:
                assert not seg[9] and seg.sum() == n - 1
            else:
                assert seg.all()
        # only the two tampered rows fail across live AND pad rows
        assert arr.sum() == len(arr) - 2

        # cross-scheme concat outside the superblock path stays illegal
        with pytest.raises(ValueError, match="mixed-scheme"):
            EntryBlock.concat([jobs[0].entries, jobs[1].entries])

    def test_all_secp_plan_with_pure_pad_lane(self):
        """3 full secp jobs over a 4-lane plan leave one PURE padding
        lane and the superblock stays a plain (single-scheme)
        EntryBlock — checked host-side without a kernel launch; pad-row
        verdict truth (the trivially-valid generator signature) is
        pinned by test_secp_pad_block_rows_verify_true and the mixed
        test's in-lane pads."""
        from tendermint_tpu.ops import mesh as ms
        from tendermint_tpu.ops.entry_block import EntryBlock

        class _J:
            def __init__(self, blk):
                self.entries = blk

        jobs = [_J(_signed_secp(16, 50 + t)) for t in range(3)]
        plan, held = ms.pack_jobs(jobs, 4)
        assert not held and plan.n_lanes == 4
        assert plan.pad == 16  # one pure padding lane
        block, spans = ms.build_superblock(plan)
        assert isinstance(block, EntryBlock)  # not a SchemeSuperBlock
        assert block.scheme == "secp256k1"
        assert len(block) == plan.bucket == 64
        rows = np.zeros(plan.bucket, dtype=bool)
        for _, off, n in spans:
            assert not rows[off:off + n].any()
            rows[off:off + n] = True
        assert int(rows.sum()) == plan.live == 48

    def test_secp_pad_block_rows_verify_true(self):
        from tendermint_tpu.ops import backend, mesh as ms

        p = ms.pad_block(5, scheme="secp256k1")
        assert p.scheme == "secp256k1" and p.epoch_key is None
        assert np.asarray(backend.verify_batch(p)).all()


@needs_crypto
class TestWrongSizeKeyLock:
    """The scheme lock, both directions: a key of the wrong scheme must
    be rejected by TYPE before any size/shape coercion can hide it."""

    def test_secp_key_into_ed25519_verifier(self):
        from tendermint_tpu.crypto.batch import Ed25519HostBatchVerifier

        sk = _secp.PrivKey((77).to_bytes(32, "big"))
        m = b"cross"
        v = Ed25519HostBatchVerifier()
        with pytest.raises(TypeError, match="pubkey is not ed25519"):
            v.add(sk.pub_key(), m, sk.sign(m))
        with pytest.raises(TypeError, match="pubkey is not ed25519"):
            v.add_entries([(sk.pub_key(), m, b"\x00" * 64)])

    def test_ed25519_key_into_secp_verifier(self):
        from tendermint_tpu.ops.mixed import Secp256k1DeviceBatchVerifier

        sk = _ed.gen_priv_key(b"\x42" * 32)
        v = Secp256k1DeviceBatchVerifier()
        with pytest.raises(TypeError, match="pubkey is not secp256k1"):
            v.add(sk.pub_key(), b"cross", sk.sign(b"cross"))

    def test_secp_verifier_rejects_bad_sig_length(self):
        from tendermint_tpu.ops.mixed import Secp256k1DeviceBatchVerifier

        sk = _secp.PrivKey((78).to_bytes(32, "big"))
        v = Secp256k1DeviceBatchVerifier()
        with pytest.raises(ValueError, match="invalid signature length"):
            v.add(sk.pub_key(), b"m", b"\x00" * 63)

    def test_secp_verifier_verdicts(self):
        from tendermint_tpu.ops.mixed import Secp256k1DeviceBatchVerifier

        v = Secp256k1DeviceBatchVerifier()
        for i in range(10):
            sk = _secp.PrivKey((300 + i).to_bytes(32, "big"))
            m = b"bv-%d" % i
            sig = sk.sign(m) if i != 4 else b"\x01" * 64
            v.add(sk.pub_key(), m, sig)
        ok, valid = v.verify()
        assert not ok and valid == [i != 4 for i in range(10)]

    def test_create_batch_verifier_stays_none_for_secp(self):
        # reference parity (crypto/batch/batch.go:26-33): commits route
        # batched secp through the scheme lanes, not the verifier seam
        from tendermint_tpu.crypto import batch as cb

        sk = _secp.PrivKey((79).to_bytes(32, "big"))
        assert cb.create_batch_verifier(sk.pub_key()) is None
        assert not cb.supports_batch_verifier(sk.pub_key())


def _purepy_env():
    from tendermint_tpu.libs import jaxcache

    env = dict(os.environ, TM_TPU_PUREPY_CRYPTO="1", JAX_PLATFORMS="cpu")
    env.pop("TM_TPU_DONATE", None)
    env.pop("TM_TPU_MESH", None)
    jaxcache.set_env(env, _repo_root())
    return env


def test_secp_isolated_runners():
    """The purepy subprocess re-run of this file (the tier-1 home of
    every crypto-gated test above) and the `prep_bench --schemes`
    acceptance gate (ONE superbatch launch + verdict/blame parity for a
    mixed-scheme commit — same pattern as --mesh), folded into one test
    and run back to back (the container is single-CPU; concurrent
    subprocesses only add scheduler overhead)."""
    if os.environ.get("TM_TPU_SECP_ISOLATED"):
        pytest.skip("already inside the isolated runner")
    try:
        import cryptography  # noqa: F401

        have_crypto = True
    except ModuleNotFoundError:
        have_crypto = False
    here = os.path.dirname(os.path.abspath(__file__))
    cmds = {}
    if not have_crypto:  # with the wheel present the suite ran directly
        cmds["lane suite"] = (
            [
                sys.executable, "-m", "pytest",
                os.path.join(here, "test_secp_lane.py"),
                "-q", "-m", "not slow", "-p", "no:cacheprovider",
            ],
            dict(_purepy_env(), TM_TPU_SECP_ISOLATED="1"),
        )
    cmds["--schemes gate"] = (
        [
            sys.executable,
            os.path.join(_repo_root(), "tools", "prep_bench.py"),
            "--schemes",
        ],
        _purepy_env(),
    )
    fails = []
    for label, (cmd, env) in cmds.items():
        r = subprocess.run(
            cmd,
            stdout=subprocess.PIPE,
            stderr=subprocess.STDOUT,
            env=env,
            cwd=_repo_root(),
            timeout=800,
        )
        if r.returncode != 0:
            fails.append(f"{label}: rc={r.returncode}\n"
                         f"{(r.stdout or b'').decode(errors='replace')[-3000:]}")
    assert not fails, "\n\n".join(fails)
