"""Unit coverage for the soak harness's telemetry layer (ISSUE 16):
`observability/timeseries.py` (sampler cadence on a virtual clock,
latency windows, span attribution, declarative SLO budgets) plus the
metrics-side satellites — lock-safe `Registry.snapshot()` and the
per-QoS-lane `queue_wait_seconds` histogram surfaced in /status.

Crypto-free and jax-free: runs in the main tier-1 pytest process (the
end-to-end soak drives live in tests/test_soak_isolated.py via the
purepy subprocess runner).
"""

import pytest

from tendermint_tpu.libs.metrics import OpsMetrics, Registry, ops_stats
from tendermint_tpu.observability.timeseries import (
    KIND_P99_MS_MAX,
    KIND_RATE_MIN,
    LatencyRecorder,
    SLOBudget,
    TelemetrySampler,
    attribute_spans,
    dominant_span,
    evaluate_slos,
    percentile,
    slo_verdict,
    timeline_latencies,
    window_stats,
)
from tendermint_tpu.simnet.clock import SimClock


class TestTelemetrySampler:
    def _rig(self, cadence=1.0, capacity=600):
        clk = SimClock(seed=0, start=100.0)
        reg = Registry()
        g = reg.gauge("ops", "dispatch_queue_depth")
        sampler = TelemetrySampler(clk, cadence_s=cadence, capacity=capacity,
                                   registry=reg)
        return clk, reg, g, sampler

    def test_tick_count_is_a_pure_function_of_duration_and_cadence(self):
        clk, _, g, sampler = self._rig(cadence=1.0)
        g.set(3.0)
        sampler.start()
        clk.run_until(deadline=110.0)
        sampler.stop()
        assert sampler.ticks == 10
        pts = sampler.series()["tendermint_ops_dispatch_queue_depth"]
        assert [t for t, _ in pts] == [101.0 + i for i in range(10)]
        assert all(v == 3.0 for _, v in pts)

    def test_ring_capacity_bounds_memory_keeping_latest(self):
        clk, _, g, sampler = self._rig(capacity=4)
        g.set(0.0)
        sampler.start()
        clk.run_until(deadline=110.0)
        pts = sampler.series()["tendermint_ops_dispatch_queue_depth"]
        assert len(pts) == 4
        assert pts[-1][0] == 110.0  # newest kept, oldest evicted

    def test_extra_sources_sampled_and_a_raising_source_is_isolated(self):
        clk, _, _, sampler = self._rig()
        sampler.add_source("verify_lane_ingress", lambda: 7.0)

        def boom():
            raise RuntimeError("source died")

        sampler.add_source("broken", boom)
        sampler.start()
        clk.run_until(deadline=103.0)
        s = sampler.series()
        assert [v for _, v in s["verify_lane_ingress"]] == [7.0, 7.0, 7.0]
        assert "broken" not in s  # never killed the tick
        assert sampler.ticks == 3

    def test_stop_halts_future_ticks(self):
        clk, _, g, sampler = self._rig()
        g.set(1.0)
        sampler.start()
        clk.run_until(deadline=102.0)
        sampler.stop()
        clk.run_until(deadline=110.0)
        assert sampler.ticks == 2


class TestWindowsAndSpans:
    def test_percentile_interpolates(self):
        assert percentile([], 0.99) == 0.0
        assert percentile([5.0], 0.99) == 5.0
        vals = [float(i) for i in range(1, 101)]
        assert percentile(vals, 0.50) == pytest.approx(50.5)
        assert percentile(vals, 0.99) == pytest.approx(99.01)

    def test_window_stats_buckets_align_to_first_sample(self):
        samples = [(10.0, 5.0, 0.0), (11.0, 7.0, 0.0),
                   (16.0, 100.0, 2.5), (17.0, 300.0, 2.6)]
        wins = window_stats(samples, 5.0)
        assert len(wins) == 2
        assert (wins[0]["t0"], wins[0]["t1"]) == (10.0, 15.0)
        assert wins[0]["count"] == 2 and wins[0]["wall_range"] is None
        assert wins[1]["max_ms"] == 300.0
        # wall extent covers the samples' wall start..(start + latency)
        w0, w1 = wins[1]["wall_range"]
        assert w0 == 2.5 and w1 == pytest.approx(2.6 + 0.3)

    def test_timeline_latencies_skip_partial_heights(self):
        tls = [
            {"height": 5, "t_applied": 50.0, "total_s": 0.2},
            {"height": 6, "t_applied": None, "total_s": None},  # in flight
        ]
        assert timeline_latencies(tls) == [(50.0, 200.0, 0.0)]

    def test_attribute_spans_filters_by_wall_range(self):
        events = [
            ("pipeline.queue_wait", 1.0, 3.0, 1, None),
            ("pipeline.device.wait", 2.0, 2.5, 1, None),
            ("other.span", 0.0, 0.1, 1, None),  # outside the window
        ]
        agg = attribute_spans(events, wall_range=[1.5, 4.0])
        assert set(agg) == {"pipeline.queue_wait", "pipeline.device.wait"}
        assert agg["pipeline.queue_wait"]["total_ms"] == pytest.approx(2000.0)
        assert dominant_span(agg) == "pipeline.queue_wait"

    def test_dominant_span_prefers_pipeline_categories(self):
        agg = attribute_spans([
            ("app.block_exec", 0.0, 10.0, 1, None),     # biggest overall
            ("pipeline.transfer", 0.0, 1.0, 1, None),
        ])
        assert dominant_span(agg) == "pipeline.transfer"
        assert dominant_span({}) is None


class TestSLOBudgets:
    def test_p99_budget_green_and_breached_with_localization(self):
        rec = LatencyRecorder()
        for i in range(20):
            rec.record("ingress", 10.0 + i, 5.0, t_wall=1.0 + i)
        # one late window of slow admissions
        for i in range(4):
            rec.record("ingress", 40.0 + i, 900.0, t_wall=31.0 + i)
        spans = [("pipeline.queue_wait", 30.0, 36.0, 1, None)]
        ok_b = SLOBudget("ingress_ok", "ingress", KIND_P99_MS_MAX, 1000.0)
        bad_b = SLOBudget("ingress_bad", "ingress", KIND_P99_MS_MAX, 100.0)
        res = evaluate_slos([ok_b, bad_b], rec, window_s=5.0,
                            span_events=spans)
        assert res[0]["ok"] and res[0]["observed"] > 5.0
        breach = res[1]
        assert not breach["ok"]
        bw = breach["breach_window"]
        assert bw["t0"] >= 40.0 and bw["count"] == 4
        assert bw["p99_ms"] == pytest.approx(900.0)
        assert bw["dominant_span"] == "pipeline.queue_wait"
        assert "pipeline.queue_wait" in bw["span_totals_ms"]

    def test_starved_lane_breaches_instead_of_passing_vacuously(self):
        rec = LatencyRecorder()
        b = SLOBudget("light_p99", "light", KIND_P99_MS_MAX, 100.0,
                      min_samples=3)
        (r,) = evaluate_slos([b], rec)
        assert not r["ok"]
        assert "starved or idle" in r["reason"]
        assert r["observed"] is None

    def test_rate_floor_and_unknown_kind(self):
        rec = LatencyRecorder()
        floor = SLOBudget("replay_rate", "replay", KIND_RATE_MIN, 10.0)
        weird = SLOBudget("weird", "x", "p42_max", 1.0)
        good, missing, bad, unk = evaluate_slos(
            [floor, floor, floor, weird], rec,
            rates={"replay": 40.0},
        )[0:1] + evaluate_slos([floor], rec)[0:1] + evaluate_slos(
            [floor], rec, rates={"replay": 3.0},
        )[0:1] + evaluate_slos([weird], rec)[0:1]
        assert good["ok"] and good["observed"] == 40.0
        assert not missing["ok"] and missing["observed"] is None
        assert not bad["ok"]
        assert not unk["ok"] and "unknown SLO kind" in unk["reason"]

    def test_slo_verdict_collects_breaches(self):
        rec = LatencyRecorder()
        rec.record("a", 0.0, 1.0)
        res = evaluate_slos([
            SLOBudget("a_p99", "a", KIND_P99_MS_MAX, 10.0),
            SLOBudget("r", "r", KIND_RATE_MIN, 5.0),
        ], rec)
        v = slo_verdict(res)
        assert not v["ok"] and v["evaluated"] == 2
        assert [b["slo"] for b in v["breaches"]] == ["r"]


class TestRegistrySnapshot:
    def test_snapshot_covers_counters_gauges_histograms(self):
        reg = Registry()
        c = reg.counter("ops", "epoch_cache_hits_total")
        g = reg.gauge("ops", "dispatch_queue_depth")
        h = reg.histogram("ops", "queue_wait_seconds", labeled=True)
        c.inc(3)
        g.set(2.0)
        h.observe(0.004, lane="ingress")
        h.observe(2.0, lane="ingress")
        h.observe(0.5, lane="consensus")
        snap = reg.snapshot()
        assert snap["tendermint_ops_epoch_cache_hits_total"]["values"][""] == 3
        assert snap["tendermint_ops_dispatch_queue_depth"]["values"][""] == 2.0
        hs = snap["tendermint_ops_queue_wait_seconds"]
        assert hs["type"] == "histogram"
        ing = hs["series"]['lane="ingress"']
        assert ing["count"] == 2 and ing["sum"] == pytest.approx(2.004)
        # raw (non-cumulative) bucket counts sum to the series count
        assert sum(ing["bucket_counts"]) == 2

    def test_snapshot_runs_collect_hooks_and_survives_a_bad_one(self):
        reg = Registry()
        g = reg.gauge("ops", "pipeline_inflight")
        reg.add_collect_hook(lambda: g.set(9.0))

        def bad_hook():
            raise RuntimeError("hook died")

        reg.add_collect_hook(bad_hook)
        snap = reg.snapshot()
        assert snap["tendermint_ops_pipeline_inflight"]["values"][""] == 9.0

    def test_queue_wait_by_lane_reaches_status_surface(self):
        """ISSUE 16 satellite: per-QoS-lane dispatch-queue wait is
        readable from ops_stats() (the /status verify_engine payload) —
        ingress starvation is visible to a scrape, not only to spans."""
        reg = Registry()
        m = OpsMetrics(reg)
        m.queue_wait_seconds.observe(0.010, lane="consensus")
        m.queue_wait_seconds.observe(0.250, lane="ingress")
        m.queue_wait_seconds.observe(0.350, lane="ingress")
        by_lane = {
            (dict(k).get("lane", "") or "unlabeled"): (s, c)
            for k, (s, c) in m.queue_wait_seconds.snapshot().items()
        }
        assert by_lane["ingress"] == (pytest.approx(0.6), 2)
        assert by_lane["consensus"][1] == 1
        # the live /status path exposes the same shape from the global
        # ops registry (counts only asserted >=0: other tests in this
        # process may already have observed waits there)
        live = ops_stats()["queue_wait_by_lane"]
        assert isinstance(live, dict)
        for lane_stats in live.values():
            assert lane_stats["count"] >= 0
            assert lane_stats["avg_ms"] >= 0.0
