"""Types layer tests: hashing, wire round-trips, proposer rotation,
vote sets, and commit verification through both host and device paths.

Mirrors the reference's test strategy for types/ (SURVEY.md §4):
validator_set_test.go proposer-rotation cases, vote_set_test.go quorum
cases, block_test.go hashing/ValidateBasic."""

import pytest

from tendermint_tpu.crypto import ed25519
from tendermint_tpu.types import (
    BLOCK_ID_FLAG_ABSENT,
    BLOCK_ID_FLAG_COMMIT,
    Block,
    BlockID,
    Commit,
    CommitSig,
    Data,
    Fraction,
    Header,
    PartSetHeader,
    Timestamp,
    Validator,
    ValidatorSet,
    Vote,
    VoteSet,
    ErrNotEnoughVotingPowerSigned,
    ErrVoteConflictingVotes,
    PRECOMMIT_TYPE,
    PREVOTE_TYPE,
    verify_commit,
    verify_commit_light,
    verify_commit_light_trusting,
)
from tendermint_tpu.types.part_set import PartSet
from tendermint_tpu.types.vote import vote_from_commit_sig

CHAIN_ID = "test-chain"


def make_validators(n, power=None):
    """n deterministic validators; returns (privkeys, ValidatorSet)."""
    pairs = []
    for i in range(n):
        sk = ed25519.gen_priv_key(bytes([i + 1]) * 32)
        pairs.append((sk, Validator.new(sk.pub_key(), power[i] if power else 100)))
    vset = ValidatorSet.new([v for _, v in pairs])
    # key privkeys by address so they follow the set's sort order
    by_addr = {v.address: sk for sk, v in pairs}
    return [by_addr[v.address] for v in vset.validators], vset


def sign_vote(sk, vset, vote_type, height, round_, block_id, ts=None):
    addr = sk.pub_key().address()
    idx, _ = vset.get_by_address(addr)
    vote = Vote(
        type=vote_type,
        height=height,
        round=round_,
        block_id=block_id,
        timestamp=ts or Timestamp(seconds=1_600_000_000, nanos=0),
        validator_address=addr,
        validator_index=idx,
    )
    sig = sk.sign(vote.sign_bytes(CHAIN_ID))
    return Vote(**{**vote.__dict__, "signature": sig})


def make_block_id(tag=b"\x01"):
    return BlockID(
        hash=tag * 32, part_set_header=PartSetHeader(total=1, hash=tag * 32)
    )


class TestBlockHashing:
    def test_header_hash_deterministic_and_field_sensitive(self):
        h = Header(
            chain_id=CHAIN_ID,
            height=5,
            time=Timestamp(seconds=100, nanos=5),
            validators_hash=b"\x01" * 32,
            next_validators_hash=b"\x02" * 32,
            consensus_hash=b"\x03" * 32,
            app_hash=b"app",
            proposer_address=b"\x04" * 20,
        )
        h2 = Header(**{**h.__dict__, "height": 6})
        assert h.hash() != h2.hash()
        assert len(h.hash()) == 32
        assert Header(chain_id=CHAIN_ID, height=5).hash() == b""  # no valhash

    def test_header_wire_roundtrip(self):
        h = Header(
            chain_id=CHAIN_ID,
            height=7,
            time=Timestamp(seconds=123, nanos=456),
            last_block_id=make_block_id(),
            validators_hash=b"\x01" * 32,
            proposer_address=b"\x04" * 20,
        )
        assert Header.decode(h.encode()) == h

    def test_commit_hash_and_roundtrip(self):
        cs = CommitSig(
            block_id_flag=BLOCK_ID_FLAG_COMMIT,
            validator_address=b"\x05" * 20,
            timestamp=Timestamp(seconds=9),
            signature=b"\x06" * 64,
        )
        commit = Commit(height=3, round=1, block_id=make_block_id(), signatures=[cs])
        assert len(commit.hash()) == 32
        rt = Commit.decode(commit.encode())
        assert rt.height == 3 and rt.round == 1 and rt.signatures == [cs]
        assert rt.block_id == commit.block_id

    def test_block_fill_header_and_validate(self):
        lc = Commit(
            height=1,
            round=0,
            block_id=make_block_id(),
            signatures=[
                CommitSig(
                    block_id_flag=BLOCK_ID_FLAG_COMMIT,
                    validator_address=b"\x05" * 20,
                    timestamp=Timestamp(seconds=9),
                    signature=b"\x06" * 64,
                )
            ],
        )
        b = Block(
            header=Header(
                chain_id=CHAIN_ID,
                height=2,
                validators_hash=b"\x01" * 32,
                next_validators_hash=b"\x01" * 32,
                consensus_hash=b"\x02" * 32,
                proposer_address=b"\x04" * 20,
            ),
            data=Data(txs=[b"tx1", b"tx2"]),
            last_commit=lc,
        )
        b.fill_header()
        b.validate_basic()
        rt = Block.decode(b.encode())
        assert rt.header == b.header
        assert rt.data.txs == [b"tx1", b"tx2"]
        assert rt.last_commit.hash() == lc.hash()


class TestPartSet:
    def test_chunk_proof_reassemble(self):
        data = bytes(range(256)) * 1024  # 256 KiB -> 4 parts
        ps = PartSet.from_data(data)
        assert ps.total() == 4 and ps.is_complete()
        ps2 = PartSet.new_from_header(ps.header())
        # add out of order; duplicates rejected as False
        for idx in (2, 0, 3, 1):
            assert ps2.add_part(ps.get_part(idx))
        assert not ps2.add_part(ps.get_part(1))
        assert ps2.is_complete()
        assert ps2.assemble() == data

    def test_corrupt_part_rejected(self):
        data = b"x" * 200000
        ps = PartSet.from_data(data)
        ps2 = PartSet.new_from_header(ps.header())
        p = ps.get_part(0)
        from tendermint_tpu.types.part_set import Part

        bad = Part(index=0, bytes=p.bytes[:-1] + b"\x00", proof=p.proof)
        with pytest.raises(ValueError):
            ps2.add_part(bad)


class TestValidatorSet:
    def test_sorting_and_hash(self):
        _, vset = make_validators(5, power=[5, 4, 3, 2, 1])
        powers = [v.voting_power for v in vset.validators]
        assert powers == sorted(powers, reverse=True)
        assert len(vset.hash()) == 32

    def test_proposer_rotation_is_fair(self):
        _, vset = make_validators(3, power=[1, 2, 3])
        counts = {}
        vs = vset.copy()
        for _ in range(600):
            p = vs.get_proposer()
            counts[p.address] = counts.get(p.address, 0) + 1
            vs.increment_proposer_priority(1)
        by_power = {v.address: v.voting_power for v in vset.validators}
        # each validator proposes proportionally to voting power (1:2:3)
        for addr, c in counts.items():
            assert abs(c - 100 * by_power[addr]) <= 2, (c, by_power[addr])

    def test_update_with_change_set(self):
        sks, vset = make_validators(3, power=[10, 10, 10])
        tvp = vset.total_voting_power()
        assert tvp == 30
        # bump one validator, remove another, add a new one
        newsk = ed25519.gen_priv_key(bytes([99]) * 32)
        changes = [
            Validator.new(sks[0].pub_key(), 20),
            Validator.new(sks[1].pub_key(), 0),  # removal
            Validator.new(newsk.pub_key(), 5),
        ]
        vset.update_with_change_set(changes)
        assert vset.size() == 3
        assert vset.total_voting_power() == 35
        _, v = vset.get_by_address(sks[0].pub_key().address())
        assert v.voting_power == 20
        assert not vset.has_address(sks[1].pub_key().address())

    def test_from_existing_preserves_priorities(self):
        _, vset = make_validators(4)
        vset.increment_proposer_priority(3)
        rebuilt = ValidatorSet.from_existing([v.copy() for v in vset.validators])
        assert [v.proposer_priority for v in rebuilt.validators] == [
            v.proposer_priority for v in vset.validators
        ]

    def test_wire_roundtrip(self):
        _, vset = make_validators(3)
        rt = ValidatorSet.decode(vset.encode())
        assert rt.hash() == vset.hash()
        assert rt.total_voting_power() == vset.total_voting_power()


def build_commit(n=4, power=None, height=10, round_=1):
    sks, vset = make_validators(n, power=power)
    block_id = make_block_id()
    vote_set = VoteSet(CHAIN_ID, height, round_, PRECOMMIT_TYPE, vset)
    for sk in sks:
        vote_set.add_vote(sign_vote(sk, vset, PRECOMMIT_TYPE, height, round_, block_id))
    return sks, vset, block_id, vote_set.make_commit()


class TestVoteSet:
    def test_quorum_tracking(self):
        sks, vset = make_validators(4)  # 4 x 100 power, quorum = 267
        block_id = make_block_id()
        vs = VoteSet(CHAIN_ID, 10, 0, PREVOTE_TYPE, vset)
        for i, sk in enumerate(sks[:2]):
            assert vs.add_vote(sign_vote(sk, vset, PREVOTE_TYPE, 10, 0, block_id))
        assert not vs.has_two_thirds_majority()
        assert vs.add_vote(sign_vote(sks[2], vset, PREVOTE_TYPE, 10, 0, block_id))
        assert vs.has_two_thirds_majority()
        maj, ok = vs.two_thirds_majority()
        assert ok and maj == block_id
        # duplicate -> False, not an error
        assert not vs.add_vote(sign_vote(sks[2], vset, PREVOTE_TYPE, 10, 0, block_id))

    def test_conflicting_vote_raises_and_is_tracked(self):
        sks, vset = make_validators(4)
        vs = VoteSet(CHAIN_ID, 10, 0, PREVOTE_TYPE, vset)
        a, b = make_block_id(b"\x0a"), make_block_id(b"\x0b")
        assert vs.add_vote(sign_vote(sks[0], vset, PREVOTE_TYPE, 10, 0, a))
        with pytest.raises(ErrVoteConflictingVotes) as ei:
            vs.add_vote(sign_vote(sks[0], vset, PREVOTE_TYPE, 10, 0, b))
        assert ei.value.vote_a.block_id == a
        assert ei.value.vote_b.block_id == b

    def test_wrong_step_and_bad_signature(self):
        sks, vset = make_validators(2)
        vs = VoteSet(CHAIN_ID, 10, 0, PREVOTE_TYPE, vset)
        with pytest.raises(ValueError):
            vs.add_vote(sign_vote(sks[0], vset, PREVOTE_TYPE, 11, 0, make_block_id()))
        good = sign_vote(sks[0], vset, PREVOTE_TYPE, 10, 0, make_block_id())
        bad = Vote(**{**good.__dict__, "signature": b"\x00" * 64})
        with pytest.raises(ValueError):
            vs.add_vote(bad)

    def test_make_commit_includes_nil_and_absent(self):
        sks, vset = make_validators(4)
        block_id = make_block_id()
        vs = VoteSet(CHAIN_ID, 10, 0, PRECOMMIT_TYPE, vset)
        for sk in sks[:3]:
            vs.add_vote(sign_vote(sk, vset, PRECOMMIT_TYPE, 10, 0, block_id))
        # 4th validator votes nil
        vs.add_vote(sign_vote(sks[3], vset, PRECOMMIT_TYPE, 10, 0, BlockID()))
        commit = vs.make_commit()
        flags = [cs.block_id_flag for cs in commit.signatures]
        assert flags.count(BLOCK_ID_FLAG_COMMIT) == 3
        assert commit.block_id == block_id


class TestVerifyCommit:
    def test_verify_commit_host_path(self):
        sks, vset, block_id, commit = build_commit(4)
        verify_commit(CHAIN_ID, vset, block_id, 10, commit)  # no raise
        verify_commit_light(CHAIN_ID, vset, block_id, 10, commit)
        verify_commit_light_trusting(CHAIN_ID, vset, commit, Fraction(1, 3))

    def test_verify_commit_device_path(self, monkeypatch):
        import tendermint_tpu.ops  # noqa: F401 — installs device factory

        monkeypatch.setenv("TM_TPU_FORCE_DEVICE", "1")
        sks, vset, block_id, commit = build_commit(4)
        verify_commit(CHAIN_ID, vset, block_id, 10, commit)

    def test_verify_commit_device_blames_bad_signature(self, monkeypatch):
        import tendermint_tpu.ops  # noqa: F401

        monkeypatch.setenv("TM_TPU_FORCE_DEVICE", "1")
        sks, vset, block_id, commit = build_commit(4)
        bad = CommitSig(
            block_id_flag=commit.signatures[2].block_id_flag,
            validator_address=commit.signatures[2].validator_address,
            timestamp=commit.signatures[2].timestamp,
            signature=b"\x01" * 64,
        )
        commit.signatures[2] = bad
        with pytest.raises(ValueError, match=r"wrong signature \(#2\)"):
            verify_commit(CHAIN_ID, vset, block_id, 10, commit)

    def test_not_enough_power(self):
        sks, vset = make_validators(4)
        block_id = make_block_id()
        vs = VoteSet(CHAIN_ID, 10, 1, PRECOMMIT_TYPE, vset)
        for sk in sks[:3]:
            vs.add_vote(sign_vote(sk, vset, PRECOMMIT_TYPE, 10, 1, block_id))
        commit = vs.make_commit()
        # drop one signature to absent: tallied 200 of 400 < 2/3
        commit.signatures[0] = CommitSig.absent()
        commit.signatures[1] = CommitSig.absent()
        with pytest.raises(ErrNotEnoughVotingPowerSigned):
            verify_commit(CHAIN_ID, vset, block_id, 10, commit)

    def test_commit_height_block_id_mismatch(self):
        sks, vset, block_id, commit = build_commit(4)
        with pytest.raises(ValueError):
            verify_commit(CHAIN_ID, vset, block_id, 11, commit)
        with pytest.raises(ValueError):
            verify_commit(CHAIN_ID, vset, make_block_id(b"\x0f"), 10, commit)

    def test_light_trusting_by_address_lookup(self):
        # trusting path looks up validators by address: use a superset valset
        sks, vset, block_id, commit = build_commit(4)
        extra = ed25519.gen_priv_key(bytes([77]) * 32)
        bigger = ValidatorSet.new(
            [v.copy() for v in vset.validators] + [Validator.new(extra.pub_key(), 100)]
        )
        verify_commit_light_trusting(CHAIN_ID, bigger, commit, Fraction(1, 3))

    def test_vote_roundtrip_and_commit_sig(self):
        sks, vset = make_validators(2)
        v = sign_vote(sks[0], vset, PRECOMMIT_TYPE, 5, 0, make_block_id())
        assert Vote.decode(v.encode()) == v
        cs = v.to_commit_sig()
        assert cs.for_block()
        back = vote_from_commit_sig(cs, v.block_id, 5, 0, v.validator_index)
        assert back.sign_bytes(CHAIN_ID) == v.sign_bytes(CHAIN_ID)
