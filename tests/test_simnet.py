"""simnet end-to-end tests: real consensus nodes, virtual network,
deterministic replay, fault injection, safety invariants.

Needs a working ed25519 signer. With the `cryptography` wheel the module
runs directly; without it, tests/test_simnet_isolated.py re-runs it in a
subprocess under TM_TPU_PUREPY_CRYPTO=1 (the env must NOT be set in the
main pytest process — see that module's docstring).
"""

import importlib.util
import os

import pytest

if importlib.util.find_spec("cryptography") is None and not os.environ.get(
    "TM_TPU_PUREPY_CRYPTO"
):
    pytest.skip(
        "needs an ed25519 signer (cryptography wheel or the isolated runner)",
        allow_module_level=True,
    )

from tendermint_tpu.simnet import (
    Cluster,
    Fault,
    LinkConfig,
    crash_restart_schedule,
    partition_heal_schedule,
    rotation_schedule,
    smoke_schedule,
)


def run(seed, faults=None, h=6, n=4, link=None, max_virtual_s=300.0, txs=0):
    c = Cluster(n_nodes=n, seed=seed, faults=faults, link=link, txs_per_node=txs)
    try:
        rep = c.run_to_height(h, max_virtual_s=max_virtual_s)
    finally:
        c.stop()
    return c, rep


class TestLiveness:
    def test_four_nodes_reach_height_invariants_green(self):
        c, rep = run(seed=1, h=6, txs=3)
        assert rep.ok, rep.reason
        assert rep.heights == [6, 6, 6, 6] or min(rep.heights) >= 6
        assert rep.violations == []
        # seeded txs actually landed in blocks
        all_txs = [
            tx
            for h in range(1, c.nodes[0].height() + 1)
            for tx in c.nodes[0].bstore.load_block(h).data.txs
        ]
        assert b"k0_0=v0" in all_txs and b"k3_2=v2" in all_txs

    def test_seven_nodes_with_minority_partition(self):
        """f=2 cluster: isolating 2 of 7 validators must not stop the
        majority (5/7 > 2/3)."""
        faults = [
            Fault(
                kind="partition",
                at_height=2,
                groups=[[0, 1, 2, 3, 4], [5, 6]],
                duration=3.0,
            )
        ]
        _, rep = run(seed=2, faults=faults, h=6, n=7)
        assert rep.ok, rep.reason

    def test_lossy_links_still_commit(self):
        link = LinkConfig(
            latency_s=0.01, jitter_s=0.02, drop=0.05, duplicate=0.05, reorder=0.1
        )
        _, rep = run(seed=3, link=link, h=6, max_virtual_s=600.0)
        assert rep.ok, rep.reason
        assert rep.net["dropped"] > 0  # the fault model actually engaged
        assert rep.net["duplicated"] > 0


class TestDeterminism:
    def test_same_seed_identical_fingerprint(self):
        _, r1 = run(seed=7)
        _, r2 = run(seed=7)
        assert r1.ok and r2.ok
        assert r1.fingerprint == r2.fingerprint
        assert r1.schedule_digest == r2.schedule_digest

    def test_same_seed_identical_with_crash_restart(self):
        """The acceptance bar: replay exactness must survive a crash +
        WAL-restart fault (the restart path replays the WAL tail)."""
        sched = crash_restart_schedule(node=2, at_height=3, restart_after=1.0)
        c1, r1 = run(seed=9, faults=sched, h=8)
        c2, r2 = run(seed=9, faults=sched, h=8)
        assert r1.ok, r1.reason
        assert c1.nodes[2].restarts == 1
        assert r1.fingerprint == r2.fingerprint
        assert r1.schedule_digest == r2.schedule_digest

    def test_different_seeds_different_schedules(self):
        """Different seeds must actually change the event order (jitter
        draws + gossip picks), not just relabel the same run."""
        link = LinkConfig(latency_s=0.005, jitter_s=0.01)
        _, r1 = run(seed=100, link=link)
        _, r2 = run(seed=101, link=link)
        assert r1.schedule_digest != r2.schedule_digest


class TestFaults:
    def test_even_partition_stalls_then_heals(self):
        """2/2 split: no side has +2/3, so commits must stop while the
        partition holds and resume after heal — BFT liveness needs a
        quorum-connected component."""
        c = Cluster(
            n_nodes=4,
            seed=4,
            faults=[
                Fault(kind="partition", at_time=0.1, groups=[[0, 1], [2, 3]])
            ],
        )
        c.start()
        t0 = c.clock.time()
        c.clock.run_until(deadline=t0 + 30.0)
        stalled_at = max(c.heights())
        # whatever committed before the split landed, nothing much after
        assert stalled_at <= 2, f"committed through a 2/2 partition: {c.heights()}"
        c._heal()
        done = c.clock.run_until(
            predicate=lambda: min(c.heights()) >= stalled_at + 3,
            deadline=c.clock.time() + 60.0,
        )
        assert done, f"no progress after heal: {c.heights()}"
        assert c.check_invariants() == []
        c.stop()

    def test_crash_restart_converges_via_wal(self):
        sched = crash_restart_schedule(node=1, at_height=3, restart_after=2.0)
        c, rep = run(seed=5, faults=sched, h=8)
        assert rep.ok, rep.reason
        assert c.nodes[1].restarts == 1
        # the restarted node's chain is byte-identical to the others
        for h in range(1, 9):
            assert (
                c.nodes[1].bstore.load_block(h).hash()
                == c.nodes[0].bstore.load_block(h).hash()
            )

    def test_crash_stop_without_restart_excluded_from_target(self):
        """A crash fault with no scheduled restart is crash-stop: the
        remaining 3/4 (quorum) must reach the target and the run must end
        at that point, not burn the virtual deadline waiting."""
        faults = [Fault(kind="crash", at_height=2, node=3)]
        c, rep = run(seed=13, faults=faults, h=5)
        assert rep.ok, rep.reason
        assert c.nodes[3].crashed and c.nodes[3].restarts == 0
        assert rep.virtual_s < 60.0  # ended on target, not on deadline
        live = [h for i, h in enumerate(rep.heights) if i != 3]
        assert min(live) >= 5

    def test_byzantine_double_sign_does_not_break_agreement(self):
        faults = [Fault(kind="double_sign", node=3)]
        c, rep = run(seed=6, faults=faults, h=6)
        assert rep.ok, rep.reason
        assert rep.violations == []
        assert c.nodes[3].byzantine
        assert any("double_sign node 3" in f for f in rep.faults_applied)

    def test_byzantine_double_sign_honors_height_trigger(self):
        """A double_sign with at_height must start equivocating at that
        height, not from genesis."""
        faults = [Fault(kind="double_sign", node=2, at_height=3)]
        c, rep = run(seed=6, faults=faults, h=6)
        assert rep.ok, rep.reason
        assert c.nodes[2].cs.do_prevote_override is not None
        applied = [f for f in rep.faults_applied if "double_sign" in f]
        assert applied and applied[0].startswith("t=")  # fired at a time

    def test_clock_skew_node_keeps_up(self):
        faults = [Fault(kind="clock_skew", at_time=0.2, node=2, skew=0.8)]
        _, rep = run(seed=8, faults=faults, h=6)
        assert rep.ok, rep.reason

    def test_smoke_schedule_end_to_end(self):
        """The CLI's --smoke scenario at module level: partition+heal then
        crash+WAL-restart, height >= 10, invariants green."""
        c, rep = run(seed=42, faults=smoke_schedule(4), h=10)
        assert rep.ok, rep.reason
        assert min(rep.heights) >= 10
        assert any("partition" in f for f in rep.faults_applied)
        assert any("restart" in f for f in rep.faults_applied)


class TestValsetRotation:
    """ISSUE 6 tentpole leg (a): val_join/val_leave/val_power faults route
    through the REAL EndBlock -> update_state -> _update_with_change_set
    path, structurally invalidating ValidatorSet.hash() every churn."""

    def test_join_leave_rotation_changes_valset_and_converges(self):
        faults = rotation_schedule(
            n_nodes=6, n_validators=4, every=4, start=3, until=12
        )
        assert [f.kind for f in faults] == [
            "val_join", "val_leave"] * 3
        c = Cluster(n_nodes=6, n_validators=4, seed=42, faults=faults)
        try:
            rep = c.run_to_height(16, max_virtual_s=300.0)
        finally:
            c.stop()
        assert rep.ok, rep.reason
        assert rep.n_validators == 4
        # every rotation surfaced as a validators_hash change on-chain
        assert len(rep.valset_changes) == 3, rep.valset_changes
        # the joined standby actually validates: the final commit carries
        # a signature from a node outside the genesis set
        seen = c.nodes[0].bstore.load_seen_commit()
        vals = c.nodes[0].sstore.load_validators(seen.height)
        genesis_pubs = {n.sk.pub_key().bytes() for n in c.nodes[:4]}
        assert any(
            v.pub_key.bytes() not in genesis_pubs for v in vals.validators
        )

    def test_rotation_replay_exact(self):
        def run():
            faults = rotation_schedule(
                n_nodes=6, n_validators=4, every=4, start=3, until=12
            )
            c = Cluster(n_nodes=6, n_validators=4, seed=7, faults=faults)
            try:
                return c.run_to_height(14, max_virtual_s=300.0)
            finally:
                c.stop()

        r1, r2 = run(), run()
        assert r1.ok and r2.ok, (r1.reason, r2.reason)
        assert r1.fingerprint == r2.fingerprint
        assert r1.schedule_digest == r2.schedule_digest

    def test_power_rotation_full_validator_cluster(self):
        """No standbys: rotations degrade to power changes — still a
        structural hash invalidation per churn."""
        faults = rotation_schedule(
            n_nodes=4, n_validators=4, every=4, start=3, until=8
        )
        assert all(f.kind == "val_power" for f in faults)
        c, rep = run(seed=3, faults=faults, h=12)
        assert rep.ok, rep.reason
        assert len(rep.valset_changes) == 2, rep.valset_changes

    def test_epoch_cache_cycles_cold_warm_evict_under_churn(self):
        """Rotation drives the device epoch cache through its whole
        lifecycle: every distinct valset cold-registers (miss), warm
        re-verifies hit, and an LRU depth below the epoch count forces
        evictions — asserted live by the harness invariants."""
        from tendermint_tpu.ops import epoch_cache

        epoch_cache.reset(depth=2)
        try:
            faults = rotation_schedule(
                n_nodes=6, n_validators=4, every=4, start=3, until=12
            )
            c = Cluster(n_nodes=6, n_validators=4, seed=7, faults=faults)
            try:
                rep = c.run_to_height(16, max_virtual_s=300.0)
            finally:
                c.stop()
            assert rep.ok, rep.reason  # includes the epoch-cache invariants
            ec = rep.epoch_cache
            assert ec["enabled"] and ec["depth"] == 2
            # genesis + 3 rotations = 4 distinct epochs
            assert ec["misses"] >= 4
            assert ec["hits"] > 0
            assert ec["evictions"] >= 2
        finally:
            epoch_cache.reset()

    def test_standby_nodes_track_chain_without_voting(self):
        c = Cluster(n_nodes=5, n_validators=3, seed=5)
        try:
            rep = c.run_to_height(6, max_virtual_s=120.0)
            assert rep.ok, rep.reason
            # standbys committed the chain...
            assert min(rep.heights) >= 6
            # ...but commits carry only the 3 validators' signature slots
            seen = c.nodes[4].bstore.load_seen_commit()
            assert len(seen.signatures) == 3
        finally:
            c.stop()


class TestScheduleSearch:
    """ISSUE 6 tentpole leg (c): seeds x generators explored until an
    invariant breaks, failing schedules delta-debugged to minimal."""

    def test_search_green_on_fixed_build(self, tmp_path):
        from tendermint_tpu.simnet.search import search_schedules

        res = search_schedules(
            [3], generators=("mixed",), n_nodes=4, height=6,
            max_virtual_s=120.0, max_wall_s=30.0,
            scenario_dir=str(tmp_path),
        )
        assert res.ok, res.failure
        assert len(res.runs) == 1 and res.runs[0]["ok"]
        assert list(tmp_path.iterdir()) == []  # no failure, no scenario

    def test_committed_scenarios_replay_green(self):
        """Every shrunk bug the search has ever found must stay fixed:
        tests/scenarios/*.json replay clean on the current build."""
        import glob

        from tendermint_tpu.simnet.search import load_scenario, run_schedule

        here = os.path.dirname(os.path.abspath(__file__))
        paths = sorted(glob.glob(os.path.join(here, "scenarios", "*.json")))
        assert paths, "regression scenario directory is empty"
        for path in paths:
            kw = load_scenario(path)
            rep = run_schedule(
                kw["faults"], kw["seed"], kw["n_nodes"],
                kw["n_validators"], kw["link"], kw["height"],
                max_virtual_s=120.0, max_wall_s=60.0,
            )
            if not rep.ok and rep.wall_budget_hit:
                pytest.skip(
                    f"{os.path.basename(path)}: wall budget cut the "
                    "replay short (machine too slow) — inconclusive"
                )
            assert rep.ok, f"{os.path.basename(path)}: {rep.reason}"


class TestInvariantCheckers:
    def test_agreement_checker_detects_divergence(self):
        """The checker itself must fire: feed it a forged conflicting
        block hash and expect a violation record."""
        c, rep = run(seed=10, h=3)
        assert rep.ok
        # simulate a diverged commit observation
        c._canonical[2] = b"\x00" * 32
        violations = c.check_invariants()
        assert any("convergence" in v for v in violations)

    def test_quorum_checker_detects_thin_commit(self):
        c, rep = run(seed=11, h=3)
        assert rep.ok
        seen = c.nodes[0].bstore.load_seen_commit()
        # the real commit passes the real checker...
        assert c.commit_quorum_violation(seen, 0) is None
        # ...and a forged sub-quorum commit must trip it
        import dataclasses

        thin = dataclasses.replace(
            seen,
            signatures=[
                sig if i == 0 else dataclasses.replace(
                    sig, block_id_flag=1, signature=b"", validator_address=b"",
                )
                for i, sig in enumerate(seen.signatures)
            ],
        )
        violation = c.commit_quorum_violation(thin, 0)
        assert violation is not None and "quorum" in violation

    def test_fault_validation_rejects_bad_schedules(self):
        with pytest.raises(ValueError):
            Cluster(n_nodes=4, faults=[Fault(kind="warp", at_time=0.0)])
        with pytest.raises(ValueError):
            Cluster(n_nodes=4, faults=[Fault(kind="crash", at_height=2, node=9)])
        with pytest.raises(ValueError):
            Cluster(n_nodes=4, faults=[Fault(kind="partition", at_time=1.0)])


class TestSteppedModeParity:
    def test_wait_for_height_blocking_wait(self):
        """The condition-based wait_for_height (satellite: no sleep-poll)
        still works on a threaded node."""
        from tests.test_consensus import make_node
        from tendermint_tpu.crypto import ed25519

        sk = ed25519.gen_priv_key(bytes([9]) * 32)
        cs, bstore, _ = make_node([sk], 0)
        cs.start()
        try:
            cs.wait_for_height(2, timeout=60)
            assert bstore.height() >= 2
            with pytest.raises(TimeoutError):
                cs.wait_for_height(10_000, timeout=0.3)
        finally:
            cs.stop()

    def test_partition_heal_schedule_helper(self):
        sched = partition_heal_schedule(4, at_height=3, duration=1.0)
        assert sched[0].groups == [[0, 1], [2, 3]]
        _, rep = run(seed=12, faults=sched, h=6)
        assert rep.ok, rep.reason
