"""Tier-1 face of the light verification service (ISSUE 11).

Same pattern as test_mesh_isolated.py / test_simnet_isolated.py: the
container lacks the `cryptography` wheel, so the service suite
(tests/test_light_service.py — parity, streaming, RPC endpoint, the
simnet churn e2e with 200+ clients) and the `tools/prep_bench.py
--light` coalescing/parity/leak gate run in SUBPROCESSES with
TM_TPU_PUREPY_CRYPTO=1, which must never leak into the main pytest
process.
"""

import os
import subprocess
import sys

import pytest


def _repo_root():
    return os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _purepy_env():
    from tendermint_tpu.libs import jaxcache

    env = dict(os.environ, TM_TPU_PUREPY_CRYPTO="1", JAX_PLATFORMS="cpu")
    env.pop("TM_TPU_DONATE", None)
    env.pop("TM_TPU_MESH", None)
    jaxcache.set_env(env, _repo_root())
    return env


def test_light_service_under_purepy_fallback():
    try:
        import cryptography  # noqa: F401

        pytest.skip("cryptography present; test_light_service runs directly")
    except ModuleNotFoundError:
        pass
    here = os.path.dirname(os.path.abspath(__file__))
    r = subprocess.run(
        [
            sys.executable, "-m", "pytest",
            os.path.join(here, "test_light_service.py"),
            "-q", "-m", "not slow", "-p", "no:cacheprovider",
        ],
        capture_output=True,
        env=_purepy_env(),
        cwd=_repo_root(),
        timeout=800,
    )
    tail = (r.stdout or b"").decode(errors="replace")[-3000:]
    assert r.returncode == 0, f"isolated test_light_service run failed:\n{tail}"


def test_prep_bench_light_gate():
    """ISSUE 11 satellite: the --light gate — cross-request same-epoch
    coalescing proven by launch count, verdict/blame parity vs the
    sequential verifier, memoized resubmission launches nothing, zero
    pool-slot leak — wired into tier-1 through the isolated runner."""
    r = subprocess.run(
        [
            sys.executable,
            os.path.join(_repo_root(), "tools", "prep_bench.py"),
            "--light",
        ],
        capture_output=True,
        env=_purepy_env(),
        cwd=_repo_root(),
        timeout=600,
    )
    out = (r.stdout or b"").decode(errors="replace")
    err = (r.stderr or b"").decode(errors="replace")
    assert r.returncode == 0, f"--light gate failed:\n{out}\n{err[-2000:]}"
