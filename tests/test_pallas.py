"""Pallas verification pipeline: differential conformance against the
ZIP-215 oracle (crypto/_edwards) and the backend dispatch wiring.

Runs the real 3-kernel pipeline (ops.pallas_verify) in interpret mode on
the CPU backend — the same traced program Mosaic compiles on TPU — over
the full edge-vector battery (small-order points, non-canonical
encodings, s >= L, corrupted keys/sigs/messages).
"""

import os

import numpy as np
import pytest

pytest.importorskip("jax")

from tendermint_tpu.crypto import _edwards as E  # noqa: E402
from tendermint_tpu.crypto import ed25519  # noqa: E402
from tendermint_tpu.ops import backend, pallas_verify as pv  # noqa: E402
from tests.test_ops import _edge_entries  # noqa: E402


def _oracle(entries):
    return [E.verify_zip215(p, m, s) for p, m, s in entries]


class TestPallasPipeline:
    def test_edge_vectors_bit_exact(self):
        entries = _edge_entries()
        bucket = ((len(entries) + 7) // 8) * 8
        args = pv.prepare_compact(entries, bucket)
        res = pv.verify_compact(*args, block=8, interpret=True)
        assert res[: len(entries)].tolist() == _oracle(entries)
        # padding lanes (identity A/R, s = k = 0) must verify
        assert res[len(entries) :].all()

    def test_multi_block_grid(self):
        sk = ed25519.gen_priv_key(b"\x09" * 32)
        entries = [
            (sk.pub_key().bytes(), b"g%d" % i, sk.sign(b"g%d" % i))
            for i in range(24)
        ]
        entries[17] = (
            entries[17][0],
            entries[17][1],
            entries[17][2][:-1] + bytes([entries[17][2][-1] ^ 1]),
        )
        args = pv.prepare_compact(entries, 24)
        res = pv.verify_compact(*args, block=8, interpret=True)
        want = [i != 17 for i in range(24)]
        assert res.tolist() == want

    def test_backend_dispatch_uses_pallas(self, monkeypatch):
        """TM_TPU_PALLAS=1 routes verify_batch through the Pallas path
        (interpret mode off-TPU) and results match the oracle."""
        monkeypatch.setenv("TM_TPU_PALLAS", "1")
        backend._use_pallas.cache_clear()
        # tiny pallas block so interpret mode stays fast
        monkeypatch.setattr(pv, "BLOCK", 8)
        try:
            entries = _edge_entries()[:10]
            res = backend.verify_batch(entries)
            assert res.tolist() == _oracle(entries)
        finally:
            backend._use_pallas.cache_clear()

    def test_prepare_compact_matches_prepare_batch_semantics(self):
        """The s<L flag and byte packing agree between the XLA and Pallas
        preps for the same entries."""
        entries = _edge_entries()
        n = len(entries)
        bucket = ((n + 7) // 8) * 8
        a_t, r_t, s_t, k_t, sok_t = pv.prepare_compact(entries, bucket)
        legacy = backend.prepare_batch(entries, backend._bucket_for(n))
        assert (sok_t[0, :n].astype(bool) == legacy[6][:n]).all()
        for i, (pk, _, sig) in enumerate(entries):
            assert bytes(a_t[:, i]) == pk
            assert bytes(r_t[:, i]) == sig[:32]
            assert bytes(s_t[:, i]) == sig[32:]
