"""sr25519 (schnorrkel/ristretto255) — reference crypto/sr25519 parity."""

import pytest

from tendermint_tpu.crypto import _ristretto as R
from tendermint_tpu.crypto import sr25519
from tendermint_tpu.crypto.encoding import pubkey_from_proto, pubkey_to_proto

# draft-irtf-cfrg-ristretto255 small-multiple test vectors (first 6)
SPEC_MULTIPLES = [
    "0000000000000000000000000000000000000000000000000000000000000000",
    "e2f2ae0a6abc4e71a884a961c500515f58e30b6aa582dd8db6a65945e08d2d76",
    "6a493210f7499cd17fecb510ae0cea23a110e8d5b901f8acadd3095c73a3b919",
    "94741f5d5d52755ece4f23f044ee27d5d1ea1e2bd196b462166b16152a9d0259",
    "da80862773358b466ffadfe0b3293ab3d9fd53c5ea6c955358f568322daf6a57",
    "e882b131016b52c1d3337080187cf768423efccbb517bb495ab812c4160ff44e",
]


class TestRistretto:
    def test_spec_small_multiples(self):
        pt = R.IDENTITY
        for i, want_hex in enumerate(SPEC_MULTIPLES):
            assert R.encode(pt) == bytes.fromhex(want_hex), f"multiple {i}"
            pt = R.add(pt, R.BASE)

    def test_decode_rejects_noncanonical(self):
        # non-canonical field element (>= p)
        assert R.decode(b"\xff" * 32) is None
        # negative s (odd)
        bad = bytearray(bytes.fromhex(SPEC_MULTIPLES[1]))
        bad[0] |= 1
        assert R.decode(bytes(bad)) is None

    def test_roundtrip(self):
        for k in (1, 7, 1234567):
            pt = R.scalar_mult(k, R.BASE)
            assert R.equals(R.decode(R.encode(pt)), pt)


class TestSr25519:
    def test_sign_verify(self):
        sk = sr25519.gen_priv_key(bytes(range(32)))
        pk = sk.pub_key()
        sig = sk.sign(b"msg")
        assert sig[63] & 0x80  # schnorrkel v1 marker
        assert pk.verify_signature(b"msg", sig)
        assert not pk.verify_signature(b"other", sig)
        bad = bytearray(sig)
        bad[5] ^= 1
        assert not pk.verify_signature(b"msg", bytes(bad))
        # missing marker bit rejected
        nomark = bytearray(sig)
        nomark[63] &= 0x7F
        assert not pk.verify_signature(b"msg", bytes(nomark))

    def test_randomized_signatures(self):
        sk = sr25519.gen_priv_key(bytes([9]) * 32)
        s1, s2 = sk.sign(b"m"), sk.sign(b"m")
        assert s1 != s2
        assert sk.pub_key().verify_signature(b"m", s1)
        assert sk.pub_key().verify_signature(b"m", s2)

    def test_batch_verifier(self):
        bv = sr25519.BatchVerifier()
        keys = [sr25519.gen_priv_key(bytes([i + 1]) * 32) for i in range(4)]
        for i, sk in enumerate(keys):
            bv.add(sk.pub_key(), b"m%d" % i, sk.sign(b"m%d" % i))
        ok, valid = bv.verify()
        assert ok and valid == [True] * 4
        bv2 = sr25519.BatchVerifier()
        bv2.add(keys[0].pub_key(), b"x", keys[0].sign(b"y"))
        ok, valid = bv2.verify()
        assert not ok and valid == [False]

    def test_proto_encoding_roundtrip(self):
        pk = sr25519.gen_priv_key(bytes([3]) * 32).pub_key()
        rt = pubkey_from_proto(pubkey_to_proto(pk))
        assert rt.type() == "sr25519" and rt.bytes() == pk.bytes()

    def test_address(self):
        pk = sr25519.gen_priv_key(bytes([4]) * 32).pub_key()
        assert len(pk.address()) == 20
