"""sr25519 (schnorrkel/ristretto255) — reference crypto/sr25519 parity."""

import os

import pytest

from tendermint_tpu.crypto import _ristretto as R
from tendermint_tpu.crypto import sr25519
from tendermint_tpu.crypto.encoding import pubkey_from_proto, pubkey_to_proto

# draft-irtf-cfrg-ristretto255 small-multiple test vectors (first 6)
SPEC_MULTIPLES = [
    "0000000000000000000000000000000000000000000000000000000000000000",
    "e2f2ae0a6abc4e71a884a961c500515f58e30b6aa582dd8db6a65945e08d2d76",
    "6a493210f7499cd17fecb510ae0cea23a110e8d5b901f8acadd3095c73a3b919",
    "94741f5d5d52755ece4f23f044ee27d5d1ea1e2bd196b462166b16152a9d0259",
    "da80862773358b466ffadfe0b3293ab3d9fd53c5ea6c955358f568322daf6a57",
    "e882b131016b52c1d3337080187cf768423efccbb517bb495ab812c4160ff44e",
]


class TestRistretto:
    def test_spec_small_multiples(self):
        pt = R.IDENTITY
        for i, want_hex in enumerate(SPEC_MULTIPLES):
            assert R.encode(pt) == bytes.fromhex(want_hex), f"multiple {i}"
            pt = R.add(pt, R.BASE)

    def test_decode_rejects_noncanonical(self):
        # non-canonical field element (>= p)
        assert R.decode(b"\xff" * 32) is None
        # negative s (odd)
        bad = bytearray(bytes.fromhex(SPEC_MULTIPLES[1]))
        bad[0] |= 1
        assert R.decode(bytes(bad)) is None

    def test_roundtrip(self):
        for k in (1, 7, 1234567):
            pt = R.scalar_mult(k, R.BASE)
            assert R.equals(R.decode(R.encode(pt)), pt)


class TestSr25519:
    def test_sign_verify(self):
        sk = sr25519.gen_priv_key(bytes(range(32)))
        pk = sk.pub_key()
        sig = sk.sign(b"msg")
        assert sig[63] & 0x80  # schnorrkel v1 marker
        assert pk.verify_signature(b"msg", sig)
        assert not pk.verify_signature(b"other", sig)
        bad = bytearray(sig)
        bad[5] ^= 1
        assert not pk.verify_signature(b"msg", bytes(bad))
        # missing marker bit rejected
        nomark = bytearray(sig)
        nomark[63] &= 0x7F
        assert not pk.verify_signature(b"msg", bytes(nomark))

    def test_randomized_signatures(self):
        sk = sr25519.gen_priv_key(bytes([9]) * 32)
        s1, s2 = sk.sign(b"m"), sk.sign(b"m")
        assert s1 != s2
        assert sk.pub_key().verify_signature(b"m", s1)
        assert sk.pub_key().verify_signature(b"m", s2)

    def test_batch_verifier(self):
        bv = sr25519.BatchVerifier()
        keys = [sr25519.gen_priv_key(bytes([i + 1]) * 32) for i in range(4)]
        for i, sk in enumerate(keys):
            bv.add(sk.pub_key(), b"m%d" % i, sk.sign(b"m%d" % i))
        ok, valid = bv.verify()
        assert ok and valid == [True] * 4
        bv2 = sr25519.BatchVerifier()
        bv2.add(keys[0].pub_key(), b"x", keys[0].sign(b"y"))
        ok, valid = bv2.verify()
        assert not ok and valid == [False]

    def test_proto_encoding_roundtrip(self):
        pk = sr25519.gen_priv_key(bytes([3]) * 32).pub_key()
        rt = pubkey_from_proto(pubkey_to_proto(pk))
        assert rt.type() == "sr25519" and rt.bytes() == pk.bytes()

    def test_address(self):
        pk = sr25519.gen_priv_key(bytes([4]) * 32).pub_key()
        assert len(pk.address()) == 20


class TestNativeMerlin:
    """native/tm_native.cpp sr25519_challenges must match the pure-Python
    merlin transcript bit-for-bit (the host half of the device lane)."""

    def test_challenges_match_pure_python(self):
        from tendermint_tpu.crypto.sr25519 import (
            SIGNING_CTX,
            _signing_transcript,
            gen_priv_key,
        )
        from tendermint_tpu.native import load

        nat = load()
        if nat is None:
            import pytest

            pytest.skip("no native toolchain")
        sk = gen_priv_key(b"\x31" * 32)
        pub = sk.pub_key().bytes()
        msgs, rss, want = [], [], []
        for i in range(6):
            msg = b"nm-%d" % i + b"y" * (i * 13 % 50)
            sig = sk.sign(msg)
            t = _signing_transcript(msg)
            t.append_message(b"proto-name", b"Schnorr-sig")
            t.append_message(b"sign:pk", pub)
            t.append_message(b"sign:R", sig[:32])
            want.append(t.challenge_bytes(b"sign:c", 64))
            msgs.append(msg)
            rss.append(sig[:32])
        got = nat.sr25519_challenges(
            SIGNING_CTX, pub * len(msgs), b"".join(rss), msgs
        )
        assert all(
            got[64 * i : 64 * (i + 1)] == want[i] for i in range(len(msgs))
        )


class TestSr25519Prep:
    def test_prepare_flags(self):
        from tendermint_tpu.crypto.sr25519 import gen_priv_key
        from tendermint_tpu.ops.pallas_sr25519 import prepare_sr25519

        sk = gen_priv_key(b"\x32" * 32)
        msg = b"prep"
        sig = sk.sign(msg)
        pub = sk.pub_key().bytes()
        entries = [
            (pub, msg, sig),
            (pub, msg, sig[:63] + bytes([sig[63] & 0x7F])),  # no v1 marker
            (
                pub,
                msg,
                sig[:32]
                + bytes(
                    b | (0x80 if i == 31 else 0)
                    for i, b in enumerate(
                        __import__(
                            "tendermint_tpu.crypto._edwards", fromlist=["L"]
                        ).L.__add__(1).to_bytes(32, "little")
                    )
                ),
            ),  # s = L + 1
            (b"\xff" * 32, msg, sig),  # non-canonical A encoding
        ]
        a_t, r_t, s_t, k_t, aok, rok, sok = prepare_sr25519(entries, 8)
        assert sok[0, 0] == 1 and aok[0, 0] == 1 and rok[0, 0] == 1
        assert sok[0, 1] == 0  # missing marker
        assert sok[0, 2] == 0  # s >= L
        assert aok[0, 3] == 0  # A >= p
        # padding lanes admissible
        assert sok[0, 4:].all() and aok[0, 4:].all() and rok[0, 4:].all()
        # s had the marker stripped
        assert s_t[31, 0] == sig[63] & 0x7F

    def test_mixed_dispatch_host_lanes(self):
        """verify_mixed partitions by key type and agrees with per-curve
        verification (device lanes off -> host paths)."""
        import os

        from tendermint_tpu.crypto import ed25519, secp256k1, sr25519
        from tendermint_tpu.ops import backend, mixed

        backend._use_pallas.cache_clear()
        prior = os.environ.get("TM_TPU_PALLAS")
        os.environ["TM_TPU_PALLAS"] = "0"
        try:
            entries = []
            ed = ed25519.gen_priv_key(b"\x33" * 32)
            entries.append((ed.pub_key(), b"m1", ed.sign(b"m1")))
            sr = sr25519.gen_priv_key(b"\x34" * 32)
            entries.append((sr.pub_key(), b"m2", sr.sign(b"m2")))
            sc = secp256k1.gen_priv_key()
            entries.append((sc.pub_key(), b"m3", sc.sign(b"m3")))
            bad = sr.sign(b"m4")
            entries.append((sr.pub_key(), b"tampered", bad))
            res = mixed.verify_mixed(entries)
            assert res == [True, True, True, False]
        finally:
            if prior is None:
                del os.environ["TM_TPU_PALLAS"]
            else:
                os.environ["TM_TPU_PALLAS"] = prior
            backend._use_pallas.cache_clear()


class TestSr25519DeviceLaneK1:
    """Always-on coverage for the default-on device lane: the ristretto
    DECODE kernel (K1) runs in interpret mode at a tiny bucket on every
    suite run (~20 s cold compile, cached afterwards), so CPU CI executes
    the sr25519 kernel code the production mixed path enables by default.
    The full-ladder differential below is @slow (compile-heavy)."""

    def test_k1_decode_differential(self):
        import jax
        import jax.numpy as jnp
        import numpy as np
        from jax.experimental import pallas as pl
        from jax.experimental.pallas import tpu as pltpu

        from tendermint_tpu.crypto import _ristretto, sr25519
        from tendermint_tpu.ops import fe_t
        from tendermint_tpu.ops import pallas_sr25519 as ps

        sk = sr25519.gen_priv_key(b"\x07" * 32)
        sig = sk.sign(b"k1")
        pub = sk.pub_key().bytes()
        # lane 1: canonical+even (passes host flags) but NOT on the curve
        # (non-square ratio) — rejection must come from the kernel itself
        bad_enc = (2).to_bytes(32, "little")
        assert _ristretto.decode(bad_enc) is None
        entries = [(pub, b"k1", sig), (bad_enc, b"x", sig)]
        args = ps.prepare_sr25519(entries, 8)
        assert args[4][0, 1] == 1, "bad_enc must pass the host-side flags"

        n = block = 8

        def spec(rows):
            return pl.BlockSpec(
                (rows, block), lambda i: (0, i), memory_space=pltpu.VMEM
            )

        k1 = pl.pallas_call(
            ps._k1r_decode_kernel,
            grid=(1,),
            in_specs=[spec(32)] * 4 + [spec(1), spec(1)],
            out_specs=[spec(8 * 32), spec(2), spec(128), spec(128)],
            out_shape=[
                jax.ShapeDtypeStruct((8 * 32, n), jnp.int32),
                jax.ShapeDtypeStruct((2, n), jnp.int32),
                jax.ShapeDtypeStruct((128, n), jnp.int32),
                jax.ShapeDtypeStruct((128, n), jnp.int32),
            ],
            interpret=True,
        )
        coords, ok, _, _ = jax.jit(k1)(*args[:6])
        ok = np.asarray(ok)
        assert ok[0, 0] == 1 and ok[1, 0] == 1  # A and R of the valid sig
        assert ok[0, 1] == 0  # off-curve A rejected in-kernel

        # lane 0's decoded A must equal the host ristretto oracle
        pt = _ristretto.decode(pub)
        assert pt is not None
        coords = np.asarray(coords)

        def limbs_to_int(rows):
            return sum(int(v) << (fe_t.RADIX * i) for i, v in enumerate(rows)) % fe_t.P

        x = limbs_to_int(coords[0:20, 0])
        y = limbs_to_int(coords[32:52, 0])
        z = limbs_to_int(coords[64:84, 0])
        assert z == 1
        assert (x, y) == (pt[0] % fe_t.P, pt[1] % fe_t.P)


@pytest.mark.slow
class TestSr25519DeviceLane:
    def test_interpret_differential(self):
        from tendermint_tpu.crypto import sr25519
        from tendermint_tpu.ops import pallas_sr25519 as ps

        sk = sr25519.gen_priv_key(b"\x01" * 32)
        msg = b"m"
        sig = sk.sign(msg)
        pub = sk.pub_key().bytes()
        entries = [(pub, msg, sig), (pub, b"bad", sig)]
        expect = [sr25519.verify(p, m, s) for p, m, s in entries]
        args = ps.prepare_sr25519(entries, 8)
        res = ps.verify_sr25519_compact(*args, block=8, interpret=True)
        assert res[:2].tolist() == expect
        assert res[2:].all(), "padding lanes (ristretto identity) must verify"
