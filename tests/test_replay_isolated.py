"""Tier-1 face of chain-replay catch-up (ISSUE 14).

Same pattern as test_ingress_isolated.py: the container lacks the
`cryptography` wheel, so the replay suite (tests/test_blocksync_replay.py
— epoch-cut planning, range verification over a real signed chain,
forged-commit fallback parity, writer-thread ordering, speculation
hit/miss/discard edges, wake-event no-hot-spin) and the
`tools/prep_bench.py --replay` gate run in SUBPROCESSES with
TM_TPU_PUREPY_CRYPTO=1, which must never leak into the main pytest
process.
"""

import os
import subprocess
import sys

import pytest


def _repo_root():
    return os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _purepy_env():
    from tendermint_tpu.libs import jaxcache

    env = dict(os.environ, TM_TPU_PUREPY_CRYPTO="1", JAX_PLATFORMS="cpu")
    env.pop("TM_TPU_DONATE", None)
    env.pop("TM_TPU_MESH", None)
    jaxcache.set_env(env, _repo_root())
    return env


def test_replay_suite_under_purepy_fallback():
    try:
        import cryptography  # noqa: F401

        pytest.skip("cryptography present; test_blocksync_replay runs directly")
    except ModuleNotFoundError:
        pass
    here = os.path.dirname(os.path.abspath(__file__))
    r = subprocess.run(
        [
            sys.executable, "-m", "pytest",
            os.path.join(here, "test_blocksync_replay.py"),
            "-q", "-m", "not slow", "-p", "no:cacheprovider",
        ],
        capture_output=True,
        env=_purepy_env(),
        cwd=_repo_root(),
        timeout=800,
    )
    tail = (r.stdout or b"").decode(errors="replace")[-3000:]
    assert r.returncode == 0, f"isolated test_blocksync_replay run failed:\n{tail}"


def test_simnet_catchup_under_purepy_fallback():
    """ISSUE 14 e2e face: a crashed node rejoins far behind under churn
    + 10% drop links and catches up live through the ReplayEngine
    (tests/test_simnet_catchup.py: range hit-rate > 0.9 in
    SimReport.catchup, replay-exact across seeds)."""
    try:
        import cryptography  # noqa: F401

        pytest.skip("cryptography present; test_simnet_catchup runs directly")
    except ModuleNotFoundError:
        pass
    here = os.path.dirname(os.path.abspath(__file__))
    r = subprocess.run(
        [
            sys.executable, "-m", "pytest",
            os.path.join(here, "test_simnet_catchup.py"),
            "-q", "-m", "not slow", "-p", "no:cacheprovider",
        ],
        capture_output=True,
        env=_purepy_env(),
        cwd=_repo_root(),
        timeout=800,
    )
    tail = (r.stdout or b"").decode(errors="replace")[-3000:]
    assert r.returncode == 0, f"isolated test_simnet_catchup run failed:\n{tail}"


def test_prep_bench_replay_gate():
    """ISSUE 14 satellite: the --replay gate — range packing proven by
    launch count (W same-epoch heights -> ceil(W*sigs/bucket) launches,
    not W), mid-range forged-commit fallback with verify_commit_light's
    exact error string, zero pool-slot leak — wired into tier-1 through
    the isolated runner."""
    r = subprocess.run(
        [
            sys.executable,
            os.path.join(_repo_root(), "tools", "prep_bench.py"),
            "--replay",
        ],
        capture_output=True,
        env=_purepy_env(),
        cwd=_repo_root(),
        timeout=600,
    )
    out = (r.stdout or b"").decode(errors="replace")
    err = (r.stderr or b"").decode(errors="replace")
    assert r.returncode == 0, f"--replay gate failed:\n{out}\n{err[-2000:]}"
