"""Overlapped relay (ISSUE 7): transfer/compute pipelining in the
dispatch-owner loop, the per-shape device buffer pool, buffer donation
parity (cold + warm epoch, buckets 128/1024), the structured async
verdict readback, and the poisoned-batch buffer-return bookkeeping.

Donation on this container's CPU backend is a no-op with a warning (XLA
CPU ignores donate_argnums) — the parity tests still pin the donated
wrappers' verdict/blame bit-equality and exercise the exact call paths
the TPU backend donates for real."""

import time

import numpy as np
import pytest

try:
    from tendermint_tpu.crypto import ed25519
except ModuleNotFoundError:
    # No cryptography wheel in this container. Do NOT flip
    # TM_TPU_PUREPY_CRYPTO here (env leaks into later-collected modules);
    # test_overlap_isolated.py re-runs this module in a subprocess with
    # the fallback enabled instead.
    pytest.skip(
        "ed25519 backend unavailable (runs via test_overlap_isolated.py)",
        allow_module_level=True,
    )

from tendermint_tpu.libs import devcheck
from tendermint_tpu.observability import trace as _tr
from tendermint_tpu.ops import backend, device_pool, epoch_cache
from tendermint_tpu.ops import ed25519_verify as ev


@pytest.fixture(autouse=True)
def _devcheck_armed():
    """ISSUE 8: the overlap suite runs with the runtime invariant
    checkers on (relay assertions, lock-order cycles, write-after-
    resolve canary); a violation fails the offending test at teardown.
    Direct kernel launches by parity tests stay legal — the relay
    assertion only gates transfer/table-upload entry points once a
    dispatcher has claimed ownership."""
    devcheck.enable(reset=True)
    yield
    try:
        devcheck.check()
    finally:
        devcheck.reset_state()
        devcheck.disable()
from tendermint_tpu.ops import pipeline as pl
from tendermint_tpu.ops._testing import drain_pool, slow_prepare
from tendermint_tpu.ops.entry_block import EntryBlock

pytestmark = pytest.mark.filterwarnings(
    "ignore:Some donated buffers were not usable"
)

_RNG = np.random.RandomState(42)


def _signed_entries(n, tag=0, bad=()):
    """n REAL (pub, msg, sig) triples, sigs at `bad` indices corrupted."""
    out = []
    for i in range(n):
        sk = ed25519.gen_priv_key(bytes([tag + 1]) * 30 + i.to_bytes(2, "big"))
        m = b"overlap-%d-%d" % (tag, i)
        s = sk.sign(m)
        if i in bad:
            s = s[:-1] + bytes([s[-1] ^ 1])
        out.append((sk.pub_key().bytes(), m, s))
    return out


def _random_entries(n, tag=0):
    """Structurally-valid random triples — verdict parity between the
    donated and plain wrappers does not need valid signatures."""
    return [
        (
            _RNG.randint(0, 256, 32, dtype=np.uint8).tobytes(),
            b"rnd-%d-%d" % (tag, i),
            _RNG.randint(0, 256, 64, dtype=np.uint8).tobytes(),
        )
        for i in range(n)
    ]


def _warm_epoch(n_vals, n_sigs, bad=()):
    """A direct EpochEntry + warm EntryBlock (val_idx/epoch_key set), the
    shape prepare_batch_cached* consumes — no cache registry involved."""
    sks = [
        ed25519.gen_priv_key(b"\x05" * 30 + i.to_bytes(2, "big"))
        for i in range(n_vals)
    ]
    pub_col = np.frombuffer(
        b"".join(sk.pub_key().bytes() for sk in sks), dtype=np.uint8
    ).reshape(n_vals, 32)
    ep = epoch_cache.EpochEntry(b"\xEE" * 32, pub_col)
    idx = _RNG.randint(0, n_vals, size=n_sigs)
    entries = []
    for j, i in enumerate(idx):
        m = b"warm-%d" % j
        s = sks[i].sign(m)
        if j in bad:
            s = s[:-1] + bytes([s[-1] ^ 1])
        entries.append((sks[i].pub_key().bytes(), m, s))
    block = EntryBlock.from_entries(entries)
    block.val_idx = idx.astype(np.int32)
    block.epoch_key = ep.key
    return ep, block


def _assert_verdict_blame_parity(a, b):
    a, b = np.asarray(a).astype(bool), np.asarray(b).astype(bool)
    assert np.array_equal(a, b)
    if not a.all():
        assert int(np.argmin(a)) == int(np.argmin(b))


class TestDonationParity:
    """Donated wrappers are bit-identical to the plain ones — verdicts
    AND blame — and never read a donated input after launch (fresh args
    per call, exactly the pipeline's usage)."""

    @pytest.mark.parametrize("bucket,n", [(128, 100), (1024, 1000)])
    def test_cold_epoch_device_hash_parity(self, bucket, n):
        entries = (
            _signed_entries(16, tag=1, bad=(3, 7)) + _random_entries(n - 16)
            if bucket == 128
            else _random_entries(n, tag=2)
        )
        block = EntryBlock.from_entries(entries)
        plain = ev.jitted_verify_device_hash(False)(
            *backend.prepare_batch_device_hash(block, bucket)
        )
        donated = ev.jitted_verify_device_hash(True)(
            *backend.prepare_batch_device_hash(block, bucket)
        )
        _assert_verdict_blame_parity(
            np.asarray(plain)[:n], np.asarray(donated)[:n]
        )

    @pytest.mark.parametrize("bucket,n", [(128, 100), (1024, 1000)])
    def test_warm_epoch_device_hash_parity(self, bucket, n):
        ep, block = _warm_epoch(100, n, bad=(5,))
        plain = backend.cached_kernel(ep, True, donate=False)(
            *backend.prepare_batch_cached_device_hash(block, bucket, ep)
        )
        donated = backend.cached_kernel(ep, True, donate=True)(
            *backend.prepare_batch_cached_device_hash(block, bucket, ep)
        )
        p, d = np.asarray(plain)[:n], np.asarray(donated)[:n]
        _assert_verdict_blame_parity(p, d)
        assert not p[5]  # the corrupted lane is blamed on both paths
        # the epoch tables survived the donated launch (donation exempt):
        # a second donated call over fresh args still verifies
        again = backend.cached_kernel(ep, True, donate=True)(
            *backend.prepare_batch_cached_device_hash(block, bucket, ep)
        )
        assert np.array_equal(np.asarray(again)[:n], p)

    def test_donated_pipeline_overlapping_batches(self, monkeypatch):
        """ISSUE 7 regression: two (five) overlapping batches with
        DISTINGUISHABLE payloads through a donation-enabled pipeline —
        a donated input buffer read after launch, or a recycled buffer
        leaking between batches, would flip verdicts across batches."""
        monkeypatch.setenv("TM_TPU_DONATE", "1")
        backend.donate_enabled.cache_clear()
        try:
            assert backend.donate_enabled() is True
            v = pl.AsyncBatchVerifier(depth=2)
            try:
                futs = [
                    v.submit(_signed_entries(8, tag=t, bad=(t % 8,)))
                    for t in range(5)
                ]
                donated_res = [f.result(timeout=300) for f in futs]
            finally:
                v.close()
        finally:
            monkeypatch.setenv("TM_TPU_DONATE", "0")
            backend.donate_enabled.cache_clear()
        try:
            v2 = pl.AsyncBatchVerifier(depth=2)
            try:
                futs = [
                    v2.submit(_signed_entries(8, tag=t, bad=(t % 8,)))
                    for t in range(5)
                ]
                plain_res = [f.result(timeout=300) for f in futs]
            finally:
                v2.close()
        finally:
            monkeypatch.delenv("TM_TPU_DONATE", raising=False)
            backend.donate_enabled.cache_clear()
        for t, (d, p) in enumerate(zip(donated_res, plain_res)):
            d, p = np.asarray(d), np.asarray(p)
            assert d.shape == (8,)
            assert not d[t % 8] and d.sum() == 7, f"batch {t}"
            assert np.array_equal(d, p)


class TestBufferPool:
    def test_poisoned_batch_leaks_no_slots(self, monkeypatch):
        """ISSUE 7 satellite: a kernel-launch failure must return the
        batch's pool slot (and depth permit) — DispatchError carries the
        buffer-return bookkeeping too."""
        real_prepare = pl.AsyncBatchVerifier._prepare
        POISON_N = 3

        def prep(entries):
            f, args, rlc, bucket = real_prepare(entries)
            if len(entries) == POISON_N:
                def boom(*_a):
                    raise RuntimeError("kernel launch exploded")

                return boom, args, rlc, bucket
            return f, args, rlc, bucket

        monkeypatch.setattr(
            pl.AsyncBatchVerifier, "_prepare", staticmethod(prep)
        )
        v = pl.AsyncBatchVerifier(depth=2)
        try:
            for round_ in range(2):
                bad = v.submit(_random_entries(POISON_N, tag=round_))
                with pytest.raises(pl.DispatchError):
                    bad.result(timeout=300)
                good = v.submit(_random_entries(8, tag=10 + round_))
                assert good.result(timeout=300).shape == (8,)
            assert v._dispatch_thread.is_alive()
            drain_pool(v._pool)
            stats = v._pool.stats()
            assert stats["in_flight"] == 0, stats
            assert stats["free"] == stats["minted"], stats
        finally:
            v.close()

    def test_transfer_failure_fails_batch_alone(self, monkeypatch):
        real = device_pool.transfer
        state = {"boom": True}

        def xfer(args):
            if state["boom"]:
                state["boom"] = False
                raise RuntimeError("relay transfer exploded")
            return real(args)

        monkeypatch.setattr(pl._dpool, "transfer", xfer)
        v = pl.AsyncBatchVerifier(depth=2)
        try:
            bad = v.submit(_random_entries(4))
            with pytest.raises(pl.DispatchError, match="transfer"):
                bad.result(timeout=300)
            good = v.submit(_random_entries(8, tag=1))
            assert good.result(timeout=300).shape == (8,)
            assert v._dispatch_thread.is_alive()
            # futures complete BEFORE the resolver returns the slot —
            # drain instead of racing the release
            drain_pool(v._pool)
            assert v._pool.in_flight() == 0
        finally:
            v.close()

    def test_pool_reuse_steady_state(self):
        """Same layout streamed repeatedly: the pool mints at most
        `pool_depth` slots, then every acquire recycles."""
        v = pl.AsyncBatchVerifier(depth=2, pool_depth=2)
        try:
            for t in range(6):
                v.submit(_random_entries(96, tag=t)).result(timeout=300)
            drain_pool(v._pool)
            stats = v._pool.stats()
            assert stats["minted"] <= 2 * stats["layouts"], stats
            assert stats["in_flight"] == 0, stats
        finally:
            v.close()


class TestOverlapStructure:
    def test_transfer_overlaps_previous_batch(self, monkeypatch):
        """Span-order proof of the pipelined loop: with a slow (mocked)
        readback and depth 1, batch k+1's transfer is issued before batch
        k resolves, transfers precede their own launch, and the transfer
        stage runs on the single dispatch-owner thread."""
        monkeypatch.setattr(
            pl.AsyncBatchVerifier, "_prepare",
            staticmethod(slow_prepare(pl.AsyncBatchVerifier._prepare, 0.1)),
        )
        monkeypatch.setattr(backend, "max_coalesce", lambda: 96)
        _tr.TRACER.clear()
        _tr.configure(enabled=True)
        v = pl.AsyncBatchVerifier(depth=1, pool_depth=2)
        try:
            v.submit(_random_entries(96, tag=99)).result(timeout=300)
            futs = [v.submit(_random_entries(96, tag=t)) for t in range(4)]
            for f in futs:
                f.result(timeout=300)
        finally:
            _tr.configure(enabled=False)
            v.close()
        xfers, dispatches, waits = [], [], []
        tids = set()
        for name, start, end, tid, args in _tr.TRACER.events():
            if name == "pipeline.transfer":
                xfers.append((start, end, args or {}))
                tids.add(tid)
            elif name == "pipeline.dispatch":
                dispatches.append((start, end))
                tids.add(tid)
            elif name == "pipeline.device_wait":
                waits.append((start, end))
        xfers.sort(), dispatches.sort(), waits.sort()
        assert len(xfers) == len(dispatches) == len(waits) == 5
        xfers, dispatches, waits = xfers[1:], dispatches[1:], waits[1:]
        # split: every batch's transfer closes before its launch opens
        assert all(x[1] <= d[0] for x, d in zip(xfers, dispatches))
        # overlap: transfer k+1 issued before batch k resolved
        overlapped = sum(
            1 for i in range(1, 4) if xfers[i][0] < waits[i - 1][1]
        )
        assert overlapped >= 2, (overlapped, xfers, waits)
        assert sum(1 for x in xfers if x[2].get("hidden")) >= 3
        # relay single-owner extends to the transfer stage
        assert tids == v.dispatch_thread_idents == {v._dispatch_thread.ident}

    def test_d2h_capability_probe_cached(self):
        first = pl._d2h_async_supported()
        assert isinstance(first, bool)
        assert pl._d2h_async_supported() is first
        assert pl._d2h_async_supported.cache_info().hits >= 1
        # on this jax, device arrays do expose the async copy
        import jax

        arr = jax.device_put(np.zeros(1, dtype=np.uint8))
        assert first == callable(getattr(arr, "copy_to_host_async", None))

    def test_overlap_metrics_surfaced(self):
        from tendermint_tpu.libs.metrics import ops_stats

        v = pl.AsyncBatchVerifier(depth=2)
        try:
            v.submit(_random_entries(32)).result(timeout=300)
        finally:
            v.close()
        s = ops_stats()
        assert "transfer_overlap_ratio" in s
        assert s["buffer_pool_hits"] + s["buffer_pool_misses"] >= 1
