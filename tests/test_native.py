"""Native C++ module (native/tm_native.cpp): parity vs the pure paths.
Skips when no toolchain can build it."""

import hashlib
import random

import numpy as np
import pytest

from tendermint_tpu.native import load

native = load()
pytestmark = pytest.mark.skipif(native is None, reason="native module unavailable")


def _pure_root(items):
    from tendermint_tpu.crypto import merkle

    if not items:
        return hashlib.sha256(b"").digest()
    if len(items) == 1:
        return merkle.leaf_hash(items[0])
    k = merkle.split_point(len(items))
    return merkle.inner_hash(_pure_root(items[:k]), _pure_root(items[k:]))


class TestNative:
    def test_merkle_root_parity(self):
        rng = random.Random(4)
        for n in (0, 1, 2, 3, 7, 16, 33, 100):
            items = [rng.randbytes(rng.randrange(0, 100)) for _ in range(n)]
            assert native.merkle_root(items) == _pure_root(items), n

    def test_sha256_many(self):
        items = [b"a", b"bb", b"" , b"x" * 1000]
        out = native.sha256_many(items)
        for i, item in enumerate(items):
            assert out[32 * i : 32 * i + 32] == hashlib.sha256(item).digest()

    def test_pack_parity(self):
        from tendermint_tpu.ops import backend
        import tendermint_tpu.native as nat
        import os

        rng = random.Random(7)
        enc = np.frombuffer(rng.randbytes(32 * 40), dtype=np.uint8).reshape(40, 32).copy()
        os.environ["TM_TPU_NO_NATIVE"] = "1"
        nat._module, nat._tried = None, False
        try:
            pure_limbs = backend._pack_le_limbs(enc)
            pure_bits = backend._bits_253(enc)
        finally:
            os.environ.pop("TM_TPU_NO_NATIVE")
            nat._module, nat._tried = None, False
        assert (backend._pack_le_limbs(enc) == pure_limbs).all()
        assert (backend._bits_253(enc) == pure_bits).all()

    def test_ed25519_challenges_differential(self):
        """Native k = SHA512(R||A||M) mod L vs hashlib/bigint, on both the
        OpenSSL one-shot path and the scalar fallback (no_ossl=True),
        including SHA-512 block-boundary message lengths."""
        import hashlib

        from tendermint_tpu.crypto._edwards import L
        from tendermint_tpu.native import load

        m = load()
        if m is None:
            import pytest

            pytest.skip("no native toolchain")
        rng = random.Random(11)
        lens = [0, 47, 63, 64, 65, 111, 112, 113, 127, 128, 129, 255, 256, 300]
        n = len(lens) + 100
        rs = rng.randbytes(32 * n)
        pubs = rng.randbytes(32 * n)
        msgs = [bytes(ln) for ln in lens] + [
            rng.randbytes(rng.randrange(0, 200)) for _ in range(100)
        ]
        for no_ossl in (False, True):
            out = m.ed25519_challenges(rs, pubs, msgs, no_ossl)
            for i in range(n):
                expect = (
                    int.from_bytes(
                        hashlib.sha512(
                            rs[32 * i : 32 * i + 32]
                            + pubs[32 * i : 32 * i + 32]
                            + msgs[i]
                        ).digest(),
                        "little",
                    )
                    % L
                ).to_bytes(32, "little")
                assert out[32 * i : 32 * i + 32] == expect, (no_ossl, i)

    def test_challenges_backend_fallback_parity(self):
        """ops.backend._challenges: native and pure-Python agree."""
        import os

        import tendermint_tpu.native as nat
        from tendermint_tpu.ops import backend

        rng = random.Random(13)
        n = 40
        r_enc = np.frombuffer(rng.randbytes(32 * n), dtype=np.uint8).reshape(n, 32).copy()
        pub = np.frombuffer(rng.randbytes(32 * n), dtype=np.uint8).reshape(n, 32).copy()
        msgs = [rng.randbytes(50 + i) for i in range(n)]
        prior = os.environ.get("TM_TPU_NO_NATIVE")
        os.environ["TM_TPU_NO_NATIVE"] = "1"
        nat._module, nat._tried = None, False
        try:
            pure = backend._challenges(r_enc, pub, msgs)
        finally:
            if prior is None:
                os.environ.pop("TM_TPU_NO_NATIVE")
            else:
                os.environ["TM_TPU_NO_NATIVE"] = prior
            nat._module, nat._tried = None, False
        assert backend._challenges(r_enc, pub, msgs) == pure

    def test_sr25519_verify_batch_differential(self):
        """Native schnorrkel verify vs the pure-Python oracle across
        valid/tampered/edge signatures."""
        from tendermint_tpu.crypto import sr25519
        from tendermint_tpu.native import load

        m = load()
        if m is None or not hasattr(m, "sr25519_verify_batch"):
            import pytest

            pytest.skip("no native sr25519")
        rng = random.Random(5)
        keys = [sr25519.gen_priv_key(bytes([i]) * 32) for i in range(4)]
        pubs, sigs, msgs = [], [], []
        for i in range(48):
            sk = keys[i % 4]
            msg = rng.randbytes(rng.randrange(0, 120))
            sig = sk.sign(msg)
            pub = sk.pub_key().bytes()
            kind = i % 6
            if kind == 1:
                sig = bytes([sig[0] ^ 1]) + sig[1:]
            elif kind == 2:
                sig = sig[:40] + bytes([sig[40] ^ 4]) + sig[41:]
            elif kind == 3:
                msg = msg + b"!"
            elif kind == 4:
                sig = sig[:63] + bytes([sig[63] & 0x7F])
            elif kind == 5:
                pub = keys[(i + 1) % 4].pub_key().bytes()
            pubs.append(pub)
            sigs.append(sig)
            msgs.append(msg)
        out = m.sr25519_verify_batch(
            b"substrate", b"".join(pubs), b"".join(sigs), msgs
        )
        expect = [sr25519.verify(p, mm, s) for p, mm, s in zip(pubs, msgs, sigs)]
        assert [bool(b) for b in out] == expect

    def test_sr25519_crypto_batch_uses_native(self):
        """crypto.sr25519.BatchVerifier agrees with per-sig verify and
        pinpoints the bad index."""
        from tendermint_tpu.crypto import sr25519

        sk = sr25519.gen_priv_key(b"\x07" * 32)
        bv = sr25519.BatchVerifier()
        msgs = [b"m%d" % i for i in range(10)]
        for i, msg in enumerate(msgs):
            sig = sk.sign(msg)
            if i == 4:
                sig = sig[:1] + bytes([sig[1] ^ 1]) + sig[2:]
            bv.add(sk.pub_key(), msg, sig)
        ok, valid = bv.verify()
        assert not ok
        assert valid[4] is False or valid[4] == 0
        assert sum(1 for v in valid if not v) == 1
