"""Observability: Prometheus exposition format, the scrape endpoint over
HTTP, span tracer nesting/ring-buffer/export, the trace_report tool, the
tracing-disabled overhead guard, and node-level integration (metrics
server + /dump_trace + /status verify-engine stats + OnStop trace flush).
"""

import json
import os
import re
import time
import urllib.request

import pytest

try:  # signature-backed paths need the OpenSSL wheel or the opt-in
    # pure-Python fallback (TM_TPU_PUREPY_CRYPTO=1, ~3ms/op — fine for
    # the handful of sigs the node tests sign); container images with
    # neither skip those classes and the rest of this suite must pass
    import cryptography  # noqa: F401

    HAVE_WHEEL = True
except ModuleNotFoundError:
    HAVE_WHEEL = False

HAVE_CRYPTO = HAVE_WHEEL or bool(os.environ.get("TM_TPU_PUREPY_CRYPTO"))

needs_crypto = pytest.mark.skipif(
    not HAVE_CRYPTO, reason="no ed25519 implementation available"
)
# the device-kernel tests cold-compile a large XLA program (~25s/shape on
# one CPU core); run them where the full image (OpenSSL wheel) is present
# or when explicitly requested alongside the pure-Python fallback
needs_wheel = pytest.mark.skipif(
    not (HAVE_WHEEL or os.environ.get("TM_TPU_RUN_KERNEL_TESTS")),
    reason="cryptography (OpenSSL wheel) not installed",
)

from tendermint_tpu.libs.metrics import (
    ConsensusMetrics,
    Counter,
    Gauge,
    Histogram,
    MempoolMetrics,
    MetricsServer,
    OpsMetrics,
    P2PMetrics,
    Registry,
    ops_stats,
)
from tendermint_tpu.observability import trace as tr


@pytest.fixture(autouse=True)
def _reset_tracer():
    """Each test starts with a clean, disabled tracer."""
    tr.configure(enabled=False)
    tr.TRACER.clear()
    yield
    tr.configure(enabled=False)
    tr.TRACER.clear()


# ---------------------------------------------------------------------------
# Exposition format
# ---------------------------------------------------------------------------


class TestExpositionFormat:
    def test_help_type_ordering(self):
        reg = Registry("tm")
        c = reg.counter("sub", "events_total", "Events.")
        c.inc(3)
        g = reg.gauge("sub", "depth", "Depth.")
        g.set(2)
        text = reg.expose()
        lines = text.strip().splitlines()
        # every family: HELP line, then TYPE line, then samples
        i = lines.index("# HELP tm_sub_events_total Events.")
        assert lines[i + 1] == "# TYPE tm_sub_events_total counter"
        assert lines[i + 2] == "tm_sub_events_total 3.0"
        j = lines.index("# HELP tm_sub_depth Depth.")
        assert lines[j + 1] == "# TYPE tm_sub_depth gauge"
        assert lines[j + 2] == "tm_sub_depth 2"
        assert text.endswith("\n")

    def test_label_escaping(self):
        c = Counter("c_total")
        c.inc(1, msg='say "hi"\nback\\slash')
        line = [ln for ln in c.expose() if not ln.startswith("#")][0]
        assert line == 'c_total{msg="say \\"hi\\"\\nback\\\\slash"} 1.0'

    def test_counter_labels_sorted_deterministic(self):
        c = Counter("x_total")
        c.inc(1, b="2", a="1")
        c.inc(1, a="1", b="2")
        lines = [ln for ln in c.expose() if not ln.startswith("#")]
        assert lines == ['x_total{a="1",b="2"} 2.0']

    def test_histogram_cumulative_invariant_unlabeled(self):
        h = Histogram("h", buckets=[0.1, 1, 10])
        for v in (0.05, 0.5, 5.0, 50.0):
            h.observe(v)
        lines = h.expose()
        buckets = [ln for ln in lines if ln.startswith("h_bucket")]
        counts = [float(ln.rsplit(" ", 1)[1]) for ln in buckets]
        assert counts == sorted(counts), "buckets must be cumulative"
        assert buckets[-1] == 'h_bucket{le="+Inf"} 4'
        assert "h_sum 55.55" in lines
        assert "h_count 4" in lines

    def test_histogram_label_support(self):
        """The satellite fix: OpsMetrics-style bucket="10240" labels merge
        with the cumulative le label and keep one HELP/TYPE header."""
        h = Histogram("hp_seconds", "Prep.", buckets=[0.01, 0.1], labeled=True)
        h.observe(0.005, bucket="128")
        h.observe(0.05, bucket="128")
        h.observe(0.5, bucket="10240")
        lines = h.expose()
        assert lines.count("# HELP hp_seconds Prep.") == 1
        assert lines.count("# TYPE hp_seconds histogram") == 1
        assert 'hp_seconds_bucket{bucket="128",le="0.01"} 1' in lines
        assert 'hp_seconds_bucket{bucket="128",le="0.1"} 2' in lines
        assert 'hp_seconds_bucket{bucket="128",le="+Inf"} 2' in lines
        assert 'hp_seconds_bucket{bucket="10240",le="+Inf"} 1' in lines
        assert 'hp_seconds_sum{bucket="128"} 0.055' in lines
        assert 'hp_seconds_count{bucket="10240"} 1' in lines
        # per-labelset cumulative invariant
        for label in ("128", "10240"):
            seq = [
                float(ln.rsplit(" ", 1)[1])
                for ln in lines
                if ln.startswith(f'hp_seconds_bucket{{bucket="{label}"')
            ]
            assert seq == sorted(seq)

    def test_unobserved_unlabeled_histogram_exposes_zeroes(self):
        h = Histogram("empty_h", buckets=[1])
        lines = h.expose()
        assert 'empty_h_bucket{le="+Inf"} 0' in lines
        assert "empty_h_count 0" in lines

    def test_metric_set_constructors(self):
        reg = Registry("tendermint")
        ConsensusMetrics(reg)
        MempoolMetrics(reg)
        P2PMetrics(reg)
        OpsMetrics(reg)
        text = reg.expose()
        for fam in (
            "tendermint_consensus_height",
            "tendermint_consensus_block_interval_seconds",
            "tendermint_mempool_size",
            "tendermint_p2p_peers",
            "tendermint_ops_sigs_verified_total",
            "tendermint_ops_host_prep_seconds",
            "tendermint_ops_device_seconds",
            "tendermint_ops_pad_waste_ratio",
        ):
            assert f"# TYPE {fam}" in text, fam


class TestScrapeEndpoint:
    def test_http_scrape_end_to_end(self):
        reg = Registry("tm")
        c = reg.counter("rpc", "requests_total", "Requests.")
        c.inc(7, method="status")
        reg2 = Registry("tm2")
        reg2.gauge("x", "y", "Y.").set(1)
        srv = MetricsServer([reg, reg2], "tcp://127.0.0.1:0")
        srv.start()
        try:
            with urllib.request.urlopen(
                f"http://{srv.listen_addr}/metrics", timeout=5
            ) as resp:
                assert resp.status == 200
                assert resp.headers["Content-Type"].startswith("text/plain")
                body = resp.read().decode()
            assert 'tm_rpc_requests_total{method="status"} 7.0' in body
            assert "tm2_x_y 1" in body  # both registries served
        finally:
            srv.stop()

    def test_collect_hook_runs_at_scrape(self):
        reg = Registry("tm")
        g = reg.gauge("mempool", "size", "Size.")
        state = {"n": 0}
        reg.add_collect_hook(lambda: g.set(state["n"]))
        state["n"] = 42
        assert "tm_mempool_size 42" in reg.expose()


# ---------------------------------------------------------------------------
# Tracer
# ---------------------------------------------------------------------------


class TestTracer:
    def test_disabled_records_nothing(self):
        with tr.span("x", a=1):
            pass
        assert tr.TRACER.events() == []
        assert tr.TRACER.recorded_total == 0

    def test_nesting_containment(self):
        tr.configure(enabled=True)
        with tr.span("parent"):
            with tr.span("child"):
                time.sleep(0.002)
        evs = {name: (s, e) for name, s, e, _, _ in tr.TRACER.events()}
        ps, pe = evs["parent"]
        cs, ce = evs["child"]
        assert ps <= cs and ce <= pe, "child span must nest inside parent"

    def test_ring_buffer_wraparound(self):
        tr.TRACER.configure(capacity=16)
        try:
            tr.configure(enabled=True)
            for i in range(40):
                tr.TRACER.record(f"s{i}", 0.0, 1.0)
            evs = tr.TRACER.events()
            assert len(evs) == 16
            assert [e[0] for e in evs] == [f"s{i}" for i in range(24, 40)]
            assert tr.TRACER.recorded_total == 40
        finally:
            tr.TRACER.configure(capacity=16384)

    def test_chrome_export_valid_json(self, tmp_path):
        tr.configure(enabled=True)
        with tr.span("outer", bucket=128):
            with tr.span("inner"):
                pass
        doc = tr.TRACER.export_chrome()
        rt = json.loads(json.dumps(doc))  # JSON-serializable round trip
        assert rt["displayTimeUnit"] == "ms"
        evs = rt["traceEvents"]
        assert len(evs) == 2
        for ev in evs:
            assert ev["ph"] == "X"
            assert set(ev) >= {"name", "ts", "dur", "pid", "tid"}
            assert ev["dur"] >= 0
        assert [e["ts"] for e in evs] == sorted(e["ts"] for e in evs)
        assert {"outer", "inner"} == {e["name"] for e in evs}
        outer = next(e for e in evs if e["name"] == "outer")
        assert outer["args"] == {"bucket": 128}
        # dump() writes the same doc to disk
        path = tr.TRACER.dump(str(tmp_path / "trace.json"))
        assert json.load(open(path)) == doc

    def test_summary_percentiles_and_device_utilization(self):
        doc = {
            "traceEvents": [
                {"name": "host_prep", "ph": "X", "ts": 0.0, "dur": 100.0},
                {"name": "device_wait", "ph": "X", "ts": 100.0, "dur": 850.0},
                # overlapping device span must not double-count
                {"name": "device_wait", "ph": "X", "ts": 500.0, "dur": 450.0},
            ]
        }
        s = tr.summarize_events(doc)
        assert s["host_prep"]["count"] == 1
        assert s["device_wait"]["count"] == 2
        assert s["device_wait"]["p50_ms"] == pytest.approx(0.65)
        wall = s["_wall"]
        assert wall["wall_ms"] == pytest.approx(0.95)
        assert wall["device_utilization"] == pytest.approx(850 / 950)

    def test_trace_report_cli(self, tmp_path, capsys):
        import sys

        sys.path.insert(
            0, os.path.join(os.path.dirname(__file__), "..", "tools")
        )
        try:
            import trace_report
        finally:
            sys.path.pop(0)
        tr.configure(enabled=True)
        for _ in range(5):
            with tr.span("ops.host_prep"):
                pass
            with tr.span("ops.device_wait"):
                time.sleep(0.001)
        path = tr.TRACER.dump(str(tmp_path / "t.json"))
        assert trace_report.main([path]) == 0
        out = capsys.readouterr().out
        assert "ops.host_prep" in out and "ops.device_wait" in out
        assert "device utilization" in out
        assert trace_report.main([path, "--json"]) == 0
        doc = json.loads(capsys.readouterr().out)
        assert doc["ops.device_wait"]["count"] == 5


# ---------------------------------------------------------------------------
# Hot-path coverage + overhead
# ---------------------------------------------------------------------------


def _entries(n, tamper=()):
    from tendermint_tpu.crypto import ed25519

    out = []
    for i in range(n):
        sk = ed25519.gen_priv_key(i.to_bytes(32, "little"))
        msg = b"obs-%d" % i
        sig = sk.sign(msg)
        if i in tamper:
            sig = sig[:-1] + bytes([sig[-1] ^ 1])
        out.append((sk.pub_key().bytes(), msg, sig))
    return out


@needs_crypto
class TestHotPathInstrumentation:
    @needs_wheel
    def test_verify_batch_records_spans_and_metrics(self, monkeypatch):
        from tendermint_tpu.libs import metrics as m
        from tendermint_tpu.ops import backend

        monkeypatch.setenv("TM_TPU_PALLAS", "0")
        backend._use_pallas.cache_clear()
        try:
            tr.configure(enabled=True)
            before = m.ops_metrics().sigs_verified.value(path="device")
            res = backend.verify_batch(_entries(8))
            assert res.all()
            assert (
                m.ops_metrics().sigs_verified.value(path="device") == before + 8
            )
            names = {e[0] for e in tr.TRACER.events()}
            assert "ops.host_prep" in names
            assert "ops.device_dispatch" in names
            assert "ops.device_wait" in names
            stats = ops_stats()
            assert stats["sigs_verified_device"] >= 8
            assert "128" in stats["batches_by_bucket"]
            assert 0.0 <= stats["pad_waste_ratio"] <= 1.0
        finally:
            backend._use_pallas.cache_clear()

    @needs_wheel
    def test_span_coverage_of_verify_wall_clock(self, monkeypatch):
        """Acceptance shape: host prep + dispatch + device wait sub-spans
        account for >= 90% of the measured verify_batch wall clock."""
        from tendermint_tpu.ops import backend

        monkeypatch.setenv("TM_TPU_PALLAS", "0")
        backend._use_pallas.cache_clear()
        try:
            entries = _entries(64)
            backend.verify_batch(entries)  # warm: compile outside the trace
            tr.TRACER.clear()
            tr.configure(enabled=True)
            t0 = time.perf_counter()
            with tr.span("wall"):
                backend.verify_batch(entries)
            wall = time.perf_counter() - t0
            parts = sum(
                e - s
                for name, s, e, _, _ in tr.TRACER.events()
                if name in ("ops.host_prep", "ops.device_dispatch",
                            "ops.device_wait")
            )
            assert parts >= 0.90 * wall, (parts, wall)
        finally:
            backend._use_pallas.cache_clear()

    def test_host_fallback_counter(self):
        from tendermint_tpu.crypto import ed25519
        from tendermint_tpu.libs import metrics as m
        from tendermint_tpu.ops.backend import Ed25519DeviceBatchVerifier

        before = m.ops_metrics().host_fallback.total()
        bv = Ed25519DeviceBatchVerifier()
        sk = ed25519.gen_priv_key(b"\x01" * 32)
        bv.add(sk.pub_key(), b"m", sk.sign(b"m"))
        ok, valid = bv.verify()  # 1 < DEVICE_THRESHOLD -> host path
        assert ok and valid == [True]
        assert m.ops_metrics().host_fallback.total() == before + 1

    @needs_wheel
    def test_tracing_disabled_overhead_guard(self, monkeypatch):
        """Tracing off must cost ~nothing on verify_batch: the per-call
        instrument overhead (the ~10 null-span entries a verify_batch
        dispatch walks through) must be < 2% of the measured verify_batch
        wall clock. Extended over flow-event sites (ISSUE 10): a span
        carrying flow kwargs and a flow_point both take the same
        single-attribute-check disabled path."""
        from tendermint_tpu.ops import backend

        monkeypatch.setenv("TM_TPU_PALLAS", "0")
        backend._use_pallas.cache_clear()
        try:
            assert not tr.TRACER.enabled
            entries = _entries(64)
            backend.verify_batch(entries)  # warm compile
            t0 = time.perf_counter()
            for _ in range(3):
                backend.verify_batch(entries)
            verify_s = (time.perf_counter() - t0) / 3

            n_ops = 10_000
            t0 = time.perf_counter()
            for _ in range(n_ops):
                with tr.span("x", n=64, bucket=128):
                    pass
                with tr.span("y", flow=123, flow_phase="t", bucket=128):
                    pass
                tr.TRACER.flow_point("z", 123, "s", n=64)
            per_span = (time.perf_counter() - t0) / (3 * n_ops)
            # ~10 instrument sites fire per verify_batch dispatch
            assert per_span * 10 < 0.02 * verify_s, (per_span, verify_s)
        finally:
            backend._use_pallas.cache_clear()

    @needs_wheel
    def test_pipeline_records_metrics(self):
        from tendermint_tpu.libs import metrics as m
        from tendermint_tpu.ops.pipeline import AsyncBatchVerifier

        v = AsyncBatchVerifier(depth=2)
        try:
            before = m.ops_metrics().pipeline_coalesced_jobs.total()
            res = v.submit(_entries(6)).result(timeout=120)
            assert res.all()
            assert m.ops_metrics().pipeline_coalesced_jobs.total() > before
        finally:
            v.close()


# ---------------------------------------------------------------------------
# Node integration
# ---------------------------------------------------------------------------


@needs_crypto
class TestNodeIntegration:
    def _single_node(self, tmp_path=None, **instr):
        from tendermint_tpu.abci import KVStoreApplication
        from tendermint_tpu.crypto import ed25519
        from tendermint_tpu.node import make_node
        from tendermint_tpu.p2p import NodeKey
        from tendermint_tpu.privval import FilePV
        from tendermint_tpu.types import Timestamp
        from tendermint_tpu.types.genesis import GenesisDoc, GenesisValidator
        from tests.test_consensus import FAST
        from tendermint_tpu.config import Config

        sk = ed25519.gen_priv_key(bytes([9]) * 32)
        doc = GenesisDoc(
            chain_id="obs-chain",
            genesis_time=Timestamp(seconds=1_700_000_000),
            validators=[
                GenesisValidator(address=b"", pub_key=sk.pub_key(), power=10)
            ],
        )
        cfg = Config()
        cfg.base.home = str(tmp_path) if tmp_path else ""
        cfg.base.db_backend = "memdb"
        cfg.consensus = FAST
        cfg.p2p.laddr = "none"
        cfg.rpc.laddr = "tcp://127.0.0.1:0"
        cfg.instrumentation.prometheus = True
        cfg.instrumentation.prometheus_listen_addr = "tcp://127.0.0.1:0"
        for k, val in instr.items():
            setattr(cfg.instrumentation, k, val)
        if tmp_path:
            cfg.ensure_dirs()
        node = make_node(
            cfg,
            app=KVStoreApplication(),
            genesis=doc,
            priv_validator=FilePV(sk),
            node_key=NodeKey.generate(bytes([88]) * 32),
            with_rpc=True,
        )
        return node

    def test_metrics_server_and_rpc_introspection(self):
        node = self._single_node(tracing=True)
        node.start()
        try:
            node.wait_for_height(2, timeout=60)
            node.mempool.check_tx(b"obs=1")
            # -- /metrics scrape: consensus + ops + mempool series -------
            with urllib.request.urlopen(
                f"http://{node.metrics_server.listen_addr}/metrics", timeout=5
            ) as resp:
                body = resp.read().decode()
            m = re.search(r"^tendermint_consensus_height (\d+)", body, re.M)
            assert m and int(m.group(1)) >= 2
            assert "# TYPE tendermint_ops_sigs_verified_total counter" in body
            assert re.search(r"^tendermint_mempool_size \d", body, re.M)
            assert "tendermint_consensus_block_interval_seconds_bucket" in body
            assert re.search(r"^tendermint_consensus_validators 1", body, re.M)
            # -- RPC: /status verify_engine + /dump_trace ----------------
            from tendermint_tpu.rpc import HTTPClient

            rpc = HTTPClient(node.rpc_server.listen_addr)
            st = rpc.status()
            ve = st["verify_engine"]
            assert ve["tracing"] is True
            assert ve["sigs_verified_host"] + ve["sigs_verified_device"] > 0
            dt = rpc.call("dump_trace")
            assert dt["enabled"] is True
            assert dt["trace"]["traceEvents"], "commit verifies must trace"
            json.dumps(dt["trace"])  # valid JSON document
            names = {e["name"] for e in dt["trace"]["traceEvents"]}
            assert "verify_commit" in names
            summ = rpc.call("dump_trace", summary=True)
            assert "trace" not in summ and "verify_commit" in summ["summary"]
        finally:
            node.stop()
            tr.configure(enabled=False)

    def test_stop_flushes_complete_trace_file(self, tmp_path):
        node = self._single_node(
            tmp_path, tracing=True, trace_dump_path="data/trace.json"
        )
        node.start()
        try:
            node.wait_for_height(1, timeout=60)
        finally:
            node.stop()
            tr.configure(enabled=False)
        path = tmp_path / "data" / "trace.json"
        assert path.exists()
        doc = json.load(open(path))
        assert doc["traceEvents"], "flushed trace must carry the run's spans"
