"""P2P stack: secret connection, mconnection multiplexing, router over
memory and TCP transports, and a 4-validator TCP localnet committing
blocks through the consensus reactor (SURVEY.md §7 stage 5)."""

import queue
import socket
import threading
import time

import pytest

from tendermint_tpu.crypto import ed25519
from tendermint_tpu.p2p import (
    ChannelDescriptor,
    MConnTransport,
    NodeKey,
    PeerAddress,
    PeerManager,
    Router,
    SecretConnection,
    new_memory_network,
    MemoryTransport,
)
from tendermint_tpu.p2p.key import node_id_from_pubkey


def _sock_pair():
    a, b = socket.socketpair()

    class S:
        def __init__(self, s):
            self._s = s

        def read(self, n):
            try:
                return self._s.recv(n)
            except OSError:
                return b""

        def write(self, data):
            self._s.sendall(data)

        def close(self):
            self._s.close()

    return S(a), S(b)


class TestSecretConnection:
    def test_handshake_and_transfer(self):
        ka = ed25519.gen_priv_key(bytes([1]) * 32)
        kb = ed25519.gen_priv_key(bytes([2]) * 32)
        sa, sb = _sock_pair()
        out = {}

        def server():
            out["b"] = SecretConnection(sb, kb)

        t = threading.Thread(target=server)
        t.start()
        ca = SecretConnection(sa, ka)
        t.join(timeout=5)
        cb = out["b"]
        assert ca.remote_pubkey.bytes() == kb.pub_key().bytes()
        assert cb.remote_pubkey.bytes() == ka.pub_key().bytes()
        # data both ways, > 1 frame
        payload = b"x" * 3000
        ca.write(payload)
        got = b""
        while len(got) < 3000:
            got += cb.read_frame()
        assert got == payload
        cb.write(b"pong")
        assert ca.read_frame() == b"pong"

    def test_tampered_frame_rejected(self):
        ka = ed25519.gen_priv_key(bytes([3]) * 32)
        kb = ed25519.gen_priv_key(bytes([4]) * 32)
        sa, sb = _sock_pair()
        out = {}
        t = threading.Thread(target=lambda: out.update(b=SecretConnection(sb, kb)))
        t.start()
        ca = SecretConnection(sa, ka)
        t.join(timeout=5)
        # write garbage directly to the underlying socket
        sa.write(b"\x00" * 1044)
        with pytest.raises(Exception):
            out["b"].read_frame()


class TestRouterMemory:
    def test_two_node_channel_roundtrip(self):
        hub = new_memory_network()
        keys = [NodeKey.generate(bytes([i + 1]) * 32) for i in range(2)]
        ids = [k.node_id for k in keys]
        desc = ChannelDescriptor(id=7)
        routers = []
        chans = []
        for i in range(2):
            t = MemoryTransport(hub, ids[i], keys[i].pub_key)
            pm = PeerManager(ids[i])
            r = Router(t, pm, ids[i])
            chans.append(r.open_channel(desc))
            routers.append(r)
            r.start()
        # node0 dials node1 (memory transport addresses are node ids)
        routers[0]._pm.add_address(PeerAddress(ids[1], ids[1]))
        deadline = time.time() + 5
        while time.time() < deadline and not routers[0].connected():
            time.sleep(0.05)
        assert ids[1] in routers[0].connected()
        chans[0].send(ids[1], b"hello")
        env = chans[1].receive(timeout=5)
        assert env.message == b"hello" and env.from_id == ids[0]
        chans[1].broadcast(b"reply")
        env2 = chans[0].receive(timeout=5)
        assert env2.message == b"reply"
        for r in routers:
            r.stop()


class TestRouterTCP:
    def test_tcp_transport_router(self):
        keys = [NodeKey.generate(bytes([i + 10]) * 32) for i in range(2)]
        ids = [k.node_id for k in keys]
        desc = ChannelDescriptor(id=9)
        transports = [MConnTransport(k.priv_key, [desc]) for k in keys]
        for t in transports:
            t.listen("127.0.0.1:0")
        routers, chans = [], []
        for i in range(2):
            pm = PeerManager(ids[i])
            r = Router(transports[i], pm, ids[i])
            chans.append(r.open_channel(desc))
            routers.append(r)
            r.start()
        routers[0]._pm.add_address(PeerAddress(ids[1], transports[1].listen_addr))
        deadline = time.time() + 10
        while time.time() < deadline and not routers[0].connected():
            time.sleep(0.05)
        assert ids[1] in routers[0].connected()
        big = bytes(range(256)) * 40  # > 1 mconn packet
        chans[0].send(ids[1], big)
        env = chans[1].receive(timeout=5)
        assert env.message == big
        for r in routers:
            r.stop()


class TestConsensusOverTCP:
    def test_four_validator_tcp_localnet(self):
        from tests.test_consensus import FAST, make_node
        from tendermint_tpu.consensus.reactor import ALL_DESCS, ConsensusReactor

        sks = [ed25519.gen_priv_key(bytes([i + 1]) * 32) for i in range(4)]
        node_keys = [NodeKey.generate(bytes([i + 50]) * 32) for i in range(4)]
        nodes, stores, routers, reactors = [], [], [], []
        transports = []
        for i in range(4):
            cs, bstore, _ = make_node(sks, i)
            t = MConnTransport(node_keys[i].priv_key, ALL_DESCS)
            t.listen("127.0.0.1:0")
            pm = PeerManager(node_keys[i].node_id)
            r = Router(t, pm, node_keys[i].node_id)
            reactor = ConsensusReactor(cs, r)
            nodes.append(cs)
            stores.append(bstore)
            routers.append(r)
            reactors.append(reactor)
            transports.append(t)
        # full mesh
        for i in range(4):
            for j in range(4):
                if i != j:
                    routers[i]._pm.add_address(
                        PeerAddress(node_keys[j].node_id, transports[j].listen_addr)
                    )
        for r in routers:
            r.start()
        for re in reactors:
            re.start()
        # wait for connectivity
        deadline = time.time() + 10
        while time.time() < deadline and any(len(r.connected()) < 3 for r in routers):
            time.sleep(0.1)
        for n in nodes:
            n.start()
        try:
            for n in nodes:
                n.wait_for_height(2, timeout=90)
        finally:
            for n in nodes:
                n.stop()
            for re in reactors:
                re.stop()
            for r in routers:
                r.stop()
        hashes = [s.load_block(2).hash() for s in stores]
        assert all(h == hashes[0] for h in hashes), "nodes diverged over TCP"


class TestPeerLifecycle:
    """peermanager.go:27-60 eviction/upgrade machinery + pqueue.go
    priority routing + flowrate limiting."""

    def test_errored_peer_evicted_and_banned(self):
        from tendermint_tpu.p2p.peermanager import EVICT_SCORE

        pm = PeerManager("self", ban_duration=5.0)
        pm.add_address(PeerAddress("bad", "bad"))
        assert pm.accepted("bad")
        for _ in range(-EVICT_SCORE):
            pm.errored("bad", ValueError("garbage"))
        assert pm.evict_next() == "bad"
        pm.disconnected("bad")
        # banned: neither dialable nor re-admittable until the ban lapses
        assert pm.is_banned("bad")
        assert pm.dial_next() is None
        assert not pm.accepted("bad")

    def test_upgrade_displaces_worst_peer(self):
        pm = PeerManager("self", max_connected=2)
        assert pm.accepted("a") and pm.accepted("b")
        # "a" misbehaves a little (score -2, above eviction threshold)
        pm.errored("a", ValueError("x"), weight=2)
        # a better candidate arrives while full: admitted, "a" queued
        assert pm.accepted("c")
        assert sorted(pm.connected_peers()) == ["a", "b", "c"]
        assert pm.evict_next() == "a"

    def test_persistent_peer_never_evicted(self):
        pm = PeerManager("self")
        pm.add_address(PeerAddress("p", "p"), persistent=True)
        assert pm.accepted("p")
        for _ in range(50):
            pm.errored("p", ValueError("x"))
        assert pm.evict_next() is None

    def test_address_book_gc(self):
        pm = PeerManager("self", max_peers=10)
        for i in range(15):
            pm.add_address(PeerAddress(f"n{i}", f"n{i}"))
        assert pm.prune_addresses() == 5
        assert len(pm.peers()) == 10

    def test_router_evicts_garbage_peer_and_gossip_stays_flat(self):
        """A peer that misbehaves repeatedly is dropped by the router's
        eviction pump while a healthy peer's high-priority traffic keeps
        flowing."""
        from tendermint_tpu.p2p.peermanager import EVICT_SCORE

        hub = new_memory_network()
        keys = [NodeKey.generate(bytes([i + 41]) * 32) for i in range(3)]
        ids = [k.node_id for k in keys]
        hi = ChannelDescriptor(id=0x22, priority=6)  # vote gossip
        routers, chans = [], []
        for i in range(3):
            t = MemoryTransport(hub, ids[i], keys[i].pub_key)
            pm = PeerManager(ids[i])
            r = Router(t, pm, ids[i])
            chans.append(r.open_channel(hi))
            routers.append(r)
            r.start()
        routers[0]._pm.add_address(PeerAddress(ids[1], ids[1]))
        routers[0]._pm.add_address(PeerAddress(ids[2], ids[2]))
        deadline = time.time() + 5
        while time.time() < deadline and len(routers[0].connected()) < 2:
            time.sleep(0.05)
        assert len(routers[0].connected()) == 2
        # peer 2 keeps sending garbage -> errored until eviction
        for _ in range(-EVICT_SCORE + 2):
            routers[0]._pm.errored(ids[2], ValueError("garbage"))
        deadline = time.time() + 5
        while time.time() < deadline and ids[2] in routers[0].connected():
            time.sleep(0.05)
        assert ids[2] not in routers[0].connected()
        # healthy peer still delivers promptly
        t0 = time.time()
        chans[0].send(ids[1], b"vote")
        env = chans[1].receive(timeout=5)
        assert env.message == b"vote" and time.time() - t0 < 1.0
        for r in routers:
            r.stop()

    def test_priority_channel_wins_per_peer_queue(self):
        """pqueue semantics: with a peer's low-priority queue stuffed, a
        high-priority message still goes out ahead of the backlog."""
        from tendermint_tpu.p2p.router import _PeerQueue

        lo = ChannelDescriptor(id=0x40, priority=1, send_queue_capacity=50)
        hi = ChannelDescriptor(id=0x22, priority=6, send_queue_capacity=50)
        pq = _PeerQueue({lo.id: lo, hi.id: hi})
        for i in range(50):
            assert pq.put(lo.id, b"bulk%d" % i)
        assert not pq.put(lo.id, b"overflow")  # bounded: drops, not blocks
        assert pq.dropped == 1
        assert pq.put(hi.id, b"vote")
        ch, msg = pq.pop(timeout=1)
        assert ch == hi.id and msg == b"vote"  # vote jumps the bulk backlog
        ch, _ = pq.pop(timeout=1)
        assert ch == lo.id

    def test_flowrate_limited_connection(self):
        """flowrate cap: pushing ~30 kB through a 50 kB/s-limited
        MConnection takes >= ~0.4s and the monitor sees the rate."""
        import socket as _socket

        from tendermint_tpu.p2p.conn.mconnection import MConnection
        from tendermint_tpu.p2p.transport import _SockStream

        a, b = _socket.socketpair()
        got = []
        done = threading.Event()

        def on_recv(ch, msg):
            got.append(msg)
            if len(got) == 30:
                done.set()

        descs = [ChannelDescriptor(id=1, send_queue_capacity=64)]
        ma = MConnection(_SockStream(a), descs, lambda c, m: None,
                         lambda e: None, send_rate=50_000)
        mb = MConnection(_SockStream(b), descs, on_recv, lambda e: None)
        ma.start()
        mb.start()
        t0 = time.time()
        for i in range(30):
            assert ma.send(1, bytes(1000))
        # generous deadline: nominal is ~0.6s, but a loaded CI host can
        # starve the writer thread well past 10s (observed full-suite flake)
        assert done.wait(30)
        dt = time.time() - t0
        assert dt >= 0.35, f"30kB at 50kB/s finished too fast: {dt:.2f}s"
        assert ma.send_monitor.total() >= 30_000
        ma.stop()
        mb.stop()


class TestConnTracker:
    """internal/p2p/conn_tracker.go: per-IP inbound connection caps."""

    def test_per_ip_cap(self):
        from tendermint_tpu.p2p.transport import ConnTracker

        t = ConnTracker(max_per_ip=2)
        assert t.add("10.0.0.1") and t.add("10.0.0.1")
        assert not t.add("10.0.0.1")  # cap
        assert t.add("10.0.0.2")  # a different IP is unaffected
        t.remove("10.0.0.1")
        assert t.add("10.0.0.1")
        assert t.count("10.0.0.1") == 2

    def test_tcp_transport_enforces_cap(self):
        import socket as _socket
        import time as _time

        from tendermint_tpu.p2p import NodeKey
        from tendermint_tpu.p2p.transport import MConnTransport

        nk = NodeKey.generate(bytes([61]) * 32)
        t = MConnTransport(nk.priv_key, [ChannelDescriptor(id=1)],
                           max_conns_per_ip=1)
        t.listen("127.0.0.1:0")
        host, _, port = t.listen_addr.rpartition(":")
        # first raw connection occupies the slot (no handshake completes,
        # but the tracker slot is held while the handshake thread runs)
        s1 = _socket.create_connection((host, int(port)))
        _time.sleep(0.3)
        # second connection from the same IP must be closed by the cap
        s2 = _socket.create_connection((host, int(port)))
        s2.settimeout(2)
        try:
            data = s2.recv(1)
            assert data == b"", "expected immediate close by conn tracker"
        except (ConnectionResetError, _socket.timeout):
            pass  # reset also acceptable
        finally:
            s1.close()
            s2.close()
            t.close()
