"""P2P stack: secret connection, mconnection multiplexing, router over
memory and TCP transports, and a 4-validator TCP localnet committing
blocks through the consensus reactor (SURVEY.md §7 stage 5)."""

import queue
import socket
import threading
import time

import pytest

from tendermint_tpu.crypto import ed25519
from tendermint_tpu.p2p import (
    ChannelDescriptor,
    MConnTransport,
    NodeKey,
    PeerAddress,
    PeerManager,
    Router,
    SecretConnection,
    new_memory_network,
    MemoryTransport,
)
from tendermint_tpu.p2p.key import node_id_from_pubkey


def _sock_pair():
    a, b = socket.socketpair()

    class S:
        def __init__(self, s):
            self._s = s

        def read(self, n):
            try:
                return self._s.recv(n)
            except OSError:
                return b""

        def write(self, data):
            self._s.sendall(data)

        def close(self):
            self._s.close()

    return S(a), S(b)


class TestSecretConnection:
    def test_handshake_and_transfer(self):
        ka = ed25519.gen_priv_key(bytes([1]) * 32)
        kb = ed25519.gen_priv_key(bytes([2]) * 32)
        sa, sb = _sock_pair()
        out = {}

        def server():
            out["b"] = SecretConnection(sb, kb)

        t = threading.Thread(target=server)
        t.start()
        ca = SecretConnection(sa, ka)
        t.join(timeout=5)
        cb = out["b"]
        assert ca.remote_pubkey.bytes() == kb.pub_key().bytes()
        assert cb.remote_pubkey.bytes() == ka.pub_key().bytes()
        # data both ways, > 1 frame
        payload = b"x" * 3000
        ca.write(payload)
        got = b""
        while len(got) < 3000:
            got += cb.read_frame()
        assert got == payload
        cb.write(b"pong")
        assert ca.read_frame() == b"pong"

    def test_tampered_frame_rejected(self):
        ka = ed25519.gen_priv_key(bytes([3]) * 32)
        kb = ed25519.gen_priv_key(bytes([4]) * 32)
        sa, sb = _sock_pair()
        out = {}
        t = threading.Thread(target=lambda: out.update(b=SecretConnection(sb, kb)))
        t.start()
        ca = SecretConnection(sa, ka)
        t.join(timeout=5)
        # write garbage directly to the underlying socket
        sa.write(b"\x00" * 1044)
        with pytest.raises(Exception):
            out["b"].read_frame()


class TestRouterMemory:
    def test_two_node_channel_roundtrip(self):
        hub = new_memory_network()
        keys = [NodeKey.generate(bytes([i + 1]) * 32) for i in range(2)]
        ids = [k.node_id for k in keys]
        desc = ChannelDescriptor(id=7)
        routers = []
        chans = []
        for i in range(2):
            t = MemoryTransport(hub, ids[i], keys[i].pub_key)
            pm = PeerManager(ids[i])
            r = Router(t, pm, ids[i])
            chans.append(r.open_channel(desc))
            routers.append(r)
            r.start()
        # node0 dials node1 (memory transport addresses are node ids)
        routers[0]._pm.add_address(PeerAddress(ids[1], ids[1]))
        deadline = time.time() + 5
        while time.time() < deadline and not routers[0].connected():
            time.sleep(0.05)
        assert ids[1] in routers[0].connected()
        chans[0].send(ids[1], b"hello")
        env = chans[1].receive(timeout=5)
        assert env.message == b"hello" and env.from_id == ids[0]
        chans[1].broadcast(b"reply")
        env2 = chans[0].receive(timeout=5)
        assert env2.message == b"reply"
        for r in routers:
            r.stop()


class TestRouterTCP:
    def test_tcp_transport_router(self):
        keys = [NodeKey.generate(bytes([i + 10]) * 32) for i in range(2)]
        ids = [k.node_id for k in keys]
        desc = ChannelDescriptor(id=9)
        transports = [MConnTransport(k.priv_key, [desc]) for k in keys]
        for t in transports:
            t.listen("127.0.0.1:0")
        routers, chans = [], []
        for i in range(2):
            pm = PeerManager(ids[i])
            r = Router(transports[i], pm, ids[i])
            chans.append(r.open_channel(desc))
            routers.append(r)
            r.start()
        routers[0]._pm.add_address(PeerAddress(ids[1], transports[1].listen_addr))
        deadline = time.time() + 10
        while time.time() < deadline and not routers[0].connected():
            time.sleep(0.05)
        assert ids[1] in routers[0].connected()
        big = bytes(range(256)) * 40  # > 1 mconn packet
        chans[0].send(ids[1], big)
        env = chans[1].receive(timeout=5)
        assert env.message == big
        for r in routers:
            r.stop()


class TestConsensusOverTCP:
    def test_four_validator_tcp_localnet(self):
        from tests.test_consensus import FAST, make_node
        from tendermint_tpu.consensus.reactor import ALL_DESCS, ConsensusReactor

        sks = [ed25519.gen_priv_key(bytes([i + 1]) * 32) for i in range(4)]
        node_keys = [NodeKey.generate(bytes([i + 50]) * 32) for i in range(4)]
        nodes, stores, routers, reactors = [], [], [], []
        transports = []
        for i in range(4):
            cs, bstore, _ = make_node(sks, i)
            t = MConnTransport(node_keys[i].priv_key, ALL_DESCS)
            t.listen("127.0.0.1:0")
            pm = PeerManager(node_keys[i].node_id)
            r = Router(t, pm, node_keys[i].node_id)
            reactor = ConsensusReactor(cs, r)
            nodes.append(cs)
            stores.append(bstore)
            routers.append(r)
            reactors.append(reactor)
            transports.append(t)
        # full mesh
        for i in range(4):
            for j in range(4):
                if i != j:
                    routers[i]._pm.add_address(
                        PeerAddress(node_keys[j].node_id, transports[j].listen_addr)
                    )
        for r in routers:
            r.start()
        for re in reactors:
            re.start()
        # wait for connectivity
        deadline = time.time() + 10
        while time.time() < deadline and any(len(r.connected()) < 3 for r in routers):
            time.sleep(0.1)
        for n in nodes:
            n.start()
        try:
            for n in nodes:
                n.wait_for_height(2, timeout=90)
        finally:
            for n in nodes:
                n.stop()
            for re in reactors:
                re.stop()
            for r in routers:
                r.stop()
        hashes = [s.load_block(2).hash() for s in stores]
        assert all(h == hashes[0] for h in hashes), "nodes diverged over TCP"
