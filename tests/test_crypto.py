"""Crypto layer tests: ed25519 (incl. ZIP-215 edge cases), secp256k1,
merkle, tmhash. Differential oracle checks mirror the reference's
crypto/ed25519/ed25519_test.go and crypto/merkle/tree_test.go coverage."""

import hashlib

import pytest

from tendermint_tpu.crypto import _edwards, batch, ed25519, merkle, secp256k1, tmhash


class TestEdwardsOracle:
    def test_base_point_order(self):
        # [L]B == identity, [L-1]B != identity
        assert _edwards.is_identity(_edwards.scalar_mult(_edwards.L, _edwards.BASE))
        assert not _edwards.is_identity(
            _edwards.scalar_mult(_edwards.L - 1, _edwards.BASE)
        )

    def test_compress_roundtrip(self):
        for k in (1, 2, 7, 12345, _edwards.L - 1):
            pt = _edwards.scalar_mult(k, _edwards.BASE)
            enc = _edwards.compress(pt)
            back = _edwards.decompress(enc)
            assert back is not None
            assert _edwards.point_equal(pt, back)

    def test_pure_sign_matches_openssl(self):
        seed = bytes(range(32))
        sk = ed25519.gen_priv_key(seed)
        msg = b"tendermint-tpu"
        assert _edwards.sign(seed, msg) == sk.sign(msg)
        assert _edwards.pubkey_from_seed(seed) == sk.pub_key().bytes()

    def test_oracle_accepts_valid_rejects_forged(self):
        seed = hashlib.sha256(b"k1").digest()
        sk = ed25519.gen_priv_key(seed)
        pub = sk.pub_key().bytes()
        msg = b"a vote"
        sig = sk.sign(msg)
        assert _edwards.verify_zip215(pub, msg, sig)
        bad = bytearray(sig)
        bad[0] ^= 1
        assert not _edwards.verify_zip215(pub, msg, bytes(bad))
        assert not _edwards.verify_zip215(pub, b"other msg", sig)

    def test_rejects_noncanonical_s(self):
        seed = hashlib.sha256(b"k2").digest()
        sk = ed25519.gen_priv_key(seed)
        msg = b"m"
        sig = bytearray(sk.sign(msg))
        s = int.from_bytes(sig[32:], "little")
        sig[32:] = (s + _edwards.L).to_bytes(32, "little")
        assert not _edwards.verify_zip215(sk.pub_key().bytes(), msg, bytes(sig))

    def test_small_order_pubkey_accepted(self):
        # ZIP-215 accepts small-order A. Identity point pubkey: y=1, x=0.
        ident_enc = (1).to_bytes(32, "little")
        # With A = O, equation is [8]([s]B - R) == O; pick s=0, R=O.
        sig = ident_enc + (0).to_bytes(32, "little")
        assert _edwards.verify_zip215(ident_enc, b"anything", sig)

    def test_noncanonical_point_encoding_accepted(self):
        # y = p + 1 encodes the same point as y = 1 (identity) but
        # non-canonically; ZIP-215 accepts it, strict RFC8032 would not.
        nc = (_edwards.P + 1).to_bytes(32, "little")
        assert _edwards.decompress(nc) is not None
        assert _edwards.decompress(nc, allow_noncanonical=False) is None
        sig = (1).to_bytes(32, "little") + (0).to_bytes(32, "little")
        assert _edwards.verify_zip215(nc, b"x", sig)

    def test_negative_zero_encoding_accepted(self):
        # ZIP-215 follows dalek decompression: "x = 0 with sign bit 1" is NOT
        # rejected (conditional negate of 0 is a no-op). Strict RFC 8032 rejects.
        neg_ident = ((1) | (1 << 255)).to_bytes(32, "little")  # y=1, sign=1
        pt = _edwards.decompress(neg_ident)
        assert pt is not None and _edwards.is_identity(pt)
        assert _edwards.decompress(neg_ident, allow_noncanonical=False) is None
        # and it verifies as a small-order pubkey with s=0, R=O
        sig = (1).to_bytes(32, "little") + bytes(32)
        assert _edwards.verify_zip215(neg_ident, b"m", sig)

    def test_torsion_points_exist_and_verify_structure(self):
        # order-4 point: x = +-sqrt(-1), y = 0
        x = _edwards.SQRT_M1
        pt = (x, 0, 1, 0)
        p2 = _edwards.point_double(pt)
        p4 = _edwards.point_double(p2)
        assert not _edwards.is_identity(p2)
        assert _edwards.is_identity(p4)


class TestEd25519Keys:
    def test_sign_verify(self):
        sk = ed25519.gen_priv_key()
        msg = b"hello consensus"
        sig = sk.sign(msg)
        assert len(sig) == 64
        assert sk.pub_key().verify_signature(msg, sig)
        assert not sk.pub_key().verify_signature(msg + b"!", sig)
        assert not sk.pub_key().verify_signature(msg, sig[:-1])

    def test_address(self):
        sk = ed25519.gen_priv_key(bytes(32))
        addr = sk.pub_key().address()
        assert addr == hashlib.sha256(sk.pub_key().bytes()).digest()[:20]
        assert len(addr) == 20

    def test_privkey_format_seed_pub(self):
        seed = hashlib.sha256(b"fmt").digest()
        sk = ed25519.gen_priv_key(seed)
        raw = sk.bytes()
        assert len(raw) == 64
        assert raw[:32] == seed
        assert raw[32:] == sk.pub_key().bytes()

    def test_zip215_vs_openssl_divergence_handled(self):
        # small-order key rejected by OpenSSL but accepted by our ZIP-215 path
        ident_enc = (1).to_bytes(32, "little")
        sig = ident_enc + (0).to_bytes(32, "little")
        pk = ed25519.PubKey(ident_enc)
        assert pk.verify_signature(b"m", sig)


class TestSecp256k1:
    def test_sign_verify_lower_s(self):
        sk = secp256k1.gen_priv_key()
        msg = b"tx bytes"
        sig = sk.sign(msg)
        assert len(sig) == 64
        pk = sk.pub_key()
        assert pk.verify_signature(msg, sig)
        # flip to upper-S: must be rejected
        r = sig[:32]
        s = int.from_bytes(sig[32:], "big")
        upper = r + (secp256k1._N - s).to_bytes(32, "big")
        assert not pk.verify_signature(msg, upper)
        assert not pk.verify_signature(b"other", sig)

    def test_deterministic_rfc6979(self):
        sk = secp256k1.gen_priv_key()
        assert sk.sign(b"same msg") == sk.sign(b"same msg")
        assert sk.sign(b"same msg") != sk.sign(b"other msg")

    def test_address_is_ripemd160_sha256(self):
        sk = secp256k1.gen_priv_key()
        pk = sk.pub_key()
        expect = hashlib.new("ripemd160", hashlib.sha256(pk.bytes()).digest()).digest()
        assert pk.address() == expect
        assert len(pk.address()) == 20


class TestMerkle:
    def test_empty(self):
        assert merkle.hash_from_byte_slices([]) == hashlib.sha256(b"").digest()

    def test_single_leaf(self):
        assert merkle.hash_from_byte_slices([b"abc"]) == hashlib.sha256(
            b"\x00abc"
        ).digest()

    def test_rfc6962_structure(self):
        # two leaves: inner(leaf(a), leaf(b))
        la = hashlib.sha256(b"\x00a").digest()
        lb = hashlib.sha256(b"\x00b").digest()
        expect = hashlib.sha256(b"\x01" + la + lb).digest()
        assert merkle.hash_from_byte_slices([b"a", b"b"]) == expect

    @pytest.mark.parametrize("n", [1, 2, 3, 4, 5, 7, 8, 9, 33])
    def test_proofs_verify(self, n):
        items = [bytes([i]) * (i + 1) for i in range(n)]
        root, proofs = merkle.proofs_from_byte_slices(items)
        assert root == merkle.hash_from_byte_slices(items)
        for i, proof in enumerate(proofs):
            proof.verify(root, items[i])
            with pytest.raises(ValueError):
                proof.verify(root, b"wrong leaf")

    def test_proof_validate_basic(self):
        root, proofs = merkle.proofs_from_byte_slices([b"a", b"b"])
        p = proofs[0]
        bad = merkle.Proof(p.total, p.index, p.leaf_hash, [b"x" * 64])
        with pytest.raises(ValueError, match="aunt #0"):
            bad.verify(root, b"a")
        huge = merkle.Proof(p.total, p.index, p.leaf_hash, [b"\0" * 32] * 101)
        with pytest.raises(ValueError, match="no more than 100"):
            huge.verify(root, b"a")
        short_leaf = merkle.Proof(p.total, p.index, b"\0" * 20, p.aunts)
        with pytest.raises(ValueError, match="leaf_hash"):
            short_leaf.verify(root, b"a")

    def test_split_point(self):
        assert merkle.split_point(2) == 1
        assert merkle.split_point(3) == 2
        assert merkle.split_point(4) == 2
        assert merkle.split_point(5) == 4
        assert merkle.split_point(8) == 4
        assert merkle.split_point(9) == 8


class TestBatchDispatch:
    def test_supports(self):
        ed = ed25519.gen_priv_key().pub_key()
        sec = secp256k1.gen_priv_key().pub_key()
        assert batch.supports_batch_verifier(ed)
        assert not batch.supports_batch_verifier(sec)
        assert not batch.supports_batch_verifier(None)

    def test_host_batch_verifier(self):
        bv = batch.Ed25519HostBatchVerifier()
        keys = [ed25519.gen_priv_key() for _ in range(4)]
        msgs = [f"msg {i}".encode() for i in range(4)]
        for sk, m in zip(keys, msgs):
            bv.add(sk.pub_key(), m, sk.sign(m))
        ok, valid = bv.verify()
        assert ok and valid == [True] * 4

        bv2 = batch.Ed25519HostBatchVerifier()
        for i, (sk, m) in enumerate(zip(keys, msgs)):
            sig = sk.sign(m)
            if i == 2:
                sig = sig[:-1] + bytes([sig[-1] ^ 0xFF])
            bv2.add(sk.pub_key(), m, sig)
        ok, valid = bv2.verify()
        assert not ok
        assert valid == [True, True, False, True]


class TestTmhash:
    def test_sizes(self):
        assert len(tmhash.sum_sha256(b"x")) == 32
        assert len(tmhash.sum_truncated(b"x")) == 20
        assert tmhash.sum_truncated(b"x") == tmhash.sum_sha256(b"x")[:20]
