"""Query-language conformance — the reference's query_test.go cases
(libs/pubsub/query/query_test.go TestMatches/TestConditions/TestMustParse)
ported against tendermint_tpu.libs.pubsub.Query, plus the tokenizer cases
the old regex splitter failed (quoted values containing ' AND ')."""

from datetime import datetime, timezone

import pytest

from tendermint_tpu.libs.pubsub import Condition, Query

TX_DATE = "2017-01-01"
TX_TIME = "2018-05-03T14:45:00Z"
NOW_DATE = datetime.now(timezone.utc).strftime("%Y-%m-%d")
NOW_TIME = datetime.now(timezone.utc).strftime("%Y-%m-%dT%H:%M:%SZ")

# (query, events, parse_err, matches) — query_test.go:43-177 TestMatches
MATCH_CASES = [
    ("tm.events.type='NewBlock'", {"tm.events.type": ["NewBlock"]}, False, True),
    ("tx.gas > 7", {"tx.gas": ["8"]}, False, True),
    ("transfer.amount > 7", {"transfer.amount": ["8stake"]}, False, True),
    ("transfer.amount > 7", {"transfer.amount": ["8.045stake"]}, False, True),
    ("transfer.amount > 7.043", {"transfer.amount": ["8.045stake"]}, False, True),
    ("transfer.amount > 8.045", {"transfer.amount": ["8.045stake"]}, False, False),
    ("tx.gas > 7 AND tx.gas < 9", {"tx.gas": ["8"]}, False, True),
    ("body.weight >= 3.5", {"body.weight": ["3.5"]}, False, True),
    ("account.balance < 1000.0", {"account.balance": ["900"]}, False, True),
    ("apples.kg <= 4", {"apples.kg": ["4.0"]}, False, True),
    ("body.weight >= 4.5", {"body.weight": ["4.5"]}, False, True),
    (
        "oranges.kg < 4 AND watermellons.kg > 10",
        {"oranges.kg": ["3"], "watermellons.kg": ["12"]},
        False,
        True,
    ),
    ("peaches.kg < 4", {"peaches.kg": ["5"]}, False, False),
    ("tx.date > DATE 2017-01-01", {"tx.date": [NOW_DATE]}, False, True),
    ("tx.date = DATE 2017-01-01", {"tx.date": [TX_DATE]}, False, True),
    ("tx.date = DATE 2018-01-01", {"tx.date": [TX_DATE]}, False, False),
    ("tx.time >= TIME 2013-05-03T14:45:00Z", {"tx.time": [NOW_TIME]}, False, True),
    ("tx.time = TIME 2013-05-03T14:45:00Z", {"tx.time": [TX_TIME]}, False, False),
    ("abci.owner.name CONTAINS 'Igor'", {"abci.owner.name": ["Igor,Ivan"]}, False, True),
    ("abci.owner.name CONTAINS 'Igor'", {"abci.owner.name": ["Pavel,Ivan"]}, False, False),
    ("abci.owner.name = 'Igor'", {"abci.owner.name": ["Igor", "Ivan"]}, False, True),
    ("abci.owner.name = 'Ivan'", {"abci.owner.name": ["Igor", "Ivan"]}, False, True),
    (
        "abci.owner.name = 'Ivan' AND abci.owner.name = 'Igor'",
        {"abci.owner.name": ["Igor", "Ivan"]},
        False,
        True,
    ),
    (
        "abci.owner.name = 'Ivan' AND abci.owner.name = 'John'",
        {"abci.owner.name": ["Igor", "Ivan"]},
        False,
        False,
    ),
    (
        "tm.events.type='NewBlock'",
        {"tm.events.type": ["NewBlock"], "app.name": ["fuzzed"]},
        False,
        True,
    ),
    (
        "app.name = 'fuzzed'",
        {"tm.events.type": ["NewBlock"], "app.name": ["fuzzed"]},
        False,
        True,
    ),
    (
        "tm.events.type='NewBlock' AND app.name = 'fuzzed'",
        {"tm.events.type": ["NewBlock"], "app.name": ["fuzzed"]},
        False,
        True,
    ),
    (
        "tm.events.type='NewHeader' AND app.name = 'fuzzed'",
        {"tm.events.type": ["NewBlock"], "app.name": ["fuzzed"]},
        False,
        False,
    ),
    ("slash EXISTS", {"slash.reason": ["missing_signature"], "slash.power": ["6000"]}, False, True),
    ("sl EXISTS", {"slash.reason": ["missing_signature"], "slash.power": ["6000"]}, False, True),
    (
        "slash EXISTS",
        {
            "transfer.recipient": ["cosmos1gu6y2a0ffteesyeyeesk23082c6998xyzmt9mz"],
            "transfer.sender": ["cosmos1crje20aj4gxdtyct7z3knxqry2jqt2fuaey6u5"],
        },
        False,
        False,
    ),
    (
        "slash.reason EXISTS AND slash.power > 1000",
        {"slash.reason": ["missing_signature"], "slash.power": ["6000"]},
        False,
        True,
    ),
    (
        "slash.reason EXISTS AND slash.power > 1000",
        {"slash.reason": ["missing_signature"], "slash.power": ["500"]},
        False,
        False,
    ),
    (
        "slash.reason EXISTS",
        {
            "transfer.recipient": ["cosmos1gu6y2a0ffteesyeyeesk23082c6998xyzmt9mz"],
            "transfer.sender": ["cosmos1crje20aj4gxdtyct7z3knxqry2jqt2fuaey6u5"],
        },
        False,
        False,
    ),
]


class TestMatches:
    @pytest.mark.parametrize("s,events,err,want", MATCH_CASES)
    def test_case(self, s, events, err, want):
        if err:
            with pytest.raises(ValueError):
                Query(s)
            return
        assert Query(s).matches(events) == want


class TestConditions:
    """query_test.go:201-247 TestConditions — typed operands."""

    def test_string(self):
        assert Query("tm.events.type='NewBlock'").conditions == [
            Condition("tm.events.type", "=", "NewBlock")
        ]

    def test_ints(self):
        assert Query("tx.gas > 7 AND tx.gas < 9").conditions == [
            Condition("tx.gas", ">", 7),
            Condition("tx.gas", "<", 9),
        ]
        got = Query("tx.gas > 7").conditions[0].operand
        assert type(got) is int

    def test_float(self):
        got = Query("body.weight >= 3.5").conditions[0].operand
        assert type(got) is float and got == 3.5

    def test_time(self):
        assert Query("tx.time >= TIME 2013-05-03T14:45:00Z").conditions == [
            Condition(
                "tx.time", ">=", datetime(2013, 5, 3, 14, 45, tzinfo=timezone.utc)
            )
        ]

    def test_date(self):
        assert Query("tx.date = DATE 2017-01-01").conditions == [
            Condition("tx.date", "=", datetime(2017, 1, 1, tzinfo=timezone.utc))
        ]

    def test_exists(self):
        assert Query("slashing EXISTS").conditions == [
            Condition("slashing", "EXISTS", None)
        ]


class TestParser:
    def test_must_parse_analogue(self):
        with pytest.raises(ValueError):
            Query("=")
        Query("tm.events.type='NewBlock'")  # must not raise

    def test_quoted_and_value_parses(self):
        """The old regex splitter broke on quoted values containing
        ' AND ' — the tokenizer must not."""
        q = Query("abci.owner.name = 'Igor AND Ivan' AND tx.gas > 7")
        assert q.conditions == [
            Condition("abci.owner.name", "=", "Igor AND Ivan"),
            Condition("tx.gas", ">", 7),
        ]
        assert q.matches({"abci.owner.name": ["Igor AND Ivan"], "tx.gas": ["9"]})
        assert not q.matches({"abci.owner.name": ["Igor"], "tx.gas": ["9"]})

    def test_invalid_queries_rejected(self):
        for bad in (
            "=",
            "tx.gas >",
            "tx.gas > 'str'",          # inequality takes no string operand
            "tx.gas CONTAINS 7",        # CONTAINS takes a quoted value
            "tx.gas = 7stake",          # trailing junk after number
            "a = 1 OR b = 2",           # no OR in the grammar
            "tx.time > TIME 2013-05-03",  # TIME needs a full timestamp
            "tx.gas = 'unterminated",
        ):
            with pytest.raises(ValueError):
                Query(bad)

    def test_int_vs_float_truncation(self):
        # int operand vs dotted value: strconv-parse-float then int64()
        assert Query("x <= 4").matches({"x": ["4.9"]})
        assert not Query("x < 4").matches({"x": ["4.0q"]})

    def test_unparseable_event_value_is_no_match(self):
        assert not Query("x > 4").matches({"x": ["...."]})
        assert not Query("t = TIME 2013-05-03T14:45:00Z").matches({"t": ["notatime"]})
