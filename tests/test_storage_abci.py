"""Storage (db, block store) and ABCI (codec, clients, server, kvstore)."""

import threading

import pytest

from tendermint_tpu.abci import (
    ABCIServer,
    KVStoreApplication,
    LocalClient,
    PersistentKVStoreApplication,
    SocketClient,
)
from tendermint_tpu.abci import types as abci
from tendermint_tpu.crypto import ed25519
from tendermint_tpu.crypto.encoding import pubkey_from_proto, pubkey_to_proto
from tendermint_tpu.db import MemDB, PrefixDB, SQLiteDB
from tendermint_tpu.store import BlockStore
from tendermint_tpu.types import (
    Block,
    Commit,
    CommitSig,
    Data,
    Header,
    Timestamp,
    BLOCK_ID_FLAG_COMMIT,
)
from tendermint_tpu.types.block import BlockID, PartSetHeader
from tendermint_tpu.types.part_set import PartSet


class TestDB:
    @pytest.mark.parametrize("make", [MemDB, lambda: SQLiteDB(":memory:")])
    def test_ordered_kv(self, make):
        db = make()
        for k in [b"b", b"a", b"c", b"ab"]:
            db.set(k, b"v" + k)
        assert db.get(b"a") == b"va"
        assert db.get(b"missing") is None
        keys = [k for k, _ in db.iterator()]
        assert keys == [b"a", b"ab", b"b", b"c"]
        assert [k for k, _ in db.iterator(b"ab", b"c")] == [b"ab", b"b"]
        assert [k for k, _ in db.reverse_iterator()] == [b"c", b"b", b"ab", b"a"]
        db.delete(b"b")
        assert db.get(b"b") is None
        db.write_batch([("set", b"x", b"1"), ("delete", b"a", None)])
        assert db.get(b"x") == b"1" and db.get(b"a") is None

    def test_prefix_db(self):
        base = MemDB()
        p1, p2 = PrefixDB(base, b"a/"), PrefixDB(base, b"b/")
        p1.set(b"k", b"1")
        p2.set(b"k", b"2")
        assert p1.get(b"k") == b"1" and p2.get(b"k") == b"2"
        assert [kv for kv in p1.iterator()] == [(b"k", b"1")]


def _make_chain_block(height, last_commit=None):
    header = Header(
        chain_id="t",
        height=height,
        validators_hash=b"\x01" * 32,
        next_validators_hash=b"\x01" * 32,
        consensus_hash=b"\x02" * 32,
        proposer_address=b"\x04" * 20,
    )
    b = Block(header=header, data=Data(txs=[b"tx-%d" % height]), last_commit=last_commit)
    b.fill_header()
    return b


def _commit_for(block, parts):
    bid = BlockID(hash=block.hash(), part_set_header=parts.header())
    return Commit(
        height=block.header.height,
        round=0,
        block_id=bid,
        signatures=[
            CommitSig(
                block_id_flag=BLOCK_ID_FLAG_COMMIT,
                validator_address=b"\x07" * 20,
                timestamp=Timestamp(seconds=4),
                signature=b"\x08" * 64,
            )
        ],
    )


class TestBlockStore:
    def test_save_load_prune(self):
        bs = BlockStore(MemDB())
        assert bs.height() == 0 and bs.base() == 0
        last_commit = None
        blocks = []
        for h in range(1, 6):
            b = _make_chain_block(h, last_commit)
            parts = PartSet.from_data(b.encode())
            seen = _commit_for(b, parts)
            bs.save_block(b, parts, seen)
            last_commit = seen
            blocks.append(b)
        assert bs.height() == 5 and bs.base() == 1 and bs.size() == 5
        lb = bs.load_block(3)
        assert lb.header == blocks[2].header
        assert bs.load_block_by_hash(blocks[2].hash()).header == blocks[2].header
        assert bs.load_block_meta(2).header == blocks[1].header
        assert bs.load_block_commit(4) is not None  # block 5's LastCommit
        assert bs.load_seen_commit().height == 5
        # out-of-order save rejected
        with pytest.raises(ValueError):
            bs.save_block(_make_chain_block(9), PartSet.from_data(b"z"), _commit_for(blocks[0], PartSet.from_data(b"z")))
        pruned = bs.prune_blocks(4)
        assert pruned == 3
        assert bs.base() == 4
        assert bs.load_block(2) is None


class TestABCICodec:
    def test_request_response_roundtrip(self):
        req = abci.RequestBeginBlock(
            hash=b"\x01" * 32,
            header=b"hdrbytes",
            last_commit_info=abci.LastCommitInfo(
                round=2,
                votes=[
                    abci.VoteInfo(
                        validator=abci.ABCIValidator(address=b"\x02" * 20, power=10),
                        signed_last_block=True,
                    )
                ],
            ),
        )
        payload = abci.enc_request_payload("begin_block", req)
        framed = abci.write_message(abci.encode_request("begin_block", payload))
        msg, n = abci.read_message(framed)
        assert n == len(framed)
        kind, p2 = abci.decode_request(msg)
        assert kind == "begin_block"
        rt = abci.dec_request_payload(kind, p2)
        assert rt == req

        resp = abci.ResponseCheckTx(code=0, gas_wanted=5, priority=7, sender="s")
        enc = abci.enc_response_payload("check_tx", resp)
        rt2 = abci.dec_response_payload("check_tx", enc)
        assert rt2 == resp


class TestKVStore:
    def test_local_client_flow(self):
        app = KVStoreApplication()
        cli = LocalClient(app)
        assert cli.info(abci.RequestInfo()).last_block_height == 0
        assert cli.check_tx(abci.RequestCheckTx(tx=b"a=1")).is_ok()
        cli.begin_block(abci.RequestBeginBlock())
        assert cli.deliver_tx(abci.RequestDeliverTx(tx=b"a=1")).is_ok()
        cli.end_block(abci.RequestEndBlock(height=1))
        c = cli.commit()
        assert c.data  # app hash
        q = cli.query(abci.RequestQuery(data=b"a", path="/key"))
        assert q.value == b"1"

    def test_socket_client_server(self):
        app = KVStoreApplication()
        srv = ABCIServer("tcp://127.0.0.1:0", app)
        srv.start()
        cli = SocketClient(srv.address)
        try:
            assert cli.echo("hello") == "hello"
            assert cli.info(abci.RequestInfo()).version.startswith("kvstore")
            # pipelined delivers
            futs = [cli.deliver_tx_async(abci.RequestDeliverTx(tx=b"k%d=v" % i)) for i in range(20)]
            cli.flush()
            assert all(f.result(timeout=5).is_ok() for f in futs)
            cli.end_block(abci.RequestEndBlock(height=1))
            cli.commit()
            assert cli.query(abci.RequestQuery(data=b"k7", path="/key")).value == b"v"
        finally:
            cli.close()
            srv.stop()

    def test_persistent_kvstore_validator_updates(self):
        from tendermint_tpu.abci.kvstore import make_validator_tx

        app = PersistentKVStoreApplication()
        pk = ed25519.gen_priv_key(bytes([1]) * 32).pub_key()
        app.init_chain(
            abci.RequestInitChain(
                validators=[abci.ValidatorUpdate(pub_key=pubkey_to_proto(pk), power=10)]
            )
        )
        app.begin_block(abci.RequestBeginBlock())
        pk2 = ed25519.gen_priv_key(bytes([2]) * 32).pub_key()
        r = app.deliver_tx(
            abci.RequestDeliverTx(tx=make_validator_tx(pk2.bytes(), 7))
        )
        assert r.is_ok()
        eb = app.end_block(abci.RequestEndBlock(height=1))
        assert len(eb.validator_updates) == 1
        assert pubkey_from_proto(eb.validator_updates[0].pub_key).bytes() == pk2.bytes()
        vals = app.validators()
        assert len(vals) == 2


class TestABCICli:
    """abci-cli parity (abci/cmd/abci-cli): batch-style commands against
    a socket kvstore server."""

    def test_cli_commands_roundtrip(self, capsys):
        from tendermint_tpu.abci import cli as abci_cli
        from tendermint_tpu.abci.kvstore import KVStoreApplication
        from tendermint_tpu.abci.server import ABCIServer

        srv = ABCIServer("tcp://127.0.0.1:0", KVStoreApplication())
        srv.start()
        addr = srv._address
        try:
            assert abci_cli.main(["--address", addr, "echo", "hello"]) == 0
            assert abci_cli.main(["--address", addr, "info"]) == 0
            assert (
                abci_cli.main(["--address", addr, "deliver_tx", '"abc=def"']) == 0
            )
            assert abci_cli.main(["--address", addr, "commit"]) == 0
            assert abci_cli.main(["--address", addr, "query", '"abc"']) == 0
            out = capsys.readouterr().out
            assert "hello" in out
            assert "value" in out
            # hex form of the same tx (stringOrHexToBytes)
            hex_tx = "0x" + b"k2=v2".hex()
            assert abci_cli.main(["--address", addr, "deliver_tx", hex_tx]) == 0
            # bad arg form errors
            assert abci_cli.main(["--address", addr, "deliver_tx", "bare"]) == 1
        finally:
            srv.stop()

    def test_cli_batch_mode(self, capsys, monkeypatch):
        import io

        from tendermint_tpu.abci import cli as abci_cli
        from tendermint_tpu.abci.kvstore import KVStoreApplication
        from tendermint_tpu.abci.server import ABCIServer

        srv = ABCIServer("tcp://127.0.0.1:0", KVStoreApplication())
        srv.start()
        addr = srv._address
        try:
            monkeypatch.setattr(
                "sys.stdin",
                io.StringIO('deliver_tx "bk=bv"\ncommit\nquery "bk"\n'),
            )
            assert abci_cli.main(["--address", addr, "batch"]) == 0
            out = capsys.readouterr().out
            assert "-> commit" in out and "-> query" in out
        finally:
            srv.stop()


class TestABCIUnknownOneof:
    def test_unknown_request_and_response_kinds_fail_loudly(self):
        """VERDICT r3 missing-item 6: a foreign app speaking an ABCI
        method this framework does not implement must produce a loud
        error, not a silently dropped message."""
        import pytest

        from tendermint_tpu.abci.types import decode_request, decode_response
        from tendermint_tpu.wire.proto import ProtoWriter

        w = ProtoWriter()
        w.write_message(99, b"\x0a\x01x", always=True)  # no such oneof
        with pytest.raises(ValueError, match="unknown ABCI request"):
            decode_request(w.bytes())
        with pytest.raises(ValueError, match="unknown ABCI response"):
            decode_response(w.bytes())
        with pytest.raises(ValueError, match="empty"):
            decode_request(b"")
