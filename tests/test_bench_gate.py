"""Tier-1 perf ratchet (ISSUE 11 satellite, ROADMAP item 4): every
committed bench artifact kind is gated against a pinned last-good round
through `tools/bench_report.py --compare --gate-pct` — direction-aware,
so a future PR that commits a regressed artifact FAILS tier-1 instead of
silently drifting the record.

Pure stdlib + the in-repo bench_report module: runs in the main tier-1
process without jax, numpy or any crypto wheel.
"""

import json
import os
import re
import sys

import pytest

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO_ROOT not in sys.path:
    sys.path.insert(0, REPO_ROOT)

from tools import bench_report  # noqa: E402

PINS_PATH = os.path.join(REPO_ROOT, "tools", "bench_pins.json")


def _pins():
    with open(PINS_PATH) as fh:
        return json.load(fh)


def _latest_of_kind(kind: str):
    """Newest committed artifact of `kind` by round number."""
    rx = re.compile(rf"^{kind.upper()}_r(\d+)\.json$")
    best, best_n = None, -1
    for name in os.listdir(REPO_ROOT):
        m = rx.match(name)
        if m and int(m.group(1)) > best_n:
            best, best_n = name, int(m.group(1))
    return best


def test_pins_file_is_wellformed():
    pins = _pins()
    assert pins["gate_pct"] > 0
    for kind, name in pins["pins"].items():
        path = os.path.join(REPO_ROOT, name)
        assert os.path.exists(path), f"pinned {kind} artifact {name} missing"
        art = bench_report.load(path)
        assert not bench_report.validate(art), f"pinned {name} is invalid"
        assert art["kind"] == kind


@pytest.mark.parametrize(
    "kind",
    ["bench", "multichip", "light", "mempool", "blocksync", "votes", "soak",
     "fleet", "schemes", "agg"],
)
def test_ratchet_gate(kind, capsys):
    """--compare pinned-last-good → newest-committed must pass the gate.
    While the pin IS the newest round this is a self-compare (trivially
    green); the moment a newer round is committed, this test is the
    ratchet that refuses a >gate_pct regression on any tracked metric."""
    pins = _pins()
    pin = pins["pins"].get(kind)
    if pin is None:
        pytest.skip(f"no pin for kind {kind}")
    latest = _latest_of_kind(kind)
    assert latest is not None
    rc = bench_report.main([
        "--compare", os.path.join(REPO_ROOT, pin),
        os.path.join(REPO_ROOT, latest),
        "--gate-pct", str(pins["gate_pct"]),
    ])
    out = capsys.readouterr().out
    assert rc == 0, (
        f"{latest} regressed past {pins['gate_pct']}% vs pinned {pin}:\n{out}"
    )


def test_gate_actually_bites(tmp_path):
    """The wiring is only worth tier-1 space if a regression FAILS:
    synthesize a 30%-worse copy of the pinned light artifact and assert
    the same gate invocation exits 1."""
    pins = _pins()
    pin_path = os.path.join(REPO_ROOT, pins["pins"]["light"])
    with open(pin_path) as fh:
        art = json.load(fh)
    art["value"] = art["value"] * 0.7
    bad = tmp_path / "LIGHT_r99.json"
    bad.write_text(json.dumps(art))
    rc = bench_report.main([
        "--compare", pin_path, str(bad),
        "--gate-pct", str(pins["gate_pct"]),
    ])
    assert rc == 1


def test_soak_gate_is_direction_aware(tmp_path):
    """SOAK lane p99s regress on a RISE, replay_heights_per_s on a FALL
    (ISSUE 16): both synthetic regressions must trip the same gate."""
    pins = _pins()
    pin_path = os.path.join(REPO_ROOT, pins["pins"]["soak"])
    with open(pin_path) as fh:
        art = json.load(fh)

    worse_p99 = dict(art)
    worse_p99["ingress_admission_p99_ms"] = (
        (art.get("ingress_admission_p99_ms") or 1.0) * 1.5
    )
    bad = tmp_path / "SOAK_r98.json"
    bad.write_text(json.dumps(worse_p99))
    rc = bench_report.main([
        "--compare", pin_path, str(bad),
        "--gate-pct", str(pins["gate_pct"]),
    ])
    assert rc == 1, "a 50% ingress-admission p99 rise must fail the gate"

    slower_replay = dict(art)
    slower_replay["replay_heights_per_s"] = (
        (art.get("replay_heights_per_s") or 1.0) * 0.5
    )
    bad2 = tmp_path / "SOAK_r99.json"
    bad2.write_text(json.dumps(slower_replay))
    rc = bench_report.main([
        "--compare", pin_path, str(bad2),
        "--gate-pct", str(pins["gate_pct"]),
    ])
    assert rc == 1, "a 50% replay heights/s fall must fail the gate"


def test_schemes_artifact_meets_acceptance_floor():
    """ISSUE 19 acceptance pinned into tier-1: the committed scheme-lane
    artifact must show the 10k-validator secp commit clearing >= 10x the
    per-signature baseline in ONE relay launch. bench.py schemes already
    exits nonzero below 10x; this keeps the COMMITTED record honest."""
    latest = _latest_of_kind("schemes")
    assert latest is not None, "no SCHEMES_r*.json committed"
    with open(os.path.join(REPO_ROOT, latest)) as fh:
        art = json.load(fh)
    assert art["vs_per_sig"] >= 10.0
    assert art["launches"] == 1
    assert art["vals"] >= 10_000


def test_agg_artifact_meets_acceptance_floor():
    """ISSUE 20 acceptance pinned into tier-1: the committed
    aggregation-lane artifact must show K commits fused into one
    multi-pairing launch (pairings amortized under 2 per commit) and the
    128-validator aggregated commit within 1/10 of the per-signature
    ed25519 commit on the wire. bench.py bls already exits nonzero past
    these floors; this keeps the COMMITTED record honest."""
    latest = _latest_of_kind("agg")
    assert latest is not None, "no AGG_r*.json committed"
    with open(os.path.join(REPO_ROOT, latest)) as fh:
        art = json.load(fh)
    assert art["pairings_per_commit"] < 2.0
    assert art["wire_ratio_vs_ed25519"] <= 0.10
    assert art["launches"] == 1
    assert art["vals"] >= 128


def test_light_artifact_in_trajectory(capsys):
    """LIGHT_r* renders through --trajectory like every other kind."""
    rc = bench_report.main(["--trajectory"])
    out = capsys.readouterr().out
    assert rc == 0
    assert "light_r01" in out
