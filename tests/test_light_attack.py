"""Light-client attack detection: a forging primary is examined against an
honest witness, LightClientAttackEvidence is built and submitted to both
sides, and the evidence verifies in the evidence pool.

Reference parity: light/detector.go:21-120 (detectDivergence +
handleConflictingHeaders), :228-374 (examineConflictingHeaderAgainstTrace),
:406-423 (newLightClientAttackEvidence); internal/evidence/verify.go:159
(pool-side verification).
"""

from dataclasses import replace

import pytest

from tendermint_tpu.crypto import ed25519
from tendermint_tpu.db import MemDB
from tendermint_tpu.light import Client, LightStore, NodeBackedProvider, TrustOptions
from tendermint_tpu.light.client import (
    ErrFailedHeaderCrossReferencing,
    ErrLightClientAttack,
)
from tendermint_tpu.light.provider import LightBlock, Provider
from tendermint_tpu.types import SignedHeader, Vote
from tendermint_tpu.types.block import BlockID, PartSetHeader
from tendermint_tpu.types.evidence import LightClientAttackEvidence
from tendermint_tpu.types.vote import PRECOMMIT_TYPE
from tendermint_tpu.types.vote_set import VoteSet
from tests.test_consensus import make_node

CHAIN_ID = "cs-chain"


@pytest.fixture(scope="module")
def produced_chain():
    sk = ed25519.gen_priv_key(bytes([9]) * 32)
    cs, bstore, _ = make_node([sk], 0)
    cs.start()
    try:
        cs.wait_for_height(5, timeout=60)
    finally:
        cs.stop()
    return sk, cs, bstore


def _forge_block(lb: LightBlock, sk, prev_forged: LightBlock = None) -> LightBlock:
    """Re-sign a lunatic variant of a real light block: forged app_hash,
    re-linked to the forged parent, committed by the real validator key."""
    hdr = replace(lb.signed_header.header, app_hash=b"\x66" * 32)
    if prev_forged is not None:
        ph = prev_forged.hash()
        hdr = replace(
            hdr,
            last_block_id=BlockID(
                hash=ph, part_set_header=PartSetHeader(total=1, hash=ph)
            ),
        )
    bid = BlockID(
        hash=hdr.hash(), part_set_header=PartSetHeader(total=1, hash=hdr.hash())
    )
    vset = lb.validators
    vs = VoteSet(CHAIN_ID, hdr.height, 0, PRECOMMIT_TYPE, vset)
    v = Vote(
        type=PRECOMMIT_TYPE,
        height=hdr.height,
        round=0,
        block_id=bid,
        timestamp=hdr.time,
        validator_address=vset.validators[0].address,
        validator_index=0,
    )
    v = replace(v, signature=sk.sign(v.sign_bytes(CHAIN_ID)))
    vs.add_vote(v)
    return LightBlock(
        signed_header=SignedHeader(header=hdr, commit=vs.make_commit()),
        validators=vset,
    )


class ForgingPrimary(Provider):
    """Serves the honest chain below the fork height and a self-consistent
    forged (lunatic) chain at and above it."""

    def __init__(self, honest: Provider, sk, fork_height: int, tip: int):
        self._forged = {}
        self._tip = tip
        prev = None
        for h in range(fork_height, tip + 1):
            fb = _forge_block(honest.light_block(h), sk, prev)
            self._forged[h] = fb
            prev = fb
        self._honest = honest
        self._fork = fork_height
        self.received_evidence = []

    def light_block(self, height: int) -> LightBlock:
        if height == 0:
            height = self._tip
        if height >= self._fork:
            return self._forged[height]
        return self._honest.light_block(height)

    def report_evidence(self, ev) -> None:
        self.received_evidence.append(ev)


class RecordingWitness(NodeBackedProvider):
    def __init__(self, *a):
        super().__init__(*a)
        self.received_evidence = []

    def report_evidence(self, ev) -> None:
        self.received_evidence.append(ev)


def test_forging_primary_detected_and_evidence_submitted(produced_chain):
    sk, cs, bstore = produced_chain
    honest = NodeBackedProvider(bstore, cs._block_exec.store)
    evil = ForgingPrimary(honest, sk, fork_height=3, tip=5)
    witness = RecordingWitness(bstore, cs._block_exec.store)
    lb1 = honest.light_block(1)
    c = Client(
        chain_id=CHAIN_ID,
        trust_options=TrustOptions(period=1e9, height=1, hash=lb1.hash()),
        primary=evil,
        witnesses=[witness],
        store=LightStore(MemDB()),
    )
    with pytest.raises(ErrLightClientAttack):
        c.verify_light_block_at_height(5)

    # evidence against the primary went to the witness
    assert len(witness.received_evidence) == 1
    ev = witness.received_evidence[0]
    assert isinstance(ev, LightClientAttackEvidence)
    # lunatic attack (forged app_hash): common height is the last agreed one
    assert ev.conflicting_header_is_invalid(
        honest.light_block(5).signed_header.header
    )
    assert ev.common_height < 3
    assert ev.conflicting_block.header().app_hash == b"\x66" * 32
    # the equivocating validator is named byzantine
    byz = ev.byzantine_validators
    assert [v.address for v in byz] == [sk.pub_key().address()]
    # counter-evidence against the witness went to the primary (best effort)
    assert len(evil.received_evidence) == 1

    # the evidence verifies in the evidence pool against real state
    from tendermint_tpu.evidence import Pool

    pool = Pool(
        MemDB(), state_store=cs._block_exec.store, block_store=bstore
    )
    pool.set_state(cs.committed_state)
    pool.add_evidence(ev)
    pending = pool.pending_evidence(-1)
    assert len(pending) == 1 and pending[0].hash() == ev.hash()


def test_unsustained_witness_divergence_removes_witness(produced_chain):
    """A witness that serves a forged header it cannot verify is dropped,
    and with no matching witness left the verification fails cross-
    referencing (detector.go:88-101)."""
    sk, cs, bstore = produced_chain
    honest = NodeBackedProvider(bstore, cs._block_exec.store)

    class EvilWitness(NodeBackedProvider):
        armed = False  # honest during client init (the root cross-check)

        def light_block(self, height):
            lb = super().light_block(height)
            if not self.armed:
                return lb
            evil_header = replace(lb.signed_header.header, app_hash=b"\x66" * 32)
            return LightBlock(
                signed_header=SignedHeader(
                    header=evil_header, commit=lb.signed_header.commit
                ),
                validators=lb.validators,
            )

    evil = EvilWitness(bstore, cs._block_exec.store)
    lb1 = honest.light_block(1)
    c = Client(
        chain_id=CHAIN_ID,
        trust_options=TrustOptions(period=1e9, height=1, hash=lb1.hash()),
        primary=honest,
        witnesses=[evil],
        store=LightStore(MemDB()),
    )
    evil.armed = True
    with pytest.raises(ErrFailedHeaderCrossReferencing):
        c.verify_light_block_at_height(3)
    assert c._witnesses == []
