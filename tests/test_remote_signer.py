"""Remote signer conformance (privval socket + the signer-harness checks).

Mirrors tools/tm-signer-harness: pubkey match, vote/proposal signing,
double-sign rejection through the remote channel."""

import threading
import time

import pytest

from tendermint_tpu.crypto import ed25519
from tendermint_tpu.privval import FilePV
from tendermint_tpu.privval.remote import RemoteSignerError, SignerClient, SignerServer
from tendermint_tpu.types import Timestamp, Vote
from tendermint_tpu.types.block import BlockID, PartSetHeader
from tendermint_tpu.types.proposal import Proposal
from tendermint_tpu.types.vote import PRECOMMIT_TYPE, PREVOTE_TYPE

CHAIN = "remote-chain"


@pytest.fixture
def signer_pair():
    pv = FilePV(ed25519.gen_priv_key(bytes([8]) * 32))
    client = SignerClient("tcp://127.0.0.1:0", timeout=10.0)
    server = SignerServer(pv, client.listen_addr)
    server.start()
    yield pv, client
    server.stop()
    client.close()


def _vote(height, round_, t=PREVOTE_TYPE):
    bid = BlockID(hash=b"\x01" * 32, part_set_header=PartSetHeader(total=1, hash=b"\x01" * 32))
    return Vote(
        type=t,
        height=height,
        round=round_,
        block_id=bid,
        timestamp=Timestamp(seconds=100),
        validator_address=b"\x02" * 20,
        validator_index=0,
    )


class TestRemoteSigner:
    def test_pubkey_and_signing(self, signer_pair):
        pv, client = signer_pair
        assert client.get_pub_key().bytes() == pv.get_pub_key().bytes()
        v = _vote(5, 0)
        sv = client.sign_vote(CHAIN, v)
        assert pv.get_pub_key().verify_signature(sv.sign_bytes(CHAIN), sv.signature)
        p = Proposal(
            height=6, round=0, pol_round=-1,
            block_id=_vote(6, 0).block_id, timestamp=Timestamp(seconds=120),
        )
        sp = client.sign_proposal(CHAIN, p)
        assert pv.get_pub_key().verify_signature(sp.sign_bytes(CHAIN), sp.signature)
        client.ping()

    def test_double_sign_rejected_via_remote(self, signer_pair):
        pv, client = signer_pair
        v1 = _vote(7, 0)
        client.sign_vote(CHAIN, v1)
        # same HRS, different block -> conflicting data error over the wire
        bid2 = BlockID(hash=b"\x03" * 32, part_set_header=PartSetHeader(total=1, hash=b"\x03" * 32))
        v2 = Vote(**{**v1.__dict__, "block_id": bid2})
        with pytest.raises(ValueError, match="conflicting data"):
            client.sign_vote(CHAIN, v2)
        # height regression also rejected
        with pytest.raises(ValueError):
            client.sign_vote(CHAIN, _vote(6, 0))

    def test_timestamp_only_resign_returns_last_signed_timestamp(self, signer_pair):
        """privval file.go:339-341: a same-HRS re-sign where only the
        timestamp differs must reuse the stored signature AND restore the
        last-signed timestamp, so the returned vote verifies."""
        pv, client = signer_pair
        v1 = _vote(9, 0)
        sv1 = client.sign_vote(CHAIN, v1)
        v2 = Vote(**{**v1.__dict__, "timestamp": Timestamp(seconds=999)})
        sv2 = client.sign_vote(CHAIN, v2)
        assert sv2.timestamp == v1.timestamp
        assert sv2.signature == sv1.signature
        assert pv.get_pub_key().verify_signature(sv2.sign_bytes(CHAIN), sv2.signature)


class TestRetrySignerClient:
    """privval/retry_signer_client.go semantics: transport errors retried
    (bounded or indefinite), signer-reported errors surfaced immediately."""

    def _wrap(self, inner, **kw):
        from tendermint_tpu.privval.remote import RetrySignerClient

        return RetrySignerClient(inner, **kw)

    def test_transport_errors_retried_until_success(self):
        from tendermint_tpu.crypto import ed25519
        from tendermint_tpu.privval.remote import RemoteSignerError

        calls = {"n": 0}
        pub = ed25519.gen_priv_key(b"\x05" * 32).pub_key()

        class Flaky:
            def get_pub_key(self):
                calls["n"] += 1
                if calls["n"] < 3:
                    raise RemoteSignerError("transient")
                return pub

        rc = self._wrap(Flaky(), retries=5, timeout=0.01)
        assert rc.get_pub_key() is pub
        assert calls["n"] == 3

    def test_retries_exhausted(self):
        import pytest as _pytest

        from tendermint_tpu.privval.remote import RemoteSignerError

        class Dead:
            def get_pub_key(self):
                raise RemoteSignerError("down")

        rc = self._wrap(Dead(), retries=3, timeout=0.01)
        with _pytest.raises(RemoteSignerError, match="exhausted"):
            rc.get_pub_key()

    def test_signer_reported_error_not_retried(self):
        import pytest as _pytest

        calls = {"n": 0}

        class Refusing:
            def sign_vote(self, chain_id, vote):
                calls["n"] += 1
                raise ValueError("double sign")

        rc = self._wrap(Refusing(), retries=5, timeout=0.01)
        with _pytest.raises(ValueError, match="double sign"):
            rc.sign_vote("c", object())
        assert calls["n"] == 1
