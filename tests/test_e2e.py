"""Manifest-driven e2e harness tests (test/e2e parity): mixed
validator/full testnet with tx load, disconnect perturbation, invariant
checks and the block-interval benchmark."""

import pytest

from tendermint_tpu.e2e import Manifest, NodeManifest, Testnet


@pytest.mark.slow
class TestE2E:
    def test_testnet_with_load_and_perturbation(self):
        manifest = Manifest(
            chain_id="e2e-ci",
            nodes=[
                NodeManifest(name="val0"),
                NodeManifest(name="val1"),
                NodeManifest(name="val2", perturb=["disconnect"]),
                NodeManifest(name="full0", mode="full"),
            ],
            load_tx_count=6,
            wait_blocks=3,
        )
        net = Testnet(manifest)
        net.setup()
        net.start()
        try:
            net.wait_for_height(2, timeout=90)
            txs = net.load_transactions()
            net.perturb()
            net.wait_for_height(5, timeout=120)
            net.check_invariants()
            bench = net.benchmark()
            assert bench["blocks"] >= 5
            # at least some load landed in blocks
            rn = net.nodes["val0"]
            found = 0
            last = bench["blocks"]
            for h in range(1, last + 1):
                blk = rn.rpc.block(h)
                found += len(blk["block"]["data"]["txs"])
            assert found >= 1
        finally:
            net.stop()
