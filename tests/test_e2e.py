"""Manifest-driven e2e harness tests (test/e2e parity): mixed
validator/full testnet with tx load, disconnect perturbation, invariant
checks and the block-interval benchmark."""

import pytest

from tendermint_tpu.e2e import Manifest, NodeManifest, Testnet


@pytest.mark.slow
class TestE2E:
    def test_testnet_with_load_and_perturbation(self):
        manifest = Manifest(
            chain_id="e2e-ci",
            nodes=[
                NodeManifest(name="val0"),
                NodeManifest(name="val1"),
                NodeManifest(name="val2", perturb=["disconnect"]),
                NodeManifest(name="full0", mode="full"),
            ],
            load_tx_count=6,
            wait_blocks=3,
        )
        net = Testnet(manifest)
        net.setup()
        net.start()
        try:
            net.wait_for_height(2, timeout=90)
            txs = net.load_transactions()
            net.perturb()
            net.wait_for_height(5, timeout=120)
            net.check_invariants()
            bench = net.benchmark()
            assert bench["blocks"] >= 5
            # at least some load landed in blocks
            rn = net.nodes["val0"]
            found = 0
            last = bench["blocks"]
            for h in range(1, last + 1):
                blk = rn.rpc.block(h)
                found += len(blk["block"]["data"]["txs"])
            assert found >= 1
        finally:
            net.stop()


@pytest.mark.slow
class TestE2EMisbehavior:
    def test_double_sign_manifest_and_validator_rotation(self):
        """runner misbehaviors + validator_test.go rotation: a manifest
        double-prevote node's evidence is committed to a block, and a
        kvstore val-update tx rotates voting power on every node."""
        manifest = Manifest(
            chain_id="e2e-byz",
            nodes=[
                NodeManifest(name="val0", power=10),
                NodeManifest(name="val1", power=10),
                NodeManifest(name="val2", power=10),
                NodeManifest(name="byz0", power=1, misbehave="double-prevote"),
            ],
            load_tx_count=0,
            wait_blocks=3,
        )
        net = Testnet(manifest)
        net.setup()
        net.start()
        try:
            net.wait_for_height(2, timeout=90)
            found = net.check_evidence_committed(timeout=60)
            assert found["evidence"], found
            ev = found["evidence"][0]
            assert ev["type"] == "tendermint/DuplicateVoteEvidence", ev
            # validator rotation: bump val2's power via the app
            net.rotate_validator_power("val2", 14)
            net.check_validator_rotation("val2", 14, timeout=60)
            net.check_invariants()
        finally:
            net.stop()


class TestGenerator:
    def test_generate_manifests_deterministic(self):
        import random

        from tendermint_tpu.e2e import generator

        r1 = random.Random(42)
        r2 = random.Random(42)
        ms1 = generator.generate(r1)
        ms2 = generator.generate(r2)
        assert [m.chain_id for m in ms1] == [m.chain_id for m in ms2]
        assert ms1 == ms2
        # 3 topologies x 2 initial heights
        assert len(ms1) == 6
        for m in ms1:
            vals = [n for n in m.nodes if n.mode == "validator"]
            assert vals, m.chain_id
            # surviving (non-killed) power must keep the 2/3 quorum
            total = sum(n.power for n in vals)
            alive = sum(n.power for n in vals if "kill" not in n.perturb)
            assert alive * 3 > total * 2
            # late joiners are never perturbed (they are not running when
            # perturb() fires) and gate on the chain's initial height
            for n in m.nodes:
                if n.start_at:
                    assert not n.perturb
                    assert n.start_at > m.initial_height
            # at most one equivocator, never below 4 validators
            byz = [n for n in m.nodes if n.misbehave]
            assert len(byz) <= 1
            if byz:
                assert len(vals) >= 4

    def test_generate_size_filter(self):
        import random

        from tendermint_tpu.e2e import generator

        ms = generator.generate(random.Random(7), min_size=4)
        assert ms and all(len(m.nodes) >= 4 for m in ms)


@pytest.mark.slow
class TestLateJoiner:
    def test_full_node_joins_late_and_syncs(self):
        """runner/start.go: a start_at node launches once the chain passes
        its height and catches up (blocksync) to the running network."""
        manifest = Manifest(
            chain_id="e2e-late",
            nodes=[
                NodeManifest(name="val0"),
                NodeManifest(name="val1"),
                NodeManifest(name="full-late", mode="full", start_at=3),
            ],
            load_tx_count=4,
            wait_blocks=3,
        )
        net = Testnet(manifest)
        net.setup()
        net.start()
        try:
            assert net.nodes["full-late"].rpc is None
            net.start_late_joiners(timeout=90)
            assert net.nodes["full-late"].rpc is not None
            net.wait_for_height(5, timeout=120)
            net.nodes["full-late"].node.wait_for_height(5, timeout=120)
            net.check_invariants()
        finally:
            net.stop()


@pytest.mark.slow
class TestGeneratedManifestRun:
    def test_run_generated_quad_manifest(self):
        """generator -> runner pipeline (the nightly sweep's shape): pick
        the generated quad/initial-height-1 manifest, drop heavyweight
        perturbations for CI determinism, run the full runner sequence."""
        import random

        from tendermint_tpu.e2e import generator

        ms = generator.generate(random.Random(2024))
        m = next(x for x in ms if x.chain_id == "gen-quad-1")
        for n in m.nodes:
            n.perturb = [p for p in n.perturb if p == "disconnect"]
            n.misbehave = ""
        net = Testnet(m)
        net.setup()
        net.start()
        try:
            net.start_late_joiners(timeout=90)
            net.wait_for_height(2, timeout=90)
            net.load_transactions()
            net.perturb()
            net.wait_for_height(m.initial_height + m.wait_blocks, timeout=120)
            net.check_invariants()
        finally:
            net.stop()
