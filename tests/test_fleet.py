"""Verification-fleet parity: local vs through-fleet (ISSUE 18).

The fleet is a TRANSPORT, not a verifier: shipping an EntryBlock to a
FleetServer over the wire codec and verifying it on the server's shared
pipeline must produce byte-identical verdicts — and, through each prep
seam's conclude(), byte-identical blame errors — to submitting the same
block to the same pipeline locally. Covered per lane:

  consensus  prepare_commit_light        (PRIORITY_CONSENSUS)
  light      prepare_commit_light_trusting (PRIORITY_CONSENSUS)
  replay     prepare_commit_range        (PRIORITY_REPLAY)

Runs real ed25519 (purepy fallback in containers without the
cryptography wheel) and the real CPU kernels — this file is executed by
tests/test_fleet_isolated.py in a TM_TPU_PUREPY_CRYPTO=1 subprocess
when the wheel is missing.
"""

import pytest

np = pytest.importorskip("numpy")
jax = pytest.importorskip("jax")

try:
    from tendermint_tpu.crypto import ed25519
except ModuleNotFoundError:
    # No cryptography wheel in this container; test_fleet_isolated.py
    # re-runs this module in a TM_TPU_PUREPY_CRYPTO=1 subprocess.
    pytest.skip(
        "ed25519 backend unavailable (runs via test_fleet_isolated.py)",
        allow_module_level=True,
    )
from tendermint_tpu.fleet.client import FleetClient  # noqa: E402
from tendermint_tpu.fleet.server import FleetServer  # noqa: E402
from tendermint_tpu.ops import pipeline as pl  # noqa: E402
from tendermint_tpu.types import (  # noqa: E402
    BlockID,
    Fraction,
    PartSetHeader,
    PRECOMMIT_TYPE,
    Timestamp,
    Validator,
    ValidatorSet,
    Vote,
    VoteSet,
)
from tendermint_tpu.types.validation import (  # noqa: E402
    prepare_commit_light,
    prepare_commit_light_trusting,
    prepare_commit_range,
)

CHAIN_ID = "fleet-parity-chain"
HEIGHT = 10


def _make_validators(n):
    pairs = []
    for i in range(n):
        sk = ed25519.gen_priv_key(bytes([i + 1]) * 32)
        pairs.append((sk, Validator.new(sk.pub_key(), 100)))
    vset = ValidatorSet.new([v for _, v in pairs])
    by_addr = {v.address: sk for sk, v in pairs}
    return [by_addr[v.address] for v in vset.validators], vset


def _make_block_id(tag=b"\x01"):
    return BlockID(hash=tag * 32,
                   part_set_header=PartSetHeader(total=1, hash=tag * 32))


def _sign_vote(sk, vset, height, round_, block_id):
    addr = sk.pub_key().address()
    idx, _ = vset.get_by_address(addr)
    vote = Vote(
        type=PRECOMMIT_TYPE, height=height, round=round_,
        block_id=block_id, timestamp=Timestamp(seconds=1_600_000_000,
                                               nanos=0),
        validator_address=addr, validator_index=idx,
    )
    sig = sk.sign(vote.sign_bytes(CHAIN_ID))
    return Vote(**{**vote.__dict__, "signature": sig})


def _build_commit(n=6, forge_at=None):
    """A real n-validator precommit; forge_at tampers that CommitSig's
    signature (the blame target)."""
    sks, vset = _make_validators(n)
    block_id = _make_block_id()
    vote_set = VoteSet(CHAIN_ID, HEIGHT, 1, PRECOMMIT_TYPE, vset)
    for sk in sks:
        vote_set.add_vote(_sign_vote(sk, vset, HEIGHT, 1, block_id))
    commit = vote_set.make_commit()
    if forge_at is not None:
        from dataclasses import replace as dc_replace

        bad = bytearray(commit.signatures[forge_at].signature)
        bad[0] ^= 0x5A
        commit.signatures[forge_at] = dc_replace(
            commit.signatures[forge_at], signature=bytes(bad))
    return sks, vset, block_id, commit


def _conclusion(conclude, verdicts):
    """(type_name, str) of what conclude raises, or None when clean."""
    try:
        conclude(verdicts)
        return None
    except Exception as e:  # noqa: BLE001 — the blame IS the result
        return (type(e).__name__, str(e))


@pytest.fixture(scope="module")
def rig():
    """One shared pipeline, served both locally and through a real
    socket fleet — the parity comparison is transport vs no-transport
    over the SAME verifier."""
    v = pl.AsyncBatchVerifier(depth=1)
    srv = FleetServer(verifier=v).start()
    cli = FleetClient(srv.addr, name="parity", lane="parity",
                      timeout_ms=120_000)
    yield v, cli
    cli.close()
    srv.stop()
    v.close()


def _both_verdicts(rig_v, rig_cli, eblk, priority):
    local = np.asarray(rig_v.submit(eblk).result(timeout=300), dtype=bool)
    fleet = np.asarray(
        rig_cli.submit(eblk, priority=priority).result(timeout=300),
        dtype=bool)
    return local, fleet


class TestForgedCommitBlameParity:
    @pytest.mark.parametrize("forge_at", [0, 3])
    def test_consensus_lane_light_prep(self, rig, forge_at):
        v, cli = rig
        _, vset, block_id, commit = _build_commit(forge_at=forge_at)
        eblk, conclude = prepare_commit_light(
            CHAIN_ID, vset, block_id, HEIGHT, commit)
        local, fleet = _both_verdicts(v, cli, eblk,
                                      pl.PRIORITY_CONSENSUS)
        assert np.array_equal(local, fleet)
        want, got = _conclusion(conclude, local), _conclusion(conclude, fleet)
        assert want is not None, "forged commit must blame"
        assert want[0] == "ValueError" and "wrong signature" in want[1]
        assert got == want  # byte-identical blame through the fleet

    def test_light_lane_trusting_prep(self, rig):
        v, cli = rig
        _, vset, _, commit = _build_commit(forge_at=2)
        eblk, conclude = prepare_commit_light_trusting(
            CHAIN_ID, vset, commit, Fraction(1, 3))
        local, fleet = _both_verdicts(v, cli, eblk,
                                      pl.PRIORITY_CONSENSUS)
        assert np.array_equal(local, fleet)
        want, got = _conclusion(conclude, local), _conclusion(conclude, fleet)
        assert want is not None and got == want

    def test_replay_lane_range_prep(self, rig):
        v, cli = rig
        _, vset, block_id, commit = _build_commit(forge_at=4)
        prepared, synced = prepare_commit_range(
            CHAIN_ID, vset, [(HEIGHT, block_id, commit)])
        assert synced == [] and len(prepared) == 1
        _h, eblk, conclude = prepared[0]
        local, fleet = _both_verdicts(v, cli, eblk, pl.PRIORITY_REPLAY)
        assert np.array_equal(local, fleet)
        want, got = _conclusion(conclude, local), _conclusion(conclude, fleet)
        assert want is not None and got == want

    def test_clean_commit_concludes_clean_both_ways(self, rig):
        v, cli = rig
        _, vset, block_id, commit = _build_commit()
        eblk, conclude = prepare_commit_light(
            CHAIN_ID, vset, block_id, HEIGHT, commit)
        local, fleet = _both_verdicts(v, cli, eblk,
                                      pl.PRIORITY_CONSENSUS)
        assert np.array_equal(local, fleet) and bool(local.all())
        assert _conclusion(conclude, local) is None
        assert _conclusion(conclude, fleet) is None
