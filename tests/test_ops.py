"""Device engine tests: field arithmetic, kernel parity vs the ZIP-215
oracle, bucketing driver, and the mesh-sharded commit step.

The differential strategy mirrors the reference's CPU↔device plan
(SURVEY.md §7 stage 1): every device result is checked against the
pure-Python oracle (crypto/_edwards), including the ZIP-215 edge cases the
reference inherits from curve25519-voi (small-order points, non-canonical
encodings, s >= L)."""

import os
import random

import numpy as np
import pytest

jax = pytest.importorskip("jax")
import jax.numpy as jnp  # noqa: E402

from tendermint_tpu.crypto import _edwards as E  # noqa: E402
from tendermint_tpu.crypto import batch as cbatch  # noqa: E402
from tendermint_tpu.crypto import ed25519  # noqa: E402
from tendermint_tpu.ops import backend, fe  # noqa: E402


class TestFieldArithmetic:
    def _vals(self):
        rng = random.Random(7)
        vals = [0, 1, 2, 19, E.P - 1, E.P, E.P + 1, 2**255 - 1]
        vals += [rng.randrange(0, E.P) for _ in range(12)]
        return vals

    def test_ring_ops(self):
        vals = self._vals()
        rng = random.Random(8)
        others = [rng.randrange(0, E.P) for _ in vals]
        a = jnp.asarray(np.stack([fe.limbs_from_int(v) for v in vals]))
        b = jnp.asarray(np.stack([fe.limbs_from_int(v) for v in others]))
        for name, got, want in [
            ("add", fe.add(a, b), [x + y for x, y in zip(vals, others)]),
            ("sub", fe.sub(a, b), [x - y for x, y in zip(vals, others)]),
            ("mul", fe.mul(a, b), [x * y for x, y in zip(vals, others)]),
            ("sq", fe.sq(a), [x * x for x in vals]),
        ]:
            got = [fe.int_from_limbs(g) % E.P for g in np.asarray(got)]
            assert got == [w % E.P for w in want], name

    def test_canon_exact_and_parity(self):
        vals = self._vals()
        a = jnp.asarray(np.stack([fe.limbs_from_int(v) for v in vals]))
        b = jnp.asarray(np.stack([fe.limbs_from_int(v + 1) for v in vals]))
        canon = np.asarray(fe.canon(fe.sub(a, b)))
        for row, x in zip(canon, vals):
            assert fe.int_from_limbs(row) == (x - (x + 1)) % E.P
        assert bool(jnp.all(fe.eq(a, a)))
        assert not bool(jnp.any(fe.eq(a, b)))
        par = np.asarray(fe.parity(a))
        assert [int(p) for p in par] == [(v % E.P) & 1 for v in vals]

    def test_exponent_chains(self):
        vals = [2, 19, E.P - 2, random.Random(5).randrange(0, E.P)]
        a = jnp.asarray(np.stack([fe.limbs_from_int(v) for v in vals]))
        got = [fe.int_from_limbs(g) % E.P for g in np.asarray(jax.jit(fe.pow22523)(a))]
        assert got == [pow(v, (E.P - 5) // 8, E.P) for v in vals]
        got = [fe.int_from_limbs(g) % E.P for g in np.asarray(jax.jit(fe.invert)(a))]
        assert got == [pow(v, E.P - 2, E.P) for v in vals]


def _edge_entries():
    """Mixed batch exercising every ZIP-215 acceptance/rejection branch."""
    rng = random.Random(11)
    entries = []
    for i in range(6):
        sk = ed25519.gen_priv_key(bytes([i + 1]) * 32)
        msg = b"msg-%d" % i
        entries.append((sk.pub_key().bytes(), msg, sk.sign(msg)))
    sk = ed25519.gen_priv_key(bytes(32))
    msg, pub = b"hello", sk.pub_key().bytes()
    sig = sk.sign(msg)
    bad = bytearray(sig)
    bad[5] ^= 1
    entries.append((pub, msg, bytes(bad)))  # corrupted sig
    entries.append((pub, b"other", sig))  # wrong msg
    badpub = bytearray(pub)
    badpub[3] ^= 1
    entries.append((bytes(badpub), msg, sig))  # corrupted pubkey
    bad_s = bytearray(sig)
    bad_s[32:] = (E.L + 5).to_bytes(32, "little")
    entries.append((pub, msg, bytes(bad_s)))  # s >= L -> reject

    # Small-order A with R = [s]B: cofactored equation accepts for ANY msg.
    small = []
    for y in range(50):
        for sgn in (0, 1):
            enc = bytearray(y.to_bytes(32, "little"))
            enc[31] |= sgn << 7
            pt = E.decompress(bytes(enc))
            if pt is not None and E.is_identity(E.mult_by_cofactor(pt)):
                small.append(bytes(enc))
    assert small
    for enc in small[:3]:
        s = rng.randrange(0, E.L)
        r = E.compress(E.scalar_mult(s, E.BASE))
        entries.append((enc, b"anything", r + s.to_bytes(32, "little")))
    # Non-canonical A encoding (y' = y + p): same point, still accepted.
    for enc in small:
        y = int.from_bytes(enc, "little") & ((1 << 255) - 1)
        if y < 19:
            enc2 = ((y + E.P) | ((enc[31] >> 7) << 255)).to_bytes(32, "little")
            s = rng.randrange(0, E.L)
            r = E.compress(E.scalar_mult(s, E.BASE))
            entries.append((enc2, b"nc", r + s.to_bytes(32, "little")))
    for _ in range(3):
        entries.append((rng.randbytes(32), rng.randbytes(20), rng.randbytes(64)))
    return entries


class TestVerifyKernel:
    def test_parity_vs_oracle(self):
        entries = _edge_entries()
        oracle = [E.verify_zip215(p, m, s) for p, m, s in entries]
        assert any(oracle) and not all(oracle)
        res = backend.verify_batch(entries)
        assert [bool(r) for r in res] == oracle

    def test_empty_and_chunking_shapes(self):
        assert backend.verify_batch([]).shape == (0,)

    def test_batch_verifier_interface(self):
        bv = backend.Ed25519DeviceBatchVerifier(force_device=True)
        sks = [ed25519.gen_priv_key(bytes([i + 1]) * 32) for i in range(4)]
        for i, sk in enumerate(sks):
            bv.add(sk.pub_key(), b"m%d" % i, sk.sign(b"m%d" % i))
        ok, valid = bv.verify()
        assert ok and valid == [True] * 4
        bv = backend.Ed25519DeviceBatchVerifier(force_device=True)
        bv.add(sks[0].pub_key(), b"x", sks[0].sign(b"y"))
        ok, valid = bv.verify()
        assert not ok and valid == [False]

    def test_dispatch_seam_installs_device_engine(self):
        import tendermint_tpu.ops  # noqa: F401 — installs the factory

        sk = ed25519.gen_priv_key(bytes([9]) * 32)
        bv = cbatch.create_batch_verifier(sk.pub_key())
        assert isinstance(bv, backend.Ed25519DeviceBatchVerifier)


class TestShardedCommit:
    def test_sharded_commit_verifier(self):
        from tendermint_tpu.ops import sharded

        n_dev = min(8, len(jax.devices()))
        mesh = sharded.make_mesh(n_dev)
        entries, powers = [], []
        for i in range(2 * n_dev):
            sk = ed25519.gen_priv_key(bytes([i + 1]) * 32)
            msg = b"commit-%d" % i
            sig = sk.sign(msg)
            if i == 3:
                sig = sig[:-1] + bytes([sig[-1] ^ 1])
            entries.append((sk.pub_key().bytes(), msg, sig))
            powers.append(1000 + i)
        valid, tallied, all_valid = sharded.verify_commit_sharded(
            entries, powers, mesh, bucket=2 * n_dev
        )
        want_valid = [i != 3 for i in range(2 * n_dev)]
        assert [bool(v) for v in valid] == want_valid
        assert not all_valid
        assert tallied == sum(p for p, w in zip(powers, want_valid) if w)

    def test_sharded_pallas_matches_host_oracle(self):
        """VERDICT r3 item 4: the PRODUCTION compact Pallas kernel under
        shard_map (interpret mode, the same traced program Mosaic
        compiles) agrees with the big-int ZIP-215 oracle lane-by-lane,
        with the psum power tally and all-valid reduction correct."""
        from tendermint_tpu.crypto import _edwards as E
        from tendermint_tpu.ops import pallas_verify as pv, sharded

        n_dev = min(8, len(jax.devices()))
        mesh = sharded.make_mesh(n_dev)
        old_block = pv.BLOCK
        pv.BLOCK = 8  # keep the interpreted ladder fast
        try:
            entries, powers = [], []
            for i in range(4 * n_dev):
                sk = ed25519.gen_priv_key(bytes([i + 1]) * 32)
                msg = b"pshard-%d" % i
                sig = sk.sign(msg)
                if i in (3, 17):
                    sig = sig[:-1] + bytes([sig[-1] ^ 1])
                entries.append((sk.pub_key().bytes(), msg, sig))
                powers.append(100 + i)
            valid, tallied, all_valid = sharded.verify_commit_sharded_pallas(
                entries, powers, mesh, bucket=8 * n_dev
            )
            oracle = [E.verify_zip215(p, m, s) for p, m, s in entries]
            assert [bool(v) for v in valid] == oracle
            assert not all_valid
            assert tallied == sum(p for p, ok in zip(powers, oracle) if ok)
        finally:
            pv.BLOCK = old_block

    def test_power_split_roundtrip(self):
        from tendermint_tpu.ops import sharded

        # Domain: up to MaxTotalVotingPower = 2^63/8 (validator_set.go:25).
        vals = [0, 1, 2**16, 2**30 - 1, 2**30, 2**60 - 1, 2**63 // 8]
        sp = sharded.split_power(np.asarray(vals))
        for lanes, v in zip(sp, vals):
            assert sharded.join_power(lanes) == v
        with pytest.raises(ValueError):
            sharded.split_power(np.asarray([2**62]))
        with pytest.raises(ValueError):
            sharded.split_power(np.asarray([-1]))


class TestFreshImportUnderTrace:
    """Regression for the round-2 bench crash: the device-hash kernel was
    the FIRST jax trace in the process, and a lazy `from . import sc`
    inside it materialized module-level jnp constants inside the trace
    (ops/sc.py L_LIMBS leaked as a DynamicJaxprTracer). The fix is
    two-fold: module-scope imports in ops/ed25519_verify.py and numpy
    (trace-immune) module constants; this test reproduces the bench's
    exact import order in a fresh interpreter so a regression fails here
    and not in the driver's bench run."""

    def test_device_hash_kernel_first_trace(self):
        import subprocess
        import sys

        code = (
            "import os\n"
            "os.environ['JAX_PLATFORMS'] = 'cpu'\n"
            "os.environ.pop('PALLAS_AXON_POOL_IPS', None)\n"
            "import numpy as np\n"
            "from tendermint_tpu.crypto import ed25519\n"
            "from tendermint_tpu.ops import backend\n"
            "sk = ed25519.gen_priv_key(b'\\x07' * 32)\n"
            "msg = b'fresh-trace'\n"
            "entries = [(sk.pub_key().bytes(), msg, sk.sign(msg))]\n"
            "args = backend.prepare_batch_device_hash(entries, 128)\n"
            "kern = backend.ed25519_verify.jitted_verify_device_hash()\n"
            "res = np.asarray(kern(*args))\n"
            "assert bool(res[0]), 'signature must verify'\n"
            "print('OK')\n"
        )
        env = dict(os.environ)
        # must be scrubbed at SPAWN time: the axon sitecustomize dials the
        # TPU relay at interpreter start (before the -c code runs), and a
        # busy/hung relay would hang this CPU-only child at import
        env.pop("PALLAS_AXON_POOL_IPS", None)
        env["JAX_PLATFORMS"] = "cpu"
        out = subprocess.run(
            [sys.executable, "-c", code],
            capture_output=True,
            text=True,
            timeout=300,
            env=env,
            cwd=os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        )
        assert out.returncode == 0, out.stderr[-2000:]
        assert "OK" in out.stdout
