"""Fuzz-style robustness tests (test/fuzz parity): random/adversarial bytes
must never crash the decoders, the mempool, the secret connection, or the
JSON-RPC server."""

import json
import random
import socket
import threading
import urllib.request

import pytest

from tendermint_tpu.abci import KVStoreApplication, LocalClient
from tendermint_tpu.crypto import ed25519
from tendermint_tpu.mempool import DuplicateTxError, MempoolFullError, TxMempool
from tendermint_tpu.wire.proto import decode_message, unmarshal_delimited


class TestProtoFuzz:
    def test_decode_random_bytes_never_crashes(self):
        rng = random.Random(1)
        for _ in range(500):
            data = rng.randbytes(rng.randrange(0, 200))
            try:
                decode_message(data)
            except ValueError:
                pass  # expected failure mode
            try:
                unmarshal_delimited(data)
            except ValueError:
                pass

    def test_typed_decoders_reject_garbage(self):
        from tendermint_tpu.types import Block, Commit, Header, Vote
        from tendermint_tpu.types.evidence import decode_evidence
        from tendermint_tpu.types.proposal import Proposal

        rng = random.Random(2)
        for cls in (Block, Commit, Header, Vote, Proposal):
            for _ in range(100):
                data = rng.randbytes(rng.randrange(0, 150))
                try:
                    cls.decode(data)
                except (ValueError, KeyError, UnicodeDecodeError, OverflowError):
                    pass
        for _ in range(100):
            try:
                decode_evidence(rng.randbytes(rng.randrange(0, 150)))
            except (ValueError, KeyError, UnicodeDecodeError, OverflowError):
                pass


class TestMempoolFuzz:
    def test_checktx_random_inputs(self):
        """test/fuzz/mempool: arbitrary tx bytes through CheckTx."""
        mp = TxMempool(LocalClient(KVStoreApplication()))
        rng = random.Random(3)
        accepted = 0
        for _ in range(300):
            tx = rng.randbytes(rng.randrange(0, 64))
            try:
                res = mp.check_tx(tx)
                if res.is_ok():
                    accepted += 1
            except (DuplicateTxError, MempoolFullError, ValueError):
                pass
        assert mp.size() == accepted
        assert mp.size() <= 300


class TestSecretConnectionFuzz:
    def test_garbage_handshake_rejected(self):
        """test/fuzz/p2p/secretconnection: junk at every stage."""
        from tendermint_tpu.p2p import SecretConnection

        rng = random.Random(4)

        class JunkStream:
            def __init__(self, data):
                self._data = data
                self.wrote = b""

            def read(self, n):
                out, self._data = self._data[:n], self._data[n:]
                return out

            def write(self, b):
                self.wrote += b

            def close(self):
                pass

        key = ed25519.gen_priv_key(bytes([5]) * 32)
        for _ in range(30):
            stream = JunkStream(rng.randbytes(rng.randrange(0, 2000)))
            with pytest.raises(Exception):
                SecretConnection(stream, key)


class TestRPCFuzz:
    def test_jsonrpc_garbage_bodies(self):
        """test/fuzz/rpc/jsonrpc: malformed HTTP/JSON-RPC bodies."""
        from tendermint_tpu.rpc.core import Environment
        from tendermint_tpu.rpc.server import RPCServer

        class FakeNode:
            pass

        srv = RPCServer("tcp://127.0.0.1:0", Environment(FakeNode()))
        srv.start()
        try:
            url = f"http://{srv.listen_addr}"
            rng = random.Random(5)
            bodies = [
                b"",
                b"{",
                b"[]",
                b"null",
                json.dumps({"method": 5}).encode(),
                json.dumps({"jsonrpc": "2.0", "method": "status", "params": "x"}).encode(),
                json.dumps({"jsonrpc": "2.0", "method": "../../etc", "id": 1}).encode(),
            ] + [rng.randbytes(rng.randrange(1, 100)) for _ in range(20)]
            for body in bodies:
                req = urllib.request.Request(
                    url, data=body, headers={"Content-Type": "application/json"}
                )
                try:
                    with urllib.request.urlopen(req, timeout=5) as resp:
                        resp.read()
                except urllib.error.HTTPError:
                    pass  # 4xx/5xx is fine; crash/hang is not
        finally:
            srv.stop()


class TestWALFuzz:
    """internal/consensus/wal_fuzz.go: arbitrary bytes fed to the WAL
    decoder must produce clean errors or truncated iteration — never an
    unhandled crash; and every well-formed prefix must replay."""

    def test_decoder_survives_garbage(self, tmp_path):
        from tendermint_tpu.consensus.wal import WAL, WALMessage

        rng = random.Random(99)
        for trial in range(40):
            p = tmp_path / f"wal-{trial}"
            p.mkdir()
            wal = WAL(str(p / "wal"))
            wal.start()
            for i in range(3):
                wal.write(
                    WALMessage(msg_kind="vote", msg_payload=b"msg-%d" % i)
                )
            wal.stop()
            # corrupt the file: random mutations, truncations, prepends
            files = sorted(p.glob("wal*"))
            assert files, list(p.iterdir())
            target = files[0]
            blob = bytearray(target.read_bytes())
            op = trial % 4
            if op == 0 and blob:
                blob[rng.randrange(len(blob))] ^= 0xFF
            elif op == 1:
                blob = blob[: rng.randrange(len(blob) + 1)]
            elif op == 2:
                blob = bytearray(rng.randbytes(rng.randrange(0, 64))) + blob
            else:
                blob += rng.randbytes(rng.randrange(1, 40))
            target.write_bytes(bytes(blob))
            wal2 = WAL(str(p / "wal"))
            wal2.start()  # torn-tail repair must not crash
            count = 0
            try:
                for _ in wal2.iter_messages():
                    count += 1
            except (ValueError, EOFError):
                pass  # clean decode error is acceptable
            wal2.stop()
            # safety property: bounded, crash-free iteration (corrupted
            # framing may occasionally mis-sync into extra records; the
            # guarantee is clean errors, not record-exact recovery)
            assert count <= 16
            # replay property: append-only garbage leaves every original
            # frame intact, so all three records must still replay
            if op == 3:
                assert count >= 3, (trial, count)
