"""Cluster flight recorder (ISSUE 10): causal cross-node tracing, the
per-height latency attribution ring, the /height_timeline RPC, and the
flight-recorder dump attached to invariant failures.

These tests drive real consensus nodes (simnet clusters and a single RPC
node), so they need an ed25519 signer: the OpenSSL wheel where present,
or the pure-Python fallback via the subprocess runner in
tests/test_flight_recorder_isolated.py (the env flag must never be set in
the main pytest process — see tendermint_tpu memory/CHANGES on suite-wide
leakage).
"""

import json
import os

import pytest

try:
    import cryptography  # noqa: F401

    HAVE_CRYPTO = True
except ModuleNotFoundError:
    HAVE_CRYPTO = bool(os.environ.get("TM_TPU_PUREPY_CRYPTO"))

if not HAVE_CRYPTO:
    pytest.skip(
        "no ed25519 implementation; run via test_flight_recorder_isolated",
        allow_module_level=True,
    )

from tendermint_tpu.observability import trace as tr
from tendermint_tpu.simnet import Cluster


@pytest.fixture(autouse=True)
def _quiet_tracer():
    tr.configure(enabled=False)
    yield
    tr.configure(enabled=False)


def _structure(doc):
    """A merged trace's replay-comparable shape: everything except the
    wall-clock-derived fields (none are present for virtual-clock node
    tracers, but the extractor is explicit about what it compares)."""
    out = []
    for ev in doc["traceEvents"]:
        out.append((
            ev.get("ph"), ev.get("name"), ev.get("pid"),
            # virtual-clock timestamps are deterministic and INCLUDED —
            # same seed must reproduce them exactly
            round(ev.get("ts", 0.0), 3), round(ev.get("dur", 0.0), 3),
            ev.get("id"),
            tuple(sorted((ev.get("args") or {}).items())),
        ))
    return out


def _run_traced(seed=11, height=5, n_nodes=4):
    c = Cluster(n_nodes=n_nodes, seed=seed, tracing=True)
    try:
        rep = c.run_to_height(height, max_virtual_s=300.0)
        doc = c.export_merged_trace()
    finally:
        c.stop()
    return rep, doc


class TestMergedTrace:
    def test_cross_node_flow_chain_present(self):
        rep, doc = _run_traced()
        assert rep.ok, rep.reason
        chains = tr.flow_chains(doc)
        assert chains, "traced run recorded no flow chains"
        full = [
            evs for evs in chains.values()
            if [e["name"] for e in evs][0] == "gossip.send"
            and evs[-1]["name"] == "consensus.verify_dispatch"
            and len({e["pid"] for e in evs}) > 1
        ]
        assert full, "no gossip.send -> deliver -> verify_dispatch chain"
        # the chain is causal: send on one node, deliver+verify on another
        evs = full[0]
        assert evs[1]["name"] == "net.deliver"
        assert evs[0]["pid"] != evs[1]["pid"]
        assert evs[1]["pid"] == evs[2]["pid"]
        phases = [(e["args"] or {}).get("flow_phase") for e in evs]
        assert phases == ["s", "t", "f"]

    def test_one_process_per_node_with_names(self):
        rep, doc = _run_traced(n_nodes=3)
        assert rep.ok
        names = {
            ev["args"]["name"]
            for ev in doc["traceEvents"]
            if ev.get("ph") == "M" and ev.get("name") == "process_name"
        }
        assert {"sim0", "sim1", "sim2"} <= names
        span_pids = {
            ev["pid"] for ev in doc["traceEvents"] if ev.get("ph") == "X"
        }
        assert len(span_pids) >= 3

    def test_merged_trace_determinism_under_replay(self):
        """Same seed, two runs: identical span/flow structure — names,
        per-node pids (merge-normalized), flow ids, args AND the
        virtual-clock timestamps all reproduce."""
        rep1, doc1 = _run_traced(seed=21)
        rep2, doc2 = _run_traced(seed=21)
        assert rep1.fingerprint == rep2.fingerprint
        assert _structure(doc1) == _structure(doc2)
        # and a different seed must actually produce a different trace
        _, doc3 = _run_traced(seed=22)
        assert _structure(doc3) != _structure(doc1)


class TestTimelineRing:
    def test_simreport_ring_populated_and_attributed(self):
        rep, _ = _run_traced(height=6)
        assert rep.ok
        tls = rep.height_timelines
        assert tls, "green run must still carry the timeline ring"
        heights = [t["height"] for t in tls]
        assert heights == sorted(heights)
        assert heights[-1] >= 6
        done = [t for t in tls if t.get("total_s") is not None]
        assert done, "committed heights must have completed timelines"
        for t in done:
            assert t["rounds"] >= 1
            phases = t["phases"]
            # a clean committed height attributes every phase
            assert set(phases) == {
                "propose", "prevote", "precommit", "commit", "apply"
            }, phases
            assert all(v >= 0 for v in phases.values())
            assert t["total_s"] >= max(phases.values())

    def test_ring_is_bounded(self, monkeypatch):
        monkeypatch.setenv("TM_TPU_TIMELINE_RING", "3")
        c = Cluster(n_nodes=4, seed=5)
        try:
            rep = c.run_to_height(7, max_virtual_s=300.0)
            assert rep.ok
            assert len(rep.height_timelines) == 3
            assert rep.height_timelines[-1]["height"] >= 7
        finally:
            c.stop()

    def test_no_flight_recorder_on_green_run(self):
        rep, _ = _run_traced()
        assert rep.ok
        assert rep.flight_recorder is None


class TestFlightRecorderDump:
    def _broken_cluster(self, tracing=True):
        """A cluster with an injected fault: once node 0 commits h >= 3 it
        re-reports the previous height through the REAL commit hook path,
        which the monotonicity invariant must flag — and the failure must
        arrive with the flight recorder attached."""
        c = Cluster(n_nodes=4, seed=9, tracing=tracing)
        node = c.nodes[0]

        def inject(height):
            if height >= 3:
                c._node_committed(node, height - 1)

        node.cs._height_events.append(inject)
        return c

    def test_dump_attached_on_invariant_failure(self):
        c = self._broken_cluster()
        try:
            rep = c.run_to_height(5, max_virtual_s=300.0)
        finally:
            c.stop()
        assert not rep.ok
        assert any("monotonicity" in v for v in rep.violations)
        fr = rep.flight_recorder
        assert fr is not None
        assert fr["tracing"] is True
        # per-node recent timelines
        assert set(fr["height_timelines"]) == {f"sim{i}" for i in range(4)}
        assert all(len(v) <= 8 for v in fr["height_timelines"].values())
        assert any(v for v in fr["height_timelines"].values())
        # merged trace tail, bounded, with the cross-node spans in it
        tail = fr["trace_tail"]["traceEvents"]
        assert 0 < len([e for e in tail if e.get("ph") != "M"]) <= 512
        assert fr["trace_events_total"] >= len(tail) - len(
            [e for e in tail if e.get("ph") == "M"]
        )
        names = {e["name"] for e in tail}
        assert "net.deliver" in names or "gossip.send" in names
        json.dumps(fr)  # the dump must be a serializable attachment

    def test_dump_without_tracing_still_carries_timelines(self):
        c = self._broken_cluster(tracing=False)
        try:
            rep = c.run_to_height(5, max_virtual_s=300.0)
        finally:
            c.stop()
        assert not rep.ok
        fr = rep.flight_recorder
        assert fr is not None
        assert fr["tracing"] is False
        assert any(v for v in fr["height_timelines"].values())


class TestHeightTimelineRPC:
    def _single_node(self):
        from tendermint_tpu.abci import KVStoreApplication
        from tendermint_tpu.crypto import ed25519
        from tendermint_tpu.node import make_node
        from tendermint_tpu.p2p import NodeKey
        from tendermint_tpu.privval import FilePV
        from tendermint_tpu.types import Timestamp
        from tendermint_tpu.types.genesis import GenesisDoc, GenesisValidator
        from tests.test_consensus import FAST
        from tendermint_tpu.config import Config

        sk = ed25519.gen_priv_key(bytes([31]) * 32)
        doc = GenesisDoc(
            chain_id="tl-chain",
            genesis_time=Timestamp(seconds=1_700_000_000),
            validators=[
                GenesisValidator(address=b"", pub_key=sk.pub_key(), power=10)
            ],
        )
        cfg = Config()
        cfg.base.home = ""
        cfg.base.db_backend = "memdb"
        cfg.consensus = FAST
        cfg.p2p.laddr = "none"
        cfg.rpc.laddr = "tcp://127.0.0.1:0"
        return make_node(
            cfg,
            app=KVStoreApplication(),
            genesis=doc,
            priv_validator=FilePV(sk),
            node_key=NodeKey.generate(bytes([77]) * 32),
            with_rpc=True,
        )

    def test_rpc_roundtrip(self):
        from tendermint_tpu.rpc import HTTPClient
        from tendermint_tpu.rpc.core import RPCError

        node = self._single_node()
        node.start()
        try:
            node.wait_for_height(2, timeout=60)
            rpc = HTTPClient(node.rpc_server.listen_addr)
            # latest
            res = rpc.call("height_timeline")
            h = int(res["height"])
            assert h >= 2
            tl = res["timeline"]
            assert tl["height"] == h
            assert tl["rounds"] >= 1
            assert tl["phases"] and all(
                v >= 0 for v in tl["phases"].values()
            )
            assert res["retained"]["count"] >= 2
            # explicit height
            res1 = rpc.call("height_timeline", height=1)
            assert int(res1["height"]) == 1
            assert res1["timeline"]["total_s"] >= 0
            # outside the ring -> RPC error, not a 0-filled record
            with pytest.raises(RPCError, match="not in the retained"):
                rpc.call("height_timeline", height=10_000)
        finally:
            node.stop()
