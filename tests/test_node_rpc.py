"""Node composition + RPC integration: a 2-validator TCP localnet built by
make_node, driven end-to-end over JSON-RPC (broadcast_tx_commit →
abci_query), plus handshake/replay restart behavior."""

import time

import pytest

from tendermint_tpu.abci import KVStoreApplication
from tendermint_tpu.config import Config, ConsensusConfig
from tendermint_tpu.crypto import ed25519
from tendermint_tpu.node import make_node
from tendermint_tpu.p2p import NodeKey
from tendermint_tpu.privval import FilePV
from tendermint_tpu.rpc import HTTPClient
from tendermint_tpu.types import Timestamp
from tendermint_tpu.types.genesis import GenesisDoc, GenesisValidator
from tests.test_consensus import FAST

CHAIN = "node-chain"


def _make_config(i):
    cfg = Config()
    cfg.base.home = ""  # memdb
    cfg.base.db_backend = "memdb"
    cfg.consensus = FAST
    cfg.p2p.laddr = "tcp://127.0.0.1:0"
    cfg.rpc.laddr = f"tcp://127.0.0.1:0"
    return cfg


@pytest.fixture
def two_node_net():
    sks = [ed25519.gen_priv_key(bytes([i + 1]) * 32) for i in range(2)]
    doc_json = GenesisDoc(
        chain_id=CHAIN,
        genesis_time=Timestamp(seconds=1_700_000_000),
        validators=[
            GenesisValidator(address=b"", pub_key=sk.pub_key(), power=10) for sk in sks
        ],
    ).to_json()
    nodes = []
    for i in range(2):
        cfg = _make_config(i)
        node = make_node(
            cfg,
            app=KVStoreApplication(),
            genesis=GenesisDoc.from_json(doc_json),
            priv_validator=FilePV(sks[i]),
            node_key=NodeKey.generate(bytes([i + 60]) * 32),
            with_rpc=True,
        )
        nodes.append(node)
    # wire persistent peers after listen addrs exist
    from tendermint_tpu.p2p import PeerAddress

    for i, n in enumerate(nodes):
        other = nodes[1 - i]
        n.router._pm.add_address(
            PeerAddress(other.node_id, other.router._transport.listen_addr),
            persistent=True,
        )
    for n in nodes:
        n.start()
    yield nodes
    for n in nodes:
        n.stop()


class TestNodeRPC:
    def test_end_to_end_tx_flow(self, two_node_net):
        nodes = two_node_net
        nodes[0].wait_for_height(2, timeout=60)
        rpc = HTTPClient(nodes[0].rpc_server.listen_addr)

        st = rpc.status()
        assert st["node_info"]["network"] == CHAIN
        assert int(st["sync_info"]["latest_block_height"]) >= 2

        # ISSUE 18: the verification-fleet section is always present —
        # all-zero counter reads when no fleet exists, never a dial
        fl = st["fleet"]
        assert set(fl) >= {"client", "server"}
        assert set(fl["client"]) >= {
            "connected", "rtt_ewma_ms", "requests",
            "timeouts", "fallbacks", "rejoins",
        }
        assert set(fl["server"]) >= {
            "connections", "frames_accepted", "frames_rejected",
            "sigs", "verdicts_streamed", "dispatch_errors",
        }

        res = rpc.broadcast_tx_commit(b"rpckey=rpcval")
        assert res["deliver_tx"]["code"] == 0
        height = int(res["height"])
        assert height > 0

        # query on the SECOND node: the tx must have replicated
        nodes[1].wait_for_height(height, timeout=60)
        rpc2 = HTTPClient(nodes[1].rpc_server.listen_addr)
        q = rpc2.abci_query(path="/key", data=b"rpckey")
        import base64

        assert base64.b64decode(q["response"]["value"]) == b"rpcval"

        # block/commit/validators surface
        blk = rpc.block(height)
        assert int(blk["block"]["header"]["height"]) == height
        cm = rpc.commit(max(1, height - 1))
        assert cm["canonical"] is True
        vals = rpc.validators(1)
        assert int(vals["total"]) == 2
        tx_res = rpc.tx(__import__("hashlib").sha256(b"rpckey=rpcval").digest(), prove=True)
        assert int(tx_res["height"]) == height

    def test_net_info_and_misc_endpoints(self, two_node_net):
        nodes = two_node_net
        nodes[0].wait_for_height(1, timeout=60)
        rpc = HTTPClient(nodes[0].rpc_server.listen_addr)
        assert rpc.health() == {}
        ni = rpc.net_info()
        assert int(ni["n_peers"]) >= 1
        gen = rpc.genesis()
        assert gen["genesis"]["chain_id"] == CHAIN
        ai = rpc.abci_info()
        assert "kvstore" in ai["response"]["version"]
        bc = rpc.call("blockchain")
        assert int(bc["last_height"]) >= 1
        ucp = rpc.call("consensus_params")
        assert int(ucp["consensus_params"]["block"]["max_bytes"]) > 0

    def test_thread_dump_endpoint(self, two_node_net):
        """/thread_dump: the goroutine-dump equivalent `debug kill`
        captures — unsafe-gated (stack traces leak internals), and must
        include the consensus receive routine's stack when enabled."""
        nodes = two_node_net
        nodes[0].wait_for_height(1, timeout=60)
        rpc = HTTPClient(nodes[0].rpc_server.listen_addr)
        # gated off by default
        with pytest.raises(Exception):
            rpc.call("thread_dump")
        nodes[0].config.rpc.unsafe = True
        try:
            td = rpc.call("thread_dump")
            assert int(td["n_threads"]) >= 2
            stacks = "".join(s for t in td["threads"] for s in t["stack"])
            assert "_receive_routine" in stacks
        finally:
            nodes[0].config.rpc.unsafe = False


class TestHandshakeReplay:
    def test_app_restart_replays_blocks(self):
        """Kill the app (fresh instance), restart node: handshake replays
        committed blocks into the app (replay.go ReplayBlocks)."""
        sk = ed25519.gen_priv_key(bytes([5]) * 32)
        doc_json = GenesisDoc(
            chain_id=CHAIN,
            genesis_time=Timestamp(seconds=1_700_000_000),
            validators=[GenesisValidator(address=b"", pub_key=sk.pub_key(), power=10)],
        ).to_json()
        cfg = _make_config(0)
        cfg.p2p.laddr = "none"
        node = make_node(
            cfg,
            app=KVStoreApplication(),
            genesis=GenesisDoc.from_json(doc_json),
            priv_validator=FilePV(sk),
            node_key=NodeKey.generate(bytes([77]) * 32),
        )
        node.start()
        node.mempool.check_tx(b"persist=1")
        node.wait_for_height(3, timeout=60)
        node.stop()
        stored_height = node.block_store.height()

        # "restart": same stores, FRESH app instance at height 0
        from tendermint_tpu.consensus.replay import Handshaker
        from tendermint_tpu.abci import LocalClient
        from tendermint_tpu.abci import types as abci_t

        fresh_app = KVStoreApplication()
        conn = LocalClient(fresh_app)
        state = node.state_store.load()
        hs = Handshaker(node.state_store, state, node.block_store, node.genesis)
        new_state = hs.handshake(conn)
        assert hs.n_blocks_replayed >= stored_height - 1
        info = conn.info(abci_t.RequestInfo())
        assert info.last_block_height >= stored_height - 1
        # the replayed app has the tx
        q = conn.query(abci_t.RequestQuery(data=b"persist", path="/key"))
        assert q.value == b"1"


class TestNodeStartupModes:
    """node.go:217-247,323-343 startup-mode selection: a fresh node with
    statesync configured restores from a peer's snapshot, backfills, and
    switches to consensus; blocksync hands off to consensus when caught
    up (covered via TCP e2e in test_e2e_proc)."""

    def test_statesync_node_restores_and_joins(self):
        import time

        from tendermint_tpu.abci import KVStoreApplication
        from tendermint_tpu.config import Config
        from tendermint_tpu.consensus.state import ConsensusState  # noqa: F401
        from tendermint_tpu.crypto import ed25519
        from tendermint_tpu.node import make_node
        from tendermint_tpu.p2p import MemoryTransport, NodeKey, PeerAddress, new_memory_network
        from tendermint_tpu.types.genesis import GenesisDoc, GenesisValidator
        from tendermint_tpu.wire.canonical import Timestamp

        hub = new_memory_network()
        sk = ed25519.gen_priv_key(bytes([77]) * 32)
        doc = GenesisDoc(
            chain_id="ss-node-chain",
            genesis_time=Timestamp(seconds=1_700_000_000),
            validators=[GenesisValidator(address=b"", pub_key=sk.pub_key(), power=10)],
        )

        def node_cfg():
            cfg = Config()
            cfg.base.home = ""
            cfg.base.db_backend = "memdb"
            from tests.test_consensus import FAST

            cfg.consensus = FAST
            cfg.p2p.laddr = ""
            cfg.rpc.laddr = ""
            return cfg

        # validator node producing snapshots
        nk_a = NodeKey.generate(bytes([78]) * 32)
        from tendermint_tpu.privval import FilePV

        node_a = make_node(
            node_cfg(),
            # generous retention: the FAST test chain outruns the default
            # keep-3 window before the syncing node can fetch chunks
            app=KVStoreApplication(snapshot_interval=2, snapshot_keep=100),
            genesis=doc,
            priv_validator=FilePV(sk),
            node_key=nk_a,
            transport=MemoryTransport(hub, nk_a.node_id, nk_a.pub_key),
        )
        node_a.start()
        try:
            node_a.wait_for_height(6, timeout=60)
            # trust root: a snapshot height the serving node can prove
            snaps = node_a.proxy_app.list_snapshots().snapshots
            assert snaps
            snap_h = max(
                s.height for s in snaps
                if s.height + 2 <= node_a.block_store.height()
            )
            trust = node_a.statesync_reactor._load_local_light_block(snap_h)

            # fresh statesyncing node
            nk_b = NodeKey.generate(bytes([79]) * 32)
            cfg_b = node_cfg()
            cfg_b.statesync.enable = True
            cfg_b.statesync.trust_height = snap_h
            cfg_b.statesync.trust_hash = trust.hash().hex()
            cfg_b.statesync.discovery_time_ms = 1500
            node_b = make_node(
                cfg_b,
                app=KVStoreApplication(),
                genesis=doc,
                node_key=nk_b,
                transport=MemoryTransport(hub, nk_b.node_id, nk_b.pub_key),
            )
            node_b.router._pm.add_address(PeerAddress(nk_a.node_id, nk_a.node_id))
            node_a.router._pm.add_address(PeerAddress(nk_b.node_id, nk_b.node_id))
            node_b.start()
            try:
                deadline = time.time() + 90
                while time.time() < deadline:
                    if node_b.consensus.committed_state.last_block_height > snap_h:
                        break
                    time.sleep(0.2)
                st = node_b.consensus.committed_state
                assert st.last_block_height >= snap_h, (
                    st.last_block_height, snap_h
                )
                # discriminate REAL statesync from a consensus-catchup
                # fallback: only the sync path plants the params
                # checkpoint at the restored snapshot height (the syncer
                # picks the NEWEST advertised snapshot, at/above snap_h)
                restored_h = st.last_height_consensus_params_changed
                assert restored_h >= snap_h, (
                    "node fell back to consensus catchup instead of "
                    "restoring a snapshot"
                )
                # the restored header was planted in the block store
                assert node_b.block_store.load_block_meta(restored_h) is not None
            finally:
                node_b.stop()
        finally:
            node_a.stop()


def test_openapi_spec_covers_route_table():
    """rpc/openapi parity: the spec documents every mounted route (and
    nothing that isn't mounted, modulo the websocket pseudo-path)."""
    import os
    import re

    from tendermint_tpu.rpc.core import ROUTES, UNSAFE_ROUTES

    spec_path = os.path.join(
        os.path.dirname(__file__), "..", "tendermint_tpu", "rpc", "openapi.yaml"
    )
    text = open(spec_path).read()
    paths = set(re.findall(r"^  /([a-z_]+):", text, re.M))
    expected = set(ROUTES) | set(UNSAFE_ROUTES) | {"websocket"}
    assert paths == expected, (paths ^ expected)


def test_seed_node_pex_discovery():
    """node.go:428 makeSeedNode: a seed-mode node runs only the p2p layer
    (pex + address book). Two validators that each know ONLY the seed must
    discover each other through it and produce blocks together."""
    from tendermint_tpu.config import MODE_SEED
    from tendermint_tpu.p2p import PeerAddress

    sks = [ed25519.gen_priv_key(bytes([i + 31]) * 32) for i in range(2)]
    doc_json = GenesisDoc(
        chain_id=CHAIN,
        genesis_time=Timestamp(seconds=1_700_000_000),
        validators=[
            GenesisValidator(address=b"", pub_key=sk.pub_key(), power=10)
            for sk in sks
        ],
    ).to_json()

    seed_cfg = _make_config(9)
    seed_cfg.base.mode = MODE_SEED
    seed_cfg.p2p.pex = True
    seed = make_node(
        seed_cfg,
        app=KVStoreApplication(),
        genesis=GenesisDoc.from_json(doc_json),
        priv_validator=None,
        node_key=NodeKey.generate(bytes([91]) * 32),
        with_rpc=False,
    )
    assert seed.consensus_reactor is None  # seed runs no consensus gossip
    assert seed.pex_reactor is not None

    vals = []
    for i in range(2):
        cfg = _make_config(i)
        cfg.p2p.pex = True
        node = make_node(
            cfg,
            app=KVStoreApplication(),
            genesis=GenesisDoc.from_json(doc_json),
            priv_validator=FilePV(sks[i]),
            node_key=NodeKey.generate(bytes([i + 93]) * 32),
            with_rpc=False,
        )
        vals.append(node)
    # validators know ONLY the seed; the seed knows both (as a bootstrap
    # would after they dial in)
    for n in vals:
        n.router._pm.add_address(
            PeerAddress(seed.node_id, seed.router._transport.listen_addr),
            persistent=True,
        )
        seed.router._pm.add_address(
            PeerAddress(n.node_id, n.router._transport.listen_addr)
        )
    try:
        seed.start()
        for n in vals:
            n.start()
        # consensus requires the two validators to find EACH OTHER via
        # pex address exchange through the seed (2/3 of power = both)
        vals[0].wait_for_height(3, timeout=90)
        vals[1].wait_for_height(3, timeout=90)
        assert any(
            pid == vals[1].node_id for pid in vals[0].router.connected()
        ), "validators never learned each other's address via pex"
    finally:
        for n in vals:
            n.stop()
        seed.stop()
