"""Test harness: force the CPU backend with an 8-device virtual mesh so
multi-chip sharding (pjit/shard_map over a Mesh) is exercised without TPU
hardware. Mirrors the reference's "multi-node without a cluster" pattern
(in-memory p2p transport, SURVEY.md §4) at the device level.

The environment's sitecustomize registers a remote-TPU ("axon") PJRT
plugin at interpreter start and points JAX_PLATFORMS at it; backend
*initialization* is lazy, so flipping the jax_platforms config here —
before any jax.devices()/jit call — keeps the whole test session on the
in-process CPU backend (the remote chip is single-tenant and must stay
free for the benchmark driver).
"""

import os

os.environ["JAX_PLATFORMS"] = "cpu"
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    ).strip()

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

# The verify kernel is a large XLA program (~60s cold compile on one CPU
# core); persist compiled executables across test sessions. The cache is
# keyed per-machine (CPU feature tag) — loading another host's XLA:CPU
# AOT results risks SIGILL (tendermint_tpu.libs.jaxcache).
from tendermint_tpu.libs import jaxcache  # noqa: E402

jaxcache.enable(jax, os.path.abspath(os.path.join(os.path.dirname(__file__), "..")))

import pytest  # noqa: E402


def pytest_collection_modifyitems(config, items):
    """`native_required` tests skip cleanly where tm_native isn't built
    (pure-python containers without a toolchain) — the differential
    suites keep their pure-python halves running everywhere."""
    from tendermint_tpu.native import load as _load_native

    if _load_native() is None:
        skip = pytest.mark.skip(reason="tm_native module not built")
        for item in items:
            if "native_required" in item.keywords:
                item.add_marker(skip)

    # The end-to-end soak smokes are the most expensive subprocess items
    # in the suite; run them after everything else so a wall-clock-capped
    # CI run truncates the soak smokes, not the unit suites.
    items.sort(key=lambda it: it.fspath.basename == "test_soak_isolated.py")
