"""Test harness: force the CPU backend with an 8-device virtual mesh so
multi-chip sharding (pjit/shard_map over a Mesh) is exercised without TPU
hardware. Mirrors the reference's "multi-node without a cluster" pattern
(in-memory p2p transport, SURVEY.md §4) at the device level.

Must run before the first `import jax` anywhere in the test session.
"""

import os

os.environ.setdefault("JAX_PLATFORMS", "cpu")
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    ).strip()
