"""Async device verification pipeline (SURVEY.md §7 hard-part 4 /
BASELINE config #5): double-buffered batch submission, pipelined commit
verification, pipelined adjacent-header verification, and the blocksync
speculative pre-verify path."""

import numpy as np
import pytest

from tendermint_tpu.crypto import ed25519
from tendermint_tpu.libs import devcheck
from tendermint_tpu.ops import pipeline as pl
from tests.test_types import CHAIN_ID, build_commit, make_validators


@pytest.fixture(autouse=True)
def _devcheck_armed():
    """ISSUE 8: the whole pipeline suite runs with the runtime invariant
    checkers on — relay-thread assertions, lock-order cycle detection,
    and the write-after-resolve canary. Any violation fails the test
    that caused it at teardown."""
    devcheck.enable(reset=True)
    yield
    try:
        devcheck.check()
    finally:
        devcheck.reset_state()
        devcheck.disable()


def _entries(n, tag=0, bad=()):
    out = []
    for i in range(n):
        sk = ed25519.gen_priv_key(bytes([tag + 1]) * 31 + bytes([i + 1]))
        m = b"pipe-%d-%d" % (tag, i)
        s = sk.sign(m)
        if i in bad:
            s = s[:-1] + bytes([s[-1] ^ 1])
        out.append((sk.pub_key().bytes(), m, s))
    return out


class TestAsyncBatchVerifier:
    def test_overlapped_batches_resolve_in_order(self):
        v = pl.AsyncBatchVerifier(depth=2)
        try:
            futs = [v.submit(_entries(8, tag=t, bad=(3,) if t == 2 else ())) for t in range(5)]
            results = [f.result(timeout=120) for f in futs]
        finally:
            v.close()
        for t, res in enumerate(results):
            assert res.shape == (8,)
            if t == 2:
                assert not res[3] and res.sum() == 7
            else:
                assert res.all()

    def test_shared_verifier_is_singleton(self):
        assert pl.shared_verifier() is pl.shared_verifier()

    def test_poisoned_job_fails_alone_dispatcher_survives(self, monkeypatch):
        """ISSUE 6 satellite: a job whose kernel launch (or lazy
        epoch-table upload — same code path: inside the prepared callable
        on the dispatch-owner thread) raises must fail ONLY its own
        future, with epoch/bucket context, and the dispatcher must keep
        serving later jobs."""
        real_prepare = pl.AsyncBatchVerifier._prepare
        POISON_N = 3  # poisoned jobs are 3 entries long, healthy ones differ

        def prep(entries):
            f, args, rlc, bucket = real_prepare(entries)
            if len(entries) == POISON_N:
                def boom(*_a):
                    raise RuntimeError("epoch table upload exploded")

                return boom, args, rlc, bucket
            return f, args, rlc, bucket

        monkeypatch.setattr(
            pl.AsyncBatchVerifier, "_prepare", staticmethod(prep)
        )
        v = pl.AsyncBatchVerifier(depth=2)
        try:
            bad = v.submit(_entries(POISON_N, tag=9))
            with pytest.raises(pl.DispatchError) as ei:
                bad.result(timeout=120)
            assert "bucket=" in str(ei.value) and "epoch=" in str(ei.value)
            assert isinstance(ei.value.__cause__, RuntimeError)
            # the dispatcher must still be alive and serving
            assert v._dispatch_thread.is_alive()
            good = v.submit(_entries(8, tag=10))
            res = good.result(timeout=120)
            assert res.shape == (8,) and res.all()
            # and a second poisoned job again fails only itself
            bad2 = v.submit(_entries(POISON_N, tag=11))
            with pytest.raises(pl.DispatchError):
                bad2.result(timeout=120)
            good2 = v.submit(_entries(5, tag=12))
            assert good2.result(timeout=120).all()
            assert v._dispatch_thread.is_alive()
            assert v._resolve_thread.is_alive()
        finally:
            v.close()


class TestPipelinedCommits:
    def test_verify_commits_pipelined_mixed(self):
        jobs = []
        # 3 good commits + 1 with a tampered signature
        commits = [build_commit(n=4, height=10 + i, round_=0) for i in range(4)]
        for i, (sks, vset, block_id, commit) in enumerate(commits):
            if i == 2:
                cs = commit.signatures[1]
                sig = cs.signature[:-1] + bytes([cs.signature[-1] ^ 1])
                commit.signatures[1] = type(cs)(
                    block_id_flag=cs.block_id_flag,
                    validator_address=cs.validator_address,
                    timestamp=cs.timestamp,
                    signature=sig,
                )
            jobs.append((vset, block_id, 10 + i, commit))
        errors = pl.verify_commits_pipelined(CHAIN_ID, jobs)
        assert errors[0] is None and errors[1] is None and errors[3] is None
        assert errors[2] is not None and "signature" in errors[2]

    def test_not_enough_power_reported(self):
        sks, vset, block_id, commit = build_commit(n=4, height=5, round_=0)
        # keep only one signature: power 100/400 < 2/3
        from tendermint_tpu.types.block import CommitSig

        commit.signatures = [
            commit.signatures[0],
            CommitSig.absent(), CommitSig.absent(), CommitSig.absent(),
        ]
        errors = pl.verify_commits_pipelined(CHAIN_ID, [(vset, block_id, 5, commit)])
        assert errors[0] is not None and "power" in errors[0].lower()


class TestPipelinedHeaders:
    def _make_chain(self, n_headers, n_vals=4):
        """A synthetic adjacent header chain signed by one validator set."""
        from dataclasses import replace

        from tendermint_tpu.types import SignedHeader
        from tendermint_tpu.types.block import BlockID, Header, PartSetHeader, Version
        from tendermint_tpu.types.vote import PRECOMMIT_TYPE
        from tendermint_tpu.types.vote_set import VoteSet
        from tendermint_tpu.wire.canonical import Timestamp
        from tests.test_types import sign_vote

        sks, vset = make_validators(n_vals)
        headers = []
        prev_hash = b"\x00" * 32
        shs = []
        for h in range(1, n_headers + 2):
            hdr = Header(
                version=Version(block=11, app=0),
                chain_id=CHAIN_ID,
                height=h,
                time=Timestamp(seconds=1_600_000_000 + h),
                last_block_id=BlockID(
                    hash=prev_hash,
                    part_set_header=PartSetHeader(total=1, hash=prev_hash),
                ) if h > 1 else BlockID(),
                validators_hash=vset.hash(),
                next_validators_hash=vset.hash(),
                consensus_hash=b"\x01" * 32,
                app_hash=b"",
                proposer_address=vset.validators[0].address,
            )
            bid = BlockID(
                hash=hdr.hash(),
                part_set_header=PartSetHeader(total=1, hash=hdr.hash()),
            )
            vs = VoteSet(CHAIN_ID, h, 0, PRECOMMIT_TYPE, vset)
            for sk in sks:
                vs.add_vote(sign_vote(sk, vset, PRECOMMIT_TYPE, h, 0, bid))
            shs.append((SignedHeader(header=hdr, commit=vs.make_commit()), vset))
            prev_hash = hdr.hash()
        return shs

    def test_adjacent_range_pipelined(self):
        shs = self._make_chain(6)
        trusted = shs[0][0]
        pl.verify_headers_pipelined(CHAIN_ID, trusted, shs[1:])

    def test_adjacent_range_detects_broken_continuity(self):
        shs = self._make_chain(4)
        trusted = shs[0][0]
        # skip one header -> not adjacent
        with pytest.raises(ValueError, match="adjacent"):
            pl.verify_headers_pipelined(CHAIN_ID, trusted, shs[2:])

    def test_adjacent_range_detects_bad_signature(self):
        shs = self._make_chain(4)
        trusted = shs[0][0]
        sh, vset = shs[2]
        cs = sh.commit.signatures[0]
        sh.commit.signatures[0] = type(cs)(
            block_id_flag=cs.block_id_flag,
            validator_address=cs.validator_address,
            timestamp=cs.timestamp,
            signature=cs.signature[:-1] + bytes([cs.signature[-1] ^ 1]),
        )
        with pytest.raises(ValueError, match="signature|power"):
            pl.verify_headers_pipelined(CHAIN_ID, trusted, shs[1:])


class TestBlocksyncSpeculation:
    def test_fresh_node_catches_up_with_speculative_verify(self, monkeypatch):
        """The blocksync pipelined path: force the speculation gate open so
        every block's commit pre-verifies through the device pipeline."""
        from tendermint_tpu.ops import backend as _backend

        monkeypatch.setattr(_backend, "DEVICE_THRESHOLD", 0)
        import tests.test_light_blocksync as tlb

        # reuse the existing blocksync e2e with the speculation gate open,
        # building the source chain inline (same as its produced_chain fixture)
        inst = tlb.TestBlockSync()
        sk = ed25519.gen_priv_key(bytes([7]) * 32)
        cs, bstore, _ = tlb.make_node([sk], 0)
        cs.start()
        try:
            cs.wait_for_height(5, timeout=60)
        finally:
            cs.stop()
        inst.test_fresh_node_catches_up((cs, bstore))
