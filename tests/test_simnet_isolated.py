"""Tier-1 simnet coverage for containers without the `cryptography` wheel.

Two layers:
  1. Crypto-free unit tests of the simulation substrate (virtual clock,
     event ordering, link fault model, partitions, fault-schedule
     parsing) — these run in the MAIN pytest process: simnet's
     clock/transport layer imports without any signer.
  2. Subprocess runs of the signer-needing end-to-end suites
     (tests/test_simnet.py and tools/simnet_run.py --smoke) under
     TM_TPU_PUREPY_CRYPTO=1. The env flag must NOT be set in the main
     process — pytest collects all modules in one interpreter and the
     flag would unlock slow OpenSSL-dependent paths suite-wide (same
     pattern as tests/test_entry_block_isolated.py).
"""

import importlib.util
import json
import os
import subprocess
import sys

import pytest

from tendermint_tpu.simnet.clock import NodeClock, SimClock
from tendermint_tpu.simnet.faults import Fault, parse_faults, smoke_schedule
from tendermint_tpu.simnet.transport import Envelope, LinkConfig, SimNetwork, SimRouter

HERE = os.path.dirname(os.path.abspath(__file__))
REPO = os.path.dirname(HERE)


class TestSimClock:
    def test_events_fire_in_time_order_with_stable_ties(self):
        clk = SimClock(seed=0, start=0.0)
        order = []
        clk.call_later(2.0, lambda: order.append("b"))
        clk.call_later(1.0, lambda: order.append("a"))
        clk.call_later(2.0, lambda: order.append("c"))  # same time as b: FIFO
        clk.call_later(3.0, lambda: order.append("d"))
        clk.run_until()
        assert order == ["a", "b", "c", "d"]
        assert clk.time() == 3.0

    def test_cancel_and_deadline(self):
        clk = SimClock(seed=0, start=0.0)
        fired = []
        t = clk.call_later(1.0, lambda: fired.append(1))
        clk.call_later(5.0, lambda: fired.append(2))
        t.cancel()
        clk.run_until(deadline=2.0)
        assert fired == []
        assert clk.time() == 2.0
        clk.run_until()
        assert fired == [2]

    def test_callbacks_can_schedule_more_events(self):
        clk = SimClock(seed=0, start=0.0)
        seen = []

        def tick(n):
            seen.append(n)
            if n < 3:
                clk.call_later(1.0, lambda: tick(n + 1))

        clk.call_later(1.0, lambda: tick(0))
        assert clk.run_until(predicate=lambda: len(seen) == 4)
        assert seen == [0, 1, 2, 3]
        assert clk.time() == 4.0

    def test_same_seed_same_rng_stream(self):
        a = [SimClock(seed=5).rng.random() for _ in range(8)]
        b = [SimClock(seed=5).rng.random() for _ in range(8)]
        c = [SimClock(seed=6).rng.random() for _ in range(8)]
        assert a == b
        assert a != c

    def test_node_clock_skew_shifts_reads_not_delays(self):
        clk = SimClock(seed=0, start=100.0)
        nc = NodeClock(clk, skew=2.5)
        assert nc.time() == 102.5
        fired = []
        nc.call_later(1.0, lambda: fired.append(clk.time()))
        clk.run_until()
        assert fired == [101.0]  # delay unaffected by skew


def _net(seed=0, link=None):
    clk = SimClock(seed=seed, start=0.0)
    net = SimNetwork(clk, default_link=link or LinkConfig(latency_s=0.01))
    inboxes = {}
    for nid in ("a", "b", "c"):
        SimRouter(net, nid)
        inboxes[nid] = []
        net.set_receiver(nid, lambda env, n=nid: inboxes[n].append(env))
    return clk, net, inboxes


class TestSimNetwork:
    def test_unicast_and_broadcast_delivery(self):
        clk, net, inboxes = _net()
        net.route("a", Envelope(to_id="b", channel_id=7, message=b"x"))
        net.route("a", Envelope(channel_id=7, message=b"y", broadcast=True))
        clk.run_until()
        assert [e.message for e in inboxes["b"]] == [b"x", b"y"]
        assert [e.message for e in inboxes["c"]] == [b"y"]
        assert inboxes["a"] == []  # broadcast never loops back
        assert net.delivered == 3

    def test_partition_blocks_and_heals(self):
        clk, net, inboxes = _net()
        net.set_partition([["a", "b"], ["c"]])
        net.route("a", Envelope(to_id="c", channel_id=1, message=b"1"))
        net.route("a", Envelope(to_id="b", channel_id=1, message=b"2"))
        clk.run_until()
        assert inboxes["c"] == []
        assert [e.message for e in inboxes["b"]] == [b"2"]
        net.heal_partition()
        net.route("a", Envelope(to_id="c", channel_id=1, message=b"3"))
        clk.run_until()
        assert [e.message for e in inboxes["c"]] == [b"3"]

    def test_partition_eats_in_flight_messages(self):
        clk, net, inboxes = _net()
        net.route("a", Envelope(to_id="c", channel_id=1, message=b"mid-flight"))
        net.set_partition([["a", "b"], ["c"]])  # applied before delivery time
        clk.run_until()
        assert inboxes["c"] == []
        assert net.dropped >= 1

    def test_down_node_sends_and_receives_nothing(self):
        clk, net, inboxes = _net()
        net.set_down("b")
        net.route("a", Envelope(to_id="b", channel_id=1, message=b"x"))
        net.route("b", Envelope(to_id="a", channel_id=1, message=b"y"))
        clk.run_until()
        assert inboxes["b"] == [] and inboxes["a"] == []

    def test_drop_and_duplicate_probabilities(self):
        link = LinkConfig(latency_s=0.001, drop=0.5)
        clk, net, inboxes = _net(seed=1, link=link)
        for i in range(100):
            net.route("a", Envelope(to_id="b", channel_id=1, message=b"%d" % i))
        clk.run_until()
        assert 20 < len(inboxes["b"]) < 80  # ~50 expected, seeded
        link2 = LinkConfig(latency_s=0.001, duplicate=1.0)
        clk2, net2, inboxes2 = _net(seed=2, link=link2)
        net2.route("a", Envelope(to_id="b", channel_id=1, message=b"x"))
        clk2.run_until()
        assert len(inboxes2["b"]) == 2

    def test_bandwidth_cap_serializes_link(self):
        # 1000 bytes at 10_000 B/s -> 0.1s per message of queueing
        link = LinkConfig(latency_s=0.0, bandwidth_bps=10_000)
        clk, net, inboxes = _net(seed=0, link=link)
        times = []
        net.set_receiver("b", lambda env: times.append(clk.time()))
        for _ in range(3):
            net.route("a", Envelope(to_id="b", channel_id=1, message=b"z" * 1000))
        clk.run_until()
        assert len(times) == 3
        assert times[0] == pytest.approx(0.1, abs=1e-6)
        assert times[2] == pytest.approx(0.3, abs=1e-6)

    def test_schedule_digest_tracks_order(self):
        clk, net, _ = _net(seed=3, link=LinkConfig(latency_s=0.01, jitter_s=0.05))
        for i in range(20):
            net.route("a", Envelope(to_id="b", channel_id=1, message=b"%d" % i))
        clk.run_until()
        d1 = net.schedule_digest()
        clk2, net2, _ = _net(seed=3, link=LinkConfig(latency_s=0.01, jitter_s=0.05))
        for i in range(20):
            net2.route("a", Envelope(to_id="b", channel_id=1, message=b"%d" % i))
        clk2.run_until()
        assert net2.schedule_digest() == d1
        clk3, net3, _ = _net(seed=4, link=LinkConfig(latency_s=0.01, jitter_s=0.05))
        for i in range(20):
            net3.route("a", Envelope(to_id="b", channel_id=1, message=b"%d" % i))
        clk3.run_until()
        assert net3.schedule_digest() != d1


class TestFaultSchedules:
    def test_parse_roundtrip_and_validation(self):
        raw = [
            {"kind": "partition", "at_height": 5, "groups": [[0, 1], [2, 3]],
             "duration": 2.0},
            {"kind": "crash", "at_height": 8, "node": 2, "restart_after": 1.0},
            {"kind": "double_sign", "node": 3},
        ]
        faults = parse_faults(raw)
        assert [f.kind for f in faults] == ["partition", "crash", "double_sign"]
        for f in faults:
            f.validate(4)
        with pytest.raises(ValueError):
            parse_faults([{"kind": "crash", "at_height": 1, "node": 0, "bogus": 1}])
        with pytest.raises(ValueError):
            Fault(kind="partition", at_time=0.0).validate(4)

    def test_smoke_schedule_shape(self):
        sched = smoke_schedule(4)
        kinds = [f.kind for f in sched]
        assert kinds == ["partition", "crash"]
        assert sched[0].duration is not None
        assert sched[1].restart_after is not None


def _purepy_env():
    return dict(os.environ, TM_TPU_PUREPY_CRYPTO="1", JAX_PLATFORMS="cpu")


def test_simnet_suite_under_purepy_fallback():
    """Re-run tests/test_simnet.py in a subprocess where the pure-Python
    signer can be enabled without leaking into this interpreter."""
    try:
        import cryptography  # noqa: F401

        pytest.skip("cryptography present; test_simnet runs directly")
    except ModuleNotFoundError:
        pass
    r = subprocess.run(
        [
            sys.executable, "-m", "pytest",
            os.path.join(HERE, "test_simnet.py"),
            "-q", "-m", "not slow", "-p", "no:cacheprovider",
        ],
        capture_output=True,
        env=_purepy_env(),
        cwd=REPO,
        timeout=700,
    )
    tail = (r.stdout or b"").decode(errors="replace")[-3000:]
    assert r.returncode == 0, f"isolated test_simnet run failed:\n{tail}"


def test_smoke_cli_partition_heal_crash_restart():
    """The acceptance gate: `simnet_run.py --smoke` — 4 nodes, partition
    + heal + crash/WAL-restart at a fixed seed, height >= 20, two runs
    with identical fingerprints — on CPU, without the OpenSSL wheel,
    in well under 60s."""
    r = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "simnet_run.py"), "--smoke"],
        capture_output=True,
        env=_purepy_env(),
        cwd=REPO,
        timeout=60,
    )
    out = (r.stdout or b"").decode(errors="replace")
    assert r.returncode == 0, f"smoke run failed:\n{out[-3000:]}"
    verdict = json.loads(out)
    assert verdict["ok"] is True
    assert verdict["replay_exact"] is True
    assert verdict["height"] >= 20
    assert verdict["violations"] == []
    assert "partition" in verdict["faults"] and "crash" in verdict["faults"]


# keep the importable surface honest: these names must exist without any
# crypto wheel for the unit layer above to be tier-1-safe
assert importlib.util.find_spec("tendermint_tpu.simnet.clock") is not None
