"""Tier-1 simnet coverage for containers without the `cryptography` wheel.

Two layers:
  1. Crypto-free unit tests of the simulation substrate (virtual clock,
     event ordering, link fault model, partitions, fault-schedule
     parsing) — these run in the MAIN pytest process: simnet's
     clock/transport layer imports without any signer.
  2. Subprocess runs of the signer-needing end-to-end suites
     (tests/test_simnet.py and tools/simnet_run.py --smoke) under
     TM_TPU_PUREPY_CRYPTO=1. The env flag must NOT be set in the main
     process — pytest collects all modules in one interpreter and the
     flag would unlock slow OpenSSL-dependent paths suite-wide (same
     pattern as tests/test_entry_block_isolated.py).
"""

import dataclasses
import importlib.util
import json
import os
import subprocess
import sys

import pytest

from tendermint_tpu.simnet.clock import NodeClock, SimClock
from tendermint_tpu.simnet.faults import (
    Fault,
    parse_faults,
    rotation_schedule,
    smoke_schedule,
)
from tendermint_tpu.simnet.transport import Envelope, LinkConfig, SimNetwork, SimRouter

HERE = os.path.dirname(os.path.abspath(__file__))
REPO = os.path.dirname(HERE)


class TestSimClock:
    def test_events_fire_in_time_order_with_stable_ties(self):
        clk = SimClock(seed=0, start=0.0)
        order = []
        clk.call_later(2.0, lambda: order.append("b"))
        clk.call_later(1.0, lambda: order.append("a"))
        clk.call_later(2.0, lambda: order.append("c"))  # same time as b: FIFO
        clk.call_later(3.0, lambda: order.append("d"))
        clk.run_until()
        assert order == ["a", "b", "c", "d"]
        assert clk.time() == 3.0

    def test_cancel_and_deadline(self):
        clk = SimClock(seed=0, start=0.0)
        fired = []
        t = clk.call_later(1.0, lambda: fired.append(1))
        clk.call_later(5.0, lambda: fired.append(2))
        t.cancel()
        clk.run_until(deadline=2.0)
        assert fired == []
        assert clk.time() == 2.0
        clk.run_until()
        assert fired == [2]

    def test_callbacks_can_schedule_more_events(self):
        clk = SimClock(seed=0, start=0.0)
        seen = []

        def tick(n):
            seen.append(n)
            if n < 3:
                clk.call_later(1.0, lambda: tick(n + 1))

        clk.call_later(1.0, lambda: tick(0))
        assert clk.run_until(predicate=lambda: len(seen) == 4)
        assert seen == [0, 1, 2, 3]
        assert clk.time() == 4.0

    def test_same_seed_same_rng_stream(self):
        a = [SimClock(seed=5).rng.random() for _ in range(8)]
        b = [SimClock(seed=5).rng.random() for _ in range(8)]
        c = [SimClock(seed=6).rng.random() for _ in range(8)]
        assert a == b
        assert a != c

    def test_node_clock_skew_shifts_reads_not_delays(self):
        clk = SimClock(seed=0, start=100.0)
        nc = NodeClock(clk, skew=2.5)
        assert nc.time() == 102.5
        fired = []
        nc.call_later(1.0, lambda: fired.append(clk.time()))
        clk.run_until()
        assert fired == [101.0]  # delay unaffected by skew


def _net(seed=0, link=None):
    clk = SimClock(seed=seed, start=0.0)
    net = SimNetwork(clk, default_link=link or LinkConfig(latency_s=0.01))
    inboxes = {}
    for nid in ("a", "b", "c"):
        SimRouter(net, nid)
        inboxes[nid] = []
        net.set_receiver(nid, lambda env, n=nid: inboxes[n].append(env))
    return clk, net, inboxes


class TestSimNetwork:
    def test_unicast_and_broadcast_delivery(self):
        clk, net, inboxes = _net()
        net.route("a", Envelope(to_id="b", channel_id=7, message=b"x"))
        net.route("a", Envelope(channel_id=7, message=b"y", broadcast=True))
        clk.run_until()
        assert [e.message for e in inboxes["b"]] == [b"x", b"y"]
        assert [e.message for e in inboxes["c"]] == [b"y"]
        assert inboxes["a"] == []  # broadcast never loops back
        assert net.delivered == 3

    def test_partition_blocks_and_heals(self):
        clk, net, inboxes = _net()
        net.set_partition([["a", "b"], ["c"]])
        net.route("a", Envelope(to_id="c", channel_id=1, message=b"1"))
        net.route("a", Envelope(to_id="b", channel_id=1, message=b"2"))
        clk.run_until()
        assert inboxes["c"] == []
        assert [e.message for e in inboxes["b"]] == [b"2"]
        net.heal_partition()
        net.route("a", Envelope(to_id="c", channel_id=1, message=b"3"))
        clk.run_until()
        assert [e.message for e in inboxes["c"]] == [b"3"]

    def test_partition_eats_in_flight_messages(self):
        clk, net, inboxes = _net()
        net.route("a", Envelope(to_id="c", channel_id=1, message=b"mid-flight"))
        net.set_partition([["a", "b"], ["c"]])  # applied before delivery time
        clk.run_until()
        assert inboxes["c"] == []
        assert net.dropped >= 1

    def test_down_node_sends_and_receives_nothing(self):
        clk, net, inboxes = _net()
        net.set_down("b")
        net.route("a", Envelope(to_id="b", channel_id=1, message=b"x"))
        net.route("b", Envelope(to_id="a", channel_id=1, message=b"y"))
        clk.run_until()
        assert inboxes["b"] == [] and inboxes["a"] == []

    def test_drop_and_duplicate_probabilities(self):
        link = LinkConfig(latency_s=0.001, drop=0.5)
        clk, net, inboxes = _net(seed=1, link=link)
        for i in range(100):
            net.route("a", Envelope(to_id="b", channel_id=1, message=b"%d" % i))
        clk.run_until()
        assert 20 < len(inboxes["b"]) < 80  # ~50 expected, seeded
        link2 = LinkConfig(latency_s=0.001, duplicate=1.0)
        clk2, net2, inboxes2 = _net(seed=2, link=link2)
        net2.route("a", Envelope(to_id="b", channel_id=1, message=b"x"))
        clk2.run_until()
        assert len(inboxes2["b"]) == 2

    def test_bandwidth_cap_serializes_link(self):
        # 1000 bytes at 10_000 B/s -> 0.1s per message of queueing
        link = LinkConfig(latency_s=0.0, bandwidth_bps=10_000)
        clk, net, inboxes = _net(seed=0, link=link)
        times = []
        net.set_receiver("b", lambda env: times.append(clk.time()))
        for _ in range(3):
            net.route("a", Envelope(to_id="b", channel_id=1, message=b"z" * 1000))
        clk.run_until()
        assert len(times) == 3
        assert times[0] == pytest.approx(0.1, abs=1e-6)
        assert times[2] == pytest.approx(0.3, abs=1e-6)

    def test_schedule_digest_tracks_order(self):
        clk, net, _ = _net(seed=3, link=LinkConfig(latency_s=0.01, jitter_s=0.05))
        for i in range(20):
            net.route("a", Envelope(to_id="b", channel_id=1, message=b"%d" % i))
        clk.run_until()
        d1 = net.schedule_digest()
        clk2, net2, _ = _net(seed=3, link=LinkConfig(latency_s=0.01, jitter_s=0.05))
        for i in range(20):
            net2.route("a", Envelope(to_id="b", channel_id=1, message=b"%d" % i))
        clk2.run_until()
        assert net2.schedule_digest() == d1
        clk3, net3, _ = _net(seed=4, link=LinkConfig(latency_s=0.01, jitter_s=0.05))
        for i in range(20):
            net3.route("a", Envelope(to_id="b", channel_id=1, message=b"%d" % i))
        clk3.run_until()
        assert net3.schedule_digest() != d1


class TestFaultSchedules:
    def test_parse_roundtrip_and_validation(self):
        raw = [
            {"kind": "partition", "at_height": 5, "groups": [[0, 1], [2, 3]],
             "duration": 2.0},
            {"kind": "crash", "at_height": 8, "node": 2, "restart_after": 1.0},
            {"kind": "double_sign", "node": 3},
        ]
        faults = parse_faults(raw)
        assert [f.kind for f in faults] == ["partition", "crash", "double_sign"]
        for f in faults:
            f.validate(4)
        with pytest.raises(ValueError):
            parse_faults([{"kind": "crash", "at_height": 1, "node": 0, "bogus": 1}])
        with pytest.raises(ValueError):
            Fault(kind="partition", at_time=0.0).validate(4)

    def test_smoke_schedule_shape(self):
        sched = smoke_schedule(4)
        kinds = [f.kind for f in sched]
        assert kinds == ["partition", "crash"]
        assert sched[0].duration is not None
        assert sched[1].restart_after is not None

    def test_valset_fault_kinds_validate(self):
        Fault(kind="val_join", at_height=5, node=4, power=10).validate(6)
        Fault(kind="val_leave", at_height=5, node=1).validate(6)
        Fault(kind="val_power", at_height=5, node=0, power=7).validate(6)
        with pytest.raises(ValueError, match="power"):
            Fault(kind="val_join", at_height=5, node=4).validate(6)
        with pytest.raises(ValueError, match="power"):
            Fault(kind="val_power", at_height=5, node=0, power=0).validate(6)
        with pytest.raises(ValueError, match="node"):
            Fault(kind="val_join", at_height=5, node=9, power=10).validate(6)

    def test_validation_tightened(self):
        """ISSUE 6 satellite: mutually exclusive triggers, kind-scoped
        optional fields."""
        with pytest.raises(ValueError, match="mutually exclusive"):
            Fault(
                kind="crash", at_height=3, at_time=1.0, node=0,
            ).validate(4)
        with pytest.raises(ValueError, match="restart_after"):
            Fault(
                kind="clock_skew", at_height=3, node=0, restart_after=1.0,
            ).validate(4)
        with pytest.raises(ValueError, match="duration"):
            Fault(
                kind="crash", at_height=3, node=0, duration=1.0,
            ).validate(4)
        with pytest.raises(ValueError, match="power only"):
            Fault(kind="crash", at_height=3, node=0, power=5).validate(4)
        # the valid forms still pass
        Fault(kind="partition", at_height=3, groups=[[0], [1, 2, 3]],
              duration=2.0).validate(4)
        Fault(kind="crash", at_height=3, node=0, restart_after=1.0).validate(4)

    def test_to_dict_minimal_and_roundtrip(self):
        f = Fault(kind="val_join", at_height=5, node=4, power=10)
        d = f.to_dict()
        assert d == {"kind": "val_join", "at_height": 5, "node": 4, "power": 10}
        assert parse_faults([d]) == [f]

    def test_rotation_schedule_membership_and_power_modes(self):
        sched = rotation_schedule(6, 4, every=4, start=3, until=12)
        assert [f.kind for f in sched] == ["val_join", "val_leave"] * 3
        # joiners are standbys first, then cycled-out validators
        assert [f.node for f in sched if f.kind == "val_join"] == [4, 5, 0]
        assert [f.node for f in sched if f.kind == "val_leave"] == [0, 1, 2]
        for f in sched:
            f.validate(6)
        # no standbys -> power churn, each still a structural change
        sched2 = rotation_schedule(4, 4, every=5, start=3, until=13)
        assert all(f.kind == "val_power" for f in sched2)
        assert len({f.power for f in sched2}) == len(sched2)


class TestSearchUnit:
    """Crypto-free layer of the schedule-search engine: generator
    determinism and shrink logic (cluster-backed search runs live in
    tests/test_simnet.py via the subprocess runner)."""

    def test_generators_are_seed_deterministic(self):
        import random

        from tendermint_tpu.simnet.search import GENERATORS

        for name, gen in GENERATORS.items():
            f1, l1 = gen(random.Random(f"{name}:5"), 8, 6)
            f2, l2 = gen(random.Random(f"{name}:5"), 8, 6)
            f3, l3 = gen(random.Random(f"{name}:6"), 8, 6)
            assert [f.to_dict() for f in f1] == [f.to_dict() for f in f2]
            assert l1 == l2
            assert f1, f"{name} generated an empty schedule"
            for f in f1:
                f.validate(8)
            # different seeds must actually explore different schedules
            assert (
                [f.to_dict() for f in f1] != [f.to_dict() for f in f3]
                or l1 != l3
            )

    def test_shrink_drops_irrelevant_faults(self):
        from tendermint_tpu.simnet.search import shrink_schedule

        poison = Fault(kind="crash", at_height=5, node=0, restart_after=1.0)
        noise = [
            Fault(kind="clock_skew", at_height=2, node=1, skew=0.3),
            Fault(kind="partition", at_height=3, groups=[[0], [1, 2, 3]],
                  duration=1.0),
            Fault(kind="double_sign", at_height=4, node=2),
        ]
        sched = [noise[0], poison, noise[1], noise[2]]
        runs = {"n": 0}

        def still_fails(cand):
            runs["n"] += 1
            return poison in cand

        minimal, used = shrink_schedule(sched, still_fails)
        assert minimal == [poison]
        assert used == runs["n"] <= 12

    def test_shrink_respects_budget(self):
        from tendermint_tpu.simnet.search import shrink_schedule

        sched = [
            Fault(kind="clock_skew", at_height=i + 2, node=0, skew=0.1)
            for i in range(6)
        ]
        minimal, used = shrink_schedule(sched, lambda cand: True, max_runs=3)
        assert used <= 3
        assert len(minimal) >= len(sched) - 3

    def test_scenario_emit_load_roundtrip(self, tmp_path):
        from tendermint_tpu.simnet.search import emit_scenario, load_scenario

        failure = {
            "generator": "mixed",
            "seed": 9,
            "reason": "height 10 not reached",
            "minimal": [
                {"kind": "partition", "at_height": 7,
                 "groups": [[0], [1, 2, 3]], "duration": 1.5},
            ],
            "link": dataclasses.asdict(LinkConfig(drop=0.05)),
            "n_nodes": 4,
            "n_validators": 4,
            "height": 10,
        }
        path = emit_scenario(str(tmp_path), failure)
        kw = load_scenario(path)
        assert kw["seed"] == 9 and kw["n_nodes"] == 4
        assert kw["faults"][0].kind == "partition"
        assert kw["link"].drop == 0.05


def _purepy_env():
    return dict(os.environ, TM_TPU_PUREPY_CRYPTO="1", JAX_PLATFORMS="cpu")


def test_simnet_suite_under_purepy_fallback():
    """Re-run tests/test_simnet.py in a subprocess where the pure-Python
    signer can be enabled without leaking into this interpreter."""
    try:
        import cryptography  # noqa: F401

        pytest.skip("cryptography present; test_simnet runs directly")
    except ModuleNotFoundError:
        pass
    r = subprocess.run(
        [
            sys.executable, "-m", "pytest",
            os.path.join(HERE, "test_simnet.py"),
            "-q", "-m", "not slow", "-p", "no:cacheprovider",
        ],
        capture_output=True,
        env=_purepy_env(),
        cwd=REPO,
        timeout=700,
    )
    tail = (r.stdout or b"").decode(errors="replace")[-3000:]
    assert r.returncode == 0, f"isolated test_simnet run failed:\n{tail}"


def test_mini_search_sweep_green_and_replay_exact():
    """ISSUE 6 satellite: a fixed-seed mini sweep (5 seeds x 2 generators,
    8 nodes, to h>=10) through the search engine must come back green on
    the fixed build, and re-running one (generator, seed) cell must be
    replay-exact — regression-guarding the schedule generators themselves
    (a generator drift would move every downstream search)."""
    code = r"""
import json, sys
from tendermint_tpu.simnet.search import search_schedules
res = search_schedules(
    list(range(5)), generators=("mixed", "churn"), n_nodes=8,
    n_validators=6, height=10, max_virtual_s=180.0, max_wall_s=30.0,
    shrink=False,
)
rerun = search_schedules(
    [0], generators=("churn",), n_nodes=8, n_validators=6, height=10,
    max_virtual_s=180.0, max_wall_s=30.0, shrink=False,
)
first_churn = next(r for r in res.runs if r["generator"] == "churn" and r["seed"] == 0)
print(json.dumps({
    "ok": res.ok,
    "n_runs": len(res.runs),
    "all_ok": all(r["ok"] for r in res.runs),
    "replay_exact": (
        rerun.runs[0]["fingerprint"] == first_churn["fingerprint"]
        and rerun.runs[0]["faults"] == first_churn["faults"]
    ),
    "reasons": [r["reason"] for r in res.runs if not r["ok"]],
}))
"""
    r = subprocess.run(
        [sys.executable, "-c", code],
        capture_output=True,
        env=_purepy_env(),
        cwd=REPO,
        timeout=600,
    )
    out = (r.stdout or b"").decode(errors="replace")
    assert r.returncode == 0, (
        f"mini sweep crashed:\n{(r.stderr or b'').decode(errors='replace')[-3000:]}"
    )
    verdict = json.loads(out.strip().splitlines()[-1])
    assert verdict["ok"] and verdict["all_ok"], verdict
    assert verdict["n_runs"] == 10
    assert verdict["replay_exact"], "generator or cluster replay drifted"


def test_smoke_cli_partition_heal_crash_restart():
    """The acceptance gate: `simnet_run.py --smoke` — 4 nodes, partition
    + heal + crash/WAL-restart at a fixed seed, height >= 20, two runs
    with identical fingerprints — on CPU, without the OpenSSL wheel,
    in well under 60s."""
    r = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "simnet_run.py"), "--smoke"],
        capture_output=True,
        env=_purepy_env(),
        cwd=REPO,
        timeout=60,
    )
    out = (r.stdout or b"").decode(errors="replace")
    assert r.returncode == 0, f"smoke run failed:\n{out[-3000:]}"
    verdict = json.loads(out)
    assert verdict["ok"] is True
    assert verdict["replay_exact"] is True
    assert verdict["height"] >= 20
    assert verdict["violations"] == []
    assert "partition" in verdict["faults"] and "crash" in verdict["faults"]


def test_devcheck_smoke_partition_heal_clean():
    """ISSUE 8 satellite: the 4-node partition+heal preset runs with the
    TM_TPU_DEVCHECK runtime checkers armed (relay-thread assertions,
    lock-order cycle detection, write-after-resolve canary, instrumented
    from process start via --devcheck) and must come back devcheck-clean
    — zero violations, with the lock instrumentation demonstrably live."""
    r = subprocess.run(
        [
            sys.executable, os.path.join(REPO, "tools", "simnet_run.py"),
            "--preset", "partition_heal", "--height", "10", "--devcheck",
        ],
        capture_output=True,
        env=_purepy_env(),
        cwd=REPO,
        timeout=120,
    )
    out = (r.stdout or b"").decode(errors="replace")
    assert r.returncode == 0, f"devcheck smoke failed:\n{out[-3000:]}"
    verdict = json.loads(out)
    assert verdict["ok"] is True
    assert verdict["height"] >= 10
    dc = verdict["devcheck"]
    assert dc["enabled"] is True
    assert dc["violations"] == []
    # the checkers must have actually been exercised, not just enabled
    assert dc["counts"]["lock_acquires"] > 0


# keep the importable surface honest: these names must exist without any
# crypto wheel for the unit layer above to be tier-1-safe
assert importlib.util.find_spec("tendermint_tpu.simnet.clock") is not None
