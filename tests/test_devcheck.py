"""libs/devcheck runtime invariant checkers (ISSUE 8).

Two layers, same pattern as the other _isolated suites:

- unit tests of the checkers themselves (lock-order cycle detection,
  write-after-resolve canary, relay ownership, zero-cost-off) run IN
  PROCESS — stdlib + numpy only, no jax, no crypto wheel;
- the injected-bug integration (TM_TPU_INJECT_LINTBUG=alias|owner driven
  through a REAL AsyncBatchVerifier with a mock kernel) needs the ops
  package, which imports the crypto seam — on containers without the
  wheel it re-runs in a purepy subprocess.

The injected-bug tests are the runtime half of the seeded-regression
requirement: re-introduce the PR-7 readback aliasing / a resolver-thread
relay touch and assert the matching checker FIRES — proving the canary
and the ownership assertion actually guard their bug class.
"""

import os
import subprocess
import sys
import threading

import numpy as np
import pytest

from tendermint_tpu.libs import devcheck


@pytest.fixture(autouse=True)
def _fresh_devcheck():
    was_on = devcheck.enabled()
    devcheck.enable(reset=True)
    yield
    devcheck.reset_state()
    if not was_on:
        devcheck.disable()


# ---------------------------------------------------------------------------
# units: lock-order cycle detector


class TestLockOrder:
    def test_consistent_order_is_clean(self):
        a, b = devcheck.DevLock("A"), devcheck.DevLock("B")
        for _ in range(3):
            with a:
                with b:
                    pass
        assert not devcheck.violations()

    def test_cycle_raises_and_records(self):
        a, b = devcheck.DevLock("A"), devcheck.DevLock("B")
        with a:
            with b:
                pass
        with pytest.raises(devcheck.DevcheckViolation) as ei:
            with b:
                with a:
                    pass
        assert "cycle" in str(ei.value)
        assert devcheck.violations()[0]["kind"] == "lock-order"

    def test_three_lock_cycle(self):
        a, b, c = (devcheck.DevLock(n) for n in "ABC")
        with a:
            with b:
                pass
        with b:
            with c:
                pass
        with pytest.raises(devcheck.DevcheckViolation):
            with c:
                with a:
                    pass

    def test_cycle_violation_releases_the_underlying_lock(self):
        # review fix: a raised violation must not leave the raw lock held
        # (the `with` never enters, so __exit__ never releases) — the
        # diagnostic must not CREATE the deadlock it reports
        a, b = devcheck.DevLock("A"), devcheck.DevLock("B")
        with a:
            with b:
                pass
        with pytest.raises(devcheck.DevcheckViolation):
            with b:
                with a:
                    pass
        assert a.acquire(blocking=False), "lock leaked by the violation"
        a.release()

    def test_bare_acquire_cycle_keeps_lock_held_for_caller(self):
        # contract (review fix): a BARE acquire() that raises the cycle
        # violation leaves the lock HELD — Condition._acquire_restore
        # (cv.wait's re-acquire) depends on owning the lock afterwards so
        # the enclosing `with cv:` __exit__ can release it
        a, b = devcheck.DevLock("A"), devcheck.DevLock("B")
        with a:
            with b:
                pass
        assert b.acquire()
        with pytest.raises(devcheck.DevcheckViolation):
            a.acquire()
        probe = []
        t = threading.Thread(
            target=lambda: probe.append(a._l.acquire(blocking=False)),
            daemon=True,
        )
        t.start()
        t.join(timeout=5)
        assert probe == [False], "bare-acquire violation must keep the lock held"
        a.release()
        b.release()

    def test_contested_inversion_raises_instead_of_hanging(self):
        # review fix: edges record at INTENT (before the blocking
        # acquire, serialized under the devcheck mutex), so a first-
        # contact AB/BA deadlock raises on one thread instead of wedging
        # both with no diagnostic
        a, b = devcheck.DevLock("A"), devcheck.DevLock("B")
        barrier = threading.Barrier(2, timeout=5)
        errs = []

        def one(first, second):
            with first:
                barrier.wait()
                try:
                    with second:
                        pass
                except devcheck.DevcheckViolation as e:
                    errs.append(e)

        t1 = threading.Thread(target=one, args=(a, b), daemon=True)
        t2 = threading.Thread(target=one, args=(b, a), daemon=True)
        t1.start()
        t2.start()
        t1.join(timeout=10)
        t2.join(timeout=10)
        assert not t1.is_alive() and not t2.is_alive(), "deadlock wedged"
        assert errs, "the inversion must be reported"
        assert devcheck.violations()[0]["kind"] == "lock-order"

    def test_same_name_nesting_is_not_a_self_cycle(self):
        # two INSTANCES of the same order class (e.g. two epoch entries)
        e1, e2 = devcheck.DevLock("epoch.entry"), devcheck.DevLock("epoch.entry")
        with e1:
            with e2:
                pass
        assert not devcheck.violations()

    def test_rlock_reentry_records_no_edge(self):
        r = devcheck.DevLock("R", reentrant=True)
        with r:
            with r:
                pass
        assert not devcheck.violations()
        assert devcheck.report()["lock_order_edges"] == 0

    def test_rlock_release_pairs_with_outermost_acquire(self):
        # review fix: the inner re-entry release must not pop the outer
        # stack entry — R is still held when X is taken, so R->X records
        r = devcheck.DevLock("R", reentrant=True)
        x = devcheck.DevLock("X")
        with r:
            with r:
                pass
            with x:
                pass
        assert devcheck.report()["lock_order_edges"] == 1

    def test_disable_between_acquire_and_release_pops_stack(self):
        # review fix: release pops unconditionally — disabling devcheck
        # mid-flight must not leave a stale held entry that manufactures
        # phantom order edges (and false cycles) for later tests
        a = devcheck.DevLock("A")
        a.acquire()
        devcheck.disable()
        a.release()
        devcheck.enable()
        b = devcheck.DevLock("B")
        with b:
            pass
        assert devcheck.report()["lock_order_edges"] == 0
        with b:
            with devcheck.DevLock("A"):
                pass  # B->A must be legal: no phantom A->B exists
        assert not devcheck.violations()

    def test_condition_wrapping_devlock(self):
        lk = devcheck.DevLock("cv.lock")
        cv = threading.Condition(lk)
        hit = []

        def waiter():
            with cv:
                cv.wait(timeout=5)
                hit.append(True)

        t = threading.Thread(target=waiter, daemon=True)
        t.start()
        import time

        time.sleep(0.1)
        with cv:
            cv.notify()
        t.join(timeout=5)
        assert hit and not devcheck.violations()

    def test_disabled_lock_is_plain(self):
        devcheck.disable()
        try:
            lk = devcheck.lock("x")
            assert not isinstance(lk, devcheck.DevLock)
        finally:
            devcheck.enable()

    def test_enabled_lock_is_instrumented(self):
        assert isinstance(devcheck.lock("x"), devcheck.DevLock)
        assert isinstance(devcheck.rlock("x"), devcheck.DevLock)


# ---------------------------------------------------------------------------
# units: write-after-resolve canary


class TestCanary:
    def test_stable_bytes_pass(self):
        arr = np.arange(16, dtype=np.uint8)
        devcheck.canary_register(arr, tag="t")
        assert devcheck.canary_sweep("here") == 0
        assert not devcheck.violations()

    def test_mutation_is_detected_once(self):
        buf = np.arange(16, dtype=np.uint8)
        view = buf[:]
        assert not view.flags.owndata
        devcheck.canary_register(view, tag="aliased")
        buf[3] ^= 0xFF
        assert devcheck.canary_sweep("sweep1") == 1
        v = devcheck.violations()
        assert v and v[0]["kind"] == "write-after-resolve"
        # entry dropped after detection: no duplicate reports
        assert devcheck.canary_sweep("sweep2") == 0

    def test_ring_bound(self):
        for i in range(200):
            devcheck.canary_register(np.full(4, i, dtype=np.uint8))
        assert devcheck.canary_sweep("x") == 0
        assert devcheck.report()["counts"]["canary_registered"] == 200

    def test_on_slot_release_sweeps(self):
        buf = np.arange(8, dtype=np.uint8)
        devcheck.canary_register(buf[:], tag="slot")
        buf[0] = 99
        devcheck.on_slot_release(())
        assert devcheck.violations()

    def test_non_ndarray_register_is_noop(self):
        devcheck.canary_register("not-an-array")
        assert devcheck.canary_sweep("x") == 0


# ---------------------------------------------------------------------------
# units: relay ownership


class TestRelayOwnership:
    def test_no_owner_means_direct_use_is_legal(self):
        devcheck.note_relay_touch("standalone")
        assert not devcheck.violations()

    def test_owner_thread_passes_others_raise(self):
        devcheck.claim_relay("me")
        devcheck.note_relay_touch("same-thread")  # owner: fine
        err = []

        def intruder():
            try:
                devcheck.note_relay_touch("other-thread")
            except devcheck.DevcheckViolation as e:
                err.append(e)

        t = threading.Thread(target=intruder, daemon=True)
        t.start()
        t.join(timeout=5)
        assert err and devcheck.violations()[0]["kind"] == "relay-ownership"

    def test_exempt_scope_passes(self):
        devcheck.claim_relay("owner")
        ok = []

        def sanctioned():
            with devcheck.exempt():
                devcheck.note_relay_touch("warmup")
            ok.append(True)

        t = threading.Thread(target=sanctioned, daemon=True)
        t.start()
        t.join(timeout=5)
        assert ok and not devcheck.violations()

    def test_zero_cost_off(self):
        devcheck.disable()
        try:
            devcheck.claim_relay("x")
            devcheck.note_relay_touch("y")
            devcheck.canary_register(np.zeros(4, dtype=np.uint8))
            assert devcheck.canary_sweep("z") == 0
            assert devcheck.report()["counts"]["relay_touches"] == 0
        finally:
            devcheck.enable()

    def test_check_raises_with_context(self):
        devcheck._violate("test-kind", "test message")
        with pytest.raises(devcheck.DevcheckViolation) as ei:
            devcheck.check()
        assert "test-kind" in str(ei.value)

    def test_unclaim_relay_retires_owner(self):
        # review fix: a closing verifier drops its dispatcher ident so
        # later standalone direct use stays legal and a recycled OS
        # thread ident cannot inherit the dead owner's pass
        devcheck.claim_relay("me")
        devcheck.unclaim_relay({threading.get_ident()})
        devcheck.note_relay_touch("after-close")  # no owners: legal
        assert not devcheck.violations()

    def test_inject_seams_require_devcheck_armed(self, monkeypatch):
        # review fix: a stale TM_TPU_INJECT_LINTBUG export with the
        # checkers OFF must stay inert (the seams corrupt verdicts)
        monkeypatch.setenv("TM_TPU_INJECT_LINTBUG", "alias")
        assert devcheck.inject_lintbug("alias")
        devcheck.disable()
        try:
            assert not devcheck.inject_lintbug("alias")
        finally:
            devcheck.enable()


# ---------------------------------------------------------------------------
# injected-bug integration: the REAL pipeline must trip the checkers

try:
    from tendermint_tpu.ops import pipeline as _pl

    _HAVE_OPS = True
except ModuleNotFoundError:
    # no crypto wheel: the purepy subprocess runner below covers these
    _HAVE_OPS = False


class _FakeDev:
    """Mock device result: materializes to a given (owned) verdict row,
    honoring the async-copy protocol so _Readback works unchanged."""

    def __init__(self, a):
        self._a = a

    def copy_to_host_async(self):
        pass

    def __array__(self, dtype=None):
        return self._a if dtype is None else self._a.astype(dtype)


def _fake_prepare_factory():
    """Per-batch mock kernels — no XLA compile. Batch verdicts DIFFER
    run to run (lane 0 flips on odd batches) so a recycled-scratch alias
    produces a byte delta the canary can see."""
    counter = {"n": 0}

    def fake_prepare(entries):
        n = len(entries)
        i = counter["n"]
        counter["n"] += 1
        verdict = np.ones(n, dtype=np.int32)
        if i % 2:
            verdict[0] = 0
        args = (np.arange(16, dtype=np.uint8),)

        def kern(*dev_args):
            return _FakeDev(verdict)

        return kern, args, None, n

    return fake_prepare


def _mk_entries(n):
    return [(bytes(32), b"m%d" % i, bytes(64)) for i in range(n)]


@pytest.mark.skipif(not _HAVE_OPS, reason="ops package needs the crypto "
                    "wheel (runs via the purepy subprocess below)")
class TestInjectedLintbugs:
    @pytest.fixture(autouse=True)
    def _mock_kernels(self, monkeypatch):
        monkeypatch.setattr(
            _pl.AsyncBatchVerifier, "_prepare",
            staticmethod(_fake_prepare_factory()),
        )
        yield

    def _run_two_batches(self):
        v = _pl.AsyncBatchVerifier(depth=2)
        try:
            r1 = np.array(v.submit(_mk_entries(8)).result(timeout=30),
                          copy=True)
            r2 = np.array(v.submit(_mk_entries(8)).result(timeout=30),
                          copy=True)
        finally:
            v.close()
        return r1, r2

    def test_clean_pipeline_has_no_violations(self):
        self._run_two_batches()
        assert not devcheck.violations()
        counts = devcheck.report()["counts"]
        assert counts["relay_touches"] >= 1       # transfers asserted
        assert counts["canary_registered"] >= 1   # verdicts canaried
        assert counts["lock_acquires"] > 0        # locks instrumented

    def test_alias_injection_trips_canary(self, monkeypatch):
        """TM_TPU_INJECT_LINTBUG=alias re-introduces PR-7: verdicts are
        delivered as views of a recycled scratch buffer; the NEXT batch's
        resolve overwrites it and the canary must catch the mutation."""
        monkeypatch.setenv("TM_TPU_INJECT_LINTBUG", "alias")
        self._run_two_batches()
        kinds = [x["kind"] for x in devcheck.violations()]
        assert "write-after-resolve" in kinds, kinds

    def test_owner_injection_trips_relay_assertion(self, monkeypatch):
        """TM_TPU_INJECT_LINTBUG=owner makes the RESOLVER thread issue a
        device transfer — the relay-ownership assertion must fire."""
        monkeypatch.setenv("TM_TPU_INJECT_LINTBUG", "owner")
        self._run_two_batches()
        kinds = [x["kind"] for x in devcheck.violations()]
        assert "relay-ownership" in kinds, kinds


def test_injected_lintbugs_under_purepy_fallback():
    """Containers without the crypto wheel run the integration layer in a
    subprocess with TM_TPU_PUREPY_CRYPTO=1 (which must not leak here)."""
    if _HAVE_OPS:
        pytest.skip("ops importable; TestInjectedLintbugs ran directly")
    here = os.path.dirname(os.path.abspath(__file__))
    env = dict(os.environ, TM_TPU_PUREPY_CRYPTO="1", JAX_PLATFORMS="cpu")
    r = subprocess.run(
        [
            sys.executable, "-m", "pytest",
            os.path.join(here, "test_devcheck.py"),
            "-q", "-k", "InjectedLintbugs", "-p", "no:cacheprovider",
        ],
        capture_output=True,
        env=env,
        cwd=os.path.dirname(here),
        timeout=600,
    )
    tail = (r.stdout or b"").decode(errors="replace")[-3000:]
    assert r.returncode == 0, f"isolated injected-lintbug run failed:\n{tail}"
