"""Trust metric (internal/p2p/trust) and UPnP (internal/p2p/upnp) parity
tests — the metric against the reference's documented math, UPnP against
an in-process fake IGD gateway (SSDP responder + SOAP endpoint)."""

import http.server
import socket
import threading

import pytest

from tendermint_tpu.p2p.trust import TrustMetric, TrustMetricStore
from tendermint_tpu.p2p import upnp


class TestTrustMetric:
    def test_perfect_history_stays_at_one(self):
        m = TrustMetric()
        for _ in range(10):
            m.good_events(5)
            m.advance()
        assert m.trust_score() == 100

    def test_proportional_drop_on_bad_events(self):
        """metric_test.go TestTrustMetricScores: all-bad current interval
        with perfect history -> P=0, I=1: 0.4*0 + 0.6*1 + 1.0*(0-1) < 0
        clamps to 0... with partial bad the derivative bites."""
        m = TrustMetric()
        m.good_events(1)
        assert m.trust_score() == 100
        m.bad_events(10)
        # proportional = 1/11, derivative negative with gamma2=1
        assert m.trust_score() < 50

    def test_trust_value_formula(self):
        """Hand-check one step: history_value=1 initially; with good=3,
        bad=1 -> P=0.75, d=-0.25 -> tv = 0.4*0.75 + 0.6*1 - 0.25 = 0.65."""
        m = TrustMetric()
        m.good_events(3)
        m.bad_events(1)
        assert abs(m.trust_value() - 0.65) < 1e-9

    def test_history_recovery(self):
        """After bad intervals, sustained good behavior recovers the
        score (integral component with optimistic weights)."""
        m = TrustMetric()
        for _ in range(3):
            m.bad_events(10)
            m.advance()
        low = m.trust_value()
        for _ in range(30):
            m.good_events(10)
            m.advance()
        assert m.trust_value() > low
        assert m.trust_value() > 0.9

    def test_faded_memory_window(self):
        """History storage stays logarithmic in the interval count
        (metric.go intervalToHistoryOffset)."""
        m = TrustMetric(tracking_window_s=1024 * 60.0, interval_s=60.0)
        for _ in range(200):
            m.good_events(1)
            m.advance()
        assert len(m.history) <= m.history_max_size
        assert m.history_max_size == 11  # floor(log2(1024)) + 1

    def test_pause_freezes_history(self):
        m = TrustMetric()
        m.good_events(5)
        m.advance()
        m.pause()
        before = m.num_intervals
        m.advance()  # paused: no-op
        assert m.num_intervals == before
        m.bad_events(1)  # unpauses and clears counters
        assert not m.paused

    def test_store_persistence_roundtrip(self):
        class MemDB(dict):
            def get(self, k):
                return dict.get(self, k)

            def set(self, k, v):
                self[k] = v

        db = MemDB()
        store = TrustMetricStore(db=db)
        m = store.get_peer_trust_metric("peer-a")
        for _ in range(5):
            m.good_events(2)
            m.bad_events(1)
            m.advance()
        val = m.trust_value()
        store.save()
        store2 = TrustMetricStore(db=db)
        assert store2.size() == 1
        m2 = store2.get_peer_trust_metric("peer-a")
        # restored history reproduces the same history value
        assert abs(m2.history_value - m.history_value) < 1e-9
        assert abs(m2.trust_value() - val) < 0.5  # fresh interval counters

    def test_corrupt_persisted_blob_tolerated(self):
        """Truncated/inconsistent saved histories must not crash startup:
        intervals claimed without supporting history data are clamped."""
        import json

        class MemDB(dict):
            def get(self, k):
                return dict.get(self, k)

            def set(self, k, v):
                self[k] = v

        db = MemDB()
        db.set(
            TrustMetricStore._KEY,
            json.dumps(
                {
                    "empty-hist": {"intervals": 5, "history": []},
                    "short-hist": {"intervals": 9, "history": [0.5]},
                    "not-a-dict": 42,
                    "ok": {"intervals": 1, "history": [0.75]},
                }
            ).encode(),
        )
        store = TrustMetricStore(db=db)
        # every loadable peer restores; none crash
        # top-level non-dict must also be tolerated
        db2 = MemDB()
        db2.set(TrustMetricStore._KEY, b"[1,2,3]")
        assert TrustMetricStore(db=db2).size() == 0
        m = store.get_peer_trust_metric("empty-hist")
        assert m.num_intervals == 0 and m.trust_score() == 100
        m2 = store.get_peer_trust_metric("short-hist")
        assert m2.num_intervals >= 1  # clamped to what [0.5] supports
        assert 0.0 <= m2.trust_value() <= 1.0
        m3 = store.get_peer_trust_metric("ok")
        assert abs(m3.history_value - 0.75) < 1e-9

    def test_concurrent_tick_single_advance(self):
        import threading as th

        m = TrustMetric(interval_s=0.01)
        m.good_events(1)
        import time as _t

        _t.sleep(0.02)
        before = m.num_intervals
        ts = [th.Thread(target=m.tick) for _ in range(4)]
        [t.start() for t in ts]
        [t.join() for t in ts]
        # elapsed interval consumed exactly once per boundary crossing
        assert m.num_intervals >= before + 1

    def test_disconnected_peer_paused(self):
        store = TrustMetricStore()
        m = store.get_peer_trust_metric("p")
        store.peer_disconnected("p")
        assert m.paused


DESC_XML = """<?xml version="1.0"?>
<root xmlns="urn:schemas-upnp-org:device-1-0">
  <device>
    <deviceType>urn:schemas-upnp-org:device:InternetGatewayDevice:1</deviceType>
    <deviceList><device>
      <deviceType>urn:schemas-upnp-org:device:WANDevice:1</deviceType>
      <deviceList><device>
        <deviceType>urn:schemas-upnp-org:device:WANConnectionDevice:1</deviceType>
        <serviceList><service>
          <serviceType>urn:schemas-upnp-org:service:WANIPConnection:1</serviceType>
          <controlURL>/ctl/IPConn</controlURL>
        </service></serviceList>
      </device></deviceList>
    </device></deviceList>
  </device>
</root>"""


class _FakeGateway(http.server.BaseHTTPRequestHandler):
    actions = []

    def do_GET(self):
        body = DESC_XML.encode()
        self.send_response(200)
        self.send_header("Content-Type", "text/xml")
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def do_POST(self):
        length = int(self.headers.get("Content-Length", "0"))
        payload = self.rfile.read(length).decode()
        action = self.headers.get("SOAPAction", "")
        type(self).actions.append((action, payload))
        if "GetExternalIPAddress" in action:
            body = (
                b"<s:Envelope><s:Body><u:GetExternalIPAddressResponse>"
                b"<NewExternalIPAddress>203.0.113.7</NewExternalIPAddress>"
                b"</u:GetExternalIPAddressResponse></s:Body></s:Envelope>"
            )
        else:
            body = b"<s:Envelope><s:Body/></s:Envelope>"
        self.send_response(200)
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def log_message(self, *a):  # quiet
        pass


@pytest.fixture
def fake_gateway():
    httpd = http.server.HTTPServer(("127.0.0.1", 0), _FakeGateway)
    _FakeGateway.actions = []
    t = threading.Thread(target=httpd.serve_forever, daemon=True)
    t.start()
    # SSDP responder on localhost UDP
    ssdp = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
    ssdp.bind(("127.0.0.1", 0))
    loc = f"http://127.0.0.1:{httpd.server_address[1]}/rootDesc.xml"

    def responder():
        try:
            data, addr = ssdp.recvfrom(2048)
            if b"M-SEARCH" in data:
                resp = (
                    "HTTP/1.1 200 OK\r\n"
                    "ST: urn:schemas-upnp-org:device:InternetGatewayDevice:1\r\n"
                    f"LOCATION: {loc}\r\n\r\n"
                ).encode()
                ssdp.sendto(resp, addr)
        except OSError:
            pass

    rt = threading.Thread(target=responder, daemon=True)
    rt.start()
    yield ssdp.getsockname(), httpd.server_address[1]
    httpd.shutdown()
    ssdp.close()


class TestUPnP:
    def test_cli_probe_upnp(self, fake_gateway, capsys):
        """CLI probe-upnp (cmd/tendermint ProbeUpnpCmd) end-to-end against
        the fake gateway: discover, map, report capabilities JSON."""
        import json

        from tendermint_tpu import cli
        from tendermint_tpu.p2p import upnp as upnp_mod

        ssdp_addr, _ = fake_gateway
        prior = upnp_mod.SSDP_ADDR
        upnp_mod.SSDP_ADDR = ssdp_addr
        try:
            rc = cli.main(
                ["probe-upnp", "--timeout", "2", "--int-port", "18421",
                 "--ext-port", "18421"]
            )
        finally:
            upnp_mod.SSDP_ADDR = prior
        assert rc == 0
        out = capsys.readouterr().out.strip().splitlines()[-1]
        caps = json.loads(out)
        assert caps["port_mapping"] is True

    def test_discover_and_map(self, fake_gateway):
        ssdp_addr, _ = fake_gateway
        nat = upnp.discover(timeout=3.0, ssdp_addr=ssdp_addr, attempts=1)
        assert nat.urn_domain == "schemas-upnp-org"
        assert nat.control_url.endswith("/ctl/IPConn")
        assert nat.get_external_address() == "203.0.113.7"
        assert nat.add_port_mapping("tcp", 26656, 26656, "tendermint") == 26656
        nat.delete_port_mapping("tcp", 26656)
        acts = [a for a, _ in _FakeGateway.actions]
        assert any("GetExternalIPAddress" in a for a in acts)
        assert any("AddPortMapping" in a for a in acts)
        assert any("DeletePortMapping" in a for a in acts)
        # the SOAP body carries the internal client and lease fields
        add_payload = next(p for a, p in _FakeGateway.actions if "AddPortMapping" in a)
        assert "<NewInternalClient>" in add_payload
        assert "<NewLeaseDuration>0</NewLeaseDuration>" in add_payload

    def test_parse_ssdp_rejects_non_gateway(self):
        resp = b"HTTP/1.1 200 OK\r\nST: upnp:rootdevice\r\nLOCATION: http://x/\r\n\r\n"
        assert upnp.parse_ssdp_response(resp) is None

    def test_discover_timeout(self):
        sink = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
        sink.bind(("127.0.0.1", 0))  # never answers
        try:
            with pytest.raises(upnp.UPnPError):
                upnp.discover(timeout=0.3, ssdp_addr=sink.getsockname(), attempts=1)
        finally:
            sink.close()
