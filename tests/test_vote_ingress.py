"""Device-batched live-vote ingress (ISSUE 15): the split add_vote —
host-stage check_vote then verdict-stage apply_vote_verdict — must be
byte-identical (exception type AND string) to the sequential path for
EVERY error add_vote can raise: forged signature, conflicting votes
(block-vs-block and nil-vs-block equivocation, with identical evidence
votes), non-deterministic signatures, wrong height/round/type, bad
index/address, exact duplicates, and the HeightVoteSet unwanted-round
budget. Plus the accumulator itself: memo-hit short-circuit, stepped
deterministic flushing, DispatchError poisoned-window isolation (the
round still completes via the per-vote fallback, devcheck armed), the
PeerState HasVoteBits OR-learn, and the simnet replay-exactness of a
cluster running with ingress on.

Needs a working ed25519 signer: with the `cryptography` wheel the module
runs directly; without it, tests/test_vote_ingress_isolated.py re-runs
it in a subprocess under TM_TPU_PUREPY_CRYPTO=1.
"""

import importlib.util
import os
import sys
import threading
import time

import pytest

if importlib.util.find_spec("cryptography") is None and not os.environ.get(
    "TM_TPU_PUREPY_CRYPTO"
):
    pytest.skip(
        "needs an ed25519 signer (cryptography wheel or the isolated runner)",
        allow_module_level=True,
    )

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO_ROOT not in sys.path:
    sys.path.insert(0, REPO_ROOT)

from tendermint_tpu.consensus import vote_ingress as vi  # noqa: E402
from tendermint_tpu.consensus.peer_state import PeerState  # noqa: E402
from tendermint_tpu.consensus.types import (  # noqa: E402
    ErrGotVoteFromUnwantedRound,
    HeightVoteSet,
)
from tendermint_tpu.crypto import ed25519 as ed  # noqa: E402
from tendermint_tpu.libs.bits import BitArray  # noqa: E402
from tendermint_tpu.ops import epoch_cache as _epoch  # noqa: E402
from tendermint_tpu.ops import pipeline as pl  # noqa: E402
from tendermint_tpu.types import (  # noqa: E402
    BlockID,
    PartSetHeader,
    Timestamp,
    Validator,
    ValidatorSet,
    Vote,
    VoteSet,
)
from tendermint_tpu.types.vote import (  # noqa: E402
    PRECOMMIT_TYPE,
    PREVOTE_TYPE,
)
from tendermint_tpu.types.vote_set import (  # noqa: E402
    ErrVoteConflictingVotes,
    ErrVoteInvalidSignature,
    ErrVoteInvalidValidatorIndex,
    ErrVoteNonDeterministicSignature,
    ErrVoteUnexpectedStep,
)

CHAIN_ID = "vote-ingress-test"
HEIGHT = 10


def make_validators(n):
    pairs = []
    for i in range(n):
        sk = ed.gen_priv_key(bytes([i + 1]) * 32)
        pairs.append((sk, Validator.new(sk.pub_key(), 100)))
    vset = ValidatorSet.new([v for _, v in pairs])
    by_addr = {v.address: sk for sk, v in pairs}
    return [by_addr[v.address] for v in vset.validators], vset


def make_block_id(tag=b"\x01"):
    return BlockID(
        hash=tag * 32, part_set_header=PartSetHeader(total=1, hash=tag * 32)
    )


def sign_vote(sk, vset, vote_type, height, round_, block_id, idx=None):
    addr = sk.pub_key().address()
    if idx is None:
        idx, _ = vset.get_by_address(addr)
    vote = Vote(
        type=vote_type,
        height=height,
        round=round_,
        block_id=block_id,
        timestamp=Timestamp(seconds=1_600_000_000, nanos=0),
        validator_address=addr,
        validator_index=idx,
    )
    sig = sk.sign(vote.sign_bytes(CHAIN_ID))
    return Vote(**{**vote.__dict__, "signature": sig})


def fresh_sets():
    """Two independent-but-identical VoteSets: one driven sequentially,
    one through the split check/verdict path."""
    sks, vset = make_validators(4)
    seq = VoteSet(CHAIN_ID, HEIGHT, 0, PREVOTE_TYPE, vset)
    bat = VoteSet(CHAIN_ID, HEIGHT, 0, PREVOTE_TYPE, vset)
    return sks, vset, seq, bat


def batched_add(vs: VoteSet, vote: Vote):
    """The ingress path against ONE VoteSet: host check, real signature
    verify (what the device lane computes), verdict application."""
    chk = vs.check_vote(vote)
    if chk is None:
        return False
    valid = chk.pub_key.verify_signature(
        vote.sign_bytes(vs.chain_id), vote.signature
    )
    return vs.apply_vote_verdict(vote, valid)


def both_raise(seq_vs, bat_vs, vote, exc_type):
    """Drive the same vote down both paths; the exceptions must match in
    TYPE and STRING — the parity contract."""
    with pytest.raises(exc_type) as e_seq:
        seq_vs.add_vote(vote)
    with pytest.raises(exc_type) as e_bat:
        batched_add(bat_vs, vote)
    assert type(e_seq.value) is type(e_bat.value)
    assert str(e_seq.value) == str(e_bat.value)
    return e_seq.value, e_bat.value


class TestVoteSetParity:
    def test_valid_vote_parity(self):
        sks, vset, seq, bat = fresh_sets()
        v = sign_vote(sks[0], vset, PREVOTE_TYPE, HEIGHT, 0, make_block_id())
        assert seq.add_vote(v) is True
        assert batched_add(bat, v) is True
        assert seq.bit_array().get_index(0)
        assert bat.bit_array().get_index(0)

    def test_forged_signature_parity(self):
        sks, vset, seq, bat = fresh_sets()
        v = sign_vote(sks[0], vset, PREVOTE_TYPE, HEIGHT, 0, make_block_id())
        bad = bytearray(v.signature)
        bad[0] ^= 0x5A
        forged = Vote(**{**v.__dict__, "signature": bytes(bad)})
        both_raise(seq, bat, forged, ErrVoteInvalidSignature)

    def test_conflicting_votes_parity_and_evidence(self):
        sks, vset, seq, bat = fresh_sets()
        a = sign_vote(sks[0], vset, PREVOTE_TYPE, HEIGHT, 0,
                      make_block_id(b"\x0a"))
        b = sign_vote(sks[0], vset, PREVOTE_TYPE, HEIGHT, 0,
                      make_block_id(b"\x0b"))
        assert seq.add_vote(a) and batched_add(bat, a)
        es, eb = both_raise(seq, bat, b, ErrVoteConflictingVotes)
        # the evidence votes — what DuplicateVoteEvidence is built from —
        # must be identical too
        assert es.vote_a == eb.vote_a and es.vote_b == eb.vote_b
        assert es.vote_a == a and es.vote_b == b

    def test_nil_vs_block_equivocation_parity(self):
        sks, vset, seq, bat = fresh_sets()
        nil = sign_vote(sks[1], vset, PREVOTE_TYPE, HEIGHT, 0, BlockID())
        blk = sign_vote(sks[1], vset, PREVOTE_TYPE, HEIGHT, 0,
                        make_block_id(b"\x0c"))
        assert seq.add_vote(nil) and batched_add(bat, nil)
        es, eb = both_raise(seq, bat, blk, ErrVoteConflictingVotes)
        assert es.vote_a == eb.vote_a == nil
        assert es.vote_b == eb.vote_b == blk

    def test_non_deterministic_signature_parity(self):
        sks, vset, seq, bat = fresh_sets()
        v = sign_vote(sks[2], vset, PREVOTE_TYPE, HEIGHT, 0, make_block_id())
        assert seq.add_vote(v) and batched_add(bat, v)
        twiddled = bytearray(v.signature)
        twiddled[-1] ^= 0x01
        v2 = Vote(**{**v.__dict__, "signature": bytes(twiddled)})
        both_raise(seq, bat, v2, ErrVoteNonDeterministicSignature)

    def test_duplicate_returns_false_on_both_paths(self):
        sks, vset, seq, bat = fresh_sets()
        v = sign_vote(sks[3], vset, PREVOTE_TYPE, HEIGHT, 0, make_block_id())
        assert seq.add_vote(v) and batched_add(bat, v)
        assert seq.add_vote(v) is False
        # the host stage already answers a duplicate: check_vote is None
        assert bat.check_vote(v) is None
        assert batched_add(bat, v) is False

    def test_wrong_height_round_type_parity(self):
        sks, vset, seq, bat = fresh_sets()
        for h, r, t in ((HEIGHT + 1, 0, PREVOTE_TYPE),
                        (HEIGHT, 3, PREVOTE_TYPE),
                        (HEIGHT, 0, PRECOMMIT_TYPE)):
            v = sign_vote(sks[0], vset, t, h, r, make_block_id())
            both_raise(seq, bat, v, ErrVoteUnexpectedStep)

    def test_bad_index_parity(self):
        sks, vset, seq, bat = fresh_sets()
        v = sign_vote(sks[0], vset, PREVOTE_TYPE, HEIGHT, 0,
                      make_block_id(), idx=-1)
        both_raise(seq, bat, v, ErrVoteInvalidValidatorIndex)
        v2 = sign_vote(sks[0], vset, PREVOTE_TYPE, HEIGHT, 0,
                       make_block_id(), idx=99)
        both_raise(seq, bat, v2, ErrVoteInvalidValidatorIndex)


class TestHeightVoteSetParity:
    def test_unwanted_round_budget_parity(self):
        sks, vset = make_validators(4)
        seq = HeightVoteSet(CHAIN_ID, HEIGHT, vset)
        bat = HeightVoteSet(CHAIN_ID, HEIGHT, vset)

        def hv_batched(hvs, vote, peer):
            chk = hvs.check_vote(vote, peer)
            if chk is None:
                return False
            valid = chk.pub_key.verify_signature(
                vote.sign_bytes(CHAIN_ID), vote.signature
            )
            return hvs.apply_vote_verdict(vote, peer, valid)

        # two catchup rounds fit the per-peer budget...
        for r in (5, 7):
            v = sign_vote(sks[0], vset, PREVOTE_TYPE, HEIGHT, r,
                          make_block_id())
            assert seq.add_vote(v, "peer-a") is True
            assert hv_batched(bat, v, "peer-a") is True
        # ...the third raises the same error on both paths
        v3 = sign_vote(sks[0], vset, PREVOTE_TYPE, HEIGHT, 9,
                       make_block_id())
        with pytest.raises(ErrGotVoteFromUnwantedRound) as e_seq:
            seq.add_vote(v3, "peer-a")
        with pytest.raises(ErrGotVoteFromUnwantedRound) as e_bat:
            bat.check_vote(v3, "peer-a")
        assert str(e_seq.value) == str(e_bat.value)

    def test_verdict_for_vanished_round_falls_back(self):
        sks, vset = make_validators(4)
        hvs = HeightVoteSet(CHAIN_ID, HEIGHT, vset)
        v = sign_vote(sks[0], vset, PREVOTE_TYPE, HEIGHT, 0, make_block_id())
        chk = hvs.check_vote(v, "p")
        assert chk is not None
        # the height advanced underneath the in-flight verdict
        hvs.reset(HEIGHT, vset)
        assert hvs.apply_vote_verdict(v, "p", True) is True
        assert hvs.prevotes(0).bit_array().get_index(v.validator_index)


class _Collector:
    """Apply callback standing in for ConsensusState._on_vote_verdicts:
    records outcomes; on a window error re-drives each vote through the
    sequential per-vote path (the consensus fallback contract)."""

    def __init__(self, vote_set=None):
        self.vs = vote_set
        self.applied = []  # (round, val_idx, verdict-or-"err")
        self.errors = []
        self.done = threading.Event()
        self.want = 0

    def __call__(self, batch, verdicts, error):
        for i, p in enumerate(batch):
            if error is not None:
                self.errors.append(type(error).__name__)
                if self.vs is not None:
                    self.vs.add_vote(p.vote)  # per-vote fallback
                self.applied.append((p.vote.round, p.vote.validator_index,
                                     "err"))
            else:
                ok = bool(verdicts[i])
                if self.vs is not None and ok:
                    self.vs.apply_vote_verdict(p.vote, True)
                self.applied.append((p.vote.round, p.vote.validator_index,
                                     ok))
        if len(self.applied) >= self.want:
            self.done.set()


def _pend(vote, sk, peer="p"):
    return vi.PendingVote(vote, peer, sk.pub_key().bytes(),
                          vote.sign_bytes(CHAIN_ID),
                          t_enq=time.perf_counter())


class TestAccumulator:
    def test_memo_hit_short_circuits(self):
        """A memoized (pub, msg, sig) verdict applies immediately —
        no window, no flush — and the memo_hits counter advances."""
        sks, vset = make_validators(4)
        v = sign_vote(sks[0], vset, PREVOTE_TYPE, HEIGHT, 0, make_block_id())
        real = ed.verify_zip215_fast

        class Memo:
            def __init__(self):
                self.cache = {}

            def __call__(self, pub, msg, sig):
                return real(pub, msg, sig)

        memo = Memo()
        pend = _pend(v, sks[0])
        memo.cache[(pend.pub, pend.msg, v.signature)] = True
        ed.verify_zip215_fast = memo
        col = _Collector()
        col.want = 1
        acc = vi.VoteIngress(col, stepped=True)
        try:
            acc.submit(pend, vset)
            assert col.done.wait(1.0)
            assert col.applied == [(0, v.validator_index, True)]
            assert acc.stats()["memo_hits"] == 1
            assert acc.stats()["batches"] == 0  # never windowed
        finally:
            acc.close()
            ed.verify_zip215_fast = real

    def test_stepped_flush_is_deterministic(self):
        """Stepped mode: nothing flushes until flush_pending(); then
        every open window applies inline in submission order — twice
        over, the apply order is identical."""

        def run():
            sks, vset = make_validators(4)
            col = _Collector()
            acc = vi.VoteIngress(col, stepped=True)
            try:
                for r in range(2):
                    for i, sk in enumerate(sks):
                        v = sign_vote(sk, vset, PREVOTE_TYPE, HEIGHT, r,
                                      make_block_id())
                        acc.submit(_pend(v, sk, peer=f"p{i}"), vset)
                assert col.applied == []  # stepped: no eager flush
                assert acc.flush_pending() is True
                assert acc.flush_pending() is False  # drained
                return list(col.applied)
            finally:
                acc.close()

        first, second = run(), run()
        assert first == second
        assert len(first) == 8
        assert all(ok is True for _, _, ok in first)

    def test_in_window_duplicate_dropped(self):
        sks, vset = make_validators(4)
        v = sign_vote(sks[0], vset, PREVOTE_TYPE, HEIGHT, 0, make_block_id())
        col = _Collector()
        acc = vi.VoteIngress(col, stepped=True)
        try:
            acc.submit(_pend(v, sks[0], peer="p1"), vset)
            acc.submit(_pend(v, sks[0], peer="p2"), vset)  # re-gossip copy
            assert acc.stats()["window_dups"] == 1
            acc.flush_pending()
            assert len(col.applied) == 1
        finally:
            acc.close()

    def test_poisoned_window_fails_alone_round_completes(self):
        """Devcheck armed: prep blows up for exactly one window size —
        that window's votes fall back to the per-vote sequential path,
        neighbouring windows are untouched, and the VoteSet still
        reaches +2/3. No devcheck violations along the way."""
        from tendermint_tpu.libs import devcheck

        _epoch.reset(8)
        sks, vset = make_validators(9)
        vs = VoteSet(CHAIN_ID, HEIGHT, 0, PREVOTE_TYPE, vset)
        bid = make_block_id()
        votes = [sign_vote(sk, vset, PREVOTE_TYPE, HEIGHT, 0, bid)
                 for sk in sks]
        poison_n = 5
        real = pl.AsyncBatchVerifier._prepare

        def poisoned(entries, *args, **kw):
            n = (len(entries.entries) if hasattr(entries, "entries")
                 else len(entries))
            if n == poison_n:
                raise RuntimeError("injected poison")
            return real(entries, *args, **kw)

        was_on = devcheck.enabled()
        devcheck.enable(reset=True)
        pl.AsyncBatchVerifier._prepare = staticmethod(poisoned)
        v = pl.AsyncBatchVerifier(depth=2)
        col = _Collector(vote_set=vs)
        col.want = 9
        # giant window: only explicit flush_now() submits, so each wave
        # below is exactly one device window
        acc = vi.VoteIngress(col, verifier=v, max_batch=256,
                             window_ms=60_000.0)
        try:
            for vt, sk in zip(votes[:4], sks[:4]):  # healthy window
                acc.submit(_pend(vt, sk), vset)
            acc.flush_now()
            deadline = time.time() + 60
            while len(col.applied) < 4 and time.time() < deadline:
                time.sleep(0.01)
            for vt, sk in zip(votes[4:], sks[4:]):  # poisoned window (5)
                acc.submit(_pend(vt, sk), vset)
            acc.flush_now()
            assert col.done.wait(60)
            assert acc.stats()["dispatch_errors"] == 1
            assert col.errors and all(e == "DispatchError"
                                      for e in col.errors)
            # the poisoned window fell back per-vote: every vote landed
            _, ok = vs.two_thirds_majority()
            assert ok, "round did not complete through the fallback"
            assert vs.bit_array().size() == 9
            assert all(vs.bit_array().get_index(i) for i in range(9))
            assert not devcheck.violations()
        finally:
            acc.close()
            v.close()
            pl.AsyncBatchVerifier._prepare = real
            if not was_on:
                devcheck.disable()

    def test_engine_absent_falls_back_to_host(self):
        """A window that cannot even be SUBMITTED host-verifies instead
        of erroring (sync_fallbacks counted) — byte-identical verdicts."""
        sks, vset = make_validators(4)

        class DeadVerifier:
            def submit(self, *a, **k):
                raise RuntimeError("engine is closed")

        col = _Collector()
        col.want = 4
        acc = vi.VoteIngress(col, verifier=DeadVerifier(), max_batch=256,
                             window_ms=60_000.0)
        try:
            for sk in sks:
                v = sign_vote(sk, vset, PREVOTE_TYPE, HEIGHT, 0,
                              make_block_id())
                acc.submit(_pend(v, sk), vset)
            acc.flush_now()
            assert col.done.wait(30)
            assert acc.stats()["sync_fallbacks"] >= 1
            assert all(ok is True for _, _, ok in col.applied)
        finally:
            acc.close()


class TestHasVoteBits:
    def test_or_learn_semantics(self):
        ps = PeerState("p")
        ps.apply_new_round_step(3, 0, 4, -1)
        ps.ensure_vote_bit_arrays(3, 5)
        bits = BitArray(5)
        bits.set_index(1, True)
        bits.set_index(3, True)
        ps.apply_has_vote_bits(3, 0, PREVOTE_TYPE, bits)
        assert ps.prs.prevotes.get_index(1)
        assert ps.prs.prevotes.get_index(3)
        # a later summary ORs in — earlier learned bits survive
        more = BitArray(5)
        more.set_index(0, True)
        ps.apply_has_vote_bits(3, 0, PREVOTE_TYPE, more)
        assert all(ps.prs.prevotes.get_index(i) for i in (0, 1, 3))
        assert not ps.prs.prevotes.get_index(2)

    def test_wrong_height_ignored(self):
        ps = PeerState("p")
        ps.apply_new_round_step(3, 0, 4, -1)
        ps.ensure_vote_bit_arrays(3, 5)
        bits = BitArray(5)
        bits.set_index(0, True)
        ps.apply_has_vote_bits(7, 0, PREVOTE_TYPE, bits)
        assert not ps.prs.prevotes.get_index(0)

    def test_last_commit_summary_learned(self):
        # peer at height 4: a summary for height 3 precommits lands in
        # its last-commit bits (the height+1 route)
        ps = PeerState("p")
        ps.apply_new_round_step(4, 0, 1, 2)
        bits = BitArray(4)
        bits.set_index(2, True)
        ps.apply_has_vote_bits(3, 2, PRECOMMIT_TYPE, bits)
        assert ps.prs.last_commit is not None
        assert ps.prs.last_commit.get_index(2)


@pytest.mark.slow
class TestSimnetIngress:
    def test_replay_exact_with_ingress(self):
        """4-node partition+heal smoke with the stepped accumulator
        attached on every node: a 2/2 split stalls quorum, heals, and
        two identical-seed runs still produce identical fingerprints,
        votes actually window (batches observed), and invariants hold."""
        from tendermint_tpu.simnet import Cluster
        from tendermint_tpu.simnet.faults import partition_heal_schedule

        def run():
            c = Cluster(
                n_nodes=4, seed=29, vote_ingress=True,
                faults=partition_heal_schedule(4, at_height=3,
                                               duration=2.0),
            )
            rep = c.run_to_height(6, max_virtual_s=600.0)
            fp = c.fingerprint()
            batches = sum(
                n.cs.vote_ingress.stats()["batches"] for n in c.nodes
                if n.cs is not None and n.cs.vote_ingress is not None
            )
            c.stop()
            return rep, fp, batches

        r1, fp1, b1 = run()
        r2, fp2, b2 = run()
        assert r1.ok and r2.ok, (r1.reason, r2.reason)
        assert not r1.violations and not r2.violations
        assert fp1 == fp2
        assert b1 == b2
        assert b1 > 0, "votes never windowed through the accumulator"
