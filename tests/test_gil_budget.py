"""GIL-budget regression gate (VERDICT item 6, tightened for round 6).

Measures the host-side (non-device) prep cost of a 10k-signature
verify_commit on the pure-Python CPU fallback — now the FUSED
columnar-from-decode path (ops/commit_prep.py): the commit decodes
straight into CommitBlock columns and one call does selection + tally +
sign-bytes + pub/sig gather + the device-hash RAM blocks. Gates:

  absolute   the full decode-to-kernel-args path (fused commit_entries ->
             prepare_batch_device_hash) for 10k sigs must stay under
             GIL_BUDGET_MS_10K = 60 ms (PR 3's gate was 150 ms against
             the PR-2 path; measured ~20 ms here on the dev container)

  relative   the stages the fused prep RESTRUCTURED — commit-side prep +
             SHA RAM-block construction — must cost <= 0.5x the PR-2
             implementation of the same stages (commit_entries object
             walk + vote_sign_bytes_block + pad_ram_block's flat scatter
             + shift-or word packing, pinned VERBATIM in the subprocess
             script: the in-tree fallback has since absorbed some of
             round 6's shared optimizations, so gating against it would
             undercount the representation change being guarded).
             Measured ~0.31x on the dev container.

  parity     both paths must produce bit-identical kernel args — the
             verdict/blame equivalence of the fused path rests on it
             (tests/test_commit_block.py covers verdict/blame parity at
             the verify_commit level).

The measurement runs in a subprocess: it needs TM_TPU_PUREPY_CRYPTO=1
(containers without the OpenSSL wheel) + TM_TPU_NO_NATIVE=1 (isolate the
pure-Python path — the gate must hold even where the native module isn't
built), and neither env var may leak into the main pytest process."""

import json
import os
import subprocess
import sys

HERE = os.path.dirname(os.path.abspath(__file__))
REPO = os.path.dirname(HERE)

GIL_BUDGET_MS_10K = 60.0
RELATIVE_GATE = 0.5
N_SIGS = 10_000

_SCRIPT = r"""
import importlib.util, json, sys, time

import numpy as np

spec = importlib.util.spec_from_file_location(
    "prep_bench", %(prep_bench)r
)
pb = importlib.util.module_from_spec(spec)
spec.loader.exec_module(pb)

from tendermint_tpu.ops import backend, pipeline
from tendermint_tpu.ops import sha512 as sha
from tendermint_tpu.types.block import Commit

chain_id = "gil-budget"
vset, commit = pb.build_synthetic_commit(%(n_sigs)d)
needed = vset.total_voting_power() * 2 // 3
bucket = backend._bucket_for(%(n_sigs)d)
# columnar-from-decode: the wire round-trip is what fills the CommitBlock
dec = Commit.decode(commit.encode())
assert dec.commit_block() is not None, "decode did not produce columns"

MAX_LEN = 64 + backend.DEVICE_HASH_MAX_MSG


def full_fused():
    dec._sb_tpl = None  # fresh sign-bytes template per rep
    blk, _ = pipeline.commit_entries(chain_id, vset, dec, needed)
    return backend.prepare_batch_device_hash(blk, bucket)


def stage_fused():
    dec._sb_tpl = None
    blk, _ = pipeline.commit_entries(chain_id, vset, dec, needed)
    assert blk.ram_hi is not None, "fused prep did not fill RAM columns"
    return sha.pad_ram_rows(blk, bucket, MAX_LEN)


# ---- the PR-2 implementation of the same stages, pinned verbatim ----

def _buf_to_words_pr2(buf, bsz, nblock):
    words = buf.reshape(bsz, nblock, 16, 8)
    hi = ((words[..., 0].astype(np.uint32) << 24)
          | (words[..., 1].astype(np.uint32) << 16)
          | (words[..., 2].astype(np.uint32) << 8)
          | words[..., 3].astype(np.uint32))
    lo = ((words[..., 4].astype(np.uint32) << 24)
          | (words[..., 5].astype(np.uint32) << 16)
          | (words[..., 6].astype(np.uint32) << 8)
          | words[..., 7].astype(np.uint32))
    return hi, lo


def pad_ram_block_pr2(block, bucket, max_len):
    nblock = (max_len + 17 + 127) // 128
    n = len(block)
    lens = np.full(bucket, 64, dtype=np.int64)
    buf = np.zeros((bucket, nblock * 128), dtype=np.uint8)
    if n:
        mbuf, offs = block.msgs_contiguous()
        offs = np.asarray(offs)
        mlens = np.diff(offs)
        lens[:n] = 64 + mlens
        buf[:n, :32] = block.sig[:, :32]
        buf[:n, 32:64] = block.pub
        total = int(mlens.sum())
        if total:
            flat = np.frombuffer(mbuf, dtype=np.uint8, count=total)
            rows = np.repeat(np.arange(n), mlens)
            cols = 64 + (np.arange(total) - np.repeat(offs[:-1], mlens))
            buf[rows, cols] = flat
    buf[n:, 0] = 1
    buf[n:, 32] = 1
    blocks = (lens + 17 + 127) // 128
    rng = np.arange(bucket)
    buf[rng, lens] = 0x80
    bitlen = lens * 8
    base = blocks * 128 - 8
    for j in range(8):
        buf[rng, base + j] = (bitlen >> (8 * (7 - j))) & 0xFF
    return _buf_to_words_pr2(buf, bucket, nblock) + (blocks.astype(np.int32),)


def stage_pr2():
    commit._sb_tpl = None
    blk, _ = pipeline.commit_entries_legacy(chain_id, vset, commit, needed)
    return pad_ram_block_pr2(blk, bucket, MAX_LEN)


def min_ms(fn, reps=5):
    fn()  # warm
    times = []
    for _ in range(reps):
        t0 = time.perf_counter()
        fn()
        times.append((time.perf_counter() - t0) * 1e3)
    return min(times)


# interleave the two stage measurements so machine noise hits both
fused_stage_times, pr2_stage_times = [], []
stage_fused(); stage_pr2()
for _ in range(5):
    t0 = time.perf_counter(); stage_fused()
    fused_stage_times.append((time.perf_counter() - t0) * 1e3)
    t0 = time.perf_counter(); stage_pr2()
    pr2_stage_times.append((time.perf_counter() - t0) * 1e3)

full_ms = min_ms(full_fused)

# arg parity: fused RAM rows (padded) vs the PR-2 pad, and the full
# kernel arg tuple vs the in-tree fallback path
hi_f, lo_f, cnt_f = stage_fused()
hi_p, lo_p, cnt_p = stage_pr2()
ram_parity = (np.array_equal(hi_f, hi_p) and np.array_equal(lo_f, lo_p)
              and np.array_equal(cnt_f, cnt_p))
dec._sb_tpl = None
args_f = backend.prepare_batch_device_hash(
    pipeline.commit_entries(chain_id, vset, dec, needed)[0], bucket)
commit._sb_tpl = None
args_p = backend.prepare_batch_device_hash(
    pipeline.commit_entries_legacy(chain_id, vset, commit, needed)[0],
    bucket)
arg_parity = all(np.array_equal(a, b) for a, b in zip(args_f, args_p))

print(json.dumps({
    "full_fused_ms": full_ms,
    "fused_stage_ms": min(fused_stage_times),
    "pr2_stage_ms": min(pr2_stage_times),
    "ram_parity": ram_parity,
    "arg_parity": arg_parity,
}))
"""


def test_10k_sig_verify_commit_prep_stays_in_budget():
    env = dict(
        os.environ,
        TM_TPU_PUREPY_CRYPTO="1",
        TM_TPU_NO_NATIVE="1",
        JAX_PLATFORMS="cpu",
    )
    script = _SCRIPT % {
        "prep_bench": os.path.join(REPO, "tools", "prep_bench.py"),
        "n_sigs": N_SIGS,
    }
    r = subprocess.run(
        [sys.executable, "-c", script],
        capture_output=True,
        env=env,
        cwd=REPO,
        timeout=600,
    )
    assert r.returncode == 0, (r.stderr or b"").decode(errors="replace")[-3000:]
    out = json.loads((r.stdout or b"").decode().strip().splitlines()[-1])
    assert out["ram_parity"], "fused RAM blocks diverge from the PR-2 pad"
    assert out["arg_parity"], "fused kernel args diverge from the fallback path"
    full, fused, pr2 = (
        out["full_fused_ms"], out["fused_stage_ms"], out["pr2_stage_ms"]
    )
    assert full <= GIL_BUDGET_MS_10K, (
        f"decode-to-kernel-args for {N_SIGS} sigs took {full:.1f} ms "
        f"(budget {GIL_BUDGET_MS_10K} ms) — the fused commit prep regressed"
    )
    assert fused <= pr2 * RELATIVE_GATE, (
        f"fused commit prep ({fused:.1f} ms) no longer beats the PR-2 "
        f"implementation of the same stages ({pr2:.1f} ms) by >= "
        f"{1 - RELATIVE_GATE:.0%}"
    )
