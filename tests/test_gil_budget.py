"""GIL-budget regression gate (VERDICT item 6).

Measures the host-side (non-device) prep cost of a 10k-signature
verify_commit on the pure-Python CPU fallback — the columnar EntryBlock
path PR 2 introduced — and fails if it regresses. Two gates:

  absolute   columnar prep for 10k sigs must stay under
             GIL_BUDGET_MS_10K = 150 ms (measured ~40 ms on the dev
             container; ~3.7x headroom for slower CI hardware)
  relative   columnar must stay <= 80% of the tuple-list baseline cost
             (measured ~43%; a revert to row-wise prep lands at 100%+)

The measurement runs in a subprocess: it needs TM_TPU_PUREPY_CRYPTO=1
(containers without the OpenSSL wheel) + TM_TPU_NO_NATIVE=1 (isolate the
pure-Python path — the gate must hold even where the native module isn't
built), and neither env var may leak into the main pytest process."""

import json
import os
import subprocess
import sys

HERE = os.path.dirname(os.path.abspath(__file__))
REPO = os.path.dirname(HERE)

GIL_BUDGET_MS_10K = 150.0
RELATIVE_GATE = 0.8
N_SIGS = 10_000

_SCRIPT = r"""
import importlib.util, json, statistics, sys, time

spec = importlib.util.spec_from_file_location(
    "prep_bench", %(prep_bench)r
)
pb = importlib.util.module_from_spec(spec)
spec.loader.exec_module(pb)

from tendermint_tpu.ops import backend, pipeline

chain_id = "gil-budget"
vset, commit = pb.build_synthetic_commit(%(n_sigs)d)
needed = vset.total_voting_power() * 2 // 3
bucket = backend._bucket_for(%(n_sigs)d)

def median_ms(fn, reps=3):
    times = []
    for _ in range(reps):
        commit._sb_tpl = None  # fresh sign-bytes template per rep
        t0 = time.perf_counter()
        fn()
        times.append((time.perf_counter() - t0) * 1e3)
    return statistics.median(times)

columnar_ms = median_ms(
    lambda: backend.prepare_batch_device_hash(
        pipeline.commit_entries(chain_id, vset, commit, needed)[0], bucket
    )
)
tuple_ms = median_ms(
    lambda: backend.prepare_batch_device_hash(
        pb.commit_entries_tuples(chain_id, vset, commit, needed), bucket
    )
)
print(json.dumps({"columnar_ms": columnar_ms, "tuple_ms": tuple_ms}))
"""


def test_10k_sig_verify_commit_prep_stays_in_budget():
    env = dict(
        os.environ,
        TM_TPU_PUREPY_CRYPTO="1",
        TM_TPU_NO_NATIVE="1",
        JAX_PLATFORMS="cpu",
    )
    script = _SCRIPT % {
        "prep_bench": os.path.join(REPO, "tools", "prep_bench.py"),
        "n_sigs": N_SIGS,
    }
    r = subprocess.run(
        [sys.executable, "-c", script],
        capture_output=True,
        env=env,
        cwd=REPO,
        timeout=600,
    )
    assert r.returncode == 0, (r.stderr or b"").decode(errors="replace")[-3000:]
    out = json.loads((r.stdout or b"").decode().strip().splitlines()[-1])
    columnar, tuple_ = out["columnar_ms"], out["tuple_ms"]
    assert columnar <= GIL_BUDGET_MS_10K, (
        f"host prep for {N_SIGS} sigs took {columnar:.1f} ms "
        f"(budget {GIL_BUDGET_MS_10K} ms) — the PR 2 host-prep cuts regressed"
    )
    assert columnar <= tuple_ * RELATIVE_GATE, (
        f"columnar prep ({columnar:.1f} ms) no longer beats the tuple "
        f"baseline ({tuple_:.1f} ms) by >= {1 - RELATIVE_GATE:.0%}"
    )
