"""Isolated runner for test_epoch_cache.py on containers without the
`cryptography` wheel (same pattern as test_commit_block_isolated.py: the
TM_TPU_PUREPY_CRYPTO flag must not leak into the main pytest process)."""

import os
import subprocess
import sys

import pytest


def test_epoch_cache_under_purepy_fallback():
    try:
        import cryptography  # noqa: F401

        pytest.skip("cryptography present; test_epoch_cache runs directly")
    except ModuleNotFoundError:
        pass
    here = os.path.dirname(os.path.abspath(__file__))
    env = dict(os.environ, TM_TPU_PUREPY_CRYPTO="1", JAX_PLATFORMS="cpu")
    r = subprocess.run(
        [
            sys.executable, "-m", "pytest",
            os.path.join(here, "test_epoch_cache.py"),
            "-q", "-m", "not slow", "-p", "no:cacheprovider",
        ],
        capture_output=True,
        env=env,
        cwd=os.path.dirname(here),
        timeout=800,
    )
    tail = (r.stdout or b"").decode(errors="replace")[-3000:]
    assert r.returncode == 0, f"isolated test_epoch_cache run failed:\n{tail}"
