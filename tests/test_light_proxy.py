"""Light proxy: proof-verifying RPC over a running node; tampered
responses are rejected."""

import base64

import pytest

from tendermint_tpu.db import MemDB
from tendermint_tpu.light import Client, LightStore, TrustOptions
from tendermint_tpu.light.provider import NodeBackedProvider
from tendermint_tpu.light.rpc import LightProxy, VerificationFailed, VerifyingClient
from tendermint_tpu.rpc import HTTPClient
from tendermint_tpu.types.tx import tx_hash
from tests.test_node_rpc import two_node_net  # noqa: F401 — fixture


@pytest.fixture
def verifying(two_node_net):  # noqa: F811
    nodes = two_node_net
    nodes[0].wait_for_height(3, timeout=60)
    rpc = HTTPClient(nodes[0].rpc_server.listen_addr)
    prov = NodeBackedProvider(nodes[0].block_store, nodes[0].state_store)
    lb1 = prov.light_block(1)
    lc = Client(
        chain_id="node-chain",
        trust_options=TrustOptions(period=1e9, height=1, hash=lb1.hash()),
        primary=prov,
        witnesses=[prov],
        store=LightStore(MemDB()),
    )
    return nodes, rpc, VerifyingClient(rpc, lc)


class TestVerifyingClient:
    def test_verified_reads(self, verifying):
        nodes, rpc, vc = verifying
        blk = vc.block(2)
        assert int(blk["block"]["header"]["height"]) == 2
        cm = vc.commit(2)
        assert int(cm["signed_header"]["header"]["height"]) == 2
        vals = vc.validators(2)
        assert int(vals["total"]) == 2

    def test_verified_tx_proof(self, verifying):
        nodes, rpc, vc = verifying
        res = rpc.broadcast_tx_commit(b"lighttx=1")
        height = int(res["height"])
        nodes[0].wait_for_height(height, timeout=30)
        out = vc.tx(tx_hash(b"lighttx=1"))
        assert int(out["height"]) == height

    def test_tampering_detected(self, verifying):
        nodes, rpc, vc = verifying

        class EvilRPC:
            def __init__(self, real):
                self._real = real

            def block(self, height):
                res = self._real.block(height)
                res["block_id"]["hash"] = "66" * 32
                return res

            def __getattr__(self, name):
                return getattr(self._real, name)

        evil_vc = VerifyingClient(EvilRPC(rpc), vc._lc)
        with pytest.raises(VerificationFailed):
            evil_vc.block(3)

    def test_light_proxy_server(self, verifying):
        nodes, rpc, vc = verifying
        proxy = LightProxy(vc, "tcp://127.0.0.1:0")
        proxy.start()
        try:
            pc = HTTPClient(proxy.listen_addr)
            blk = pc.call("block", height=2)
            assert int(blk["block"]["header"]["height"]) == 2
            st = pc.call("status")
            assert st["node_info"]["network"] == "node-chain"
        finally:
            proxy.stop()
