"""Auxiliary subsystems: indexer + tx_search, rollback, inspect mode,
CLI commands, fail-point injection, pubsub queries, bit arrays."""

import json
import os
import subprocess
import sys

import pytest

from tendermint_tpu.db import MemDB
from tendermint_tpu.libs.bits import BitArray
from tendermint_tpu.libs.pubsub import Query


class TestQueryLanguage:
    def test_conditions(self):
        q = Query("tm.event='Tx' AND tx.height>5 AND app.key CONTAINS 'ab'")
        assert q.matches({"tm.event": ["Tx"], "tx.height": ["6"], "app.key": ["xaby"]})
        assert not q.matches({"tm.event": ["Tx"], "tx.height": ["5"], "app.key": ["xaby"]})
        assert not q.matches({"tm.event": ["NewBlock"], "tx.height": ["9"], "app.key": ["ab"]})
        assert Query("tx.hash EXISTS").matches({"tx.hash": ["AA"]})
        assert not Query("tx.hash EXISTS").matches({"other": ["AA"]})

    def test_invalid_query(self):
        with pytest.raises(ValueError):
            Query("this is !! not a query ==")


class TestBitArray:
    def test_ops(self):
        a = BitArray(10)
        a.set_index(2, True)
        a.set_index(7, True)
        b = BitArray(10)
        b.set_index(7, True)
        assert a.get_index(2) and not a.get_index(3)
        assert a.sub(b).get_true_indices() == [2]
        assert a.or_(b).num_true_bits() == 2
        assert a.and_(b).get_true_indices() == [7]
        assert a.not_().num_true_bits() == 8
        rt = BitArray.decode(a.encode())
        assert rt == a
        idx, ok = a.pick_random()
        assert ok and idx in (2, 7)


class TestIndexer:
    def test_index_and_search(self):
        from tendermint_tpu.abci import types as abci
        from tendermint_tpu.indexer import KVSink
        from tendermint_tpu.types.tx import tx_hash

        sink = KVSink(MemDB())
        res = abci.ResponseDeliverTx(code=0)
        sink.index_tx(
            5, 0, b"tx-a", res,
            {"tm.event": ["Tx"], "app.creator": ["alice"], "tx.height": ["5"]},
        )
        sink.index_tx(
            6, 1, b"tx-b", res,
            {"tm.event": ["Tx"], "app.creator": ["bob"], "tx.height": ["6"]},
        )
        rec = sink.get_tx(tx_hash(b"tx-a"))
        assert rec["height"] == 5
        hits = sink.search_txs("app.creator='alice'")
        assert len(hits) == 1 and hits[0]["tx"] == b"tx-a".hex()
        hits = sink.search_txs("tm.event='Tx' AND tx.height>5")
        assert len(hits) == 1 and hits[0]["height"] == 6
        sink.index_block(5, {"block.height": ["5"]})
        sink.index_block(6, {"block.height": ["6"]})
        assert sink.search_blocks("block.height='6'") == [6]

    def test_indexer_service_end_to_end(self):
        """Indexer wired to a real running chain via the eventbus."""
        from tendermint_tpu.crypto import ed25519
        from tendermint_tpu.indexer import IndexerService, KVSink
        from tests.test_consensus import make_node

        sk = ed25519.gen_priv_key(bytes([9]) * 32)
        cs, bstore, _ = make_node([sk], 0, tx_source=[b"idx=1"])
        sink = KVSink(MemDB())
        svc = IndexerService([sink], cs._event_bus)
        svc.start()
        cs.start()
        try:
            cs.wait_for_height(2, timeout=30)
        finally:
            cs.stop()
            svc.stop()
        from tendermint_tpu.types.tx import tx_hash

        rec = sink.get_tx(tx_hash(b"idx=1"))
        assert rec is not None and rec["code"] == 0


class TestRollback:
    def test_rollback_one_height(self):
        from tendermint_tpu.crypto import ed25519
        from tendermint_tpu.state.rollback import rollback_state
        from tests.test_consensus import make_node

        sk = ed25519.gen_priv_key(bytes([3]) * 32)
        cs, bstore, _ = make_node([sk], 0)
        cs.start()
        try:
            cs.wait_for_height(4, timeout=30)
        finally:
            cs.stop()
        sstore = cs._block_exec.store
        before = sstore.load()
        h = before.last_block_height
        if bstore.height() == h + 1:
            # stopped mid-apply: block persisted, state not yet. The
            # reference returns the CURRENT state unchanged
            # (rollback.go:24-29) — no state to roll back.
            new_h, app_hash = rollback_state(sstore, bstore)
            assert new_h == h
            assert app_hash == before.app_hash
            assert sstore.load().last_block_height == h
            # the normal-shutdown case must still roll back: re-run after
            # pretending the tail block was applied is not possible here,
            # so verify via the invariant error path instead
        else:
            assert bstore.height() == h
            new_h, app_hash = rollback_state(sstore, bstore)
            assert new_h == h - 1
            after = sstore.load()
            assert after.last_block_height == h - 1
            meta = bstore.load_block_meta(h)
            assert app_hash == meta.header.app_hash

    def test_rollback_mid_apply_returns_current_state(self):
        """blockstore one ahead of statestore (crash between save_block
        and state save) — rollback is a no-op returning the current state
        (rollback.go:24-29); a larger divergence is an invariant error."""
        from types import SimpleNamespace

        from tendermint_tpu.state.rollback import rollback_state

        state = SimpleNamespace(last_block_height=7, app_hash=b"\xaa" * 32)

        class SS:
            def load(self):
                return state

        class BS:
            def __init__(self, h):
                self._h = h

            def height(self):
                return self._h

        assert rollback_state(SS(), BS(8)) == (7, b"\xaa" * 32)
        with pytest.raises(RuntimeError, match="not one below or equal"):
            rollback_state(SS(), BS(9))


class TestInspect:
    def test_inspect_serves_indexer_rpcs_from_dead_node_dir(self, tmp_path):
        """VERDICT r3 item 6 / internal/inspect/rpc/rpc.go:48-66: kill a
        node, run inspect over its DATA DIR (sqlite stores + tx_index
        sink), find a tx by hash and by event query, and block_search."""
        import json
        import urllib.request

        from tendermint_tpu.abci import KVStoreApplication
        from tendermint_tpu.config import Config
        from tendermint_tpu.crypto import ed25519
        from tendermint_tpu.db import backend as db_backend
        from tendermint_tpu.inspect import Inspector
        from tendermint_tpu.node import make_node
        from tendermint_tpu.p2p import NodeKey
        from tendermint_tpu.privval import FilePV
        from tendermint_tpu.rpc import HTTPClient
        from tendermint_tpu.state.store import StateStore
        from tendermint_tpu.store import BlockStore
        from tendermint_tpu.types import Timestamp
        from tendermint_tpu.types.genesis import GenesisDoc, GenesisValidator
        from tests.test_node_rpc import FAST

        sk = ed25519.gen_priv_key(bytes([8]) * 32)
        doc = GenesisDoc(
            chain_id="inspect-chain",
            genesis_time=Timestamp(seconds=1_700_000_000),
            validators=[
                GenesisValidator(address=b"", pub_key=sk.pub_key(), power=10)
            ],
        )
        cfg = Config()
        cfg.base.home = str(tmp_path)
        cfg.base.db_backend = "sqlite"
        cfg.consensus = FAST
        cfg.p2p.laddr = "tcp://127.0.0.1:0"
        cfg.rpc.laddr = "tcp://127.0.0.1:0"
        node = make_node(
            cfg,
            app=KVStoreApplication(),
            genesis=doc,
            priv_validator=FilePV(sk),
            node_key=NodeKey.generate(bytes([42]) * 32),
            with_rpc=True,
        )
        node.start()
        try:
            rpc = HTTPClient(node.rpc_server.listen_addr)
            res = rpc.call("broadcast_tx_commit", tx="696e73703d6b6579")  # insp=key
            assert int(res["deliver_tx"]["code"]) == 0
            tx_hash_hex = res["hash"]
            height = int(res["height"])
            node.wait_for_height(height + 1, timeout=30)
        finally:
            node.stop()

        # the node is dead; inspect opens the same data dir from disk
        insp = Inspector(
            cfg,
            doc,
            StateStore(db_backend("sqlite", cfg.base.db_path("state"))),
            BlockStore(db_backend("sqlite", cfg.base.db_path("blockstore"))),
        )
        insp.start()
        try:
            rpc = HTTPClient(insp.listen_addr)
            # tx by hash
            got = rpc.call("tx", hash=tx_hash_hex)
            assert got["hash"].lower() == tx_hash_hex.lower()
            assert int(got["height"]) == height
            # tx by event query through the persisted index sink
            hits = rpc.call("tx_search", query="app.creator='Cosmoshi Netowoko'")
            assert int(hits["total_count"]) >= 1
            assert any(t["hash"].lower() == tx_hash_hex.lower() for t in hits["txs"])
            # block_search over the same sink
            blocks = rpc.call("block_search", query=f"block.height={height}")
            assert any(
                int(b["block"]["header"]["height"]) == height
                for b in blocks["blocks"]
            )
            # routes outside the inspect table are refused cleanly
            # (internal/inspect/rpc/rpc.go Routes)
            from tendermint_tpu.rpc.core import RPCError

            with pytest.raises(RPCError) as ei:
                rpc.call("broadcast_tx_sync", tx="00")
            assert ei.value.code == -32601
            # ...including over the websocket upgrade (the route gate
            # must not be bypassable by switching transports)
            from tendermint_tpu.rpc.client import WSClient

            ws = WSClient(insp.listen_addr)
            try:
                with pytest.raises(RPCError) as ei2:
                    ws.call("broadcast_tx_sync", {"tx": "00"})
                assert ei2.value.code == -32601
                got_h = ws.call("block", {"height": height})
                assert int(got_h["block"]["header"]["height"]) == height
            finally:
                ws.close()
        finally:
            insp.stop()

    def test_inspect_serves_store_rpcs(self):
        from tendermint_tpu.config import default_config
        from tendermint_tpu.crypto import ed25519
        from tendermint_tpu.inspect import Inspector
        from tendermint_tpu.rpc import HTTPClient
        from tendermint_tpu.types.genesis import GenesisDoc, GenesisValidator
        from tendermint_tpu.types import Timestamp
        from tests.test_consensus import make_node

        sk = ed25519.gen_priv_key(bytes([4]) * 32)
        cs, bstore, _ = make_node([sk], 0)
        cs.start()
        try:
            cs.wait_for_height(3, timeout=30)
        finally:
            cs.stop()
        cfg = default_config("")
        cfg.rpc.laddr = "tcp://127.0.0.1:0"
        doc = GenesisDoc(
            chain_id="cs-chain",
            genesis_time=Timestamp(seconds=1_700_000_000),
            validators=[GenesisValidator(address=b"", pub_key=sk.pub_key(), power=10)],
        )
        insp = Inspector(cfg, doc, cs._block_exec.store, bstore)
        insp.start()
        try:
            rpc = HTTPClient(insp.listen_addr)
            blk = rpc.block(2)
            assert int(blk["block"]["header"]["height"]) == 2
            vals = rpc.validators(1)
            assert int(vals["total"]) == 1
        finally:
            insp.stop()


class TestCLI:
    def test_init_and_keys(self, tmp_path):
        from tendermint_tpu.cli import main

        home = str(tmp_path / "home")
        assert main(["--home", home, "init", "validator", "--chain-id", "cli-test"]) == 0
        assert os.path.exists(os.path.join(home, "config", "genesis.json"))
        assert os.path.exists(os.path.join(home, "config", "priv_validator_key.json"))
        assert os.path.exists(os.path.join(home, "config", "config.toml"))
        # idempotent re-init keeps the same key
        with open(os.path.join(home, "config", "node_key.json")) as fh:
            nk1 = json.load(fh)["id"]
        assert main(["--home", home, "init", "validator"]) == 0
        with open(os.path.join(home, "config", "node_key.json")) as fh:
            assert json.load(fh)["id"] == nk1

    def test_testnet_generation(self, tmp_path):
        from tendermint_tpu.cli import main
        from tendermint_tpu.config import Config

        out = str(tmp_path / "net")
        assert main(["testnet", "--v", "3", "--o", out, "--chain-id", "net-test"]) == 0
        for i in range(3):
            cfg = Config.load(os.path.join(out, f"node{i}", "config", "config.toml"))
            assert cfg.p2p.persistent_peers.count("@") == 3
        g0 = open(os.path.join(out, "node0", "config", "genesis.json")).read()
        g1 = open(os.path.join(out, "node1", "config", "genesis.json")).read()
        assert g0 == g1
        assert json.loads(g0)["chain_id"] == "net-test"

    def test_unsafe_reset(self, tmp_path):
        from tendermint_tpu.cli import main

        home = str(tmp_path / "home")
        main(["--home", home, "init", "validator"])
        marker = os.path.join(home, "data", "junk.db")
        open(marker, "w").write("x")
        assert main(["--home", home, "unsafe-reset-all"]) == 0
        assert not os.path.exists(marker)


class TestSQLSink:
    """psql sink parity (internal/state/indexer/sink/psql + schema.sql)
    over DB-API — exercised here on sqlite3; production plugs a psycopg2
    connection factory."""

    def _sink(self):
        import sqlite3

        from tendermint_tpu.indexer.sql_sink import SQLSink

        return SQLSink(lambda: sqlite3.connect(":memory:"), "sql-chain")

    def test_blocks_txs_events_roundtrip(self):
        sink = self._sink()
        sink.index_block(1, {"block.proposer": ["aa"]})

        class _R:
            code = 0

        sink.index_tx(1, 0, b"tx-1", _R(), {"transfer.to": ["alice"]})
        sink.index_tx(1, 1, b"tx-2", _R(), {"transfer.to": ["bob"]})
        # idempotent re-index (same block/index)
        sink.index_tx(1, 1, b"tx-2", _R(), {"transfer.to": ["bob"]})
        assert sink.tx_count() == 2
        from tendermint_tpu.types.tx import tx_hash

        found = sink.find_tx_hashes_by_event("transfer.to", "alice")
        assert found == [tx_hash(b"tx-1").hex().upper()]
        sink.close()

    def test_multi_block_unique_constraint(self):
        sink = self._sink()
        for h in (1, 2, 3):
            sink.index_block(h, {"k.a": [str(h)]})
            sink.index_block(h, {"k.b": [str(h)]})  # same height, more events
        cur = sink._conn.cursor()
        cur.execute("SELECT COUNT(*) FROM blocks")
        assert cur.fetchone()[0] == 3
        sink.close()


class TestWALTools:
    def test_wal2json_json2wal_roundtrip(self, tmp_path, capsys):
        """scripts/wal2json + json2wal parity: binary -> JSON lines ->
        binary reproduces the byte-identical CRC-framed WAL."""
        import json as _json
        import struct
        import zlib

        from tendermint_tpu import cli
        from tendermint_tpu.consensus.wal import WAL, WALMessage, _encode_record

        wal_path = tmp_path / "wal"
        msgs = [
            WALMessage(end_height=3),
            WALMessage(timeout=(1000, 4, 0, 1)),
            WALMessage(msg_kind="vote", msg_payload=b"\x01\x02\xff", peer_id="p1"),
            WALMessage(msg_kind="block_part", msg_payload=b"\x00" * 40, peer_id=""),
        ]
        with open(wal_path, "wb") as fh:
            for m in msgs:
                body = _encode_record(m)
                crc = zlib.crc32(body) & 0xFFFFFFFF
                fh.write(struct.pack(">II", crc, len(body)) + body)
        orig = wal_path.read_bytes()

        assert cli.main(["wal2json", str(wal_path)]) == 0
        lines = capsys.readouterr().out.strip().splitlines()
        assert len(lines) == 4
        assert _json.loads(lines[0]) == {"end_height": 3}
        assert _json.loads(lines[2])["msg"]["kind"] == "vote"

        json_path = tmp_path / "wal.json"
        json_path.write_text("\n".join(lines) + "\n")
        out_path = tmp_path / "wal2"
        assert cli.main(["json2wal", str(out_path), "--input", str(json_path)]) == 0
        assert out_path.read_bytes() == orig
        # and it decodes back to the same records
        assert [m.end_height for m in WAL._iter_file(str(out_path))] == [
            m.end_height for m in msgs
        ]
