"""Light client + blocksync tests.

Light: sequential + skipping verification against a real produced chain,
witness divergence detection, backwards verification.
Blocksync: a fresh node catches up from a peer over the memory transport,
verifying every block on the batch path (SURVEY.md §7 stage 6).
"""

import time

import pytest

from tendermint_tpu.crypto import ed25519
from tendermint_tpu.db import MemDB
from tendermint_tpu.light import (
    Client,
    LightStore,
    NodeBackedProvider,
    TrustOptions,
    verify_adjacent,
)
from tendermint_tpu.light.client import ErrLightClientAttack
from tendermint_tpu.p2p import (
    MemoryTransport,
    NodeKey,
    PeerAddress,
    PeerManager,
    Router,
    new_memory_network,
)
from tendermint_tpu.types import SignedHeader, Timestamp
from tests.test_consensus import FAST, make_node


@pytest.fixture(scope="module")
def produced_chain():
    """A 1-validator chain run to height >= 5, exposing node internals."""
    sk = ed25519.gen_priv_key(bytes([7]) * 32)
    cs, bstore, _ = make_node([sk], 0)
    cs.start()
    try:
        cs.wait_for_height(5, timeout=60)
    finally:
        cs.stop()
    return cs, bstore


def _provider(cs, bstore):
    return NodeBackedProvider(bstore, cs._block_exec.store)


class TestLightClient:
    def _client(self, cs, bstore, sequential=False, witnesses=None):
        prov = _provider(cs, bstore)
        lb1 = prov.light_block(1)
        return Client(
            chain_id="cs-chain",
            trust_options=TrustOptions(period=1e9, height=1, hash=lb1.hash()),
            primary=prov,
            witnesses=witnesses if witnesses is not None else [prov],
            store=LightStore(MemDB()),
            sequential=sequential,
        )

    def test_sequential_verification(self, produced_chain):
        cs, bstore = produced_chain
        c = self._client(cs, bstore, sequential=True)
        lb = c.verify_light_block_at_height(4)
        assert lb.height == 4
        # all intermediate headers are now trusted
        assert c.trusted_light_block(2) is not None
        assert c.trusted_light_block(3) is not None

    def test_skipping_verification(self, produced_chain):
        cs, bstore = produced_chain
        c = self._client(cs, bstore)
        lb = c.verify_light_block_at_height(5)
        assert lb.height == 5

    def test_backwards_verification(self, produced_chain):
        cs, bstore = produced_chain
        prov = _provider(cs, bstore)
        lb4 = prov.light_block(4)
        c = Client(
            chain_id="cs-chain",
            trust_options=TrustOptions(period=1e9, height=4, hash=lb4.hash()),
            primary=prov,
            witnesses=[prov],
            store=LightStore(MemDB()),
        )
        lb2 = c.verify_light_block_at_height(2)
        assert lb2.height == 2

    def test_witness_divergence_detected(self, produced_chain):
        cs, bstore = produced_chain
        prov = _provider(cs, bstore)

        class EvilWitness(NodeBackedProvider):
            armed = False  # honest during client init (the root cross-check)

            def light_block(self, height):
                lb = super().light_block(height)
                if not self.armed:
                    return lb
                from dataclasses import replace

                evil_header = replace(lb.signed_header.header, app_hash=b"\x66" * 32)
                return type(lb)(
                    signed_header=SignedHeader(
                        header=evil_header, commit=lb.signed_header.commit
                    ),
                    validators=lb.validators,
                )

        evil = EvilWitness(bstore, cs._block_exec.store)
        c = self._client(cs, bstore, witnesses=[evil])
        evil.armed = True
        # the witness can't sustain its forged header (its commit signs the
        # real one), so it is removed and cross-referencing fails
        # (detector.go:88-101); the sustained-forgery attack path is covered
        # in tests/test_light_attack.py
        from tendermint_tpu.light.client import ErrFailedHeaderCrossReferencing

        with pytest.raises(ErrFailedHeaderCrossReferencing):
            c.verify_light_block_at_height(3)
        assert c._witnesses == []

    def test_expired_trust_rejected(self, produced_chain):
        cs, bstore = produced_chain
        c = self._client(cs, bstore)
        # "now" far in the future: trusted header expired
        future = Timestamp(seconds=2**35, nanos=0)
        from tendermint_tpu.light.verifier import ErrOldHeaderExpired

        with pytest.raises(ErrOldHeaderExpired):
            c.verify_light_block_at_height(5, now=future)


class TestBlockSync:
    def test_fresh_node_catches_up(self, produced_chain):
        from tendermint_tpu.blocksync import BLOCKSYNC_DESC, BlockSyncReactor
        from tendermint_tpu.state import make_genesis_state
        from tendermint_tpu.state.execution import BlockExecutor
        from tendermint_tpu.state.store import StateStore
        from tendermint_tpu.store import BlockStore
        from tendermint_tpu.abci import KVStoreApplication, LocalClient
        from tendermint_tpu.types.genesis import GenesisDoc, GenesisValidator

        cs, src_store = produced_chain

        hub = new_memory_network()
        keys = [NodeKey.generate(bytes([i + 30]) * 32) for i in range(2)]
        routers = []
        for i in range(2):
            t = MemoryTransport(hub, keys[i].node_id, keys[i].pub_key)
            pm = PeerManager(keys[i].node_id)
            r = Router(t, pm, keys[i].node_id)
            routers.append(r)

        # node 0: serves the produced chain
        serving = BlockSyncReactor(
            routers[0], src_store, cs._block_exec, cs.committed_state
        )

        # node 1: fresh from genesis
        sk = ed25519.gen_priv_key(bytes([7]) * 32)
        doc = GenesisDoc(
            chain_id="cs-chain",
            genesis_time=Timestamp(seconds=1_700_000_000),
            validators=[GenesisValidator(address=b"", pub_key=sk.pub_key(), power=10)],
        )
        genesis = make_genesis_state(doc)
        sstore = StateStore(MemDB())
        sstore.save(genesis)
        fresh_store = BlockStore(MemDB())
        ex = BlockExecutor(sstore, LocalClient(KVStoreApplication()), block_store=fresh_store)
        caught = []
        syncing = BlockSyncReactor(
            routers[1], fresh_store, ex, genesis, on_caught_up=lambda s: caught.append(s)
        )

        routers[0]._pm.add_address(PeerAddress(keys[1].node_id, keys[1].node_id))
        for r in routers:
            r.start()
        serving.start()
        syncing.start()
        target = src_store.height() - 1  # can't verify the tip without a next block
        deadline = time.time() + 30
        try:
            while time.time() < deadline and fresh_store.height() < target:
                time.sleep(0.1)
        finally:
            serving.stop()
            syncing.stop()
            for r in routers:
                r.stop()
        assert fresh_store.height() >= target
        for h in range(1, target + 1):
            assert fresh_store.load_block(h).hash() == src_store.load_block(h).hash()
        assert caught, "on_caught_up was not reported"


class TestLightClientAPI:
    """client.go public-surface parity: VerifyHeader, height accessors,
    witness management, init-time witness cross-check."""

    def _client(self, cs, bstore, witnesses=None):
        prov = _provider(cs, bstore)
        lb1 = prov.light_block(1)
        return Client(
            chain_id="cs-chain",
            trust_options=TrustOptions(period=1e9, height=1, hash=lb1.hash()),
            primary=prov,
            witnesses=witnesses if witnesses is not None else [prov],
            store=LightStore(MemDB()),
        ), prov

    def test_verify_header_and_accessors(self, produced_chain):
        cs, bstore = produced_chain
        c, prov = self._client(cs, bstore)
        assert c.chain_id() == "cs-chain"
        assert c.primary() is prov
        assert c.last_trusted_height() == 1
        assert c.first_trusted_height() == 1
        hdr3 = prov.light_block(3).signed_header.header
        c.verify_header(hdr3)  # fetches + verifies through the primary
        assert c.last_trusted_height() >= 3
        # re-verifying a trusted header is a no-op; a mismatching one errors
        c.verify_header(hdr3)
        from dataclasses import replace

        import pytest as _pytest

        forged = replace(hdr3, app_hash=b"\x13" * 32)
        with _pytest.raises(ValueError):
            c.verify_header(forged)

    def test_witness_management(self, produced_chain):
        cs, bstore = produced_chain
        c, prov = self._client(cs, bstore)
        extra = _provider(cs, bstore)
        c.add_provider(extra)
        assert len(c.witnesses()) == 2
        c.remove_witnesses([0])
        assert c.witnesses() == [extra]
        import pytest as _pytest

        with _pytest.raises(RuntimeError):
            c.remove_witnesses([0])  # cannot remove all witnesses
        c.cleanup()
        assert c.last_trusted_height() == -1

    def test_init_conflicting_witness_rejected(self, produced_chain):
        """compareFirstHeaderWithWitnesses: a witness serving a different
        root header aborts client construction."""
        from dataclasses import replace

        import pytest as _pytest

        from tendermint_tpu.light.client import ErrLightClientAttack
        from tendermint_tpu.light.provider import LightBlock

        cs, bstore = produced_chain
        prov = _provider(cs, bstore)

        class ConflictingWitness(type(prov)):
            def light_block(self, height):
                lb = super().light_block(height)
                return LightBlock(
                    signed_header=SignedHeader(
                        header=replace(
                            lb.signed_header.header, app_hash=b"\x31" * 32
                        ),
                        commit=lb.signed_header.commit,
                    ),
                    validators=lb.validators,
                )

        evil = ConflictingWitness(bstore, cs._block_exec.store)
        lb1 = prov.light_block(1)
        with _pytest.raises(ErrLightClientAttack):
            Client(
                chain_id="cs-chain",
                trust_options=TrustOptions(period=1e9, height=1, hash=lb1.hash()),
                primary=prov,
                witnesses=[evil],
                store=LightStore(MemDB()),
            )
