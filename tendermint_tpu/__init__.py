"""tendermint_tpu — a TPU-native BFT state-machine-replication framework.

A from-scratch rebuild of the capability surface of Tendermint Core
(reference: /root/reference, v0.35.0-unreleased): BFT consensus, authenticated
P2P gossip, mempool, evidence, block/state sync, light clients, ABCI
application boundary, RPC, and validator key management — with the
per-height vote-signature verification hot path (VerifyCommit /
VerifyCommitLight and the light-client header loop) offloaded to batched,
fixed-shape JAX/Pallas kernels on TPU behind the `crypto.batch` seam.

Layout (mirrors the reference layer map in SURVEY.md §1, redesigned TPU-first):
  crypto/    key/signature/hash abstractions + host implementations
  ops/       TPU compute path: limb field arithmetic, curve ops, batched verify
  parallel/  device-mesh sharding of the verification batch (pjit/shard_map)
  wire/      deterministic protobuf encoding (sign bytes are consensus-critical)
  types/     Block/Vote/ValidatorSet/Commit + commit verification
  abci/      application boundary
  storage/   key-value, block and state stores
  mempool/   priority mempool + gossip
  consensus/ the BFT state machine, WAL, replay
  p2p/       router, peer manager, transports, secret connection
  light/     light client verification
  privval/   validator key management (file + remote signers)
  rpc/       JSON-RPC service
  node/      composition root
"""

__version__ = "0.1.0"
