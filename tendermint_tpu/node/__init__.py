"""Node composition — wires every subsystem into a running node.

Reference parity: node/node.go:122 makeNode + node/setup.go factories:
DBs → stores → ABCI proxy (4 logical connections) → handshake/replay →
mempool/evidence → consensus (+WAL, privval) → p2p router + reactors →
RPC. Startup-mode selection (statesync → blocksync → consensus,
node.go:217-247) is driven by config + peer state.
"""

from __future__ import annotations

import os
import threading
import time
from dataclasses import dataclass, field
from typing import List, Optional

from ..abci import LocalClient, SocketClient
from ..abci.application import Application
from ..blocksync import BLOCKSYNC_DESC, BlockSyncReactor
from ..config import Config, MODE_SEED, MODE_VALIDATOR
from ..consensus import WAL, ConsensusState
from ..consensus.reactor import ALL_DESCS as CONSENSUS_DESCS, ConsensusReactor
from ..consensus.replay import Handshaker
from ..db import MemDB, backend as db_backend
from ..eventbus import EventBus
from ..evidence import Pool as EvidencePool
from ..evidence.reactor import EVIDENCE_DESC, EvidenceReactor
from ..mempool import TxMempool
from ..mempool.reactor import MEMPOOL_DESC, MempoolReactor
from ..p2p import MConnTransport, MemoryTransport, NodeKey, PeerManager, Router
from ..p2p.pex import PEX_DESC
from ..privval import FilePV
from ..state import make_genesis_state
from ..state.execution import BlockExecutor
from ..state.store import StateStore
from ..store import BlockStore
from ..types.genesis import GenesisDoc

ALL_CHANNEL_DESCS = CONSENSUS_DESCS + [BLOCKSYNC_DESC, MEMPOOL_DESC, EVIDENCE_DESC, PEX_DESC]


@dataclass
class Node:
    """A fully wired node (node.go nodeImpl)."""

    config: Config
    genesis: GenesisDoc
    node_key: NodeKey
    event_bus: EventBus
    state_store: StateStore
    block_store: BlockStore
    mempool: TxMempool
    evidence_pool: EvidencePool
    block_exec: BlockExecutor
    consensus: ConsensusState
    router: Optional[Router] = None
    consensus_reactor: Optional[ConsensusReactor] = None
    mempool_reactor: Optional[MempoolReactor] = None
    evidence_reactor: Optional[EvidenceReactor] = None
    blocksync_reactor: Optional[BlockSyncReactor] = None
    statesync_reactor: object = None
    pex_reactor: object = None
    rpc_server: object = None
    proxy_app: object = None
    indexer_service: object = None
    tx_index_sink: object = None
    metrics_server: object = None       # libs.metrics.MetricsServer
    metrics_registry: object = None     # this node's Registry
    _started: bool = False
    _stopping: threading.Event = field(default_factory=threading.Event)
    # serializes startup-mode handoffs against stop(): a handoff holds it
    # across the _stopping check AND consensus.start(), and stop() sets
    # _stopping under it, so a late handoff can never resurrect consensus
    # on a node whose reactors were already torn down
    _handoff_mtx: threading.RLock = field(default_factory=threading.RLock)

    def start(self) -> None:
        """OnStart (node.go:490-560) + startup-mode selection
        (node.go:217-247,323-343): statesync -> blocksync -> consensus."""
        if self.metrics_server is not None:
            self.metrics_server.start()
        if self.indexer_service is not None:
            self.indexer_service.start()
        if self.router is not None:
            self.router.start()
        for r in (self.mempool_reactor, self.evidence_reactor,
                  self.consensus_reactor, self.pex_reactor,
                  self.statesync_reactor):
            if r is not None:
                r.start()
        from ..config import MODE_SEED as _seed

        if self.config.base.mode != _seed:
            if self._should_state_sync():
                threading.Thread(target=self._run_state_sync, daemon=True).start()
            elif self._should_block_sync():
                self._start_blocksync_then_consensus()
            else:
                # straight to consensus — still SERVE blocksync requests
                # so peers can catch up from this node
                if self.blocksync_reactor is not None:
                    self.blocksync_reactor.stop_consuming()
                    self.blocksync_reactor.start()
                self.consensus.start()
        if self.rpc_server is not None:
            self.rpc_server.start()
        self._started = True

    # -- startup-mode selection (node.go:217-247) ------------------------

    def _should_state_sync(self) -> bool:
        cfg = self.config.statesync
        return bool(
            self.statesync_reactor is not None
            and cfg.enable
            and cfg.trust_hash
            and cfg.trust_height > 0
            and self.block_store.height() == 0
        )

    def _should_block_sync(self) -> bool:
        """Route through blocksync only when there are peers to sync from
        (pool.is_caught_up needs at least one reporting peer; a loner
        node must start consensus directly)."""
        return bool(
            self.blocksync_reactor is not None
            and self.config.blocksync.enable
            and self.config.p2p.persistent_peers
        )

    def _run_state_sync(self) -> None:
        """syncer.SyncAny + backfill, then hand off (node.go:323-343).
        ANY failure (bad trust hash, sync errors) must fall through to the
        next startup mode — a dead daemon thread would leave the node
        serving RPC but never progressing."""
        from ..state import make_genesis_state
        from ..statesync import SyncError

        cfg = self.config.statesync
        synced_state = None
        if self._stopping.is_set():
            return
        try:
            genesis_state = make_genesis_state(self.genesis)
            trust_hash = cfg.trust_hash.lower().removeprefix("0x")
            state, _commit = self.statesync_reactor.sync_any(
                genesis_state,
                trust_height=cfg.trust_height,
                trust_hash=bytes.fromhex(trust_hash),
                discovery_time=cfg.discovery_time_ms / 1000.0,
                chunk_timeout=cfg.chunk_request_timeout_ms / 1000.0,
            )
            try:
                self.statesync_reactor.backfill(state)
            except SyncError:
                pass  # best effort (evidence window may be unservable)
            self.consensus.catch_up_to_state(state)
            synced_state = state
        except SyncError as e:
            print(f"state sync failed: {e}; falling back", flush=True)
        except Exception as e:  # noqa: BLE001 — e.g. malformed trust hash
            print(f"state sync aborted: {e}; falling back", flush=True)
        if synced_state is not None and self.blocksync_reactor is not None:
            # re-point the pool at the restored height: re-requesting from
            # genesis would re-apply old blocks against the restored app
            self.blocksync_reactor.reset_to_state(synced_state)
        with self._handoff_mtx:
            if self._stopping.is_set():
                return
            if self._should_block_sync():
                start_blocksync = True
            else:
                start_blocksync = False
                if self.blocksync_reactor is not None:
                    self.blocksync_reactor.stop_consuming()
                    self.blocksync_reactor.start()
                self.consensus.start()
        if start_blocksync:
            self._start_blocksync_then_consensus()

    def _start_blocksync_then_consensus(self) -> None:
        """Catch up over the blocksync channel, then switch to consensus
        when the pool reports caught-up; a watchdog switches anyway when
        blocksync makes no progress (this node may BE the tip, or its
        peers may be unable to serve)."""
        switched = threading.Event()

        def switch(state) -> None:
            # single-shot under the node handoff lock: on_caught_up and
            # the watchdog can race at the deadline boundary, and stop()
            # sets _stopping under the same lock — holding it across
            # consensus.start() closes the check-then-start TOCTOU window
            with self._handoff_mtx:
                if switched.is_set() or self._stopping.is_set():
                    return
                switched.set()
                self.blocksync_reactor.stop_consuming()
                try:
                    self.consensus.catch_up_to_state(state)
                except RuntimeError:
                    return  # already running (defensive)
                self.consensus.start()

        with self._handoff_mtx:
            if self._stopping.is_set():
                return  # stop() won the race before blocksync began
            self.blocksync_reactor._on_caught_up = switch
            self.blocksync_reactor.start()

        def watchdog() -> None:
            # refresh on PROGRESS (height advancing), not on peer
            # presence: a stalled peer must not postpone consensus forever
            last_height = self.block_store.height()
            deadline = time.time() + 10.0
            hard_deadline = time.time() + 120.0
            while time.time() < min(deadline, hard_deadline):
                if switched.is_set() or self._stopping.is_set():
                    return
                h = self.block_store.height()
                if h > last_height:
                    last_height = h
                    deadline = time.time() + 10.0
                time.sleep(0.25)
            switch(self.blocksync_reactor._state)

        threading.Thread(target=watchdog, daemon=True).start()

    def stop(self) -> None:
        # set under the handoff lock: any in-flight handoff either finishes
        # starting consensus before we proceed (and gets stopped below), or
        # observes _stopping and aborts
        with self._handoff_mtx:
            self._stopping.set()
        if self.rpc_server is not None:
            self.rpc_server.stop()
        from ..config import MODE_SEED as _seed

        if self.config.base.mode != _seed:
            self.consensus.stop()
        for r in (self.consensus_reactor, self.mempool_reactor,
                  self.evidence_reactor, self.blocksync_reactor,
                  self.statesync_reactor, self.pex_reactor):
            if r is not None:
                r.stop()
        if self.router is not None:
            self.router.stop()
        if self.indexer_service is not None:
            self.indexer_service.stop()
        if self.metrics_server is not None:
            self.metrics_server.stop()
        self._flush_trace()

    def _flush_trace(self) -> None:
        """OnStop trace flush: leave a COMPLETE Chrome-trace file on
        shutdown (SIGTERM included — cli start routes SIGTERM here)."""
        from ..observability import trace as _trace

        if not _trace.TRACER.enabled:
            return
        path = self.config.instrumentation.trace_dump_path
        if not path:
            return
        if not os.path.isabs(path) and self.config.base.home:
            path = os.path.join(self.config.base.home, path)
        try:
            _trace.TRACER.dump(path)
        except OSError as e:
            print(f"trace flush to {path} failed: {e}", flush=True)

    @property
    def node_id(self) -> str:
        return self.node_key.node_id

    def wait_for_height(self, height: int, timeout: float = 60.0) -> None:
        self.consensus.wait_for_height(height, timeout)


def make_node(
    config: Config,
    app: Optional[Application] = None,
    genesis: Optional[GenesisDoc] = None,
    priv_validator: Optional[FilePV] = None,
    node_key: Optional[NodeKey] = None,
    transport=None,
    with_rpc: bool = False,
) -> Node:
    """node.go:122 makeNode. `app` in-process means the "local" ABCI client
    (abci/client/local_client.go); otherwise config.proxy_app is dialed."""
    home = config.base.home
    if home:
        config.ensure_dirs()

    # genesis
    if genesis is None:
        genesis = GenesisDoc.from_file(config.base.genesis_path())
    genesis.validate_and_complete()

    # node key
    if node_key is None:
        if home:
            node_key = NodeKey.load_or_generate(config.base.node_key_path())
        else:
            node_key = NodeKey.generate()

    # DBs + stores (node.go initDBs)
    def _db(name: str):
        if config.base.db_backend in ("memdb", "mem") or not home:
            return MemDB()
        return db_backend(config.base.db_backend, config.base.db_path(name))

    block_store = BlockStore(_db("blockstore"))
    state_store = StateStore(_db("state"))

    # state bootstrap
    state = state_store.load()
    if state is None:
        state = make_genesis_state(genesis)
        state_store.save(state)

    # ABCI clients (proxy.AppConns: one logical conn per use here)
    if app is not None:
        consensus_conn = LocalClient(app)
        mempool_conn = LocalClient(app)
        query_conn = LocalClient(app)
    else:
        consensus_conn = SocketClient(config.base.proxy_app)
        mempool_conn = SocketClient(config.base.proxy_app)
        query_conn = SocketClient(config.base.proxy_app)

    event_bus = EventBus()

    # handshake / replay (node.go:227)
    handshaker = Handshaker(state_store, state, block_store, genesis, event_bus)
    state = handshaker.handshake(consensus_conn)

    # mempool + evidence
    mempool = TxMempool(mempool_conn, config.mempool, height=state.last_block_height)
    evidence_pool = EvidencePool(
        MemDB() if not home else _db("evidence"),
        state_store=state_store,
        block_store=block_store,
    )
    evidence_pool.set_state(state)

    block_exec = BlockExecutor(
        state_store,
        consensus_conn,
        mempool=mempool,
        evpool=evidence_pool,
        block_store=block_store,
        event_bus=event_bus,
    )

    # privval
    if priv_validator is None and config.base.mode == MODE_VALIDATOR and home:
        priv_validator = FilePV.load_or_generate(
            config.priv_validator.key_path(home),
            config.priv_validator.state_path(home),
        )

    # instrumentation (node.go:377 createAndStartPrometheusServer + the
    # defaultMetricsProvider wiring in setup.go): per-node registry for
    # consensus/mempool/p2p sets; the process-wide ops registry (device
    # verify engine) is served alongside it.
    registry = None
    cons_metrics = None
    mp_metrics = None
    p2p_metrics = None
    if config.instrumentation.prometheus:
        from ..libs import metrics as _metrics

        registry = _metrics.Registry(config.instrumentation.namespace)
        cons_metrics = _metrics.ConsensusMetrics(registry)
        mp_metrics = _metrics.MempoolMetrics(registry)
        p2p_metrics = _metrics.P2PMetrics(registry)
        mempool.metrics = mp_metrics
        _metrics.ops_metrics()  # eager: ops families expose before traffic
    if config.instrumentation.tracing:
        from ..observability import trace as _trace

        _trace.configure(
            enabled=True, capacity=config.instrumentation.trace_buffer_size
        )

    wal = None
    if home:
        import os as _os

        from ..libs import autofile as _autofile

        wal = WAL(
            config.consensus.wal_path(home),
            head_size_limit=int(
                _os.environ.get(
                    "TM_TPU_WAL_HEAD_LIMIT", _autofile.DEFAULT_HEAD_SIZE_LIMIT
                )
            ),
        )

    consensus = ConsensusState(
        config.consensus,
        state,
        block_exec,
        block_store,
        mempool=mempool,
        evpool=evidence_pool,
        event_bus=event_bus,
        wal=wal,
        priv_validator=priv_validator,
        metrics=cons_metrics,
    )

    # p2p (node.go createTransport/createPeerManager/createRouter)
    router = None
    consensus_reactor = None
    mempool_reactor = None
    evidence_reactor = None
    if transport is None and config.p2p.laddr and config.p2p.laddr != "none":
        from ..types.node_info import NodeInfo

        node_info = NodeInfo(
            node_id=node_key.node_id,
            listen_addr=config.p2p.laddr,
            network=genesis.chain_id,
            moniker=config.base.moniker,
            channels=bytes(d.id for d in ALL_CHANNEL_DESCS),
        )
        transport = MConnTransport(node_key.priv_key, ALL_CHANNEL_DESCS, node_info)
        addr = config.p2p.laddr
        for prefix in ("tcp://",):
            if addr.startswith(prefix):
                addr = addr[len(prefix):]
        transport.listen(addr)
    pex_reactor = None
    blocksync_reactor = None
    statesync_reactor = None
    if transport is not None:
        pm_db = MemDB() if not home else _db("peers")
        peer_manager = PeerManager(
            node_key.node_id, pm_db, max_connected=config.p2p.max_connections
        )
        router = Router(transport, peer_manager, node_key.node_id)
        if config.base.mode != MODE_SEED:
            consensus_reactor = ConsensusReactor(consensus, router)
            mempool_reactor = MempoolReactor(
                mempool, router, broadcast=config.mempool.broadcast
            )
            evidence_reactor = EvidenceReactor(evidence_pool, router)
            if config.blocksync.enable:
                blocksync_reactor = BlockSyncReactor(
                    router, block_store, block_exec, state
                )
            # the statesync reactor always SERVES snapshots/light blocks/
            # params (reactor.go runs in every full node); RESTORING via
            # sync_any only happens when configured (Node.start)
            from ..statesync import StateSyncReactor

            if True:

                statesync_reactor = StateSyncReactor(
                    router,
                    query_conn,
                    state_store,
                    block_store,
                    genesis.chain_id,
                    serving=True,
                )
        if config.p2p.pex:
            from ..p2p.pex import PexReactor

            pex_reactor = PexReactor(router, peer_manager)
        # persistent peers
        from ..p2p import PeerAddress

        for entry in filter(None, config.p2p.persistent_peers.split(",")):
            nid, _, paddr = entry.partition("@")
            peer_manager.add_address(PeerAddress(nid.strip(), paddr.strip()), persistent=True)

    # indexer (node.go createAndStartIndexerService)
    indexer_service = None
    tx_index_sink = None
    if "kv" in config.tx_index.indexer:
        from ..indexer import IndexerService, KVSink

        tx_index_sink = KVSink(MemDB() if not home else _db("tx_index"))
        indexer_service = IndexerService([tx_index_sink], event_bus)

    node = Node(
        config=config,
        genesis=genesis,
        node_key=node_key,
        event_bus=event_bus,
        state_store=state_store,
        block_store=block_store,
        mempool=mempool,
        evidence_pool=evidence_pool,
        block_exec=block_exec,
        consensus=consensus,
        router=router,
        consensus_reactor=consensus_reactor,
        mempool_reactor=mempool_reactor,
        evidence_reactor=evidence_reactor,
        proxy_app=query_conn,
    )
    node.pex_reactor = pex_reactor
    node.blocksync_reactor = blocksync_reactor
    node.statesync_reactor = statesync_reactor
    node.indexer_service = indexer_service
    node.tx_index_sink = tx_index_sink
    if registry is not None:
        from ..libs import metrics as _metrics

        def _collect() -> None:
            # pull-style gauges sampled at scrape time
            mp_metrics.size.set(mempool.size())
            mp_metrics.size_bytes.set(mempool.size_bytes())
            p2p_metrics.peers.set(
                len(node.router.connected()) if node.router else 0
            )

        registry.add_collect_hook(_collect)
        node.metrics_registry = registry
        node.metrics_server = _metrics.MetricsServer(
            [registry, _metrics.global_registry()],
            config.instrumentation.prometheus_listen_addr,
        )
    if with_rpc and config.rpc.laddr:
        from ..rpc.server import RPCServer
        from ..rpc.core import Environment

        env = Environment(node)
        cert, key = config.rpc.tls_cert_file, config.rpc.tls_key_file
        cfg_dir = (
            os.path.join(home, "config") if home else ""
        )
        if cert and not os.path.isabs(cert):
            cert = os.path.join(cfg_dir, cert)
        if key and not os.path.isabs(key):
            key = os.path.join(cfg_dir, key)
        node.rpc_server = RPCServer(
            config.rpc.laddr, env, tls_cert_file=cert, tls_key_file=key
        )
    return node
