"""Configuration tree.

Reference parity: config/config.go — Base/RPC/P2P/Mempool/StateSync/
Consensus/TxIndex/Instrumentation sections with the reference's defaults
(consensus timeouts config.go:956-962), TOML load/save via stdlib tomllib
+ a minimal writer, node modes validator/full/seed.
"""

from __future__ import annotations

import os
from dataclasses import dataclass, field, asdict
from typing import List, Optional

MODE_FULL = "full"
MODE_VALIDATOR = "validator"
MODE_SEED = "seed"


@dataclass
class BaseConfig:
    """config.go BaseConfig."""

    home: str = ""
    chain_id: str = ""
    moniker: str = "anonymous"
    mode: str = MODE_VALIDATOR
    db_backend: str = "sqlite"
    db_dir: str = "data"
    genesis_file: str = "config/genesis.json"
    node_key_file: str = "config/node_key.json"
    abci: str = "socket"
    proxy_app: str = "tcp://127.0.0.1:26658"
    filter_peers: bool = False

    def genesis_path(self) -> str:
        return os.path.join(self.home, self.genesis_file)

    def node_key_path(self) -> str:
        return os.path.join(self.home, self.node_key_file)

    def db_path(self, name: str) -> str:
        return os.path.join(self.home, self.db_dir, f"{name}.db")


@dataclass
class PrivValidatorConfig:
    """config.go PrivValidatorConfig."""

    key_file: str = "config/priv_validator_key.json"
    state_file: str = "data/priv_validator_state.json"
    listen_addr: str = ""

    def key_path(self, home: str) -> str:
        return os.path.join(home, self.key_file)

    def state_path(self, home: str) -> str:
        return os.path.join(home, self.state_file)


@dataclass
class RPCConfig:
    laddr: str = "tcp://127.0.0.1:26657"
    cors_allowed_origins: List[str] = field(default_factory=list)
    unsafe: bool = False
    max_open_connections: int = 900
    max_subscription_clients: int = 100
    max_subscriptions_per_client: int = 5
    timeout_broadcast_tx_commit_ms: int = 10000
    max_body_bytes: int = 1000000
    max_header_bytes: int = 1 << 20
    # TLS: both set -> the RPC server serves HTTPS/WSS
    # (rpc/jsonrpc/server/http_server.go ServeTLS; config.go TLSCertFile).
    # Relative paths resolve under <home>/config/.
    tls_cert_file: str = ""
    tls_key_file: str = ""
    pprof_laddr: str = ""


@dataclass
class P2PConfig:
    laddr: str = "tcp://0.0.0.0:26656"
    external_address: str = ""
    persistent_peers: str = ""
    bootstrap_peers: str = ""
    max_connections: int = 64
    max_incoming_connection_attempts: int = 100
    flush_throttle_timeout_ms: int = 100
    max_packet_msg_payload_size: int = 1400
    send_rate: int = 5120000
    recv_rate: int = 5120000
    pex: bool = True
    private_peer_ids: str = ""
    allow_duplicate_ip: bool = False
    handshake_timeout_ms: int = 20000
    dial_timeout_ms: int = 3000


@dataclass
class MempoolConfig:
    recheck: bool = True
    broadcast: bool = True
    size: int = 5000
    max_txs_bytes: int = 1073741824  # 1GB
    cache_size: int = 10000
    keep_invalid_txs_in_cache: bool = False
    max_tx_bytes: int = 1048576  # 1MB
    ttl_duration_ms: int = 0
    ttl_num_blocks: int = 0


@dataclass
class StateSyncConfig:
    enable: bool = False
    rpc_servers: List[str] = field(default_factory=list)
    trust_height: int = 0
    trust_hash: str = ""
    trust_period_ms: int = 168 * 3600 * 1000  # 1 week
    discovery_time_ms: int = 15000
    chunk_request_timeout_ms: int = 15000
    fetchers: int = 4


@dataclass
class BlockSyncConfig:
    enable: bool = True
    version: str = "v0"


@dataclass
class ConsensusConfig:
    """config.go:922-962 — timeouts in milliseconds."""

    wal_file: str = "data/cs.wal/wal"
    timeout_propose_ms: int = 3000
    timeout_propose_delta_ms: int = 500
    timeout_prevote_ms: int = 1000
    timeout_prevote_delta_ms: int = 500
    timeout_precommit_ms: int = 1000
    timeout_precommit_delta_ms: int = 500
    timeout_commit_ms: int = 1000
    skip_timeout_commit: bool = False
    create_empty_blocks: bool = True
    create_empty_blocks_interval_ms: int = 0
    peer_gossip_sleep_duration_ms: int = 100
    peer_query_maj23_sleep_duration_ms: int = 2000
    double_sign_check_height: int = 0

    # timeout helpers (config.go Propose/Prevote/Precommit/Commit methods)
    def propose_timeout(self, round_: int) -> float:
        return (self.timeout_propose_ms + self.timeout_propose_delta_ms * round_) / 1000.0

    def prevote_timeout(self, round_: int) -> float:
        return (self.timeout_prevote_ms + self.timeout_prevote_delta_ms * round_) / 1000.0

    def precommit_timeout(self, round_: int) -> float:
        return (self.timeout_precommit_ms + self.timeout_precommit_delta_ms * round_) / 1000.0

    def commit_timeout(self) -> float:
        return self.timeout_commit_ms / 1000.0

    def wal_path(self, home: str) -> str:
        return os.path.join(home, self.wal_file)


@dataclass
class TxIndexConfig:
    indexer: List[str] = field(default_factory=lambda: ["kv"])
    psql_conn: str = ""


@dataclass
class InstrumentationConfig:
    prometheus: bool = False
    prometheus_listen_addr: str = ":26660"
    max_open_connections: int = 3
    namespace: str = "tendermint"
    # Span tracing (observability.trace): off by default — the tracer's
    # disabled path is a single attribute check on the hot path. When on,
    # spans land in a fixed-size ring buffer served by the /dump_trace RPC
    # and (if trace_dump_path is set, resolved under <home>) flushed as a
    # Chrome-trace JSON file on node stop. TM_TPU_TRACE=1 also enables.
    tracing: bool = False
    trace_buffer_size: int = 16384
    trace_dump_path: str = ""


@dataclass
class Config:
    """config.go:61-74 — the full tree."""

    base: BaseConfig = field(default_factory=BaseConfig)
    priv_validator: PrivValidatorConfig = field(default_factory=PrivValidatorConfig)
    rpc: RPCConfig = field(default_factory=RPCConfig)
    p2p: P2PConfig = field(default_factory=P2PConfig)
    mempool: MempoolConfig = field(default_factory=MempoolConfig)
    statesync: StateSyncConfig = field(default_factory=StateSyncConfig)
    blocksync: BlockSyncConfig = field(default_factory=BlockSyncConfig)
    consensus: ConsensusConfig = field(default_factory=ConsensusConfig)
    tx_index: TxIndexConfig = field(default_factory=TxIndexConfig)
    instrumentation: InstrumentationConfig = field(default_factory=InstrumentationConfig)

    def validate_basic(self) -> None:
        if self.base.mode not in (MODE_FULL, MODE_VALIDATOR, MODE_SEED):
            raise ValueError(f"unknown mode: {self.base.mode}")
        if self.mempool.size < 0:
            raise ValueError("mempool size can't be negative")

    def ensure_dirs(self) -> None:
        for sub in ("config", "data"):
            os.makedirs(os.path.join(self.base.home, sub), exist_ok=True)

    # -- TOML -----------------------------------------------------------

    def save(self, path: Optional[str] = None) -> None:
        path = path or os.path.join(self.base.home, "config", "config.toml")
        with open(path, "w") as fh:
            fh.write(_to_toml(self))

    @classmethod
    def load(cls, path: str) -> "Config":
        try:
            import tomllib
        except ModuleNotFoundError:  # Python < 3.11
            import tomli as tomllib

        with open(path, "rb") as fh:
            data = tomllib.load(fh)
        cfg = cls()
        for section_name, section in data.items():
            tgt = getattr(cfg, section_name, None)
            if tgt is None or not isinstance(section, dict):
                continue
            for k, v in section.items():
                if hasattr(tgt, k):
                    setattr(tgt, k, v)
        return cfg


def _toml_value(v) -> str:
    if isinstance(v, bool):
        return "true" if v else "false"
    if isinstance(v, (int, float)):
        return str(v)
    if isinstance(v, list):
        return "[" + ", ".join(_toml_value(x) for x in v) + "]"
    return '"' + str(v).replace("\\", "\\\\").replace('"', '\\"') + '"'


def _to_toml(cfg: Config) -> str:
    out = []
    for section_name, section in asdict(cfg).items():
        out.append(f"[{section_name}]")
        for k, v in section.items():
            out.append(f"{k} = {_toml_value(v)}")
        out.append("")
    return "\n".join(out)


def default_config(home: str) -> Config:
    cfg = Config()
    cfg.base.home = home
    return cfg
