"""Loader for the native C++ module (native/tm_native.cpp).

Builds on first use with the in-image toolchain (g++ via setuptools'
build_ext), caches the shared object under native/_build, and degrades to
None when no compiler is available — all callers keep a pure-Python path.
"""

from __future__ import annotations

import importlib.util
import os
import sys
import sysconfig
import threading

_lock = threading.Lock()
_module = None
_tried = False

_ROOT = os.path.join(os.path.dirname(__file__), "..", "native")
_BUILD = os.path.join(_ROOT, "_build")


def _so_path() -> str:
    suffix = sysconfig.get_config_var("EXT_SUFFIX") or ".so"
    return os.path.join(_BUILD, f"tm_native{suffix}")


def _build() -> bool:
    src = os.path.join(_ROOT, "tm_native.cpp")
    if not os.path.exists(src):
        return False
    os.makedirs(_BUILD, exist_ok=True)
    import subprocess

    suffix = sysconfig.get_config_var("EXT_SUFFIX") or ".so"
    out = _so_path()
    include = sysconfig.get_path("include")
    cmd = [
        "g++", "-O3", "-march=x86-64-v3", "-funroll-loops", "-shared", "-fPIC", "-std=c++17",
        f"-I{include}", src, "-o", out,
    ]
    try:
        res = subprocess.run(cmd, capture_output=True, timeout=120)
        return res.returncode == 0 and os.path.exists(out)
    except (OSError, subprocess.TimeoutExpired):
        return False


def load():
    """Returns the tm_native module or None."""
    global _module, _tried
    with _lock:
        if _module is not None or _tried:
            return _module
        _tried = True
        if os.environ.get("TM_TPU_NO_NATIVE"):
            return None
        so = _so_path()
        src = os.path.join(_ROOT, "tm_native.cpp")
        if not os.path.exists(so) or (
            os.path.exists(src) and os.path.getmtime(src) > os.path.getmtime(so)
        ):
            if not _build():
                return None
        spec = importlib.util.spec_from_file_location("tm_native", so)
        if spec is None or spec.loader is None:
            return None
        mod = importlib.util.module_from_spec(spec)
        try:
            spec.loader.exec_module(mod)
        except ImportError:
            return None
        _module = mod
        return _module
