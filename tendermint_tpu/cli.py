"""Command-line interface.

Reference parity: cmd/tendermint/ (main.go:16-50) — init, start,
gen-validator, gen-node-key, show-node-id, show-validator, testnet,
rollback, inspect, reset-unsafe, version. Built on argparse instead of
cobra; `python -m tendermint_tpu <command>`.
"""

from __future__ import annotations

import argparse
import base64
import json
import os
import shutil
import sys
import time


def _cfg(home: str):
    from .config import Config, default_config

    path = os.path.join(home, "config", "config.toml")
    if os.path.exists(path):
        cfg = Config.load(path)
        cfg.base.home = home
        return cfg
    return default_config(home)


def cmd_version(args) -> int:
    from .version import TM_VERSION, BLOCK_PROTOCOL, P2P_PROTOCOL

    print(f"tendermint-tpu {TM_VERSION} (block protocol {BLOCK_PROTOCOL}, p2p {P2P_PROTOCOL})")
    return 0


def cmd_init(args) -> int:
    """init [validator|full|seed] (cmd init.go)."""
    from .config import default_config
    from .privval import FilePV
    from .p2p import NodeKey
    from .types.genesis import GenesisDoc, GenesisValidator
    from .wire.canonical import Timestamp

    home = args.home
    cfg = default_config(home)
    cfg.base.mode = args.mode
    cfg.ensure_dirs()

    pv = FilePV.load_or_generate(
        cfg.priv_validator.key_path(home), cfg.priv_validator.state_path(home)
    )
    pv.save()
    nk = NodeKey.load_or_generate(cfg.base.node_key_path())

    gen_path = cfg.base.genesis_path()
    if not os.path.exists(gen_path):
        doc = GenesisDoc(
            chain_id=args.chain_id or f"test-chain-{os.urandom(3).hex()}",
            genesis_time=Timestamp(seconds=int(time.time())),
            validators=(
                [GenesisValidator(address=b"", pub_key=pv.get_pub_key(), power=10)]
                if args.mode == "validator"
                else []
            ),
        )
        doc.validate_and_complete()
        doc.save_as(gen_path)
    cfg.save()
    print(f"Initialized {args.mode} node in {home} (node id {nk.node_id})")
    return 0


def cmd_gen_validator(args) -> int:
    from .privval import FilePV

    pv = FilePV.generate()
    pk = pv.get_pub_key()
    print(
        json.dumps(
            {
                "address": pk.address().hex().upper(),
                "pub_key": {
                    "type": "tendermint/PubKeyEd25519",
                    "value": base64.b64encode(pk.bytes()).decode(),
                },
                "priv_key": {
                    "type": "tendermint/PrivKeyEd25519",
                    "value": base64.b64encode(pv._priv_key.bytes()).decode(),
                },
            },
            indent=2,
        )
    )
    return 0


def cmd_gen_node_key(args) -> int:
    from .p2p import NodeKey

    nk = NodeKey.generate()
    print(json.dumps({"id": nk.node_id}))
    return 0


def cmd_show_node_id(args) -> int:
    from .p2p import NodeKey

    cfg = _cfg(args.home)
    nk = NodeKey.load_or_generate(cfg.base.node_key_path())
    print(nk.node_id)
    return 0


def cmd_show_validator(args) -> int:
    from .privval import FilePV

    cfg = _cfg(args.home)
    pv = FilePV.load(
        cfg.priv_validator.key_path(args.home), cfg.priv_validator.state_path(args.home)
    )
    pk = pv.get_pub_key()
    print(
        json.dumps(
            {"type": "tendermint/PubKeyEd25519", "value": base64.b64encode(pk.bytes()).decode()}
        )
    )
    return 0


def cmd_start(args) -> int:
    """start (run_node.go): run a node until interrupted."""
    import signal

    from .node import make_node
    from .abci import KVStoreApplication

    cfg = _cfg(args.home)
    app = None
    if args.proxy_app == "kvstore" or cfg.base.proxy_app == "kvstore":
        app = KVStoreApplication()
    node = make_node(cfg, app=app, with_rpc=True)
    node.start()
    print(f"node {node.node_id} started; RPC at {cfg.rpc.laddr}", flush=True)

    # SIGTERM must take the same orderly path as ^C: node.stop() flushes
    # the span-trace ring to a COMPLETE Chrome-trace file and shuts the
    # metrics scrape endpoint down (OnStop hooks), instead of the default
    # hard exit leaving a truncated dump.
    def _terminate(signum, frame):
        raise KeyboardInterrupt

    signal.signal(signal.SIGTERM, _terminate)
    try:
        while True:
            time.sleep(1)
    except KeyboardInterrupt:
        node.stop()
    return 0


def cmd_testnet(args) -> int:
    """testnet (testnet.go): generate config dirs for a localnet."""
    from .config import default_config
    from .privval import FilePV
    from .p2p import NodeKey
    from .types.genesis import GenesisDoc, GenesisValidator
    from .wire.canonical import Timestamp

    n = args.v
    out = args.o
    pvs, node_keys = [], []
    for i in range(n):
        home = os.path.join(out, f"node{i}")
        cfg = default_config(home)
        cfg.ensure_dirs()
        pv = FilePV.load_or_generate(
            cfg.priv_validator.key_path(home), cfg.priv_validator.state_path(home)
        )
        pv.save()
        pvs.append(pv)
        node_keys.append(NodeKey.load_or_generate(cfg.base.node_key_path()))
    doc = GenesisDoc(
        chain_id=args.chain_id or f"testnet-{os.urandom(3).hex()}",
        genesis_time=Timestamp(seconds=int(time.time())),
        validators=[
            GenesisValidator(address=b"", pub_key=pv.get_pub_key(), power=1)
            for pv in pvs
        ],
    )
    doc.validate_and_complete()
    base = args.port_base
    peers = ",".join(
        f"{nk.node_id}@127.0.0.1:{base + 10 * i}" for i, nk in enumerate(node_keys)
    )
    for i in range(n):
        home = os.path.join(out, f"node{i}")
        cfg = default_config(home)
        cfg.p2p.laddr = f"tcp://127.0.0.1:{base + 10 * i}"
        cfg.rpc.laddr = f"tcp://127.0.0.1:{base + 1 + 10 * i}"
        cfg.p2p.persistent_peers = peers
        cfg.save()
        doc.save_as(cfg.base.genesis_path())
    print(f"Successfully initialized {n} node directories in {out}")
    return 0


def cmd_replay(args) -> int:
    """replay / replay-console (replay_file.go:38-90): RE-DRIVE the
    consensus WAL through the state machine against snapshot copies of
    the stores. Without --console every record is applied and the final
    round state printed; with --console the playback manager accepts
    `next [N]`, `back [N]`, `rs [field]`, `n`, `quit` (replayConsoleLoop,
    replay_file.go:199-305)."""
    from .consensus.replay_console import Playback

    cfg = _cfg(args.home)
    cfg.base.home = args.home
    pb = Playback(cfg)
    if not args.console:
        n = pb.step(len(pb._records))
        print(
            f"replayed {n} WAL records; round state: {pb.round_state()}; "
            f"last committed height: {pb.cs.rs.height - 1}"
        )
        return 0
    print(f"{pb.remaining()} WAL records loaded; type `next [N]`, `back [N]`, "
          "`rs [field]`, `n`, or `quit`")
    while True:
        try:
            line = input("> ").strip()
        except EOFError:
            return 0
        if not line:
            continue
        tokens = line.split()
        cmd = tokens[0]
        if cmd in ("quit", "q", "exit"):
            return 0
        if cmd == "next":
            n = 1
            if len(tokens) > 1:
                try:
                    n = int(tokens[1])
                except ValueError:
                    print("next takes an integer argument")
                    continue
            applied = pb.step(n)
            print(f"applied {applied} record(s); rs {pb.round_state()}")
        elif cmd == "back":
            n = 1
            if len(tokens) > 1:
                try:
                    n = int(tokens[1])
                except ValueError:
                    print("back takes an integer argument")
                    continue
            if n < 1 or n > pb.count:
                print(
                    f"argument to back must be in 1..{pb.count} "
                    "(the current count)"
                )
                continue
            pb.reset_back(n)
            print(f"reset to record {pb.count}; rs {pb.round_state()}")
        elif cmd == "rs":
            print(pb.round_state(tokens[1] if len(tokens) > 1 else "short"))
        elif cmd == "n":
            print(pb.count)
        else:
            print(f"unknown command {cmd!r}")


_DEBUG_CAPTURE_METHODS = (
    "status",
    "net_info",
    "dump_consensus_state",
    "consensus_state",
    "thread_dump",  # goroutine-dump equivalent (rpc.core.thread_dump)
    "dump_trace",  # flush the observability span ring buffer
)


def _debug_capture(rpc_laddr: str, home: str, out: str) -> list:
    """Shared capture for `debug dump` and `debug kill`: node state over
    RPC + the on-disk config."""
    import json as _json
    import urllib.request

    os.makedirs(out, exist_ok=True)
    base = rpc_laddr
    for prefix in ("tcp://",):
        if base.startswith(prefix):
            base = "http://" + base[len(prefix):]
    captured = []
    for method in _DEBUG_CAPTURE_METHODS:
        try:
            with urllib.request.urlopen(f"{base}/{method}", timeout=5) as r:
                data = _json.loads(r.read())
            with open(os.path.join(out, f"{method}.json"), "w") as f:
                _json.dump(data, f, indent=2)
            captured.append(method)
        except (OSError, ValueError) as e:  # incl. malformed JSON bodies
            print(f"warning: {method} failed: {e}", file=sys.stderr)
    cfg_path = os.path.join(home, "config", "config.toml")
    if os.path.exists(cfg_path):
        import shutil

        shutil.copy(cfg_path, os.path.join(out, "config.toml"))
        captured.append("config.toml")
    return captured


def cmd_debug(args) -> int:
    """debug dump|kill (cmd/tendermint/commands/debug): capture a node's
    status, consensus state, net info, thread dump and span trace from
    its RPC into a directory; `kill` then SIGKILLs the node process
    (debug/kill.go: capture-then-kill, so the dump reflects the state the
    process died in)."""
    import signal

    mode = getattr(args, "mode", "dump") or "dump"
    captured = _debug_capture(args.rpc_laddr, args.home, args.output_directory)
    print(f"captured {captured} into {args.output_directory}")
    if mode == "kill":
        if not args.pid:
            print("debug kill: --pid is required", file=sys.stderr)
            return 1
        try:
            os.kill(args.pid, signal.SIGKILL)
        except (OSError, ProcessLookupError) as e:
            print(f"debug kill: SIGKILL {args.pid} failed: {e}", file=sys.stderr)
            return 1
        print(f"killed pid {args.pid}")
        return 0
    return 0 if captured else 1


def cmd_key_migrate(args) -> int:
    """key-migrate (cmd key-migrate): rewrite every store database into a
    fresh file, dropping dead space and normalizing the on-disk layout."""
    from .db import SQLiteDB

    migrated = []
    data_dir = os.path.join(args.home, "data")
    if not os.path.isdir(data_dir):
        print(f"no data directory at {data_dir}", file=sys.stderr)
        return 1
    for name in sorted(os.listdir(data_dir)):
        if not name.endswith(".db"):
            continue
        src_path = os.path.join(data_dir, name)
        tmp_path = src_path + ".migrate"
        if os.path.exists(tmp_path):
            os.remove(tmp_path)
        src = SQLiteDB(src_path)
        dst = SQLiteDB(tmp_path)
        n = 0
        batch = []
        for k, v in src.iterator(None, None):
            batch.append(("set", k, v))
            n += 1
            if len(batch) >= 1000:
                dst.write_batch(batch)
                batch = []
        if batch:
            dst.write_batch(batch)
        src.close()
        dst.close()
        # drop stale sqlite sidecars BEFORE the swap: a crash after
        # os.replace but before cleanup would otherwise leave the OLD
        # database's -wal applied to the NEW file (malformed image)
        for path in (src_path, tmp_path):
            for suffix in ("-wal", "-shm"):
                try:
                    os.remove(path + suffix)
                except FileNotFoundError:
                    pass
        os.replace(tmp_path, src_path)
        migrated.append((name, n))
    for name, n in migrated:
        print(f"migrated {name}: {n} keys")
    return 0


def cmd_reindex_event(args) -> int:
    """reindex-event (commands/reindex_event.go): rebuild the tx/block
    event indexes from the block store + stored ABCI responses."""
    from .abci import types as abci_t
    from .db import SQLiteDB
    from .eventbus import _merge_abci_events
    from .indexer import KVSink
    from .state.store import StateStore
    from .store import BlockStore


    data = os.path.join(args.home, "data")
    bstore = BlockStore(SQLiteDB(os.path.join(data, "blockstore.db")))
    sstore = StateStore(SQLiteDB(os.path.join(data, "state.db")))
    sink = KVSink(SQLiteDB(os.path.join(data, "tx_index.db")))
    start = args.start_height or bstore.base() or 1
    end = args.end_height or bstore.height()
    indexed = 0
    for h in range(start, end + 1):
        block = bstore.load_block(h)
        responses = sstore.load_abci_responses(h)
        if block is None or responses is None:
            continue
        eb = abci_t.dec_response_payload("end_block", responses.end_block)
        bb = abci_t.dec_response_payload("begin_block", responses.begin_block) \
            if getattr(responses, "begin_block", None) else None
        blk_events = {}
        for res in (bb, eb):
            if res is not None:
                # append (not overwrite): begin/end block may emit the same
                # composite key and the live index keeps both values
                _merge_abci_events(blk_events, res.events)
        sink.index_block(h, blk_events)
        for i, raw in enumerate(responses.deliver_txs):
            r = abci_t.dec_response_payload("deliver_tx", raw)
            tx_events = {}
            _merge_abci_events(tx_events, r.events)
            sink.index_tx(h, i, block.data.txs[i], r, tx_events)
            indexed += 1
    print(f"reindexed blocks {start}..{end}: {indexed} txs")
    return 0


def cmd_light(args) -> int:
    """light (commands/light.go): run a verifying light proxy against a
    primary + witnesses, serving verified RPC reads."""
    from .db import MemDB
    from .light import Client, LightStore, TrustOptions
    from .light.provider import HTTPProvider
    from .light.rpc import LightProxy, VerifyingClient
    from .rpc.client import HTTPClient

    primary = HTTPProvider(args.primary)
    witnesses = [HTTPProvider(w) for w in args.witnesses.split(",") if w]
    if not witnesses:
        # commands/light.go refuses to run without a real witness: with the
        # primary as its own witness, divergence detection is vacuous
        print(
            "error: at least one witness (-w) distinct from the primary is "
            "required for attack detection",
            file=sys.stderr,
        )
        return 1
    if args.trusted_height and args.trusted_hash:
        opts = TrustOptions(
            period=float(args.trusting_period),
            height=int(args.trusted_height),
            hash=bytes.fromhex(args.trusted_hash),
        )
    else:
        lb = primary.light_block(0)
        print(
            f"no trust root given; trusting the primary's latest header "
            f"{lb.height} {lb.hash().hex()}"
        )
        opts = TrustOptions(
            period=float(args.trusting_period), height=lb.height, hash=lb.hash()
        )
    client = Client(
        chain_id=args.chain_id,
        trust_options=opts,
        primary=primary,
        witnesses=witnesses,
        store=LightStore(MemDB()),
    )
    vc = VerifyingClient(HTTPClient(args.primary), client)
    srv = LightProxy(vc, args.laddr)
    srv.start()
    print(f"light proxy for {args.chain_id} listening on {args.laddr}", flush=True)
    try:
        while True:
            time.sleep(1)
    except KeyboardInterrupt:
        srv.stop()
    return 0


def cmd_signer_harness(args) -> int:
    """signer-harness (tools/tm-signer-harness): run the conformance
    battery against a remote signer (gRPC address or socket listen
    address) or a local FilePV key file."""
    from .tools.signer_harness import run_harness

    expected = None
    if args.expect_key_file:
        from .privval import FilePV

        pv = FilePV.load(args.expect_key_file, args.expect_key_file + ".state")
        expected = pv.get_pub_key()
    if args.grpc:
        from .privval.grpc import GRPCSignerClient

        signer = GRPCSignerClient(args.grpc)
    elif args.listen:
        from .privval.remote import SignerClient

        print(f"waiting for the signer to dial {args.listen} ...", flush=True)
        signer = SignerClient(args.listen)
    else:
        from .privval import FilePV

        if not args.key_file:
            print("one of --grpc, --listen or --key-file is required", file=sys.stderr)
            return 2
        signer = FilePV.load(args.key_file, args.key_file + ".state")
    rep = run_harness(signer, chain_id=args.chain_id, expected_pub_key=expected)
    for r in rep.results:
        print(f"{'PASS' if r.ok else 'FAIL'}  {r.name}" + (f"  ({r.detail})" if r.detail else ""))
    print("OVERALL:", "PASS" if rep.passed else "FAIL")
    return 0 if rep.passed else 1


def cmd_rollback(args) -> int:
    from .db import backend as db_backend
    from .state.rollback import rollback_state
    from .state.store import StateStore
    from .store import BlockStore

    cfg = _cfg(args.home)
    state_store = StateStore(db_backend("sqlite", cfg.base.db_path("state")))
    block_store = BlockStore(db_backend("sqlite", cfg.base.db_path("blockstore")))
    height, app_hash = rollback_state(state_store, block_store)
    print(f"Rolled back state to height {height} and hash {app_hash.hex().upper()}")
    return 0


def cmd_inspect(args) -> int:
    from .db import backend as db_backend
    from .inspect import Inspector
    from .state.store import StateStore
    from .store import BlockStore
    from .types.genesis import GenesisDoc

    cfg = _cfg(args.home)
    genesis = GenesisDoc.from_file(cfg.base.genesis_path())
    inspector = Inspector(
        cfg,
        genesis,
        StateStore(db_backend("sqlite", cfg.base.db_path("state"))),
        BlockStore(db_backend("sqlite", cfg.base.db_path("blockstore"))),
    )
    inspector.start()
    print(f"inspect RPC at {inspector.listen_addr}", flush=True)
    try:
        while True:
            time.sleep(1)
    except KeyboardInterrupt:
        inspector.stop()
    return 0


def cmd_probe_upnp(args) -> int:
    """probe-upnp (cmd/tendermint/commands/probe_upnp.go): discover a
    UPnP gateway, map/unmap a test port, print the capabilities JSON."""
    from .p2p import upnp

    try:
        caps = upnp.probe(int_port=args.int_port, ext_port=args.ext_port,
                          timeout=args.timeout)
    except upnp.UPnPError as e:
        print(f"Probe failed: {e}")
        return 1
    print(json.dumps({"port_mapping": caps.port_mapping, "hairpin": caps.hairpin}))
    return 0


def cmd_reset_unsafe(args) -> int:
    """unsafe-reset-all: wipe data, keep config + priv key state zeroed."""
    data = os.path.join(args.home, "data")
    if os.path.isdir(data):
        shutil.rmtree(data)
    os.makedirs(data, exist_ok=True)
    print(f"Removed all blockchain history in {data}")
    return 0


def cmd_wal2json(args) -> int:
    """scripts/wal2json/main.go:1 — decode a binary WAL file to one JSON
    object per line on stdout (operator tooling for WAL surgery)."""
    import json as _json

    from .consensus.wal import WAL

    for msg in WAL._iter_file(args.wal_file):
        obj = {}
        if msg.end_height is not None:
            obj["end_height"] = msg.end_height
        elif msg.timeout is not None:
            d, h, r, s = msg.timeout
            obj["timeout"] = {"duration_ms": d, "height": h, "round": r, "step": s}
        else:
            obj["msg"] = {
                "kind": msg.msg_kind,
                "payload": msg.msg_payload.hex(),
                "peer_id": msg.peer_id,
            }
        print(_json.dumps(obj))
    return 0


def cmd_json2wal(args) -> int:
    """scripts/json2wal/main.go:1 — re-encode wal2json output (one JSON
    object per line on stdin or --input) into a CRC-framed binary WAL."""
    import json as _json
    import struct as _struct
    import zlib as _zlib

    from .consensus.wal import MAX_MSG_SIZE, WALMessage, _encode_record

    src = open(args.input, "r") if args.input else sys.stdin
    try:
        with open(args.wal_file, "wb") as out:
            for line in src:
                line = line.strip()
                if not line:
                    continue
                obj = _json.loads(line)
                if "end_height" in obj:
                    msg = WALMessage(end_height=int(obj["end_height"]))
                elif "timeout" in obj:
                    t = obj["timeout"]
                    msg = WALMessage(
                        timeout=(int(t["duration_ms"]), int(t["height"]),
                                 int(t["round"]), int(t["step"]))
                    )
                else:
                    m = obj["msg"]
                    msg = WALMessage(
                        msg_kind=m["kind"],
                        msg_payload=bytes.fromhex(m["payload"]),
                        peer_id=m.get("peer_id", ""),
                    )
                body = _encode_record(msg)
                if len(body) > MAX_MSG_SIZE:
                    # an oversized frame would make WAL._iter_file stop
                    # silently at replay, dropping the tail — refuse here
                    print(
                        f"error: record too big ({len(body)} > "
                        f"{MAX_MSG_SIZE} bytes)", file=sys.stderr,
                    )
                    return 1
                crc = _zlib.crc32(body) & 0xFFFFFFFF
                out.write(_struct.pack(">II", crc, len(body)) + body)
    finally:
        if args.input:
            src.close()
    return 0


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(prog="tendermint-tpu")
    p.add_argument("--home", default=os.path.expanduser("~/.tendermint-tpu"))
    sub = p.add_subparsers(dest="command")

    sub.add_parser("version")
    sp = sub.add_parser("init")
    sp.add_argument("mode", nargs="?", default="validator",
                    choices=["validator", "full", "seed"])
    sp.add_argument("--chain-id", default="")
    sub.add_parser("gen-validator")
    sub.add_parser("gen-node-key")
    sub.add_parser("show-node-id")
    sub.add_parser("show-validator")
    sp = sub.add_parser("start")
    sp.add_argument("--proxy-app", default="")
    sp = sub.add_parser("testnet")
    sp.add_argument("--v", type=int, default=4)
    sp.add_argument("--o", default="./mytestnet")
    sp.add_argument("--chain-id", default="")
    sp.add_argument("--port-base", type=int, default=26656)
    sp = sub.add_parser("replay")
    sp.add_argument("--console", action="store_true")
    sp = sub.add_parser("debug")
    sp.add_argument("mode", nargs="?", default="dump", choices=["dump", "kill"])
    sp.add_argument("--rpc-laddr", default="http://127.0.0.1:26657")
    sp.add_argument("--output-directory", default="./debug-dump")
    sp.add_argument("--pid", type=int, default=0, help="process to SIGKILL (kill mode)")
    sub.add_parser("key-migrate")
    sp = sub.add_parser("reindex-event")
    sp.add_argument("--start-height", type=int, default=0)
    sp.add_argument("--end-height", type=int, default=0)
    sp = sub.add_parser("light")
    sp.add_argument("chain_id")
    sp.add_argument("--primary", "-p", required=True)
    sp.add_argument("--witnesses", "-w", default="")
    sp.add_argument("--trusted-height", type=int, default=0)
    sp.add_argument("--trusted-hash", default="")
    sp.add_argument("--trusting-period", default=str(14 * 24 * 3600))
    sp.add_argument("--laddr", default="tcp://127.0.0.1:8888")
    sp = sub.add_parser("signer-harness")
    sp.add_argument("--grpc", default="", help="gRPC signer address")
    sp.add_argument("--listen", default="", help="listen addr a socket signer dials")
    sp.add_argument("--key-file", default="", help="local FilePV key file")
    sp.add_argument("--expect-key-file", default="")
    sp.add_argument("--chain-id", default="signer-harness")
    sp = sub.add_parser("probe-upnp")
    sp.add_argument("--int-port", type=int, default=8001)
    sp.add_argument("--ext-port", type=int, default=8001)
    sp.add_argument("--timeout", type=float, default=3.0)
    sub.add_parser("rollback")
    sub.add_parser("inspect")
    sp = sub.add_parser("wal2json")
    sp.add_argument("wal_file")
    sp = sub.add_parser("json2wal")
    sp.add_argument("wal_file")
    sp.add_argument("--input", default="")
    sub.add_parser("unsafe-reset-all")
    return p


COMMANDS = {
    "version": cmd_version,
    "init": cmd_init,
    "gen-validator": cmd_gen_validator,
    "gen-node-key": cmd_gen_node_key,
    "show-node-id": cmd_show_node_id,
    "show-validator": cmd_show_validator,
    "start": cmd_start,
    "testnet": cmd_testnet,
    "replay": cmd_replay,
    "debug": cmd_debug,
    "key-migrate": cmd_key_migrate,
    "reindex-event": cmd_reindex_event,
    "light": cmd_light,
    "signer-harness": cmd_signer_harness,
    "probe-upnp": cmd_probe_upnp,
    "rollback": cmd_rollback,
    "inspect": cmd_inspect,
    "wal2json": cmd_wal2json,
    "json2wal": cmd_json2wal,
    "unsafe-reset-all": cmd_reset_unsafe,
}


def main(argv=None) -> int:
    args = build_parser().parse_args(argv)
    if not args.command:
        build_parser().print_help()
        return 1
    return COMMANDS[args.command](args)


if __name__ == "__main__":
    sys.exit(main())
