"""Command-line interface.

Reference parity: cmd/tendermint/ (main.go:16-50) — init, start,
gen-validator, gen-node-key, show-node-id, show-validator, testnet,
rollback, inspect, reset-unsafe, version. Built on argparse instead of
cobra; `python -m tendermint_tpu <command>`.
"""

from __future__ import annotations

import argparse
import base64
import json
import os
import shutil
import sys
import time


def _cfg(home: str):
    from .config import Config, default_config

    path = os.path.join(home, "config", "config.toml")
    if os.path.exists(path):
        cfg = Config.load(path)
        cfg.base.home = home
        return cfg
    return default_config(home)


def cmd_version(args) -> int:
    from .version import TM_VERSION, BLOCK_PROTOCOL, P2P_PROTOCOL

    print(f"tendermint-tpu {TM_VERSION} (block protocol {BLOCK_PROTOCOL}, p2p {P2P_PROTOCOL})")
    return 0


def cmd_init(args) -> int:
    """init [validator|full|seed] (cmd init.go)."""
    from .config import default_config
    from .privval import FilePV
    from .p2p import NodeKey
    from .types.genesis import GenesisDoc, GenesisValidator
    from .wire.canonical import Timestamp

    home = args.home
    cfg = default_config(home)
    cfg.base.mode = args.mode
    cfg.ensure_dirs()

    pv = FilePV.load_or_generate(
        cfg.priv_validator.key_path(home), cfg.priv_validator.state_path(home)
    )
    pv.save()
    nk = NodeKey.load_or_generate(cfg.base.node_key_path())

    gen_path = cfg.base.genesis_path()
    if not os.path.exists(gen_path):
        doc = GenesisDoc(
            chain_id=args.chain_id or f"test-chain-{os.urandom(3).hex()}",
            genesis_time=Timestamp(seconds=int(time.time())),
            validators=(
                [GenesisValidator(address=b"", pub_key=pv.get_pub_key(), power=10)]
                if args.mode == "validator"
                else []
            ),
        )
        doc.validate_and_complete()
        doc.save_as(gen_path)
    cfg.save()
    print(f"Initialized {args.mode} node in {home} (node id {nk.node_id})")
    return 0


def cmd_gen_validator(args) -> int:
    from .privval import FilePV

    pv = FilePV.generate()
    pk = pv.get_pub_key()
    print(
        json.dumps(
            {
                "address": pk.address().hex().upper(),
                "pub_key": {
                    "type": "tendermint/PubKeyEd25519",
                    "value": base64.b64encode(pk.bytes()).decode(),
                },
                "priv_key": {
                    "type": "tendermint/PrivKeyEd25519",
                    "value": base64.b64encode(pv._priv_key.bytes()).decode(),
                },
            },
            indent=2,
        )
    )
    return 0


def cmd_gen_node_key(args) -> int:
    from .p2p import NodeKey

    nk = NodeKey.generate()
    print(json.dumps({"id": nk.node_id}))
    return 0


def cmd_show_node_id(args) -> int:
    from .p2p import NodeKey

    cfg = _cfg(args.home)
    nk = NodeKey.load_or_generate(cfg.base.node_key_path())
    print(nk.node_id)
    return 0


def cmd_show_validator(args) -> int:
    from .privval import FilePV

    cfg = _cfg(args.home)
    pv = FilePV.load(
        cfg.priv_validator.key_path(args.home), cfg.priv_validator.state_path(args.home)
    )
    pk = pv.get_pub_key()
    print(
        json.dumps(
            {"type": "tendermint/PubKeyEd25519", "value": base64.b64encode(pk.bytes()).decode()}
        )
    )
    return 0


def cmd_start(args) -> int:
    """start (run_node.go): run a node until interrupted."""
    from .node import make_node
    from .abci import KVStoreApplication

    cfg = _cfg(args.home)
    app = None
    if args.proxy_app == "kvstore" or cfg.base.proxy_app == "kvstore":
        app = KVStoreApplication()
    node = make_node(cfg, app=app, with_rpc=True)
    node.start()
    print(f"node {node.node_id} started; RPC at {cfg.rpc.laddr}", flush=True)
    try:
        while True:
            time.sleep(1)
    except KeyboardInterrupt:
        node.stop()
    return 0


def cmd_testnet(args) -> int:
    """testnet (testnet.go): generate config dirs for a localnet."""
    from .config import default_config
    from .privval import FilePV
    from .p2p import NodeKey
    from .types.genesis import GenesisDoc, GenesisValidator
    from .wire.canonical import Timestamp

    n = args.v
    out = args.o
    pvs, node_keys = [], []
    for i in range(n):
        home = os.path.join(out, f"node{i}")
        cfg = default_config(home)
        cfg.ensure_dirs()
        pv = FilePV.load_or_generate(
            cfg.priv_validator.key_path(home), cfg.priv_validator.state_path(home)
        )
        pv.save()
        pvs.append(pv)
        node_keys.append(NodeKey.load_or_generate(cfg.base.node_key_path()))
    doc = GenesisDoc(
        chain_id=args.chain_id or f"testnet-{os.urandom(3).hex()}",
        genesis_time=Timestamp(seconds=int(time.time())),
        validators=[
            GenesisValidator(address=b"", pub_key=pv.get_pub_key(), power=1)
            for pv in pvs
        ],
    )
    doc.validate_and_complete()
    peers = ",".join(
        f"{nk.node_id}@127.0.0.1:{26656 + 10 * i}" for i, nk in enumerate(node_keys)
    )
    for i in range(n):
        home = os.path.join(out, f"node{i}")
        cfg = default_config(home)
        cfg.p2p.laddr = f"tcp://127.0.0.1:{26656 + 10 * i}"
        cfg.rpc.laddr = f"tcp://127.0.0.1:{26657 + 10 * i}"
        cfg.p2p.persistent_peers = peers
        cfg.save()
        doc.save_as(cfg.base.genesis_path())
    print(f"Successfully initialized {n} node directories in {out}")
    return 0


def cmd_rollback(args) -> int:
    from .db import backend as db_backend
    from .state.rollback import rollback_state
    from .state.store import StateStore
    from .store import BlockStore

    cfg = _cfg(args.home)
    state_store = StateStore(db_backend("sqlite", cfg.base.db_path("state")))
    block_store = BlockStore(db_backend("sqlite", cfg.base.db_path("blockstore")))
    height, app_hash = rollback_state(state_store, block_store)
    print(f"Rolled back state to height {height} and hash {app_hash.hex().upper()}")
    return 0


def cmd_inspect(args) -> int:
    from .db import backend as db_backend
    from .inspect import Inspector
    from .state.store import StateStore
    from .store import BlockStore
    from .types.genesis import GenesisDoc

    cfg = _cfg(args.home)
    genesis = GenesisDoc.from_file(cfg.base.genesis_path())
    inspector = Inspector(
        cfg,
        genesis,
        StateStore(db_backend("sqlite", cfg.base.db_path("state"))),
        BlockStore(db_backend("sqlite", cfg.base.db_path("blockstore"))),
    )
    inspector.start()
    print(f"inspect RPC at {inspector.listen_addr}", flush=True)
    try:
        while True:
            time.sleep(1)
    except KeyboardInterrupt:
        inspector.stop()
    return 0


def cmd_reset_unsafe(args) -> int:
    """unsafe-reset-all: wipe data, keep config + priv key state zeroed."""
    data = os.path.join(args.home, "data")
    if os.path.isdir(data):
        shutil.rmtree(data)
    os.makedirs(data, exist_ok=True)
    print(f"Removed all blockchain history in {data}")
    return 0


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(prog="tendermint-tpu")
    p.add_argument("--home", default=os.path.expanduser("~/.tendermint-tpu"))
    sub = p.add_subparsers(dest="command")

    sub.add_parser("version")
    sp = sub.add_parser("init")
    sp.add_argument("mode", nargs="?", default="validator",
                    choices=["validator", "full", "seed"])
    sp.add_argument("--chain-id", default="")
    sub.add_parser("gen-validator")
    sub.add_parser("gen-node-key")
    sub.add_parser("show-node-id")
    sub.add_parser("show-validator")
    sp = sub.add_parser("start")
    sp.add_argument("--proxy-app", default="")
    sp = sub.add_parser("testnet")
    sp.add_argument("--v", type=int, default=4)
    sp.add_argument("--o", default="./mytestnet")
    sp.add_argument("--chain-id", default="")
    sub.add_parser("rollback")
    sub.add_parser("inspect")
    sub.add_parser("unsafe-reset-all")
    return p


COMMANDS = {
    "version": cmd_version,
    "init": cmd_init,
    "gen-validator": cmd_gen_validator,
    "gen-node-key": cmd_gen_node_key,
    "show-node-id": cmd_show_node_id,
    "show-validator": cmd_show_validator,
    "start": cmd_start,
    "testnet": cmd_testnet,
    "rollback": cmd_rollback,
    "inspect": cmd_inspect,
    "unsafe-reset-all": cmd_reset_unsafe,
}


def main(argv=None) -> int:
    args = build_parser().parse_args(argv)
    if not args.command:
        build_parser().print_help()
        return 1
    return COMMANDS[args.command](args)


if __name__ == "__main__":
    sys.exit(main())
