"""FleetServer: the network-facing end of the verification fleet.

Accepts wire frames over TCP, rebuilds EntryBlocks, and submits them to
an AsyncBatchVerifier at the client-declared QoS tier — so same-epoch
blocks from DIFFERENT nodes land in the same coalescer window and
cross-node coalesce into mesh lanes exactly like same-process callers.
Verdicts stream back in COMPLETION order (not submit order): each reply
carries the request_id so the client demuxes, and the submit frame's
flow id is continued through ``TRACER.flow_point`` so a flight-recorder
chain spans client-node → fleet → verdict.

Threading: one accept thread; per connection one reader thread and one
writer thread joined by an outbox queue. Verdict futures complete on
the verifier's resolver thread — the done-callback only ENQUEUES the
encoded reply, so the resolver never blocks on socket I/O and the
pipeline's lock discipline is preserved.

Failure containment mirrors the wire's error taxonomy: a malformed or
version-skewed frame earns an ERROR reply and the connection lives on;
an oversize length prefix kills (only) that connection; a verifier
exception (DispatchError et al.) earns an ERROR frame with code
ERR_DISPATCH for just that request.
"""

from __future__ import annotations

import queue
import socket
import threading
from typing import Dict, Optional, Tuple

import numpy as np

from ..libs.metrics import fleet_metrics
from ..observability.trace import TRACER
from . import wire

_PRIORITY_MAX = 2  # ingress — the lowest QoS tier the wire can name


class FleetServer:
    """Serve EntryBlock verification to remote nodes over the fleet wire.

    ``verifier`` is any object with ``submit(entries, flow=None,
    priority=0) -> Future`` (AsyncBatchVerifier-shaped). When None it is
    resolved lazily to ``ops.pipeline.shared_verifier()`` on the first
    accepted frame — constructing a FleetServer never spins up jax.
    """

    def __init__(self, addr: Tuple[str, int] = ("127.0.0.1", 0),
                 verifier=None):
        self._verifier = verifier
        self._m = fleet_metrics()
        self._lsock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._lsock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._lsock.bind(addr)
        self._lsock.listen(64)
        self._stopped = threading.Event()
        self._conn_mtx = threading.Lock()
        self._conns: Dict[int, "_Conn"] = {}
        self._next_conn = 0
        self._accept_thread: Optional[threading.Thread] = None

    # -- lifecycle -----------------------------------------------------

    @property
    def addr(self) -> Tuple[str, int]:
        return self._lsock.getsockname()[:2]

    def start(self) -> "FleetServer":
        self._accept_thread = threading.Thread(
            target=self._accept_loop, name="fleet-accept", daemon=True
        )
        self._accept_thread.start()
        return self

    def stop(self) -> None:
        """Stop accepting and abort every live connection (simulates a
        fleet-host crash as far as clients can tell)."""
        if self._stopped.is_set():
            return
        self._stopped.set()
        # a blocked accept() is not reliably woken by close() on Linux:
        # poke the listener with a throwaway dial so the accept thread
        # observes _stopped and exits instead of eating the join timeout
        try:
            socket.create_connection(self.addr, timeout=1.0).close()
        except OSError:
            pass
        try:
            self._lsock.close()
        except OSError:
            pass
        with self._conn_mtx:
            conns = list(self._conns.values())
        for c in conns:
            c.abort()
        if self._accept_thread is not None:
            self._accept_thread.join(timeout=5.0)

    def stats(self) -> dict:
        with self._conn_mtx:
            return {
                "addr": "%s:%d" % self.addr if not self._stopped.is_set() else "",
                "connections": len(self._conns),
                "stopped": self._stopped.is_set(),
            }

    # -- internals -----------------------------------------------------

    def _resolve_verifier(self):
        if self._verifier is None:
            from ..ops.pipeline import shared_verifier
            self._verifier = shared_verifier()
        return self._verifier

    def _accept_loop(self) -> None:
        while not self._stopped.is_set():
            try:
                sock, _peer = self._lsock.accept()
            except OSError:
                return  # listener closed
            with self._conn_mtx:
                if self._stopped.is_set():
                    sock.close()
                    return
                cid = self._next_conn
                self._next_conn += 1
                conn = _Conn(self, cid, sock)
                self._conns[cid] = conn
            self._m.server_connections.set(len(self._conns))
            conn.start()

    def _drop_conn(self, cid: int) -> None:
        with self._conn_mtx:
            self._conns.pop(cid, None)
            n = len(self._conns)
        self._m.server_connections.set(n)


class _Conn:
    """One accepted client connection: reader + writer thread pair."""

    def __init__(self, server: FleetServer, cid: int, sock: socket.socket):
        self._server = server
        self._cid = cid
        self._sock = sock
        sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        self._outbox: "queue.Queue[Optional[bytes]]" = queue.Queue()
        self._closed = threading.Event()
        self._m = server._m

    def start(self) -> None:
        threading.Thread(
            target=self._read_loop, name=f"fleet-read-{self._cid}", daemon=True
        ).start()
        threading.Thread(
            target=self._write_loop, name=f"fleet-write-{self._cid}", daemon=True
        ).start()

    def abort(self) -> None:
        if self._closed.is_set():
            return
        self._closed.set()
        self._outbox.put(None)
        try:
            self._sock.shutdown(socket.SHUT_RDWR)
        except OSError:
            pass
        try:
            self._sock.close()
        except OSError:
            pass
        self._server._drop_conn(self._cid)

    # -- reader --------------------------------------------------------

    def _read_loop(self) -> None:
        decoder = wire.FrameDecoder()
        try:
            while not self._closed.is_set():
                try:
                    data = self._sock.recv(1 << 20)
                except OSError:
                    return
                if not data:
                    return
                try:
                    payloads = decoder.feed(data)
                except wire.OversizeFrame as e:
                    # framing lost — reply best-effort, then close THIS
                    # connection; the server itself stays up
                    self._m.server_frames_rejected.inc(reason="oversize")
                    self._outbox.put(wire.encode_error(0, wire.ERR_OVERSIZE, str(e)))
                    return
                for payload in payloads:
                    self._handle_payload(payload)
        finally:
            self.abort()

    def _handle_payload(self, payload: bytes) -> None:
        try:
            frame = wire.parse_frame(payload)
        except wire.VersionSkew as e:
            self._m.server_frames_rejected.inc(reason="version")
            self._outbox.put(wire.encode_error(0, wire.ERR_VERSION, str(e)))
            return
        except wire.WireError as e:
            # recoverable: the length prefix framed the junk, so the
            # stream is still in sync — reject the frame, keep the conn
            self._m.server_frames_rejected.inc(reason="malformed")
            self._outbox.put(wire.encode_error(0, wire.ERR_MALFORMED, str(e)))
            return
        if not isinstance(frame, wire.SubmitFrame):
            self._m.server_frames_rejected.inc(reason="malformed")
            self._outbox.put(wire.encode_error(
                0, wire.ERR_MALFORMED, f"server expects SUBMIT, got kind "
                f"{type(frame).__name__}"))
            return
        self._submit(frame)

    def _submit(self, frame: wire.SubmitFrame) -> None:
        lane = frame.lane or "unlabeled"
        self._m.server_frames_accepted.inc(lane=lane)
        self._m.server_sigs.inc(len(frame.block), lane=lane)
        flow = frame.flow or None
        TRACER.flow_point("fleet.server.recv", flow, "t",
                          lane=lane, n=len(frame.block))
        priority = min(max(int(frame.priority), 0), _PRIORITY_MAX)
        request_id = frame.request_id
        try:
            verifier = self._server._resolve_verifier()
            try:
                fut = verifier.submit(frame.block, flow=flow,
                                      priority=priority, origin=lane)
            except TypeError:
                # duck-typed verifiers predating the origin= kwarg
                fut = verifier.submit(frame.block, flow=flow,
                                      priority=priority)
        except Exception as e:  # submit itself failed (closed, bad block)
            self._m.server_dispatch_errors.inc()
            self._outbox.put(wire.encode_error(
                request_id, wire.ERR_DISPATCH, str(e)))
            return

        def _done(f, _rid=request_id, _flow=flow):
            # Runs on the verifier's resolver thread: enqueue only —
            # never touch the socket here.
            try:
                verdicts = np.asarray(f.result(), dtype=bool)
            except Exception as e:
                self._m.server_dispatch_errors.inc()
                self._outbox.put(wire.encode_error(
                    _rid, wire.ERR_DISPATCH, str(e)))
                return
            TRACER.flow_point("fleet.server.verdict", _flow, "t",
                              n=int(verdicts.shape[0]))
            self._m.server_verdicts_streamed.inc()
            self._outbox.put(wire.encode_verdicts(_rid, verdicts))

        fut.add_done_callback(_done)

    # -- writer --------------------------------------------------------

    def _write_loop(self) -> None:
        while True:
            buf = self._outbox.get()
            if buf is None:
                return
            try:
                self._sock.sendall(buf)
            except OSError:
                self.abort()
                return


class LoopbackFleetHost:
    """A socket-free fleet host for deterministic (simnet) runs.

    Drives the SAME wire encode/parse code as the real server — so the
    serialization path is exercised and the tmlint fleet-transport rule
    keeps all wire calls inside fleet modules — but handles each frame
    synchronously: ``handle(payload) -> reply frame bytes``. The
    verifier here is any callable ``(EntryBlock, priority) -> (n,) bool
    array`` (simnet supplies a deterministic checker; no threads, no
    sockets, no wall clock).
    """

    def __init__(self, verify_fn):
        self._verify_fn = verify_fn
        self.killed = False
        self.frames_accepted = 0
        self.frames_rejected = 0
        self.sigs = 0
        self.by_priority: Dict[int, int] = {}

    def kill(self) -> None:
        self.killed = True

    def revive(self) -> None:
        self.killed = False

    def handle(self, payload: bytes) -> bytes:
        if self.killed:
            raise ConnectionError("fleet host is down")
        try:
            frame = wire.parse_frame(payload)
        except wire.WireError as e:
            self.frames_rejected += 1
            code = (wire.ERR_VERSION if isinstance(e, wire.VersionSkew)
                    else wire.ERR_MALFORMED)
            return wire.encode_error(0, code, str(e))
        if not isinstance(frame, wire.SubmitFrame):
            self.frames_rejected += 1
            return wire.encode_error(0, wire.ERR_MALFORMED,
                                     "host expects SUBMIT")
        self.frames_accepted += 1
        self.sigs += len(frame.block)
        pr = min(max(int(frame.priority), 0), _PRIORITY_MAX)
        self.by_priority[pr] = self.by_priority.get(pr, 0) + 1
        try:
            verdicts = np.asarray(self._verify_fn(frame.block, pr), dtype=bool)
        except Exception as e:
            return wire.encode_error(frame.request_id, wire.ERR_DISPATCH,
                                     str(e))
        return wire.encode_verdicts(frame.request_id, verdicts)
