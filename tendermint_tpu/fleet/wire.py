"""Fleet wire format: length-prefixed columnar EntryBlock frames.

EntryBlocks are already columnar (pub (n,32) u8, sig (n,64) u8, one
contiguous msgs buffer + (n+1,) i64 offsets), so serialization is
near-free: the encoder emits an iovec of header bytes plus raw
memoryviews over the numpy columns — zero copies on the send side.
The decoder rebuilds the block with ``np.frombuffer`` over slices of
the received payload (read-only views, one copy per frame at the
socket boundary, which is unavoidable).

Frame layout (all little-endian):

    u32 payload_len | payload

    payload := MAGIC("TMFL") u16 version u8 kind u8 flags | body

SUBMIT body (kind=1):

    u64 request_id | u64 flow (0 = none) | u8 priority | u8 meta_flags
    | u16 lane_len | lane utf-8
    | u32 n | u64 msgs_len
    | pub n*32 | sig n*64 | offsets (n+1)*8 i64 | msgs
    | [if meta_flags & FLAG_EPOCH:  u16 ek_len | epoch_key | val_idx n*4 i32]

VERDICT body (kind=2):   u64 request_id | u32 n | n bytes of 0/1
ERROR body   (kind=3):   u64 request_id | u8 code | u16 msg_len | msg utf-8

Error taxonomy:

* ``WireError`` — malformed payload. Recoverable: the 4-byte length
  prefix still framed the junk, so the connection survives and the
  peer answers with an ERROR frame.
* ``VersionSkew`` — well-framed but from a different protocol version.
  Recoverable the same way (code ERR_VERSION).
* ``OversizeFrame`` — the length prefix exceeds ``max_frame``. Framing
  can no longer be trusted, so the *connection* must close — but only
  the connection; the server stays up.
* ``TruncatedFrame`` — EOF mid-frame (peer died). Connection-fatal.
"""

from __future__ import annotations

import os
import struct
from typing import Iterator, List, NamedTuple, Optional, Sequence, Union

import numpy as np

from ..ops.entry_block import EntryBlock

MAGIC = b"TMFL"
VERSION = 1

KIND_SUBMIT = 1
KIND_VERDICT = 2
KIND_ERROR = 3

ERR_MALFORMED = 1
ERR_VERSION = 2
ERR_DISPATCH = 3
ERR_OVERSIZE = 4
ERR_CLOSED = 5

FLAG_EPOCH = 1  # meta_flags bit0: epoch_key + val_idx tail present

_LEN = struct.Struct("<I")
_HDR = struct.Struct("<4sHBB")           # magic, version, kind, flags
_SUBMIT_META = struct.Struct("<QQBBH")   # request_id, flow, priority, meta_flags, lane_len
_SUBMIT_SHAPE = struct.Struct("<IQ")     # n, msgs_len
_VERDICT_META = struct.Struct("<QI")     # request_id, n
_ERROR_META = struct.Struct("<QBH")      # request_id, code, msg_len
_EK_LEN = struct.Struct("<H")

_DEF_MAX_FRAME = 64 * 1024 * 1024


def max_frame_bytes() -> int:
    """Hard per-frame ceiling (``TM_TPU_FLEET_MAX_FRAME``, default 64 MiB)."""
    try:
        v = int(os.environ.get("TM_TPU_FLEET_MAX_FRAME", _DEF_MAX_FRAME))
    except ValueError:
        v = _DEF_MAX_FRAME
    return max(4096, v)


class WireError(ValueError):
    """Malformed frame payload; the connection survives (framing intact)."""


class VersionSkew(WireError):
    """Frame from an incompatible protocol version."""

    def __init__(self, got: int):
        super().__init__(f"fleet wire version skew: got v{got}, speak v{VERSION}")
        self.got = got


class OversizeFrame(WireError):
    """Length prefix exceeds max_frame — framing lost, connection must close."""


class TruncatedFrame(WireError):
    """EOF arrived mid-frame (peer died with bytes in flight)."""


class SubmitFrame(NamedTuple):
    request_id: int
    flow: int          # 0 = no flow
    priority: int
    lane: str
    block: EntryBlock


class VerdictFrame(NamedTuple):
    request_id: int
    verdicts: np.ndarray  # (n,) bool


class ErrorFrame(NamedTuple):
    request_id: int
    code: int
    message: str


Frame = Union[SubmitFrame, VerdictFrame, ErrorFrame]


def _col_bytes(arr: np.ndarray) -> memoryview:
    # Contiguous little-endian bytes over a column, copy-free when the
    # array is already C-contiguous (EntryBlock columns always are).
    a = np.ascontiguousarray(arr)
    if a.dtype.byteorder == ">":  # pragma: no cover - no BE hosts in CI
        a = a.astype(a.dtype.newbyteorder("<"))
    if a.size == 0:  # zero-size views can't be cast flat
        return memoryview(b"")
    return memoryview(a).cast("B")


def encode_submit(
    request_id: int,
    block: EntryBlock,
    *,
    flow: int = 0,
    priority: int = 0,
    lane: str = "",
) -> List[Union[bytes, memoryview]]:
    """Encode an EntryBlock SUBMIT frame as an iovec (zero-copy columns).

    Returns a list of buffers suitable for ``socket.sendmsg`` or
    sequential ``sendall``; the numpy columns are passed through as
    memoryviews without copying.
    """
    n = len(block)
    lane_b = lane.encode("utf-8")
    if len(lane_b) > 0xFFFF:
        raise WireError("lane name too long")
    msgs_buf, offs = block.msgs_contiguous()
    msgs_len = len(msgs_buf)

    has_epoch = block.epoch_key is not None and block.val_idx is not None
    meta_flags = FLAG_EPOCH if has_epoch else 0

    iov: List[Union[bytes, memoryview]] = []
    head = (
        _HDR.pack(MAGIC, VERSION, KIND_SUBMIT, 0)
        + _SUBMIT_META.pack(request_id, flow, priority, meta_flags, len(lane_b))
        + lane_b
        + _SUBMIT_SHAPE.pack(n, msgs_len)
    )
    payload_len = (
        len(head) + n * 32 + n * 64 + (n + 1) * 8 + msgs_len
    )
    ek_b = b""
    if has_epoch:
        ek_b = bytes(block.epoch_key)
        if len(ek_b) > 0xFFFF:
            raise WireError("epoch_key too long")
        payload_len += _EK_LEN.size + len(ek_b) + n * 4
    if payload_len > max_frame_bytes():
        raise OversizeFrame(
            f"encoded frame {payload_len}B exceeds max_frame {max_frame_bytes()}B"
        )

    iov.append(_LEN.pack(payload_len) + head)
    iov.append(_col_bytes(block.pub))
    iov.append(_col_bytes(block.sig))
    iov.append(_col_bytes(offs.astype("<i8", copy=False)))
    iov.append(memoryview(msgs_buf) if not isinstance(msgs_buf, memoryview) else msgs_buf)
    if has_epoch:
        iov.append(_EK_LEN.pack(len(ek_b)) + ek_b)
        iov.append(_col_bytes(block.val_idx.astype("<i4", copy=False)))
    return iov


def encode_verdicts(request_id: int, verdicts: np.ndarray) -> bytes:
    v = np.asarray(verdicts).astype(np.uint8, copy=False).reshape(-1)
    payload = (
        _HDR.pack(MAGIC, VERSION, KIND_VERDICT, 0)
        + _VERDICT_META.pack(request_id, v.shape[0])
        + v.tobytes()
    )
    return _LEN.pack(len(payload)) + payload


def encode_error(request_id: int, code: int, message: str) -> bytes:
    msg_b = message.encode("utf-8")[:0xFFFF]
    payload = (
        _HDR.pack(MAGIC, VERSION, KIND_ERROR, 0)
        + _ERROR_META.pack(request_id, code, len(msg_b))
        + msg_b
    )
    return _LEN.pack(len(payload)) + payload


def _need(payload: bytes, off: int, n: int, what: str) -> None:
    if off + n > len(payload):
        raise WireError(f"truncated {what}: need {n}B at {off}, have {len(payload)}")


def parse_frame(payload: bytes) -> Frame:
    """Parse one complete frame payload (length prefix already stripped).

    Raises WireError / VersionSkew on malformed input; both are
    per-frame recoverable because framing came from the length prefix.
    """
    _need(payload, 0, _HDR.size, "header")
    magic, version, kind, _flags = _HDR.unpack_from(payload, 0)
    if magic != MAGIC:
        raise WireError(f"bad magic {magic!r}")
    if version != VERSION:
        raise VersionSkew(version)
    off = _HDR.size

    if kind == KIND_SUBMIT:
        _need(payload, off, _SUBMIT_META.size, "submit meta")
        request_id, flow, priority, meta_flags, lane_len = _SUBMIT_META.unpack_from(
            payload, off
        )
        off += _SUBMIT_META.size
        _need(payload, off, lane_len, "lane name")
        try:
            lane = payload[off : off + lane_len].decode("utf-8")
        except UnicodeDecodeError as e:
            raise WireError(f"lane name not utf-8: {e}") from None
        off += lane_len
        _need(payload, off, _SUBMIT_SHAPE.size, "submit shape")
        n, msgs_len = _SUBMIT_SHAPE.unpack_from(payload, off)
        off += _SUBMIT_SHAPE.size

        _need(payload, off, n * 32, "pub column")
        pub = np.frombuffer(payload, dtype=np.uint8, count=n * 32, offset=off)
        pub = pub.reshape(n, 32)
        off += n * 32
        _need(payload, off, n * 64, "sig column")
        sig = np.frombuffer(payload, dtype=np.uint8, count=n * 64, offset=off)
        sig = sig.reshape(n, 64)
        off += n * 64
        _need(payload, off, (n + 1) * 8, "offsets column")
        offsets = np.frombuffer(payload, dtype="<i8", count=n + 1, offset=off)
        off += (n + 1) * 8
        _need(payload, off, msgs_len, "msgs buffer")
        msgs = payload[off : off + msgs_len]
        off += msgs_len

        if offsets[0] != 0:
            raise WireError(f"offsets[0] = {int(offsets[0])}, want 0")
        if int(offsets[-1]) != msgs_len:
            raise WireError(
                f"offsets[-1] = {int(offsets[-1])} != msgs_len {msgs_len}"
            )
        if n and np.any(np.diff(offsets) < 0):
            raise WireError("offsets not non-decreasing")

        epoch_key: Optional[bytes] = None
        val_idx: Optional[np.ndarray] = None
        if meta_flags & FLAG_EPOCH:
            _need(payload, off, _EK_LEN.size, "epoch_key length")
            (ek_len,) = _EK_LEN.unpack_from(payload, off)
            off += _EK_LEN.size
            _need(payload, off, ek_len, "epoch_key")
            epoch_key = payload[off : off + ek_len]
            off += ek_len
            _need(payload, off, n * 4, "val_idx column")
            val_idx = np.frombuffer(payload, dtype="<i4", count=n, offset=off)
            off += n * 4
        if off != len(payload):
            raise WireError(f"{len(payload) - off}B of trailing junk")

        block = EntryBlock(
            pub=pub,
            sig=sig,
            msgs=msgs,
            offsets=offsets.astype(np.int64, copy=False),
            epoch_key=epoch_key,
            val_idx=(
                val_idx.astype(np.int32, copy=False) if val_idx is not None else None
            ),
        )
        return SubmitFrame(request_id, flow, priority, lane, block)

    if kind == KIND_VERDICT:
        _need(payload, off, _VERDICT_META.size, "verdict meta")
        request_id, n = _VERDICT_META.unpack_from(payload, off)
        off += _VERDICT_META.size
        _need(payload, off, n, "verdict bytes")
        v = np.frombuffer(payload, dtype=np.uint8, count=n, offset=off)
        off += n
        if off != len(payload):
            raise WireError(f"{len(payload) - off}B of trailing junk")
        return VerdictFrame(request_id, v.astype(bool))

    if kind == KIND_ERROR:
        _need(payload, off, _ERROR_META.size, "error meta")
        request_id, code, msg_len = _ERROR_META.unpack_from(payload, off)
        off += _ERROR_META.size
        _need(payload, off, msg_len, "error message")
        try:
            msg = payload[off : off + msg_len].decode("utf-8")
        except UnicodeDecodeError as e:
            raise WireError(f"error message not utf-8: {e}") from None
        off += msg_len
        if off != len(payload):
            raise WireError(f"{len(payload) - off}B of trailing junk")
        return ErrorFrame(request_id, code, msg)

    raise WireError(f"unknown frame kind {kind}")


class FrameDecoder:
    """Incremental stream → complete frame payloads.

    Feed arbitrary byte chunks; get back complete payloads (length
    prefix stripped). Tolerates any fragmentation. Raises
    ``OversizeFrame`` when a length prefix exceeds the cap — after
    that the stream's framing cannot be trusted and the connection
    must close.
    """

    def __init__(self, max_frame: Optional[int] = None):
        self._buf = bytearray()
        self._max = max_frame if max_frame is not None else max_frame_bytes()

    def feed(self, data: bytes) -> List[bytes]:
        self._buf.extend(data)
        out: List[bytes] = []
        while True:
            if len(self._buf) < _LEN.size:
                break
            (plen,) = _LEN.unpack_from(self._buf, 0)
            if plen > self._max:
                raise OversizeFrame(
                    f"frame length {plen}B exceeds max_frame {self._max}B"
                )
            if len(self._buf) < _LEN.size + plen:
                break
            out.append(bytes(self._buf[_LEN.size : _LEN.size + plen]))
            del self._buf[: _LEN.size + plen]
        return out

    def eof(self) -> None:
        """Signal end-of-stream; raises if a partial frame was pending."""
        if self._buf:
            raise TruncatedFrame(
                f"EOF with {len(self._buf)}B of partial frame buffered"
            )

    @property
    def pending(self) -> int:
        return len(self._buf)


def send_frame(sock, iov: Sequence[Union[bytes, memoryview]]) -> None:
    """Write one encoded frame (iovec or single buffer) to a socket."""
    if isinstance(iov, (bytes, bytearray, memoryview)):
        sock.sendall(iov)
        return
    if not hasattr(sock, "sendmsg"):
        for b in iov:
            sock.sendall(b)
        return
    # One syscall per round when the platform supports scatter-gather
    # (Linux always does); loop handles rare partial sends.
    bufs = [b if isinstance(b, memoryview) else memoryview(b) for b in iov]
    while bufs:
        sent = sock.sendmsg(bufs)
        while sent:
            if sent >= len(bufs[0]):
                sent -= len(bufs[0])
                bufs.pop(0)
            else:
                bufs[0] = bufs[0][sent:]
                sent = 0


def iter_frames(decoder: FrameDecoder, data: bytes) -> Iterator[Frame]:
    """Convenience: feed + parse in one step (used by loopback paths)."""
    for payload in decoder.feed(data):
        yield parse_frame(payload)
