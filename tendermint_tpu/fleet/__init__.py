"""The verification fleet (ISSUE 18, ROADMAP item 2).

Many tendermint nodes, one device fleet: a network-facing EntryBlock
verify service. `wire` is the length-prefixed columnar frame format
(near-free serialization — EntryBlocks are already contiguous buffers),
`server` accepts frames and feeds the shared AsyncBatchVerifier at each
client's QoS tier (so same-epoch blocks from DIFFERENT nodes cross-node
coalesce into mesh lanes), and `client` is the duck-typed remote
verifier that plugs in behind the ingress fabric's LaneSpec seam with
RTT-EWMA health tracking and graceful local-fallback degradation.

Import discipline: nothing here imports jax at module level — the wire
format and client run on pure numpy + stdlib sockets, and the server
resolves its verifier lazily exactly like the ingress lanes do.
"""

import os as _os

from .wire import (  # noqa: F401
    VERSION,
    FrameDecoder,
    OversizeFrame,
    TruncatedFrame,
    VersionSkew,
    WireError,
)

# Flow-domain partitioning (observability/trace.set_flow_domain): a
# process participating in a fleet sets TM_TPU_FLEET_FLOW_DOMAIN to a
# distinct small integer so merged flight-recorder traces from client
# nodes + fleet host never alias locally-allocated flow ids.
_domain = _os.environ.get("TM_TPU_FLEET_FLOW_DOMAIN", "")
if _domain:
    try:
        from ..observability.trace import set_flow_domain as _set_fd
        _set_fd(int(_domain))
    except ValueError:
        pass
