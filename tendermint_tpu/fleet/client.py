"""FleetClient: the node-side end of the verification fleet.

Duck-typed as a pipeline verifier — ``submit(entries, flow=None,
priority=0) -> Future`` resolving to an (n,) bool verdict array — so it
plugs straight into the ingress fabric's ``LaneSpec.verifier`` seam: a
lane routes its flushed windows over the wire instead of into the local
engine, and nothing else about the lane changes.

Health + graceful degradation contract (the load-bearing part):

* Every request carries a deadline (``TM_TPU_FLEET_TIMEOUT_MS``). A
  timeout or any socket error marks the fleet DOWN: all in-flight
  futures fail with ``FleetUnavailable`` and further ``submit()`` calls
  raise it immediately — no queueing behind a dead fleet, no stall.
* ``FleetUnavailable.fallback_to_host`` is the duck-typed marker the
  ingress completer checks: windows that died post-submit host-verify
  through the lane's existing ``host_fn`` instead of poisoning; a
  pre-submit raise rides the lane's ``submit_error_to_host`` path. The
  ingress fabric never imports this module.
* While down, a rejoin thread redials every ``TM_TPU_FLEET_REJOIN_MS``;
  on success the client is UP again and the next window rides the
  fleet. RTT is tracked as an EWMA and exported via FleetMetrics.

A server-side verification failure (ERROR frame, code DISPATCH) is NOT
a fleet failure: the future fails with ``RemoteDispatchError`` — which
deliberately lacks the fallback marker — so it poisons exactly that
window, mirroring a local DispatchError.
"""

from __future__ import annotations

import itertools
import os
import socket
import threading
import time
from concurrent.futures import Future
from typing import Dict, Optional, Tuple

import numpy as np

from ..libs.metrics import fleet_metrics
from ..observability.trace import TRACER
from . import wire

_DEF_TIMEOUT_MS = 5000.0
_DEF_REJOIN_MS = 500.0
_EWMA_ALPHA = 0.2


def _env_ms(name: str, default: float) -> float:
    try:
        return float(os.environ.get(name, default))
    except ValueError:
        return default


class FleetUnavailable(RuntimeError):
    """The fleet is down (timeout / socket error / not yet joined).

    ``fallback_to_host`` is the duck-typed contract with ops/ingress.py:
    a lane whose in-flight window fails with an error carrying this
    marker host-verifies the window instead of poisoning it.
    """

    fallback_to_host = True


class RemoteDispatchError(RuntimeError):
    """The fleet answered with an ERROR frame: the verifier on the far
    side raised for this request. Poisons only this window — no host
    fallback (the same work would likely fail locally too)."""

    def __init__(self, message: str, code: int = wire.ERR_DISPATCH):
        super().__init__(message)
        self.code = code


class FleetClient:
    """One node's connection to a fleet host.

    ``lane`` is declared per-submit via the LaneSpec seam's wrapper (or
    defaults to the client ``name``) and rides the wire so the server's
    per-lane counters and the cross-node coalescer see who sent what.
    """

    def __init__(self, addr: Tuple[str, int], name: str = "node",
                 lane: str = "", timeout_ms: Optional[float] = None,
                 rejoin_ms: Optional[float] = None,
                 connect: bool = True):
        self._addr = addr
        self.name = name
        self._lane = lane or name
        self._timeout_s = (
            timeout_ms if timeout_ms is not None
            else _env_ms("TM_TPU_FLEET_TIMEOUT_MS", _DEF_TIMEOUT_MS)
        ) / 1000.0
        self._rejoin_s = (
            rejoin_ms if rejoin_ms is not None
            else _env_ms("TM_TPU_FLEET_REJOIN_MS", _DEF_REJOIN_MS)
        ) / 1000.0
        self._target = "%s:%d" % addr
        self._m = fleet_metrics()
        self._mtx = threading.Lock()
        # serializes whole-frame writes: two threads flushing windows
        # concurrently must not interleave their iovecs on the stream
        self._send_mtx = threading.Lock()
        self._sock: Optional[socket.socket] = None
        self._epoch = 0  # bumps on every disconnect; stale threads exit
        self._pending: Dict[int, Tuple[Future, float]] = {}
        self._next_req = itertools.count(1)
        self._closed = threading.Event()
        self._rejoining = False
        self._rtt_ewma_s: Optional[float] = None
        self.rejoins = 0
        self.fallbacks = 0
        self.timeouts = 0
        self._m.client_connected.set(0, target=self._target)
        if connect:
            try:
                self._connect_locked_entry()
            except OSError:
                self._schedule_rejoin()

    # -- public surface ------------------------------------------------

    @property
    def connected(self) -> bool:
        with self._mtx:
            return self._sock is not None

    def rtt_ewma_ms(self) -> Optional[float]:
        with self._mtx:
            return self._rtt_ewma_s * 1000.0 if self._rtt_ewma_s else None

    def stats(self) -> dict:
        with self._mtx:
            return {
                "target": self._target,
                "connected": self._sock is not None,
                "rtt_ewma_ms": (
                    self._rtt_ewma_s * 1000.0 if self._rtt_ewma_s else None
                ),
                "pending": len(self._pending),
                "rejoins": self.rejoins,
                "fallbacks": self.fallbacks,
                "timeouts": self.timeouts,
            }

    def submit(self, entries, flow: Optional[int] = None,
               priority: int = 0) -> Future:
        """Verifier-shaped submit: ship the block to the fleet, return a
        Future resolving to the (n,) bool verdict array. Raises
        FleetUnavailable immediately while degraded."""
        from ..ops.entry_block import as_block
        block = as_block(entries)
        with self._mtx:
            if self._closed.is_set():
                raise FleetUnavailable("fleet client closed")
            sock = self._sock
            if sock is None:
                self.fallbacks += 1
                self._m.client_fallbacks.inc(target=self._target)
                raise FleetUnavailable(
                    f"fleet {self._target} is down (rejoining)")
            rid = next(self._next_req)
            fut: Future = Future()
            self._pending[rid] = (fut, time.monotonic())
        iov = wire.encode_submit(rid, block, flow=flow or 0,
                                 priority=priority, lane=self._lane)
        TRACER.flow_point("fleet.client.send", flow, "t",
                          target=self._target, n=len(block))
        self._m.client_requests.inc(target=self._target)
        try:
            with self._send_mtx:
                wire.send_frame(sock, iov)
        except OSError as e:
            self._mark_down(f"send failed: {e}")
            # _mark_down already failed `fut` along with everything else
        return fut

    def close(self) -> None:
        self._closed.set()
        self._mark_down("client closed")

    # -- connection lifecycle -----------------------------------------

    def _connect_locked_entry(self) -> None:
        """Dial and install a fresh connection (raises OSError)."""
        sock = socket.create_connection(self._addr, timeout=self._timeout_s)
        sock.settimeout(None)
        sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        with self._mtx:
            # clear the rejoin flag HERE, atomically with installing the
            # socket: if this connection dies instantly, the reader's
            # _mark_down -> _schedule_rejoin must see rejoining=False or
            # nobody ever redials again
            self._rejoining = False
            if self._closed.is_set():
                sock.close()
                return
            self._sock = sock
            self._epoch += 1
            epoch = self._epoch
        self._m.client_connected.set(1, target=self._target)
        threading.Thread(target=self._read_loop, args=(sock, epoch),
                         name=f"fleet-client-read-{self.name}",
                         daemon=True).start()
        threading.Thread(target=self._watchdog, args=(epoch,),
                         name=f"fleet-client-watch-{self.name}",
                         daemon=True).start()

    def _mark_down(self, reason: str) -> None:
        with self._mtx:
            sock, self._sock = self._sock, None
            dead = list(self._pending.values())
            self._pending.clear()
            self._epoch += 1
        if sock is not None:
            try:
                sock.close()
            except OSError:
                pass
        self._m.client_connected.set(0, target=self._target)
        if dead:
            self.fallbacks += len(dead)
            self._m.client_fallbacks.inc(len(dead), target=self._target)
        err = FleetUnavailable(f"fleet {self._target} unavailable: {reason}")
        for fut, _t in dead:
            if not fut.done():
                fut.set_exception(err)
        if not self._closed.is_set():
            self._schedule_rejoin()

    def _schedule_rejoin(self) -> None:
        with self._mtx:
            if self._rejoining or self._closed.is_set():
                return
            self._rejoining = True
        threading.Thread(target=self._rejoin_loop,
                         name=f"fleet-client-rejoin-{self.name}",
                         daemon=True).start()

    def _rejoin_loop(self) -> None:
        while not self._closed.is_set():
            time.sleep(self._rejoin_s)
            if self._closed.is_set():
                break
            try:
                self._connect_locked_entry()
            except OSError:
                continue
            self.rejoins += 1
            self._m.client_rejoins.inc(target=self._target)
            return  # flag already cleared inside _connect_locked_entry
        with self._mtx:
            self._rejoining = False

    # -- reader + watchdog --------------------------------------------

    def _read_loop(self, sock: socket.socket, epoch: int) -> None:
        decoder = wire.FrameDecoder()
        while True:
            try:
                data = sock.recv(1 << 20)
            except OSError:
                data = b""
            if not data:
                with self._mtx:
                    stale = epoch != self._epoch
                if not stale:
                    self._mark_down("connection lost")
                return
            try:
                payloads = decoder.feed(data)
                frames = [wire.parse_frame(p) for p in payloads]
            except wire.WireError as e:
                with self._mtx:
                    stale = epoch != self._epoch
                if not stale:
                    self._mark_down(f"bad frame from fleet: {e}")
                return
            for frame in frames:
                self._dispatch_reply(frame)

    def _dispatch_reply(self, frame: wire.Frame) -> None:
        if isinstance(frame, wire.VerdictFrame):
            with self._mtx:
                ent = self._pending.pop(frame.request_id, None)
                if ent is not None:
                    rtt = time.monotonic() - ent[1]
                    if self._rtt_ewma_s is None:
                        self._rtt_ewma_s = rtt
                    else:
                        self._rtt_ewma_s += _EWMA_ALPHA * (rtt - self._rtt_ewma_s)
                    self._m.client_rtt_ewma_ms.set(
                        self._rtt_ewma_s * 1000.0, target=self._target)
            if ent is not None:
                fut = ent[0]
                if not fut.done():
                    fut.set_result(np.asarray(frame.verdicts, dtype=bool))
            return
        if isinstance(frame, wire.ErrorFrame):
            with self._mtx:
                ent = self._pending.pop(frame.request_id, None)
            if ent is not None:
                fut = ent[0]
                if not fut.done():
                    fut.set_exception(
                        RemoteDispatchError(frame.message, frame.code))
            # request_id 0 = connection-scoped error (malformed echo /
            # version skew report); nothing pending to fail
            return
        # a SUBMIT from the server makes no sense; ignore

    def _watchdog(self, epoch: int) -> None:
        tick = max(0.005, min(0.05, self._timeout_s / 4.0))
        while not self._closed.is_set():
            time.sleep(tick)
            now = time.monotonic()
            with self._mtx:
                if epoch != self._epoch:
                    return  # connection was replaced; a new watchdog runs
                expired = [
                    rid for rid, (_f, t0) in self._pending.items()
                    if now - t0 > self._timeout_s
                ]
            if expired:
                self.timeouts += len(expired)
                self._m.client_timeouts.inc(len(expired), target=self._target)
                # a stuck fleet is indistinguishable from a dead one:
                # degrade the whole connection (fails ALL pending) and
                # let the rejoin loop probe for recovery
                self._mark_down(f"{len(expired)} request(s) timed out")
                return


class LoopbackSession:
    """Socket-free client session over a LoopbackFleetHost (simnet).

    Synchronous and deterministic: encode → framing → host.handle →
    framing → decode, exercising the full wire path with no threads or
    wall clock. A killed host raises FleetUnavailable exactly like the
    real client's degraded mode."""

    def __init__(self, host, name: str = "node", lane: str = ""):
        self._host = host
        self.name = name
        self._lane = lane or name
        self._next_req = itertools.count(1)
        self.requests = 0
        self.fallbacks = 0

    def submit_block(self, block, *, flow: int = 0, priority: int = 0):
        rid = next(self._next_req)
        iov = wire.encode_submit(rid, block, flow=flow, priority=priority,
                                 lane=self._lane)
        data = b"".join(bytes(b) for b in iov)
        payloads = wire.FrameDecoder().feed(data)
        self.requests += 1
        try:
            reply_bytes = self._host.handle(payloads[0])
        except ConnectionError as e:
            self.fallbacks += 1
            raise FleetUnavailable(str(e)) from None
        reply = wire.parse_frame(wire.FrameDecoder().feed(reply_bytes)[0])
        if isinstance(reply, wire.ErrorFrame):
            raise RemoteDispatchError(reply.message, reply.code)
        assert isinstance(reply, wire.VerdictFrame) and reply.request_id == rid
        return np.asarray(reply.verdicts, dtype=bool)
