"""BlockStore — persisted blocks, parts, commits keyed by height.

Reference parity: internal/store/store.go. Key scheme is ordered-iteration
friendly: a 1-byte tag + big-endian height so height ranges are key ranges
(the reference uses orderedcode; big-endian int64 gives the same ordering
for non-negative heights).
"""

from __future__ import annotations

import struct
import threading
from dataclasses import dataclass
from typing import List, Optional

from ..db import DB, Batch
from ..types import Block, BlockID, Commit, Header, SignedHeader
from ..types.part_set import Part, PartSet
from ..wire.proto import ProtoWriter, decode_message, field_bytes, field_int, to_signed64

_TAG_META = b"\x00"
_TAG_PART = b"\x01"
_TAG_COMMIT = b"\x02"
_TAG_SEEN_COMMIT = b"\x03"
_TAG_BLOCK_HASH = b"\x04"

INT64_MAX = (1 << 63) - 1


def _h(height: int) -> bytes:
    return struct.pack(">q", height)


def block_meta_key(height: int) -> bytes:
    return _TAG_META + _h(height)


def block_part_key(height: int, index: int) -> bytes:
    return _TAG_PART + _h(height) + struct.pack(">i", index)


def block_commit_key(height: int) -> bytes:
    return _TAG_COMMIT + _h(height)


def seen_commit_key() -> bytes:
    return _TAG_SEEN_COMMIT


def block_hash_key(h: bytes) -> bytes:
    return _TAG_BLOCK_HASH + h


@dataclass
class BlockMeta:
    """types/block_meta.go: BlockID + sizes + header + num_txs."""

    block_id: BlockID
    block_size: int
    header: Header
    num_txs: int

    def encode(self) -> bytes:
        w = ProtoWriter()
        w.write_message(1, self.block_id.encode(), always=True)
        w.write_varint(2, self.block_size)
        w.write_message(3, self.header.encode(), always=True)
        w.write_varint(4, self.num_txs)
        return w.bytes()

    @classmethod
    def decode(cls, data: bytes) -> "BlockMeta":
        f = decode_message(data)
        return cls(
            block_id=BlockID.decode(field_bytes(f, 1)),
            block_size=to_signed64(field_int(f, 2)),
            header=Header.decode(field_bytes(f, 3)),
            num_txs=to_signed64(field_int(f, 4)),
        )


class BlockStore:
    """internal/store/store.go:30-530."""

    def __init__(self, db: DB):
        self._db = db
        self._mtx = threading.RLock()

    # -- range info -----------------------------------------------------

    def base(self) -> int:
        for k, _ in self._db.iterator(block_meta_key(1), block_meta_key(INT64_MAX)):
            return struct.unpack(">q", k[1:9])[0]
        return 0

    def height(self) -> int:
        for k, _ in self._db.reverse_iterator(
            block_meta_key(1), block_meta_key(INT64_MAX)
        ):
            return struct.unpack(">q", k[1:9])[0]
        return 0

    def size(self) -> int:
        h = self.height()
        return 0 if h == 0 else h - self.base() + 1

    def load_base_meta(self) -> Optional[BlockMeta]:
        b = self.base()
        return self.load_block_meta(b) if b else None

    # -- loads ----------------------------------------------------------

    def load_block(self, height: int) -> Optional[Block]:
        meta = self.load_block_meta(height)
        if meta is None:
            return None
        parts = []
        for i in range(meta.block_id.part_set_header.total):
            p = self.load_block_part(height, i)
            if p is None:
                return None
            parts.append(p.bytes)
        return Block.decode(b"".join(parts))

    def load_block_by_hash(self, h: bytes) -> Optional[Block]:
        raw = self._db.get(block_hash_key(h))
        if raw is None:
            return None
        return self.load_block(int(raw.decode()))

    def load_block_part(self, height: int, index: int) -> Optional[Part]:
        raw = self._db.get(block_part_key(height, index))
        return Part.decode(raw) if raw is not None else None

    def load_block_meta(self, height: int) -> Optional[BlockMeta]:
        raw = self._db.get(block_meta_key(height))
        return BlockMeta.decode(raw) if raw is not None else None

    def load_block_commit(self, height: int) -> Optional[Commit]:
        raw = self._db.get(block_commit_key(height))
        return Commit.decode(raw) if raw is not None else None

    def load_seen_commit(self) -> Optional[Commit]:
        raw = self._db.get(seen_commit_key())
        return Commit.decode(raw) if raw is not None else None

    # -- saves ----------------------------------------------------------

    def save_block(self, block: Block, block_parts: PartSet, seen_commit: Commit) -> None:
        """store.go:429-490: meta + parts + last_commit + seen commit."""
        if block is None:
            raise ValueError("cannot save nil block")
        with self._mtx:
            height = block.header.height
            hash_ = block.hash()
            if not block_parts.is_complete():
                raise ValueError("cannot save block with incomplete parts")
            w = self.height()
            if w > 0 and height != w + 1:
                raise ValueError(f"cannot save block at height {height}, expected {w + 1}")

            batch = Batch(self._db)
            block_id = BlockID(hash=hash_, part_set_header=block_parts.header())
            meta = BlockMeta(
                block_id=block_id,
                block_size=len(block.encode()),
                header=block.header,
                num_txs=len(block.data.txs),
            )
            batch.set(block_meta_key(height), meta.encode())
            batch.set(block_hash_key(hash_), str(height).encode())
            for i in range(block_parts.total()):
                part = block_parts.get_part(i)
                batch.set(block_part_key(height, i), part.encode())
            if block.last_commit is not None:
                batch.set(block_commit_key(height - 1), block.last_commit.encode())
            batch.set(seen_commit_key(), seen_commit.encode())
            batch.write()

    def save_seen_commit(self, height: int, seen_commit: Commit) -> None:
        self._db.set(seen_commit_key(), seen_commit.encode())

    def save_signed_header(self, sh: SignedHeader, block_id: BlockID) -> None:
        """store.go:513-530 (used by statesync bootstrap)."""
        height = sh.header.height
        if self.load_block_meta(height) is not None:
            raise ValueError(f"a header at height {height} already exists")
        meta = BlockMeta(block_id=block_id, block_size=0, header=sh.header, num_txs=0)
        batch = Batch(self._db)
        batch.set(block_meta_key(height), meta.encode())
        batch.set(block_commit_key(height), sh.commit.encode())
        batch.write()

    # -- pruning --------------------------------------------------------

    def prune_blocks(self, height: int) -> int:
        """store.go:287-338: delete everything below `height`."""
        if height <= 0:
            raise ValueError("height must be greater than 0")
        with self._mtx:
            if height > self.height():
                raise ValueError("cannot prune beyond the latest height")
            if height < self.base():
                return 0
            pruned = 0
            batch = Batch(self._db)
            for k, raw in list(self._db.iterator(block_meta_key(0), block_meta_key(height))):
                meta = BlockMeta.decode(raw)
                batch.delete(block_hash_key(meta.block_id.hash))
                batch.delete(k)
                for i in range(meta.block_id.part_set_header.total):
                    h = struct.unpack(">q", k[1:9])[0]
                    batch.delete(block_part_key(h, i))
                pruned += 1
            for k, _ in list(
                self._db.iterator(block_commit_key(0), block_commit_key(height))
            ):
                batch.delete(k)
            batch.write()
            return pruned
