"""tendermint_tpu.db — ordered key-value store abstraction.

Reference parity: the external tm-db interface the reference builds its
stores on (SURVEY.md L4; config/config.go:179-194 backend selection).
Backends here:
  - MemDB:    in-memory ordered map (tm-db memdb) — tests, light store
  - SQLiteDB: persistent backend on Python's stdlib sqlite3 (replaces
    goleveldb as the zero-dependency default; WAL mode, single writer)
  - PrefixDB: namespaced view over another DB (tm-db prefixdb)

Iteration is byte-order ascending over [start, end) like tm-db's Iterator;
reverse_iterator mirrors ReverseIterator ((start, end] semantics are NOT
copied — tm-db uses [start, end) reversed, which is what we do).
"""

from __future__ import annotations

import abc
import bisect
import sqlite3
import threading
from typing import Dict, Iterator, List, Optional, Tuple


class DB(abc.ABC):
    @abc.abstractmethod
    def get(self, key: bytes) -> Optional[bytes]: ...

    @abc.abstractmethod
    def set(self, key: bytes, value: bytes) -> None: ...

    @abc.abstractmethod
    def delete(self, key: bytes) -> None: ...

    @abc.abstractmethod
    def iterator(
        self, start: Optional[bytes] = None, end: Optional[bytes] = None
    ) -> Iterator[Tuple[bytes, bytes]]: ...

    @abc.abstractmethod
    def reverse_iterator(
        self, start: Optional[bytes] = None, end: Optional[bytes] = None
    ) -> Iterator[Tuple[bytes, bytes]]: ...

    def has(self, key: bytes) -> bool:
        return self.get(key) is not None

    def write_batch(self, ops: List[Tuple[str, bytes, Optional[bytes]]]) -> None:
        """Atomic-ish batch: ops are ("set", k, v) or ("delete", k, None)."""
        for op, k, v in ops:
            if op == "set":
                self.set(k, v)  # type: ignore[arg-type]
            elif op == "delete":
                self.delete(k)
            else:
                raise ValueError(f"unknown batch op {op}")

    def close(self) -> None:
        pass


class Batch:
    """tm-db Batch shim: accumulate then write atomically."""

    def __init__(self, db: DB):
        self._db = db
        self._ops: List[Tuple[str, bytes, Optional[bytes]]] = []

    def set(self, key: bytes, value: bytes) -> None:
        self._ops.append(("set", bytes(key), bytes(value)))

    def delete(self, key: bytes) -> None:
        self._ops.append(("delete", bytes(key), None))

    def write(self) -> None:
        self._db.write_batch(self._ops)
        self._ops = []


class MemDB(DB):
    def __init__(self):
        self._data: Dict[bytes, bytes] = {}
        self._keys: List[bytes] = []
        self._mtx = threading.RLock()

    def get(self, key: bytes) -> Optional[bytes]:
        with self._mtx:
            return self._data.get(bytes(key))

    def set(self, key: bytes, value: bytes) -> None:
        key = bytes(key)
        with self._mtx:
            if key not in self._data:
                bisect.insort(self._keys, key)
            self._data[key] = bytes(value)

    def delete(self, key: bytes) -> None:
        key = bytes(key)
        with self._mtx:
            if key in self._data:
                del self._data[key]
                i = bisect.bisect_left(self._keys, key)
                del self._keys[i]

    def _range(self, start: Optional[bytes], end: Optional[bytes]) -> List[bytes]:
        with self._mtx:
            lo = bisect.bisect_left(self._keys, start) if start is not None else 0
            hi = bisect.bisect_left(self._keys, end) if end is not None else len(self._keys)
            return self._keys[lo:hi]

    def iterator(self, start=None, end=None):
        for k in self._range(start, end):
            v = self.get(k)
            if v is not None:
                yield k, v

    def reverse_iterator(self, start=None, end=None):
        for k in reversed(self._range(start, end)):
            v = self.get(k)
            if v is not None:
                yield k, v


class SQLiteDB(DB):
    """Persistent ordered KV on sqlite3 (stdlib; replaces goleveldb)."""

    def __init__(self, path: str):
        self._conn = sqlite3.connect(path, check_same_thread=False)
        self._mtx = threading.RLock()
        with self._mtx:
            self._conn.execute("PRAGMA journal_mode=WAL")
            self._conn.execute("PRAGMA synchronous=NORMAL")
            self._conn.execute(
                "CREATE TABLE IF NOT EXISTS kv (k BLOB PRIMARY KEY, v BLOB NOT NULL)"
            )
            self._conn.commit()

    def get(self, key: bytes) -> Optional[bytes]:
        with self._mtx:
            row = self._conn.execute(
                "SELECT v FROM kv WHERE k = ?", (bytes(key),)
            ).fetchone()
        return bytes(row[0]) if row else None

    def set(self, key: bytes, value: bytes) -> None:
        with self._mtx:
            self._conn.execute(
                "INSERT OR REPLACE INTO kv (k, v) VALUES (?, ?)",
                (bytes(key), bytes(value)),
            )
            self._conn.commit()

    def delete(self, key: bytes) -> None:
        with self._mtx:
            self._conn.execute("DELETE FROM kv WHERE k = ?", (bytes(key),))
            self._conn.commit()

    def write_batch(self, ops) -> None:
        with self._mtx:
            for op, k, v in ops:
                if op == "set":
                    self._conn.execute(
                        "INSERT OR REPLACE INTO kv (k, v) VALUES (?, ?)", (k, v)
                    )
                else:
                    self._conn.execute("DELETE FROM kv WHERE k = ?", (k,))
            self._conn.commit()

    def _query(self, start, end, desc: bool):
        q = "SELECT k, v FROM kv"
        clauses, args = [], []
        if start is not None:
            clauses.append("k >= ?")
            args.append(bytes(start))
        if end is not None:
            clauses.append("k < ?")
            args.append(bytes(end))
        if clauses:
            q += " WHERE " + " AND ".join(clauses)
        q += " ORDER BY k DESC" if desc else " ORDER BY k ASC"
        with self._mtx:
            rows = self._conn.execute(q, args).fetchall()
        return [(bytes(k), bytes(v)) for k, v in rows]

    def iterator(self, start=None, end=None):
        yield from self._query(start, end, desc=False)

    def reverse_iterator(self, start=None, end=None):
        yield from self._query(start, end, desc=True)

    def close(self) -> None:
        with self._mtx:
            self._conn.close()


class PrefixDB(DB):
    """Namespaced view (tm-db prefixdb)."""

    def __init__(self, db: DB, prefix: bytes):
        self._db = db
        self._prefix = bytes(prefix)

    def _k(self, key: bytes) -> bytes:
        return self._prefix + bytes(key)

    def get(self, key: bytes) -> Optional[bytes]:
        return self._db.get(self._k(key))

    def set(self, key: bytes, value: bytes) -> None:
        self._db.set(self._k(key), value)

    def delete(self, key: bytes) -> None:
        self._db.delete(self._k(key))

    def write_batch(self, ops) -> None:
        self._db.write_batch([(op, self._k(k), v) for op, k, v in ops])

    def _strip(self, it):
        n = len(self._prefix)
        for k, v in it:
            yield k[n:], v

    def iterator(self, start=None, end=None):
        s = self._k(start) if start is not None else self._prefix
        if end is not None:
            e = self._k(end)
        else:
            e = _prefix_end(self._prefix)
        yield from self._strip(self._db.iterator(s, e))

    def reverse_iterator(self, start=None, end=None):
        s = self._k(start) if start is not None else self._prefix
        if end is not None:
            e = self._k(end)
        else:
            e = _prefix_end(self._prefix)
        yield from self._strip(self._db.reverse_iterator(s, e))


def _prefix_end(prefix: bytes) -> Optional[bytes]:
    """Smallest byte string greater than every key with this prefix."""
    b = bytearray(prefix)
    for i in reversed(range(len(b))):
        if b[i] != 0xFF:
            b[i] += 1
            return bytes(b[: i + 1])
    return None


def backend(kind: str, path: Optional[str] = None) -> DB:
    """config/config.go:179-194 backend selection, TPU-build edition."""
    if kind in ("memdb", "mem"):
        return MemDB()
    if kind in ("sqlite", "goleveldb", "default"):
        if not path:
            raise ValueError("persistent backend needs a path")
        return SQLiteDB(path)
    raise ValueError(f"unknown db backend {kind!r}")
