"""Per-peer consensus round-state tracking for targeted gossip.

Reference parity: internal/consensus/peer_state.go (PeerRoundState,
peer_state.go:28+): the reactor keeps, for every peer, which height/round/
step it is in and bit arrays of which proposal parts and votes it already
has, so gossip sends each peer only what it is missing — instead of
re-flooding every vote to every peer (reactor.go:503 gossipDataRoutine,
:715 gossipVotesRoutine pick from exactly these structures).
"""

from __future__ import annotations

import os
import random
import threading
from dataclasses import dataclass, field
from typing import Optional

# Fault-search regression seam: TM_TPU_GOSSIP_BUG_CATCHUP=1 strips the
# reference's ensureCatchUpCommitRound tracking (peer_state.go) out of
# BOTH gossip pick paths — the mechanism whose absence in pick_vote_to_send
# was one of the two real gossip bugs simnet found in PR 3 (laggards whose
# round advanced past the commit round were never served and wedged).
# Without the catch-up commit bits, a node that falls >= 2 heights behind
# (crash + WAL-restart while the cluster advances, or a healed minority
# partition) can never be served historical commit precommits and stalls
# forever. The schedule-search harness (simnet/search.py) uses the flag to
# prove the search+shrink loop rediscovers and minimizes the bug; it must
# NEVER be set outside that harness.
_BUG_NO_CATCHUP_ROUND = bool(os.environ.get("TM_TPU_GOSSIP_BUG_CATCHUP"))

from ..libs.bits import BitArray
from ..types import BlockID, Vote, VoteSet
from ..types.block import PartSetHeader
from ..types.proposal import Proposal
from ..types.vote import PRECOMMIT_TYPE, PREVOTE_TYPE
from .types import STEP_NEW_HEIGHT  # noqa: F401  (re-exported for reactor use)


def commit_to_vote(commit, idx: int) -> Optional[Vote]:
    """Reconstruct the precommit Vote behind commit.signatures[idx]
    (types/vote_set.go CommitToVoteSet / types/block.go:816 semantics)."""
    cs = commit.signatures[idx]
    if cs.is_absent():
        return None
    return Vote(
        type=PRECOMMIT_TYPE,
        height=commit.height,
        round=commit.round,
        block_id=cs.block_id(commit.block_id),
        timestamp=cs.timestamp,
        validator_address=cs.validator_address,
        validator_index=idx,
        signature=cs.signature,
    )


@dataclass
class PeerRoundState:
    """peer_state.go PeerRoundState / internal/consensus/types."""

    height: int = 0
    round: int = -1
    step: int = 0
    proposal: bool = False
    proposal_block_part_set_header: Optional[PartSetHeader] = None
    proposal_block_parts: Optional[BitArray] = None
    proposal_pol_round: int = -1
    proposal_pol: Optional[BitArray] = None
    prevotes: Optional[BitArray] = None
    precommits: Optional[BitArray] = None
    last_commit_round: int = -1
    last_commit: Optional[BitArray] = None
    catchup_commit_round: int = -1
    catchup_commit: Optional[BitArray] = None


class PeerState:
    """Mutable per-peer view, updated from NewRoundStep/HasVote/
    VoteSetBits/NewValidBlock messages and from our own sends."""

    def __init__(self, peer_id: str, rng=None):
        self.peer_id = peer_id
        self.prs = PeerRoundState()
        self._mtx = threading.RLock()
        # gossip-pick randomness source; injectable so a deterministic
        # driver (simnet) can seed it — default keeps the global PRNG
        self._rng = rng if rng is not None else random

    # -- applying messages from the peer --------------------------------

    def apply_new_round_step(
        self, height: int, round_: int, step: int, last_commit_round: int
    ) -> None:
        """peer_state.go:348 ApplyNewRoundStepMessage."""
        with self._mtx:
            prs = self.prs
            ps_height, ps_round = prs.height, prs.round
            ps_precommits = prs.precommits
            ps_catchup_round = prs.catchup_commit_round
            ps_catchup_commit = prs.catchup_commit

            prs.height = height
            prs.round = round_
            prs.step = step
            if ps_height != height or ps_round != round_:
                prs.proposal = False
                prs.proposal_block_part_set_header = None
                prs.proposal_block_parts = None
                prs.proposal_pol_round = -1
                prs.proposal_pol = None
                prs.prevotes = None
                prs.precommits = None
            if ps_height == height and ps_round != round_ and round_ == ps_catchup_round:
                # peer caught up to the round we were accumulating a
                # catchup commit for — reuse those precommit bits
                prs.precommits = ps_catchup_commit
            if ps_height != height:
                # shift: the peer's precommits for its previous height
                # become its last commit (peer_state.go:373-381)
                if ps_height + 1 == height and ps_round == last_commit_round:
                    prs.last_commit = ps_precommits
                else:
                    prs.last_commit = None
                prs.last_commit_round = last_commit_round
                prs.catchup_commit_round = -1
                prs.catchup_commit = None

    def apply_new_valid_block(
        self,
        height: int,
        round_: int,
        psh: PartSetHeader,
        parts: BitArray,
        is_commit: bool,
    ) -> None:
        """peer_state.go ApplyNewValidBlockMessage."""
        with self._mtx:
            prs = self.prs
            if prs.height != height:
                return
            if prs.round != round_ and not is_commit:
                return
            prs.proposal_block_part_set_header = psh
            prs.proposal_block_parts = parts

    def apply_proposal(self, proposal: Proposal) -> None:
        """peer_state.go SetHasProposal."""
        with self._mtx:
            prs = self.prs
            if prs.height != proposal.height or prs.round != proposal.round:
                return
            if prs.proposal:
                return
            prs.proposal = True
            if prs.proposal_block_parts is None:
                psh = proposal.block_id.part_set_header
                prs.proposal_block_part_set_header = psh
                prs.proposal_block_parts = BitArray(max(psh.total, 1))
            prs.proposal_pol_round = proposal.pol_round
            prs.proposal_pol = None  # until a ProposalPOL arrives

    def apply_proposal_pol(self, height: int, pol_round: int, pol: BitArray) -> None:
        with self._mtx:
            prs = self.prs
            if prs.height != height or prs.proposal_pol_round != pol_round:
                return
            prs.proposal_pol = pol

    def apply_has_vote(self, height: int, round_: int, type_: int, index: int) -> None:
        with self._mtx:
            if self.prs.height != height:
                return
            self._set_has_vote_locked(height, round_, type_, index)

    def apply_vote_set_bits(
        self, height: int, round_: int, type_: int, bits: BitArray,
        our_votes: Optional[BitArray] = None,
    ) -> None:
        """peer_state.go ApplyVoteSetBitsMessage: when the response is
        keyed to a specific BlockID we only learn bits we also have set
        (our_votes AND bits), otherwise take the peer's word wholesale."""
        with self._mtx:
            cur = self._get_vote_bits_locked(height, round_, type_)
            if cur is None:
                self._ensure_vote_bits_locked(height, round_, type_, bits.size())
                cur = self._get_vote_bits_locked(height, round_, type_)
            if cur is None:
                return
            if our_votes is not None:
                learned = our_votes.and_(bits).or_(cur)
            else:
                learned = bits.copy()
            self._put_vote_bits_locked(height, round_, type_, learned)

    def apply_has_vote_bits(
        self, height: int, round_: int, type_: int, bits: BitArray
    ) -> None:
        """Coalesced HasVote (ISSUE 15 traffic diet): one bit-array summary
        replaces a burst of per-index HasVote messages. Unlike VoteSetBits
        responses, these are the sender's own authoritative "I hold these
        votes" bits, so they are always OR-learned — never a wholesale
        replace — to compose with bits we learned from earlier sends."""
        with self._mtx:
            cur = self._get_vote_bits_locked(height, round_, type_)
            if cur is None:
                self._ensure_vote_bits_locked(height, round_, type_, bits.size())
                cur = self._get_vote_bits_locked(height, round_, type_)
            if cur is None:
                return
            self._put_vote_bits_locked(height, round_, type_, bits.or_(cur))

    # -- bookkeeping after our own sends --------------------------------

    def set_has_proposal_block_part(self, height: int, round_: int, index: int) -> None:
        with self._mtx:
            prs = self.prs
            if prs.height != height or prs.round != round_:
                return
            if prs.proposal_block_parts is None:
                return
            prs.proposal_block_parts.set_index(index, True)

    def set_has_vote(self, height: int, round_: int, type_: int, index: int) -> None:
        with self._mtx:
            self._set_has_vote_locked(height, round_, type_, index)

    # -- vote bit-array plumbing (peer_state.go getVoteBitArray) ----------

    def _get_vote_bits_locked(
        self, height: int, round_: int, type_: int
    ) -> Optional[BitArray]:
        prs = self.prs
        if prs.height == height:
            if prs.round == round_:
                return prs.prevotes if type_ == PREVOTE_TYPE else prs.precommits
            if prs.catchup_commit_round == round_ and type_ == PRECOMMIT_TYPE:
                return prs.catchup_commit
            if prs.proposal_pol_round == round_ and type_ == PREVOTE_TYPE:
                return prs.proposal_pol
        elif prs.height == height + 1:
            if prs.last_commit_round == round_ and type_ == PRECOMMIT_TYPE:
                return prs.last_commit
        return None

    def _put_vote_bits_locked(
        self, height: int, round_: int, type_: int, bits: BitArray
    ) -> None:
        prs = self.prs
        if prs.height == height:
            if prs.round == round_:
                if type_ == PREVOTE_TYPE:
                    prs.prevotes = bits
                else:
                    prs.precommits = bits
            elif prs.catchup_commit_round == round_ and type_ == PRECOMMIT_TYPE:
                prs.catchup_commit = bits
            elif prs.proposal_pol_round == round_ and type_ == PREVOTE_TYPE:
                prs.proposal_pol = bits
        elif prs.height == height + 1:
            if prs.last_commit_round == round_ and type_ == PRECOMMIT_TYPE:
                prs.last_commit = bits

    def _ensure_vote_bits_locked(
        self, height: int, round_: int, type_: int, num_validators: int
    ) -> None:
        prs = self.prs
        if prs.height == height + 1:
            # the peer is one height ahead: these votes are its last commit
            # (peer_state.go ensureVoteBitArrays seeds LastCommit for
            # Height == height+1 unconditionally; getVoteBitArray still
            # gates on LastCommitRound == round)
            if prs.last_commit is None:
                prs.last_commit = BitArray(num_validators)
            return
        if prs.height != height:
            return
        if prs.round == round_:
            if prs.prevotes is None:
                prs.prevotes = BitArray(num_validators)
            if prs.precommits is None:
                prs.precommits = BitArray(num_validators)
        if prs.catchup_commit_round == round_ and prs.catchup_commit is None:
            prs.catchup_commit = BitArray(num_validators)
        if prs.proposal_pol_round == round_ and prs.proposal_pol is None:
            prs.proposal_pol = BitArray(num_validators)

    def ensure_vote_bit_arrays(self, height: int, num_validators: int) -> None:
        """peer_state.go EnsureVoteBitArrays."""
        with self._mtx:
            prs = self.prs
            if prs.height == height:
                if prs.prevotes is None:
                    prs.prevotes = BitArray(num_validators)
                if prs.precommits is None:
                    prs.precommits = BitArray(num_validators)
                if prs.catchup_commit is None and prs.catchup_commit_round >= 0:
                    prs.catchup_commit = BitArray(num_validators)
                if prs.proposal_pol is None and prs.proposal_pol_round >= 0:
                    prs.proposal_pol = BitArray(num_validators)
            elif prs.height == height + 1:
                if prs.last_commit is None:
                    prs.last_commit = BitArray(num_validators)

    def ensure_catchup_commit_round(
        self, height: int, round_: int, num_validators: int
    ) -> None:
        """peer_state.go EnsureCatchUpCommitRound: we know `height` has a
        commit at `round_`; prepare to track which of its precommits the
        peer has."""
        with self._mtx:
            prs = self.prs
            if prs.height != height:
                return
            if prs.catchup_commit_round == round_:
                return
            prs.catchup_commit_round = round_
            if round_ == prs.round:
                prs.catchup_commit = prs.precommits
            else:
                prs.catchup_commit = BitArray(num_validators)

    def _set_has_vote_locked(self, height: int, round_: int, type_: int, index: int) -> None:
        bits = self._get_vote_bits_locked(height, round_, type_)
        if bits is not None and 0 <= index < bits.size():
            bits.set_index(index, True)

    # -- gossip picks (peer_state.go PickVoteToSend) ----------------------

    def pick_vote_to_send(self, votes: Optional[VoteSet]) -> Optional[Vote]:
        """Pick one vote from `votes` (our VoteSet) that this peer does not
        have yet, ensuring the peer-side bit array exists. Does NOT mark
        the vote as held — the reactor calls set_has_vote after a
        successful send (reactor.go:1008 pickSendVote)."""
        if votes is None or not votes.votes:
            return None
        n_vals = len(votes.votes)
        height, round_, type_ = votes.height, votes.round, votes.signed_msg_type
        with self._mtx:
            if votes.is_commit() and not _BUG_NO_CATCHUP_ROUND:
                # the set is a commit (vote_set.go IsCommit: PRECOMMITs
                # with a +2/3 block): a peer stuck in a LATER round of
                # this height can still take these round-`round_`
                # precommits — track them in the catchup bits
                # (peer_state.go PickVoteToSend → ensureCatchUpCommit-
                # Round). Without this, a laggard whose round advanced
                # past the commit round never gets served and wedges.
                self.ensure_catchup_commit_round(height, round_, n_vals)
            self._ensure_vote_bits_locked(height, round_, type_, n_vals)
            peer_bits = self._get_vote_bits_locked(height, round_, type_)
            if peer_bits is None:
                return None
            missing = votes.bit_array().sub(peer_bits)
            idx_list = missing.get_true_indices()
            if not idx_list:
                return None
            idx = self._rng.choice(idx_list)
            return votes.get_by_index(idx)

    def init_proposal_block_parts(self, psh: PartSetHeader) -> None:
        """peer_state.go InitProposalBlockParts: seed the part-tracking bit
        array (used for catchup gossip of committed blocks)."""
        with self._mtx:
            prs = self.prs
            if (
                prs.proposal_block_part_set_header is not None
                and prs.proposal_block_part_set_header == psh
            ):
                return
            prs.proposal_block_part_set_header = psh
            prs.proposal_block_parts = BitArray(max(psh.total, 1))

    def pick_commit_vote_to_send(self, commit) -> Optional[Vote]:
        """Pick one precommit reconstructed from a stored Commit that this
        peer (which is at commit.height, behind us) does not have yet —
        reactor.go:756-777 catchup via gossipVotesForHeight +
        peer_state.go EnsureCatchUpCommitRound."""
        with self._mtx:
            prs = self.prs
            if prs.height != commit.height:
                return None
            n = len(commit.signatures)
            if prs.catchup_commit_round != commit.round or prs.catchup_commit is None:
                if _BUG_NO_CATCHUP_ROUND:
                    return None  # regression seam: no catch-up rebind
                prs.catchup_commit_round = commit.round
                prs.catchup_commit = (
                    prs.precommits if commit.round == prs.round and prs.precommits is not None
                    else BitArray(n)
                )
            have = BitArray(n)
            for i, cs in enumerate(commit.signatures):
                if not cs.is_absent():
                    have.set_index(i, True)
            missing = have.sub(prs.catchup_commit)
            idx_list = missing.get_true_indices()
            if not idx_list:
                return None
            idx = self._rng.choice(idx_list)
            return commit_to_vote(commit, idx)

    def set_has_catchup_commit_vote(self, height: int, round_: int, index: int) -> None:
        with self._mtx:
            prs = self.prs
            if prs.height != height or prs.catchup_commit_round != round_:
                return
            if prs.catchup_commit is not None and 0 <= index < prs.catchup_commit.size():
                prs.catchup_commit.set_index(index, True)

    def snapshot(self) -> PeerRoundState:
        """A shallow copy safe to read without the lock."""
        with self._mtx:
            prs = self.prs
            return PeerRoundState(
                height=prs.height, round=prs.round, step=prs.step,
                proposal=prs.proposal,
                proposal_block_part_set_header=prs.proposal_block_part_set_header,
                proposal_block_parts=prs.proposal_block_parts,
                proposal_pol_round=prs.proposal_pol_round,
                proposal_pol=prs.proposal_pol,
                prevotes=prs.prevotes, precommits=prs.precommits,
                last_commit_round=prs.last_commit_round,
                last_commit=prs.last_commit,
                catchup_commit_round=prs.catchup_commit_round,
                catchup_commit=prs.catchup_commit,
            )
